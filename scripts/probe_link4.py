"""Probe 4: d2h pull floor anatomy — single vs multi-array pulls, async
copy_to_host, device_get batching, pull-size scaling."""

import time

import jax
import jax.numpy as jnp
import numpy as np


def t(fn, n=10, warm=2):
    for _ in range(warm):
        fn()
    s = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - s) / n


def main():
    N = 16 * 1024 * 1024
    a = jax.device_put(np.arange(N, dtype=np.int32))
    a.block_until_ready()
    bump = jax.jit(lambda x, i: x + i)

    # pull floor vs size, fresh array each time (single pull per call)
    for nbytes in (4, 4096, 262144, 1 << 20, 4 << 20):
        n = max(nbytes // 4, 1)
        i = [0]

        def run():
            out = bump(a, i[0])[:n]
            i[0] += 1
            return np.asarray(out)

        dt = t(run, n=8)
        print(f"jit+pull {nbytes:>9} B: {dt*1e3:8.2f} ms")

    # multi-array pull: 4 arrays sequential np.asarray vs device_get batch
    f4 = jax.jit(lambda x, i: (x[:1] + i, x[:1024] + i, x[:65536] + i, x[: 1 << 18] + i))
    i = [100]

    def seq_pull():
        outs = f4(a, i[0])
        i[0] += 1
        return [np.asarray(o) for o in outs]

    dt = t(seq_pull, n=8)
    print(f"4 outputs, sequential np.asarray: {dt*1e3:.2f} ms")

    def batch_pull():
        outs = f4(a, i[0])
        i[0] += 1
        return jax.device_get(outs)

    dt = t(batch_pull, n=8)
    print(f"4 outputs, jax.device_get(tuple): {dt*1e3:.2f} ms")

    def async_pull():
        outs = f4(a, i[0])
        i[0] += 1
        for o in outs:
            o.copy_to_host_async()
        return [np.asarray(o) for o in outs]

    dt = t(async_pull, n=8)
    print(f"4 outputs, copy_to_host_async then asarray: {dt*1e3:.2f} ms")

    # single concatenated output
    fc_ = jax.jit(
        lambda x, i: jnp.concatenate([x[:1] + i, x[:1024] + i, x[:65536] + i, x[: 1 << 18] + i])
    )

    def concat_pull():
        out = fc_(a, i[0])
        i[0] += 1
        return np.asarray(out)

    dt = t(concat_pull, n=8)
    print(f"1 concatenated output ({(1+1024+65536+(1<<18))*4} B): {dt*1e3:.2f} ms")

    # scalar-only pull (.item())
    fs = jax.jit(lambda x, i: (x.sum() + i).astype(jnp.int32))

    def item_pull():
        out = fs(a, i[0])
        i[0] += 1
        return out.item()

    dt = t(item_pull, n=8)
    print(f"scalar .item() pull: {dt*1e3:.2f} ms")


if __name__ == "__main__":
    main()
