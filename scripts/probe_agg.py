"""Probe: aggregation-kernel candidates on real TPU.

Measures, per candidate-block count M over a [nb, SUB, 128] point table:
  scan   - the round-3 bitmask scan kernel (reference point)
  xd     - XLA block-gather density (gather + scatter-add)
  xb     - XLA block-gather bounds (gather + masked reduce)
  pb     - Pallas bounds: block DMA + VPU reduce, per-slot [1,128] out
  pd_r   - Pallas density: one-hot MXU matmul, chunked via reshape
           (CH,128)->(1,CH*128)  [tests whether Mosaic takes the reshape]
  pd_f   - Pallas density: one-hot MXU matmul, fori over sublanes

Run on TPU:  python scripts/probe_agg.py
"""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from geomesa_tpu.scan import block_kernels as bk

LANES = 128
SUB = 128
H = W = 256
CH = 32  # sublanes per matmul chunk in pd_r


def timeit(fn, *args, n=20):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


# ---------------------------------------------------------------- pallas
def _mask_px_py(x_ref, y_ref, boxes_ref, gb_ref, bid_ok):
    x = x_ref[0]
    y = y_ref[0]
    w = jnp.zeros(x.shape, dtype=jnp.bool_)
    for k in range(8):
        w |= (
            (x >= boxes_ref[k, 0]) & (x <= boxes_ref[k, 2])
            & (y >= boxes_ref[k, 1]) & (y <= boxes_ref[k, 3])
        )
    x0, y0, x1, y1 = gb_ref[0, 0], gb_ref[0, 1], gb_ref[0, 2], gb_ref[0, 3]
    m = w & bid_ok & (x >= x0) & (x <= x1) & (y >= y0) & (y <= y1)
    px = jnp.clip(((x - x0) / (x1 - x0) * W).astype(jnp.int32), 0, W - 1)
    py = jnp.clip(((y - y0) / (y1 - y0) * H).astype(jnp.int32), 0, H - 1)
    return m, px, py


def _density_kernel_reshape(bids_ref, boxes_ref, gb_ref, x_ref, y_ref, out_ref):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    m, px, py = _mask_px_py(x_ref, y_ref, boxes_ref, gb_ref, bids_ref[i] >= 0)
    pix_y = jnp.where(m, py, -1)  # -1 never matches an iota row
    acc = jnp.zeros((H, W), jnp.float32)
    for c in range(SUB // CH):
        yy = pix_y[c * CH : (c + 1) * CH, :].reshape(1, CH * LANES)
        xx = px[c * CH : (c + 1) * CH, :].reshape(1, CH * LANES)
        ay = (lax.broadcasted_iota(jnp.int32, (H, CH * LANES), 0) == yy).astype(
            jnp.bfloat16
        )
        ax = (lax.broadcasted_iota(jnp.int32, (W, CH * LANES), 0) == xx).astype(
            jnp.bfloat16
        )
        acc += lax.dot_general(
            ay, ax, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
    out_ref[...] += acc


def _density_kernel_fori(bids_ref, boxes_ref, gb_ref, x_ref, y_ref, out_ref):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    m, px, py = _mask_px_py(x_ref, y_ref, boxes_ref, gb_ref, bids_ref[i] >= 0)
    pix_y = jnp.where(m, py, -1)

    def body(s, acc):
        yy = lax.dynamic_slice(pix_y, (s, 0), (1, LANES))
        xx = lax.dynamic_slice(px, (s, 0), (1, LANES))
        ay = (lax.broadcasted_iota(jnp.int32, (H, LANES), 0) == yy).astype(jnp.bfloat16)
        ax = (lax.broadcasted_iota(jnp.int32, (W, LANES), 0) == xx).astype(jnp.bfloat16)
        return acc + lax.dot_general(
            ay, ax, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )

    out_ref[...] += lax.fori_loop(0, SUB, body, jnp.zeros((H, W), jnp.float32))


def _bounds_kernel(bids_ref, boxes_ref, gb_ref, x_ref, y_ref, out_ref):
    x = x_ref[0]
    y = y_ref[0]
    w = jnp.zeros(x.shape, dtype=jnp.bool_)
    for k in range(8):
        w |= (
            (x >= boxes_ref[k, 0]) & (x <= boxes_ref[k, 2])
            & (y >= boxes_ref[k, 1]) & (y <= boxes_ref[k, 3])
        )
    inf = jnp.float32(jnp.inf)
    row = jnp.zeros((1, LANES), jnp.float32)
    row = row.at[0, 0].set(w.sum(dtype=jnp.float32))
    row = row.at[0, 1].set(jnp.where(w, x, inf).min())
    row = row.at[0, 2].set(jnp.where(w, x, -inf).max())
    row = row.at[0, 3].set(jnp.where(w, y, inf).min())
    row = row.at[0, 4].set(jnp.where(w, y, -inf).max())
    out_ref[...] = row


def make_pallas(kernel, out_shape, out_block, M):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(M,),
        in_specs=[
            pl.BlockSpec((8, LANES), lambda i, bids: (0, 0)),
            pl.BlockSpec((1, LANES), lambda i, bids: (0, 0)),
            pl.BlockSpec((1, SUB, LANES), lambda i, bids: (jnp.maximum(bids[i], 0), 0, 0)),
            pl.BlockSpec((1, SUB, LANES), lambda i, bids: (jnp.maximum(bids[i], 0), 0, 0)),
        ],
        out_specs=pl.BlockSpec(out_block[0], out_block[1]),
    )
    return jax.jit(
        lambda bids, boxes, gb, xs, ys: pl.pallas_call(
            kernel, grid_spec=grid_spec, out_shape=out_shape
        )(bids, boxes, gb, xs, ys)
    )


# ------------------------------------------------------------------ xla
@jax.jit
def xla_density(bids, boxes, gb, xs, ys):
    x = xs[jnp.maximum(bids, 0)]
    y = ys[jnp.maximum(bids, 0)]
    w = jnp.zeros(x.shape, dtype=jnp.bool_)
    for k in range(8):
        w |= (
            (x >= boxes[k, 0]) & (x <= boxes[k, 2])
            & (y >= boxes[k, 1]) & (y <= boxes[k, 3])
        )
    x0, y0, x1, y1 = gb[0, 0], gb[0, 1], gb[0, 2], gb[0, 3]
    m = w & (bids >= 0)[:, None, None] & (x >= x0) & (x <= x1) & (y >= y0) & (y <= y1)
    px = jnp.clip(((x - x0) / (x1 - x0) * W).astype(jnp.int32), 0, W - 1)
    py = jnp.clip(((y - y0) / (y1 - y0) * H).astype(jnp.int32), 0, H - 1)
    flat = (py * W + px).ravel()
    return (
        jnp.zeros(H * W, jnp.float32).at[flat].add(m.ravel().astype(jnp.float32))
    ).reshape(H, W)


@jax.jit
def xla_bounds(bids, boxes, gb, xs, ys):
    x = xs[jnp.maximum(bids, 0)]
    y = ys[jnp.maximum(bids, 0)]
    w = jnp.zeros(x.shape, dtype=jnp.bool_)
    for k in range(8):
        w |= (
            (x >= boxes[k, 0]) & (x <= boxes[k, 2])
            & (y >= boxes[k, 1]) & (y <= boxes[k, 3])
        )
    inf = jnp.float32(jnp.inf)
    return jnp.stack(
        [
            w.sum(axis=(1, 2), dtype=jnp.float32),
            jnp.where(w, x, inf).min(axis=(1, 2)),
            jnp.where(w, x, -inf).max(axis=(1, 2)),
            jnp.where(w, y, inf).min(axis=(1, 2)),
            jnp.where(w, y, -inf).max(axis=(1, 2)),
        ],
        axis=1,
    )


def main():
    print("backend:", jax.default_backend(), flush=True)
    nb = 4096  # 67M rows
    rng = np.random.default_rng(0)
    xs = jax.device_put(
        rng.uniform(-180, 180, nb * SUB * LANES).astype(np.float32).reshape(nb, SUB, LANES)
    )
    ys = jax.device_put(
        rng.uniform(-90, 90, nb * SUB * LANES).astype(np.float32).reshape(nb, SUB, LANES)
    )
    boxes = bk.pack_boxes(np.array([[-40.0, -30.0, 60.0, 40.0]]), None)
    gb = np.zeros((1, LANES), np.float32)
    gb[0, :4] = [-40, -30, 60, 40]

    for M in (256, 1024):
        bids, _ = bk.pad_bids(
            np.sort(rng.choice(nb, M, replace=False)), nb, pad=-1, bucket=M
        )
        # reference: bitmask scan
        cols3 = (xs, ys)
        t_scan = timeit(
            lambda b: bk.block_scan(
                cols3, jnp.maximum(jnp.asarray(b), 0), jnp.asarray(boxes),
                jnp.zeros((8, LANES), jnp.int32),
                col_names=("x", "y"), has_boxes=True, has_windows=False, extent=False,
            ),
            bids,
        )
        t_xd = timeit(xla_density, bids, boxes, gb, xs, ys)
        t_xb = timeit(xla_bounds, bids, boxes, gb, xs, ys)
        print(f"M={M}: scan={t_scan*1e3:.2f}ms xla_density={t_xd*1e3:.2f}ms xla_bounds={t_xb*1e3:.2f}ms", flush=True)

        pb = make_pallas(
            _bounds_kernel,
            jax.ShapeDtypeStruct((M, LANES), jnp.float32),
            ((1, LANES), lambda i, bids: (i, 0)),
            M,
        )
        try:
            t_pb = timeit(pb, bids, boxes, gb, xs, ys)
            ok = np.allclose(np.asarray(pb(bids, boxes, gb, xs, ys))[:, :5],
                             np.asarray(xla_bounds(bids, boxes, gb, xs, ys)), atol=1e-3)
            print(f"M={M}: pallas_bounds={t_pb*1e3:.2f}ms match={ok}", flush=True)
        except Exception as e:
            print(f"M={M}: pallas_bounds FAILED: {type(e).__name__}: {str(e)[:300]}", flush=True)

        for name, kern in (("pd_reshape", _density_kernel_reshape), ("pd_fori", _density_kernel_fori)):
            pd = make_pallas(
                kern,
                jax.ShapeDtypeStruct((H, W), jnp.float32),
                ((H, W), lambda i, bids: (0, 0)),
                M,
            )
            try:
                t_pd = timeit(pd, bids, boxes, gb, xs, ys)
                ok = np.allclose(np.asarray(pd(bids, boxes, gb, xs, ys)),
                                 np.asarray(xla_density(bids, boxes, gb, xs, ys)))
                print(f"M={M}: {name}={t_pd*1e3:.2f}ms match={ok}", flush=True)
            except Exception as e:
                print(f"M={M}: {name} FAILED: {type(e).__name__}: {str(e)[:300]}", flush=True)


if __name__ == "__main__":
    main()
