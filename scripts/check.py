#!/usr/bin/env python
"""geomesa-lint runner: the repo's static-analysis gate (docs/analysis.md).

Runs every shipped rule (geomesa_tpu.analysis) over geomesa_tpu/ +
scripts/ + docs/*.md and fails loudly on new findings — the same exit
convention as scripts/bench_gate.py, so CI treats both gates alike:

    0 = clean (no findings beyond the suppression baseline)
    1 = findings (each printed as path:line: [rule-id] message + fix)
    2 = unusable input (bad arguments, unknown rule id, missing repo)

Usage:
    python scripts/check.py                  # human output
    python scripts/check.py --json           # machine output (CI; stable
                                             # schema_version field)
    python scripts/check.py --rules knob-undeclared,metric-convention
    python scripts/check.py --changed        # findings scoped to files the
                                             # git working tree touched
    python scripts/check.py --profile        # per-rule wall-time table
    python scripts/check.py --list-rules     # rule catalog (id + summary)
    python scripts/check.py --write-baseline # accept current findings

tests/test_static_analysis.py runs the same analysis in-process, which
makes a clean tree a tier-1 invariant; this entry point exists for
humans, hooks and CI logs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

#: machine-output contract version (--json): bump ONLY on breaking
#: shape changes so CI consumers can pin against it
SCHEMA_VERSION = 1


def _changed_paths(root: str) -> "set[str] | None":
    """Repo-relative paths the git working tree touched (staged,
    unstaged, and untracked) — the --changed scope. None when git is
    unavailable or ``root`` is not a work tree."""
    import subprocess

    try:
        proc = subprocess.run(
            # -z: NUL-separated RAW paths (no C-style quoting — quoted
            # output would make findings in non-ASCII/quoted filenames
            # silently miss the changed set, a false-clean gate)
            ["git", "-C", root, "status", "--porcelain", "-z",
             "--untracked-files=all"],
            capture_output=True, text=True, timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    out: set[str] = set()
    fields = proc.stdout.split("\0")
    i = 0
    while i < len(fields):
        entry = fields[i]
        i += 1
        if len(entry) < 4:
            continue
        status, path = entry[:2], entry[3:]
        out.add(path.replace(os.sep, "/"))
        if status[0] in ("R", "C") and i < len(fields):
            # rename/copy records carry the ORIGINAL path as the next
            # NUL field; scope to the new name only
            i += 1
    return out


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument(
        "--root", default=REPO,
        help="repo root to analyze (default: this checkout; exit-code "
        "tests point it at staged mini-repos)",
    )
    ap.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    ap.add_argument(
        "--baseline", default=None,
        help="suppression baseline path (default: the checked-in "
        "geomesa_tpu/analysis/baseline.txt)",
    )
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="write current findings into the baseline (adopt-time only; "
        "tier-1 requires the shipped baseline to stay empty)",
    )
    ap.add_argument(
        "--show-suppressed", action="store_true",
        help="also print baseline/inline-suppressed findings",
    )
    ap.add_argument(
        "--changed", action="store_true",
        help="report only findings in files the git working tree "
        "touched (fast pre-commit iteration; rules still analyze the "
        "whole repo — cross-file invariants need it)",
    )
    ap.add_argument(
        "--profile", action="store_true",
        help="print a per-rule wall-time table after the findings",
    )
    args = ap.parse_args()

    from geomesa_tpu import analysis
    from geomesa_tpu.analysis.core import default_baseline_path

    if args.list_rules:
        for rule in analysis.ALL_RULES:
            print(f"{rule.id:24s} {rule.description}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = {r.strip() for r in args.rules.split(",") if r.strip()}
        known = {r.id for r in analysis.ALL_RULES}
        unknown = rule_ids - known
        if unknown:
            print(
                f"check: unknown rule id(s) {sorted(unknown)}; "
                f"known: {sorted(known)}",
                file=sys.stderr,
            )
            return 2

    baseline = args.baseline
    if (
        baseline is not None
        and not os.path.exists(baseline)
        and not args.write_baseline  # write mode creates the file
    ):
        print(f"check: baseline {baseline!r} does not exist", file=sys.stderr)
        return 2
    if not os.path.isdir(args.root):
        print(f"check: root {args.root!r} is not a directory", file=sys.stderr)
        return 2

    t0 = time.perf_counter()
    profile_rows = None
    try:
        if args.profile:
            # per-rule attribution: time each rule's check() over ONE
            # shared Project (registries/lock model memoize on it, so
            # the table charges each rule its marginal cost), then run
            # the normal suppression-filtered pass for the verdict
            from geomesa_tpu.analysis.core import Project, run_rules

            project = Project.load(args.root)
            rules = [
                r for r in analysis.ALL_RULES
                if rule_ids is None or r.id in rule_ids
            ]
            profile_rows = []
            for rule in rules:
                r0 = time.perf_counter()
                raised = sum(1 for _ in rule.check(project))
                profile_rows.append(
                    (rule.id, time.perf_counter() - r0, raised)
                )
            result = run_rules(project, rules, baseline=baseline)
        else:
            result = analysis.run(
                args.root, rule_ids=rule_ids, baseline=baseline
            )
    except Exception as e:  # analyzer bug = unusable input, not "clean"
        print(f"check: analysis failed: {e!r}", file=sys.stderr)
        return 2
    dt = time.perf_counter() - t0

    if args.changed:
        changed = _changed_paths(args.root)
        if changed is None:
            print(
                "check: --changed needs a git work tree at --root",
                file=sys.stderr,
            )
            return 2
        result.findings = [f for f in result.findings if f.path in changed]
        result.suppressed = [
            f for f in result.suppressed if f.path in changed
        ]

    if args.write_baseline:
        from geomesa_tpu.analysis import load_baseline

        path = baseline or default_baseline_path(args.root)
        try:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            have = load_baseline(path)
            fresh = sorted(
                {f.key for f in result.findings} - have
            )
            with open(path, "a", encoding="utf-8") as fh:
                for key in fresh:
                    fh.write(key + "\n")
        except OSError as e:
            print(f"check: cannot write baseline {path!r}: {e}", file=sys.stderr)
            return 2
        print(f"check: appended {len(fresh)} new keys to {path}")
        return 0

    if args.json:
        payload = {
            "schema_version": SCHEMA_VERSION,
            "findings": [f.to_json() for f in result.findings],
            "suppressed": [f.to_json() for f in result.suppressed],
            "clean": result.clean,
            "changed_only": bool(args.changed),
            "seconds": round(dt, 3),
        }
        if profile_rows is not None:
            payload["profile"] = [
                {"rule": rid, "seconds": round(s, 4), "raised": n}
                for rid, s, n in profile_rows
            ]
        print(json.dumps(payload, indent=2))
    else:
        for f in result.findings:
            print(f.render())
        if args.show_suppressed:
            for f in result.suppressed:
                print(f"suppressed: {f.render()}")
        if profile_rows is not None:
            width = max(len(r) for r, _, _ in profile_rows)
            for rid, s, n in sorted(profile_rows, key=lambda r: -r[1]):
                print(f"  {rid:{width}s} {s * 1e3:8.1f} ms  {n} raised")
        n, s = len(result.findings), len(result.suppressed)
        scope = " (changed files only)" if args.changed else ""
        print(
            f"check: {n} finding(s), {s} suppressed, "
            f"{dt * 1e3:.0f} ms{scope}"
        )
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
