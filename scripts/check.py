#!/usr/bin/env python
"""geomesa-lint runner: the repo's static-analysis gate (docs/analysis.md).

Runs every shipped rule (geomesa_tpu.analysis) over geomesa_tpu/ +
scripts/ + docs/*.md and fails loudly on new findings — the same exit
convention as scripts/bench_gate.py, so CI treats both gates alike:

    0 = clean (no findings beyond the suppression baseline)
    1 = findings (each printed as path:line: [rule-id] message + fix)
    2 = unusable input (bad arguments, unknown rule id, missing repo)

Usage:
    python scripts/check.py                  # human output
    python scripts/check.py --json           # machine output (CI)
    python scripts/check.py --rules knob-undeclared,metric-convention
    python scripts/check.py --list-rules     # rule catalog (id + summary)
    python scripts/check.py --write-baseline # accept current findings

tests/test_static_analysis.py runs the same analysis in-process, which
makes a clean tree a tier-1 invariant; this entry point exists for
humans, hooks and CI logs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true", help="JSON output")
    ap.add_argument(
        "--root", default=REPO,
        help="repo root to analyze (default: this checkout; exit-code "
        "tests point it at staged mini-repos)",
    )
    ap.add_argument(
        "--rules", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalog and exit",
    )
    ap.add_argument(
        "--baseline", default=None,
        help="suppression baseline path (default: the checked-in "
        "geomesa_tpu/analysis/baseline.txt)",
    )
    ap.add_argument(
        "--write-baseline", action="store_true",
        help="write current findings into the baseline (adopt-time only; "
        "tier-1 requires the shipped baseline to stay empty)",
    )
    ap.add_argument(
        "--show-suppressed", action="store_true",
        help="also print baseline/inline-suppressed findings",
    )
    args = ap.parse_args()

    from geomesa_tpu import analysis
    from geomesa_tpu.analysis.core import default_baseline_path

    if args.list_rules:
        for rule in analysis.ALL_RULES:
            print(f"{rule.id:24s} {rule.description}")
        return 0

    rule_ids = None
    if args.rules:
        rule_ids = {r.strip() for r in args.rules.split(",") if r.strip()}
        known = {r.id for r in analysis.ALL_RULES}
        unknown = rule_ids - known
        if unknown:
            print(
                f"check: unknown rule id(s) {sorted(unknown)}; "
                f"known: {sorted(known)}",
                file=sys.stderr,
            )
            return 2

    baseline = args.baseline
    if (
        baseline is not None
        and not os.path.exists(baseline)
        and not args.write_baseline  # write mode creates the file
    ):
        print(f"check: baseline {baseline!r} does not exist", file=sys.stderr)
        return 2
    if not os.path.isdir(args.root):
        print(f"check: root {args.root!r} is not a directory", file=sys.stderr)
        return 2

    t0 = time.perf_counter()
    try:
        result = analysis.run(args.root, rule_ids=rule_ids, baseline=baseline)
    except Exception as e:  # analyzer bug = unusable input, not "clean"
        print(f"check: analysis failed: {e!r}", file=sys.stderr)
        return 2
    dt = time.perf_counter() - t0

    if args.write_baseline:
        from geomesa_tpu.analysis import load_baseline

        path = baseline or default_baseline_path(args.root)
        try:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            have = load_baseline(path)
            fresh = sorted(
                {f.key for f in result.findings} - have
            )
            with open(path, "a", encoding="utf-8") as fh:
                for key in fresh:
                    fh.write(key + "\n")
        except OSError as e:
            print(f"check: cannot write baseline {path!r}: {e}", file=sys.stderr)
            return 2
        print(f"check: appended {len(fresh)} new keys to {path}")
        return 0

    if args.json:
        print(json.dumps({
            "findings": [f.to_json() for f in result.findings],
            "suppressed": [f.to_json() for f in result.suppressed],
            "clean": result.clean,
            "seconds": round(dt, 3),
        }, indent=2))
    else:
        for f in result.findings:
            print(f.render())
        if args.show_suppressed:
            for f in result.suppressed:
                print(f"suppressed: {f.render()}")
        n, s = len(result.findings), len(result.suppressed)
        print(
            f"check: {n} finding(s), {s} suppressed, "
            f"{dt * 1e3:.0f} ms"
        )
    return 0 if result.clean else 1


if __name__ == "__main__":
    sys.exit(main())
