#!/usr/bin/env python
"""The single-provenance pod scale driver (docs/distributed.md).

One script produces every `SCALE_1B.json` row, and every row it writes
carries an AT-DRIVER-TIME provenance stamp (driver, argv, UTC time,
platform, device count, git revision) — the fix for the carry-forward
problem VERDICT flags: a row whose stamp names an old revision is
visibly stale, never silently re-asserted by a later round.

The run, at every scale, is the same code path:

1. **host-local ingest** — rows generate in chunks (per-chunk seeds, so
   the brute-force referee can regenerate any chunk without holding the
   dataset), partition by owner hash, and feed one pipelined
   ``BulkLoader`` per host; per-host leg seconds accumulate so the
   host-parallel wall (slowest host) is reported next to the measured
   single-process wall;
2. **config-1 queries** — the 12-probe bbox+DURING ladder against the
   pod store, each answer checked EXACT against chunked brute-force
   recomputation over the regenerated columns (no second store, so the
   referee scales to 1e9);
3. **the fused join leg** — a >8-member same-variant ``query_many``
   batch that must take the cross-host fused dispatch (instrumented at
   the shard seam), every member exact vs brute force;
4. **streamed compaction** — ``geomesa.tpu.compact.span.rows`` bounded
   so `_stream_cols` genuinely runs many spans per column, peak RSS
   sampled and reported as a multiple of the store's column set.

``--ci`` runs the identical path at a scaled-down row count and turns
the report into assertions (exactness, fused dispatch taken, bounded
RSS) with a nonzero exit on violation — the tier-1-adjacent smoke the
1B row's code path is pinned by. Without ``--ci`` the defaults target
the full 1e9-row run (TPU pod or a large-RAM host).

Usage:
    python scripts/run_pod_scale.py --ci
    python scripts/run_pod_scale.py --rows 1000000000 --hosts 4
"""

from __future__ import annotations

import argparse
import datetime
import gc
import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

DAY = 86_400_000
T0 = 1_704_067_200_000
SEED = 20_001
SPEC = "dtg:Date,*geom:Point:srid=4326"
DUR_LO = T0 + 3 * DAY
DUR_HI = T0 + 12 * DAY


def _parse_args(argv):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--ci", action="store_true",
                    help="scaled-down assertion mode (the CI smoke)")
    ap.add_argument("--rows", type=int, default=None,
                    help="total rows (default: 2M with --ci, 1e9 without)")
    ap.add_argument("--hosts", type=int, default=4)
    ap.add_argument("--devices-per-host", type=int, default=0,
                    help="0 = even split of visible devices")
    ap.add_argument("--driver", default="sim",
                    choices=("sim", "distributed", "auto"))
    ap.add_argument("--chunk", type=int, default=500_000,
                    help="generation/referee chunk rows")
    ap.add_argument("--span-rows", type=int, default=4_194_304,
                    help="geomesa.tpu.compact.span.rows for the streamed "
                         "compaction (CI forces 65536)")
    ap.add_argument("--out", default=os.path.join(ROOT, "SCALE_1B.json"))
    return ap.parse_args(argv)


def provenance(argv) -> dict:
    try:
        rev = subprocess.run(
            ["git", "-C", ROOT, "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=10,
        ).stdout.strip() or None
    except Exception:
        rev = None
    import jax

    return {
        "driver": "scripts/run_pod_scale.py",
        "argv": list(argv),
        "time_utc": datetime.datetime.now(datetime.timezone.utc)
        .isoformat(timespec="seconds"),
        "platform": jax.default_backend(),
        "devices": len(jax.devices()),
        "python": sys.version.split()[0],
        "git_rev": rev,
    }


def _chunk_cols(ci: int, k: int):
    """Chunk ci's columns, regenerable independently of every other
    chunk (per-chunk seed): the ingest side and the brute-force referee
    call this with identical arguments and get identical rows."""
    import numpy as np

    rng = np.random.default_rng(SEED + 7 * ci)
    return (
        rng.uniform(-60, 60, k),                      # x
        rng.uniform(-45, 45, k),                      # y
        T0 + rng.integers(0, 20 * DAY, k),            # dtg ms
    )


def _probes():
    import numpy as np

    rng = np.random.default_rng(SEED + 3)
    out = []
    for i in range(12):
        # round to the filter string's 4 decimals so the brute-force
        # referee tests EXACTLY the box the store parses
        x0 = round(float(rng.uniform(-55, 40)), 4)
        y0 = round(float(rng.uniform(-40, 30)), 4)
        w, h = (4.0, 3.0) if i % 2 else (14.0, 10.0)
        # config 1 is bbox+DURING: every probe is timed
        out.append((x0, y0, round(x0 + w, 4), round(y0 + h, 4), True))
    return out


def _filter(box, timed: bool) -> str:
    f = f"bbox(geom, {box[0]:.4f}, {box[1]:.4f}, {box[2]:.4f}, {box[3]:.4f})"
    if timed:
        f += (" AND dtg DURING 2024-01-04T00:00:00Z/2024-01-13T00:00:00Z")
    return f


def _brute_counts(n: int, chunk: int, boxes) -> list:
    """Chunked brute-force truth for every probe at once: one pass over
    the regenerated columns, O(chunk) memory at any n."""
    import numpy as np

    # the DURING window above, in ms (inclusive bounds match the store)
    lo = int(np.datetime64("2024-01-04T00:00:00", "ms").astype(np.int64))
    hi = int(np.datetime64("2024-01-13T00:00:00", "ms").astype(np.int64))
    counts = [0] * len(boxes)
    ci = 0
    for s in range(0, n, chunk):
        k = min(chunk, n - s)
        x, y, t = _chunk_cols(ci, k)
        ci += 1
        for j, (x0, y0, x1, y1, timed) in enumerate(boxes):
            m = (x >= x0) & (x <= x1) & (y >= y0) & (y <= y1)
            if timed:
                # DURING: inclusive lo, exclusive hi (validate_1b.py)
                m &= (t >= lo) & (t < hi)
            counts[j] += int(m.sum())
    return counts


def run(args, argv) -> dict:
    import numpy as np

    from bench import _RssSampler, _ingest_column_set_bytes, _malloc_trim, \
        _rss_bytes
    from geomesa_tpu import conf
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.ingest.pipeline import BulkLoader
    from geomesa_tpu.parallel.dtable import DistributedIndexTable
    from geomesa_tpu.pod import make_host_group
    from geomesa_tpu.sft import FeatureType

    n = args.rows or (2_000_000 if args.ci else 1_000_000_000)
    span_rows = 65_536 if args.ci else args.span_rows
    stamp = provenance(argv)
    print(f"[pod-scale] provenance: {json.dumps(stamp)}", file=sys.stderr)

    gc.collect()
    _malloc_trim()
    rss_baseline = _rss_bytes()
    group = make_host_group(
        hosts=args.hosts,
        devices_per_host=args.devices_per_host or None,
        driver=args.driver,
    )
    H = group.hosts
    sft = FeatureType.from_spec("sc", SPEC)
    sft.user_data["geomesa.indices.enabled"] = "z3"
    ds = DataStore(mesh=group)
    ds.create_schema(sft)

    # 1. pipelined ingest into the pod store, chunked generation (the
    # pod table deals each build host-major: every host sorts/builds
    # only its own contiguous shard on its own device slice; the
    # per-host ingest differential itself is BENCH_POD.json's row)
    loader = BulkLoader(ds, "sc")
    t_ingest0 = time.perf_counter()
    ci = 0
    for s0 in range(0, n, args.chunk):
        k = min(args.chunk, n - s0)
        x, y, t = _chunk_cols(ci, k)
        ci += 1
        loader.put(FeatureCollection.from_columns(
            sft, np.arange(s0, s0 + k).astype(str),
            {"dtg": t, "geom": (x, y)},
        ))
    loader.close()
    ingest_s = time.perf_counter() - t_ingest0
    assert ds.count("sc") == n
    print(
        f"[pod-scale] ingest {n:,} rows in {ingest_s:.1f}s "
        f"({n / ingest_s:,.0f} rows/s)", file=sys.stderr,
    )

    # 2. config-1 queries, exact vs chunked brute force
    boxes = _probes()
    truth = _brute_counts(n, args.chunk, boxes)
    latencies = []
    got = []
    for box in boxes:
        f = _filter(box, box[4])
        t0 = time.perf_counter()
        got.append(int(ds.count("sc", f)))
        latencies.append(round(time.perf_counter() - t0, 4))
    queries_exact = got == truth
    print(
        f"[pod-scale] queries exact={queries_exact} "
        f"p50={sorted(latencies)[len(latencies) // 2]:.3f}s "
        f"(hits {min(truth):,}..{max(truth):,})", file=sys.stderr,
    )

    # 3. the fused join leg: >8 same-variant members so the batch
    # genuinely packs fused chunks; instrument the shard seam
    fused_calls = [0]
    orig = DistributedIndexTable._fused_raw_finishes

    def spy(self, *a, **kw):
        fused_calls[0] += 1
        return orig(self, *a, **kw)

    DistributedIndexTable._fused_raw_finishes = spy
    try:
        batch = [_filter(b, True) for b in boxes[:10]]
        outs = ds.query_many("sc", batch)
    finally:
        DistributedIndexTable._fused_raw_finishes = orig
    fused_exact = [len(o) for o in outs] == truth[:10]
    print(
        f"[pod-scale] fused join: {len(batch)} members, "
        f"{fused_calls[0]} shard legs, exact={fused_exact}",
        file=sys.stderr,
    )

    # 4. streamed compaction under a bounded span: the pipelined load
    # already built the base table, so a delta write forces the real
    # full merge-and-rebuild `_stream_cols` bounds at 1B
    lo = int(np.datetime64("2024-01-04T00:00:00", "ms").astype(np.int64))
    hi = int(np.datetime64("2024-01-13T00:00:00", "ms").astype(np.int64))
    n_delta = max(args.chunk // 5, min(n // 50, 2_000_000))
    dx, dy, dt = _chunk_cols(10_000_019, n_delta)  # reserved chunk seed
    ds.write("sc", FeatureCollection.from_columns(
        sft, np.char.add("d", np.arange(n_delta).astype(str)),
        {"dtg": dt, "geom": (dx, dy)},
    ), check_ids=False)
    b0 = boxes[0]
    delta0 = int((
        (dx >= b0[0]) & (dx <= b0[2]) & (dy >= b0[1]) & (dy <= b0[3])
        & (dt >= lo) & (dt < hi)
    ).sum())
    del dx, dy, dt
    conf.COMPACT_SPAN_ROWS.set(span_rows)
    try:
        gc.collect()
        _malloc_trim()
        column_set = _ingest_column_set_bytes(ds, "sc")
        rss_pre = _rss_bytes()
        t0 = time.perf_counter()
        with _RssSampler() as rss:
            ds.compact("sc")
        compact_s = time.perf_counter() - t0
    finally:
        conf.COMPACT_SPAN_ROWS.clear()
    # the 1B memory claim: compaction's TRANSIENT stays a small
    # multiple of one column set on top of the resident store —
    # never a second doubled copy of every column at once
    transient_over_cs = (rss.peak - rss_pre) / max(column_set, 1)
    table = next(t for (tn, _), t in ds._tables.items() if tn == "sc")
    spans_per_column = -(-table.n // max(table.block, span_rows))
    post = int(ds.count("sc", _filter(b0, b0[4])))
    compact_exact = post == truth[0] + delta0
    print(
        f"[pod-scale] streamed compaction of {n_delta:,}-row delta in "
        f"{compact_s:.1f}s, {spans_per_column} spans/column, transient "
        f"{transient_over_cs:.2f}x column set, exact={compact_exact}",
        file=sys.stderr,
    )

    row = {
        "scenario": "pod_scale_ci" if args.ci else "pod_scale",
        "n_rows": n,
        "hosts": H,
        "devices_per_host": group.devices_per_host,
        "pod_driver": group.driver,
        "ingest": {
            "measured_s": round(ingest_s, 1),
            "rows_per_s": int(n / ingest_s),
        },
        "queries": {
            "n": len(boxes),
            "exact": bool(queries_exact),
            "latencies_s": latencies,
            "p50_s": sorted(latencies)[len(latencies) // 2],
        },
        "fused_join": {
            "members": len(batch),
            "shard_legs": fused_calls[0],
            "exact": bool(fused_exact),
        },
        "compaction": {
            "streamed": True,
            "span_rows": span_rows,
            "delta_rows": int(n_delta),
            "spans_per_column": int(spans_per_column),
            "compact_s": round(compact_s, 1),
            "column_set_bytes": int(column_set),
            "rss_baseline_bytes": int(rss_baseline),
            "rss_pre_compact_bytes": int(rss_pre),
            "rss_peak_bytes": int(rss.peak),
            "transient_over_column_set": round(transient_over_cs, 3),
            "exact": bool(compact_exact),
        },
        "provenance": stamp,
    }

    if args.ci:
        failures = []
        if not queries_exact:
            failures.append(f"query counts {got} != truth {truth}")
        if not fused_exact:
            failures.append("fused join member counts diverge from truth")
        if fused_calls[0] < 1:
            failures.append("batch never took the fused dispatch")
        if not compact_exact:
            failures.append("post-compaction probe diverges")
        if spans_per_column < 10:
            failures.append(
                f"only {spans_per_column} spans/column — the bounded "
                "path did not really run"
            )
        if transient_over_cs >= 2.0:
            failures.append(
                f"compaction transient {transient_over_cs:.2f}x column "
                "set (bound 2.0)"
            )
        row["ci_failures"] = failures
        if failures:
            for f in failures:
                print(f"[pod-scale] CI FAIL: {f}", file=sys.stderr)
    return row


def write_row(out_path: str, row: dict) -> None:
    """Append to SCALE_1B.json's row list; a pre-provenance legacy
    single-object file becomes rows[0], marked carried-forward."""
    rows = []
    if os.path.exists(out_path):
        with open(out_path) as fh:
            old = json.load(fh)
        if isinstance(old, dict) and "rows" in old:
            rows = old["rows"]
        elif isinstance(old, dict):
            old.setdefault("provenance", {
                "driver": "scripts/validate_1b.py",
                "note": "pre-provenance row carried forward verbatim; "
                        "stamped rows begin with scripts/run_pod_scale.py",
            })
            rows = [old]
    rows.append(row)
    with open(out_path, "w") as fh:
        json.dump({"rows": rows}, fh, indent=1)
    print(f"[pod-scale] wrote {out_path} ({len(rows)} rows)",
          file=sys.stderr)


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    args = _parse_args(argv)
    if args.driver == "sim" and "XLA_FLAGS" not in os.environ and (
        os.environ.get("JAX_PLATFORMS", "cpu") == "cpu"
    ):
        # the sim driver needs >= hosts devices; on CPU, fork the
        # virtual-device world BEFORE jax initializes
        os.environ["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count="
            f"{max(8, args.hosts)}"
        )
    row = run(args, argv)
    write_row(args.out, row)
    print(json.dumps(row))
    return 1 if row.get("ci_failures") else 0


if __name__ == "__main__":
    sys.exit(main())
