"""Probe the device->host pull floor and candidate workarounds.

Round-3 probes measured ~66 ms per device_get regardless of size
(PERF.md §1) — the floor IS the p50 of small queries. This probe checks
whether any supported output path beats it on the tunneled runtime:

1. plain jax.device_get of jit outputs, several sizes (the baseline);
2. np.asarray on the output (same path, sanity);
3. copy_to_host_async + block, overlap-friendly variant;
4. jit with out_shardings memory_kind="pinned_host" (XLA writes the
   output into host-visible memory; the pull may skip a round trip);
5. dispatch/pull overlap: issue query B's device call before pulling
   query A's result (pipelining two in-flight queries).

Run: python scripts/probe_floor.py  (needs the TPU; ~1 min)
"""

import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, n=10):
    fn()  # warm
    ts = []
    for _ in range(n):
        t = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t)
    return float(np.median(ts)) * 1e3


def main():
    dev = jax.devices()[0]
    print("device:", dev)
    x = jax.device_put(np.arange(1 << 20, dtype=np.float32), dev)

    @jax.jit
    def f(x, n):
        return (x[:n] * 2).sum(), x[:n] * 2

    for size in (128, 1 << 12, 1 << 16, 1 << 20):
        @jax.jit
        def g(x):
            return x[:size] * 2

        out = g(x)
        out.block_until_ready()
        ms = timeit(lambda: jax.device_get(g(x)))
        print(f"device_get jit out {size * 4 / 1024:.0f} KB: {ms:.1f} ms")

    # pinned_host output
    try:
        sh = jax.sharding.SingleDeviceSharding(dev, memory_kind="pinned_host")

        @jax.jit
        def h(x):
            return x[: 1 << 12] * 2

        hp = jax.jit(h, out_shardings=sh)
        out = hp(x)
        out.block_until_ready()
        ms = timeit(lambda: np.asarray(hp(x)))
        print(f"pinned_host out 16 KB: {ms:.1f} ms")
    except Exception as e:  # noqa: BLE001
        print("pinned_host unsupported:", type(e).__name__, str(e)[:120])

    # async copy overlap
    @jax.jit
    def g2(x):
        return x[: 1 << 12] * 2

    def overlap():
        a = g2(x)
        try:
            a.copy_to_host_async()
        except Exception:  # noqa: BLE001
            pass
        b = g2(x)  # second dispatch in flight
        ra = jax.device_get(a)
        rb = jax.device_get(b)
        return ra, rb

    ms = timeit(overlap)
    print(f"two overlapped queries: {ms:.1f} ms ({ms / 2:.1f} ms each)")

    # dispatch-only cost (no pull)
    def dispatch_only():
        g2(x).block_until_ready()

    print(f"dispatch+block, no pull: {timeit(dispatch_only):.1f} ms")


if __name__ == "__main__":
    main()
