"""Profile config-4 spatial_join_indexed on the live device.

Rebuilds the exact bench config-4 store (env-scalable) and times the
join phases: scan_config (host z-ranges), submit (dispatch), pull+decode
(finish callbacks), host refine, concat. Run: python scripts/profile_join.py
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

N = int(os.environ.get("N", 20_000_000))
N_POLY = int(os.environ.get("N_POLY", 256))
SEED = 42


def gdelt_points(n, rng):
    n_clustered = n // 2
    n_uniform = n - n_clustered
    cx = rng.uniform(-160, 160, 64)
    cy = rng.uniform(-55, 65, 64)
    which = rng.integers(0, 64, n_clustered)
    x = np.concatenate([
        rng.uniform(-180, 180, n_uniform),
        np.clip(cx[which] + rng.normal(0, 3.0, n_clustered), -180, 180),
    ])
    y = np.concatenate([
        rng.uniform(-90, 90, n_uniform),
        np.clip(cy[which] + rng.normal(0, 2.0, n_clustered), -90, 90),
    ])
    return x, y


def main():
    from geomesa_tpu import geometry as geo
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.filter.predicates import BBox
    from geomesa_tpu.sft import FeatureType
    from geomesa_tpu.sql.join import spatial_join_indexed

    rng = np.random.default_rng(SEED + 30)
    x, y = gdelt_points(N, rng)
    px0 = rng.uniform(-170, 150, N_POLY)
    py0 = rng.uniform(-80, 60, N_POLY)
    pw = rng.uniform(1, 12, N_POLY)
    ph = rng.uniform(1, 8, N_POLY)
    polys = geo.PackedGeometryColumn.from_boxes(px0, py0, px0 + pw, py0 + ph)

    psft = FeatureType.from_spec("pts", "*geom:Point:srid=4326")
    psft.user_data["geomesa.indices.enabled"] = "z2"
    gsft = FeatureType.from_spec("adm", "*geom:Polygon:srid=4326")
    poly_fc = FeatureCollection.from_columns(gsft, np.arange(N_POLY), {"geom": polys})
    ds = DataStore()
    ds.create_schema(psft)
    print(f"building {N:,} point store ...", file=sys.stderr)
    ds.write("pts", FeatureCollection.from_columns(
        psft, np.arange(N), {"geom": (x, y)}), check_ids=False)

    spatial_join_indexed(ds, "pts", poly_fc, "contains")  # warmup

    # phase timing: replicate the join loop with instrumentation
    idx = next(i for i in ds.indexes("pts") if i.name == "z2")
    table = ds.table("pts", "z2")
    pts = ds.features("pts").geom_column
    lgeoms = poly_fc.geometries()

    for trial in range(3):
        t0 = time.perf_counter()
        t_cfg = t_submit = 0.0
        finishes = []
        for g in lgeoms:
            a = time.perf_counter()
            f = BBox("geom", *g.bounds())
            cfg = idx.scan_config(f)
            b = time.perf_counter()
            t_cfg += b - a
            finishes.append(table.scan_submit(cfg) if cfg and not cfg.disjoint else None)
            t_submit += time.perf_counter() - b
        t_disp = time.perf_counter() - t0

        t_pull = t_refine = 0.0
        n_pairs = 0
        n_unc = 0
        for k, fin in enumerate(finishes):
            if fin is None:
                continue
            a = time.perf_counter()
            ordinals, certain = fin()
            b = time.perf_counter()
            t_pull += b - a
            unc = np.flatnonzero(~certain)
            n_unc += len(unc)
            if len(unc):
                g = lgeoms[k]
                x0, y0, x1, y1 = g.bounds()
                ux, uy = pts.x[ordinals[unc]], pts.y[ordinals[unc]]
                ok = (ux > x0) & (ux < x1) & (uy > y0) & (uy < y1)
                keep = certain.copy()
                keep[unc] = ok
                ordinals = ordinals[keep]
            n_pairs += len(ordinals)
            t_refine += time.perf_counter() - b
        total = time.perf_counter() - t0
        print(
            f"trial {trial}: total {total*1e3:.0f} ms | dispatch {t_disp*1e3:.0f} "
            f"(scan_config {t_cfg*1e3:.0f}, submit {t_submit*1e3:.0f}) | "
            f"pull+decode {t_pull*1e3:.0f} | refine {t_refine*1e3:.0f} | "
            f"pairs {n_pairs:,} unc {n_unc:,}"
        )

    # the real entry point, for reference
    for trial in range(2):
        t0 = time.perf_counter()
        li, ri = spatial_join_indexed(ds, "pts", poly_fc, "contains")
        print(f"spatial_join_indexed: {(time.perf_counter()-t0)*1e3:.0f} ms, {len(li):,} pairs")


if __name__ == "__main__":
    main()
