"""Profile the TPU link: dispatch RTT, h2d/d2h bandwidth, scan compute rate.

Run on the real chip to size the query-path design (how many round trips a
query can afford; whether a full linear scan beats a gather)."""

import time

import jax
import jax.numpy as jnp
import numpy as np


def t(fn, n=10, warm=2):
    for _ in range(warm):
        fn()
    s = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - s) / n


def main():
    dev = jax.devices()[0]
    print(f"device: {dev}, backend: {jax.default_backend()}")

    # 1. dispatch RTT: trivial jit, block
    one = jnp.ones((8,), jnp.float32)
    f = jax.jit(lambda x: x + 1)
    f(one).block_until_ready()
    rtt = t(lambda: f(one).block_until_ready(), n=20)
    print(f"jit dispatch+sync RTT: {rtt*1e3:.2f} ms")

    # 2. d2h transfer: scalar, 4KB, 4MB, 64MB
    for nbytes in (4, 4 << 10, 4 << 20, 64 << 20):
        n = nbytes // 4
        a = jax.device_put(np.zeros(n, np.int32))
        a.block_until_ready()
        dt = t(lambda: np.asarray(a), n=5)
        print(f"d2h {nbytes:>10} B: {dt*1e3:8.2f} ms  ({nbytes/dt/1e9:6.2f} GB/s)")

    # 3. h2d transfer
    for nbytes in (4 << 10, 4 << 20, 64 << 20):
        n = nbytes // 4
        h = np.zeros(n, np.int32)
        dt = t(lambda: jax.device_put(h).block_until_ready(), n=5)
        print(f"h2d {nbytes:>10} B: {dt*1e3:8.2f} ms  ({nbytes/dt/1e9:6.2f} GB/s)")

    # 4. full-table scan: mask+count over 128M rows x (2 f32 + 2 i32)
    N = 128 * 1024 * 1024
    cols = {
        "x": jax.device_put(np.random.default_rng(0).uniform(-180, 180, N).astype(np.float32)),
        "y": jax.device_put(np.random.default_rng(1).uniform(-90, 90, N).astype(np.float32)),
        "tbin": jax.device_put(np.zeros(N, np.int32)),
        "toff": jax.device_put(np.random.default_rng(2).integers(0, 1 << 20, N).astype(np.int32)),
    }
    for v in cols.values():
        v.block_until_ready()
    nbytes = sum(int(v.nbytes) for v in cols.values())
    print(f"table bytes: {nbytes/1e9:.2f} GB")

    boxes = jnp.asarray(np.array([[-10, -10, 10, 10]] * 8, np.float32))
    windows = jnp.asarray(np.array([[0, 0, 1 << 19]] * 8, np.int32))

    @jax.jit
    def count_scan(cols, boxes, windows):
        x, y, tb, to = cols["x"], cols["y"], cols["tbin"], cols["toff"]
        m = jnp.zeros(x.shape, bool)
        for i in range(boxes.shape[0]):
            m = m | ((x >= boxes[i, 0]) & (x <= boxes[i, 2]) & (y >= boxes[i, 1]) & (y <= boxes[i, 3]))
        mw = jnp.zeros(x.shape, bool)
        for i in range(windows.shape[0]):
            mw = mw | ((tb == windows[i, 0]) & (to >= windows[i, 1]) & (to <= windows[i, 2]))
        return (m & mw).sum(dtype=jnp.int32)

    count_scan(cols, boxes, windows).block_until_ready()
    dt = t(lambda: count_scan(cols, boxes, windows).block_until_ready(), n=10)
    print(f"count scan 128M rows: {dt*1e3:.2f} ms  ({nbytes/dt/1e9:.1f} GB/s effective)")

    # 5. count + nonzero compact at CAP=1M
    CAP = 1 << 20

    @jax.jit
    def scan_compact(cols, boxes, windows):
        x, y, tb, to = cols["x"], cols["y"], cols["tbin"], cols["toff"]
        m = jnp.zeros(x.shape, bool)
        for i in range(boxes.shape[0]):
            m = m | ((x >= boxes[i, 0]) & (x <= boxes[i, 2]) & (y >= boxes[i, 1]) & (y <= boxes[i, 3]))
        for i in range(windows.shape[0]):
            pass
        mw = jnp.zeros(x.shape, bool)
        for i in range(windows.shape[0]):
            mw = mw | ((tb == windows[i, 0]) & (to >= windows[i, 1]) & (to <= windows[i, 2]))
        m = m & mw
        count = m.sum(dtype=jnp.int32)
        (idx,) = jnp.nonzero(m, size=CAP, fill_value=-1)
        return count, idx

    c, idx = scan_compact(cols, boxes, windows)
    c.block_until_ready()
    dt = t(lambda: jax.block_until_ready(scan_compact(cols, boxes, windows)), n=10)
    print(f"scan+nonzero(1M) 128M rows: {dt*1e3:.2f} ms  ({nbytes/dt/1e9:.1f} GB/s effective)")

    # 6. end-to-end query shape: dispatch + d2h of count + d2h of 64K rows
    def full_query():
        c, idx = scan_compact(cols, boxes, windows)
        n = int(c)
        rows = np.asarray(idx[: 64 * 1024])
        return n, rows

    dt = t(full_query, n=10)
    print(f"end-to-end (scan + count sync + 256KB rows d2h): {dt*1e3:.2f} ms")

    # 7. gather-based tile scan comparison (1/8 of table via 2048-tiles)
    T = N // 2048 // 8
    tiles = jnp.asarray(np.arange(T, dtype=np.int32) * 8)

    @jax.jit
    def gather_scan(cols, tiles, boxes, windows):
        base = tiles[:, None] * 2048 + jnp.arange(2048, dtype=jnp.int32)
        g = {k: v[base] for k, v in cols.items()}
        x, y, tb, to = g["x"], g["y"], g["tbin"], g["toff"]
        m = jnp.zeros(x.shape, bool)
        for i in range(boxes.shape[0]):
            m = m | ((x >= boxes[i, 0]) & (x <= boxes[i, 2]) & (y >= boxes[i, 1]) & (y <= boxes[i, 3]))
        mw = jnp.zeros(x.shape, bool)
        for i in range(windows.shape[0]):
            mw = mw | ((tb == windows[i, 0]) & (to >= windows[i, 1]) & (to <= windows[i, 2]))
        return (m & mw).sum(dtype=jnp.int32)

    gather_scan(cols, tiles, boxes, windows).block_until_ready()
    dt = t(lambda: gather_scan(cols, tiles, boxes, windows).block_until_ready(), n=10)
    print(f"gather scan 1/8 table ({T} tiles): {dt*1e3:.2f} ms  ({nbytes/8/dt/1e9:.1f} GB/s effective)")


if __name__ == "__main__":
    main()
