#!/usr/bin/env python
"""Bench regression gate: compare a fresh bench json against the recorded
baseline and FAIL on regression (docs/ingest.md "Benchmarks & regression
gate"; docs/streaming.md "Bench recipe").

Usage:
    # produce a fresh run at a SCRATCH path (never the committed
    # baseline!), then gate it against the repo's recorded file
    GEOMESA_BENCH_CONFIGS=pip_join \
        GEOMESA_BENCH_PIP_OUT=/tmp/BENCH_PIP_JOIN.json python bench.py
    python scripts/bench_gate.py --fresh /tmp/BENCH_PIP_JOIN.json

    GEOMESA_BENCH_CONFIGS=stream \
        GEOMESA_BENCH_STREAM_OUT=/tmp/BENCH_STREAM.json python bench.py
    python scripts/bench_gate.py --fresh /tmp/BENCH_STREAM.json

    GEOMESA_BENCH_CONFIGS=standing \
        GEOMESA_BENCH_GEOFENCE_OUT=/tmp/BENCH_GEOFENCE.json python bench.py
    python scripts/bench_gate.py --fresh /tmp/BENCH_GEOFENCE.json

The default --baseline is inferred from the fresh file's name
(BENCH_STREAM* gates against the committed BENCH_STREAM.json, everything
else against BENCH_PIP_JOIN.json). The gate refuses to compare a file
against itself (exit 2): a self-comparison always passes and would mask
any regression.

Checks, per scenario present in BOTH files:
- the guarded metric may not regress by more than --max-regress
  (default 0.20 = 20%) against the baseline — cost metrics
  (``raster_ms_per_q``, ``raster_ms``, ``adaptive_ms``) may not rise,
  throughput metrics (``streamed_rows_per_s``,
  ``wal_interval_rows_per_s``, ``replay_rows_per_s``) may not fall;
- every ``identical`` flag in the fresh run must be true — a speedup
  that changed answers is a bug, not a win;
- within-run bounds on the fresh file alone (FRESH_BOUNDS): the
  streaming WAL's ``sync=interval`` overhead must stay within 15% of
  the same run's no-WAL throughput (``interval_over_nowal >= 0.85``).

Exit code 0 = pass, 1 = regression / broken identity, 2 = unusable input.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# scenario -> guarded metric specs, each (field path, direction,
# fallback baseline paths): "lower" metrics are costs (regression =
# rising), "higher" metrics are throughputs (regression = falling).
# Paths are dot-nested into the scenario row ("query.fold_window_p99_ms");
# fallbacks let a renamed field gate against a baseline recorded under
# the old name (round 11: fold_window_p99_ms was in_fold_p99_ms).
SCENARIO_SPECS = {
    "z2_polygon_pip_batch": [("raster_ms_per_q", "lower", ())],
    "z2_polygon_join": [("raster_ms", "lower", ())],
    "host_grid_join": [("adaptive_ms", "lower", ())],
    "stream_sustained": [
        ("streamed_rows_per_s", "higher", ()),
        ("query.fold_window_p99_ms", "lower", ("query.in_fold_p99_ms",)),
    ],
    "stream_wal": [("wal_interval_rows_per_s", "higher", ())],
    "wal_replay": [("replay_rows_per_s", "higher", ())],
    "knn_batched": [("batched_qps", "higher", ())],
    "serving_obs": [
        ("off.qps", "higher", ()),
        ("sampled.qps", "higher", ()),
    ],
    "ops_plane": [
        ("qps_unscraped", "higher", ()),
        ("qps_scraped", "higher", ()),
    ],
    "standing_geofence": [
        ("speedup_vs_naive", "higher", ()),
        ("inverted_us_per_event", "lower", ()),
        ("matcher_on_rows_per_s", "higher", ()),
    ],
    # replication: the baseline-compared metric is the SCALING RATIO
    # (host-speed cancels out; absolute QPS and staleness wall-clock
    # swing >20% run-to-run on a shared host) — the teeth for
    # staleness/loss live in FRESH_BOUNDS, which run on every fresh
    # file; the deterministic row counts pin the bench shape and keep
    # the scenarios in the identical-flag sweep
    "replica_scaling": [("qps_scaling_2f", "higher", ())],
    "replica_staleness": [("streamed_rows", "higher", ())],
    "replica_failover": [("acked_rows", "higher", ())],
    # data plane (docs/serving.md "The data plane"): like replication,
    # absolute QPS/latency swing run-to-run on a shared host, so the
    # baseline comparison pins only deterministic shape counts (and the
    # identical-flag sweep); the fairness/durability teeth live in
    # FRESH_BOUNDS, which run on every fresh file
    "serve_http_mixed": [("cold_rows", "higher", ())],
    "serve_http_fairness": [],
    "serve_http_durability": [("acked_rows", "higher", ())],
    # live map tiles (docs/tiles.md): same shared-host reasoning — the
    # baseline comparison pins the deterministic workload shape (and
    # the identical-flag sweep); the speedup / p99 / hit-ratio /
    # invalidation teeth live in FRESH_BOUNDS
    "tiles_serving": [
        ("cold_rows", "higher", ()),
        ("zooms_measured", "higher", ()),
    ],
    "tiles_invalidation": [("warmed_tiles", "higher", ())],
    # self-tuning drift (docs/tuning.md "The drift gate"): absolute QPS
    # swings on a shared host, so the baseline comparison pins only the
    # deterministic workload shape (and the identical-flag sweep, which
    # here is the DISARMED-off-switch bit-identity oracle); the
    # degradation / oracle-ratio / decision teeth live in FRESH_BOUNDS
    "config_drift": [("n_points", "higher", ())],
    # multi-host pods (docs/distributed.md): the baseline-compared
    # metrics are the WITHIN-RUN speedup ratios (host-speed cancels
    # out, like replica_scaling); the absolute floors live in
    # FRESH_BOUNDS and the in-bench differential rides the
    # identical-flag sweep
    "pod_scan": [("scan_speedup", "higher", ())],
    "pod_ingest": [("ingest_speedup", "higher", ())],
}

# within-run invariants checked on the FRESH file alone (no baseline
# needed): scenario -> (field path, bound, kind, message). kind "min":
# the value may not fall below the bound (the ISSUE 10 WAL acceptance);
# kind "max": it may not exceed it (the round-11 pause-kill acceptance:
# fold-window query p99 within 2x steady state; the round-11 kNN bar:
# batched throughput >= 60 q/s).
FRESH_BOUNDS = {
    "stream_wal": [(
        "interval_over_nowal", 0.85, "min",
        "sync=interval throughput must stay within 15% of no-WAL",
    )],
    "stream_sustained": [(
        "query.fold_window_p99_over_steady", 2.0, "max",
        "fold-window query p99 must stay within 2x steady-state p99",
    )],
    "knn_batched": [(
        "batched_qps", 60.0, "min",
        "batched kNN must clear the 60 q/s bar (VERDICT weak #5)",
    )],
    # the ISSUE 13 observability acceptance: sampled (1/64) tracing
    # keeps >=95% of tracing-off serving QPS within the same run; the
    # live histogram p99 agrees with the offline percentile within one
    # log bucket; a captured slow-query trace explains >=90% of its
    # wall through >=5 top-level phases
    "serving_obs": [
        ("sampled_over_off", 0.95, "min",
         "sampled (1/64) tracing must keep >=95% of tracing-off QPS"),
        ("hist_p99.bucket_delta", 1.0, "max",
         "live histogram p99 must agree with offline p99 within 1 bucket"),
        ("slow_trace.phase_cover", 0.90, "min",
         "slow-query trace phases must cover >=90% of the root wall"),
        ("slow_trace.n_phases", 5.0, "min",
         "a fused batched slow query must show >=5 distinct phases"),
    ],
    # the ISSUE 15 ops-plane acceptance: a 1 Hz /metrics+/health
    # scraper costs the serving tier <=5% QPS within the same run;
    # estimate-vs-actual is recorded for >=99% of executed scans; the
    # stale-stats trigger fires on a mutated-without-analyze store and
    # clears after analyze_stats
    "ops_plane": [
        ("scraped_over_unscraped", 0.95, "min",
         "a 1 Hz /metrics+/health scraper must keep >=95% of unscraped QPS"),
        ("scrapes", 10.0, "min",
         "the scraped mode must actually have scraped (>=2 per rep)"),
        ("estimate_coverage", 0.99, "min",
         "estimate-vs-actual must be recorded for >=99% of executed scans"),
        ("stale_demonstrated", 1.0, "min",
         "the stale-stats health reason must fire on the mutated store"),
        ("stale_cleared", 1.0, "min",
         "analyze_stats must clear the stale-stats reason"),
    ],
    # the ISSUE 14 standing-query acceptance: >=1M registered geofences
    # under sustained ingest; inverted matching >=50x cheaper per event
    # than the naive all-subscription evaluation measured in the SAME
    # run; the matcher riding the ack path may not cost ingest more
    # than 10% of the matcher-off rate (also within-run)
    "standing_geofence": [
        ("subscriptions", 1_000_000.0, "min",
         "the bench must register >=1M standing geofences"),
        ("speedup_vs_naive", 50.0, "min",
         "inverted matching must be >=50x below naive per-event cost"),
        ("ingest_ratio", 0.9, "min",
         "matcher-on ingest must hold >=0.9x the matcher-off rate"),
    ],
    # the replication acceptance (docs/replication.md): two followers
    # must add real aggregate read capacity; the measured staleness
    # watermark stays bounded under sustained ingest; kill-the-leader
    # failover loses ZERO acknowledged rows and invents none
    "replica_scaling": [(
        "qps_scaling_2f", 1.5, "min",
        "aggregate read QPS at 2 followers must be >=1.5x leader-alone",
    )],
    "replica_staleness": [(
        "staleness_p99_ms", 2000.0, "max",
        "follower staleness p99 must stay bounded (the SLO default)",
    )],
    "replica_failover": [
        ("acked_loss", 0.0, "max",
         "kill-the-leader failover may lose ZERO acknowledged rows"),
        ("invented", 0.0, "max",
         "failover may not invent rows that were never written"),
    ],
    # the data-plane acceptance (docs/serving.md "The data plane"): an
    # adversarial tenant flooding the listener costs a compliant
    # tenant's read p99 at most 1.5x, the adversary is VISIBLY shed
    # (429s accounted per tenant, never silent queueing), and every
    # HTTP-acked ingest row survives kill -9 + recover
    "serve_http_fairness": [
        ("degradation", 1.5, "max",
         "compliant-tenant p99 under adversarial flood must stay <=1.5x"),
        ("adversary_shed", 1.0, "min",
         "the flooding tenant must have been visibly shed (429s)"),
    ],
    "serve_http_durability": [
        ("acked_loss", 0.0, "max",
         "HTTP-acked ingest rows may not be lost by kill -9 + recover"),
        ("invented", 0.0, "max",
         "recover may not invent rows that were never acked"),
    ],
    # the ISSUE 18 map-tile acceptance (docs/tiles.md): precomposed
    # serving >=5x the from-scratch path at matched workload across
    # >=3 zooms with the in-bench bit-identity oracle green (the
    # identical-flag sweep); warm-hit p99 bounded under sustained
    # ingest; the pyramid absorbs the warm working set; one localized
    # write invalidates ONLY touched tiles — dirty tiles recompose
    # under a new ETag while far tiles keep answering 304
    "tiles_serving": [
        ("speedup_min", 5.0, "min",
         "precomposed tiles must be >=5x from-scratch at every zoom"),
        ("zooms_measured", 3.0, "min",
         "the speedup must be measured across >=3 zooms"),
        ("warm_p99_ms", 75.0, "max",
         "tile p99 must stay bounded under sustained ingest"),
        ("hit_ratio", 0.7, "min",
         "the pyramid must absorb the warm working set (cache hits)"),
    ],
    "tiles_invalidation": [
        ("far_304", 1.0, "min",
         "a tile far from the write must keep answering 304"),
        ("touched_recomposed", 1.0, "min",
         "a tile overlapping the write must recompose with a new ETag"),
    ],
    # the ISSUE 19 self-tuning acceptance (docs/tuning.md): under the
    # drifted workload a FROZEN config degrades its own pre-drift rate
    # by >=30% while the armed controller holds within 1.5x of the
    # oracle config, records its decisions, and the disarmed store
    # stays bit-identical to a store without the tier
    "config_drift": [
        ("frozen_degradation", 1.30, "min",
         "the frozen config must degrade >=30% under the drifted workload"),
        ("tuned_over_oracle", 1.5, "max",
         "the self-tuned store must hold within 1.5x of the oracle config"),
        ("decisions_recorded", 1.0, "min",
         "the controller must RECORD the decisions that recovered the rate"),
        ("disarmed_identical", 1.0, "min",
         "geomesa.tuning.enabled=false must be bit-identical to no tier"),
    ],
    # the ISSUE 20 pod acceptance (docs/distributed.md): H=4 sim hosts
    # on the same device budget clear real speedup floors — selective
    # scan from owning-host-only dispatch, ingest from per-host 1/H
    # legs (slowest-host wall, the host-parallel model) — with the
    # in-bench pod-vs-flat differential green
    "pod_scan": [
        ("scan_speedup", 2.5, "min",
         "H=4 selective scan must clear 2.5x the flat mesh on the "
         "same device budget"),
        ("hosts", 4.0, "min",
         "the pod bench must run >= 4 sim hosts"),
    ],
    "pod_ingest": [(
        "ingest_speedup", 2.0, "min",
        "host-local ingest (slowest-host wall) must clear 2x the "
        "single flat loader",
    )],
}

# fresh-file basename marker -> committed baseline it gates against
BASELINES = {
    "BENCH_STREAM": "BENCH_STREAM.json",
    "BENCH_WAL": "BENCH_WAL.json",
    "BENCH_KNN": "BENCH_KNN.json",
    "BENCH_OBS": "BENCH_OBS.json",
    "BENCH_OPS_PLANE": "BENCH_OPS_PLANE.json",
    "BENCH_GEOFENCE": "BENCH_GEOFENCE.json",
    "BENCH_REPLICA": "BENCH_REPLICA.json",
    "BENCH_SERVE_HTTP": "BENCH_SERVE_HTTP.json",
    "BENCH_TILES": "BENCH_TILES.json",
    "BENCH_DRIFT": "BENCH_DRIFT.json",
    "BENCH_POD": "BENCH_POD.json",
}
DEFAULT_BASELINE = "BENCH_PIP_JOIN.json"


def _get(row: dict, path: str):
    """Dot-nested field lookup ("query.fold_window_p99_ms"); None when
    any step is missing."""
    cur = row
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def default_baseline(fresh_path: str, repo: str) -> str:
    name = os.path.basename(fresh_path).upper()
    for marker, baseline in BASELINES.items():
        if name.startswith(marker):
            return os.path.join(repo, baseline)
    return os.path.join(repo, DEFAULT_BASELINE)


def _rows(path: str) -> dict:
    with open(path) as fh:
        payload = json.load(fh)
    return {r["scenario"]: r for r in payload.get("rows", []) if "scenario" in r}


def gate(fresh_path: str, baseline_path: str, max_regress: float) -> int:
    if os.path.realpath(fresh_path) == os.path.realpath(baseline_path):
        print(
            "bench_gate: --fresh and --baseline are the same file; a "
            "self-comparison cannot detect a regression — write the fresh "
            "run to a scratch path (GEOMESA_BENCH_PIP_OUT / "
            "GEOMESA_BENCH_STREAM_OUT)",
            file=sys.stderr,
        )
        return 2
    try:
        fresh = _rows(fresh_path)
        base = _rows(baseline_path)
    except (OSError, ValueError, KeyError) as e:
        print(f"bench_gate: cannot read inputs: {e}", file=sys.stderr)
        return 2
    shared = [s for s in SCENARIO_SPECS if s in fresh and s in base]
    if not shared:
        print("bench_gate: no shared scenarios between fresh and baseline",
              file=sys.stderr)
        return 2
    failed = False
    for s, bounds in FRESH_BOUNDS.items():
        if s not in fresh:
            continue
        for field, bound, kind, why in bounds:
            val = _get(fresh[s], field)
            if val is None:
                continue
            val = float(val)
            bad = val < bound if kind == "min" else val > bound
            verdict = "FAIL" if bad else "ok"
            edge = "floor" if kind == "min" else "ceiling"
            print(f"{verdict:4s} {s}: {field} {val:.3f} ({edge} {bound}; {why})")
            if bad:
                failed = True
    for s in shared:
        f_row, b_row = fresh[s], base[s]
        if not f_row.get("identical", False):
            print(f"FAIL {s}: fresh run's identical flag is not true")
            failed = True
        for field, direction, fallbacks in SCENARIO_SPECS[s]:
            f_val = _get(f_row, field)
            b_val = _get(b_row, field)
            b_name = field
            for fb in fallbacks if b_val is None else ():
                b_val = _get(b_row, fb)
                if b_val is not None:
                    b_name = fb
                    break
            if f_val is None or b_val is None:
                continue
            f_val, b_val = float(f_val), float(b_val)
            if direction == "lower":
                ratio = f_val / max(b_val, 1e-12) - 1.0
            else:
                ratio = 1.0 - f_val / max(b_val, 1e-12)
            verdict = "FAIL" if ratio > max_regress else "ok"
            arrow = "rose" if direction == "lower" else "fell"
            via = "" if b_name == field else f" (baseline field {b_name})"
            print(
                f"{verdict:4s} {s}: {field} {b_val:.3f} -> {f_val:.3f} "
                f"({arrow} {ratio:+.1%}, limit +{max_regress:.0%}){via}"
            )
            if ratio > max_regress:
                failed = True
    return 1 if failed else 0


def main() -> int:
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "--fresh", required=True,
        help="freshly produced bench json (a scratch path, e.g. the "
        "GEOMESA_BENCH_PIP_OUT / GEOMESA_BENCH_STREAM_OUT target — never "
        "the committed baseline)",
    )
    ap.add_argument(
        "--baseline", default=None,
        help="recorded baseline json (default: the committed file matching "
        "the fresh file's name)",
    )
    ap.add_argument(
        "--max-regress", type=float, default=0.20,
        help="max tolerated fractional regression (default 0.20)",
    )
    args = ap.parse_args()
    baseline = args.baseline or default_baseline(args.fresh, repo)
    return gate(args.fresh, baseline, args.max_regress)


if __name__ == "__main__":
    sys.exit(main())
