import sys; sys.path.insert(0, "/root/repo")  # PYTHONPATH breaks the axon jax plugin discovery
import time
import numpy as np
from geomesa_tpu import geometry as geo
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.planning.explain import Explainer

n = 10_000_000
rng = np.random.default_rng(62)
cx = rng.uniform(-160, 160, 256); cy = rng.uniform(-55, 65, 256)
which = rng.integers(0, 256, n)
x0 = np.clip(cx[which] + rng.normal(0, 0.5, n), -179.9, 179.8)
y0 = np.clip(cy[which] + rng.normal(0, 0.4, n), -89.9, 89.8)
w = rng.uniform(0.0002, 0.002, n); h = rng.uniform(0.0002, 0.002, n)
col = geo.PackedGeometryColumn.from_boxes(x0, y0, x0+w, y0+h)
sft = FeatureType.from_spec("bld", "*geom:Polygon:srid=4326")
sft.user_data["geomesa.indices.enabled"] = "xz2"
ds = DataStore(); ds.create_schema(sft)
fc = FeatureCollection.from_columns(sft, np.arange(n), {"geom": col})
t = time.perf_counter(); ds.write("bld", fc, check_ids=False)
print("ingest", round(time.perf_counter()-t, 1), flush=True)

def qs(seed, k=12):
    r = np.random.default_rng(seed); out = []
    for _ in range(k):
        c = r.integers(0, 256); qw = float(r.choice([0.02, 0.05, 0.1, 0.5, 2.0]))
        qx = cx[c]+r.uniform(-1, 1); qy = cy[c]+r.uniform(-0.8, 0.8)
        poly = (f"POLYGON(({qx:.4f} {qy:.4f}, {qx+qw:.4f} {qy:.4f}, "
                f"{qx+qw:.4f} {qy+qw:.4f}, {qx:.4f} {qy+qw:.4f}, {qx:.4f} {qy:.4f}))")
        out.append(f"INTERSECTS(geom, {poly})")
    return out

for q in qs(1):
    ds.query("bld", q)

for q in qs(2, 8):
    e = Explainer()
    t0 = time.perf_counter()
    res = ds.query("bld", q, explain=e)
    dt = time.perf_counter() - t0
    print(f"== {dt*1000:7.1f} ms  hits={len(res.ids):6d}")
    print(e.render(), flush=True)
