"""Probe: cProfile of one XZ2 bbox query at 50M polygons, per stage.

Profiles the host side of a single extent query (range planning,
candidate pruning, decode, refinement) to find the next host hotspot.
Run on the TPU:
    python scripts/probe_xz2_stage.py
"""

import sys; sys.path.insert(0, "/root/repo")
import time, cProfile, pstats
import numpy as np
from geomesa_tpu import geometry as geo
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.planning.explain import Explainer

n = 50_000_000
rng = np.random.default_rng(62)
cx = rng.uniform(-160, 160, 256); cy = rng.uniform(-55, 65, 256)
which = rng.integers(0, 256, n)
x0 = np.clip(cx[which] + rng.normal(0, 0.5, n), -179.9, 179.8)
y0 = np.clip(cy[which] + rng.normal(0, 0.4, n), -89.9, 89.8)
w = rng.uniform(0.0002, 0.002, n); h = rng.uniform(0.0002, 0.002, n)
col = geo.PackedGeometryColumn.from_boxes(x0, y0, x0+w, y0+h)
sft = FeatureType.from_spec("bld", "*geom:Polygon:srid=4326")
sft.user_data["geomesa.indices.enabled"] = "xz2"
ds = DataStore(); ds.create_schema(sft)
fc = FeatureCollection.from_columns(sft, np.arange(n), {"geom": col})
ds.write("bld", fc, check_ids=False)

r = np.random.default_rng(20020)
# rebuild the worst query from probe seed 2: find a 2deg query with many hits
qs = []
rr = np.random.default_rng(2)
for _ in range(40):
    c = rr.integers(0, 256); qw = float(rr.choice([0.02, 0.05, 0.1, 0.5, 2.0]))
    qx = cx[c]+rr.uniform(-1, 1); qy = cy[c]+rr.uniform(-0.8, 0.8)
    qs.append((qw, qx, qy))
# warm
from geomesa_tpu.filter import ecql
def q_of(qw, qx, qy):
    return (f"INTERSECTS(geom, POLYGON(({qx:.4f} {qy:.4f}, {qx+qw:.4f} {qy:.4f}, "
            f"{qx+qw:.4f} {qy+qw:.4f}, {qx:.4f} {qy+qw:.4f}, {qx:.4f} {qy:.4f})))")
for qw, qx, qy in qs[:10]:
    ds.query("bld", q_of(qw, qx, qy))
# the biggest: run explain + cProfile
best = max(qs, key=lambda t: t[0])
q = q_of(*best)
res = ds.query("bld", q)
print("hits", len(res.ids), flush=True)
e = Explainer()
t0 = time.perf_counter()
res = ds.query("bld", q, explain=e)
print("total", round((time.perf_counter()-t0)*1e3), "ms")
print(e.render())
pr = cProfile.Profile(); pr.enable()
for _ in range(3):
    ds.query("bld", q)
pr.disable()
st = pstats.Stats(pr); st.sort_stats("cumulative")
st.print_stats(18)
