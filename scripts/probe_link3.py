"""Probe 3: scalar-arg upload cost, multi-arg h2d, and the full candidate
query design: fused mask -> per-tile counts -> tile-level sort compaction ->
gather packed bits of hit tiles -> one pull."""

import time

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial


def t(fn, n=10, warm=2):
    for _ in range(warm):
        fn()
    s = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - s) / n


def main():
    N = 128 * 1024 * 1024
    TILE = 2048
    n_tiles = N // TILE
    rng = np.random.default_rng(0)
    x = jax.device_put(rng.uniform(-180, 180, N).astype(np.float32))
    jax.block_until_ready(x)

    f1 = jax.jit(lambda x, s: (x >= s).sum(dtype=jnp.int32))
    f1(x, 0.5).block_until_ready()
    dt = t(lambda: f1(x, float(np.random.uniform())).block_until_ready(), n=10)
    print(f"jit with 1 fresh python-float arg: {dt*1e3:.2f} ms")

    f40 = jax.jit(lambda x, *s: (x >= sum(s)).sum(dtype=jnp.int32))
    args = [float(v) for v in np.random.uniform(size=40)]
    f40(x, *args).block_until_ready()
    dt = t(
        lambda: f40(x, *[float(v) for v in np.random.uniform(size=40)]).block_until_ready(),
        n=10,
    )
    print(f"jit with 40 fresh python-float args: {dt*1e3:.2f} ms")

    f2 = jax.jit(lambda x, a, b: (x >= a[0]).sum(dtype=jnp.int32) + b[0])
    a = np.zeros(16, np.float32)
    b = np.zeros(24, np.int32)
    f2(x, a, b).block_until_ready()
    dt = t(
        lambda: f2(
            x,
            np.random.uniform(size=16).astype(np.float32),
            np.random.randint(0, 5, 24).astype(np.int32),
        ).block_until_ready(),
        n=10,
    )
    print(f"jit with 2 fresh small numpy args: {dt*1e3:.2f} ms")

    # full mock query: resident cols, packed params, tile compaction, one pull
    cols = {
        "x": x,
        "y": jax.device_put(rng.uniform(-90, 90, N).astype(np.float32)),
        "tbin": jax.device_put(rng.integers(0, 17, N).astype(np.int32)),
        "toff": jax.device_put(rng.integers(0, 1 << 20, N).astype(np.int32)),
    }
    jax.block_until_ready(list(cols.values()))
    nbytes = sum(int(v.nbytes) for v in cols.values())
    M = 1024  # hit-tile slots

    @partial(jax.jit, static_argnames=("nb", "nw"))
    def query_kernel(x, y, tb, to, params, *, nb=4, nw=8):
        boxes = jax.lax.bitcast_convert_type(params[: nb * 4], jnp.float32).reshape(nb, 4)
        windows = params[nb * 4 : nb * 4 + nw * 3].astype(jnp.int32).reshape(nw, 3)
        x2 = x.reshape(n_tiles, TILE)
        y2 = y.reshape(n_tiles, TILE)
        tb2 = tb.reshape(n_tiles, TILE)
        to2 = to.reshape(n_tiles, TILE)
        m = jnp.zeros((n_tiles, TILE), bool)
        for i in range(nb):
            m |= (x2 >= boxes[i, 0]) & (x2 <= boxes[i, 2]) & (y2 >= boxes[i, 1]) & (y2 <= boxes[i, 3])
        mw = jnp.zeros((n_tiles, TILE), bool)
        for i in range(nw):
            mw |= (tb2 == windows[i, 0]) & (to2 >= windows[i, 1]) & (to2 <= windows[i, 2])
        m &= mw
        tile_counts = m.sum(axis=1, dtype=jnp.int32)
        total = tile_counts.sum()
        # pack bits: [n_tiles, TILE/32] i32
        bits = m.reshape(n_tiles, TILE // 32, 32).astype(jnp.uint32)
        packed = (bits << jnp.arange(32, dtype=jnp.uint32)[None, None, :]).sum(
            axis=2, dtype=jnp.uint32
        )
        # tile-level compaction: sort tile ids by (has-hits desc, id asc)
        key = jnp.where(tile_counts > 0, jnp.arange(n_tiles, dtype=jnp.int32), jnp.int32(1 << 30))
        hit_ids = jax.lax.sort(key)[:M]
        safe = jnp.where(hit_ids < n_tiles, hit_ids, 0)
        out_bits = packed[safe]  # [M, 64] u32
        out_counts = tile_counts[safe]
        n_hit_tiles = (tile_counts > 0).sum(dtype=jnp.int32)
        return total, n_hit_tiles, hit_ids, out_bits, out_counts

    def pack_params(boxes, windows, nb=4, nw=8):
        p = np.zeros(nb * 4 + nw * 3, np.uint32)
        b = np.full((nb, 4), np.nan, np.float32)
        b[:, 0] = np.inf
        b[:, 2] = -np.inf
        b[: len(boxes)] = boxes
        p[: nb * 4] = b.reshape(-1).view(np.uint32)
        w = np.zeros((nw, 3), np.int32)
        w[:, 0] = -1
        w[: len(windows)] = windows
        p[nb * 4 :] = w.reshape(-1).view(np.uint32)
        return p

    boxes = np.array([[-10.0, -10.0, 10.0, 10.0]], np.float32)
    windows = np.array([[3, 0, 1 << 18]], np.int32)

    def run_query():
        qx = np.random.uniform(-90, 90)
        b = boxes + np.float32(qx) * np.array([1, 0, 1, 0], np.float32)
        p = pack_params(b, windows)
        total, nh, hit_ids, out_bits, out_counts = query_kernel(
            cols["x"], cols["y"], cols["tbin"], cols["toff"], p
        )
        total = int(total)
        nh = int(nh)
        ids = np.asarray(hit_ids)
        bits = np.asarray(out_bits)
        # host decode: rows of the first few tiles
        rows = []
        for k in range(min(nh, M)):
            seg = np.unpackbits(np.ascontiguousarray(bits[k]).view(np.uint8), bitorder="little")
            rows.append(np.flatnonzero(seg) + ids[k] * TILE)
        nrows = sum(len(r) for r in rows)
        return total, nh, nrows

    r = run_query()
    print(f"mock query result: total={r[0]}, hit_tiles={r[1]}, decoded={r[2]}")
    dt = t(run_query, n=10)
    print(f"mock query end-to-end: {dt*1e3:.2f} ms  (scan {nbytes/1e9:.1f} GB -> {nbytes/dt/1e9:.0f} GB/s equiv)")

    # kernel-only (no pulls)
    p = pack_params(boxes, windows)
    dt = t(lambda: jax.block_until_ready(query_kernel(cols["x"], cols["y"], cols["tbin"], cols["toff"], p)), n=10)
    print(f"kernel-only (incl. param h2d): {dt*1e3:.2f} ms")

    # kernel with resident params (pure compute)
    pd = jax.device_put(p)
    pd.block_until_ready()
    dt = t(lambda: jax.block_until_ready(query_kernel(cols["x"], cols["y"], cols["tbin"], cols["toff"], pd)), n=10)
    print(f"kernel-only (resident params): {dt*1e3:.2f} ms")


if __name__ == "__main__":
    main()
