"""Probe: repeated density() aggregations over a 50M-point Z2 store.

Measures the steady-state cost of many density push-downs on one table
(kernel reuse after the first compile, per-query dispatch + pull floor).
Run on the TPU:
    python scripts/probe_density_many.py
"""

import sys; sys.path.insert(0, "/root/repo")
import time
import numpy as np
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType

n = 50_000_000
rng = np.random.default_rng(3)
sft = FeatureType.from_spec("d", "dtg:Date,*geom:Point:srid=4326")
ds = DataStore(); ds.create_schema(sft)
t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
ds.write("d", FeatureCollection.from_columns(
    sft, np.arange(n),
    {"dtg": t0 + rng.integers(0, 10**9, n),
     "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n))}), check_ids=False)

# 8x4 world tile grid at 256x256 per tile (one WMS heatmap frame)
reqs = []
for i in range(8):
    for j in range(4):
        x0, y0 = -180 + i * 45, -90 + j * 45
        env = (x0, y0, x0 + 45, y0 + 45)
        reqs.append((f"bbox(geom, {x0}, {y0}, {x0+45}, {y0+45})", env))
ds.density_many("d", reqs[:4])  # warm compile
t = time.perf_counter()
seq = [ds.density("d", f, envelope=e) for f, e in reqs]
t_seq = time.perf_counter() - t
t = time.perf_counter()
many = ds.density_many("d", reqs)
t_many = time.perf_counter() - t
for a, b in zip(seq, many):
    assert np.array_equal(a, b)
total = sum(float(g.sum()) for g in many)
assert abs(total - n) < 200, total  # loose f32 tile edges may double-count a handful
print(f"32-tile frame: sequential {t_seq:.2f}s, pipelined {t_many:.2f}s "
      f"({t_seq/t_many:.1f}x)")
