"""Probe 5: the candidate-block Pallas scan kernel end-to-end.

Design under test:
- cols stored [n_blocks, SUB, 128] (BLOCK = SUB*128 rows per block)
- grid over M candidate blocks, block ids scalar-prefetched (index_map DMA)
- params (wide+inner boxes/windows) as small VMEM blocks via jit args
- outputs: wide + inner packed bitplanes [M, SUB//32, 128] u32
- one batched pull, host decode via unpackbits
"""

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK = 16384
SUB = BLOCK // 128  # 128 sublanes
PACK = SUB // 32  # packed rows per plane


def scan_kernel(bids_ref, boxes_ref, wins_ref, x_ref, y_ref, tb_ref, to_ref, outw_ref, outi_ref):
    x = x_ref[0]
    y = y_ref[0]
    tb = tb_ref[0]
    to = to_ref[0]

    def box_mask(o):
        hit = jnp.zeros(x.shape, dtype=jnp.bool_)
        for k in range(8):
            hit |= (
                (x >= boxes_ref[k, 0 + o])
                & (x <= boxes_ref[k, 2 + o])
                & (y >= boxes_ref[k, 1 + o])
                & (y <= boxes_ref[k, 3 + o])
            )
        return hit

    def win_mask(o):
        hit = jnp.zeros(x.shape, dtype=jnp.bool_)
        for k in range(8):
            hit |= (
                (tb >= wins_ref[k, 0 + o])
                & (tb <= wins_ref[k, 1 + o])
                & (to >= wins_ref[k, 2 + o])
                & (to <= wins_ref[k, 3 + o])
            )
        return hit

    wide = box_mask(0) & win_mask(0)
    inner = box_mask(4) & win_mask(4)

    shifts = jnp.arange(32, dtype=jnp.int32)[None, :, None]

    def pack(m):
        u = m.astype(jnp.int32).reshape(PACK, 32, 128)
        return (u << shifts).sum(axis=1, dtype=jnp.int32)

    outw_ref[0] = pack(wide)
    outi_ref[0] = pack(inner)


@partial(jax.jit, static_argnames=("M",))
def block_scan(x3, y3, tb3, to3, bids, boxes, wins, *, M):
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(M,),
        in_specs=[
            pl.BlockSpec((8, 128), lambda i, bids: (0, 0)),
            pl.BlockSpec((8, 128), lambda i, bids: (0, 0)),
            pl.BlockSpec((1, SUB, 128), lambda i, bids: (bids[i], 0, 0)),
            pl.BlockSpec((1, SUB, 128), lambda i, bids: (bids[i], 0, 0)),
            pl.BlockSpec((1, SUB, 128), lambda i, bids: (bids[i], 0, 0)),
            pl.BlockSpec((1, SUB, 128), lambda i, bids: (bids[i], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, PACK, 128), lambda i, bids: (i, 0, 0)),
            pl.BlockSpec((1, PACK, 128), lambda i, bids: (i, 0, 0)),
        ],
    )
    return pl.pallas_call(
        scan_kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((M, PACK, 128), jnp.int32),
            jax.ShapeDtypeStruct((M, PACK, 128), jnp.int32),
        ],
    )(bids, boxes, wins, x3, y3, tb3, to3)


def decode_rows(packed, bids, n_real):
    """packed [M, PACK, 128] u32 -> global row ids (vectorized numpy)."""
    p = packed[:n_real]  # [m, PACK, 128]
    bits = np.unpackbits(p.view(np.uint8).reshape(n_real, PACK, 128, 4), axis=-1, bitorder="little")
    # bit b of u32 at [blk, j, lane] -> local row (j*32 + b)*128 + lane
    bits = bits.reshape(n_real, PACK, 128, 32).transpose(0, 1, 3, 2)  # [m, PACK, 32, 128]
    flat = bits.reshape(n_real, BLOCK)
    blk, local = np.nonzero(flat)
    return bids[:n_real][blk].astype(np.int64) * BLOCK + local


def t(fn, n=10, warm=2):
    for _ in range(warm):
        fn()
    s = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - s) / n


def main():
    N = 64 * 1024 * 1024
    n_blocks = N // BLOCK
    rng = np.random.default_rng(0)
    xh = rng.uniform(-180, 180, N).astype(np.float32)
    yh = rng.uniform(-90, 90, N).astype(np.float32)
    tbh = rng.integers(0, 18, N).astype(np.int32)
    toh = rng.integers(0, 604800, N).astype(np.int32)
    x3 = jax.device_put(xh.reshape(n_blocks, SUB, 128))
    y3 = jax.device_put(yh.reshape(n_blocks, SUB, 128))
    tb3 = jax.device_put(tbh.reshape(n_blocks, SUB, 128))
    to3 = jax.device_put(toh.reshape(n_blocks, SUB, 128))
    jax.block_until_ready([x3, y3, tb3, to3])
    print(f"cols resident: {4*N*4/1e9:.2f} GB, n_blocks={n_blocks}")

    def pack_params(bw, bi, ww, wi):
        boxes = np.zeros((8, 128), np.float32)
        boxes[:, 0] = np.inf
        boxes[:, 2] = -np.inf
        boxes[:, 4] = np.inf
        boxes[:, 6] = -np.inf
        boxes[: len(bw), 0:4] = bw
        boxes[: len(bi), 4:8] = bi
        wins = np.zeros((8, 128), np.int32)
        wins[:, 0] = 1
        wins[:, 1] = 0
        wins[:, 4] = 1
        wins[:, 5] = 0
        wins[: len(ww), 0:4] = ww
        wins[: len(wi), 4:8] = wi
        return boxes, wins

    bw = np.array([[-10, -10, 10, 10]], np.float32)
    bi = np.array([[-10, -10, 10, 10]], np.float32)
    ww = np.array([[3, 5, 0, 604799]], np.int32)
    wi = np.array([[3, 5, 0, 604799]], np.int32)
    boxes, wins = pack_params(bw, bi, ww, wi)

    for M in (128, 1024):
        bids = np.zeros(M, np.int32)
        real = np.arange(0, n_blocks, max(1, n_blocks // M), dtype=np.int32)[:M]
        bids[: len(real)] = real

        # compile
        s = time.perf_counter()
        outs = block_scan(x3, y3, tb3, to3, bids, boxes, wins, M=M)
        jax.block_until_ready(outs)
        print(f"M={M}: compile+first run {time.perf_counter()-s:.1f}s")

        dt = t(lambda: jax.block_until_ready(block_scan(x3, y3, tb3, to3, bids, boxes, wins, M=M)), n=10)
        bytes_read = M * BLOCK * 16
        print(f"M={M}: kernel {dt*1e3:.2f} ms ({bytes_read/dt/1e9:.0f} GB/s)")

        def query():
            ow, oi = block_scan(x3, y3, tb3, to3, bids, boxes, wins, M=M)
            ow_h, oi_h = jax.device_get((ow, oi))
            rows = decode_rows(ow_h, bids, len(real))
            return rows

        rows = query()
        dt = t(query, n=10)
        print(f"M={M}: end-to-end query {dt*1e3:.2f} ms, rows={len(rows)}")

    # correctness check vs numpy on the sampled blocks
    M = 128
    bids = np.zeros(M, np.int32)
    real = np.arange(0, n_blocks, max(1, n_blocks // M), dtype=np.int32)[:M]
    bids[: len(real)] = real
    ow, oi = block_scan(x3, y3, tb3, to3, bids, boxes, wins, M=M)
    rows = np.sort(decode_rows(np.asarray(ow), bids, len(real)))
    sel = np.zeros(N, bool)
    for b in real:
        sel[b * BLOCK : (b + 1) * BLOCK] = True
    m = sel & (xh >= -10) & (xh <= 10) & (yh >= -10) & (yh <= 10) & (tbh >= 3) & (tbh <= 5) & (toh >= 0) & (toh <= 604799)
    expect = np.flatnonzero(m)
    ok = len(rows) == len(expect) and np.array_equal(rows, expect)
    print(f"correctness: {ok} ({len(rows)} vs {len(expect)})")


if __name__ == "__main__":
    main()
