"""1B-row north-star validation (VERDICT r4 #4, option (a) layout).

Builds the config-1 store at N=1e9 with the packed-time z3 layout
(12 B/row device columns) and validates end to end:
- per-chip HBM accounting printed against the v5e 16 GB budget;
- a query set checked EXACTLY against chunked brute-force truth;
- a 2M recent append through the delta tier + compaction, re-checked.

On the TPU the same configuration runs via
``GEOMESA_BENCH_N=1000000000 python bench.py`` (bench.py enables
packed-time past 600M rows). This script is the CPU-backend scale
validation (PERF.md 4d at 100M, extended to 1e9): the host "device"
is RAM, so the layout, sort, scan, decode and refinement paths are the
real ones; only the kernel backend differs (XLA gather vs Pallas DMA).

Usage: JAX_PLATFORMS=cpu python scripts/validate_1b.py  [N override via
GEOMESA_1B_N]
"""

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax

jax.config.update("jax_platforms", "cpu")
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType

N = int(os.environ.get("GEOMESA_1B_N", 1_000_000_000))
DAY = 86_400_000
SEED = 7


def log(msg):
    print(f"[1b] {msg}", file=sys.stderr, flush=True)


def gen_points(n, rng):
    """GDELT-shaped points, f32, chunked generation (no f64 temporaries
    at the full N)."""
    x = np.empty(n, np.float32)
    y = np.empty(n, np.float32)
    cx = rng.uniform(-160, 160, 64)
    cy = rng.uniform(-55, 65, 64)
    chunk = 50_000_000
    for s in range(0, n, chunk):
        e = min(s + chunk, n)
        m = e - s
        half = m // 2
        x[s : s + half] = rng.uniform(-180, 180, half).astype(np.float32)
        y[s : s + half] = rng.uniform(-90, 90, half).astype(np.float32)
        which = rng.integers(0, 64, m - half)
        x[s + half : e] = np.clip(
            cx[which] + rng.normal(0, 3.0, m - half), -180, 180
        ).astype(np.float32)
        y[s + half : e] = np.clip(
            cy[which] + rng.normal(0, 2.0, m - half), -90, 90
        ).astype(np.float32)
        log(f"gen {e:,}/{n:,}")
    return x, y


def truth_count_ids(x, y, t, q, sample_cap=50):
    """Chunked brute force: (count, first ids) for one query tuple."""
    x0, y0, x1, y1, lo, hi = q
    total = 0
    ids = []
    chunk = 100_000_000
    for s in range(0, len(x), chunk):
        e = min(s + chunk, len(x))
        m = (
            (x[s:e] >= x0) & (x[s:e] <= x1)
            & (y[s:e] >= y0) & (y[s:e] <= y1)
            & (t[s:e] >= lo) & (t[s:e] < hi)
        )
        total += int(m.sum())
        if len(ids) < sample_cap:
            ids.extend((s + np.flatnonzero(m)[: sample_cap - len(ids)]).tolist())
    return total, ids


def main():
    rng = np.random.default_rng(SEED)
    t_start = time.perf_counter()
    log(f"generating {N:,} points ...")
    x, y = gen_points(N, rng)
    t0 = int(np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64))
    span = 120 * DAY
    t = t0 + rng.integers(0, span, N)
    log(f"generated in {time.perf_counter() - t_start:.0f}s")

    sft = FeatureType.from_spec("gdelt", "dtg:Date,*geom:Point:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = "z3"
    sft.user_data["geomesa.z3.packed-time"] = "true"
    ds = DataStore()
    ds.create_schema(sft)
    fc = FeatureCollection.from_columns(
        sft, np.arange(N), {"dtg": t, "geom": (x, y)}
    )
    t_in = time.perf_counter()
    ds.write("gdelt", fc, check_ids=False)
    ingest_s = time.perf_counter() - t_in
    table = ds.table("gdelt", "z3")
    tbl = getattr(table, "main", table)
    dev_gb = tbl.nbytes_device / 1e9
    log(
        f"ingest {ingest_s:.0f}s ({N / ingest_s:,.0f} rows/s); device "
        f"columns {dev_gb:.2f} GB ({tbl.nbytes_device / N:.1f} B/row) "
        f"vs v5e HBM 16 GB"
    )

    qs = []
    r = np.random.default_rng(SEED + 1)
    for _ in range(12):
        w = float(r.choice([1.0, 5.0, 20.0, 40.0]))
        qx = float(r.uniform(-175, 175 - w))
        qy = float(r.uniform(-85, 85 - w / 2))
        lo = int(t0 + r.integers(0, span - 7 * DAY))
        hi = lo + int(r.choice([1, 7, 21])) * DAY
        # round THROUGH the expr's %.4f formatting so the brute-force
        # truth tests the exact values the parser will see (an unrounded
        # bound differs by up to 5e-5 deg — at 1e9 rows that sliver holds
        # a point every few million hits)
        qs.append((
            float(f"{qx:.4f}"), float(f"{qy:.4f}"),
            float(f"{qx + w:.4f}"), float(f"{qy + w / 2:.4f}"), lo, hi,
        ))

    lat = []
    ok = 0
    for i, q in enumerate(qs):
        expr = (
            f"bbox(geom, {q[0]:.4f}, {q[1]:.4f}, {q[2]:.4f}, {q[3]:.4f}) "
            f"AND dtg DURING {np.datetime64(q[4], 'ms')}Z/"
            f"{np.datetime64(q[5], 'ms')}Z"
        )
        s = time.perf_counter()
        out = ds.query("gdelt", expr)
        lat.append(time.perf_counter() - s)
        want_n, want_ids = truth_count_ids(x, y, t, q)
        got_ids = np.asarray(out.ids)
        assert len(out) == want_n, (expr, len(out), want_n)
        assert set(want_ids) <= set(got_ids[np.isin(got_ids, want_ids)].tolist())
        ok += 1
        log(f"query {i}: {len(out):,} hits in {lat[-1]:.2f}s — exact")

    # recent-time append through the delta tier, then compaction
    n2 = 2_000_000
    t_ap = time.perf_counter()
    ds.write("gdelt", FeatureCollection.from_columns(
        sft, np.arange(N, N + n2),
        {
            "dtg": t0 + span - np.abs(r.integers(0, 3 * DAY, n2)),
            "geom": (
                r.uniform(-180, 180, n2).astype(np.float32),
                r.uniform(-90, 90, n2).astype(np.float32),
            ),
        },
    ), check_ids=False)
    append_s = time.perf_counter() - t_ap
    q = qs[0]
    expr = (
        f"bbox(geom, {q[0]:.4f}, {q[1]:.4f}, {q[2]:.4f}, {q[3]:.4f}) "
        f"AND dtg DURING {np.datetime64(q[4], 'ms')}Z/{np.datetime64(q[5], 'ms')}Z"
    )
    n_after = len(ds.query("gdelt", expr))
    log(f"append 2M in {append_s:.1f}s; post-append query {n_after:,} hits")
    # exactness across main + delta: re-check query 0's truth including
    # the appended rows (their dtg window rarely overlaps q0, but the
    # check is structural, not probabilistic)
    fc2 = ds.features("gdelt")
    ax = np.asarray(fc2.geom_column.x)[N:]
    ay = np.asarray(fc2.geom_column.y)[N:]
    at = np.asarray(fc2.columns["dtg"])[N:]
    want0, _ = truth_count_ids(x, y, t, q)
    want_extra = int(
        ((ax >= q[0]) & (ax <= q[2]) & (ay >= q[1]) & (ay <= q[3])
         & (at >= q[4]) & (at < q[5])).sum()
    )
    assert n_after == want0 + want_extra, (n_after, want0, want_extra)
    t_c = time.perf_counter()
    ds.compact("gdelt")
    compact_s = time.perf_counter() - t_c
    n_compacted = len(ds.query("gdelt", expr))
    assert n_compacted == n_after, (n_compacted, n_after)
    log(f"compaction {compact_s:.1f}s; post-compaction query exact")

    print(json.dumps({
        "n_rows": N,
        "device_bytes_per_row": round(tbl.nbytes_device / N, 2),
        "device_gb": round(dev_gb, 2),
        "hbm_budget_gb": 16.0,
        "ingest_rows_per_s": round(N / ingest_s, 1),
        "queries_exact": ok,
        "query_p50_s": round(float(np.percentile(lat, 50)), 2),
        "append_2m_s": round(append_s, 1),
        "post_append_exact": True,
        "compact_s": round(compact_s, 1),
        "backend": jax.default_backend(),
    }), flush=True)


if __name__ == "__main__":
    main()
