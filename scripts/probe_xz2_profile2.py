import sys; sys.path.insert(0, "/root/repo")
import time
import numpy as np
import jax
from geomesa_tpu import geometry as geo
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.filter import ecql
from geomesa_tpu.scan import block_kernels as bk

n = 10_000_000
rng = np.random.default_rng(62)
cx = rng.uniform(-160, 160, 256); cy = rng.uniform(-55, 65, 256)
which = rng.integers(0, 256, n)
x0 = np.clip(cx[which] + rng.normal(0, 0.5, n), -179.9, 179.8)
y0 = np.clip(cy[which] + rng.normal(0, 0.4, n), -89.9, 89.8)
w = rng.uniform(0.0002, 0.002, n); h = rng.uniform(0.0002, 0.002, n)
col = geo.PackedGeometryColumn.from_boxes(x0, y0, x0+w, y0+h)
sft = FeatureType.from_spec("bld", "*geom:Polygon:srid=4326")
sft.user_data["geomesa.indices.enabled"] = "xz2"
ds = DataStore(); ds.create_schema(sft)
fc = FeatureCollection.from_columns(sft, np.arange(n), {"geom": col})
ds.write("bld", fc, check_ids=False)
table = ds.table("bld", "xz2")
print("n_blocks total:", table.n_blocks, "cols:", table.col_names, flush=True)

idx = ds.indexes("bld")[0]

def mk(seed, k):
    r = np.random.default_rng(seed); out = []
    for _ in range(k):
        c = r.integers(0, 256); qw = float(r.choice([0.02, 0.05, 0.1, 0.5, 2.0]))
        qx = cx[c]+r.uniform(-1, 1); qy = cy[c]+r.uniform(-0.8, 0.8)
        poly = (f"POLYGON(({qx:.4f} {qy:.4f}, {qx+qw:.4f} {qy:.4f}, "
                f"{qx+qw:.4f} {qy+qw:.4f}, {qx:.4f} {qy+qw:.4f}, {qx:.4f} {qy:.4f}))")
        out.append(f"INTERSECTS(geom, {poly})")
    return out

for q in mk(1, 12):
    ds.query("bld", q)  # warm compile

for q in mk(2, 8):
    cfg = idx.scan_config(ecql.parse(q))
    t0 = time.perf_counter()
    overlap, contained = table.candidate_spans_split(cfg)
    t_spans = time.perf_counter() - t0
    blocks = table.candidate_blocks(overlap)
    blocks2 = table._full_or(blocks)
    bids, n_real = bk.pad_bids(blocks2, table.n_blocks)
    t1 = time.perf_counter()
    finish = table._device_scan_submit(blocks, cfg)
    jax.block_until_ready  # no-op marker
    t_dispatch = time.perf_counter() - t1
    t2 = time.perf_counter()
    rows, certain = finish()
    t_finish = time.perf_counter() - t2
    print(f"spans={len(overlap):4d}+{len(contained):3d}  blocks={len(blocks):5d} bucket={len(bids):5d} "
          f"spans_ms={t_spans*1e3:6.1f} dispatch_ms={t_dispatch*1e3:6.1f} "
          f"finish_ms={t_finish*1e3:6.1f} rows={len(rows):6d}", flush=True)
