"""Two-PROCESS distributed mesh probe (VERDICT r4 weak #7): each process
contributes 4 virtual CPU devices via jax.distributed, the multihost
mesh spans all 8, and a shard_map psum crosses the process boundary —
the DCN-analogue path executed for real (single machine, TCP transport).

Usage: python scripts/probe_multiprocess.py  (spawns its two workers)

Status note (round 5): in THIS build environment the axon TPU plugin
hangs jax.distributed.initialize before the CPU backend comes up, so
the live two-process run cannot complete here; on a stock JAX install
(no tunnel plugin) it runs as written. The host-major layout logic this
would exercise is pinned by tests/test_multihost_mesh.py, including a
full query path over the (hosts x devices_per_host)-shaped mesh.
"""

import os
import subprocess
import sys
import time


def worker(pid: int):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.distributed.initialize(
        coordinator_address="127.0.0.1:23417", num_processes=2, process_id=pid
    )
    import numpy as np
    from jax.sharding import PartitionSpec as P

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from geomesa_tpu.parallel.mesh import make_multihost_mesh

    mesh = make_multihost_mesh()  # 2 hosts x 4 devices, host-major
    assert mesh.devices.shape == (8,), mesh.devices.shape
    pids = [d.process_index for d in mesh.devices.ravel()]
    assert pids == sorted(pids), f"not host-major: {pids}"

    def body(x):
        return jax.lax.psum(x.sum(), "shard")

    fn = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=P("shard"), out_specs=P(),
            check_vma=False,
        )
    )
    import jax.numpy as jnp

    # each device holds one row; global array is process-sharded
    from jax.sharding import NamedSharding

    global_shape = (8, 128)
    local = np.full((4, 128), 1.0 + pid, np.float32)
    arrs = [
        jax.device_put(local[i : i + 1], d)
        for i, d in enumerate(jax.local_devices())
    ]
    x = jax.make_array_from_single_device_arrays(
        global_shape, NamedSharding(mesh, P("shard")), arrs
    )
    out = fn(x)
    got = float(np.asarray(out)[()] if np.asarray(out).shape == () else np.asarray(out).ravel()[0])
    want = 128 * 4 * (1.0 + 2.0)  # both processes' rows in one psum
    assert abs(got - want) < 1e-3, (got, want)
    if pid == 0:
        print(f"PASS: cross-process psum = {got} (expected {want})", flush=True)


def main():
    if len(sys.argv) > 1:
        worker(int(sys.argv[1]))
        return
    procs = [
        subprocess.Popen([sys.executable, os.path.abspath(__file__), str(i)])
        for i in range(2)
    ]
    rc = [p.wait(timeout=300) for p in procs]
    if any(rc):
        raise SystemExit(f"worker rcs: {rc}")
    print("two-process distributed probe: OK", flush=True)


if __name__ == "__main__":
    main()
