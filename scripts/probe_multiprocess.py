"""Two-PROCESS distributed mesh probe (VERDICT r4 weak #7): each process
contributes 4 virtual CPU devices via jax.distributed, the multihost
mesh spans all 8, and a shard_map psum crosses the process boundary —
the DCN-analogue path executed for real (single machine, TCP transport).

Usage: python scripts/probe_multiprocess.py          (spawns its two workers)
       python scripts/probe_multiprocess.py --json   (machine-readable verdict)

The ``--json`` mode is the pod host-group tier's capability probe
(geomesa_tpu/pod/hostgroup.py): it always exits 0 and prints ONE json
line ``{"supported": ..., "verdict": "supported"|"UNSUPPORTED"|"error",
"reason": ...}`` — the distributed driver and its tests key off the
verdict (skip-not-fail on CPU backends without multi-process
collectives) instead of pattern-matching exit codes.

Environment note (late round 5): the TPU tunnel plugin used to hang the
workers — its sitecustomize.py (on PYTHONPATH) monkeypatches
jax.get_backend to initialize EVERY backend, so jax.devices() blocked
on the tunnel claim whenever another process held or wedged the TPU
lease, even under JAX_PLATFORMS=cpu. The launcher now strips that site
dir from the workers' PYTHONPATH and shadows sitecustomize/jax_plugins
with empty modules; the probe then PASSES here reliably (~7 s wall,
verified while a wedged TPU claim was in flight in another process).
Run via the suite: tests/test_multihost_mesh.py::test_two_process_probe.
"""

import os
import shutil
import subprocess
import sys
import tempfile
import time


def worker(pid: int, port: int):
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{port}", num_processes=2, process_id=pid
    )
    import numpy as np
    from jax.sharding import PartitionSpec as P

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    from geomesa_tpu.parallel.dtable import _shard_map
    from geomesa_tpu.parallel.mesh import make_multihost_mesh

    mesh = make_multihost_mesh()  # 2 hosts x 4 devices, host-major
    assert mesh.devices.shape == (8,), mesh.devices.shape
    pids = [d.process_index for d in mesh.devices.ravel()]
    assert pids == sorted(pids), f"not host-major: {pids}"

    def body(x):
        return jax.lax.psum(x.sum(), "shard")

    fn = jax.jit(_shard_map(body, mesh, P("shard"), P()))
    import jax.numpy as jnp

    # each device holds one row; global array is process-sharded
    from jax.sharding import NamedSharding

    global_shape = (8, 128)
    local = np.full((4, 128), 1.0 + pid, np.float32)
    arrs = [
        jax.device_put(local[i : i + 1], d)
        for i, d in enumerate(jax.local_devices())
    ]
    x = jax.make_array_from_single_device_arrays(
        global_shape, NamedSharding(mesh, P("shard")), arrs
    )
    try:
        out = fn(x)
    except RuntimeError as e:
        if "aren't implemented on the CPU backend" in str(e):
            # this jax build's CPU client has no cross-process collective
            # transport: the probe is unsupported here, not failing
            print("UNSUPPORTED: no CPU multiprocess computations", flush=True)
            sys.exit(3)
        raise
    got = float(np.asarray(out)[()] if np.asarray(out).shape == () else np.asarray(out).ravel()[0])
    want = 128 * 4 * (1.0 + 2.0)  # both processes' rows in one psum
    assert abs(got - want) < 1e-3, (got, want)
    if pid == 0:
        print(f"PASS: cross-process psum = {got} (expected {want})", flush=True)


def probe() -> dict:
    """Launch the two workers and distill their exit codes into the
    machine-readable capability verdict (never raises):

    - ``supported``  — the cross-process psum ran and checked out;
    - ``UNSUPPORTED`` — a worker hit the backend's missing-collective
      error (exit 3): the environment can't run multi-process
      collectives, which is a skip, not a failure;
    - ``error``      — anything else (crash, timeout, port exhaustion).
    """
    try:
        rc = _launch_workers()
    except Exception as e:  # launcher infrastructure failure
        return {"supported": False, "verdict": "error",
                "reason": f"probe launcher failed: {e}", "worker_rcs": None}
    if not any(rc):
        return {"supported": True, "verdict": "supported",
                "reason": "two-process jax.distributed psum OK",
                "worker_rcs": rc}
    if 3 in rc:
        return {"supported": False, "verdict": "UNSUPPORTED",
                "reason": "no cross-process collectives on this backend "
                          "(CPU client without multiprocess computations)",
                "worker_rcs": rc}
    return {"supported": False, "verdict": "error",
            "reason": f"probe workers failed (rcs={rc})", "worker_rcs": rc}


def main():
    if sys.argv[1:2] == ["--json"]:
        import json

        print(json.dumps(probe()), flush=True)
        return
    if len(sys.argv) > 2:
        worker(int(sys.argv[1]), int(sys.argv[2]))
        return
    rc = _launch_workers()
    if not any(rc):
        print("two-process distributed probe: OK", flush=True)
        return
    if 3 in rc:
        # a worker reported UNSUPPORTED (see worker()): propagate the
        # distinct code so the suite can skip, not fail
        raise SystemExit(3)
    raise SystemExit(f"worker rcs: {rc}")


def _launch_workers() -> list:
    """Spawn the two isolated workers; return their exit codes."""
    # isolate the CPU-only workers from the TPU tunnel plugin: it
    # injects via a sitecustomize.py on PYTHONPATH that monkeypatches
    # jax.get_backend to initialize EVERY backend — jax.devices() then
    # blocks on the tunnel claim whenever another process holds (or
    # wedges) the TPU lease, regardless of JAX_PLATFORMS=cpu. Strip its
    # site dir from the workers' PYTHONPATH and shadow sitecustomize +
    # the jax_plugins namespace with empty modules.
    shadow = tempfile.mkdtemp(prefix="noplug_")
    os.makedirs(os.path.join(shadow, "jax_plugins"), exist_ok=True)
    open(os.path.join(shadow, "jax_plugins", "__init__.py"), "w").close()
    open(os.path.join(shadow, "sitecustomize.py"), "w").close()
    env = dict(os.environ)
    kept = [
        p for p in env.get("PYTHONPATH", "").split(os.pathsep)
        if p and "axon_site" not in p
    ]
    env["PYTHONPATH"] = os.pathsep.join([shadow] + kept)
    import socket

    try:
        for attempt in range(2):
            # fresh coordinator port per run: a fixed one collides with
            # an earlier run's TIME_WAIT/stale workers. bind-then-close
            # is racy (another process can grab the port before worker 0
            # binds it), hence the one retry with a new port.
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                port = s.getsockname()[1]
            procs = [
                subprocess.Popen(
                    [sys.executable, os.path.abspath(__file__), str(i), str(port)],
                    env=env,
                )
                for i in range(2)
            ]
            try:
                # one shared 90 s deadline per attempt (not per worker):
                # two attempts total ~185 s, safely under the suite
                # wrapper's 240 s cap, so OUR finally-kill reaps the
                # workers rather than the test runner orphaning them
                # with the launcher
                attempt_deadline = time.monotonic() + 90
                rc = [
                    p.wait(timeout=max(attempt_deadline - time.monotonic(), 1))
                    for p in procs
                ]
            except subprocess.TimeoutExpired:
                rc = [1, 1]
            finally:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
            if not any(rc) or 3 in rc:
                return rc
            if attempt == 0:
                print(f"worker rcs: {rc}; retrying on a fresh port", flush=True)
    finally:
        shutil.rmtree(shadow, ignore_errors=True)
    return rc


if __name__ == "__main__":
    main()
