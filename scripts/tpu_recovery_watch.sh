#!/bin/bash
# Round-5 tunnel-recovery watcher: probe the TPU at low frequency (no
# retry thrash); when PJRT init succeeds, immediately capture (1) the
# full five-config bench (refreshes BENCH_LAST_GOOD.json with the
# fused-join numbers) and (2) the 1B-row config-1 run. Hard deadline
# leaves the device free for the driver's end-of-round bench.
set -u
cd /root/repo
DEADLINE=${DEADLINE:-"14:15"}
LOG=/root/repo/tpu_watch.log
echo "watch start $(date)" >> "$LOG"

deadline_epoch=$(date -d "today $DEADLINE" +%s)

while true; do
  now=$(date +%s)
  if [ "$now" -ge "$deadline_epoch" ]; then
    echo "deadline reached $(date); stopping watch" >> "$LOG"
    exit 0
  fi
  if timeout 150 python -c "import jax; d=jax.devices(); assert d" >/dev/null 2>&1; then
    echo "TPU recovered at $(date); starting full bench" >> "$LOG"
    break
  fi
  echo "probe failed $(date)" >> "$LOG"
  sleep 780
done

# full five-config driver-grade run (no overrides -> updates last-good);
# needs ~75 min — if recovery came too late, leave the device for the
# driver's own end-of-round run instead of colliding with it
now=$(date +%s)
if [ $((deadline_epoch - now)) -lt 5400 ]; then
  echo "recovered too late for a full bench ($(date)); leaving TPU idle" >> "$LOG"
  exit 0
fi
timeout $((deadline_epoch - now)) python bench.py \
  > /root/repo/bench_r5_refresh.log 2> /root/repo/bench_r5_refresh.err
echo "full bench rc=$? at $(date)" >> "$LOG"

now=$(date +%s)
if [ $((deadline_epoch - now)) -lt 7200 ]; then
  echo "not enough time for the 1B run ($(date)); stopping" >> "$LOG"
  exit 0
fi
GEOMESA_BENCH_N=1000000000 GEOMESA_BENCH_CONFIGS=1 GEOMESA_BENCH_INIT_RETRIES=2 \
  timeout $((deadline_epoch - $(date +%s) - 300)) python bench.py \
  > /root/repo/bench_1b_final.log 2> /root/repo/bench_1b_final.err
echo "1B bench rc=$? at $(date)" >> "$LOG"
