"""Probe: XZ2 ingest pipeline wall-time split at 50M polygons.

Times the write path stage by stage (geometry build, write-key encode,
sort, device upload) for an extent store — the numbers behind the
pipelined-ingest design in docs/ingest.md. Run on the TPU:
    python scripts/probe_xz2_pipeline.py
"""

import sys; sys.path.insert(0, "/root/repo")
import time
import numpy as np
from geomesa_tpu import geometry as geo
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType

n = 50_000_000
rng = np.random.default_rng(62)
cx = rng.uniform(-160, 160, 256); cy = rng.uniform(-55, 65, 256)
which = rng.integers(0, 256, n)
x0 = np.clip(cx[which] + rng.normal(0, 0.5, n), -179.9, 179.8)
y0 = np.clip(cy[which] + rng.normal(0, 0.4, n), -89.9, 89.8)
w = rng.uniform(0.0002, 0.002, n); h = rng.uniform(0.0002, 0.002, n)
col = geo.PackedGeometryColumn.from_boxes(x0, y0, x0+w, y0+h)
sft = FeatureType.from_spec("bld", "*geom:Polygon:srid=4326")
sft.user_data["geomesa.indices.enabled"] = "xz2"
ds = DataStore(); ds.create_schema(sft)
ds.write("bld", FeatureCollection.from_columns(sft, np.arange(n), {"geom": col}), check_ids=False)

def mk(seed, k):
    r = np.random.default_rng(seed); out = []
    for _ in range(k):
        c = r.integers(0, 256); qw = float(r.choice([0.02, 0.05, 0.1, 0.5, 2.0]))
        qx = cx[c]+r.uniform(-1, 1); qy = cy[c]+r.uniform(-0.8, 0.8)
        out.append(f"INTERSECTS(geom, POLYGON(({qx:.4f} {qy:.4f}, {qx+qw:.4f} {qy:.4f}, "
                   f"{qx+qw:.4f} {qy+qw:.4f}, {qx:.4f} {qy+qw:.4f}, {qx:.4f} {qy:.4f})))")
    return out

for q in mk(1, 40):
    ds.query("bld", q)

qs = mk(2, 40)
t = time.perf_counter()
seq = [ds.query("bld", q) for q in qs]
t_seq = time.perf_counter() - t
t = time.perf_counter()
pipe = ds.query_many("bld", qs)
t_pipe = time.perf_counter() - t
hits = sum(len(r.ids) for r in seq)
assert [sorted(r.ids.tolist()) for r in seq] == [sorted(r.ids.tolist()) for r in pipe]
print(f"sequential: {t_seq:.2f}s ({hits/t_seq:,.0f} features/s)")
print(f"pipelined : {t_pipe:.2f}s ({hits/t_pipe:,.0f} features/s)  speedup {t_seq/t_pipe:.2f}x")
