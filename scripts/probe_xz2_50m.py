"""Probe: XZ2 (extent) query path end to end at 50M polygons.

Builds a 50M-row extent store (clustered small boxes), then times bbox
queries across selectivities — the wide-only plane rule and the XZ
candidate pruning under real skew. Run on the TPU:
    python scripts/probe_xz2_50m.py
"""

import sys; sys.path.insert(0, "/root/repo")
import time
import numpy as np
import jax
from geomesa_tpu import geometry as geo
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.filter import ecql
from geomesa_tpu.scan import block_kernels as bk

n = 50_000_000
rng = np.random.default_rng(62)
cx = rng.uniform(-160, 160, 256); cy = rng.uniform(-55, 65, 256)
which = rng.integers(0, 256, n)
x0 = np.clip(cx[which] + rng.normal(0, 0.5, n), -179.9, 179.8)
y0 = np.clip(cy[which] + rng.normal(0, 0.4, n), -89.9, 89.8)
w = rng.uniform(0.0002, 0.002, n); h = rng.uniform(0.0002, 0.002, n)
col = geo.PackedGeometryColumn.from_boxes(x0, y0, x0+w, y0+h)
sft = FeatureType.from_spec("bld", "*geom:Polygon:srid=4326")
sft.user_data["geomesa.indices.enabled"] = "xz2"
ds = DataStore(); ds.create_schema(sft)
fc = FeatureCollection.from_columns(sft, np.arange(n), {"geom": col})
t = time.perf_counter(); ds.write("bld", fc, check_ids=False)
print("ingest", round(time.perf_counter()-t, 1), flush=True)
table = ds.table("bld", "xz2")
print("n_blocks:", table.n_blocks, flush=True)
idx = ds.indexes("bld")[0]

def mk(seed, k):
    r = np.random.default_rng(seed); out = []
    for _ in range(k):
        c = r.integers(0, 256); qw = float(r.choice([0.02, 0.05, 0.1, 0.5, 2.0]))
        qx = cx[c]+r.uniform(-1, 1); qy = cy[c]+r.uniform(-0.8, 0.8)
        poly = (f"POLYGON(({qx:.4f} {qy:.4f}, {qx+qw:.4f} {qy:.4f}, "
                f"{qx+qw:.4f} {qy+qw:.4f}, {qx:.4f} {qy+qw:.4f}, {qx:.4f} {qy:.4f}))")
        out.append((qw, f"INTERSECTS(geom, {poly})"))
    return out

t=time.perf_counter()
for _, q in mk(1, 40):
    ds.query("bld", q)
print("warmup", round(time.perf_counter()-t,1), flush=True)

rows_out = []
for qw, q in mk(2, 40):
    cfg = idx.scan_config(ecql.parse(q))
    t0 = time.perf_counter()
    overlap, contained = table.candidate_spans_split(cfg)
    t_spans = time.perf_counter() - t0
    blocks = table.candidate_blocks(overlap)
    bids, n_real = bk.pad_bids(table._full_or(blocks), table.n_blocks)
    t1 = time.perf_counter()
    res = ds.query("bld", q)
    t_q = time.perf_counter() - t1
    cont_rows = sum(z - a for a, z in contained)
    rows_out.append((t_q, qw, len(overlap), len(contained), cont_rows,
                     len(blocks), len(bids), t_spans, len(res.ids)))
rows_out.sort(reverse=True)
print(" q_ms |  qw  | ov | cont | cont_rows | blocks | bucket | spans_ms | hits")
for t_q, qw, ov, co, cr, bl, bu, ts, h in rows_out[:12]:
    print(f"{t_q*1e3:6.0f} | {qw:4.2f} | {ov:3d} | {co:3d} | {cr:9d} | {bl:6d} | {bu:6d} | {ts*1e3:7.1f} | {h}")
tot = sum(r[0] for r in rows_out); hits = sum(r[-1] for r in rows_out)
lat = sorted(r[0] for r in rows_out)
print(f"mean {tot/40*1e3:.0f} ms  p50 {lat[20]*1e3:.0f}  p99 {lat[-1]*1e3:.0f}  hits {hits}")
