"""Probe 2: cache-busted d2h, small-arg jit call cost, and the candidate
fast-scan design (linear pass + per-tile counts + packed bitmask)."""

import time

import jax
import jax.numpy as jnp
import numpy as np


def t(fn, n=10, warm=2):
    for _ in range(warm):
        fn()
    s = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - s) / n


def main():
    print(f"jax {jax.__version__}, device: {jax.devices()[0]}")

    # d2h, cache-busted: fresh array per call via tiny device compute
    for nbytes in (4, 256 << 10, 4 << 20, 16 << 20, 64 << 20):
        n = max(nbytes // 4, 1)
        a = jax.device_put(np.zeros(n, np.int32))
        bump = jax.jit(lambda x, i: x + i)
        outs = [bump(a, i) for i in range(12)]
        jax.block_until_ready(outs)
        k = [0]

        def pull():
            np.asarray(outs[k[0]])
            k[0] += 1

        s = time.perf_counter()
        for _ in range(10):
            pull()
        dt = (time.perf_counter() - s) / 10
        print(f"d2h {nbytes:>10} B: {dt*1e3:8.2f} ms  ({nbytes/dt/1e9:6.2f} GB/s)")

    # small-arg jit call: numpy args uploaded per call
    N = 128 * 1024 * 1024
    x = jax.device_put(np.random.default_rng(0).uniform(-180, 180, N).astype(np.float32))
    x.block_until_ready()

    f = jax.jit(lambda x, p: (x >= p[0]).sum(dtype=jnp.int32))
    p = np.array([0.5, 1.0], np.float32)
    f(x, p).block_until_ready()
    dt = t(lambda: f(x, jnp.asarray(np.random.uniform(size=2).astype(np.float32))).block_until_ready(), n=10)
    print(f"jit with fresh 8B numpy arg: {dt*1e3:.2f} ms")

    big = np.zeros(256, np.float32)
    g = jax.jit(lambda x, p: (x >= p[0]).sum(dtype=jnp.int32))
    g(x, big).block_until_ready()
    dt = t(lambda: g(x, np.random.uniform(size=256).astype(np.float32)).block_until_ready(), n=10)
    print(f"jit with fresh 1KB numpy arg: {dt*1e3:.2f} ms")

    # single-pass fused predicate via broadcast-in-one-read
    cols = {
        "x": x,
        "y": jax.device_put(np.random.default_rng(1).uniform(-90, 90, N).astype(np.float32)),
        "tbin": jax.device_put(np.random.default_rng(3).integers(0, 17, N).astype(np.int32)),
        "toff": jax.device_put(np.random.default_rng(2).integers(0, 1 << 20, N).astype(np.int32)),
    }
    jax.block_until_ready(list(cols.values()))
    nbytes = sum(int(v.nbytes) for v in cols.values())
    TILE = 2048
    n_tiles = N // TILE

    @jax.jit
    def scan3(x, y, tb, to, boxes, windows):
        # [N, B] broadcast: one read of each column, fused compare-reduce
        bx = (
            (x[:, None] >= boxes[None, :, 0])
            & (x[:, None] <= boxes[None, :, 2])
            & (y[:, None] >= boxes[None, :, 1])
            & (y[:, None] <= boxes[None, :, 3])
        ).any(axis=1)
        tw = (
            (tb[:, None] == windows[None, :, 0])
            & (to[:, None] >= windows[None, :, 1])
            & (to[:, None] <= windows[None, :, 2])
        ).any(axis=1)
        m = bx & tw
        mt = m.reshape(n_tiles, TILE)
        tile_counts = mt.sum(axis=1, dtype=jnp.int32)
        bits = mt.reshape(n_tiles * TILE // 8, 8).astype(jnp.uint8)
        packed = (bits << jnp.arange(8, dtype=jnp.uint8)[None, :]).sum(axis=1, dtype=jnp.uint8)
        return tile_counts, packed

    boxes = np.array([[-10, -10, 10, 10]] * 8, np.float32)
    windows = np.array([[0, 0, 1 << 19]] * 8, np.int32)
    r = scan3(cols["x"], cols["y"], cols["tbin"], cols["toff"], boxes, windows)
    jax.block_until_ready(r)
    dt = t(
        lambda: jax.block_until_ready(
            scan3(cols["x"], cols["y"], cols["tbin"], cols["toff"], boxes, windows)
        ),
        n=10,
    )
    print(f"scan3 (counts+bitmask, no pull) 128M: {dt*1e3:.2f} ms  ({nbytes/dt/1e9:.1f} GB/s)")

    # end-to-end: scan + pull counts + pull packed bitmask + host nonzero
    def query():
        tc, packed = scan3(cols["x"], cols["y"], cols["tbin"], cols["toff"], boxes, windows)
        tc = np.asarray(tc)
        hit_tiles = np.flatnonzero(tc)
        pk = np.asarray(packed)  # full 16MB pull
        rows = []
        for tile in hit_tiles[:64]:
            seg = np.unpackbits(pk[tile * (TILE // 8) : (tile + 1) * (TILE // 8)])
            rows.append(np.flatnonzero(seg) + tile * TILE)
        return hit_tiles

    query()
    dt = t(query, n=8)
    print(f"end-to-end query (scan + 2 pulls + host nonzero): {dt*1e3:.2f} ms")

    # variant: segment the packed pull to hit tiles only (one fancy-index on device? no —
    # host-side slice of the packed array per contiguous run)
    def query2():
        tc, packed = scan3(cols["x"], cols["y"], cols["tbin"], cols["toff"], boxes, windows)
        tc = np.asarray(tc)
        hit_tiles = np.flatnonzero(tc)
        return hit_tiles, int(tc.sum())

    dt = t(query2, n=8)
    print(f"query2 (scan + counts pull only): {dt*1e3:.2f} ms")


if __name__ == "__main__":
    main()
