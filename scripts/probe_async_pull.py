"""Probe: device->host pull strategies for many small independent kernels.

Dispatches 40 tiny jit calls and compares serialized synchronous pulls
against copy_to_host_async + one batched device_get (the overlap the
fused scan's _fused_pull relies on). Run on the TPU:
    python scripts/probe_async_pull.py
"""

import sys; sys.path.insert(0, "/root/repo")
import time
import numpy as np
import jax
import jax.numpy as jnp

# 40 small independent kernels; compare: sync pulls, async-copy pulls
@jax.jit
def f(x):
    return (x * 2 + 1).sum(axis=-1).astype(jnp.int32)

xs = [jnp.ones((128, 1024), jnp.float32) + i for i in range(40)]
for x in xs[:2]:
    np.asarray(f(x))

# sync: dispatch+pull one by one
t = time.perf_counter()
outs = [np.asarray(f(x)) for x in xs]
t_sync = time.perf_counter() - t

# pipelined: dispatch all, then pull
t = time.perf_counter()
ys = [f(x) for x in xs]
outs2 = [np.asarray(y) for y in ys]
t_pipe = time.perf_counter() - t

# pipelined + copy_to_host_async
t = time.perf_counter()
ys = [f(x) for x in xs]
for y in ys:
    y.copy_to_host_async()
outs3 = [np.asarray(y) for y in ys]
t_async = time.perf_counter() - t

print(f"sync {t_sync*1e3:.0f} ms | dispatch-all {t_pipe*1e3:.0f} ms | +copy_to_host_async {t_async*1e3:.0f} ms")
