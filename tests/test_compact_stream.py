"""Streamed compaction (`IndexTable._stream_cols`; docs/ingest.md
"Memory model"): the 1B-row code path pinned in tier-1 at CI scale.

Two contracts, both with ``geomesa.tpu.compact.span.rows`` forced small
so the bounded gather genuinely runs MANY spans per column:

- **exactness** — a compaction streamed through tiny spans produces a
  table bit-identical (counts, ids, sorted keys) to one built with the
  default span;
- **bounded memory** — compaction peak RSS stays under the DECLARED
  column-set multiple (the ``compaction.peak_over_column_set``
  criterion BENCH_INGEST.json records at 100M rows): ~one transient
  column family, never a doubled column set. Run in a fresh SUBPROCESS
  with a phase-scoped sampler, so other tests' allocator history can't
  pollute the measurement.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from geomesa_tpu import conf
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
DAY = 86_400_000
T0 = 1_704_067_200_000

# the declared bound: store-attributable compaction peak over the full
# column set. The classic (pre-stream) build materialized a second
# sorted copy of every column at once (>= 2x + the device set); the
# streamed build holds ~one span + one column + the new device columns.
PEAK_OVER_COLUMN_SET_MAX = 2.0


def _store(n, seed=3, span_blocks=None):
    sft = FeatureType.from_spec("cmp", "val:Double,dtg:Date,*geom:Point:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = "z3"
    ds = DataStore()
    ds.create_schema(sft)
    rng = np.random.default_rng(seed)
    ds.write("cmp", FeatureCollection.from_columns(
        sft, np.arange(n, dtype=np.int64),
        {"val": rng.uniform(0, 1, n),
         "dtg": T0 + rng.integers(0, 40 * DAY, n),
         "geom": (rng.uniform(-70, 70, n), rng.uniform(-50, 50, n))},
    ), check_ids=False)
    return ds


def _fingerprint(ds):
    import hashlib

    h = hashlib.blake2b(digest_size=16)
    for (tn, name), t in sorted(ds._tables.items()):
        h.update(f"{tn}/{name}/{t.n}/{t.n_blocks}".encode())
        h.update(np.ascontiguousarray(np.asarray(t.perm)).tobytes())
        h.update(np.ascontiguousarray(np.asarray(t.zs)).tobytes())
    return h.hexdigest()


class TestStreamedExactness:
    def test_tiny_spans_build_the_identical_table(self):
        """Force the span to ONE BLOCK of rows (the maximal span count)
        and compare against the default multi-million-row span: sorted
        keys, block layout and every query answer must be identical."""
        n = 120_000
        ref = _store(n)
        ref.compact("cmp")
        conf.COMPACT_SPAN_ROWS.set(1)  # clamps up to one block per span
        try:
            tiny = _store(n)
            tiny.compact("cmp")
        finally:
            conf.COMPACT_SPAN_ROWS.clear()
        assert _fingerprint(tiny) == _fingerprint(ref)
        queries = [
            "bbox(geom, -10, -10, 10, 10)",
            "bbox(geom, 5, 5, 40, 30) AND "
            "dtg DURING 2024-01-03T00:00:00Z/2024-01-19T00:00:00Z",
            "INCLUDE",
        ]
        for q in queries:
            a, b = tiny.query("cmp", q), ref.query("cmp", q)
            assert sorted(np.asarray(a.ids).tolist()) == \
                sorted(np.asarray(b.ids).tolist())
            assert tiny.count("cmp", q) == ref.count("cmp", q) == len(b)


_RSS_SCRIPT = r"""
import gc, json, os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {root!r})
import numpy as np
from bench import _RssSampler, _ingest_column_set_bytes, _malloc_trim, _rss_bytes
from geomesa_tpu import conf
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType

n = {n}
gc.collect(); _malloc_trim()
rss_baseline = _rss_bytes()  # bare process: interpreter + jax + XLA

sft = FeatureType.from_spec("cmp", "val:Double,dtg:Date,*geom:Point:srid=4326")
sft.user_data["geomesa.indices.enabled"] = "z3"
ds = DataStore()
ds.create_schema(sft)
rng = np.random.default_rng(7)
ds.write("cmp", FeatureCollection.from_columns(
    sft, np.arange(n, dtype=np.int64),
    {{"val": rng.uniform(0, 1, n),
      "dtg": 1_704_067_200_000 + rng.integers(0, 40 * 86_400_000, n),
      "geom": (rng.uniform(-70, 70, n), rng.uniform(-50, 50, n))}},
), check_ids=False)
probe_before = ds.count("cmp", "bbox(geom, -10, -10, 0, 0)")

# the CI-scale bounded-memory setting: many spans per column
conf.COMPACT_SPAN_ROWS.set({span_rows})
gc.collect(); _malloc_trim()
column_set = _ingest_column_set_bytes(ds, "cmp")
with _RssSampler() as rss:
    ds.compact("cmp")
peak_over_cs = (rss.peak - rss_baseline) / max(column_set, 1)
probe_after = ds.count("cmp", "bbox(geom, -10, -10, 0, 0)")
table = next(t for (tn, _), t in ds._tables.items() if tn == "cmp")
print(json.dumps({{
    "n": n,
    "span_rows": {span_rows},
    "spans_per_column": -(-table.n // max(table.block, {span_rows})),
    "block": table.block,
    "column_set_bytes": column_set,
    "rss_baseline_bytes": rss_baseline,
    "rss_peak_bytes": rss.peak,
    "peak_over_column_set": round(peak_over_cs, 3),
    "probe_before": int(probe_before),
    "probe_after": int(probe_after),
    "total": int(ds.count("cmp")),
}}))
"""


class TestBoundedRss:
    def test_compaction_peak_under_declared_column_set_multiple(self):
        """The 1B run's memory contract at CI scale: with the span
        forced to 64Ki rows (dozens of spans per column) the compaction
        peak stays under PEAK_OVER_COLUMN_SET_MAX x the column set —
        measured in a fresh subprocess whose RSS history is exactly
        (interpreter + jax + this store), the same accounting
        BENCH_INGEST.json's ``compaction.peak_over_column_set`` row
        uses at 100M rows."""
        n = 1_500_000
        out = subprocess.run(
            [sys.executable, "-c",
             _RSS_SCRIPT.format(root=ROOT, n=n, span_rows=65_536)],
            capture_output=True, text=True, timeout=420,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PYTHONPATH": ROOT, "XLA_FLAGS": ""},
        )
        assert out.returncode == 0, out.stderr[-2000:]
        row = json.loads(out.stdout.splitlines()[-1])
        assert row["total"] == n
        assert row["probe_after"] == row["probe_before"] > 0  # exactness
        assert row["spans_per_column"] >= 10  # the bounded path REALLY ran
        assert row["peak_over_column_set"] < PEAK_OVER_COLUMN_SET_MAX, row
