"""Pallas scan kernel: parity with the XLA gather path (interpret mode on
the CPU test platform; the same kernel compiles on TPU)."""

import numpy as np
import pytest

import jax.numpy as jnp

from geomesa_tpu.scan import kernels, pallas_kernels

TILE = 1024  # multiple of 8 * 128


def _cols(n_pad, with_time=True, extent=False, seed=0):
    rng = np.random.default_rng(seed)
    cols = {}
    if extent:
        x0 = rng.uniform(-180, 179, n_pad).astype(np.float32)
        y0 = rng.uniform(-90, 89, n_pad).astype(np.float32)
        cols["gxmin"] = x0
        cols["gymin"] = y0
        cols["gxmax"] = x0 + rng.uniform(0, 5, n_pad).astype(np.float32)
        cols["gymax"] = y0 + rng.uniform(0, 5, n_pad).astype(np.float32)
    else:
        cols["x"] = rng.uniform(-180, 180, n_pad).astype(np.float32)
        cols["y"] = rng.uniform(-90, 90, n_pad).astype(np.float32)
    if with_time:
        cols["tbin"] = rng.integers(2800, 2805, n_pad).astype(np.int32)
        cols["toff"] = rng.integers(0, 604800, n_pad).astype(np.int32)
    # sentinel-pad the tail like IndexTable does
    for k in ("x", "gxmin"):
        if k in cols:
            cols[k][-7:] = np.inf
    if "tbin" in cols:
        cols["tbin"][-7:] = -1
    return {k: jnp.asarray(v) for k, v in cols.items()}


def _mask_pair(cols, tile_ids, boxes, windows, extent=False):
    m_x, base_x = kernels._tile_mask(cols, tile_ids, boxes, windows, TILE, extent)
    names = tuple(sorted(cols))
    blocks = tuple(cols[k].reshape(-1, TILE // 128, 128) for k in names)
    m_p = pallas_kernels.pallas_tile_mask(
        blocks, tile_ids, boxes, windows,
        tile=TILE, extent_mode=extent, col_names=names, interpret=True,
    )
    return np.asarray(m_x), np.asarray(m_p), base_x


class TestPallasParity:
    def test_boxes_and_windows(self):
        cols = _cols(8 * TILE)
        tile_ids = kernels.pad_tiles(np.array([0, 2, 3, 7]))
        boxes = kernels.pad_boxes(np.array([[-20.0, -10.0, 40.0, 35.0], [100.0, 0.0, 160.0, 50.0]]))
        windows = kernels.pad_windows(np.array([[2801, 0, 604799], [2803, 1000, 300000]]))
        mx, mp, _ = _mask_pair(cols, tile_ids, boxes, windows)
        assert mx.any()
        np.testing.assert_array_equal(mx, mp)

    def test_boxes_only(self):
        cols = _cols(4 * TILE, with_time=False)
        tile_ids = kernels.pad_tiles(np.array([1, 3]))
        boxes = kernels.pad_boxes(np.array([[-50.0, -50.0, 50.0, 50.0]]))
        mx, mp, _ = _mask_pair(cols, tile_ids, boxes, None)
        np.testing.assert_array_equal(mx, mp)

    def test_no_predicates_validity_only(self):
        cols = _cols(2 * TILE, with_time=False)
        tile_ids = kernels.pad_tiles(np.array([0, 1]))
        mx, mp, _ = _mask_pair(cols, tile_ids, None, None)
        # pad rows (inf sentinels) excluded in both
        assert mx.sum() == 2 * TILE - 7
        np.testing.assert_array_equal(mx, mp)

    def test_extent_mode(self):
        cols = _cols(4 * TILE, with_time=False, extent=True)
        tile_ids = kernels.pad_tiles(np.array([0, 2]))
        boxes = kernels.pad_boxes(np.array([[-30.0, -30.0, 30.0, 30.0]]))
        mx, mp, _ = _mask_pair(cols, tile_ids, boxes, None, extent=True)
        assert mx.any()
        np.testing.assert_array_equal(mx, mp)

    def test_supported_layouts(self):
        assert pallas_kernels.supported(1024, 8192)
        assert not pallas_kernels.supported(64, 8192)  # too small
        assert not pallas_kernels.supported(1000, 8000)  # not lane-aligned


class TestStoreParity:
    def test_full_query_path_interpret(self, monkeypatch):
        """Whole store query with the Pallas kernel forced on (interpret)."""
        monkeypatch.setenv("GEOMESA_TPU_PALLAS", "1")
        from geomesa_tpu.datastore import DataStore
        from geomesa_tpu.features import FeatureCollection
        from geomesa_tpu.sft import FeatureType

        sft = FeatureType.from_spec("p", "dtg:Date,*geom:Point:srid=4326")
        ds = DataStore(tile=TILE)
        ds.create_schema(sft)
        n = 5000
        rng = np.random.default_rng(9)
        t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
        x = rng.uniform(-180, 180, n)
        y = rng.uniform(-90, 90, n)
        t = t0 + rng.integers(0, 20 * 86400_000, n)
        ds.write("p", FeatureCollection.from_columns(
            sft, [str(i) for i in range(n)], {"dtg": t, "geom": (x, y)}
        ))
        lo = np.datetime64("2024-01-03T00:00:00", "ms").astype(np.int64)
        hi = np.datetime64("2024-01-12T00:00:00", "ms").astype(np.int64)
        q = (
            "bbox(geom, -60, -40, 60, 40) AND dtg DURING "
            "2024-01-03T00:00:00Z/2024-01-12T00:00:00Z"
        )
        hits = ds.query("p", q)
        truth = (x >= -60) & (x <= 60) & (y >= -40) & (y <= 40) & (t >= lo) & (t < hi)
        assert sorted(hits.ids.tolist()) == sorted(
            np.arange(n).astype(str)[truth].tolist()
        )
