"""Live map tiles (ISSUE 18; docs/tiles.md): the precomposed density
pyramid and its bit-identity / scoped-invalidation contracts.

The invariants under test:

- **bit-identity everywhere**: every precomposed tile equals the
  from-scratch oracle (:meth:`TilePyramid.fresh`) exactly — across all
  zooms, under fuzzed point sets, under sustained flush/fold mutation,
  and for the adversarial fold whose slices straddle a tile boundary;
- **exact-once binning**: a point on a shared tile edge lands in
  exactly one tile, so per-zoom totals always conserve the row count;
- **scoped invalidation, both directions**: a localized write dirties
  ONLY the overlapping tile per zoom (they recompose) while far tiles
  keep serving warm without recomposition;
- **TTL jitter** (``geomesa.cache.ttl.jitter``): deterministic per-key
  expiry spread — same key, same schedule, across cache instances;
- **fault points**: ``tiles.compose`` / ``tiles.leaf.scan`` fire under
  an armed chaos schedule (points="tiles.*") and the pyramid recovers
  cleanly once disarmed.
"""

import numpy as np
import pytest

from geomesa_tpu import fault, geometry as geo
from geomesa_tpu.cache import CacheConfig
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.streaming import LambdaStore, StreamConfig
from geomesa_tpu.tiles import (
    KINDS, TileLattice, TilePyramid, TilesConfig, encode_png, render,
)

SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"
T0 = int(np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64))
DAY = 86_400_000

#: small pyramid for fast full-matrix sweeps: 2+8+32 tiles, 32x32 px
SMALL = TilesConfig(leaf_zoom=2, px=32)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    fault.injector().reset()


def _store(n=0, seed=0, cache=True):
    from geomesa_tpu.metrics import MetricsRegistry

    ds = DataStore(
        cache=CacheConfig(max_bytes=1 << 22) if cache else None
    )
    ds.metrics = MetricsRegistry()
    sft = FeatureType.from_spec("t", SPEC)
    ds.create_schema(sft)
    if n:
        ds.write("t", _fc(sft, [f"c{i}" for i in range(n)], seed=seed))
    return ds, sft


def _fc(sft, ids, seed=0, lon=(-179.9, 179.9), lat=(-89.9, 89.9)):
    rng = np.random.default_rng(seed)
    n = len(ids)
    return FeatureCollection.from_columns(
        sft, list(ids),
        {"name": np.array(["n"] * n),
         "dtg": T0 + rng.integers(0, 30 * DAY, n),
         "geom": (rng.uniform(*lon, n), rng.uniform(*lat, n))},
    )


def _xy_fc(sft, ids, x, y):
    n = len(ids)
    return FeatureCollection.from_columns(
        sft, list(ids),
        {"name": np.array(["n"] * n),
         "dtg": np.full(n, T0, dtype=np.int64),
         "geom": (np.asarray(x, float), np.asarray(y, float))},
    )


def _assert_identical(pyramid, type_name="t", zooms=None):
    """Every tile at every zoom equals the from-scratch oracle, and the
    per-zoom total equals the store's row count (no double-binning)."""
    total = None
    for z in zooms or range(pyramid.lattice.leaf_zoom + 1):
        nx, ny = pyramid.lattice.n_tiles(z)
        zsum = 0.0
        for x in range(nx):
            for y in range(ny):
                warm = pyramid.fetch(type_name, z, x, y)
                oracle = pyramid.fresh(type_name, z, x, y)
                assert np.array_equal(warm.grid, oracle.grid), (z, x, y)
                zsum += warm.grid.sum()
        if total is None:
            total = zsum
        assert zsum == total, (z, zsum, total)
    return total


# -- the lattice geometry --------------------------------------------------


class TestLattice:
    def test_tile_counts_and_validity(self):
        lat = TileLattice(leaf_zoom=3, px=256)
        assert lat.n_tiles(0) == (2, 1)
        assert lat.n_tiles(3) == (16, 8)
        assert lat.valid(0, 1, 0) and not lat.valid(0, 2, 0)
        assert not lat.valid(-1, 0, 0) and not lat.valid(4, 0, 0)
        assert not lat.valid(1, 0, -1)

    def test_edges_exact_and_partitioning(self):
        lat = TileLattice(leaf_zoom=2, px=32)
        assert lat.xe[0] == -180.0 and lat.xe[-1] == 180.0
        assert lat.ye[0] == -90.0 and lat.ye[-1] == 90.0
        assert np.all(np.diff(lat.xe) > 0) and np.all(np.diff(lat.ye) > 0)
        # adjacent tile bboxes share their edge coordinate EXACTLY
        for z in range(3):
            nx, ny = lat.n_tiles(z)
            for x in range(nx - 1):
                a = lat.tile_bbox(z, x, 0)
                b = lat.tile_bbox(z, x + 1, 0)
                assert a[2] == b[0]
            for y in range(ny - 1):
                a = lat.tile_bbox(z, 0, y)
                b = lat.tile_bbox(z, 0, y + 1)
                # tile y counts from north: y+1 is SOUTH of y
                assert a[1] == b[3]

    def test_bin_leaf_half_open_and_world_edges(self):
        lat = TileLattice(leaf_zoom=2, px=32)
        # a point exactly on an interior pixel edge bins into the pixel
        # whose LOWER edge it is (half-open [lo, hi))
        edge = float(lat.xe[7])
        col, _row, ok = lat.bin_leaf(
            np.array([edge]), np.array([0.0])
        )
        assert ok[0] and col[0] == 7
        # the world's own closed upper edges clamp into the last pixel
        col, row, ok = lat.bin_leaf(
            np.array([180.0, -180.0]), np.array([90.0, -90.0])
        )
        assert ok.all()
        assert col[0] == lat.nx - 1 and col[1] == 0
        assert row[0] == 0 and row[1] == lat.ny - 1  # row 0 = north
        # outside the world: masked out
        _c, _r, ok = lat.bin_leaf(
            np.array([180.1, -999.0]), np.array([0.0, 0.0])
        )
        assert not ok.any()

    def test_children_tile_the_parent_span(self):
        lat = TileLattice(leaf_zoom=3, px=64)
        c0, c1, r0, r1 = lat.leaf_span(1, 2, 1)
        cols = np.zeros(c1 - c0, bool)
        rows = np.zeros(r1 - r0, bool)
        for cz, cx, cy in lat.children_of(1, 2, 1):
            assert cz == 2
            k0, k1, m0, m1 = lat.leaf_span(cz, cx, cy)
            assert c0 <= k0 < k1 <= c1 and r0 <= m0 < m1 <= r1
            cols[k0 - c0:k1 - c0] ^= True
            rows[m0 - r0:m1 - r0] ^= True
        # every leaf column/row covered by exactly TWO children (2x2)
        assert not cols.any() and not rows.any()

    def test_leaf_tiles_overlapping(self):
        lat = TileLattice(leaf_zoom=2, px=32)
        cx, cy = lat.n_tiles(2)
        assert lat.leaf_tiles_overlapping(None) == cx * cy
        # deep inside one 45-degree leaf tile
        assert lat.leaf_tiles_overlapping((10.0, 10.0, 20.0, 20.0)) == 1
        # straddling one vertical tile boundary (lon = 0)
        assert lat.leaf_tiles_overlapping((-1.0, 10.0, 1.0, 20.0)) == 2
        # straddling a corner: 2x2 tiles
        assert lat.leaf_tiles_overlapping((-1.0, -1.0, 1.0, 1.0)) == 4
        # a box hanging off the world clips, not crashes
        assert lat.leaf_tiles_overlapping((170.0, 80.0, 999.0, 999.0)) == 1


# -- the stdlib PNG encoder ------------------------------------------------


class TestPng:
    def test_signature_determinism_all_kinds(self):
        rng = np.random.default_rng(0)
        grid = rng.integers(0, 50, (32, 32)).astype(np.float64)
        for kind in KINDS:
            a = render(kind, grid)
            b = render(kind, grid)
            assert a == b
            assert a[:8] == b"\x89PNG\r\n\x1a\n"
            assert a.endswith(b"IEND\xaeB`\x82")
        with pytest.raises(ValueError):
            render("viridis", grid)

    def test_empty_grid_renders(self):
        grid = np.zeros((16, 16))
        for kind in KINDS:
            assert render(kind, grid)[:8] == b"\x89PNG\r\n\x1a\n"

    def test_scanlines_decode(self):
        import struct
        import zlib

        grid = np.arange(64, dtype=np.float64).reshape(8, 8)
        png = render("count", grid)
        # IHDR dims match the grid
        w, h = struct.unpack(">II", png[16:24])
        assert (w, h) == (8, 8)
        # IDAT inflates to h filter-0 scanlines of w bytes
        i = png.index(b"IDAT")
        (length,) = struct.unpack(">I", png[i - 4:i])
        raw = zlib.decompress(png[i + 4:i + 4 + length])
        assert len(raw) == h * (1 + w)
        assert all(raw[r * (w + 1)] == 0 for r in range(h))


# -- bit-identity: the tentpole contract -----------------------------------


class TestPyramidIdentity:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_identity_matrix_fuzzed(self, seed):
        ds, _sft = _store(n=800, seed=seed)
        p = TilePyramid(ds, SMALL)
        assert _assert_identical(p) == 800.0
        ds.close()

    def test_identity_with_points_on_every_tile_edge(self):
        ds, sft = _store()
        lat = TileLattice(SMALL.leaf_zoom, SMALL.px)
        # one point ON every interior leaf-TILE boundary intersection
        xs = [float(lat.xe[c]) for c in range(0, lat.nx, SMALL.px)][1:]
        ys = [float(lat.ye[r]) for r in range(0, lat.ny, SMALL.px)][1:-1]
        px_, py_ = np.meshgrid(np.array(xs), np.array(ys))
        px_, py_ = px_.ravel(), py_.ravel()
        ds.write("t", _xy_fc(sft, [f"e{i}" for i in range(len(px_))],
                             px_, py_))
        p = TilePyramid(ds, SMALL)
        # shared-edge points bin exactly once: totals conserve
        assert _assert_identical(p) == float(len(px_))
        ds.close()

    def test_identity_under_sustained_flush_and_fold(self):
        ds, sft = _store(n=400, seed=3)
        p = TilePyramid(ds, SMALL)
        _assert_identical(p)  # warm the whole pyramid
        lam = LambdaStore(
            ds, "t", config=StreamConfig(chunk_rows=32, fold_rows=8),
        )
        rng = np.random.default_rng(9)
        total = 400
        for round_ in range(3):
            rows = [
                {"name": "h", "dtg": T0 + round_,
                 "geom": geo.Point(float(rng.uniform(-170, 170)),
                                   float(rng.uniform(-80, 80)))}
                for _ in range(40)
            ]
            lam.write(rows, ids=[f"h{round_}_{i}" for i in range(40)])
            lam.flush()
            total += 40
            assert _assert_identical(p) == float(total)
        # a fold that REPLACES existing ids must not change totals
        moved = _fc(sft, [f"c{i}" for i in range(50)], seed=77)
        ds.fold_upsert("t", moved)
        assert _assert_identical(p) == float(total)
        lam.close()

    def test_fold_slices_straddling_tile_boundaries(self):
        """The adversarial case: a sliced fold whose every slice
        straddles a leaf-tile boundary — per-slice scoped bumps must
        leave every tile bit-identical to the oracle."""
        ds, sft = _store(n=200, seed=4)
        p = TilePyramid(ds, SMALL)
        _assert_identical(p)
        # points alternating across the lon=0 tile boundary (a boundary
        # at EVERY zoom), in batch order, so each 8-row slice straddles
        n = 64
        x = np.where(np.arange(n) % 2 == 0, -0.25, 0.25)
        y = np.linspace(-40, 40, n)
        batch = _xy_fc(sft, [f"s{i}" for i in range(n)], x, y)
        ds.fold_upsert("t", batch, slice_rows=8)
        assert _assert_identical(p) == float(200 + n)
        ds.close()

    def test_uncached_store_still_correct(self):
        ds, _sft = _store(n=150, seed=5, cache=False)
        p = TilePyramid(ds, SMALL)
        assert p.stats()["tile_grid_entries"] == 0
        assert _assert_identical(p, zooms=(0, 2)) == 150.0
        assert p.stats()["tile_grid_entries"] == 0  # never caches
        ds.close()


# -- scoped invalidation: both directions ----------------------------------


class TestScopedInvalidation:
    def test_flush_dirties_only_touched_tiles(self):
        ds, sft = _store(n=300, seed=6)
        p = TilePyramid(ds, SMALL)
        _assert_identical(p)  # warm every tile at every zoom
        compose0 = ds.metrics.counter_value("geomesa.tiles.compose")
        # one point deep inside a single generation grid cell, far from
        # any tile boundary: exactly ONE tile per zoom overlaps it
        ds.write("t", _xy_fc(sft, ["probe"], [8.0], [8.0]))
        # direction 1: far tiles stay warm (peek still serves them)
        far = p.peek("t", SMALL.leaf_zoom, 0, 0)  # far west tile
        assert far is not None
        # direction 2: the touched tile is stale (peek refuses it)
        tx = 4  # lon 8 at z=2: col 4 of 8
        ty = 1  # lat 8 from north: row 1 of 4
        assert p.peek("t", SMALL.leaf_zoom, tx, ty) is None
        # a full refetch recomposes EXACTLY one tile per zoom
        _assert_identical(p)
        recomposed = (
            ds.metrics.counter_value("geomesa.tiles.compose") - compose0
        )
        assert recomposed == SMALL.leaf_zoom + 1, recomposed
        ds.close()

    def test_tick_is_the_etag_source(self):
        ds, sft = _store(n=100, seed=7)
        p = TilePyramid(ds, SMALL)
        g1 = p.fetch("t", 0, 0, 0)
        assert p.fetch("t", 0, 0, 0).tick == g1.tick  # warm: same tick
        ds.write("t", _xy_fc(sft, ["w"], [-90.0 + 1.0], [45.0]))
        g2 = p.fetch("t", 0, 0, 0)
        assert g2.tick > g1.tick  # dirtied tile recomposed at a new tick

    def test_note_delta_accounting(self):
        ds, sft = _store(n=50, seed=8)
        p = TilePyramid(ds, SMALL)
        s0 = p.stats()
        ds.write("t", _xy_fc(sft, ["a"], [10.0], [10.0]))
        s1 = p.stats()
        assert s1["tile_deltas"] == s0["tile_deltas"] + 1
        assert s1["tile_dirty_leaves"] == s0["tile_dirty_leaves"] + 1
        assert ds.metrics.counter_value("geomesa.tiles.dirty") >= 1

    def test_schema_drop_and_quarantine_hooks(self):
        ds, _sft = _store(n=60, seed=9)
        p = TilePyramid(ds, SMALL)
        p.fetch("t", 0, 0, 0)  # composes (and caches) its whole subtree
        assert p.stats()["tile_grid_entries"] > 0
        ds.cache.on_schema_dropped("t")
        assert p.stats()["tile_grid_entries"] == 0


# -- TTL jitter (geomesa.cache.ttl.jitter) ---------------------------------


class TestTtlJitter:
    def _cache(self, jitter):
        from geomesa_tpu.cache.generations import GenerationTracker
        from geomesa_tpu.cache.result import ResultCache, ResultCacheConf

        return ResultCache(
            ResultCacheConf(
                max_bytes=1 << 20, ttl_s=100.0, ttl_jitter=jitter
            ),
            GenerationTracker(),
        )

    def _expiry(self, cache, key):
        import time

        from geomesa_tpu.cache.generations import KeyRange

        t0 = time.monotonic()
        cache.admit(key, "t", KeyRange.everything(),
                    np.zeros(4), 1.0, cache.generations.tick())
        return cache._entries[key].expires_at - t0

    def test_jitter_spreads_expiry_deterministically(self):
        c = self._cache(0.5)
        keys = [f"tiles/t/2/{x}/{y}" for x in range(4) for y in range(2)]
        expiries = {k: self._expiry(c, k) for k in keys}
        # a burst of same-TTL admissions no longer expires in lockstep:
        # spread inside [ttl, ttl * 1.5], and meaningfully apart
        for e in expiries.values():
            assert 100.0 <= e <= 150.0 + 0.1
        assert max(expiries.values()) - min(expiries.values()) > 5.0
        # deterministic: a fresh cache re-derives the SAME schedule
        c2 = self._cache(0.5)
        for k, e in expiries.items():
            assert abs(self._expiry(c2, k) - e) < 0.1

    def test_zero_jitter_is_exact_ttl(self):
        c = self._cache(0.0)
        for key in ("k1", "k2"):
            assert abs(self._expiry(c, key) - 100.0) < 0.1

    def test_knob_plumbs_through_both_cache_tiers(self):
        from geomesa_tpu import conf
        from geomesa_tpu.cache import CacheConfig as CC

        conf.CACHE_TTL_JITTER.set(0.25)
        try:
            assert CC.from_properties().ttl_jitter == 0.25
            assert TilesConfig.from_properties().ttl_jitter == 0.25
            # knob-resolved configs flow into BOTH ResultCache tiers
            ds = DataStore(cache=CC.from_properties())
            ds.create_schema(FeatureType.from_spec("t", SPEC))
            assert ds.cache.result.conf.ttl_jitter == 0.25
            p = TilePyramid(ds)
            assert p._result.conf.ttl_jitter == 0.25
            ds.close()
        finally:
            conf.CACHE_TTL_JITTER.clear()


# -- fault points under chaos ----------------------------------------------


class TestChaos:
    def test_tiles_fault_points_fire_and_recover(self):
        ds, _sft = _store(n=80, seed=10)
        p = TilePyramid(ds, SMALL)
        with fault.chaos(
            seed=1, rate=1.0, points="tiles.*", kinds=("io_error",)
        ) as spec:
            with pytest.raises(fault.InjectedIOError):
                p.fetch("t", 0, 0, 0)
            assert spec.fired >= 1
        # leaf-scan point specifically: compose passes, the scan trips
        with fault.chaos(
            seed=2, rate=1.0, points="tiles.leaf.*", kinds=("io_error",)
        ) as spec:
            with pytest.raises(fault.InjectedIOError):
                p.fetch("t", SMALL.leaf_zoom, 0, 0)
            assert spec.fired >= 1
        # disarmed: the pyramid serves correct tiles again
        assert _assert_identical(p, zooms=(0,)) == 80.0
        ds.close()


# -- the offline CLI twin --------------------------------------------------


class TestCli:
    def test_cmd_tile_writes_the_served_png(self, tmp_path):
        from geomesa_tpu.cli import main
        from geomesa_tpu.storage import persist

        root = str(tmp_path / "cat")
        ds, sft = _store(n=120, seed=11)
        persist.save(ds, root)
        out = str(tmp_path / "tile.png")
        rc = main([
            "tile", "-c", root, "-f", "t", "1", "1", "0",
            "--kind", "density", "-o", out,
        ])
        assert rc == 0
        data = open(out, "rb").read()
        assert data[:8] == b"\x89PNG\r\n\x1a\n"
        # the CLI bytes equal the pyramid render of the same tile
        p = TilePyramid(ds)
        assert data == render("density", p.fetch("t", 1, 1, 0).grid)
        # --fresh (the oracle path) produces the same bytes
        out2 = str(tmp_path / "tile2.png")
        assert main([
            "tile", "-c", root, "-f", "t", "1", "1", "0", "-o", out2,
            "--fresh",
        ]) == 0
        assert open(out2, "rb").read() == data
        # error paths: bad kind, bad zoom, unknown type
        assert main([
            "tile", "-c", root, "-f", "t", "1", "1", "0", "--kind", "x",
        ]) == 1
        assert main(["tile", "-c", root, "-f", "t", "9", "0", "0"]) == 1
        assert main(["tile", "-c", root, "-f", "zz", "0", "0", "0"]) == 1
        ds.close()


def test_encode_png_rejects_bad_shapes():
    with pytest.raises(ValueError):
        encode_png(np.zeros((4, 4, 5), np.uint8))
