"""Host groups (docs/distributed.md): the pod tier's layout authority.

- **layout**: ``host_major_slices`` is the ONE deal shared by the flat
  multihost mesh axis and the pod's per-host shard meshes — contiguous
  per-host blocks for single-process device lists, process-grouped for
  real ``jax.distributed`` worlds;
- **drivers**: the sim driver slices the in-process virtual-device mesh
  into H synthetic hosts (the CPU-CI path every pod test runs on); the
  distributed driver demands a real multi-process world and raises
  :class:`PodUnsupported` — with the capability probe's machine-readable
  reason — anywhere it cannot run (tests skip, not fail);
- **per-host link profile** (ISSUE 20 satellite): measured RTTs derive
  one fused slot cap PER HOST through the shared
  ``derive_link_constants`` / ``doubling_ladder`` rule, so one slow
  host's bigger amortization bucket never inflates its peers' pad-slot
  work; ``PodIndexTable`` stamps each shard's ``_slot_cap`` from it.
"""

import numpy as np
import pytest

from geomesa_tpu import conf
from geomesa_tpu.parallel.mesh import host_major_slices
from geomesa_tpu.pod import PodUnsupported, make_host_group, probe_capability
from geomesa_tpu.scan import block_kernels as bk
from geomesa_tpu.storage.table import FUSED_CHUNK_SLOTS


class _Dev:
    """jax.Device stand-in: just the attributes the layout code reads."""

    def __init__(self, i, proc=0):
        self.id = i
        self.process_index = proc

    def __repr__(self):
        return f"d{self.id}@p{self.process_index}"


class TestHostMajorSlices:
    def test_single_process_slices_are_contiguous(self):
        devs = [_Dev(i) for i in range(8)]
        slices = host_major_slices(devs, 4, 2)
        assert [[d.id for d in s] for s in slices] == [
            [0, 1], [2, 3], [4, 5], [6, 7]
        ]

    def test_multi_process_groups_by_process(self):
        # a real pod: device ids interleave but process_index decides
        devs = [_Dev(0, 0), _Dev(2, 1), _Dev(1, 0), _Dev(3, 1)]
        slices = host_major_slices(devs, 2, 2)
        assert [[d.process_index for d in s] for s in slices] == [[0, 0], [1, 1]]
        assert [[d.id for d in s] for s in slices] == [[0, 1], [2, 3]]

    def test_flat_mesh_and_pod_slices_agree(self):
        """The pod's per-host slices concatenate to EXACTLY the flat
        host-major mesh order — the two views never disagree on which
        host owns which device (shard h of the pod == contiguous
        device block h of the flat mesh)."""
        import jax

        devs = jax.devices()
        group = make_host_group(hosts=4, devices_per_host=2, driver="sim")
        flat = [d for s in group.device_slices for d in s]
        assert flat == list(group.flat_mesh().devices.flatten())
        assert flat == devs[:8]


class TestSimDriver:
    def test_slices_and_meshes(self):
        group = make_host_group(hosts=4, devices_per_host=2, driver="sim")
        assert group.driver == "sim"
        assert (group.hosts, group.devices_per_host) == (4, 2)
        for h in range(4):
            m = group.mesh(h)
            assert list(m.devices.flatten()) == list(group.device_slices[h])
            assert m is group.mesh(h)  # cached

    def test_dph_defaults_to_even_split(self):
        group = make_host_group(hosts=2, driver="sim")
        assert group.devices_per_host == 4  # 8 virtual devices / 2

    def test_needs_explicit_host_count(self):
        with pytest.raises(ValueError, match="host count"):
            make_host_group(driver="sim")

    def test_too_few_devices_is_unsupported(self):
        with pytest.raises(PodUnsupported, match="devices"):
            make_host_group(hosts=64, driver="sim")

    def test_knob_resolution(self):
        """geomesa.pod.hosts / .devices.per.host / .driver settle the
        group when the call site passes nothing (docs/config.md)."""
        conf.POD_HOSTS.set(2)
        conf.POD_DEVICES_PER_HOST.set(3)
        conf.POD_DRIVER.set("sim")
        try:
            group = make_host_group()
            assert (group.hosts, group.devices_per_host) == (2, 3)
        finally:
            conf.POD_HOSTS.clear()
            conf.POD_DEVICES_PER_HOST.clear()
            conf.POD_DRIVER.clear()

    def test_unknown_driver_rejected(self):
        with pytest.raises(ValueError, match="driver"):
            make_host_group(hosts=2, driver="nope")


class TestDistributedDriver:
    def test_probe_verdict_is_machine_readable(self):
        v = probe_capability()
        assert v["verdict"] in ("supported", "UNSUPPORTED", "error")
        assert isinstance(v["supported"], bool)
        assert v["supported"] == (v["verdict"] == "supported")
        assert "reason" in v

    def test_single_process_raises_pod_unsupported(self):
        """A single-process world can never run the distributed driver:
        either the backend has no multi-process collectives (the CPU CI
        verdict) or the process wasn't launched under jax.distributed.
        Both surface as PodUnsupported — the skip-not-fail contract the
        differential matrix keys off."""
        with pytest.raises(PodUnsupported):
            make_host_group(driver="distributed")


class TestPerHostLinkProfile:
    def test_caps_ride_the_doubling_ladder(self):
        group = make_host_group(hosts=4, devices_per_host=2, driver="sim")
        caps = group.set_link_profile([66.0, 0.4, None, 16.5])
        # design-point RTT keeps the hand-tuned cap; a fast link snaps
        # to the 256 floor; a quarter-design link lands on 512; None
        # leaves that host on the process-wide default
        assert caps == [FUSED_CHUNK_SLOTS, 256, None, 512]
        assert [group.slot_cap(h) for h in range(4)] == caps
        assert group.link_rtts_ms == [66.0, 0.4, None, 16.5]
        # the per-host cap flows through the table-level resolution
        assert bk.fused_slot_cap(caps[1]) == 256
        assert bk.fused_slot_cap(None) == FUSED_CHUNK_SLOTS

    def test_wrong_length_rejected(self):
        group = make_host_group(hosts=2, devices_per_host=2, driver="sim")
        with pytest.raises(ValueError, match="RTTs"):
            group.set_link_profile([1.0])

    def test_probe_links_installs_a_profile(self):
        group = make_host_group(hosts=2, devices_per_host=2, driver="sim")
        rtts = group.probe_links(samples=1)
        assert len(rtts) == 2 and all(r is not None and r >= 0 for r in rtts)
        assert all(
            group.slot_cap(h) in (256, 512, 1024, FUSED_CHUNK_SLOTS)
            for h in range(2)
        )

    def test_pinned_knob_beats_per_host_cap(self):
        conf.SCAN_FUSED_SLOTS.set(512)
        try:
            assert bk.fused_slot_cap(2048) == 512
        finally:
            conf.SCAN_FUSED_SLOTS.clear()

    def test_shards_stamp_their_host_cap(self):
        """PodIndexTable gives every host shard ITS host's probed cap:
        the slow host's shard amortizes over a bigger bucket while the
        fast host keeps the floor (one table, two different canonical
        fused shapes — per host, never process-global)."""
        from geomesa_tpu.datastore import DataStore
        from geomesa_tpu.features import FeatureCollection
        from geomesa_tpu.sft import FeatureType

        group = make_host_group(hosts=2, devices_per_host=2, driver="sim")
        group.set_link_profile([0.4, 66.0])
        ds = DataStore(mesh=group)
        sft = FeatureType.from_spec("lp", "dtg:Date,*geom:Point:srid=4326")
        ds.create_schema(sft)
        rng = np.random.default_rng(0)
        n = 1500
        t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
        ds.write("lp", FeatureCollection.from_columns(
            sft, [str(i) for i in range(n)],
            {"dtg": t0 + rng.integers(0, 86400_000, n),
             "geom": (rng.uniform(-60, 60, n), rng.uniform(-30, 30, n))},
        ))
        ds.compact("lp")
        table = next(t for (tn, _), t in ds._tables.items() if tn == "lp")
        assert table.shards[0]._slot_cap == 256
        assert table.shards[1]._slot_cap == FUSED_CHUNK_SLOTS
