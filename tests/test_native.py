"""Native C++ encoder: bit-exact parity with the numpy curve path."""

import numpy as np
import pytest

from geomesa_tpu import native
from geomesa_tpu.curve.binnedtime import BinnedTime, MAX_BIN, MAX_OFFSET, TimePeriod
from geomesa_tpu.curve.z2sfc import Z2SFC
from geomesa_tpu.curve.z3sfc import Z3SFC
from geomesa_tpu.curve.zorder import Z2, Z3

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)


def test_morton2_parity():
    rng = np.random.default_rng(0)
    x = rng.integers(0, 1 << 31, 10_000).astype(np.uint64)
    y = rng.integers(0, 1 << 31, 10_000).astype(np.uint64)
    np.testing.assert_array_equal(native.morton2(x, y), Z2.index(x, y))


def test_morton3_parity_and_decode():
    rng = np.random.default_rng(1)
    x = rng.integers(0, 1 << 21, 10_000).astype(np.uint64)
    y = rng.integers(0, 1 << 21, 10_000).astype(np.uint64)
    t = rng.integers(0, 1 << 21, 10_000).astype(np.uint64)
    z = native.morton3(x, y, t)
    np.testing.assert_array_equal(z, Z3.index(x, y, t))
    dx, dy, dt = native.morton3_decode(z)
    np.testing.assert_array_equal(dx, x)
    np.testing.assert_array_equal(dy, y)
    np.testing.assert_array_equal(dt, t)


@pytest.mark.parametrize("period", ["day", "week"])
def test_z3_write_keys_parity(period):
    rng = np.random.default_rng(2)
    n = 20_000
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    # include exact boundary values where float rounding bites
    x[:4] = [-180.0, 180.0, 0.0, -0.0]
    y[:4] = [-90.0, 90.0, 0.0, 179.9999 % 90]
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    millis = t0 + rng.integers(0, 400 * 86400_000, n)
    millis[:2] = [0, t0]

    out = native.z3_write_keys(x, y, millis, period, MAX_OFFSET[TimePeriod(period)], MAX_BIN)
    assert out is not None
    bins, zs, cols = out

    sfc = Z3SFC.for_period(period)
    binner = BinnedTime(period)
    binned = binner.to_binned(millis)
    want_z = sfc.index(x, y, binned.offset.astype(np.float64))
    np.testing.assert_array_equal(zs, want_z.astype(np.uint64))
    np.testing.assert_array_equal(bins, binned.bin.astype(np.int32))
    np.testing.assert_array_equal(cols["toff"], binned.offset.astype(np.int32))
    np.testing.assert_array_equal(cols["x"], x.astype(np.float32))


def test_z3_write_keys_rejects_bad_dates():
    with pytest.raises(ValueError):
        native.z3_write_keys(
            np.zeros(1), np.zeros(1), np.array([-5]), "week",
            MAX_OFFSET[TimePeriod.WEEK], MAX_BIN,
        )
    far = np.array([(MAX_BIN + 10) * 7 * 86_400_000], dtype=np.int64)
    with pytest.raises(ValueError):
        native.z3_write_keys(
            np.zeros(1), np.zeros(1), far, "week",
            MAX_OFFSET[TimePeriod.WEEK], MAX_BIN,
        )


def test_z3_calendar_period_falls_back():
    assert (
        native.z3_write_keys(np.zeros(1), np.zeros(1), np.array([0]), "month", 1, 1)
        is None
    )


def test_z2_write_keys_parity():
    rng = np.random.default_rng(3)
    n = 20_000
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    x[:2] = [-180.0, 180.0]
    y[:2] = [-90.0, 90.0]
    z, cols = native.z2_write_keys(x, y)
    want = Z2SFC().index(x, y)
    np.testing.assert_array_equal(z, want.astype(np.uint64))
    np.testing.assert_array_equal(cols["y"], y.astype(np.float32))


def test_store_query_identical_with_and_without_native(monkeypatch):
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.sft import FeatureType

    def build():
        sft = FeatureType.from_spec("n", "dtg:Date,*geom:Point:srid=4326")
        ds = DataStore(tile=64)
        ds.create_schema(sft)
        rng = np.random.default_rng(4)
        n = 2000
        t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
        ds.write("n", FeatureCollection.from_columns(
            sft, [str(i) for i in range(n)],
            {"dtg": t0 + rng.integers(0, 20 * 86400_000, n),
             "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n))},
        ))
        return ds

    q = "bbox(geom, -30, -20, 40, 35) AND dtg DURING 2024-01-03T00:00:00Z/2024-01-12T00:00:00Z"
    with_native = sorted(build().query("n", q).ids.tolist())
    monkeypatch.setattr(native, "_lib", False)
    without = sorted(build().query("n", q).ids.tolist())
    assert with_native == without and len(with_native) > 0


class TestBitmaskDecode:
    """Native bitmask decode + span merge vs the numpy reference paths."""

    def _planes(self, seed, n_real=6, pack=4):
        rng = np.random.default_rng(seed)
        # full u32 range: bit 31 (the int32 sign bit) must be exercised —
        # a signed shift/compare regression in the C++ would only show there
        wide = (
            rng.integers(0, 1 << 32, (n_real, pack, 128), dtype=np.uint64)
            .astype(np.uint32)
            .view(np.int32)
        )
        wide[rng.uniform(size=wide.shape) < 0.7] = 0  # sparse-ish
        inner = wide & (
            rng.integers(0, 1 << 32, wide.shape, dtype=np.uint64)
            .astype(np.uint32)
            .view(np.int32)
        )
        bids = np.sort(rng.choice(50, n_real, replace=False)).astype(np.int64)
        return wide, inner, bids

    def test_decode_matches_numpy(self):
        from geomesa_tpu import native
        from geomesa_tpu.scan import block_kernels as bk

        if not native.available():
            import pytest

            pytest.skip("native unavailable")
        for seed in range(5):
            wide, inner, bids = self._planes(seed)
            block = wide.shape[1] * 32 * 128
            got = native.bitmask_decode_pair(wide, inner, bids, len(bids), block)
            assert got is not None
            assert (np.asarray(wide) < 0).any()  # sign bit really exercised
            # numpy reference
            wb = bk._unpack_plane(wide, len(bids))
            blk, local = np.nonzero(wb)
            rows = bids[blk] * block + local
            cert = bk._unpack_plane(inner, len(bids))[blk, local].astype(bool)
            assert np.array_equal(got[0], rows)
            assert np.array_equal(got[1], cert)

    def test_decode_unsorted_bids_resorted(self):
        from geomesa_tpu import native
        from geomesa_tpu.scan import block_kernels as bk

        if not native.available():
            import pytest

            pytest.skip("native unavailable")
        wide, inner, _ = self._planes(11, n_real=4)
        bids = np.array([9, 2, 30, 5], dtype=np.int64)  # deliberately unsorted
        rows, cert = bk.decode_bits_pair(wide, inner, bids, 4)
        assert np.all(rows[1:] > rows[:-1])  # globally ascending after resort
        # membership matches the numpy reference
        wb = bk._unpack_plane(wide, 4)
        blk, local = np.nonzero(wb)
        block = wide.shape[1] * 32 * 128
        want = np.sort(bids[blk] * block + local)
        assert np.array_equal(rows, want)

    def test_merge_rows_spans_matches_numpy(self):
        from geomesa_tpu import native
        from geomesa_tpu.storage.table import (
            _merge_sorted_rows, _rows_in_spans, _span_rows,
        )

        if not native.available():
            import pytest

            pytest.skip("native unavailable")
        rng = np.random.default_rng(3)
        for _ in range(10):
            spans = []
            pos = 0
            for _ in range(rng.integers(1, 6)):
                pos += int(rng.integers(5, 40))
                end = pos + int(rng.integers(1, 30))
                spans.append((pos, end))
                pos = end
            rows = np.unique(rng.integers(0, pos + 50, 60)).astype(np.int64)
            cert = rng.uniform(size=len(rows)) < 0.5
            got = native.merge_rows_spans(spans, rows, cert)
            assert got is not None
            dup = _rows_in_spans(rows, spans)
            want_rows, want_cert = _merge_sorted_rows(
                _span_rows(spans), rows[~dup], cert[~dup]
            )
            assert np.array_equal(got[0], want_rows)
            assert np.array_equal(got[1], want_cert)


def test_counting_argsort_matches_stable_argsort():
    rng = np.random.default_rng(7)
    keys = rng.integers(0, 1024, 100_000).astype(np.int64)
    perm = native.counting_argsort(keys, 1024)
    np.testing.assert_array_equal(
        np.asarray(perm, dtype=np.int64), np.argsort(keys, kind="stable")
    )


def test_xz_index_parity():
    from geomesa_tpu.curve.xzsfc import XZSFC

    rng = np.random.default_rng(8)
    for dims in (2, 3):
        sfc = XZSFC(12 if dims == 2 else 10, dims)
        lo = rng.uniform(0, 0.98, (20_000, dims))
        hi = lo + rng.uniform(0, 0.02, (20_000, dims)) ** 2
        # include degenerate (point-like) and full-extent elements
        lo[:5] = 0.0
        hi[:5] = 1.0
        hi[5:10] = lo[5:10]
        got = native.xz_index(lo, hi, dims, sfc.g, sfc.subtree_size)
        want = sfc.sequence_code(lo, sfc.length_at(lo, hi))
        np.testing.assert_array_equal(got, want)


def test_bitmask_decode_wide_only():
    from geomesa_tpu.scan import block_kernels as bk

    rng = np.random.default_rng(9)
    wide = (
        rng.integers(0, 1 << 32, (5, 4, 128), dtype=np.uint64)
        .astype(np.uint32)
        .view(np.int32)
    )
    wide[rng.uniform(size=wide.shape) < 0.6] = 0
    bids = np.sort(rng.choice(40, 5, replace=False)).astype(np.int64)
    block = 4 * 32 * 128
    got = native.bitmask_decode(wide, bids, 5, block)
    flat = bk._unpack_plane(wide, 5)
    blk, local = np.nonzero(flat)
    want = bids[blk].astype(np.int64) * block + local
    np.testing.assert_array_equal(got, want)


def test_xz_ranges_parity():
    """Native XZ BFS vs the python pass: exact match uncapped; covering
    superset when the range budget caps (gap-close tie-breaks differ)."""
    from geomesa_tpu.curve.xzsfc import XElement, XZSFC

    rng = np.random.default_rng(12)
    for dims in (2, 3):
        sfc = XZSFC(12 if dims == 2 else 10, dims)
        for trial in range(12):
            k = rng.integers(1, 3)
            qs = []
            for _ in range(k):
                lo = rng.uniform(0, 0.9, dims)
                hi = lo + rng.uniform(0.001, 0.1, dims) ** (1 + trial % 2)
                qs.append(XElement(tuple(lo), tuple(np.minimum(hi, 1.0))))
            got = sfc.ranges(qs, max_ranges=200_000)  # large: no capping
            native._lib, saved = False, native._lib
            try:
                want = sfc.ranges(qs, max_ranges=200_000)
            finally:
                native._lib = saved
            assert [(r.lower, r.upper, r.contained) for r in got] == [
                (r.lower, r.upper, r.contained) for r in want
            ]

    # capped: both produce <= max_ranges ranges covering the uncapped set
    sfc = XZSFC(12, 2)
    qs = [XElement((0.1, 0.1), (0.6, 0.55))]
    full = sfc.ranges(qs, max_ranges=100_000)
    capped = sfc.ranges(qs, max_ranges=50)
    assert len(capped) <= 50
    # coverage: the union of capped intervals contains every full range
    # (merge kind-insensitively first: containment flags may differ)
    ivals = sorted((r.lower, r.upper) for r in capped)
    merged = [list(ivals[0])]
    for lo, hi in ivals[1:]:
        if lo <= merged[-1][1] + 1:
            merged[-1][1] = max(merged[-1][1], hi)
        else:
            merged.append([lo, hi])
    lows = np.array([m[0] for m in merged])
    highs = np.array([m[1] for m in merged])
    for r in full:
        i = np.searchsorted(lows, r.lower, side="right") - 1
        assert i >= 0 and highs[i] >= r.upper  # covered


class TestNativePointsInPolygon:
    def test_parity_vs_numpy(self):
        from geomesa_tpu import geometry as geo
        from geomesa_tpu import native

        if not native.available():
            pytest.skip("native unavailable")
        rng = np.random.default_rng(0)
        n = 50_000
        px = rng.uniform(-5, 15, n)
        py = rng.uniform(-5, 15, n)
        # concave polygon with a hole + a second disjoint part
        shell = np.array(
            [[0, 0], [10, 0], [10, 10], [6, 10], [6, 4], [4, 4], [4, 10],
             [0, 10], [0, 0]], float)
        hole = np.array([[1, 1], [3, 1], [3, 3], [1, 3], [1, 1]], float)
        part2 = geo.Polygon(np.array(
            [[12, 12], [14, 12], [14, 14], [12, 14], [12, 12]], float))
        mp = geo.MultiPolygon([geo.Polygon(shell, [hole]), part2])
        got = native.points_in_polygon(
            px, py,
            [shell, hole, np.asarray(part2.shell)], [0, 0, 1],
        )
        # numpy truth via the per-ring path (force below native threshold)
        want = np.zeros(n, dtype=bool)
        for pi, p in enumerate([geo.Polygon(shell, [hole]), part2]):
            parity = geo.points_in_ring(px, py, p.shell)
            for h in p.holes:
                parity ^= geo.points_in_ring(px, py, h)
            want |= parity
        np.testing.assert_array_equal(got, want)
        # and the public entry point routes identically above threshold
        via_public = geo.points_in_polygon(px, py, mp)
        np.testing.assert_array_equal(via_public, want)

    def test_boundary_grid_cases(self):
        from geomesa_tpu import geometry as geo
        from geomesa_tpu import native

        if not native.available():
            pytest.skip("native unavailable")
        # points exactly on integer grid lines of a unit-square lattice:
        # parity semantics must match numpy bit-for-bit
        xs, ys = np.meshgrid(np.linspace(-1, 3, 41), np.linspace(-1, 3, 41))
        px, py = xs.ravel(), ys.ravel()
        sq = geo.box(0, 0, 2, 2)
        got = native.points_in_polygon(px, py, [np.asarray(sq.shell)], [0])
        want = geo.points_in_ring(px, py, np.asarray(sq.shell))
        np.testing.assert_array_equal(got, want)

    def test_slanted_edge_points(self):
        """Points exactly ON slanted edges: native must match numpy even
        where FMA contraction could flip the strict x comparison."""
        from geomesa_tpu import geometry as geo
        from geomesa_tpu import native

        if not native.available():
            pytest.skip("native unavailable")
        tri = np.array([[0, 0], [7, 3], [2, 9], [0, 0]], float)
        # sample points ON each edge at irrational-ish parameters
        ts = np.linspace(0.01, 0.99, 997)
        pts = []
        for a, b in zip(tri[:-1], tri[1:]):
            pts.append(a + ts[:, None] * (b - a))
        p = np.concatenate(pts)
        got = native.points_in_polygon(p[:, 0], p[:, 1], [tri], [0])
        want = geo.points_in_ring(p[:, 0], p[:, 1], tri)
        np.testing.assert_array_equal(got, want)
