"""Native C++ encoder: bit-exact parity with the numpy curve path."""

import numpy as np
import pytest

from geomesa_tpu import native
from geomesa_tpu.curve.binnedtime import BinnedTime, MAX_BIN, MAX_OFFSET, TimePeriod
from geomesa_tpu.curve.z2sfc import Z2SFC
from geomesa_tpu.curve.z3sfc import Z3SFC
from geomesa_tpu.curve.zorder import Z2, Z3

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)


def test_morton2_parity():
    rng = np.random.default_rng(0)
    x = rng.integers(0, 1 << 31, 10_000).astype(np.uint64)
    y = rng.integers(0, 1 << 31, 10_000).astype(np.uint64)
    np.testing.assert_array_equal(native.morton2(x, y), Z2.index(x, y))


def test_morton3_parity_and_decode():
    rng = np.random.default_rng(1)
    x = rng.integers(0, 1 << 21, 10_000).astype(np.uint64)
    y = rng.integers(0, 1 << 21, 10_000).astype(np.uint64)
    t = rng.integers(0, 1 << 21, 10_000).astype(np.uint64)
    z = native.morton3(x, y, t)
    np.testing.assert_array_equal(z, Z3.index(x, y, t))
    dx, dy, dt = native.morton3_decode(z)
    np.testing.assert_array_equal(dx, x)
    np.testing.assert_array_equal(dy, y)
    np.testing.assert_array_equal(dt, t)


@pytest.mark.parametrize("period", ["day", "week"])
def test_z3_write_keys_parity(period):
    rng = np.random.default_rng(2)
    n = 20_000
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    # include exact boundary values where float rounding bites
    x[:4] = [-180.0, 180.0, 0.0, -0.0]
    y[:4] = [-90.0, 90.0, 0.0, 179.9999 % 90]
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    millis = t0 + rng.integers(0, 400 * 86400_000, n)
    millis[:2] = [0, t0]

    out = native.z3_write_keys(x, y, millis, period, MAX_OFFSET[TimePeriod(period)], MAX_BIN)
    assert out is not None
    bins, zs, cols = out

    sfc = Z3SFC.for_period(period)
    binner = BinnedTime(period)
    binned = binner.to_binned(millis)
    want_z = sfc.index(x, y, binned.offset.astype(np.float64))
    np.testing.assert_array_equal(zs, want_z.astype(np.uint64))
    np.testing.assert_array_equal(bins, binned.bin.astype(np.int32))
    np.testing.assert_array_equal(cols["toff"], binned.offset.astype(np.int32))
    np.testing.assert_array_equal(cols["x"], x.astype(np.float32))


def test_z3_write_keys_rejects_bad_dates():
    with pytest.raises(ValueError):
        native.z3_write_keys(
            np.zeros(1), np.zeros(1), np.array([-5]), "week",
            MAX_OFFSET[TimePeriod.WEEK], MAX_BIN,
        )
    far = np.array([(MAX_BIN + 10) * 7 * 86_400_000], dtype=np.int64)
    with pytest.raises(ValueError):
        native.z3_write_keys(
            np.zeros(1), np.zeros(1), far, "week",
            MAX_OFFSET[TimePeriod.WEEK], MAX_BIN,
        )


def test_z3_calendar_period_falls_back():
    assert (
        native.z3_write_keys(np.zeros(1), np.zeros(1), np.array([0]), "month", 1, 1)
        is None
    )


def test_z2_write_keys_parity():
    rng = np.random.default_rng(3)
    n = 20_000
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    x[:2] = [-180.0, 180.0]
    y[:2] = [-90.0, 90.0]
    z, cols = native.z2_write_keys(x, y)
    want = Z2SFC().index(x, y)
    np.testing.assert_array_equal(z, want.astype(np.uint64))
    np.testing.assert_array_equal(cols["y"], y.astype(np.float32))


def test_store_query_identical_with_and_without_native(monkeypatch):
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.sft import FeatureType

    def build():
        sft = FeatureType.from_spec("n", "dtg:Date,*geom:Point:srid=4326")
        ds = DataStore(tile=64)
        ds.create_schema(sft)
        rng = np.random.default_rng(4)
        n = 2000
        t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
        ds.write("n", FeatureCollection.from_columns(
            sft, [str(i) for i in range(n)],
            {"dtg": t0 + rng.integers(0, 20 * 86400_000, n),
             "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n))},
        ))
        return ds

    q = "bbox(geom, -30, -20, 40, 35) AND dtg DURING 2024-01-03T00:00:00Z/2024-01-12T00:00:00Z"
    with_native = sorted(build().query("n", q).ids.tolist())
    monkeypatch.setattr(native, "_lib", False)
    without = sorted(build().query("n", q).ids.tolist())
    assert with_native == without and len(with_native) > 0
