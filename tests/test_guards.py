"""Query guards, interceptors, audit, and metrics."""

import numpy as np
import pytest

from geomesa_tpu.audit import AuditWriter
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import And, BBox, Filter
from geomesa_tpu.metrics import MetricsRegistry
from geomesa_tpu.planning.guards import (
    FullTableScanGuard,
    GraduatedQueryGuard,
    SizeBound,
    TemporalQueryGuard,
)
from geomesa_tpu.planning.planner import QueryGuardError
from geomesa_tpu.sft import FeatureType

SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"
DAY = 86400_000


def _store(**kw):
    sft = FeatureType.from_spec("g", SPEC)
    ds = DataStore(tile=64, **kw)
    ds.create_schema(sft)
    n = 500
    rng = np.random.default_rng(0)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    ds.write(
        "g",
        FeatureCollection.from_columns(
            sft,
            [str(i) for i in range(n)],
            {
                "name": np.array(["x"] * n),
                "dtg": t0 + rng.integers(0, 30 * DAY, n),
                "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)),
            },
        ),
    )
    return ds


Q_OK = "bbox(geom, 0, 0, 10, 10) AND dtg DURING 2024-01-02T00:00:00Z/2024-01-04T00:00:00Z"
Q_LONG = "bbox(geom, 0, 0, 10, 10) AND dtg DURING 2024-01-01T00:00:00Z/2024-01-25T00:00:00Z"
Q_WIDE_LONG = "bbox(geom, -170, -80, 170, 80) AND dtg DURING 2024-01-01T00:00:00Z/2024-01-25T00:00:00Z"


class TestGuards:
    def test_full_table_scan_guard(self):
        ds = _store(guards=[FullTableScanGuard()])
        with pytest.raises(QueryGuardError):
            ds.query("g", "name = 'x'")  # name not indexed -> full scan
        assert len(ds.query("g", Q_OK)) >= 0  # indexed path still fine
        assert len(ds.query("g")) == 500  # Include is allowed

    def test_block_full_table_scans_compat(self):
        ds = _store(block_full_table_scans=True)
        with pytest.raises(QueryGuardError):
            ds.query("g", "name = 'x'")

    def test_temporal_guard(self):
        ds = _store(guards=[TemporalQueryGuard(max_ms=7 * DAY)])
        assert len(ds.query("g", Q_OK)) >= 0
        with pytest.raises(QueryGuardError):
            ds.query("g", Q_LONG)
        with pytest.raises(QueryGuardError):
            ds.query("g", "bbox(geom, 0, 0, 10, 10)")  # unbounded time

    def test_graduated_guard(self):
        ds = _store(
            guards=[
                GraduatedQueryGuard(
                    [
                        SizeBound(400.0, 60 * DAY),  # small boxes: long history ok
                        SizeBound(None, 3 * DAY),  # anything bigger: 3 days max
                    ]
                )
            ]
        )
        assert len(ds.query("g", Q_LONG)) >= 0  # 100 deg^2, within tier 1
        with pytest.raises(QueryGuardError):
            ds.query("g", Q_WIDE_LONG)  # huge box + 24 days

    def test_interceptor_rewrites(self):
        class ForceBox:
            def rewrite(self, type_name: str, f: Filter) -> Filter:
                return And((BBox("geom", 0.0, 0.0, 20.0, 20.0), f))

        ds = _store(interceptors=[ForceBox()])
        out = ds.query("g")
        x = out.columns["geom"].x
        y = out.columns["geom"].y
        assert ((x >= 0) & (x <= 20) & (y >= 0) & (y <= 20)).all()


class TestAuditMetrics:
    def test_audit_events(self):
        audit = AuditWriter()
        ds = _store(audit=audit)
        ds.query("g", Q_OK)
        ds.query("g", "name = 'x'")
        events = audit.drain()
        assert len(events) == 2
        assert events[0]["strategy"] == "z3"
        assert events[1]["strategy"] == "full-scan"
        assert events[0]["planTimeMillis"] >= 0
        assert audit.drain() == []

    def test_metrics(self):
        reg = MetricsRegistry()
        ds = _store(metrics=reg)
        ds.query("g", Q_OK)
        snap = reg.snapshot()
        assert snap["counters"]["geomesa.query.count"] == 1
        assert snap["histograms"]["geomesa.query.scan"]["count"] == 1
        text = reg.render_prometheus()
        assert "geomesa_query_count 1" in text

    def test_timer_context(self):
        reg = MetricsRegistry()
        with reg.time("op"):
            pass
        assert reg.timers["op"].count == 1


class TestAgeOff:
    """AgeOff: query-time hiding via interceptor + physical removal."""

    T0 = int(np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64))

    def _store(self, interceptor=None):
        from geomesa_tpu import FeatureCollection

        sft = FeatureType.from_spec("ev", "dtg:Date,*geom:Point:srid=4326")
        ds = DataStore(interceptors=[interceptor] if interceptor else None)
        ds.create_schema(sft)
        rng = np.random.default_rng(5)
        n = 1000
        # half old (day 0), half recent (day 20)
        t = np.where(np.arange(n) < n // 2, self.T0, self.T0 + 20 * 86400_000)
        ds.write("ev", FeatureCollection.from_columns(
            sft, [str(i) for i in range(n)],
            {"dtg": t, "geom": (rng.uniform(-50, 50, n), rng.uniform(-40, 40, n))},
        ), check_ids=False)
        return ds

    def test_interceptor_hides_expired(self):
        from geomesa_tpu.planning.guards import AgeOffInterceptor

        now = self.T0 + 21 * 86400_000
        ic = AgeOffInterceptor(ttl_ms=5 * 86400_000, now_ms=now)
        ds = self._store(ic)
        out = ds.query("ev")
        assert len(out) == 500  # the old half is hidden
        assert all(int(i) >= 500 for i in out.ids)
        # conjunct composes with user filters
        out2 = ds.query("ev", "bbox(geom, -50, -40, 50, 40)")
        assert len(out2) == 500

    def test_physical_age_off(self):
        ds = self._store()
        now = self.T0 + 21 * 86400_000
        removed = ds.age_off("ev", ttl_ms=5 * 86400_000, now_ms=now)
        assert removed == 500
        assert ds.count("ev") == 500
        assert all(int(i) >= 500 for i in ds.query("ev").ids)

    def test_age_off_requires_dtg(self):
        from geomesa_tpu import FeatureCollection

        sft = FeatureType.from_spec("nt", "*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        with pytest.raises(ValueError):
            ds.age_off("nt", ttl_ms=1000)


class TestPersistedAudit:
    """File-backed audit (VERDICT r4 missing #4) + the visibility-disables-
    aggregation explain signal (weak #6)."""

    def test_file_audit_writer(self, tmp_path):
        from geomesa_tpu.audit import FileAuditWriter

        path = str(tmp_path / "audit.jsonl")
        audit = FileAuditWriter(path)
        ds = _store(audit=audit)
        ds.query("g", Q_OK)
        ds.query("g", "name = 'x'")
        ds.density("g", Q_OK)  # aggregation paths audited too
        audit.close()
        events = FileAuditWriter.read(path)
        assert len(events) == 3
        assert events[0]["strategy"] == "z3"
        assert {"filter", "strategy", "hits", "planTimeMillis",
                "scanTimeMillis", "ranges", "date"} <= set(events[0])
        # appends across writer instances (a restarted store keeps the log)
        audit2 = FileAuditWriter(path)
        ds2 = _store(audit=audit2)
        ds2.query("g", Q_OK)
        audit2.close()
        assert len(FileAuditWriter.read(path)) == 4

    def test_visibility_fallback_signal(self):
        from geomesa_tpu.planning.explain import Explainer
        from geomesa_tpu.security import VIS_FIELD_KEY

        sft = FeatureType.from_spec(
            "v", "name:String,vis:String,dtg:Date,*geom:Point:srid=4326"
        )
        sft.user_data[VIS_FIELD_KEY] = "vis"
        reg = MetricsRegistry()
        ds = DataStore(tile=64, auths=("admin",), metrics=reg)
        ds.create_schema(sft)
        n = 200
        rng = np.random.default_rng(1)
        t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
        ds.write("v", FeatureCollection.from_columns(
            sft, [str(i) for i in range(n)],
            {"name": np.array(["x"] * n),
             "vis": np.array(["admin", ""] * (n // 2)),
             "dtg": t0 + rng.integers(0, 30 * DAY, n),
             "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n))},
        ))
        exp = Explainer()
        ds.density("v", Q_OK, explain=exp)
        assert "visibility" in exp.render().lower()
        assert reg.snapshot()["counters"]["geomesa.query.vis_fallback"] == 1
        # bounds + count estimate produce the same signal
        exp2 = Explainer()
        ds.bounds("v", Q_OK, explain=exp2)
        assert "visibility" in exp2.render().lower()
        exp3 = Explainer()
        ds.stats_query("v", "Count()", Q_OK, estimate=True, explain=exp3)
        assert "visibility" in exp3.render().lower()
        # a store without auths does NOT emit the signal
        exp4 = Explainer()
        ds_open = _store()
        ds_open.density("g", Q_OK, explain=exp4)
        assert "visibility" not in exp4.render().lower()


class TestAttributeVisibility:
    """Per-attribute labels (VERDICT r4 missing #3; reference
    geomesa-security SecurityUtils attribute-level visibility): an
    attribute with vis=<label> is projected out for auths that cannot
    satisfy the label; rows stay visible."""

    def _store(self, auths):
        sft = FeatureType.from_spec(
            "av", "name:String,ssn:String:vis=admin,dtg:Date,*geom:Point:srid=4326"
        )
        ds = DataStore(tile=64, auths=auths)
        ds.create_schema(sft)
        n = 50
        rng = np.random.default_rng(2)
        t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
        ds.write("av", FeatureCollection.from_columns(
            sft, [str(i) for i in range(n)],
            {"name": np.array(["x"] * n),
             "ssn": np.array([f"s{i}" for i in range(n)]),
             "dtg": t0 + rng.integers(0, 30 * DAY, n),
             "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n))},
        ))
        return ds

    def test_unauthorized_loses_attribute(self):
        ds = self._store(auths=("user",))
        out = ds.query("av", Q_WIDE_LONG)
        assert len(out) > 0
        assert "ssn" not in out.columns
        assert "name" in out.columns

    def test_authorized_sees_attribute(self):
        ds = self._store(auths=("admin",))
        out = ds.query("av", Q_WIDE_LONG)
        assert len(out) > 0 and "ssn" in out.columns

    def test_no_auths_configured_sees_all(self):
        ds = self._store(auths=None)
        out = ds.query("av", Q_WIDE_LONG)
        assert "ssn" in out.columns

    def test_filter_on_hidden_attribute_rejected(self):
        """Predicate probing must not recover hidden values (review
        finding): a filter referencing a vis-protected attribute is
        rejected at plan time for unauthorized auths."""
        from geomesa_tpu.planning.errors import QueryGuardError

        ds = self._store(auths=("user",))
        with pytest.raises(QueryGuardError, match="ssn"):
            ds.query("av", "ssn = 's5'")
        # authorized auths may filter on it
        ds2 = self._store(auths=("admin",))
        out = ds2.query("av", "ssn = 's5'")
        assert len(out) == 1
        # unrelated predicates still work for unauthorized auths
        assert len(ds.query("av", "name = 'x'")) == 50


def test_temporal_guard_resolves_property_tier():
    """geomesa.guard.temporal.max.duration (docs/config.md): an unset
    max_ms resolves the knob — programmatic override and env included —
    matching the reference property of the same name."""
    from geomesa_tpu.conf import GUARD_TEMPORAL_MAX
    from geomesa_tpu.planning.guards import TemporalQueryGuard

    assert TemporalQueryGuard().max_ms == 7 * 86_400_000  # one week
    assert TemporalQueryGuard.from_properties().max_ms == 7 * 86_400_000
    GUARD_TEMPORAL_MAX.set(3_600_000)
    try:
        assert TemporalQueryGuard().max_ms == 3_600_000
        # explicit max_ms still wins over the property
        assert TemporalQueryGuard(max_ms=5).max_ms == 5
    finally:
        GUARD_TEMPORAL_MAX.clear()
