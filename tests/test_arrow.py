"""Arrow columnar output: dictionary encoding, record-batch streaming,
and the no-Python-rows guarantee (reference ArrowScan + DeltaWriter)."""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu.io.arrow import arrow_stream, read_arrow_table, to_arrow_table

SPEC = "name:String,age:Int,score:Double,dtg:Date,*geom:Point:srid=4326"


def make_fc(n, seed=0):
    rng = np.random.default_rng(seed)
    sft = FeatureType.from_spec("a", SPEC)
    t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
    return sft, FeatureCollection.from_columns(
        sft,
        [str(i) for i in range(n)],
        {
            "name": np.array([f"cat{i % 13}" for i in range(n)]),
            "age": (np.arange(n) % 90).astype(np.int32),
            "score": rng.uniform(0, 1, n),
            "dtg": t0 + rng.integers(0, 86400_000 * 10, n),
            "geom": (rng.uniform(-60, 60, n), rng.uniform(-45, 45, n)),
        },
    )


class TestArrowStream:
    def test_roundtrip_with_dictionaries(self):
        _, fc = make_fc(5000)
        data = arrow_stream(fc)
        table = read_arrow_table(data)
        assert table.num_rows == 5000
        # string column is dictionary-encoded with 13 unique values
        field = table.schema.field("name")
        assert pa.types.is_dictionary(field.type)
        name_col = table.column("name").combine_chunks()
        chunk = name_col.chunk(0) if hasattr(name_col, "chunk") else name_col
        assert len(chunk.dictionary) == 13
        assert table.column("name").to_pylist() == fc.columns["name"].tolist()
        # dates come back as timestamp[ms]
        assert pa.types.is_timestamp(table.schema.field("dtg").type)
        got_ms = np.asarray(table.column("dtg").cast(pa.int64()))
        assert np.array_equal(got_ms, np.asarray(fc.columns["dtg"]))
        # points are FixedSizeList<2 x f64>
        geom = table.column("geom").combine_chunks()
        xy = np.asarray(geom.flatten())
        assert np.allclose(xy[0::2], fc.columns["geom"].x)
        assert np.allclose(xy[1::2], fc.columns["geom"].y)

    def test_record_batch_streaming(self):
        _, fc = make_fc(10000)
        data = arrow_stream(fc, batch_rows=1024)
        import pyarrow.ipc as ipc

        with ipc.open_stream(pa.py_buffer(data)) as r:
            batches = list(r)
        assert len(batches) == 10  # 10000 / 1024 -> 10 batches
        assert sum(b.num_rows for b in batches) == 10000

    def test_no_python_row_materialization(self, monkeypatch):
        _, fc = make_fc(100_000)

        def boom(self):  # any row-wise path is a bug
            raise AssertionError("to_rows called during arrow export")

        monkeypatch.setattr(FeatureCollection, "to_rows", boom)
        data = arrow_stream(fc)
        assert read_arrow_table(data).num_rows == 100_000

    def test_store_query_export(self):
        sft, fc = make_fc(8000)
        ds = DataStore()
        ds.create_schema(sft)
        ds.write("a", fc)
        out = ds.query("a", "bbox(geom, -30, -20, 30, 20)")
        from geomesa_tpu.io.exporters import export

        table = read_arrow_table(export(out, "arrow"))
        assert table.num_rows == len(out)
        assert pa.types.is_dictionary(table.schema.field("name").type)

    def test_extent_geometries_as_wkb(self):
        sft = FeatureType.from_spec("p", "name:String,*geom:Polygon:srid=4326")
        rows = [
            {
                "__id__": str(i),
                "name": f"p{i}",
                "geom": f"POLYGON(({i} 0, {i+1} 0, {i+1} 1, {i} 1, {i} 0))",
            }
            for i in range(50)
        ]
        fc = FeatureCollection.from_rows(sft, rows)
        table = read_arrow_table(arrow_stream(fc))
        from geomesa_tpu import geometry as geo

        g0 = geo.from_wkb(table.column("geom").to_pylist()[7])
        assert g0.bounds() == (7.0, 0.0, 8.0, 1.0)

    def test_plain_encoding_without_dictionary(self):
        _, fc = make_fc(100)
        table = read_arrow_table(arrow_stream(fc, dictionary=False))
        assert pa.types.is_string(table.schema.field("name").type)
        assert table.column("name").to_pylist() == fc.columns["name"].tolist()


class TestDeltaWriter:
    """ArrowDeltaWriter: incremental stream with dictionary deltas
    (reference geomesa-arrow DeltaWriter protocol)."""

    def test_delta_stream_roundtrip(self):
        pytest.importorskip("pyarrow")
        from geomesa_tpu.io.arrow import ArrowDeltaWriter, read_arrow_table

        sft = FeatureType.from_spec(
            "t", "name:String,v:Integer,*geom:Point:srid=4326"
        )
        w = ArrowDeltaWriter(sft, batch_rows=256)
        rng = np.random.default_rng(0)
        all_names = []
        for b in range(3):
            n = 700
            names = np.array(
                [f"b{b}_{i % 5}" for i in range(n)], dtype=object
            )
            fc = FeatureCollection.from_columns(
                sft, np.arange(b * n, (b + 1) * n),
                {
                    "name": names,
                    "v": rng.integers(0, 9, n).astype(np.int32),
                    "geom": (rng.uniform(-1, 1, n), rng.uniform(-1, 1, n)),
                },
            )
            w.write(fc)
            all_names.extend(names.tolist())
        table = read_arrow_table(w.finish())
        assert table.num_rows == 3 * 700
        assert table["name"].to_pylist() == all_names
        # repeated values across batches share one dictionary code space
        assert len(w._dicts["name"][0]) == 15

    def test_empty_finish(self):
        pytest.importorskip("pyarrow")
        from geomesa_tpu.io.arrow import ArrowDeltaWriter

        sft = FeatureType.from_spec("t", "name:String,*geom:Point:srid=4326")
        assert ArrowDeltaWriter(sft).finish() == b""
