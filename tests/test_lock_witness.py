"""The dynamic lock witness (docs/concurrency.md): prove the static
lock model against reality, tier-1.

Layers:

- **the runtime has teeth**: wrapper passthrough when disarmed, edge
  recording / re-entrancy / aliasing semantics, Condition wait frame
  handling, blocking events via fault points, and the deterministic
  two-thread A->B / B->A inversion whose cycle the witness must report;
- **model vs reality, both directions**: a workload across every
  concurrent tier (DataStore writes + cached queries, BulkLoader
  ingest, LambdaStore + WAL + flush/fold + checkpoint, the serving
  scheduler, a chaos schedule) under an armed witness must (a) witness
  EVERY LOCKS-registry lock, (b) observe an acyclic acquisition graph
  that is (c) a subgraph of the static model's predicted edges, and
  (d) never reach a fault point while a HOT lock is held — the runtime
  twin of blocking-under-lock, pinning the WAL _rotate/close fix;
- **overhead**: the witnessed workload stays within 1.5x of the
  unwitnessed wall time (disarmed it is zero-cost by construction).

The observed graph is ALWAYS dumped to the
``geomesa.tpu.lock.witness.artifact`` path (default
``/tmp/lock_witness.json``) so CI failures are diagnosable from logs.
"""

import os
import threading
import time

import numpy as np
import pytest

from geomesa_tpu import fault, lockwitness
from geomesa_tpu import geometry as geo
from geomesa_tpu.analysis.core import Project
from geomesa_tpu.analysis.lockmodel import LOCKS, LockModel
from geomesa_tpu.cache import CacheConfig
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.storage import persist
from geomesa_tpu.streaming import (
    LambdaStore,
    PipeTransport,
    ReplicaStore,
    SegmentShipper,
    StreamConfig,
    WalConfig,
)

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"
T0 = int(np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64))
DAY = 86_400_000


@pytest.fixture(autouse=True)
def _disarm():
    """Every test leaves the witness disarmed and the injector clean
    (objects built while armed keep their wrappers — they only feed the
    report, which the next enable() resets)."""
    yield
    lockwitness.disable()
    fault.injector().reset()


def _rows(n, seed=0, prefix="r"):
    rng = np.random.default_rng(seed)
    xs = rng.uniform(-50, 50, n)
    ys = rng.uniform(-50, 50, n)
    ts = T0 + rng.integers(0, 30 * DAY, n)
    return [
        {
            "__id__": f"{prefix}{i}",
            "name": "n",
            "dtg": np.datetime64(int(ts[i]), "ms"),
            "geom": f"POINT ({xs[i]:.6f} {ys[i]:.6f})",
        }
        for i in range(n)
    ]


def _fc(sft, n, seed=0, prefix="c"):
    rng = np.random.default_rng(seed)
    return FeatureCollection.from_columns(
        sft, [f"{prefix}{i}" for i in range(n)],
        {"name": np.array(["n"] * n),
         "dtg": T0 + rng.integers(0, 30 * DAY, n),
         "geom": (rng.uniform(-50, 50, n), rng.uniform(-50, 50, n))},
    )


# -- layer 1: the runtime has teeth ---------------------------------------


def test_disarmed_witness_is_passthrough():
    lockwitness.disable()
    lock = threading.Lock()
    assert lockwitness.witness(lock, "X._lock") is lock
    cond = threading.Condition()
    assert lockwitness.witness(cond, "X._cond") is cond


def test_edges_reentrancy_and_aliasing():
    lockwitness.enable()
    a = lockwitness.witness(threading.Lock(), "Fix._a")
    b = lockwitness.witness(threading.RLock(), "Fix._b")
    b2 = lockwitness.witness(threading.RLock(), "Fix._b")
    with a:
        assert lockwitness.held_locks() == ("Fix._a",)
        with b:
            with b:  # re-entrant same instance: NOT an edge, not aliased
                pass
            with b2:  # distinct instance, same name: aliased, not an edge
                pass
    assert lockwitness.held_locks() == ()
    snap = lockwitness.REPORT.snapshot()
    assert ("Fix._a", "Fix._b") in lockwitness.REPORT.edges
    assert ("Fix._b", "Fix._b") not in lockwitness.REPORT.edges
    assert snap["aliased"] == {"Fix._b ~ Fix._b": 1}
    assert {"Fix._a", "Fix._b"} <= set(snap["seen"])
    assert lockwitness.REPORT.cycle() is None


def test_two_thread_inversion_reports_cycle(tmp_path):
    """The deterministic A->B / B->A inversion: thread one nests A->B,
    thread two (strictly after) nests B->A; the witness must report the
    cycle even though the interleaving never actually deadlocked."""
    lockwitness.enable()
    a = lockwitness.witness(threading.Lock(), "Inv._a")
    b = lockwitness.witness(threading.Lock(), "Inv._b")
    first_done = threading.Event()

    def t1():
        with a:
            with b:
                pass
        first_done.set()

    def t2():
        first_done.wait()
        with b:
            with a:
                pass

    threads = [threading.Thread(target=t) for t in (t1, t2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    cyc = lockwitness.REPORT.cycle()
    assert cyc is not None
    assert set(cyc) == {"Inv._a", "Inv._b"}
    # the artifact records the cycle for CI forensics
    out = lockwitness.dump(str(tmp_path / "w.json"))
    import json

    payload = json.load(open(out))
    assert payload["cycle"] is not None
    assert "Inv._a -> Inv._b" in payload["edge_counts"]


def test_condition_wait_releases_held_frame():
    lockwitness.enable()
    cond = lockwitness.witness(threading.Condition(), "Fix._cond")
    seen_during_wait = []

    def waker():
        with cond:
            cond.notify_all()

    with cond:
        assert lockwitness.held_locks() == ("Fix._cond",)
        t = threading.Thread(target=waker)
        # wait() pops the held frame (the lock is released) and
        # re-pushes it on wake; a timeout-less wait would hang here
        # without the waker
        t.start()
        cond.wait(timeout=5.0)
        seen_during_wait.append(lockwitness.held_locks())
        t.join()
    assert seen_during_wait == [("Fix._cond",)]
    assert lockwitness.held_locks() == ()


def test_fault_points_record_blocking_events():
    lockwitness.enable()
    lock = lockwitness.witness(threading.Lock(), "Fix._hot")
    fault.fault_point("persist.gc")  # no lock held: not an event
    with lock:
        fault.fault_point("persist.gc")
    blocking = lockwitness.REPORT.snapshot()["blocking"]
    assert blocking == {"Fix._hot @ persist.gc": 1}


# -- layer 2: model vs reality, both directions ---------------------------


def _workload(tmp_path, metrics=None):
    """One pass over every concurrent tier; returns nothing — the point
    is which locks it crosses (construction happens INSIDE, so an armed
    witness wraps everything). The observability tier runs armed too:
    a fresh Tracer (sampling every root) and an attached SLO tracker,
    so Tracer._lock and SloTracker._lock are witnessed under the same
    concurrent serving load as the store locks."""
    from geomesa_tpu import conf, obs
    from geomesa_tpu.ingest import BulkLoader, PipelineConfig
    from geomesa_tpu.metrics import MetricsRegistry

    conf.OBS_TRACE_SAMPLE.set(1)
    obs.install(obs.Tracer())  # constructed armed: its lock is wrapped
    ds = DataStore(cache=CacheConfig(max_bytes=1 << 22, tile_bits=4))
    # a store-level registry (constructed under the armed witness):
    # without one, record_query skips the tile tier's cost gate and
    # TileAggregateCache._lock would never be crossed
    ds.metrics = metrics if metrics is not None else MetricsRegistry()
    ds.attach_slo()  # SLO windows fed through the registry observer hook
    # self-tuning tier (docs/tuning.md), armed at interval=1 so every
    # recorded query runs an adaptation pulse in its caller's thread:
    # TuningManager._lock is witnessed on the pacing/claim path while
    # the pulse crosses the accuracy, SLO and metrics locks OUTSIDE it
    ds.attach_tuning(enabled=True, interval=1)
    sft = FeatureType.from_spec("t", SPEC)
    ds.create_schema(sft)
    ds.write("t", _fc(sft, 200, seed=0))
    ds.compact("t")
    # cached read path: miss then hit (ResultCache + generations), and
    # record_query feeds the tile tier's cost gate
    for _ in range(2):
        ds.query("t", "BBOX(geom, -20, -20, 20, 20)")
    # pipelined ingest (BulkLoader._cv / _stage_lock)
    loader = BulkLoader(ds, "t", config=PipelineConfig(workers=2))
    loader.put(_fc(sft, 64, seed=1, prefix="b"))
    loader.close()
    # serving tier: admitted queries cross the scheduler condition;
    # the ops plane mounts alongside (constructed armed) and scrapes
    # /metrics + /health + /debug/vars WHILE a query is in flight, so
    # TelemetryRecorder._lock is witnessed under concurrent
    # scrape+serve (EstimateAccuracy._lock is crossed by every query's
    # record path — the store has sketches from the write above)
    import urllib.request

    sched = ds.serve()
    srv = ds.serve_ops()
    # data plane (docs/serving.md "The data plane"), mounted on the
    # same scheduler: tenant-tagged HTTP query + ingest traffic crosses
    # TenantRegistry._lock under concurrent handler threads, alongside
    # the scheduler condition and the store write lock
    dsv = ds.serve(port=0)
    try:
        fut = sched.submit("t", "BBOX(geom, -10, -10, 10, 10)")
        srv.recorder.sample()
        for path in ("/metrics", "/health", "/debug/vars?window=60"):
            urllib.request.urlopen(srv.url + path, timeout=10).read()
        fut.result(30)
        from geomesa_tpu.serving import DataClient

        dsv.tenants.configure("wl", queue_max=8)
        client = DataClient(dsv.url, tenant="wl")
        client.query("t", cql="BBOX(geom, -10, -10, 10, 10)")
        # tile pyramid (docs/tiles.md): a leaf fetch crosses
        # TilePyramid._lock on the scan-EWMA path; the ingest below
        # then crosses it AGAIN under the store write lock (the
        # declared DataStore._write_lock -> TilePyramid._lock edge,
        # via on_mutation -> note_delta). One LEAF tile: a single scan.
        client.tile("t", "density", 3, 0, 0)
        client.ingest("t", {"type": "FeatureCollection", "features": [{
            "type": "Feature", "id": "wl-ingest-1",
            "geometry": {"type": "Point", "coordinates": [0.5, 0.5]},
            "properties": {"name": "wl", "dtg": 1704067200000},
        }]})
        client.tenants()
    finally:
        dsv.close()
        srv.close()
    # multi-host pod tier (docs/distributed.md), constructed armed: the
    # link-profile install crosses HostGroup._probe_lock, and id-less
    # writes cross PodStore._route_lock on the auto-id counter before
    # the pod.wal.route hop fans the batch out to its owning hosts
    from geomesa_tpu.pod import PodStore, make_host_group

    pg = make_host_group(hosts=2, devices_per_host=1, driver="sim")
    pg.set_link_profile([10.0, 40.0])
    pod = PodStore(FeatureType.from_spec("p", SPEC), pg)
    try:
        pod.write([
            {"name": "p", "dtg": np.datetime64(T0, "ms"),
             "geom": geo.Point(float(i), float(i))}
            for i in range(8)
        ])
        pod.query()
        pod.count()
    finally:
        pod.close()
    # streaming tier over a durably saved cold store, WAL attached,
    # tiny segments so rotation happens (the fixed seal-fsync path),
    # chaos armed at rate=0 so every stream.* fault point consults the
    # schedule (ChaosSpec._lock) without firing anything
    root = tmp_path / "w"
    persist.save(ds, root)
    lam = LambdaStore(
        ds, "t",
        config=StreamConfig(chunk_rows=64, fold_rows=8, workers=2),
        wal_dir=str(root / "_wal"),
        wal_config=WalConfig(sync="always", segment_bytes=4 << 10),
    )
    try:
        with fault.chaos(
            seed=3, rate=0.0,
            points="stream.*,streaming.*,standing.*,replica.*",
        ):
            # standing tier (docs/standing.md), constructed armed: the
            # subscription index, a continuous window and the alert
            # queue all cross their locks on every write below
            from geomesa_tpu.streaming.standing import (
                Subscription, WindowSpec,
            )

            lam.subscribe(Subscription("w", "geofence", geom=geo.Polygon(
                [(-30, -30), (30, -30), (30, 30), (-30, 30), (-30, -30)]
            )))
            # a non-rectangular geofence so matching crosses the host
            # ray cast and the _MatchGate cost EWMAs (the rect above
            # takes the box fast path, which touches neither)
            lam.subscribe(Subscription("t", "geofence", geom=geo.Polygon(
                [(-30, -30), (30, -30), (0.0, 30), (-30, -30)]
            )))
            lam.standing().add_window("m", WindowSpec(size_ms=60_000))
            lam.write(_rows(150, seed=2))
            lam.flush()
            lam.write(_rows(150, seed=3))          # updates: fold path
            lam.delete([f"r{i}" for i in range(10)])  # hot-lock WAL hook
            lam.flush()
            lam.query("BBOX(geom, -30, -30, 30, 30)")
            lam.standing().alerts.drain()
            # replication tier (docs/replication.md), constructed
            # armed: the shipper's bookkeeping lock crosses on
            # attach/pump, the follower's watermark lock on every
            # applied record
            end_a, end_b = PipeTransport.pair()
            ship = SegmentShipper(lam, chunk_bytes=4096)
            fid = ship.attach(end_a)
            fol = ReplicaStore(
                str(root), str(tmp_path / "fw" / "_wal"), end_b,
                type_name="t",
                config=StreamConfig(chunk_rows=64, fold_rows=4096),
            )
            try:
                ship.pump()
                fol.drain()
                fol.staleness_ms()
            finally:
                ship.detach(fid)
                fol.close()
            lam.checkpoint(str(root))
    finally:
        lam.close()
        sched.close()
        conf.OBS_TRACE_SAMPLE.clear()
        # the armed controllers write through GLOBAL conf: reset the
        # four steered knobs so later tests see stock defaults
        for prop in (conf.CACHE_MIN_COST, conf.SCAN_FUSED_SLOTS,
                     conf.STREAM_FOLD_SLICE_ROWS, conf.STREAM_CHUNK_ROWS):
            prop.clear()
        obs.install(obs.Tracer())  # drop the witness-wrapped tracer


def test_every_registry_lock_witnessed_graph_acyclic_and_subgraph(tmp_path):
    """THE model-vs-reality gate (docs/concurrency.md): drive the
    workload under an armed witness, then check both directions —
    every LOCKS lock witnessed, the observed graph acyclic and inside
    the static prediction, no fault point under a hot lock. The
    observed graph is dumped to the artifact path either way."""
    lockwitness.enable()
    try:
        _workload(tmp_path)
    finally:
        lockwitness.disable()
    report = lockwitness.REPORT
    artifact = lockwitness.dump()  # the CI artifact, pass or fail
    snap = report.snapshot()

    # (a) every registry lock actually witnessed — a LOCKS entry the
    # workload cannot reach is as suspect as an unregistered lock
    missing = set(LOCKS) - set(snap["seen"])
    assert not missing, (
        f"registry locks never witnessed: {sorted(missing)} "
        f"(see {artifact})"
    )

    # (b) observed acquisition order is acyclic
    assert report.cycle() is None, (
        f"observed lock-order cycle {report.cycle()} (see {artifact})"
    )

    # (c) observed edges are a subgraph of the static model's predicted
    # edges (AST-derived + declared callback edges)
    model = LockModel.of(Project.load(ROOT))
    predicted = model.predicted_edges()
    surprise = [
        e for e in report.edges
        if e not in predicted and e[0] != e[1]
    ]
    assert not surprise, (
        f"observed edges missing from the static model: {surprise} "
        f"(see {artifact}) — resolve them in lockmodel (derived or "
        "DECLARED_EDGES) so the model stays truthful"
    )

    # (d) no fault point (IO/latency step) fired while a HOT lock was
    # held — the runtime twin of blocking-under-lock, pinning the WAL
    # _rotate/close seal-fsync fix. DECLARED_BLOCKING pairs (the
    # apply-then-record delete hook) are the registry's accepted,
    # justified exceptions.
    import fnmatch

    from geomesa_tpu.analysis.lockmodel import DECLARED_BLOCKING

    def declared(lock, point):
        return any(
            lock == dl and fnmatch.fnmatch(point, pat)
            for dl, pat, _why in DECLARED_BLOCKING
        )

    hot_blocking = {
        k: n for k, n in snap["blocking"].items()
        if model.is_hot(k.split(" @ ")[0])
        and not declared(*k.split(" @ "))
    }
    assert not hot_blocking, (
        f"fault points reached under hot locks: {hot_blocking} "
        f"(see {artifact})"
    )

    # the load-bearing nesting was actually observed, not vacuously
    assert ("WriteAheadLog._sync_lock", "WriteAheadLog._lock") in report.edges
    assert (
        "StreamingFeatureCache._lock", "WriteAheadLog._lock"
    ) in report.edges, "the delete hook's WAL append was not observed"
    assert os.path.exists(artifact)


def test_wal_rotation_seals_outside_append_lock(tmp_path):
    """Regression pin for the blocking-under-lock fix: with the witness
    armed and tiny segments, rotations happen during sustained appends
    and the stream.wal.rotate fault point must fire under the SYNC lock
    only — never while the hot append lock is held — while recovery
    still sees every acknowledged row."""
    lockwitness.enable()
    try:
        ds = DataStore()
        sft = FeatureType.from_spec("t", SPEC)
        ds.create_schema(sft)
        root = tmp_path / "s"
        persist.save(ds, root)
        lam = LambdaStore(
            ds, "t", config=StreamConfig(chunk_rows=64),
            wal_dir=str(root / "_wal"),
            wal_config=WalConfig(sync="always", segment_bytes=2 << 10),
        )
        lam.write(_rows(200, seed=5))
        assert lam.wal.metrics.counter_value(
            "geomesa.stream.wal.rotations"
        ) >= 1, "workload never rotated — shrink segment_bytes"
        lam.checkpoint(str(root))
        lam.close()
    finally:
        lockwitness.disable()
    blocking = lockwitness.REPORT.snapshot()["blocking"]
    rotate_holders = {
        k for k in blocking if k.endswith("@ stream.wal.rotate")
    }
    assert all(
        k.startswith("WriteAheadLog._sync_lock") for k in rotate_holders
    ), rotate_holders
    assert not any(
        k.startswith("WriteAheadLog._lock ") for k in blocking
    ), blocking
    # durability held across the un-locked seal: recovery replays clean
    again = LambdaStore.recover(str(root))
    assert again.count() == 200
    again.close()


# -- layer 3: overhead ----------------------------------------------------


def _overhead_workload():
    """Lock-crossing-heavy but real work: hot-tier writes + flushes
    into a cold store (no WAL fsyncs — disk noise would swamp the
    measurement)."""
    ds = DataStore()
    sft = FeatureType.from_spec("t", SPEC)
    ds.create_schema(sft)
    lam = LambdaStore(
        ds, "t", config=StreamConfig(chunk_rows=256, workers=2),
    )
    for batch in range(4):
        lam.write(_rows(1500, seed=batch, prefix=f"o{batch}_"))
        lam.flush()
    n = lam.count()
    lam.close()
    return n


def test_witness_overhead_smoke():
    """Witnessed wall time <= 1.5x unwitnessed (best-of-3 each; the
    disarmed path is passthrough so the baseline is the true cost)."""
    def measure():
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            n = _overhead_workload()
            best = min(best, time.perf_counter() - t0)
            assert n == 6000
        return best

    lockwitness.disable()
    base = measure()
    lockwitness.enable()
    try:
        witnessed = measure()
    finally:
        lockwitness.disable()
    assert witnessed <= 1.5 * base + 0.05, (
        f"witnessed {witnessed:.3f}s vs base {base:.3f}s "
        f"({witnessed / base:.2f}x, budget 1.5x)"
    )
