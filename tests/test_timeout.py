"""Scan deadline enforcement (reference ThreadManagement + per-plan
timeouts): queries carry a wall-clock budget and abort with QueryTimeout
at the next stage boundary once overdue."""

import numpy as np
import pytest

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu.planning.hints import QueryHints
from geomesa_tpu.planning.planner import QueryTimeout


@pytest.fixture(scope="module")
def ds():
    rng = np.random.default_rng(4)
    n = 5000
    sft = FeatureType.from_spec("t", "name:String,dtg:Date,*geom:Point:srid=4326")
    store = DataStore()
    store.create_schema(sft)
    t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
    fc = FeatureCollection.from_columns(
        sft, [str(i) for i in range(n)],
        {"name": np.array([f"n{i % 7}" for i in range(n)]),
         "dtg": t0 + rng.integers(0, 86400_000 * 20, n),
         "geom": (rng.uniform(-50, 50, n), rng.uniform(-50, 50, n))},
    )
    store.write("t", fc)
    return store


Q = "bbox(geom, -10, -10, 10, 10) AND dtg DURING 2024-01-02T00:00:00Z/2024-01-09T00:00:00Z"


class TestQueryTimeout:
    def test_tiny_deadline_indexed_scan_raises(self, ds):
        with pytest.raises(QueryTimeout):
            ds.query("t", Q, hints=QueryHints(timeout=1e-9))

    def test_tiny_deadline_full_scan_raises(self, ds):
        # LIKE on a non-indexed attribute -> full host scan path
        with pytest.raises(QueryTimeout):
            ds.query("t", "name LIKE 'n%'", hints=QueryHints(timeout=1e-9))

    def test_generous_deadline_unaffected(self, ds):
        out = ds.query("t", Q, hints=QueryHints(timeout=60.0))
        assert len(out) == len(ds.query("t", Q))

    def test_store_default_timeout(self, ds):
        ds.query_timeout = 1e-9
        try:
            with pytest.raises(QueryTimeout):
                ds.query("t", Q)
            # per-query hint overrides the store default
            out = ds.query("t", Q, hints=QueryHints(timeout=60.0))
            assert len(out) > 0
        finally:
            ds.query_timeout = None

    def test_invalid_timeout_rejected(self, ds):
        with pytest.raises(ValueError):
            ds.query("t", Q, hints=QueryHints(timeout=-1))

    def test_timeout_carries_elapsed_and_budget(self, ds):
        with pytest.raises(QueryTimeout) as ei:
            ds.query("t", Q, hints=QueryHints(timeout=1e-9))
        assert ei.value.budget_s == pytest.approx(1e-9)
        assert ei.value.elapsed_s is not None
        assert ei.value.elapsed_s > ei.value.budget_s
        assert "budget" in str(ei.value)


class TestTimeoutMetrics:
    def _metered_store(self):
        from geomesa_tpu.metrics import MetricsRegistry

        reg = MetricsRegistry()
        sft = FeatureType.from_spec("m", "dtg:Date,*geom:Point:srid=4326")
        store = DataStore(metrics=reg)
        store.create_schema(sft)
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
        n = 500
        rng = np.random.default_rng(7)
        store.write("m", FeatureCollection.from_columns(
            sft, [str(i) for i in range(n)],
            {"dtg": t0 + rng.integers(0, 86400_000 * 10, n),
             "geom": (rng.uniform(-50, 50, n), rng.uniform(-50, 50, n))},
        ))
        return store, reg

    Q = "bbox(geom, -10, -10, 10, 10) AND dtg DURING 2024-01-02T00:00:00Z/2024-01-05T00:00:00Z"

    def test_timed_out_scan_increments_counter(self):
        store, reg = self._metered_store()
        with pytest.raises(QueryTimeout):
            store.query("m", self.Q, hints=QueryHints(timeout=1e-9))
        assert reg.counters["geomesa.query.timeout"] == 1
        # a timed-out query is NOT recorded as a completed one
        assert reg.counters.get("geomesa.query.count", 0) == 0

    def test_pipelined_timeout_also_counted(self):
        store, reg = self._metered_store()
        plans = [store.planner.plan("m", self.Q) for _ in range(2)]
        finishes = store.planner.submit_many(
            plans, hints=QueryHints(timeout=1e-9)
        )
        for fin in finishes:
            with pytest.raises(QueryTimeout):
                fin()
        assert reg.counters["geomesa.query.timeout"] == 2

    def test_aggregation_timeout_also_counted(self):
        store, reg = self._metered_store()
        store.query_timeout = 1e-9
        try:
            with pytest.raises(QueryTimeout):
                store.stats_query("m", "Count()", self.Q, estimate=True)
        finally:
            store.query_timeout = None
        assert reg.counters["geomesa.query.timeout"] >= 1

    def test_successful_query_leaves_counter_untouched(self):
        store, reg = self._metered_store()
        store.query("m", self.Q, hints=QueryHints(timeout=60.0))
        assert reg.counters.get("geomesa.query.timeout", 0) == 0
        assert reg.counters["geomesa.query.count"] == 1
