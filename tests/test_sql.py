"""ST_ function library and grid-partitioned spatial join."""

import numpy as np
import pytest

from geomesa_tpu import geometry as geo
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.sql import FUNCTIONS, spatial_join, st_call
from geomesa_tpu.sql import functions as F


class TestFunctions:
    def test_registry_size(self):
        assert len(FUNCTIONS) >= 30

    def test_constructors(self):
        p = st_call("ST_Point", 1.0, 2.0)
        assert (p.x, p.y) == (1.0, 2.0)
        b = F.st_makebbox(0, 0, 2, 2)
        assert b.bounds() == (0, 0, 2, 2)
        line = F.st_makeline([F.st_point(0, 0), F.st_point(3, 4)])
        assert F.st_length(line) == 5.0
        g = F.st_geomfromwkt("POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))")
        assert F.st_area(g) == 16.0

    def test_accessors(self):
        g = geo.box(0, 0, 2, 4)
        assert F.st_geometrytype(g) == "Polygon"
        env = F.st_envelope(g)
        assert env.bounds() == (0, 0, 2, 4)
        c = F.st_centroid(g)
        assert (round(c.x, 9), round(c.y, 9)) == (1.0, 2.0)

    def test_centroid_with_hole(self):
        outer = np.array([[0, 0], [10, 0], [10, 10], [0, 10], [0, 0]], float)
        hole = np.array([[6, 4], [9, 4], [9, 6], [6, 6], [6, 4]], float)
        c = F.st_centroid(geo.Polygon(outer, [hole]))
        assert c.x < 5.0  # hole on the right pulls centroid left
        assert abs(c.y - 5.0) < 1e-9

    def test_relations(self):
        a = geo.box(0, 0, 4, 4)
        b = geo.box(2, 2, 6, 6)
        c = geo.box(10, 10, 11, 11)
        assert F.st_intersects(a, b) and not F.st_intersects(a, c)
        assert F.st_disjoint(a, c)
        assert F.st_contains(a, geo.Point(1, 1))
        assert F.st_within(geo.Point(1, 1), a)
        assert F.st_overlaps(a, b) and not F.st_overlaps(a, c)
        assert F.st_distance(a, c) == pytest.approx(np.hypot(6, 6))
        assert F.st_dwithin(a, b, 0.1)

    def test_outputs(self):
        g = geo.Point(3.5, -2.25)
        assert geo.from_wkt(F.st_astext(g)) == g
        assert geo.from_wkb(F.st_asbinary(g)) == g

    def test_buffer_point(self):
        ring = F.st_bufferpoint(geo.Point(0, 0), 111_320.0)
        x0, y0, x1, y1 = ring.bounds()
        assert 0.9 < y1 < 1.1 and -1.1 < y0 < -0.9

    def test_translate(self):
        g = geo.box(0, 0, 1, 1)
        t = F.st_translate(g, 5, -2)
        assert t.bounds() == (5, -2, 6, -1)

    def test_unknown(self):
        with pytest.raises(KeyError):
            st_call("ST_Bogus", 1)


def _points_fc(xy, name="pts"):
    sft = FeatureType.from_spec(name, "*geom:Point:srid=4326")
    xy = np.asarray(xy, dtype=np.float64)
    return FeatureCollection.from_columns(
        sft, np.arange(len(xy)).astype(str), {"geom": (xy[:, 0], xy[:, 1])}
    )


def _polys_fc(polys, name="polys"):
    sft = FeatureType.from_spec(name, "*geom:Polygon:srid=4326")
    return FeatureCollection.from_columns(
        sft, np.arange(len(polys)).astype(str), {"geom": polys}
    )


class TestSpatialJoin:
    def test_points_in_polygons(self):
        rng = np.random.default_rng(1)
        pts = rng.uniform(0, 10, (500, 2))
        polys = [geo.box(0, 0, 3, 3), geo.box(5, 5, 9, 9), geo.box(2, 2, 4, 4)]
        li, ri = spatial_join(_polys_fc(polys), _points_fc(pts), "contains")
        # brute force
        want = set()
        for i, p in enumerate(polys):
            x0, y0, x1, y1 = p.bounds()
            for j, (x, y) in enumerate(pts):
                if x0 <= x <= x1 and y0 <= y <= y1:
                    want.add((i, j))
        assert set(zip(li.tolist(), ri.tolist())) == want

    def test_intersects_polygons(self):
        a = [geo.box(0, 0, 2, 2), geo.box(10, 10, 12, 12)]
        b = [geo.box(1, 1, 3, 3), geo.box(20, 20, 21, 21), geo.box(11, 9, 13, 11)]
        li, ri = spatial_join(_polys_fc(a), _polys_fc(b, "b"), "intersects")
        assert set(zip(li.tolist(), ri.tolist())) == {(0, 0), (1, 2)}

    def test_dwithin_points(self):
        a = _points_fc([(0, 0), (5, 5)])
        b = _points_fc([(0.5, 0.0), (4.0, 4.0), (30, 30)], "b")
        li, ri = spatial_join(a, b, "dwithin", max_distance=1.6)
        assert set(zip(li.tolist(), ri.tolist())) == {(0, 0), (1, 1)}

    def test_empty(self):
        a = _points_fc(np.zeros((0, 2)))
        b = _points_fc([(1, 1)])
        li, ri = spatial_join(a, b)
        assert len(li) == 0 and len(ri) == 0

    def test_disjoint_envelopes(self):
        a = _points_fc([(0, 0)])
        b = _points_fc([(50, 50)], "b")
        li, _ = spatial_join(a, b)
        assert len(li) == 0


class TestNewStFunctions:
    """Round-4 ST_ additions: hull, simplify, boundary, accessors,
    geohash/TWKB bridges."""

    def test_convexhull(self):
        from geomesa_tpu.sql import functions as F

        rng = np.random.default_rng(0)
        pts = geo.MultiPoint(
            [geo.Point(float(x), float(y)) for x, y in rng.uniform(0, 1, (100, 2))]
            + [geo.Point(0, 0), geo.Point(1, 0), geo.Point(1, 1), geo.Point(0, 1)]
        )
        h = F.st_convexhull(pts)
        assert isinstance(h, geo.Polygon)
        assert abs(h.area - 1.0) < 1e-9
        # degenerate: single + collinear
        assert isinstance(F.st_convexhull(geo.Point(1, 2)), geo.Point)
        col = geo.MultiPoint([geo.Point(0, 0), geo.Point(1, 1), geo.Point(2, 2)])
        assert isinstance(F.st_convexhull(col), geo.LineString)

    def test_simplify_circle(self):
        from geomesa_tpu.sql import functions as F

        t = np.linspace(0, 2 * np.pi, 400)
        ring = np.stack([np.cos(t), np.sin(t)], axis=1)
        ring[-1] = ring[0]
        s = F.st_simplify(geo.Polygon(ring), 0.05)
        assert 8 <= len(s.shell) < 100
        assert abs(s.area - np.pi) < 0.2

    def test_boundary_and_accessors(self):
        from geomesa_tpu.sql import functions as F

        line = geo.LineString(np.array([[0, 0], [1, 1], [2, 0]], float))
        assert F.st_startpoint(line).x == 0
        assert F.st_endpoint(line).x == 2
        assert F.st_pointn(line, 2).y == 1
        assert len(F.st_boundary(line).parts) == 2
        sq = geo.Polygon(
            np.array([[0, 0], [4, 0], [4, 4], [0, 4], [0, 0]], float),
            [np.array([[1, 1], [1, 2], [2, 2], [2, 1], [1, 1]], float)],
        )
        assert F.st_numinteriorrings(sq) == 1
        assert isinstance(F.st_interiorringn(sq, 1), geo.LineString)
        assert isinstance(F.st_boundary(sq), geo.MultiLineString)
        mp = geo.MultiPoint([geo.Point(0, 0), geo.Point(1, 1)])
        assert F.st_numgeometries(mp) == 2
        assert F.st_geometryn(mp, 2).x == 1

    def test_geohash_twkb_bridges(self):
        from geomesa_tpu.sql import functions as F

        p = geo.Point(10.40744, 57.64911)
        assert F.st_geohash(p, 11) == "u4pruydqqvj"
        cell = F.st_geomfromgeohash("u4pruydqqvj")
        assert F.st_contains(cell, F.st_pointfromgeohash("u4pruydqqvj"))
        g2 = F.st_geomfromtwkb(F.st_astwkb(p))
        assert abs(g2.x - p.x) < 1e-7
        # registry dispatch path
        assert F.st_call("st_geohash", p, 5) == str(F.st_geohash(p, 5))
