"""ST_ function library and grid-partitioned spatial join."""

import numpy as np
import pytest

from geomesa_tpu import geometry as geo
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.sql import FUNCTIONS, spatial_join, st_call
from geomesa_tpu.sql import functions as F


class TestFunctions:
    def test_registry_size(self):
        assert len(FUNCTIONS) >= 30

    def test_constructors(self):
        p = st_call("ST_Point", 1.0, 2.0)
        assert (p.x, p.y) == (1.0, 2.0)
        b = F.st_makebbox(0, 0, 2, 2)
        assert b.bounds() == (0, 0, 2, 2)
        line = F.st_makeline([F.st_point(0, 0), F.st_point(3, 4)])
        assert F.st_length(line) == 5.0
        g = F.st_geomfromwkt("POLYGON ((0 0, 4 0, 4 4, 0 4, 0 0))")
        assert F.st_area(g) == 16.0

    def test_accessors(self):
        g = geo.box(0, 0, 2, 4)
        assert F.st_geometrytype(g) == "Polygon"
        env = F.st_envelope(g)
        assert env.bounds() == (0, 0, 2, 4)
        c = F.st_centroid(g)
        assert (round(c.x, 9), round(c.y, 9)) == (1.0, 2.0)

    def test_centroid_with_hole(self):
        outer = np.array([[0, 0], [10, 0], [10, 10], [0, 10], [0, 0]], float)
        hole = np.array([[6, 4], [9, 4], [9, 6], [6, 6], [6, 4]], float)
        c = F.st_centroid(geo.Polygon(outer, [hole]))
        assert c.x < 5.0  # hole on the right pulls centroid left
        assert abs(c.y - 5.0) < 1e-9

    def test_relations(self):
        a = geo.box(0, 0, 4, 4)
        b = geo.box(2, 2, 6, 6)
        c = geo.box(10, 10, 11, 11)
        assert F.st_intersects(a, b) and not F.st_intersects(a, c)
        assert F.st_disjoint(a, c)
        assert F.st_contains(a, geo.Point(1, 1))
        assert F.st_within(geo.Point(1, 1), a)
        assert F.st_overlaps(a, b) and not F.st_overlaps(a, c)
        assert F.st_distance(a, c) == pytest.approx(np.hypot(6, 6))
        assert F.st_dwithin(a, b, 0.1)

    def test_outputs(self):
        g = geo.Point(3.5, -2.25)
        assert geo.from_wkt(F.st_astext(g)) == g
        assert geo.from_wkb(F.st_asbinary(g)) == g

    def test_buffer_point(self):
        ring = F.st_bufferpoint(geo.Point(0, 0), 111_320.0)
        x0, y0, x1, y1 = ring.bounds()
        assert 0.9 < y1 < 1.1 and -1.1 < y0 < -0.9

    def test_translate(self):
        g = geo.box(0, 0, 1, 1)
        t = F.st_translate(g, 5, -2)
        assert t.bounds() == (5, -2, 6, -1)

    def test_unknown(self):
        with pytest.raises(KeyError):
            st_call("ST_Bogus", 1)


def _points_fc(xy, name="pts"):
    sft = FeatureType.from_spec(name, "*geom:Point:srid=4326")
    xy = np.asarray(xy, dtype=np.float64)
    return FeatureCollection.from_columns(
        sft, np.arange(len(xy)).astype(str), {"geom": (xy[:, 0], xy[:, 1])}
    )


def _polys_fc(polys, name="polys"):
    sft = FeatureType.from_spec(name, "*geom:Polygon:srid=4326")
    return FeatureCollection.from_columns(
        sft, np.arange(len(polys)).astype(str), {"geom": polys}
    )


class TestSpatialJoin:
    def test_points_in_polygons(self):
        rng = np.random.default_rng(1)
        pts = rng.uniform(0, 10, (500, 2))
        polys = [geo.box(0, 0, 3, 3), geo.box(5, 5, 9, 9), geo.box(2, 2, 4, 4)]
        li, ri = spatial_join(_polys_fc(polys), _points_fc(pts), "contains")
        # brute force
        want = set()
        for i, p in enumerate(polys):
            x0, y0, x1, y1 = p.bounds()
            for j, (x, y) in enumerate(pts):
                if x0 <= x <= x1 and y0 <= y <= y1:
                    want.add((i, j))
        assert set(zip(li.tolist(), ri.tolist())) == want

    def test_intersects_polygons(self):
        a = [geo.box(0, 0, 2, 2), geo.box(10, 10, 12, 12)]
        b = [geo.box(1, 1, 3, 3), geo.box(20, 20, 21, 21), geo.box(11, 9, 13, 11)]
        li, ri = spatial_join(_polys_fc(a), _polys_fc(b, "b"), "intersects")
        assert set(zip(li.tolist(), ri.tolist())) == {(0, 0), (1, 2)}

    def test_dwithin_points(self):
        a = _points_fc([(0, 0), (5, 5)])
        b = _points_fc([(0.5, 0.0), (4.0, 4.0), (30, 30)], "b")
        li, ri = spatial_join(a, b, "dwithin", max_distance=1.6)
        assert set(zip(li.tolist(), ri.tolist())) == {(0, 0), (1, 1)}

    def test_empty(self):
        a = _points_fc(np.zeros((0, 2)))
        b = _points_fc([(1, 1)])
        li, ri = spatial_join(a, b)
        assert len(li) == 0 and len(ri) == 0

    def test_disjoint_envelopes(self):
        a = _points_fc([(0, 0)])
        b = _points_fc([(50, 50)], "b")
        li, _ = spatial_join(a, b)
        assert len(li) == 0


class TestNewStFunctions:
    """Round-4 ST_ additions: hull, simplify, boundary, accessors,
    geohash/TWKB bridges."""

    def test_convexhull(self):
        from geomesa_tpu.sql import functions as F

        rng = np.random.default_rng(0)
        pts = geo.MultiPoint(
            [geo.Point(float(x), float(y)) for x, y in rng.uniform(0, 1, (100, 2))]
            + [geo.Point(0, 0), geo.Point(1, 0), geo.Point(1, 1), geo.Point(0, 1)]
        )
        h = F.st_convexhull(pts)
        assert isinstance(h, geo.Polygon)
        assert abs(h.area - 1.0) < 1e-9
        # degenerate: single + collinear
        assert isinstance(F.st_convexhull(geo.Point(1, 2)), geo.Point)
        col = geo.MultiPoint([geo.Point(0, 0), geo.Point(1, 1), geo.Point(2, 2)])
        assert isinstance(F.st_convexhull(col), geo.LineString)

    def test_simplify_circle(self):
        from geomesa_tpu.sql import functions as F

        t = np.linspace(0, 2 * np.pi, 400)
        ring = np.stack([np.cos(t), np.sin(t)], axis=1)
        ring[-1] = ring[0]
        s = F.st_simplify(geo.Polygon(ring), 0.05)
        assert 8 <= len(s.shell) < 100
        assert abs(s.area - np.pi) < 0.2

    def test_boundary_and_accessors(self):
        from geomesa_tpu.sql import functions as F

        line = geo.LineString(np.array([[0, 0], [1, 1], [2, 0]], float))
        assert F.st_startpoint(line).x == 0
        assert F.st_endpoint(line).x == 2
        assert F.st_pointn(line, 2).y == 1
        assert len(F.st_boundary(line).parts) == 2
        sq = geo.Polygon(
            np.array([[0, 0], [4, 0], [4, 4], [0, 4], [0, 0]], float),
            [np.array([[1, 1], [1, 2], [2, 2], [2, 1], [1, 1]], float)],
        )
        assert F.st_numinteriorrings(sq) == 1
        assert isinstance(F.st_interiorringn(sq, 1), geo.LineString)
        assert isinstance(F.st_boundary(sq), geo.MultiLineString)
        mp = geo.MultiPoint([geo.Point(0, 0), geo.Point(1, 1)])
        assert F.st_numgeometries(mp) == 2
        assert F.st_geometryn(mp, 2).x == 1

    def test_geohash_twkb_bridges(self):
        from geomesa_tpu.sql import functions as F

        p = geo.Point(10.40744, 57.64911)
        assert F.st_geohash(p, 11) == "u4pruydqqvj"
        cell = F.st_geomfromgeohash("u4pruydqqvj")
        assert F.st_contains(cell, F.st_pointfromgeohash("u4pruydqqvj"))
        g2 = F.st_geomfromtwkb(F.st_astwkb(p))
        assert abs(g2.x - p.x) < 1e-7
        # registry dispatch path
        assert F.st_call("st_geohash", p, 5) == str(F.st_geohash(p, 5))


class TestUdfParitySweep:
    """Reference spark-jts UDF parity: typed constructors, casts,
    dimension/simplicity accessors, GeoJSON, DE-9IM relations, sphere
    metrics, closest point, antimeridian split, limited overlay."""

    def test_typed_wkt_constructors(self):
        from geomesa_tpu.sql import functions as F

        assert F.st_pointfromtext("POINT (3 4)").y == 4
        assert F.st_linefromtext("LINESTRING (0 0, 1 1)").length > 0
        assert F.st_polygonfromtext("POLYGON ((0 0, 1 0, 1 1, 0 0))").area > 0
        assert len(F.st_mpointfromtext("MULTIPOINT ((0 0), (1 1))").parts) == 2
        assert len(F.st_mlinefromtext(
            "MULTILINESTRING ((0 0, 1 1), (2 2, 3 3))").parts) == 2
        assert len(F.st_mpolyfromtext(
            "MULTIPOLYGON (((0 0, 1 0, 1 1, 0 0)))").parts) == 1
        with pytest.raises(TypeError):
            F.st_pointfromtext("LINESTRING (0 0, 1 1)")
        p = F.st_pointfromwkb(geo.to_wkb(geo.Point(5, 6)))
        assert (p.x, p.y) == (5, 6)
        ring = geo.LineString(
            np.array([[0, 0], [2, 0], [2, 2], [0, 0]], float))
        assert isinstance(F.st_polygon(ring), geo.Polygon)
        box = F.st_makebox(geo.Point(0, 1), geo.Point(2, 3))
        assert box.bounds() == (0, 1, 2, 3)
        assert F.st_makepointm(1, 2, 99).x == 1

    def test_casts(self):
        from geomesa_tpu.sql import functions as F

        p = geo.Point(1, 2)
        assert F.st_casttogeometry(p) is p
        assert F.st_casttopoint(p) is p
        with pytest.raises(TypeError):
            F.st_casttolinestring(p)
        with pytest.raises(TypeError):
            F.st_casttopolygon(p)

    def test_dimension_accessors(self):
        from geomesa_tpu.sql import functions as F

        line = geo.LineString(np.array([[0, 0], [1, 1]], float))
        assert F.st_coorddim(line) == 2
        assert F.st_dimension(geo.Point(0, 0)) == 0
        assert F.st_dimension(line) == 1
        assert F.st_dimension(geo.box(0, 0, 1, 1)) == 2
        assert F.st_dimension(geo.MultiPolygon([geo.box(0, 0, 1, 1)])) == 2
        assert not F.st_isempty(line)
        assert F.st_isempty(geo.MultiPoint([]))
        assert F.st_iscollection(geo.MultiPoint([]))
        assert not F.st_iscollection(line)

    def test_closed_simple_ring(self):
        from geomesa_tpu.sql import functions as F

        open_l = geo.LineString(np.array([[0, 0], [1, 1], [2, 0]], float))
        ring = geo.LineString(
            np.array([[0, 0], [1, 0], [1, 1], [0, 0]], float))
        bowtie = geo.LineString(
            np.array([[0, 0], [2, 2], [2, 0], [0, 2]], float))
        assert not F.st_isclosed(open_l)
        assert F.st_isclosed(ring)
        assert F.st_issimple(open_l)
        assert F.st_issimple(ring)
        assert not F.st_issimple(bowtie)
        assert F.st_isring(ring)
        assert not F.st_isring(open_l)
        dup = geo.MultiPoint([geo.Point(1, 1), geo.Point(1, 1)])
        assert not F.st_issimple(dup)

    def test_geojson_roundtrip(self):
        import json

        from geomesa_tpu.sql import functions as F

        poly = geo.Polygon(
            np.array([[0, 0], [4, 0], [4, 4], [0, 4], [0, 0]], float),
            [np.array([[1, 1], [1, 2], [2, 2], [2, 1], [1, 1]], float)],
        )
        for g in (
            geo.Point(1, 2),
            geo.LineString(np.array([[0, 0], [1, 1]], float)),
            poly,
            geo.MultiPoint([geo.Point(0, 0), geo.Point(1, 1)]),
            geo.MultiPolygon([poly]),
        ):
            s = F.st_asgeojson(g)
            assert json.loads(s)["type"] == g.geom_type
            g2 = F.st_geomfromgeojson(s)
            assert g2 == g

    def test_latlontext_bytearray(self):
        from geomesa_tpu.sql import functions as F

        txt = F.st_aslatlontext(geo.Point(-122.5, 37.75))
        assert txt.endswith("W") and "N" in txt and "37°45'" in txt
        assert F.st_bytearray("abc") == b"abc"

    def test_touches_crosses(self):
        from geomesa_tpu.sql import functions as F

        a = geo.box(0, 0, 2, 2)
        b = geo.box(2, 0, 4, 2)      # shares an edge with a
        c = geo.box(1, 1, 3, 3)      # overlaps a
        assert F.st_touches(a, b)
        assert not F.st_touches(a, c)
        line_through = geo.LineString(np.array([[-1, 1], [3, 1]], float))
        line_touch = geo.LineString(np.array([[-1, 0], [3, 0]], float))
        assert F.st_crosses(line_through, a)
        assert not F.st_crosses(line_touch, a)
        assert F.st_touches(line_touch, a)
        # L/L proper crossing vs shared-run overlap
        l1 = geo.LineString(np.array([[0, 0], [2, 2]], float))
        l2 = geo.LineString(np.array([[0, 2], [2, 0]], float))
        l3 = geo.LineString(np.array([[1, 1], [3, 3]], float))
        assert F.st_crosses(l1, l2)
        assert not F.st_crosses(l1, l3)  # collinear overlap, not a cross
        # point in polygon interior crosses nothing (P/A is within)
        assert not F.st_crosses(geo.Point(1, 1), a)

    def test_relate(self):
        from geomesa_tpu.sql import functions as F

        a = geo.box(0, 0, 2, 2)
        b = geo.box(2, 0, 4, 2)
        c = geo.box(1, 1, 3, 3)
        far = geo.box(10, 10, 11, 11)
        assert F.st_relate(a, b) == "FF2F11212"   # edge-adjacent squares (JTS)
        assert F.st_relatebool(a, b, "FF*FT****")  # touches pattern
        assert F.st_relatebool(a, c, "T*T***T**")  # overlaps pattern
        assert F.st_relatebool(a, far, "FF*FF****")  # disjoint
        inside = geo.Point(1, 1)
        assert F.st_relatebool(inside, a, "T*F**F***")  # within pattern

    def test_sphere_metrics(self):
        from geomesa_tpu.sql import functions as F

        sf = geo.Point(-122.4194, 37.7749)
        la = geo.Point(-118.2437, 34.0522)
        d = F.st_distancesphere(sf, la)
        assert 550_000 < d < 570_000  # ~559 km
        line = geo.LineString(np.array([[-122.4194, 37.7749],
                                        [-118.2437, 34.0522]], float))
        assert abs(F.st_lengthsphere(line) - d) < 1.0
        assert abs(F.st_aggregatedistancesphere([sf, la]) - d) < 1.0
        assert F.st_aggregatedistancesphere([sf]) == 0.0

    def test_closestpoint(self):
        from geomesa_tpu.sql import functions as F

        sq = geo.box(0, 0, 2, 2)
        p = F.st_closestpoint(sq, geo.Point(5, 1))
        assert (p.x, p.y) == (2, 1)
        line = geo.LineString(np.array([[0, 0], [10, 0]], float))
        p2 = F.st_closestpoint(line, geo.Point(3, 4))
        assert (p2.x, p2.y) == (3, 0)
        # crossing lines: the closest point is the crossing itself
        l1 = geo.LineString(np.array([[0, 0], [2, 2]], float))
        l2 = geo.LineString(np.array([[0, 2], [2, 0]], float))
        px = F.st_closestpoint(l1, l2)
        assert abs(px.x - 1) < 1e-9 and abs(px.y - 1) < 1e-9

    def test_makevalid(self):
        from geomesa_tpu.sql import functions as F

        # ring with a duplicated vertex and an open end
        ring = np.array([[0, 0], [0, 0], [4, 0], [4, 4], [0, 4]], float)
        fixed = F.st_makevalid(geo.LineString(ring))
        c = np.asarray(fixed.coords)
        assert len(c) == 4  # duplicate dropped

    def test_antimeridian_safe(self):
        from geomesa_tpu.sql import functions as F

        # polygon spanning 170..-170 (crosses the antimeridian)
        poly = geo.Polygon(np.array(
            [[170, 0], [-170, 0], [-170, 10], [170, 10], [170, 0]], float))
        safe = F.st_antimeridiansafegeom(poly)
        assert isinstance(safe, geo.MultiPolygon)
        assert len(safe.parts) == 2
        areas = sorted(p.area for p in safe.parts)
        assert abs(sum(areas) - 200.0) < 1e-6  # 20 deg x 10 deg total
        bounds = [p.bounds() for p in safe.parts]
        assert all(b[2] <= 180.0 and b[0] >= -180.0 for b in bounds)
        # non-crossing geometries pass through untouched
        small = geo.box(0, 0, 1, 1)
        assert F.st_antimeridiansafegeom(small) is small
        line = geo.LineString(np.array([[175, 0], [-175, 5]], float))
        safe_l = F.st_antimeridiansafegeom(line)
        assert isinstance(safe_l, geo.MultiLineString)
        assert len(safe_l.parts) == 2

    def test_intersection_point_line(self):
        from geomesa_tpu.sql import functions as F

        sq = geo.box(0, 0, 4, 4)
        assert F.st_intersection(geo.Point(1, 1), sq) == geo.Point(1, 1)
        assert F.st_intersection(geo.Point(9, 9), sq)._coord_count() == 0
        line = geo.LineString(np.array([[-2, 2], [6, 2]], float))
        seg = F.st_intersection(line, sq)
        assert isinstance(seg, geo.LineString)
        c = np.asarray(seg.coords)
        assert c[0].tolist() == [0, 2] and c[-1].tolist() == [4, 2]
        # line passing outside
        miss = geo.LineString(np.array([[-2, 9], [6, 9]], float))
        assert F.st_intersection(miss, sq)._coord_count() == 0

    def test_intersection_polygons(self):
        from geomesa_tpu.sql import functions as F

        a = geo.box(0, 0, 4, 4)
        b = geo.box(2, 2, 6, 6)
        out = F.st_intersection(a, b)
        assert isinstance(out, geo.Polygon)
        assert abs(out.area - 4.0) < 1e-9
        assert out.bounds() == (2, 2, 4, 4)
        # disjoint -> empty
        assert F.st_intersection(a, geo.box(9, 9, 10, 10))._coord_count() == 0
        # concave x concave raises rather than approximating
        concave = geo.Polygon(np.array(
            [[0, 0], [4, 0], [4, 4], [2, 1], [0, 4], [0, 0]], float))
        with pytest.raises(ValueError):
            F.st_intersection(concave, concave)

    def test_difference(self):
        from geomesa_tpu.sql import functions as F

        sq = geo.box(0, 0, 4, 4)
        assert F.st_difference(geo.Point(9, 9), sq) == geo.Point(9, 9)
        line = geo.LineString(np.array([[-2, 2], [6, 2]], float))
        out = F.st_difference(line, sq)
        assert isinstance(out, geo.MultiLineString)
        assert len(out.parts) == 2
        total = sum(p.length for p in out.parts)
        assert abs(total - 4.0) < 1e-9  # 2 outside on each side

    def test_registry_covers_reference_names(self):
        """Every implemented name resolves through st_call with the
        reference's CamelCase spelling."""
        from geomesa_tpu.sql import FUNCTIONS, st_call

        assert len(FUNCTIONS) >= 75
        sq = geo.box(0, 0, 2, 2)
        assert st_call("ST_Touches", sq, geo.box(2, 0, 4, 2))
        assert st_call("ST_Dimension", sq) == 2
        assert st_call("ST_IsCollection", geo.MultiPoint([]))


class TestUdfReviewFixes:
    """Regression pins for the code-review findings on the UDF sweep."""

    def test_antimeridian_line_west_piece_bounds(self):
        from geomesa_tpu.sql import functions as F

        line = geo.LineString(np.array([[175, 0], [-175, 5]], float))
        safe = F.st_antimeridiansafegeom(line)
        for part in safe.parts:
            x0, _, x1, _ = part.bounds()
            assert x1 - x0 <= 10.0, f"piece spans the map: {part.bounds()}"
        # the west piece starts exactly at -180
        west = min(safe.parts, key=lambda p: p.bounds()[0])
        assert west.bounds()[0] == -180.0

    def test_closestpoint_multipoint(self):
        from geomesa_tpu.sql import functions as F

        mp = geo.MultiPoint([geo.Point(0, 0), geo.Point(1, 1)])
        p = F.st_closestpoint(mp, geo.Point(5, 5))
        assert (p.x, p.y) == (1, 1)
        # point-typed right operand against a polygon left operand
        sq = geo.box(0, 0, 2, 2)
        p2 = F.st_closestpoint(sq, mp)  # intersecting: a shared point
        assert geo.intersects(geo.Point(p2.x, p2.y), sq)

    def test_line_through_polygon_vertices(self):
        from geomesa_tpu.sql import functions as F

        sq = geo.box(0, 0, 2, 2)
        diag = geo.LineString(np.array([[-1, -1], [3, 3]], float))
        assert F.st_crosses(diag, sq)
        assert not F.st_touches(diag, sq)
        # symmetric corner-to-corner through-vertex entry (midpoint of the
        # single edge is the box corner itself)
        diag2 = geo.LineString(np.array([[-3, -3], [3, 3]], float))
        assert F.st_crosses(diag2, sq)
        # L/L crossing through a vertex of the other line
        bent = geo.LineString(np.array([[0, 0], [1, 1], [2, 0]], float))
        vert = geo.LineString(np.array([[1, 0], [1, 2]], float))
        assert F.st_crosses(bent, vert)

    def test_closed_line_boundary_empty(self):
        from geomesa_tpu.sql import functions as F

        ring = geo.LineString(np.array([[1, 1], [2, 1], [2, 2], [1, 1]], float))
        assert F.st_boundary(ring)._coord_count() == 0
        sq = geo.box(0, 0, 4, 4)
        m = F.st_relate(ring, sq)
        assert m[3] == "F"  # BI: closed line has no boundary
        # mod-2: two open parts sharing one endpoint -> 2 odd endpoints
        a = geo.LineString(np.array([[0, 0], [1, 0]], float))
        b = geo.LineString(np.array([[1, 0], [2, 0]], float))
        bd = F.st_boundary(geo.MultiLineString([a, b]))
        assert sorted((p.x, p.y) for p in bd.parts) == [(0, 0), (2, 0)]

    def test_dms_carry(self):
        from geomesa_tpu.sql import functions as F

        txt = F.st_aslatlontext(geo.Point(0.0, 8.9999999999))
        assert txt.startswith("9°0'0.000\"N")
        assert "60.000" not in txt

    def test_simple_large_line_fast(self):
        import time

        from geomesa_tpu.sql import functions as F

        t = np.linspace(0, 50 * np.pi, 20000)
        spiral = geo.LineString(np.stack([t * np.cos(t), t * np.sin(t)], 1))
        t0 = time.monotonic()
        assert F.st_issimple(spiral)
        assert time.monotonic() - t0 < 10.0

    def test_simple_degenerate_axis_lines_fast(self):
        """Axis-degenerate tracks (every x-span — or every y-span —
        overlapping) must not blow up the sweep prune's time or memory:
        the sweep picks the axis with fewer candidate pairs."""
        import time

        from geomesa_tpu.sql import functions as F

        yy = np.linspace(0.0, 1000.0, 100_000)
        zz = np.zeros_like(yy)
        for coords in (np.stack([zz, yy], 1), np.stack([yy, zz], 1)):
            t0 = time.monotonic()
            assert F._line_is_simple(coords)
            assert time.monotonic() - t0 < 10.0
        # ... and a crossing is still caught on such a track
        bad = np.stack([zz[:100], yy[:100]], 1).copy()
        bad[-1] = (0.0, yy[50])  # doubles back over the middle
        assert not F._line_is_simple(bad)


class TestUdfReviewFixes2:
    """Second review pass: boundary-identical interiors, on-meridian
    vertices, chained-multiline interiors, degenerate overlay inputs."""

    def test_equal_polygons_not_touching(self):
        from geomesa_tpu.sql import functions as F

        a = geo.box(0, 0, 2, 2)
        assert not F.st_touches(a, geo.box(0, 0, 2, 2))
        assert F.st_relate(a, geo.box(0, 0, 2, 2))[0] != "F"  # II nonempty
        # one polygon tracing part of the other's boundary, overlapping
        half = geo.box(0, 0, 1, 2)
        assert not F.st_touches(a, half)

    def test_antimeridian_vertex_on_meridian(self):
        from geomesa_tpu.sql import functions as F

        line = geo.LineString(np.array([[170, 0], [180, 0], [-170, 0]], float))
        safe = F.st_antimeridiansafegeom(line)
        parts = safe.parts if hasattr(safe, "parts") else [safe]
        for p in parts:
            x0, _, x1, _ = p.bounds()
            assert -180.0 <= x0 and x1 <= 180.0, p.bounds()
            assert x1 - x0 <= 10.0

    def test_chained_multiline_interior_node(self):
        from geomesa_tpu.sql import functions as F

        chain = geo.MultiLineString([
            geo.LineString(np.array([[0, 0], [1, 0]], float)),
            geo.LineString(np.array([[1, 0], [2, 0]], float)),
        ])
        # (1,0) is interior by the mod-2 rule: a point there is WITHIN
        assert not F.st_touches(geo.Point(1, 0), chain)
        assert F.st_touches(geo.Point(0, 0), chain)  # a true endpoint

    def test_makevalid_collapsed_shell(self):
        from geomesa_tpu.sql import functions as F

        degenerate = geo.Polygon(
            np.array([[1, 1], [1, 1], [1, 1], [1, 1]], float))
        out = F.st_makevalid(degenerate)
        assert out._coord_count() == 0  # empty, not a crash

    def test_disconnected_concave_intersection_refused(self):
        from geomesa_tpu.sql import functions as F

        u_shape = geo.Polygon(np.array(
            [[0, 0], [5, 0], [5, 4], [4, 4], [4, 1], [1, 1], [1, 4],
             [0, 4], [0, 0]], float))
        band = geo.box(-1, 2, 6, 5)  # cuts the U into two prongs
        with pytest.raises(ValueError):
            F.st_intersection(u_shape, band)
        # connected concave intersection still works
        low_band = geo.box(-1, -1, 6, 0.5)
        out = F.st_intersection(u_shape, low_band)
        assert abs(out.area - 2.5) < 1e-9  # 5 wide x 0.5 tall


class TestLeafletPopupEscape:
    def test_popup_sink_escaped(self):
        import numpy as np

        from geomesa_tpu.features import FeatureCollection
        from geomesa_tpu.io.exporters import export
        from geomesa_tpu.sft import FeatureType

        sft = FeatureType.from_spec("m", "name:String,*geom:Point:srid=4326")
        fc = FeatureCollection.from_columns(
            sft, ["0"],
            {"name": np.array(["<img src=x onerror=alert(1)>"], dtype=object),
             "geom": (np.array([1.0]), np.array([2.0]))},
        )
        html = export(fc, "leaflet")
        # the hostile value rides inside the GeoJSON (JS string), and the
        # popup renderer escapes before inserting as HTML
        assert "esc(JSON.stringify" in html


class TestIndexedJoin:
    """Device-side join against an indexed point store (VERDICT r4 #3):
    results must match the host grid join pair for pair."""

    def _setup(self, n_pts=20000, n_poly=40, seed=5):
        import numpy as np

        from geomesa_tpu import geometry as geo
        from geomesa_tpu.datastore import DataStore
        from geomesa_tpu.features import FeatureCollection
        from geomesa_tpu.sft import FeatureType

        rng = np.random.default_rng(seed)
        x = rng.uniform(-90, 90, n_pts)
        y = rng.uniform(-45, 45, n_pts)
        sft = FeatureType.from_spec("jp", "*geom:Point:srid=4326")
        sft.user_data["geomesa.indices.enabled"] = "z2"
        ds = DataStore(tile=64)
        ds.create_schema(sft)
        ds.write("jp", FeatureCollection.from_columns(
            sft, np.arange(n_pts), {"geom": (x, y)}))
        px0 = rng.uniform(-85, 70, n_poly)
        py0 = rng.uniform(-40, 30, n_poly)
        pw = rng.uniform(1, 12, n_poly)
        ph = rng.uniform(1, 8, n_poly)
        polys = geo.PackedGeometryColumn.from_boxes(px0, py0, px0 + pw, py0 + ph)
        gsft = FeatureType.from_spec("adm", "*geom:Polygon:srid=4326")
        left = FeatureCollection.from_columns(gsft, np.arange(n_poly), {"geom": polys})
        return ds, left, (x, y), (px0, py0, px0 + pw, py0 + ph)

    def test_matches_host_join_contains(self):
        import numpy as np

        from geomesa_tpu.sql.join import spatial_join, spatial_join_indexed

        ds, left, _, _ = self._setup()
        li, ri = spatial_join_indexed(ds, "jp", left, "contains")
        hl, hr = spatial_join(left, ds.features("jp"), "contains")
        got = sorted(zip(li.tolist(), ri.tolist()))
        want = sorted(zip(hl.tolist(), hr.tolist()))
        assert len(got) > 1000
        assert got == want

    def test_matches_brute_force_intersects(self):
        import numpy as np

        from geomesa_tpu.sql.join import spatial_join_indexed

        ds, left, (x, y), (bx0, by0, bx1, by1) = self._setup(n_pts=8000, n_poly=16)
        li, ri = spatial_join_indexed(ds, "jp", left, "intersects")
        pairs = set(zip(li.tolist(), ri.tolist()))
        want = set()
        for k in range(16):
            m = (x >= bx0[k]) & (x <= bx1[k]) & (y >= by0[k]) & (y <= by1[k])
            want |= {(k, int(j)) for j in np.flatnonzero(m)}
        assert pairs == want

    def test_nonrect_polygons_device_pip(self):
        import numpy as np

        from geomesa_tpu import geometry as geo
        from geomesa_tpu.features import FeatureCollection
        from geomesa_tpu.sft import FeatureType
        from geomesa_tpu.sql.join import spatial_join_indexed

        ds, _, (x, y), _ = self._setup(n_pts=8000)
        tris = []
        rng = np.random.default_rng(11)
        for _ in range(12):
            cx, cy = rng.uniform(-60, 60), rng.uniform(-30, 30)
            r = rng.uniform(3, 15)
            tris.append(geo.Polygon(
                [(cx - r, cy - r), (cx + r, cy - r), (cx, cy + r)]))
        gsft = FeatureType.from_spec("tri", "*geom:Polygon:srid=4326")
        left = FeatureCollection.from_columns(
            gsft, np.arange(12), {"geom": geo.PackedGeometryColumn.from_geometries(tris)})
        li, ri = spatial_join_indexed(ds, "jp", left, "intersects")
        pairs = set(zip(li.tolist(), ri.tolist()))
        want = set()
        for k, t in enumerate(tris):
            m = geo.points_in_polygon(x, y, t)
            want |= {(k, int(j)) for j in np.flatnonzero(m)}
        assert len(pairs) > 100
        assert pairs == want

    def test_with_delta_tier(self):
        import numpy as np

        from geomesa_tpu.features import FeatureCollection
        from geomesa_tpu.sql.join import spatial_join, spatial_join_indexed

        ds, left, _, _ = self._setup(n_pts=5000)
        # un-compacted second write: the join must see delta rows too
        rng = np.random.default_rng(13)
        sft = ds.get_schema("jp")
        ds.write("jp", FeatureCollection.from_columns(
            sft, np.arange(100000, 100200),
            {"geom": (rng.uniform(-90, 90, 200), rng.uniform(-45, 45, 200))}),
            check_ids=False)
        li, ri = spatial_join_indexed(ds, "jp", left, "contains")
        hl, hr = spatial_join(left, ds.features("jp"), "contains")
        assert sorted(zip(li.tolist(), ri.tolist())) == sorted(zip(hl.tolist(), hr.tolist()))

    def test_many_edge_polygon_exact(self):
        """A left polygon past the edge-bucket ladder (>256 edges) must
        host-refine every candidate — bbox certainty alone would emit
        bbox-inside-but-outside-polygon false pairs (review regression)."""
        import numpy as np

        from geomesa_tpu import geometry as geo
        from geomesa_tpu.features import FeatureCollection
        from geomesa_tpu.sft import FeatureType
        from geomesa_tpu.sql.join import spatial_join_indexed

        ds, _, (x, y), _ = self._setup(n_pts=4000)
        a = np.linspace(0, 2 * np.pi, 301)[:-1]
        ell = geo.Polygon([(30 * np.cos(t), 15 * np.sin(t)) for t in a])
        gsft = FeatureType.from_spec("big", "*geom:Polygon:srid=4326")
        left = FeatureCollection.from_columns(
            gsft, np.arange(1),
            {"geom": geo.PackedGeometryColumn.from_geometries([ell])})
        li, ri = spatial_join_indexed(ds, "jp", left, "intersects")
        truth = geo.points_in_polygon(x, y, ell)
        assert set(ri.tolist()) == set(np.flatnonzero(truth).tolist())

    def test_missing_index_clear_error(self):
        import numpy as np
        import pytest

        from geomesa_tpu.sql.join import spatial_join_indexed

        ds, left, _, _ = self._setup(n_pts=100)
        with pytest.raises(ValueError, match="s2"):
            spatial_join_indexed(ds, "jp", left, "contains", index="s2")
