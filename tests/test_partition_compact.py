"""Partition-preserving merge compaction (reference TimePartition,
index/conf/partition/TimePartition.scala): folding a delta into the sorted
table sorts ONLY the delta and re-uploads only device blocks past the
first insertion point — time partitions are contiguous segments of the
(bin, z) sort, so recent-time appends touch only the tail."""

import numpy as np
import pytest

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu.filter import ecql

SPEC = "dtg:Date,*geom:Point:srid=4326"
T0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
DAY = 86400_000


def _fc(sft, ids, day_lo, day_hi, seed):
    rng = np.random.default_rng(seed)
    n = len(ids)
    return FeatureCollection.from_columns(
        sft, ids,
        {
            "dtg": T0 + rng.integers(day_lo * DAY, day_hi * DAY, n),
            "geom": (rng.uniform(-60, 60, n), rng.uniform(-45, 45, n)),
        },
    )


QUERIES = [
    "bbox(geom, -20, -15, 25, 20) AND dtg DURING 2024-01-02T00:00:00Z/2024-01-25T00:00:00Z",
    "bbox(geom, -5, -5, 5, 5)",
    "bbox(geom, -60, -45, 60, 45) AND dtg DURING 2024-01-20T00:00:00Z/2024-01-23T00:00:00Z",
]


class TestMergeCompaction:
    def _store(self, tile=4096):
        sft = FeatureType.from_spec("p", SPEC)
        sft.user_data["geomesa.indices.enabled"] = "z3"
        ds = DataStore(tile=tile)
        ds.create_schema(sft)
        return ds, sft

    def test_recent_append_sorts_only_delta(self):
        ds, sft = self._store()
        n_base, n_delta = 40960, 2000
        ds.write("p", _fc(sft, [str(i) for i in range(n_base)], 0, 20, 1), check_ids=False)
        base_table = ds._tables[("p", "z3")]
        assert base_table.rows_sorted == n_base
        # recent-time delta (days 19-21): lands in the tail bins
        ds.write(
            "p", _fc(sft, [f"d{i}" for i in range(n_delta)], 19, 21, 2), check_ids=False
        )
        ds.compact("p")
        t = ds._tables[("p", "z3")]
        assert t.n == n_base + n_delta
        assert t.rows_sorted == n_delta  # only the delta was sorted
        assert t.rows_uploaded < t.n_pad  # prefix device blocks reused

    def test_merged_equals_fresh_build(self):
        ds, sft = self._store()
        base = _fc(sft, [str(i) for i in range(30000)], 0, 25, 3)
        delta = _fc(sft, [f"d{i}" for i in range(3000)], 10, 26, 4)
        ds.write("p", base, check_ids=False)
        ds.write("p", delta, check_ids=False)
        ds.compact("p")

        fresh, _ = self._store()
        fresh.write("p", base, check_ids=False)
        fresh.write("p", delta, check_ids=False)
        fresh._main_rows["p"] = 0  # force a from-scratch rebuild
        fresh.compact("p")

        a, b = ds._tables[("p", "z3")], fresh._tables[("p", "z3")]
        assert np.array_equal(np.asarray(a.perm, np.int64), np.asarray(b.perm, np.int64))
        assert np.array_equal(a.bins, b.bins)
        assert np.array_equal(a.zs, b.zs)
        for k in a.col_names:
            assert np.array_equal(np.asarray(a.cols3[k]), np.asarray(b.cols3[k]))
        for q in QUERIES:
            assert sorted(ds.query("p", q).ids.tolist()) == sorted(
                fresh.query("p", q).ids.tolist()
            )

    def test_queries_exact_after_merge(self):
        ds, sft = self._store()
        ds.write("p", _fc(sft, [str(i) for i in range(20000)], 0, 15, 5), check_ids=False)
        ds.write("p", _fc(sft, [f"a{i}" for i in range(1500)], 14, 16, 6), check_ids=False)
        ds.compact("p")
        # second merge round on top of a merged table
        ds.write("p", _fc(sft, [f"b{i}" for i in range(1500)], 15, 17, 7), check_ids=False)
        ds.compact("p")
        full = ds.features("p")
        for q in QUERIES:
            f = ecql.parse(q)
            expect = sorted(full.ids[np.asarray(f.evaluate(full.batch))].tolist())
            assert sorted(ds.query("p", q).ids.tolist()) == expect

    def test_old_time_delta_still_exact(self):
        ds, sft = self._store()
        ds.write("p", _fc(sft, [str(i) for i in range(20000)], 10, 25, 8), check_ids=False)
        # delta BEFORE the base time range: inserts at the head, full upload
        ds.write("p", _fc(sft, [f"o{i}" for i in range(1000)], 0, 2, 9), check_ids=False)
        ds.compact("p")
        t = ds._tables[("p", "z3")]
        assert t.rows_sorted == 1000
        full = ds.features("p")
        for q in QUERIES[:2]:
            f = ecql.parse(q)
            expect = sorted(full.ids[np.asarray(f.evaluate(full.batch))].tolist())
            assert sorted(ds.query("p", q).ids.tolist()) == expect
