"""Query expression transforms (VERDICT r4 missing #2).

Reference: QueryPlanner.scala:189-312 configureQuery transform handling —
derived expressions (renames, functions over attributes) evaluated in the
query pipeline, sharing the converter expression DSL (io.converters).
"""

import numpy as np
import pytest

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.planning.hints import QueryHints
from geomesa_tpu.sft import FeatureType


def _store():
    n = 100
    rng = np.random.default_rng(11)
    x = rng.uniform(-170, 170, n)
    y = rng.uniform(-80, 80, n)
    names = np.array([f"n{i:03d}" for i in range(n)])
    val = rng.uniform(0, 10, n)
    sft = FeatureType.from_spec(
        "t", "name:String,val:Double,*geom:Point:srid=4326"
    )
    ds = DataStore()
    ds.create_schema(sft)
    ds.write("t", FeatureCollection.from_columns(
        sft, np.arange(n), {"name": names, "val": val, "geom": (x, y)}
    ))
    return ds, x, y, names, val


class TestExpressionTransforms:
    def test_st_xy_accessors_and_plain_name(self):
        ds, x, y, names, _ = _store()
        out = ds.query(
            "t", "INCLUDE",
            hints=QueryHints(transforms=["lon=st_x(geom)", "lat=st_y(geom)", "name"]),
        )
        assert list(out.columns) == ["lon", "lat", "name"]
        ids = np.asarray(out.ids)
        np.testing.assert_allclose(out.columns["lon"], x[ids])
        np.testing.assert_allclose(out.columns["lat"], y[ids])
        assert out.sft.attr("lon").type == "Double"

    def test_rename_and_cast(self):
        ds, _, _, names, val = _store()
        out = ds.query(
            "t", "INCLUDE",
            hints=QueryHints(transforms=["label=name", "ival=val::int"]),
        )
        ids = np.asarray(out.ids)
        assert out.columns["label"].dtype.kind in "US"
        np.testing.assert_array_equal(out.columns["label"], names[ids])
        np.testing.assert_array_equal(
            out.columns["ival"], val[ids].astype(np.int64)
        )
        assert out.sft.attr("ival").type == "Long"

    def test_string_functions(self):
        ds, _, _, names, _ = _store()
        out = ds.query(
            "t", "IN ('3')",
            hints=QueryHints(transforms=["u=uppercase(name)", "c=concat(name, '!')"]),
        )
        assert out.columns["u"][0] == names[3].upper()
        assert out.columns["c"][0] == names[3] + "!"

    def test_geometry_producing_expression(self):
        ds, x, y, _, _ = _store()
        out = ds.query(
            "t", "IN ('5')",
            hints=QueryHints(transforms=["b=st_bufferpoint(geom, 111320)"]),
        )
        g = out.geometries()[0]
        bx = g.bounds()
        # ~1 degree lon radius at the equator scaled by 1/cos(lat)
        assert bx[0] < x[5] < bx[2] and bx[1] < y[5] < bx[3]
        assert out.sft.geom_field == "b"
        # point-producing expression becomes a PointColumn geometry
        out2 = ds.query(
            "t", "IN ('5')",
            hints=QueryHints(transforms=["c=st_centroid(geom)", "v=val"]),
        )
        from geomesa_tpu.filter.predicates import PointColumn

        assert isinstance(out2.geom_column, PointColumn)
        assert abs(float(out2.geom_column.x[0]) - x[5]) < 1e-9

    def test_unknown_attr_raises(self):
        ds, *_ = _store()
        with pytest.raises(KeyError):
            ds.query("t", "INCLUDE", hints=QueryHints(transforms=["nope"]))

    def test_plain_projection_still_works(self):
        ds, *_ = _store()
        out = ds.query("t", "INCLUDE", hints=QueryHints(transforms=["name"]))
        assert list(out.columns) == ["name"]


    def test_typo_identifier_raises(self):
        ds, *_ = _store()
        with pytest.raises(KeyError, match="unknown field"):
            ds.query("t", "IN ('1')",
                     hints=QueryHints(transforms=["x=concat(nmae, '!')"]))

    def test_int_expression_with_nulls_promotes_to_float(self):
        sft = FeatureType.from_spec("m", "a:String,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        ds.write("m", FeatureCollection.from_columns(
            sft, np.arange(2), {"a": np.array(["5", "x"]),
                                "geom": (np.zeros(2), np.zeros(2))}
        ))
        # st_dimension returns ints; rename a mixed-success int parse:
        # use a direct callable check at the collection level instead
        fc = ds.query("m", "INCLUDE")
        out = fc.transform(["d=st_dimension(geom)"])
        assert out.columns["d"].dtype == np.int64  # pure ints stay ints

    def test_secondary_geometry_then_computed_default(self):
        from geomesa_tpu.filter.predicates import PointColumn
        sft = FeatureType.from_spec("g2t", "val:Double,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        ds.write("g2t", FeatureCollection.from_columns(
            sft, np.arange(2), {"val": np.arange(2.0),
                                "geom": (np.ones(2), np.ones(2))}
        ))
        fc = ds.query("g2t", "INCLUDE")
        out = fc.transform(["val", "p=st_centroid(geom)"])
        # the computed geometry is the default geom_field
        assert out.sft.geom_field == "p"
        assert isinstance(out.geom_column, PointColumn)
