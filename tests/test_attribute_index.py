"""Attribute index: lexicode ordering, strategy selection, exactness vs
brute force, secondary spatio-temporal device predicates."""

import numpy as np
import pytest

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.filter import ecql
from geomesa_tpu.utils import lexicode

SPEC = "name:String:index=true,age:Int:index=true,score:Double:index=true,dtg:Date,*geom:Point:srid=4326"


class TestLexicode:
    def test_int_order(self):
        vals = np.array([-(2**62), -5, -1, 0, 1, 7, 2**62])
        codes = lexicode.lex_int(vals)
        assert (codes[:-1] < codes[1:]).all()

    def test_float_order(self):
        vals = np.array([-np.inf, -1e300, -1.5, -0.0, 0.0, 1e-300, 2.5, np.inf])
        codes = lexicode.lex_float(vals)
        assert (codes[:-1] <= codes[1:]).all()

    def test_string_order_weak(self):
        vals = np.array(["", "a", "abcdefgh", "abcdefghZZZ", "b", "zzz"])
        codes = lexicode.lex_string(vals)
        assert (codes[:-1] <= codes[1:]).all()
        # >8-char strings collide onto their prefix (documented)
        a, b = lexicode.lex_string(np.array(["abcdefghXXX", "abcdefghYYY"]))
        assert a == b

    def test_bounds_unbounded(self):
        lo, hi = lexicode.bounds_to_range(None, None, "Int")
        assert lo == 0 and hi == lexicode.U64_MAX


@pytest.fixture(scope="module")
def ds():
    sft = FeatureType.from_spec("t", SPEC)
    ds = DataStore(tile=64)
    ds.create_schema(sft)
    n = 3000
    rng = np.random.default_rng(5)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    fc = FeatureCollection.from_columns(
        sft,
        [str(i) for i in range(n)],
        {
            "name": np.array([f"user_{i % 37:03d}" for i in range(n)]),
            "age": rng.integers(0, 100, n),
            "score": rng.uniform(-10, 10, n),
            "dtg": t0 + rng.integers(0, 30 * 86400_000, n),
            "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)),
        },
    )
    ds.write("t", fc)
    return ds, fc


class TestAttributeIndex:
    def test_indexes_created(self, ds):
        store, _ = ds
        names = {i.name for i in store.indexes("t")}
        assert {"attr_name", "attr_age", "attr_score"} <= names

    def test_equality_picks_attr_index(self, ds):
        store, _ = ds
        plan = store.planner.plan("t", "name = 'user_005'")
        assert plan.index == "attr_name"

    def test_equality_matches_brute_force(self, ds):
        store, fc = ds
        hits = store.query("t", "name = 'user_005'")
        truth = np.asarray(fc.columns["name"]) == "user_005"
        assert sorted(hits.ids.tolist()) == sorted(fc.ids[truth].tolist())

    def test_int_range(self, ds):
        store, fc = ds
        hits = store.query("t", "age >= 90")
        truth = np.asarray(fc.columns["age"]) >= 90
        assert sorted(hits.ids.tolist()) == sorted(fc.ids[truth].tolist())

    def test_float_range_negative(self, ds):
        store, fc = ds
        hits = store.query("t", "score BETWEEN -5.5 AND -1.25")
        s = np.asarray(fc.columns["score"])
        truth = (s >= -5.5) & (s <= -1.25)
        assert sorted(hits.ids.tolist()) == sorted(fc.ids[truth].tolist())

    def test_attr_with_spatiotemporal_secondary(self, ds):
        store, fc = ds
        q = (
            "name = 'user_011' AND bbox(geom, -90, -45, 90, 45) "
            "AND dtg DURING 2024-01-05T00:00:00Z/2024-01-20T00:00:00Z"
        )
        hits = store.query("t", q)
        x = fc.columns["geom"].x
        y = fc.columns["geom"].y
        t = np.asarray(fc.columns["dtg"])
        lo = np.datetime64("2024-01-05T00:00:00", "ms").astype(np.int64)
        hi = np.datetime64("2024-01-20T00:00:00", "ms").astype(np.int64)
        truth = (
            (np.asarray(fc.columns["name"]) == "user_011")
            & (x >= -90) & (x <= 90) & (y >= -45) & (y <= 45)
            & (t >= lo) & (t < hi)
        )
        assert sorted(hits.ids.tolist()) == sorted(fc.ids[truth].tolist())

    def test_in_clause(self, ds):
        store, fc = ds
        hits = store.query("t", "name IN ('user_001', 'user_002')")
        names = np.asarray(fc.columns["name"])
        truth = (names == "user_001") | (names == "user_002")
        assert sorted(hits.ids.tolist()) == sorted(fc.ids[truth].tolist())

    def test_disjoint_attr_filter(self, ds):
        store, _ = ds
        assert len(store.query("t", "age > 50 AND age < 10")) == 0

    def test_cost_prefers_selective_attr_over_z3(self, ds):
        store, _ = ds
        # a tiny attribute range beats a world-spanning z3 scan
        plan = store.planner.plan(
            "t",
            "name = 'user_000' AND bbox(geom, -180, -90, 180, 90) "
            "AND dtg DURING 2024-01-01T00:00:00Z/2024-02-01T00:00:00Z",
        )
        assert plan.index == "attr_name"


class TestLongStringLexicode:
    """Two-word string sort keys (VERDICT r4 weak #4): values sharing an
    8-byte prefix must prune by the secondary word, not scan whole
    collision spans. Reference lexicodes FULL values into row keys
    (AttributeIndexKey.scala:21-70)."""

    def _long_string_store(self, n=4000, n_distinct=80):
        # high-cardinality long strings that ALL share a 12-byte prefix:
        # the u64 primary code is identical for every row
        rng = np.random.default_rng(7)
        distinct = np.array(
            [f"sensor-group-{i:06d}-{rng.integers(1e9):09d}" for i in range(n_distinct)]
        )
        vals = distinct[rng.integers(0, n_distinct, n)]
        sft = FeatureType.from_spec(
            "ls", "tag:String:index=true,*geom:Point:srid=4326"
        )
        ds = DataStore(tile=64)
        ds.create_schema(sft)
        ds.write("ls", FeatureCollection.from_columns(
            sft, np.arange(n),
            {"tag": vals, "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n))},
        ))
        return ds, vals, distinct

    def test_equality_span_proportional_to_selectivity(self):
        ds, vals, distinct = self._long_string_store()
        idx = next(i for i in ds.indexes("ls") if i.name == "attr_tag")
        table = ds.table("ls", "attr_tag")
        want = str(distinct[17])
        cfg = idx.scan_config(ecql.parse(f"tag = '{want}'"))
        spans = table.candidate_spans(cfg)
        rows = sum(hi - lo for lo, hi in spans)
        true_hits = int((vals == want).sum())
        # without the secondary word every row collides (shared prefix)
        # and the span would be the whole table
        assert rows == true_hits, (rows, true_hits)

    def test_range_spans_narrow(self):
        ds, vals, distinct = self._long_string_store()
        idx = next(i for i in ds.indexes("ls") if i.name == "attr_tag")
        table = ds.table("ls", "attr_tag")
        lo, hi = str(distinct[10]), str(distinct[20])
        cfg = idx.scan_config(
            ecql.parse(f"tag >= '{lo}' AND tag <= '{hi}'")
        )
        spans = table.candidate_spans(cfg)
        rows = sum(h - l for l, h in spans)
        true_hits = int(((vals >= lo) & (vals <= hi)).sum())
        assert rows == true_hits, (rows, true_hits)

    def test_query_results_exact_after_mutations(self):
        ds, vals, distinct = self._long_string_store(n=2000, n_distinct=40)
        # delete some rows and write more (compaction path with sub keys)
        ds.delete_features("ls", f"tag = '{distinct[0]}'")
        rng = np.random.default_rng(8)
        extra = distinct[rng.integers(0, 40, 500)]
        from geomesa_tpu.features import FeatureCollection as FC

        sft = ds.get_schema("ls")
        ds.write("ls", FC.from_columns(
            sft, np.arange(10_000, 10_500),
            {"tag": extra,
             "geom": (rng.uniform(-180, 180, 500), rng.uniform(-90, 90, 500))},
        ))
        for want in (distinct[0], distinct[5], distinct[39]):
            out = ds.query("ls", f"tag = '{want}'")
            survivors = int((vals == want).sum()) if want != distinct[0] else 0
            survivors += int((extra == want).sum())
            assert len(out) == survivors, (want, len(out), survivors)

    def test_unicode_long_strings(self):
        rng = np.random.default_rng(9)
        distinct = np.array([f"café-münchen-{i:04d}" for i in range(30)])
        vals = distinct[rng.integers(0, 30, 500)]
        sft = FeatureType.from_spec(
            "us", "tag:String:index=true,*geom:Point:srid=4326"
        )
        ds = DataStore(tile=64)
        ds.create_schema(sft)
        ds.write("us", FeatureCollection.from_columns(
            sft, np.arange(500),
            {"tag": vals,
             "geom": (rng.uniform(-180, 180, 500), rng.uniform(-90, 90, 500))},
        ))
        want = str(distinct[7])
        out = ds.query("us", f"tag = '{want}'")
        assert len(out) == int((vals == want).sum())
