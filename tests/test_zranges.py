"""Range decomposition covering tests: brute-force verification that
zranges always covers every point in the query box (never a false miss),
and that `contained` ranges never include points outside the box.

Modeled on the reference's Z3RangeTest / ZRangeTest
(/root/reference/geomesa-z3/src/test/scala/.../zorder/sfcurve/).
"""

import numpy as np
import pytest

from geomesa_tpu.curve.zorder import Z2, Z3
from geomesa_tpu.curve.zranges import ZBox, merge_ranges, zranges, IndexRange


def brute_force_cover_check(curve, box: ZBox, ranges, dims_range):
    """Every z of a point in the box must fall in some range; every z in a
    `contained` range must decode to a point in the box."""
    grids = np.meshgrid(*[np.arange(lo, hi + 1) for lo, hi in dims_range])
    zs = curve.index(*[g.ravel().astype(np.uint64) for g in grids]).astype(np.int64)
    lo = np.array([r.lower for r in ranges])
    hi = np.array([r.upper for r in ranges])
    # coverage: each z in some [lo, hi]
    idx = np.searchsorted(lo, zs, side="right") - 1
    ok = (idx >= 0) & (zs <= hi[np.clip(idx, 0, len(hi) - 1)])
    assert ok.all(), f"missed {int((~ok).sum())} points of {len(zs)}"


class TestZ2Ranges:
    @pytest.mark.parametrize("seed", range(8))
    def test_random_boxes_covered(self, seed):
        rng = np.random.default_rng(seed)
        x0, x1 = sorted(rng.integers(0, 64, 2).tolist())
        y0, y1 = sorted(rng.integers(0, 64, 2).tolist())
        box = ZBox((x0, y0), (x1, y1))
        ranges = zranges(Z2, [box], max_ranges=2000, max_recurse=32)
        brute_force_cover_check(Z2, box, ranges, [(x0, x1), (y0, y1)])

    def test_contained_ranges_exact(self):
        box = ZBox((0, 0), (15, 15))  # aligned power-of-two box
        ranges = zranges(Z2, [box], max_ranges=2000, max_recurse=32)
        # an aligned 16x16 box is exactly one contained range of 256 cells
        assert len(ranges) == 1
        assert ranges[0].contained
        assert ranges[0].upper - ranges[0].lower + 1 == 256

    def test_contained_flag_correct(self):
        rng = np.random.default_rng(42)
        for _ in range(5):
            x0, x1 = sorted(rng.integers(0, 32, 2).tolist())
            y0, y1 = sorted(rng.integers(0, 32, 2).tolist())
            ranges = zranges(Z2, [ZBox((x0, y0), (x1, y1))], max_ranges=5000, max_recurse=32)
            for r in ranges:
                if r.contained:
                    for z in range(r.lower, r.upper + 1):
                        x, y = Z2.decode(np.uint64(z))
                        assert x0 <= int(x) <= x1 and y0 <= int(y) <= y1

    def test_max_ranges_budget(self):
        # a degenerate thin box produces many ranges; budget must cap them
        box = ZBox((0, 5), ((1 << 31) - 1, 5))
        ranges = zranges(Z2, [box], max_ranges=20)
        assert 0 < len(ranges) <= 20

    def test_multiple_boxes(self):
        b1 = ZBox((0, 0), (7, 7))
        b2 = ZBox((100, 100), (107, 107))
        ranges = zranges(Z2, [b1, b2], max_ranges=2000, max_recurse=32)
        brute_force_cover_check(Z2, b1, ranges, [(0, 7), (0, 7)])
        brute_force_cover_check(Z2, b2, ranges, [(100, 107), (100, 107)])


class TestZ3Ranges:
    @pytest.mark.parametrize("seed", range(4))
    def test_random_boxes_covered(self, seed):
        rng = np.random.default_rng(100 + seed)
        x0, x1 = sorted(rng.integers(0, 16, 2).tolist())
        y0, y1 = sorted(rng.integers(0, 16, 2).tolist())
        t0, t1 = sorted(rng.integers(0, 16, 2).tolist())
        box = ZBox((x0, y0, t0), (x1, y1, t1))
        ranges = zranges(Z3, [box], max_ranges=2000, max_recurse=32)
        brute_force_cover_check(Z3, box, ranges, [(x0, x1), (y0, y1), (t0, t1)])


class TestMergeRanges:
    def test_merge_overlapping(self):
        rs = [IndexRange(0, 10, True), IndexRange(5, 20, True), IndexRange(22, 30, False)]
        merged = merge_ranges(rs)
        assert [(r.lower, r.upper) for r in merged] == [(0, 20), (22, 30)]

    def test_merge_adjacent_same_kind(self):
        rs = [IndexRange(0, 10, False), IndexRange(11, 20, False)]
        merged = merge_ranges(rs)
        assert [(r.lower, r.upper) for r in merged] == [(0, 20)]
        assert not merged[0].contained

    def test_adjacent_mixed_kind_not_merged(self):
        # a contained range keeps its no-refinement guarantee: merging it
        # into an overlapping neighbor would force refinement of its rows
        rs = [IndexRange(0, 10, True), IndexRange(11, 20, False)]
        merged = merge_ranges(rs)
        assert [(r.lower, r.upper, r.contained) for r in merged] == [
            (0, 10, True),
            (11, 20, False),
        ]

    def test_cap_closes_smallest_gaps(self):
        rs = [IndexRange(0, 1, True), IndexRange(5, 6, True), IndexRange(100, 101, True)]
        merged = merge_ranges(rs, max_ranges=2)
        assert len(merged) == 2
        assert (merged[0].lower, merged[0].upper) == (0, 6)
        assert (merged[1].lower, merged[1].upper) == (100, 101)


class TestZdivTightening:
    """zdiv (LITMAX/BIGMIN) is wired into single-box decomposition as an
    endpoint-tightening pass — ranges must still cover, and endpoints of
    every returned range must decode to in-box points."""

    @pytest.mark.parametrize("seed", range(8))
    def test_endpoints_in_box(self, seed):
        rng = np.random.default_rng(200 + seed)
        x0, x1 = sorted(rng.integers(0, 64, 2).tolist())
        y0, y1 = sorted(rng.integers(0, 64, 2).tolist())
        box = ZBox((x0, y0), (x1, y1))
        ranges = zranges(Z2, [box], max_ranges=2000, max_recurse=32)
        brute_force_cover_check(Z2, box, ranges, [(x0, x1), (y0, y1)])
        for r in ranges:
            for z in (r.lower, r.upper):
                x, y = Z2.decode(np.uint64(z))
                assert x0 <= int(x) <= x1, (r, int(x), int(y))
                assert y0 <= int(y) <= y1, (r, int(x), int(y))

    def test_coarse_ranges_tightened(self):
        # with a tiny recursion budget the BFS emits coarse cells; the zdiv
        # pass must still pull endpoints into the box
        box = ZBox((3, 5), (36, 41))
        ranges = zranges(Z2, [box], max_ranges=10, max_recurse=1)
        brute_force_cover_check(Z2, box, ranges, [(3, 36), (5, 41)])
        for r in ranges:
            x, y = Z2.decode(np.uint64(r.lower))
            assert 3 <= int(x) <= 36 and 5 <= int(y) <= 41


class TestRangeQuality:
    """False-positive over-coverage at the DEFAULT recursion budget must stay
    bounded (the reference tunes this via ZN.DefaultRecurse; analogous to the
    range-count expectations in Z3RangeTest)."""

    def test_default_budget_tightness_z2(self):
        # a realistic city-scale bbox at full 31-bit precision
        from geomesa_tpu.curve.z2sfc import Z2SFC
        sfc = Z2SFC()
        ranges = sfc.ranges([(-74.1, 40.6, -73.8, 40.9)])  # default budgets
        assert ranges, "no ranges returned"
        covered = sum(r.upper - r.lower + 1 for r in ranges)
        # exact cell count of the query box
        nx = int(sfc.lon.normalize(-73.8)) - int(sfc.lon.normalize(-74.1)) + 1
        ny = int(sfc.lat.normalize(40.9)) - int(sfc.lat.normalize(40.6)) + 1
        exact = nx * ny
        # allow bounded over-coverage at the default budget
        assert covered >= exact
        assert covered <= exact * 40, f"over-coverage {covered / exact:.1f}x"

    def test_validation_errors(self):
        from geomesa_tpu.curve.z2sfc import Z2SFC
        with pytest.raises(ValueError):
            Z2SFC().ranges([(10.0, 0.0, -10.0, 5.0)])  # inverted x
        with pytest.raises(ValueError):
            zranges(Z2, [ZBox((5, 0), (1, 3))])
        with pytest.raises(ValueError):
            zranges(Z2, [ZBox((0, 0), (1, 1))], max_ranges=0)


class TestMultiBoxTightening:
    def test_multibox_endpoints_in_union(self):
        b1 = ZBox((0, 0), (10, 10))
        b2 = ZBox((40, 40), (50, 50))
        ranges = zranges(Z2, [b1, b2], max_ranges=50, max_recurse=3)
        brute_force_cover_check(Z2, b1, ranges, [(0, 10), (0, 10)])
        brute_force_cover_check(Z2, b2, ranges, [(40, 50), (40, 50)])
        for r in ranges:
            for z in (r.lower, r.upper):
                x, y = int(Z2.decode(np.uint64(z))[0]), int(Z2.decode(np.uint64(z))[1])
                in1 = 0 <= x <= 10 and 0 <= y <= 10
                in2 = 40 <= x <= 50 and 40 <= y <= 50
                assert in1 or in2, (r, x, y)
