"""The data plane over real sockets (geomesa_tpu/serving/http.py,
docs/serving.md "The data plane"): every test round-trips through a
bound listener and the stdlib DataClient — no handler short-circuits.

The contracts pinned here:

- **wire == in-process**: streamed GeoJSON and Arrow IPC responses are
  BIT-IDENTICAL to the one-shot exporters over the same direct query;
- **paging is complete**: sort_by + offset/limit pages union to exactly
  the full result, no duplicates, no gaps;
- **ack == durable**: an ingest 200 on a WAL-backed store survives
  `wal.crash()` (kill -9) + `LambdaStore.recover`;
- **shed is visible**: admission pressure answers 429 + Retry-After
  (never silent queueing), per-tenant quotas isolate a flooding tenant
  from a compliant one, and `/tenants` accounts for both;
- **replicas are honest**: reads honor the max-staleness header (503 +
  Retry-After when unmeasured/stale), writes answer 403 + the leader
  address;
- **auths narrow, never widen**: requested auths beyond the server's
  are 403; a subset masks rows server-side;
- hostile payloads and hostile visibility expressions are counted 400s
  (plus direct parser fuzz), never worker tracebacks.
"""

import json
import threading

import numpy as np
import pytest

from geomesa_tpu import geometry as geo, security
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.io.exporters import _geojson
from geomesa_tpu.metrics import MetricsRegistry
from geomesa_tpu.security import VIS_FIELD_KEY, VisibilityError
from geomesa_tpu.serving import (
    DataClient,
    QueryScheduler,
    ServeError,
    ServingConfig,
)
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.storage import persist
from geomesa_tpu.streaming import (
    LambdaStore,
    PipeTransport,
    ReplicaStore,
    SegmentShipper,
    StreamConfig,
    WalConfig,
)

DAY = 86400_000
T0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
Q = "BBOX(geom, -60, -45, 60, 45)"
SPEC = "name:String:index=true,dtg:Date,*geom:Point:srid=4326"


def _store(n=300, auths=None, spec=SPEC, type_name="t", extra_cols=None,
           user_data=None):
    sft = FeatureType.from_spec(type_name, spec)
    for k, v in (user_data or {}).items():
        sft.user_data[k] = v
    ds = DataStore(tile=64, auths=auths, metrics=MetricsRegistry())
    ds.create_schema(sft)
    rng = np.random.default_rng(11)
    cols = {
        "name": np.array([f"n{i:04d}" for i in range(n)]),
        "dtg": T0 + rng.integers(0, 20 * DAY, n),
        "geom": (rng.uniform(-50, 50, n), rng.uniform(-40, 40, n)),
    }
    cols.update(extra_cols or {})
    ds.write(type_name, FeatureCollection.from_columns(
        sft, [f"f{i}" for i in range(n)], cols,
    ))
    return ds


def _feature(fid, name, x=0.5, y=0.5, dtg=1704067200000, **props):
    props = dict({"name": name, "dtg": dtg}, **props)
    return {
        "type": "Feature", "id": fid,
        "geometry": {"type": "Point", "coordinates": [x, y]},
        "properties": props,
    }


def _payload(*features):
    return {"type": "FeatureCollection", "features": list(features)}


@pytest.fixture(scope="module")
def served():
    """(store, server, client) over one module-lifetime DataStore."""
    ds = _store()
    srv = ds.serve(port=0)
    try:
        yield ds, srv, DataClient(srv.url)
    finally:
        ds.close()


# -- wire formats: bit-identical to the in-process exporters ----------------

class TestWireFormats:
    def test_geojson_bytes_identical_to_export(self, served):
        ds, srv, client = served
        status, hdrs, raw = client.request(
            "GET", "/query/t?cql=" + Q.replace(" ", "%20")
        )
        direct = ds.query("t", Q)
        assert status == 200
        assert hdrs["Content-Type"] == "application/geo+json"
        assert hdrs["X-Geomesa-Rows"] == str(len(direct))
        assert raw == _geojson(direct).encode()

    def test_geojson_identity_across_page_sizes(self, served):
        """Chunk boundaries are a transport detail: any page_rows
        reassembles to the same bytes."""
        ds, srv, client = served
        want = _geojson(ds.query("t", Q)).encode()
        for rows in (1, 7, 100, 100000):
            _, _, raw = client.request(
                "GET",
                f"/query/t?cql={Q.replace(' ', '%20')}&page_rows={rows}",
            )
            assert raw == want, rows

    def test_arrow_bytes_identical_to_stream(self, served):
        from geomesa_tpu.io.arrow import arrow_stream, read_arrow

        ds, srv, client = served
        raw = client.query("t", cql=Q, fmt="arrow", page_rows=64)
        direct = ds.query("t", Q)
        assert raw == arrow_stream(direct, batch_rows=64)
        # and it decodes back to the same collection
        rt = read_arrow(raw, sft=ds.get_schema("t"))
        assert sorted(map(str, rt.ids.tolist())) == sorted(
            map(str, direct.ids.tolist())
        )

    def test_keep_alive_connection_reused(self, served):
        ds, srv, client = served
        with DataClient(srv.url, keep_alive=True) as ka:
            first = ka.query("t", cql=Q, limit=5)
            conn = ka._conn
            assert conn is not None
            for _ in range(3):
                assert ka.query("t", cql=Q, limit=5) == first
                assert ka._conn is conn  # same socket the whole time
            # a dead socket is transparently reopened for GETs
            conn.close()
            assert ka.query("t", cql=Q, limit=5) == first
        assert ka._conn is None  # context exit dropped it

    def test_empty_result_both_formats(self, served):
        ds, srv, client = served
        none = "BBOX(geom, 170, 80, 171, 81)"
        out = client.query("t", cql=none)
        assert out["type"] == "FeatureCollection" and out["features"] == []
        raw = client.query("t", cql=none, fmt="arrow")
        from geomesa_tpu.io.arrow import read_arrow_table

        assert read_arrow_table(raw).num_rows == 0


# -- paging -----------------------------------------------------------------

class TestPaging:
    def test_paged_union_is_complete_and_duplicate_free(self, served):
        ds, srv, client = served
        page = 64
        got = []
        offset = 0
        while True:
            out = client.query(
                "t", cql=Q, sort_by="name", offset=offset, limit=page
            )
            feats = out["features"]
            got.extend(f["id"] for f in feats)
            offset += page
            if len(feats) < page:
                break
        full = ds.query("t", Q)
        assert len(got) == len(set(got)) == len(full)
        assert set(got) == set(map(str, full.ids.tolist()))
        # pages came out in one global sorted order, not per-page order
        names = {str(i): str(v) for i, v in zip(
            full.ids.tolist(), np.asarray(full.columns["name"]).tolist()
        )}
        assert [names[g] for g in got] == sorted(names[g] for g in got)

    def test_limit_caps_rows_and_header(self, served):
        ds, srv, client = served
        status, hdrs, raw = client.request(
            "GET", "/query/t?limit=10"
        )
        assert status == 200 and hdrs["X-Geomesa-Rows"] == "10"
        assert len(json.loads(raw)["features"]) == 10


# -- the error contract -----------------------------------------------------

class TestErrorContract:
    def test_statuses(self, served):
        ds, srv, client = served
        for path, want in (
            ("/query/nope", 404),          # unknown type
            ("/nope", 404),                # unknown path
            ("/query/t?fmt=csv", 400),     # unknown format
            ("/query/t?cql=NOT%20CQL(((", 400),  # ECQL parse error
            ("/query/t?limit=banana", 400),      # bad parameter
        ):
            status, hdrs, raw = client.request("GET", path)
            assert status == want, path
            assert "error" in json.loads(raw), path

    def test_bad_requests_counted_and_worker_survives(self, served):
        ds, srv, client = served
        before = ds.metrics.counters.get("geomesa.serve.badrequest", 0)
        with pytest.raises(ServeError) as e:
            client.query("t", cql="NOT CQL(((")
        assert e.value.status == 400
        assert ds.metrics.counters["geomesa.serve.badrequest"] > before
        assert client.health()["http_status"] == 200  # still serving

    def test_post_requires_length_and_bounds_body(self, served):
        ds, srv, client = served
        status, _, _ = client.request("POST", "/ingest/t")
        assert status == 411
        big = srv.max_body_bytes + 1
        status, _, raw = client.request(
            "POST", "/ingest/t",
            headers={"Content-Length": str(big)},
        )
        assert status == 413 and "bound" in json.loads(raw)["error"]


# -- ops endpoints ride the same port ---------------------------------------

class TestOpsMounted:
    def test_ops_surfaces_on_data_port(self, served):
        ds, srv, client = served
        h = client.health()
        assert h["http_status"] == 200 and h["status"] in (
            "ready", "degraded", "unhealthy"
        )
        assert "geomesa" in client.metrics_text()
        assert client.stats()  # non-empty stats payload
        rep = client.tenants()
        assert {"default_weight", "default_queue_max", "tenants"} <= set(rep)


# -- ingest -----------------------------------------------------------------

class TestIngest:
    def test_cold_store_ingest_roundtrip(self):
        ds = _store(n=10)
        with ds.serve(port=0) as srv:
            client = DataClient(srv.url)
            ack = client.ingest("t", _payload(
                _feature("in-0", "zz-a"), _feature("in-1", "zz-b", x=1.5),
            ))
            assert ack == {"acked": 2, "durable": False, "type": "t"}
            out = client.query("t", cql="name = 'zz-a'")
            assert [f["id"] for f in out["features"]] == ["in-0"]
        assert ds.metrics.counters["geomesa.serve.ingested"] == 2
        ds.close()

    def test_wal_ack_survives_crash_and_recover(self, tmp_path):
        """ack == durable: kill -9 after the 200, recover from disk,
        every acked id is back."""
        ds = _store(n=20)
        root = str(tmp_path / "s")
        persist.save(ds, root)
        lam = LambdaStore(
            ds, "t", config=StreamConfig(chunk_rows=64, fold_rows=4096),
            wal_dir=f"{root}/_wal",
            wal_config=WalConfig(sync="always", sync_interval_ms=1e9),
        )
        srv = lam.serve(port=0)
        client = DataClient(srv.url)
        ack = client.ingest("t", _payload(
            *(_feature(f"d{i}", f"dur-{i}", x=i * 0.01) for i in range(15))
        ))
        assert ack["acked"] == 15 and ack["durable"] is True
        srv.close()
        lam.wal.crash()  # kill -9: no close, no checkpoint
        rec = LambdaStore.recover(root)
        got = set(map(str, rec.query("INCLUDE").ids.tolist()))
        assert {f"d{i}" for i in range(15)} <= got
        lam.flusher.close()
        rec.close()

    def test_hostile_payloads_are_counted_400s(self, served):
        ds, srv, client = served
        before = ds.metrics.counters.get("geomesa.serve.badrequest", 0)
        cases = [
            (b'{"type": "FeatureCollection", "features": [{', "geojson"),
            (b"not json at all", "geojson"),
            (b'{"type": "Polygon"}', "geojson"),  # not a collection
            (b"\xff\xfe\x00garbage-ipc", "arrow"),
        ]
        for body, fmt in cases:
            with pytest.raises(ServeError) as e:
                client.ingest("t", body, fmt=fmt)
            assert e.value.status == 400, body
        assert (
            ds.metrics.counters["geomesa.serve.badrequest"]
            >= before + len(cases)
        )
        assert client.health()["http_status"] == 200  # workers alive

    def test_hostile_visibility_label_rejected_before_storage(self):
        ds = _store(
            n=10, auths=("admin",),
            spec=SPEC + ",vis:String",
            extra_cols={"vis": np.array([""] * 10)},
            user_data={VIS_FIELD_KEY: "vis"},
        )
        with ds.serve(port=0) as srv:
            client = DataClient(srv.url)
            with pytest.raises(ServeError) as e:
                client.ingest("t", _payload(
                    _feature("bad-0", "x", vis="admin & ((((("),
                ))
            assert e.value.status == 400
            assert "isibility" in e.value.body
            out = client.query("t", cql="name = 'x'")
            assert out["features"] == []  # nothing stored
        ds.close()


# -- admission control: shed is visible, tenants are isolated ---------------

class TestAdmission:
    def test_tenant_quota_sheds_429_with_retry_after(self):
        ds = _store(n=50)
        srv = ds.serve(port=0)
        srv.tenants.configure("flood", queue_max=0)
        flood = DataClient(srv.url, tenant="flood")
        calm = DataClient(srv.url, tenant="calm")
        with pytest.raises(ServeError) as e:
            flood.query("t", cql=Q)
        assert e.value.status == 429
        assert e.value.retry_after is not None and e.value.retry_after > 0
        # the compliant tenant is untouched by the flood tenant's quota
        out = calm.query("t", cql=Q, limit=5)
        assert len(out["features"]) == 5
        rep = srv.tenants.report()
        rows = {r["tenant"]: r for r in rep["tenants"]}
        assert rows["flood"]["shed"] >= 1 and rows["flood"]["served"] == 0
        assert rows["calm"]["served"] >= 1 and rows["calm"]["shed"] == 0
        ds.close()

    def test_shared_queue_full_sheds_429_not_silent_queueing(self):
        """A full admission queue answers 429 + Retry-After immediately
        — deterministic via an unstarted scheduler holding one queued
        submission."""
        ds = _store(n=50)
        sched = QueryScheduler(ds, ServingConfig(queue_max=1))
        ds.scheduler = sched  # serve() reuses the attached scheduler
        srv = ds.serve(port=0)
        sched.submit("t", Q, block=False)  # parks: dispatcher never ran
        client = DataClient(srv.url)
        with pytest.raises(ServeError) as e:
            client.query("t", cql=Q)
        assert e.value.status == 429 and e.value.retry_after is not None
        assert "Retry-After" in e.value.headers
        ds.close()

    def test_concurrent_mixed_tenants_all_accounted(self):
        ds = _store(n=100)
        srv = ds.serve(port=0)
        errs = []

        def worker(tenant, n=4):
            c = DataClient(srv.url, tenant=tenant)
            for _ in range(n):
                try:
                    c.query("t", cql=Q, limit=3)
                except Exception as e:  # noqa: BLE001 — collected below
                    errs.append(e)

        ts = [threading.Thread(target=worker, args=(f"w{i}",))
              for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        assert not errs
        rows = {r["tenant"]: r for r in srv.tenants.report()["tenants"]}
        for i in range(4):
            assert rows[f"w{i}"]["served"] == 4
        ds.close()


# -- auths: narrow, never widen ---------------------------------------------

class TestAuths:
    def _vis_store(self):
        n = 40
        return _store(
            n=n, auths=("admin", "user"),
            spec=SPEC + ",vis:String",
            extra_cols={"vis": np.array(["admin", "user"] * (n // 2))},
            user_data={VIS_FIELD_KEY: "vis"},
        )

    def test_subset_auths_mask_rows(self):
        ds = self._vis_store()
        with ds.serve(port=0) as srv:
            full = DataClient(srv.url, auths=("admin", "user"))
            narrow = DataClient(srv.url, auths=("user",))
            all_rows = full.query("t", cql=Q)["features"]
            user_rows = narrow.query("t", cql=Q)["features"]
            assert 0 < len(user_rows) < len(all_rows)
            assert all(
                f["properties"]["vis"] == "user" for f in user_rows
            )
        ds.close()

    def test_auths_beyond_server_403(self):
        ds = self._vis_store()
        with ds.serve(port=0) as srv:
            client = DataClient(srv.url, auths=("secret",))
            for call in (
                lambda: client.query("t", cql=Q),
                lambda: client.ingest("t", _payload(_feature("a", "x"))),
            ):
                with pytest.raises(ServeError) as e:
                    call()
                assert e.value.status == 403
                assert "not held" in e.value.body
        ds.close()


# -- replicas ---------------------------------------------------------------

def _leader(tmp_path, n=30):
    ds = _store(n=n)
    root = str(tmp_path / "s")
    persist.save(ds, root)
    lam = LambdaStore(
        ds, "t", config=StreamConfig(chunk_rows=64, fold_rows=4096),
        wal_dir=f"{root}/_wal",
        wal_config=WalConfig(sync="always", sync_interval_ms=1e9),
    )
    return root, lam


class TestReplica:
    def test_staleness_bound_and_follower_403(self, tmp_path):
        root, lam = _leader(tmp_path)
        a, b = PipeTransport.pair()
        fol = ReplicaStore(
            root, str(tmp_path / "f" / "_wal"), b, type_name="t",
            config=StreamConfig(chunk_rows=64, fold_rows=4096),
        )
        ship = SegmentShipper(lam)
        ship.attach(a)
        srv = fol.serve(port=0, leader_url="http://leader.example:8080")
        client = DataClient(srv.url)
        # unmeasured staleness: a bounded read answers 503 + Retry-After
        with pytest.raises(ServeError) as e:
            client.query("t", cql=Q, max_staleness_ms=1000)
        assert e.value.status == 503
        assert e.value.retry_after is not None
        # an unbounded read serves whatever the replica has
        assert len(client.query("t", cql=Q)["features"]) > 0
        # replicate a write, then the bounded read succeeds and sees it
        lam.write(
            [{"name": "repl-new", "dtg": int(T0), "geom": geo.Point(1.0, 1.0)}],
            ids=["r-new"],
        )
        ship.pump()
        fol.drain()
        out = client.query("t", cql="name = 'repl-new'",
                           max_staleness_ms=60_000)
        assert [f["id"] for f in out["features"]] == ["r-new"]
        # writes are refused with the leader's address
        with pytest.raises(ServeError) as e:
            client.ingest("t", _payload(_feature("w", "x")))
        assert e.value.status == 403
        assert e.value.headers.get("X-Geomesa-Leader") == (
            "http://leader.example:8080"
        )
        srv.close()
        fol.close()
        lam.close()

    def test_disk_tail_replica_measures_staleness(self, tmp_path):
        """The CLI topology: no live transport, just tail_disk() over
        the leader's WAL directory."""
        root, lam = _leader(tmp_path)
        lam.write(
            [{"name": "tailed", "dtg": int(T0), "geom": geo.Point(2.0, 2.0)}],
            ids=["t-new"],
        )

        class _NoTransport:
            def send(self, msg):
                pass

            def recv(self, timeout=0.0):
                return None

            def close(self):
                pass

        fol = ReplicaStore(
            root, str(tmp_path / "f2" / "_wal"), _NoTransport(),
            type_name="t",
            config=StreamConfig(chunk_rows=64, fold_rows=4096),
        )
        applied = fol.tail_disk(f"{root}/_wal")
        assert applied >= 1 and fol.staleness_ms() is not None
        with fol.serve(port=0) as srv:
            out = DataClient(srv.url).query(
                "t", cql="name = 'tailed'", max_staleness_ms=60_000
            )
            assert [f["id"] for f in out["features"]] == ["t-new"]
        fol.close()
        lam.close()


# -- the CLI ----------------------------------------------------------------

class TestCli:
    def test_serve_command_smoke(self, tmp_path, capsys):
        from geomesa_tpu.cli import build_parser, cmd_serve

        ds = _store(n=15)
        root = str(tmp_path / "cat")
        persist.save(ds, root)
        ds.close()
        args = build_parser().parse_args(["serve", "-c", root, "--port", "0"])
        srv = cmd_serve(args, hold=False)
        try:
            assert f"at {srv.url}" in capsys.readouterr().out
            out = DataClient(srv.url).query("t", cql=Q)
            assert len(out["features"]) == 15
        finally:
            srv.store.close()

    def test_serve_replica_command_smoke(self, tmp_path, capsys):
        from geomesa_tpu.cli import build_parser, cmd_serve

        root, lam = _leader(tmp_path, n=12)
        args = build_parser().parse_args([
            "serve", "-c", root, "-f", "t", "--port", "0",
            "--replica-of", f"{root}/_wal",
            "--replica-wal", str(tmp_path / "rw"),
            "--leader-url", "http://leader:1",
        ])
        srv = cmd_serve(args, hold=False)
        try:
            client = DataClient(srv.url)
            assert len(client.query("t", cql=Q)["features"]) == 12
            with pytest.raises(ServeError) as e:
                client.ingest("t", _payload(_feature("w", "x")))
            assert e.value.status == 403
            assert e.value.headers.get("X-Geomesa-Leader") == "http://leader:1"
        finally:
            srv.store.close()
            lam.close()


# -- the visibility parser under fire (security.py hardening) ---------------

class TestVisibilityFuzz:
    def test_random_garbage_raises_only_visibility_error(self):
        rng = np.random.default_rng(3)
        alphabet = list("abcXYZ01&|()!~ \t\"'\\,;%$#@在界") + ["&&", "||"]
        for _ in range(300):
            expr = "".join(
                rng.choice(alphabet)
                for _ in range(int(rng.integers(0, 40)))
            )
            try:
                security.validate(expr)
                security.visible(expr, frozenset({"a"}))
            except VisibilityError:
                pass  # the only acceptable failure

    def test_valid_expressions_still_pass(self):
        for expr, auths, want in (
            ("", frozenset(), True),
            ("a", {"a"}, True),
            ("a&b", {"a", "b"}, True),
            ("a&b", {"a"}, False),
            ("(a|b)&c", {"b", "c"}, True),
            ("((a))", {"a"}, True),
        ):
            security.validate(expr)
            assert security.visible(expr, frozenset(auths)) is want, expr

    def test_length_and_depth_bombs_bounded(self):
        too_long = "a&" * (security.MAX_EXPRESSION_LENGTH // 2) + "a&a"
        with pytest.raises(VisibilityError, match="chars"):
            security.validate(too_long)
        bomb = "(" * (security.MAX_EXPRESSION_DEPTH + 8) + "a" + ")" * (
            security.MAX_EXPRESSION_DEPTH + 8
        )
        with pytest.raises(VisibilityError):
            security.validate(bomb)
        # at-the-limit inputs parse fine (the bound is not off by a mile)
        ok_depth = "(" * 8 + "a" + ")" * 8
        security.validate(ok_depth)

    def test_mask_over_hostile_object_column(self):
        labels = np.array(
            ["a", "", None, "a&zzz", "a|b"], dtype=object
        )
        m = security.visibility_mask(labels, frozenset({"a"}))
        assert m.tolist() == [True, True, True, False, True]


# -- live map tiles over the wire (docs/tiles.md) ----------------------------

class TestTiles:
    """`GET /tiles/<type>/<kind>/{z}/{x}/{y}`: PNG/Arrow payloads,
    generation-derived ETags, 304 revalidation with zero aggregation
    work, and scoped invalidation observable over the socket."""

    def _tile_store(self, n=400):
        from geomesa_tpu.cache import CacheConfig

        sft = FeatureType.from_spec("t", SPEC)
        ds = DataStore(
            tile=64, metrics=MetricsRegistry(),
            cache=CacheConfig(max_bytes=1 << 22),
        )
        ds.create_schema(sft)
        rng = np.random.default_rng(21)
        ds.write("t", FeatureCollection.from_columns(
            sft, [f"f{i}" for i in range(n)],
            {"name": np.array([f"n{i}" for i in range(n)]),
             "dtg": T0 + rng.integers(0, 20 * DAY, n),
             "geom": (rng.uniform(-170, 170, n), rng.uniform(-80, 80, n))},
        ))
        return ds

    def _agg_work(self, ds):
        """Counter snapshot of every code path that aggregates or
        composes — the 304 path must move NONE of them."""
        return tuple(
            ds.metrics.counter_value(n) for n in (
                "geomesa.tiles.compose", "geomesa.tiles.leaf.scan",
                "geomesa.tiles.fresh",
            )
        )

    def test_png_etag_304_roundtrip(self):
        ds = self._tile_store()
        with ds.serve(port=0) as srv:
            c = DataClient(srv.url)
            st, h, body = c.tile("t", "density", 1, 1, 0)
            assert st == 200
            assert h["Content-Type"] == "image/png"
            assert body[:8] == b"\x89PNG\r\n\x1a\n"
            assert h["Cache-Control"] == "no-cache"
            etag = h["ETag"]
            assert etag.startswith('"t') and etag.endswith('"')
            # revalidation: 304, empty body, same etag, NO aggregation
            # or render work, counted by geomesa.tiles.not_modified
            work0 = self._agg_work(ds)
            nm0 = ds.metrics.counter_value("geomesa.tiles.not_modified")
            st2, h2, b2 = c.tile("t", "density", 1, 1, 0, etag=etag)
            assert (st2, b2) == (304, b"")
            assert h2["ETag"] == etag
            assert self._agg_work(ds) == work0
            assert ds.metrics.counter_value(
                "geomesa.tiles.not_modified"
            ) == nm0 + 1
            # a stale etag re-serves the body
            st3, h3, b3 = c.tile("t", "density", 1, 1, 0, etag='"t999"')
            assert st3 == 200 and b3 == body
        ds.close()

    def test_warm_bit_identical_to_fresh_mode(self):
        pytest.importorskip("pyarrow")
        ds = self._tile_store()
        with ds.serve(port=0) as srv:
            c = DataClient(srv.url)
            for z, x, y in ((0, 0, 0), (1, 3, 1), (2, 5, 2), (3, 11, 4)):
                _st, _h, warm = c.tile("t", "count", z, x, y, fmt="arrow")
                _st, _h, oracle = c.tile(
                    "t", "count", z, x, y, fmt="arrow", mode="fresh"
                )
                assert warm == oracle, (z, x, y)
        ds.close()

    def test_arrow_grid_decodes(self):
        pa = pytest.importorskip("pyarrow")
        ds = self._tile_store(n=100)
        with ds.serve(port=0) as srv:
            c = DataClient(srv.url)
            _st, _h, data = c.tile("t", "count", 0, 0, 0, fmt="arrow")
            table = pa.ipc.open_stream(data).read_all()
            meta = table.schema.metadata
            h_, w_ = int(meta[b"rows"]), int(meta[b"cols"])
            grid = np.asarray(table["count"]).reshape(h_, w_)
            assert grid.shape == (256, 256)
            # the wire grid IS the pyramid grid
            assert np.array_equal(grid, srv.tiles.fetch("t", 0, 0, 0).grid)
        ds.close()

    def test_ingest_invalidates_scoped_over_http(self):
        ds = self._tile_store()
        with ds.serve(port=0) as srv:
            c = DataClient(srv.url)
            z = srv.tiles.lattice.leaf_zoom
            # two leaf tiles far apart: one will be written into
            _st, th, _b = c.tile("t", "density", z, 8, 3)   # near (8, 8)
            _st, fh, _b = c.tile("t", "density", z, 0, 0)   # far west
            ack = c.ingest("t", _payload(_feature("new-0", "x", 8.0, 8.0)))
            assert ack["acked"] == 1
            # touched tile: the old etag misses and a NEW etag arrives
            st, h2, _b = c.tile("t", "density", z, 8, 3, etag=th["ETag"])
            assert st == 200 and h2["ETag"] != th["ETag"]
            # far tile: still 304 off its old etag (stayed warm)
            st, _h, _b = c.tile("t", "density", z, 0, 0, etag=fh["ETag"])
            assert st == 304
        ds.close()

    def test_error_statuses(self):
        ds = self._tile_store(n=20)
        with ds.serve(port=0) as srv:
            c = DataClient(srv.url)
            for args, kwargs, want in (
                (("t", "viridis", 0, 0, 0), {}, 400),       # unknown kind
                (("t", "density", 9, 0, 0), {}, 400),       # beyond leaf zoom
                (("t", "density", 0, 5, 0), {}, 400),       # x out of range
                (("zz", "density", 0, 0, 0), {}, 404),      # unknown type
                (("t", "density", 0, 0, 0), {"fmt": "bmp"}, 400),
            ):
                with pytest.raises(ServeError) as ei:
                    c.tile(*args, **kwargs)
                assert ei.value.status == want, (args, kwargs)
            # malformed path shape: 404, counted, no traceback
            status, _h, _b = c.request("GET", "/tiles/t/density/1/2")
            assert status == 404
        ds.close()

    def test_visibility_labeled_schema_narrowed_auths_403(self):
        from geomesa_tpu.cache import CacheConfig

        sft = FeatureType.from_spec("t", SPEC + ",vis:String")
        sft.user_data[VIS_FIELD_KEY] = "vis"
        ds = DataStore(
            tile=64, auths=("admin", "user"), metrics=MetricsRegistry(),
            cache=CacheConfig(max_bytes=1 << 22),
        )
        ds.create_schema(sft)
        ds.write("t", FeatureCollection.from_columns(
            sft, ["a", "b"],
            {"name": np.array(["x", "y"]),
             "dtg": np.full(2, T0, dtype=np.int64),
             "geom": (np.array([1.0, 2.0]), np.array([1.0, 2.0])),
             "vis": np.array(["admin", "user"])},
        ))
        with ds.serve(port=0) as srv:
            c = DataClient(srv.url)
            # un-narrowed: tiles serve (the process's full view)
            st, _h, _b = c.tile("t", "density", 0, 0, 0)
            assert st == 200
            # narrowed auths cannot read whole-store densities
            with pytest.raises(ServeError) as ei:
                c.tile("t", "density", 0, 0, 0, auths=("user",))
            assert ei.value.status == 403
        ds.close()

    def test_tile_latency_histogram_records(self):
        ds = self._tile_store(n=50)
        with ds.serve(port=0) as srv:
            c = DataClient(srv.url)
            c.tile("t", "heat", 1, 0, 0)
            st, h, _b = c.tile("t", "heat", 1, 0, 0)
            c.tile("t", "heat", 1, 0, 0, etag=h["ETag"])
            text = c.metrics_text()
            assert "geomesa_tiles_fetch_seconds_bucket" in text
            assert "geomesa_tiles_served 2" in text
        ds.close()
