"""Model-based mutation fuzz: random write/upsert/modify/delete/age_off
sequences on a DataStore, cross-checked after every op against a plain
dict-of-rows reference model (the update-surface analogue of the query
fuzz in test_fuzz_queries)."""

import numpy as np
import pytest

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu import geometry as geo

T0 = 1704067200000  # 2024-01-01
DAY = 86_400_000
SPEC = "name:String,age:Int,dtg:Date,*geom:Point:srid=4326"


def _batch(sft, rng, ids):
    n = len(ids)
    return FeatureCollection.from_columns(
        sft, ids,
        {"name": np.array([f"n{rng.integers(0, 6)}" for _ in range(n)],
                          dtype=object),
         "age": rng.integers(0, 100, n),
         "dtg": T0 + rng.integers(0, 60 * DAY, n),
         "geom": (rng.uniform(-170, 170, n), rng.uniform(-85, 85, n))},
    )


def _model_rows(fc):
    out = {}
    x, y = np.asarray(fc.geom_column.x), np.asarray(fc.geom_column.y)
    for i, fid in enumerate(np.asarray(fc.ids).tolist()):
        out[str(fid)] = {
            "name": fc.columns["name"][i],
            "age": int(np.asarray(fc.columns["age"])[i]),
            "dtg": int(np.asarray(fc.columns["dtg"])[i]),
            "x": float(x[i]), "y": float(y[i]),
        }
    return out


def _check(ds, model, rng):
    """Random queries against the model after a mutation."""
    # full count
    assert ds.count("m") == len(model)
    for _ in range(3):
        # boxes stay inside [-180, 180]: wrap semantics are pinned
        # elsewhere (test_datastore), and the flat model here doesn't wrap
        x0 = float(rng.uniform(-180, 100))
        y0 = float(rng.uniform(-90, 50))
        w = float(rng.uniform(5, min(80.0, 180.0 - x0)))
        t_lo = T0 + int(rng.integers(0, 40 * DAY))
        t_hi = t_lo + int(rng.integers(DAY, 30 * DAY))
        q = (f"bbox(geom, {x0}, {y0}, {x0 + w}, {y0 + w}) AND dtg DURING "
             f"{np.datetime64(t_lo, 'ms')}Z/{np.datetime64(t_hi, 'ms')}Z")
        got = sorted(np.asarray(ds.query("m", q).ids).tolist())
        want = sorted(
            fid for fid, r in model.items()
            if x0 <= r["x"] <= x0 + w and y0 <= r["y"] <= y0 + w
            and t_lo <= r["dtg"] <= t_hi
        )
        assert got == want, f"query mismatch after mutation: {q}"
    # attribute query
    name = f"n{rng.integers(0, 6)}"
    got = sorted(np.asarray(ds.query("m", f"name = '{name}'").ids).tolist())
    want = sorted(fid for fid, r in model.items() if r["name"] == name)
    assert got == want


@pytest.mark.parametrize("indices", [None, "s3"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_mutation_sequences(seed, indices):
    rng = np.random.default_rng(seed)
    sft = FeatureType.from_spec("m", SPEC)
    if indices:  # pin the S3 store end-to-end (VERDICT r4 weak #5)
        sft.user_data["geomesa.indices.enabled"] = indices
    ds = DataStore()
    ds.create_schema(sft)
    model: dict = {}
    next_id = 0

    for step in range(12):
        op = rng.choice(["write", "upsert", "modify", "delete"])
        if op == "write" or not model:
            n = int(rng.integers(50, 400))
            ids = [str(next_id + i) for i in range(n)]
            next_id += n
            fc = _batch(sft, rng, ids)
            ds.write("m", fc)
            model.update(_model_rows(fc))
        elif op == "upsert":
            # replace a random existing subset + some fresh ids
            existing = list(model)
            k = int(rng.integers(1, min(80, len(existing)) + 1))
            chosen = list(rng.choice(existing, k, replace=False))
            fresh = [str(next_id + i) for i in range(int(rng.integers(0, 20)))]
            next_id += len(fresh)
            fc = _batch(sft, rng, chosen + fresh)
            ds.upsert("m", fc)
            model.update(_model_rows(fc))
        elif op == "modify":
            name = f"n{rng.integers(0, 6)}"
            new_age = int(rng.integers(200, 300))
            px, py = float(rng.uniform(-170, 170)), float(rng.uniform(-85, 85))
            moved = ds.modify_features(
                "m", {"age": new_age, "geom": geo.Point(px, py)},
                f"name = '{name}'",
            )
            want = [fid for fid, r in model.items() if r["name"] == name]
            assert moved == len(want)
            for fid in want:
                model[fid].update({"age": new_age, "x": px, "y": py})
        else:  # delete
            cutoff = int(rng.integers(150, 250))
            removed = ds.delete_features("m", f"age > {cutoff}")
            want = [fid for fid, r in model.items() if r["age"] > cutoff]
            assert removed == len(want)
            for fid in want:
                del model[fid]
        _check(ds, model, rng)


@pytest.mark.parametrize("indices", ["xz2", "xz3"])
@pytest.mark.parametrize("seed", [0, 1])
def test_extent_mutation_sequences(seed, indices):
    """The same model-based check over an XZ2/XZ3 polygon store: writes,
    geometry-moving modifies, and deletes keep index results exact
    (xz3 adds a time attribute so re-keying crosses time bins too)."""
    rng = np.random.default_rng(100 + seed)
    sft = FeatureType.from_spec("me", "tag:String,dtg:Date,*geom:Polygon:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = indices
    ds = DataStore()
    ds.create_schema(sft)
    model: dict = {}  # id -> (tag, (x0, y0, x1, y1))
    next_id = 0

    def rects(n):
        x0 = rng.uniform(-170, 165, n)
        y0 = rng.uniform(-85, 80, n)
        w = rng.uniform(0.01, 2.0, n)
        h = rng.uniform(0.01, 2.0, n)
        return x0, y0, x0 + w, y0 + h

    def batch(ids):
        n = len(ids)
        x0, y0, x1, y1 = rects(n)
        col = geo.PackedGeometryColumn.from_boxes(x0, y0, x1, y1)
        tags = np.array([f"t{rng.integers(0, 4)}" for _ in range(n)], dtype=object)
        fc = FeatureCollection.from_columns(
            sft, ids,
            {"tag": tags, "dtg": T0 + rng.integers(0, 60 * DAY, n), "geom": col},
        )
        rows = {
            str(fid): (tags[i], (x0[i], y0[i], x1[i], y1[i]))
            for i, fid in enumerate(ids)
        }
        return fc, rows

    def check():
        assert ds.count("me") == len(model)
        for _ in range(3):
            qx = float(rng.uniform(-170, 120))
            qy = float(rng.uniform(-85, 40))
            w = float(rng.uniform(2, 40))
            q = f"bbox(geom, {qx}, {qy}, {qx + w}, {qy + w})"
            got = sorted(np.asarray(ds.query("me", q).ids).tolist())
            want = sorted(
                fid for fid, (_, (x0, y0, x1, y1)) in model.items()
                if x0 <= qx + w and x1 >= qx and y0 <= qy + w and y1 >= qy
            )
            assert got == want, q

    for step in range(8):
        op = rng.choice(["write", "modify", "delete"])
        if op == "write" or not model:
            n = int(rng.integers(100, 600))
            ids = [str(next_id + i) for i in range(n)]
            next_id += n
            fc, rows = batch(ids)
            ds.write("me", fc)
            model.update(rows)
        elif op == "modify":
            tag = f"t{rng.integers(0, 4)}"
            # random destination cell so XZ2 re-keying is exercised at
            # varying resolutions/signs, like the point-store fuzz
            dx0, dy0, dx1, dy1 = (float(v[0]) for v in rects(1))
            updates = {"geom": geo.box(dx0, dy0, dx1, dy1)}
            new_dtg = None
            if rng.uniform() < 0.5:  # cross TIME bins too (xz3 re-keying)
                new_dtg = int(T0 + rng.integers(0, 60 * DAY))
                updates["dtg"] = new_dtg
            moved = ds.modify_features("me", updates, f"tag = '{tag}'")
            want = [fid for fid, (t, _) in model.items() if t == tag]
            assert moved == len(want)
            for fid in want:
                model[fid] = (tag, (dx0, dy0, dx1, dy1))
        else:
            tag = f"t{rng.integers(0, 4)}"
            removed = ds.delete_features("me", f"tag = '{tag}'")
            want = [fid for fid, (t, _) in model.items() if t == tag]
            assert removed == len(want)
            for fid in want:
                del model[fid]
        check()


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_cached_store_matches_uncached_oracle(seed):
    """Cache-tier invalidation fuzz (ISSUE 2 satellite): a cache-enabled
    store and an uncached oracle receive IDENTICAL random mutation
    sequences; every query runs twice on the cached store (the second
    answer may come from cache) and must match the oracle row-for-row —
    zero stale results across write/query interleavings."""
    from geomesa_tpu.metrics import MetricsRegistry

    rng = np.random.default_rng(300 + seed)
    reg = MetricsRegistry()
    stores = []
    for cache in (True, False):
        sft = FeatureType.from_spec("m", SPEC)
        ds = DataStore(metrics=reg if cache else None, cache=cache)
        ds.create_schema(sft)
        stores.append(ds)
    cached, oracle = stores
    next_id = 0

    def check_queries():
        nonlocal rng
        for _ in range(3):
            x0 = float(rng.uniform(-180, 100))
            y0 = float(rng.uniform(-90, 50))
            w = float(rng.uniform(5, min(80.0, 180.0 - x0)))
            t_lo = T0 + int(rng.integers(0, 40 * DAY))
            t_hi = t_lo + int(rng.integers(DAY, 30 * DAY))
            qs = [
                f"bbox(geom, {x0}, {y0}, {x0 + w}, {y0 + w})",
                (f"bbox(geom, {x0}, {y0}, {x0 + w}, {y0 + w}) AND dtg "
                 f"DURING {np.datetime64(t_lo, 'ms')}Z/"
                 f"{np.datetime64(t_hi, 'ms')}Z"),
            ]
            for q in qs:
                want = oracle.query("m", q)
                wi = np.argsort(np.asarray(want.ids).astype(str))
                for _ in range(2):  # second pass may serve from cache
                    got = cached.query("m", q)
                    gi = np.argsort(np.asarray(got.ids).astype(str))
                    assert np.array_equal(
                        np.asarray(got.ids)[gi], np.asarray(want.ids)[wi]
                    ), f"stale ids after mutation: {q}"
                    # column BYTES too, not just membership (a stale
                    # cached entry can differ in values under same ids)
                    for col in ("name", "age", "dtg"):
                        assert np.array_equal(
                            np.asarray(got.columns[col])[gi],
                            np.asarray(want.columns[col])[wi],
                        ), f"stale column {col}: {q}"
            # the tile-aggregate path: exact count vs the oracle
            assert cached.count("m", qs[0]) == len(oracle.query("m", qs[0]))

    model_ids: list = []
    for step in range(10):
        op = rng.choice(["write", "upsert", "modify", "delete"])
        if op == "write" or not model_ids:
            n = int(rng.integers(50, 300))
            ids = [str(next_id + i) for i in range(n)]
            next_id += n
            sft = cached.get_schema("m")
            fc = _batch(sft, rng, ids)
            cached.write("m", fc)
            oracle.write("m", fc)
            model_ids.extend(ids)
        elif op == "upsert":
            k = int(rng.integers(1, min(60, len(model_ids)) + 1))
            chosen = list(rng.choice(model_ids, k, replace=False))
            fc = _batch(cached.get_schema("m"), rng, chosen)
            cached.upsert("m", fc)
            oracle.upsert("m", fc)
        elif op == "modify":
            name = f"n{rng.integers(0, 6)}"
            new_age = int(rng.integers(200, 300))
            px = float(rng.uniform(-170, 170))
            py = float(rng.uniform(-85, 85))
            updates = {"age": new_age, "geom": geo.Point(px, py)}
            a = cached.modify_features("m", updates, f"name = '{name}'")
            b = oracle.modify_features("m", updates, f"name = '{name}'")
            assert a == b
        else:
            cutoff = int(rng.integers(150, 250))
            a = cached.delete_features("m", f"age > {cutoff}")
            b = oracle.delete_features("m", f"age > {cutoff}")
            assert a == b
            if a:
                model_ids = sorted(
                    np.asarray(cached.features("m").ids).astype(str).tolist()
                )
        check_queries()
    # the fuzz exercised the cache, not an always-miss degenerate path
    assert reg.counters["geomesa.cache.hit"] > 0
