"""Catalog tiers added in round 4: typed system properties (conf),
metadata KV backends, and the IndexAdapter SPI seam."""

import numpy as np
import pytest

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu.conf import COMPACT_MIN_ROWS, SCAN_RANGES_TARGET
from geomesa_tpu.storage.metadata import CachedMetadata, FileMetadata, InMemoryMetadata


class TestSystemProperties:
    def test_default_and_override(self):
        assert SCAN_RANGES_TARGET.get() == 2000
        SCAN_RANGES_TARGET.set(500)
        try:
            assert SCAN_RANGES_TARGET.get() == 500
        finally:
            SCAN_RANGES_TARGET.clear()
        assert SCAN_RANGES_TARGET.get() == 2000

    def test_env_resolution(self, monkeypatch):
        monkeypatch.setenv(COMPACT_MIN_ROWS.env_key, "1024")
        assert COMPACT_MIN_ROWS.get() == 1024

    def test_bad_env_falls_back(self, monkeypatch):
        monkeypatch.setenv(COMPACT_MIN_ROWS.env_key, "not-a-number")
        assert COMPACT_MIN_ROWS.get() == COMPACT_MIN_ROWS.default

    def test_ranges_budget_applies(self):
        from geomesa_tpu.curve.z2sfc import Z2SFC

        wide = [(-170.0, -80.0, 170.0, 80.0)]
        many = Z2SFC().ranges(wide)
        SCAN_RANGES_TARGET.set(16)
        try:
            few = Z2SFC().ranges(wide)
        finally:
            SCAN_RANGES_TARGET.clear()
        assert len(few) <= 16 < len(many) + 1


class TestMetadata:
    def _exercise(self, md):
        assert md.get("t~schema") is None
        md.insert("t~schema", "a:Int,*geom:Point:srid=4326")
        md.insert("t~user_data", "{}")
        md.insert("u~schema", "other")
        assert md.get("t~schema").startswith("a:Int")
        assert dict(md.scan("t~")) == {
            "t~schema": "a:Int,*geom:Point:srid=4326", "t~user_data": "{}",
        }
        md.remove("t~schema")
        assert md.get("t~schema") is None
        assert md.get("u~schema") == "other"

    def test_in_memory(self):
        self._exercise(InMemoryMetadata())

    def test_file_backed(self, tmp_path):
        self._exercise(FileMetadata(str(tmp_path / "md")))

    def test_file_rejects_traversal(self, tmp_path):
        md = FileMetadata(str(tmp_path / "md"))
        with pytest.raises(ValueError):
            md.insert("../evil", "x")

    def test_cached_invalidation(self, tmp_path):
        backend = FileMetadata(str(tmp_path / "md"))
        md = CachedMetadata(backend)
        md.insert("k", "v1")
        backend.insert("k", "v2")  # external change: cache is stale
        assert md.get("k") == "v1"
        md.invalidate()
        assert md.get("k") == "v2"


def _store(**kw):
    sft = FeatureType.from_spec("c", "name:String,dtg:Date,*geom:Point:srid=4326")
    ds = DataStore(**kw)
    ds.create_schema(sft)
    rng = np.random.default_rng(2)
    n = 1500
    t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
    fc = FeatureCollection.from_columns(
        sft, [str(i) for i in range(n)],
        {"name": np.array([f"n{i % 5}" for i in range(n)]),
         "dtg": t0 + rng.integers(0, 86400_000 * 10, n),
         "geom": (rng.uniform(-40, 40, n), rng.uniform(-30, 30, n))},
    )
    ds.write("c", fc)
    return ds


class TestAdapterSeam:
    def test_store_catalog_entries(self):
        ds = _store()
        assert "geom:Point" in ds.metadata.get("c~schema")
        assert "z3" in ds.metadata.get("c~indices")
        ds.delete_schema("c")
        assert ds.metadata.get("c~schema") is None

    def test_custom_adapter_is_used(self):
        from geomesa_tpu.storage.adapter import InProcessAdapter

        calls = {"create": 0, "delete": 0}

        class CountingAdapter(InProcessAdapter):
            def create_table(self, keyspace, keys, old=None, main_rows=0):
                calls["create"] += 1
                return super().create_table(keyspace, keys, old=old, main_rows=main_rows)

            def delete_table(self, table):
                calls["delete"] += 1

        ds = _store(adapter=CountingAdapter())
        assert calls["create"] >= 1
        n_before = calls["create"]
        out = ds.query("c", "bbox(geom, -10, -10, 10, 10)")
        assert len(out) > 0
        ds.delete_schema("c")
        assert calls["delete"] >= n_before  # every table released

    def test_concurrent_writes_serialized(self):
        import threading

        sft = FeatureType.from_spec("w", "dtg:Date,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        rng = np.random.default_rng(0)
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)

        def batch(tag):
            n = 400
            return FeatureCollection.from_columns(
                sft, [f"{tag}{i}" for i in range(n)],
                {"dtg": t0 + rng.integers(0, 86400_000, n),
                 "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n))},
            )

        batches = [batch(t) for t in "abcdefgh"]
        threads = [
            threading.Thread(target=ds.write, args=("w", b)) for b in batches
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert ds.count("w") == 8 * 400
        ds.compact("w")
        assert ds.count("w") == 8 * 400


class TestConcurrentReadWrite:
    def test_readers_during_writes(self):
        """Queries racing appends must never error and always see a
        consistent snapshot (row counts monotonically between the
        pre-write and post-write totals; ids unique)."""
        import threading

        sft = FeatureType.from_spec("rw", "v:Integer,*geom:Point:srid=4326")
        ds = DataStore(tile=64)
        ds.create_schema(sft)
        rng = np.random.default_rng(3)
        base = 2000
        ds.write("rw", FeatureCollection.from_columns(
            sft, np.arange(base),
            {"v": np.arange(base),
             "geom": (rng.uniform(-10, 10, base), rng.uniform(-10, 10, base))},
        ), check_ids=False)

        errors: list = []
        counts: list = []
        stop = threading.Event()

        def reader():
            try:
                while not stop.is_set():
                    out = ds.query("rw", "bbox(geom, -10, -10, 10, 10)")
                    ids = np.asarray(out.ids)
                    assert len(np.unique(ids)) == len(ids)
                    counts.append(len(out))
            except Exception as e:  # pragma: no cover - failure reporting
                errors.append(e)

        readers = [threading.Thread(target=reader) for _ in range(3)]
        for r in readers:
            r.start()
        n_batches, per = 6, 500
        for b in range(n_batches):
            start = base + b * per
            ds.write("rw", FeatureCollection.from_columns(
                sft, np.arange(start, start + per),
                {"v": np.arange(start, start + per),
                 "geom": (rng.uniform(-10, 10, per), rng.uniform(-10, 10, per))},
            ), check_ids=False)
        stop.set()
        for r in readers:
            r.join(timeout=30)
        assert not errors, errors[:3]
        total = base + n_batches * per
        assert len(ds.query("rw", "INCLUDE")) == total
        assert counts and all(base <= c <= total for c in counts)
