"""Differential fuzz: random filters through the full planner/kernel
pipeline must match brute-force evaluation over all rows.

The reference pins planner correctness with per-case unit tests; here a
seeded random sweep across filter shapes (bbox/intersects/time/attribute,
AND/OR/NOT nesting) catches edge interactions the hand-written cases
miss (empty ranges, degenerate boxes, antimeridian-adjacent windows,
mixed-kind ORs that fall to union plans or full scans)."""

import numpy as np
import pytest

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType

DAY = 86400_000
N = 4000

def _wrap_lon_mask(x, qx, x1):
    """Wrap-aware longitude truth (GeoTools BBOX semantics, matching the
    planner's normalize_antimeridian rewrite) — shared by every fuzz
    class so the truth logic cannot drift per call site."""
    if x1 - qx >= 360.0:
        return np.ones(len(x), dtype=bool)
    if x1 > 180.0:
        return (x >= qx) | (x <= x1 - 360.0)
    return (x >= qx) & (x <= x1)


@pytest.fixture(scope="module")
def world():
    rng = np.random.default_rng(99)
    sft = FeatureType.from_spec(
        "w", "kind:String:index=true,score:Double,dtg:Date,*geom:Point:srid=4326"
    )
    ds = DataStore(tile=64)
    ds.create_schema(sft)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    x = rng.uniform(-180, 180, N)
    y = rng.uniform(-90, 90, N)
    t = t0 + rng.integers(0, 30 * DAY, N)
    kind = np.array(["a", "b", "c", "d"])[rng.integers(0, 4, N)]
    score = rng.uniform(0, 100, N)
    ds.write("w", FeatureCollection.from_columns(
        sft, [str(i) for i in range(N)],
        {"kind": kind, "score": score, "dtg": t, "geom": (x, y)},
    ))
    return ds, dict(x=x, y=y, t=t, kind=kind, score=score, t0=t0)


def _random_leaf(rng, cols):
    t0 = cols["t0"]
    k = rng.integers(0, 4)
    if k == 0:  # bbox (occasionally degenerate / world-spanning)
        w = float(rng.choice([0.0, 1.0, 20.0, 400.0]))
        # round-trip through the formatted text so the truth mask uses
        # EXACTLY the values the parser will see
        qx = float(f"{rng.uniform(-180, 180 - min(w, 10)):.3f}")
        qy = float(f"{rng.uniform(-90, 90 - min(w / 2, 10)):.3f}")
        x1 = float(f"{qx + w:.3f}")
        y1 = float(f"{qy + w / 2:.3f}")
        expr = f"bbox(geom, {qx}, {qy}, {x1}, {y1})"
        mask = _wrap_lon_mask(cols["x"], qx, x1) & (cols["y"] >= qy) & (cols["y"] <= y1)
        return expr, mask
    if k == 1:  # time window (occasionally empty or outside data range)
        lo = int(t0 + rng.integers(-5, 40) * DAY)
        hi = lo + int(rng.choice([0, 1, 7, 60])) * DAY
        expr = (
            f"dtg DURING {np.datetime64(lo, 'ms')}Z/{np.datetime64(hi, 'ms')}Z"
        )
        return expr, (cols["t"] >= lo) & (cols["t"] < hi)
    if k == 2:  # attribute equality
        v = str(rng.choice(["a", "b", "c", "d", "zz"]))
        return f"kind = '{v}'", cols["kind"] == v
    lo = float(f"{rng.uniform(0, 90):.3f}")
    hi = float(f"{lo + float(rng.choice([0.0, 5.0, 50.0])):.3f}")
    return (
        f"score BETWEEN {lo} AND {hi}",
        (cols["score"] >= lo) & (cols["score"] <= hi),
    )


def _random_filter(rng, cols, depth=0):
    if depth < 2 and rng.uniform() < 0.45:
        op = str(rng.choice(["AND", "OR"]))
        (e1, m1), (e2, m2) = (
            _random_filter(rng, cols, depth + 1),
            _random_filter(rng, cols, depth + 1),
        )
        m = (m1 & m2) if op == "AND" else (m1 | m2)
        return f"({e1}) {op} ({e2})", m
    if depth > 0 and rng.uniform() < 0.15:
        e, m = _random_leaf(rng, cols)
        return f"NOT ({e})", ~m
    return _random_leaf(rng, cols)


@pytest.mark.parametrize("seed", range(60))
def test_random_filter_matches_brute_force(world, seed):
    ds, cols = world
    rng = np.random.default_rng(1000 + seed)
    expr, mask = _random_filter(rng, cols)
    out = ds.query("w", expr)
    got = np.sort(np.asarray(out.ids, dtype=np.int64))
    want = np.flatnonzero(mask)
    assert np.array_equal(got, want), (
        expr, len(got), len(want),
        np.setdiff1d(got, want)[:5], np.setdiff1d(want, got)[:5],
    )


def _check_fused_batch(ds, cols, seed, n_filters=10):
    """One query_many batch of random filters vs brute-force truth —
    shared by the parametrized sweep and the stress sweep."""
    rng = np.random.default_rng(seed)
    exprs, masks = zip(*(_random_filter(rng, cols) for _ in range(n_filters)))
    outs = ds.query_many("w", list(exprs))
    for expr, mask, out in zip(exprs, masks, outs):
        got = np.sort(np.asarray(out.ids, dtype=np.int64))
        want = np.flatnonzero(mask)
        assert np.array_equal(got, want), (seed, expr, len(got), len(want))


@pytest.mark.parametrize("batch", range(6))
def test_random_filter_batches_fuse_exactly(world, batch):
    """The fused batch path (query_many -> submit_many -> fused kernel
    chunks) must answer random filter MIXES exactly like brute force —
    same sweep as above, ten filters per batch so box/window scans
    actually share fused dispatches."""
    ds, cols = world
    _check_fused_batch(ds, cols, 7000 + batch)


def test_fused_batch_stress_sweep(world):
    """100 further batches in one test (seeds disjoint from the
    parametrized sweep): ~2 s of pure fused-path stress, post-warmup, so
    chunk-packing edge cases (member counts, sparse fallbacks, mixed
    variant groups) see a wide input distribution every run."""
    ds, cols = world
    for batch in range(100):
        _check_fused_batch(ds, cols, 50_000 + batch)


class TestExtentFuzz:
    """Same differential sweep over an XZ2 extent store: random rectangle
    footprints, random INTERSECTS/bbox/NOT combinations vs brute-force
    bbox-overlap truth (rect geometries' intersects IS bbox overlap)."""

    N = 3000

    @pytest.fixture(scope="class")
    def bld(self):
        from geomesa_tpu import geometry as geo

        rng = np.random.default_rng(7)
        sft = FeatureType.from_spec("bld", "*geom:Polygon:srid=4326")
        sft.user_data["geomesa.indices.enabled"] = "xz2"
        ds = DataStore(tile=64)
        ds.create_schema(sft)
        x0 = rng.uniform(-170, 168, self.N)
        y0 = rng.uniform(-80, 78, self.N)
        w = rng.uniform(0.001, 1.5, self.N)
        h = rng.uniform(0.001, 1.2, self.N)
        col = geo.PackedGeometryColumn.from_boxes(x0, y0, x0 + w, y0 + h)
        ds.write("bld", FeatureCollection.from_columns(
            sft, [str(i) for i in range(self.N)], {"geom": col}
        ))
        return ds, (x0, y0, x0 + w, y0 + h)

    @pytest.mark.parametrize("seed", range(30))
    def test_random_extent_filters(self, bld, seed):
        ds, (bx0, by0, bx1, by1) = bld
        rng = np.random.default_rng(400 + seed)

        def leaf():
            qw = float(rng.choice([0.05, 1.0, 15.0]))
            qx = float(f"{rng.uniform(-175, 175 - qw):.3f}")
            qy = float(f"{rng.uniform(-85, 85 - qw):.3f}")
            x1 = float(f"{qx + qw:.3f}")
            y1 = float(f"{qy + qw:.3f}")
            if rng.uniform() < 0.5:
                expr = f"bbox(geom, {qx}, {qy}, {x1}, {y1})"
            else:
                expr = (
                    f"INTERSECTS(geom, POLYGON(({qx} {qy}, {x1} {qy}, "
                    f"{x1} {y1}, {qx} {y1}, {qx} {qy})))"
                )
            m = (bx0 <= x1) & (bx1 >= qx) & (by0 <= y1) & (by1 >= qy)
            return expr, m

        (e1, m1), (e2, m2) = leaf(), leaf()
        op = str(rng.choice(["AND", "OR"]))
        expr = f"({e1}) {op} ({e2})"
        mask = (m1 & m2) if op == "AND" else (m1 | m2)
        if rng.uniform() < 0.3:
            expr = f"NOT ({expr})"
            mask = ~mask
        out = ds.query("bld", expr)
        got = np.sort(np.asarray(out.ids, dtype=np.int64))
        np.testing.assert_array_equal(got, np.flatnonzero(mask))


class TestAggregationFuzz:
    """Random density/count/bounds configs: mesh == single-device == numpy
    truth (loose f32 tolerance where the device path is widened)."""

    @pytest.fixture(scope="class")
    def pair(self):
        from geomesa_tpu.parallel import make_mesh

        rng = np.random.default_rng(17)
        sft = FeatureType.from_spec("ev", "dtg:Date,*geom:Point:srid=4326")
        n = 5000
        t0 = int(np.datetime64("2024-05-01", "ms").astype(np.int64))
        cols = {
            "dtg": t0 + rng.integers(0, 86400_000 * 15, n),
            "geom": (rng.uniform(-90, 90, n), rng.uniform(-45, 45, n)),
        }
        stores = []
        for mesh in (None, make_mesh(4)):
            ds = DataStore(tile=32, mesh=mesh)
            ds.create_schema(sft)
            ds.write("ev", FeatureCollection.from_columns(
                sft, [str(i) for i in range(n)], dict(cols)))
            stores.append(ds)
        return stores, cols

    @pytest.mark.parametrize("seed", range(12))
    def test_random_aggregations(self, pair, seed):
        (single, mesh), cols = pair
        rng = np.random.default_rng(800 + seed)
        w = float(rng.choice([5.0, 30.0, 100.0]))
        qx = float(f"{rng.uniform(-90, 90 - w):.2f}")
        qy = float(f"{rng.uniform(-45, 45 - min(w, 40)):.2f}")
        x1, y1 = qx + w, min(qy + w, 45.0)
        q = f"bbox(geom, {qx}, {qy}, {x1}, {y1})"
        x, y = cols["geom"]
        m = (x >= qx) & (x <= x1) & (y >= qy) & (y <= y1)

        assert single.count("ev", q) == mesh.count("ev", q) == int(m.sum())
        gw, gh = int(rng.choice([32, 64])), int(rng.choice([32, 64]))
        d1 = single.density("ev", q, width=gw, height=gh)
        d2 = mesh.density("ev", q, width=gw, height=gh)
        np.testing.assert_allclose(d1, d2, atol=1e-4)
        assert abs(float(d1.sum()) - int(m.sum())) <= max(2, 0.02 * m.sum())
        b1 = single.bounds("ev", q, estimate=True)
        b2 = mesh.bounds("ev", q, estimate=True)
        if b1 is None or b2 is None:
            assert b1 == b2
        else:
            np.testing.assert_allclose(
                np.array(b1, float), np.array(b2, float), atol=1e-3
            )


class TestXZ3Fuzz:
    """Differential sweep over an XZ3 extent+time store (VERDICT r4 weak
    #5: XZ3 had no direct end-to-end fuzz). Random rectangle footprints
    with timestamps; random (bbox|INTERSECTS) x time-window combinations
    vs brute-force bbox-overlap & time-range truth."""

    N = 2500

    @pytest.fixture(scope="class")
    def store(self):
        from geomesa_tpu import geometry as geo

        rng = np.random.default_rng(21)
        sft = FeatureType.from_spec("tx", "dtg:Date,*geom:Polygon:srid=4326")
        sft.user_data["geomesa.indices.enabled"] = "xz3"
        ds = DataStore(tile=64)
        ds.create_schema(sft)
        t0 = np.datetime64("2024-03-01T00:00:00", "ms").astype(np.int64)
        x0 = rng.uniform(-170, 168, self.N)
        y0 = rng.uniform(-80, 78, self.N)
        w = rng.uniform(0.001, 1.5, self.N)
        h = rng.uniform(0.001, 1.2, self.N)
        t = t0 + rng.integers(0, 45 * DAY, self.N)
        col = geo.PackedGeometryColumn.from_boxes(x0, y0, x0 + w, y0 + h)
        ds.write("tx", FeatureCollection.from_columns(
            sft, [str(i) for i in range(self.N)], {"dtg": t, "geom": col}
        ))
        assert [i.name for i in ds.indexes("tx")] == ["xz3"]
        return ds, (x0, y0, x0 + w, y0 + h, t, t0)

    @pytest.mark.parametrize("seed", range(30))
    def test_random_xz3_filters(self, store, seed):
        ds, (bx0, by0, bx1, by1, t, t0) = store
        rng = np.random.default_rng(4400 + seed)
        qw = float(rng.choice([0.05, 1.0, 15.0]))
        qx = float(f"{rng.uniform(-175, 175 - qw):.3f}")
        qy = float(f"{rng.uniform(-85, 85 - qw):.3f}")
        x1, y1 = float(f"{qx + qw:.3f}"), float(f"{qy + qw:.3f}")
        if rng.uniform() < 0.5:
            spatial = f"bbox(geom, {qx}, {qy}, {x1}, {y1})"
        else:
            spatial = (
                f"INTERSECTS(geom, POLYGON(({qx} {qy}, {x1} {qy}, "
                f"{x1} {y1}, {qx} {y1}, {qx} {qy})))"
            )
        sm = (bx0 <= x1) & (bx1 >= qx) & (by0 <= y1) & (by1 >= qy)
        lo = int(t0 + rng.integers(-5, 50) * DAY)
        hi = lo + int(rng.choice([0, 1, 7, 30])) * DAY
        tm = (t >= lo) & (t < hi)
        expr = (
            f"({spatial}) AND dtg DURING "
            f"{np.datetime64(lo, 'ms')}Z/{np.datetime64(hi, 'ms')}Z"
        )
        mask = sm & tm
        if rng.uniform() < 0.25:  # spatial-only through the XZ3 index
            expr, mask = spatial, sm
        out = ds.query("tx", expr)
        got = np.sort(np.asarray(out.ids, dtype=np.int64))
        np.testing.assert_array_equal(got, np.flatnonzero(mask), err_msg=expr)


class TestS3Fuzz:
    """Differential sweep over an S3 point store (S2 cells + time bins;
    VERDICT r4 weak #5: S3 was only covered via coverer unit tests)."""

    N = 3000

    @pytest.fixture(scope="class")
    def store(self):
        rng = np.random.default_rng(23)
        sft = FeatureType.from_spec("s3p", "dtg:Date,*geom:Point:srid=4326")
        sft.user_data["geomesa.indices.enabled"] = "s3"
        ds = DataStore(tile=64)
        ds.create_schema(sft)
        t0 = np.datetime64("2024-03-01T00:00:00", "ms").astype(np.int64)
        x = rng.uniform(-180, 180, self.N)
        y = rng.uniform(-90, 90, self.N)
        t = t0 + rng.integers(0, 45 * DAY, self.N)
        ds.write("s3p", FeatureCollection.from_columns(
            sft, [str(i) for i in range(self.N)], {"dtg": t, "geom": (x, y)}
        ))
        assert [i.name for i in ds.indexes("s3p")] == ["s3"]
        return ds, (x, y, t, t0)

    @pytest.mark.parametrize("seed", range(30))
    def test_random_s3_filters(self, store, seed):
        ds, (x, y, t, t0) = store
        rng = np.random.default_rng(4700 + seed)
        w = float(rng.choice([0.5, 5.0, 40.0, 200.0]))
        qx = float(f"{rng.uniform(-180, 180 - min(w, 20)):.3f}")
        qy = float(f"{rng.uniform(-90, 90 - min(w / 2, 10)):.3f}")
        x1, y1 = float(f"{qx + w:.3f}"), float(f"{qy + w / 2:.3f}")
        sm = _wrap_lon_mask(x, qx, x1) & (y >= qy) & (y <= y1)
        lo = int(t0 + rng.integers(-5, 50) * DAY)
        hi = lo + int(rng.choice([0, 1, 7, 30])) * DAY
        expr = (
            f"bbox(geom, {qx}, {qy}, {x1}, {y1}) AND dtg DURING "
            f"{np.datetime64(lo, 'ms')}Z/{np.datetime64(hi, 'ms')}Z"
        )
        mask = sm & (t >= lo) & (t < hi)
        if rng.uniform() < 0.25:  # spatial-only through the S3 index
            expr, mask = f"bbox(geom, {qx}, {qy}, {x1}, {y1})", sm
        out = ds.query("s3p", expr)
        got = np.sort(np.asarray(out.ids, dtype=np.int64))
        np.testing.assert_array_equal(got, np.flatnonzero(mask), err_msg=expr)
