"""Processes (kNN, proximity, tube select, unique) against brute force."""

import numpy as np
import pytest

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.process import knn_search, proximity_search, tube_select, unique_values
from geomesa_tpu.process.knn import haversine_m
from geomesa_tpu.sft import FeatureType

SPEC = "kind:String,dtg:Date,*geom:Point:srid=4326"
DAY = 86400_000


@pytest.fixture(scope="module")
def ds():
    sft = FeatureType.from_spec("p", SPEC)
    store = DataStore(tile=64)
    store.create_schema(sft)
    n = 4000
    rng = np.random.default_rng(7)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    x = rng.uniform(-10, 10, n)
    y = rng.uniform(-10, 10, n)
    t = t0 + rng.integers(0, 10 * DAY, n)
    fc = FeatureCollection.from_columns(
        sft,
        [str(i) for i in range(n)],
        {
            "kind": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
            "dtg": t,
            "geom": (x, y),
        },
    )
    store.write("p", fc)
    return store, fc, (x, y, t, t0)


class TestKnn:
    def test_matches_brute_force(self, ds):
        store, fc, (x, y, _, _) = ds
        out = knn_search(store, "p", 1.0, 2.0, k=15, estimated_distance_m=5_000)
        d = haversine_m(1.0, 2.0, x, y)
        want = np.argsort(d, kind="stable")[:15]
        got = sorted(out.ids.tolist())
        assert got == sorted(fc.ids[want].tolist())
        # ordered nearest-first
        dx, dy = out.representative_xy()
        dists = haversine_m(1.0, 2.0, dx, dy)
        assert (np.diff(dists) >= 0).all()

    def test_k_larger_than_data(self, ds):
        store, fc, _ = ds
        out = knn_search(
            store, "p", 0.0, 0.0, k=10**6, max_distance_m=5_000_000
        )
        assert len(out) == len(fc)

    def test_with_filter(self, ds):
        store, fc, (x, y, _, _) = ds
        from geomesa_tpu.filter import ecql

        out = knn_search(store, "p", 0.0, 0.0, k=5, filter=ecql.parse("kind = 'a'"))
        assert set(np.asarray(out.columns["kind"])) == {"a"}
        kinds = np.asarray(fc.columns["kind"])
        d = haversine_m(0.0, 0.0, x, y)
        d[kinds != "a"] = np.inf
        want = np.argsort(d, kind="stable")[:5]
        assert sorted(out.ids.tolist()) == sorted(fc.ids[want].tolist())


class TestProximity:
    def test_matches_brute_force(self, ds):
        store, fc, (x, y, _, _) = ds
        pts = [(0.0, 0.0), (5.0, 5.0)]
        out = proximity_search(store, "p", pts, distance_m=100_000)
        d = np.minimum(
            haversine_m(0.0, 0.0, x, y), haversine_m(5.0, 5.0, x, y)
        )
        truth = d <= 100_000
        assert sorted(out.ids.tolist()) == sorted(fc.ids[truth].tolist())

    def test_empty_inputs(self, ds):
        store, _, _ = ds
        assert len(proximity_search(store, "p", [], 1000)) == 0


class TestTube:
    def test_corridor(self, ds):
        store, fc, (x, y, t, t0) = ds
        track_xy = [(-5.0, -5.0), (0.0, 0.0), (5.0, 5.0)]
        track_t = [t0, t0 + 5 * DAY, t0 + 10 * DAY]
        out = tube_select(store, "p", track_xy, track_t, buffer_m=150_000)
        # brute force: distance to interpolated position at each row's time
        px = np.interp(t, np.array(track_t), np.array([p[0] for p in track_xy]))
        py = np.interp(t, np.array(track_t), np.array([p[1] for p in track_xy]))
        truth = haversine_m(x, y, px, py) <= 150_000
        assert sorted(out.ids.tolist()) == sorted(fc.ids[truth].tolist())

    def test_bad_track(self, ds):
        store, _, _ = ds
        with pytest.raises(ValueError):
            tube_select(store, "p", [(0, 0)], [0], buffer_m=100)


class TestUnique:
    def test_counts(self, ds):
        store, fc, _ = ds
        pairs = unique_values(store, "p", "kind", sort_by_count=True)
        vals, cnts = np.unique(np.asarray(fc.columns["kind"]), return_counts=True)
        assert dict(pairs) == dict(zip(vals.tolist(), cnts.tolist()))
        assert pairs[0][1] == max(cnts)


class TestJoinProcess:
    """JoinProcess analogue: correlate two types by attribute value."""

    def _stores(self):
        rng = np.random.default_rng(9)
        ds = DataStore()
        tracks = FeatureType.from_spec(
            "tracks", "vessel:String:index=true,dtg:Date,*geom:Point:srid=4326"
        )
        info = FeatureType.from_spec(
            "vessels", "vessel:String:index=true,flag:String,*geom:Point:srid=4326"
        )
        ds.create_schema(tracks)
        ds.create_schema(info)
        n = 2000
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
        ds.write("tracks", FeatureCollection.from_columns(
            tracks, [str(i) for i in range(n)],
            {"vessel": np.array([f"v{i % 40}" for i in range(n)]),
             "dtg": t0 + rng.integers(0, 86400_000, n),
             "geom": (rng.uniform(-60, 60, n), rng.uniform(-45, 45, n))},
        ))
        ds.write("vessels", FeatureCollection.from_columns(
            info, [f"m{i}" for i in range(60)],
            {"vessel": np.array([f"v{i}" for i in range(60)]),
             "flag": np.array([f"f{i % 5}" for i in range(60)]),
             "geom": (rng.uniform(-60, 60, 60), rng.uniform(-45, 45, 60))},
        ))
        return ds

    def test_join_by_attribute(self):
        from geomesa_tpu.process import join_search

        ds = self._stores()
        out = join_search(
            ds, "tracks", "vessels", "vessel",
            primary_filter="bbox(geom, -20, -15, 20, 15)",
        )
        # expected: vessels whose id appears among the primary hits
        hits = ds.query("tracks", "bbox(geom, -20, -15, 20, 15)")
        want = sorted(set(hits.columns["vessel"].tolist()))
        assert sorted(out.columns["vessel"].tolist()) == want
        assert len(out) > 0

    def test_join_with_secondary_filter(self):
        from geomesa_tpu.process import join_search

        ds = self._stores()
        out = join_search(
            ds, "tracks", "vessels", "vessel",
            primary_filter="bbox(geom, -60, -45, 60, 45)",
            secondary_filter="flag = 'f2'",
        )
        assert len(out) > 0
        assert set(out.columns["flag"].tolist()) == {"f2"}

    def test_join_value_cap_falls_back_to_mask(self):
        from geomesa_tpu.process import join_search

        ds = self._stores()
        small = join_search(ds, "tracks", "vessels", "vessel", max_values=3)
        full = join_search(ds, "tracks", "vessels", "vessel")
        assert sorted(small.ids.tolist()) == sorted(full.ids.tolist())

    def test_empty_primary(self):
        from geomesa_tpu.process import join_search

        ds = self._stores()
        out = join_search(
            ds, "tracks", "vessels", "vessel",
            primary_filter="vessel = 'nope'",
        )
        assert len(out) == 0 and out.sft.name == "vessels"

    def test_unknown_attribute_rejected(self):
        from geomesa_tpu.process import join_search

        ds = self._stores()
        with pytest.raises(ValueError):
            join_search(ds, "tracks", "vessels", "missing")


class TestKnnRadiusEstimate:
    def test_auto_radius_reduces_expansions(self):
        """Stats-based start radius: the first window should usually hold k
        neighbours, so the expansion loop runs once for uniform data."""
        rng = np.random.default_rng(14)
        n = 20000
        sft = FeatureType.from_spec("p", "*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        ds.write("p", FeatureCollection.from_columns(
            sft, np.arange(n), {"geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n))}
        ), check_ids=False)
        from geomesa_tpu.process.knn import _estimate_radius_m, knn_search

        r = _estimate_radius_m(ds, "p", 10)
        # ~50 pts per sq-degree here: a sane estimate sits well under 100km
        assert 1000 < r < 200_000
        queries = 0
        orig = ds.query

        def counting(*a, **k):
            nonlocal queries
            queries += 1
            return orig(*a, **k)

        ds.query = counting
        out = knn_search(ds, "p", 0.0, 0.0, k=10)
        assert len(out) == 10
        assert queries <= 2  # estimate good enough to avoid radius doubling

    def test_fallback_without_stats(self):
        from geomesa_tpu.process.knn import _estimate_radius_m

        sft = FeatureType.from_spec("e", "*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        assert _estimate_radius_m(ds, "e", 10) == 10_000.0
