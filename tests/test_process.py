"""Processes (kNN, proximity, tube select, unique) against brute force."""

import numpy as np
import pytest

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.process import knn_search, proximity_search, tube_select, unique_values
from geomesa_tpu.process.knn import haversine_m
from geomesa_tpu.sft import FeatureType

SPEC = "kind:String,dtg:Date,*geom:Point:srid=4326"
DAY = 86400_000


@pytest.fixture(scope="module")
def ds():
    sft = FeatureType.from_spec("p", SPEC)
    store = DataStore(tile=64)
    store.create_schema(sft)
    n = 4000
    rng = np.random.default_rng(7)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    x = rng.uniform(-10, 10, n)
    y = rng.uniform(-10, 10, n)
    t = t0 + rng.integers(0, 10 * DAY, n)
    fc = FeatureCollection.from_columns(
        sft,
        [str(i) for i in range(n)],
        {
            "kind": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
            "dtg": t,
            "geom": (x, y),
        },
    )
    store.write("p", fc)
    return store, fc, (x, y, t, t0)


class TestKnn:
    def test_matches_brute_force(self, ds):
        store, fc, (x, y, _, _) = ds
        out = knn_search(store, "p", 1.0, 2.0, k=15, estimated_distance_m=5_000)
        d = haversine_m(1.0, 2.0, x, y)
        want = np.argsort(d, kind="stable")[:15]
        got = sorted(out.ids.tolist())
        assert got == sorted(fc.ids[want].tolist())
        # ordered nearest-first
        dx, dy = out.representative_xy()
        dists = haversine_m(1.0, 2.0, dx, dy)
        assert (np.diff(dists) >= 0).all()

    def test_k_larger_than_data(self, ds):
        store, fc, _ = ds
        out = knn_search(
            store, "p", 0.0, 0.0, k=10**6, max_distance_m=5_000_000
        )
        assert len(out) == len(fc)

    def test_with_filter(self, ds):
        store, fc, (x, y, _, _) = ds
        from geomesa_tpu.filter import ecql

        out = knn_search(store, "p", 0.0, 0.0, k=5, filter=ecql.parse("kind = 'a'"))
        assert set(np.asarray(out.columns["kind"])) == {"a"}
        kinds = np.asarray(fc.columns["kind"])
        d = haversine_m(0.0, 0.0, x, y)
        d[kinds != "a"] = np.inf
        want = np.argsort(d, kind="stable")[:5]
        assert sorted(out.ids.tolist()) == sorted(fc.ids[want].tolist())


class TestProximity:
    def test_matches_brute_force(self, ds):
        store, fc, (x, y, _, _) = ds
        pts = [(0.0, 0.0), (5.0, 5.0)]
        out = proximity_search(store, "p", pts, distance_m=100_000)
        d = np.minimum(
            haversine_m(0.0, 0.0, x, y), haversine_m(5.0, 5.0, x, y)
        )
        truth = d <= 100_000
        assert sorted(out.ids.tolist()) == sorted(fc.ids[truth].tolist())

    def test_empty_inputs(self, ds):
        store, _, _ = ds
        assert len(proximity_search(store, "p", [], 1000)) == 0


class TestTube:
    def test_corridor(self, ds):
        store, fc, (x, y, t, t0) = ds
        track_xy = [(-5.0, -5.0), (0.0, 0.0), (5.0, 5.0)]
        track_t = [t0, t0 + 5 * DAY, t0 + 10 * DAY]
        out = tube_select(store, "p", track_xy, track_t, buffer_m=150_000)
        # brute force: distance to interpolated position at each row's time
        px = np.interp(t, np.array(track_t), np.array([p[0] for p in track_xy]))
        py = np.interp(t, np.array(track_t), np.array([p[1] for p in track_xy]))
        truth = haversine_m(x, y, px, py) <= 150_000
        assert sorted(out.ids.tolist()) == sorted(fc.ids[truth].tolist())

    def test_bad_track(self, ds):
        store, _, _ = ds
        with pytest.raises(ValueError):
            tube_select(store, "p", [(0, 0)], [0], buffer_m=100)


class TestUnique:
    def test_counts(self, ds):
        store, fc, _ = ds
        pairs = unique_values(store, "p", "kind", sort_by_count=True)
        vals, cnts = np.unique(np.asarray(fc.columns["kind"]), return_counts=True)
        assert dict(pairs) == dict(zip(vals.tolist(), cnts.tolist()))
        assert pairs[0][1] == max(cnts)


class TestJoinProcess:
    """JoinProcess analogue: correlate two types by attribute value."""

    def _stores(self):
        rng = np.random.default_rng(9)
        ds = DataStore()
        tracks = FeatureType.from_spec(
            "tracks", "vessel:String:index=true,dtg:Date,*geom:Point:srid=4326"
        )
        info = FeatureType.from_spec(
            "vessels", "vessel:String:index=true,flag:String,*geom:Point:srid=4326"
        )
        ds.create_schema(tracks)
        ds.create_schema(info)
        n = 2000
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
        ds.write("tracks", FeatureCollection.from_columns(
            tracks, [str(i) for i in range(n)],
            {"vessel": np.array([f"v{i % 40}" for i in range(n)]),
             "dtg": t0 + rng.integers(0, 86400_000, n),
             "geom": (rng.uniform(-60, 60, n), rng.uniform(-45, 45, n))},
        ))
        ds.write("vessels", FeatureCollection.from_columns(
            info, [f"m{i}" for i in range(60)],
            {"vessel": np.array([f"v{i}" for i in range(60)]),
             "flag": np.array([f"f{i % 5}" for i in range(60)]),
             "geom": (rng.uniform(-60, 60, 60), rng.uniform(-45, 45, 60))},
        ))
        return ds

    def test_join_by_attribute(self):
        from geomesa_tpu.process import join_search

        ds = self._stores()
        out = join_search(
            ds, "tracks", "vessels", "vessel",
            primary_filter="bbox(geom, -20, -15, 20, 15)",
        )
        # expected: vessels whose id appears among the primary hits
        hits = ds.query("tracks", "bbox(geom, -20, -15, 20, 15)")
        want = sorted(set(hits.columns["vessel"].tolist()))
        assert sorted(out.columns["vessel"].tolist()) == want
        assert len(out) > 0

    def test_join_with_secondary_filter(self):
        from geomesa_tpu.process import join_search

        ds = self._stores()
        out = join_search(
            ds, "tracks", "vessels", "vessel",
            primary_filter="bbox(geom, -60, -45, 60, 45)",
            secondary_filter="flag = 'f2'",
        )
        assert len(out) > 0
        assert set(out.columns["flag"].tolist()) == {"f2"}

    def test_join_value_cap_falls_back_to_mask(self):
        from geomesa_tpu.process import join_search

        ds = self._stores()
        small = join_search(ds, "tracks", "vessels", "vessel", max_values=3)
        full = join_search(ds, "tracks", "vessels", "vessel")
        assert sorted(small.ids.tolist()) == sorted(full.ids.tolist())

    def test_empty_primary(self):
        from geomesa_tpu.process import join_search

        ds = self._stores()
        out = join_search(
            ds, "tracks", "vessels", "vessel",
            primary_filter="vessel = 'nope'",
        )
        assert len(out) == 0 and out.sft.name == "vessels"

    def test_unknown_attribute_rejected(self):
        from geomesa_tpu.process import join_search

        ds = self._stores()
        with pytest.raises(ValueError):
            join_search(ds, "tracks", "vessels", "missing")


class TestKnnRadiusEstimate:
    def test_auto_radius_reduces_expansions(self):
        """Stats-based start radius: the first window should usually hold k
        neighbours, so the expansion loop runs once for uniform data."""
        rng = np.random.default_rng(14)
        n = 20000
        sft = FeatureType.from_spec("p", "*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        ds.write("p", FeatureCollection.from_columns(
            sft, np.arange(n), {"geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n))}
        ), check_ids=False)
        from geomesa_tpu.process.knn import _estimate_radius_m, knn_search

        r = _estimate_radius_m(ds, "p", 10, 0.0, 0.0, 1_000_000.0)
        # ~50 pts per sq-degree here: a sane estimate sits well under 100km
        assert 1000 < r < 200_000
        queries = 0
        orig = ds.query

        def counting(*a, **k):
            nonlocal queries
            queries += 1
            return orig(*a, **k)

        ds.query = counting
        out = knn_search(ds, "p", 0.0, 0.0, k=10)
        assert len(out) == 10
        assert queries <= 2  # estimate good enough to avoid radius doubling

    def test_fallback_without_stats(self):
        from geomesa_tpu.process.knn import _estimate_radius_m

        sft = FeatureType.from_spec("e", "*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        assert _estimate_radius_m(ds, "e", 10, 0.0, 0.0, 1_000_000.0) == 10_000.0


class TestRouteSearch:
    """route_search vs a brute-force numpy re-implementation (reference
    RouteSearchProcess: dwithin buffer + closest-segment heading match)."""

    @pytest.fixture(scope="class")
    def route_ds(self):
        from geomesa_tpu.process.knn import METERS_PER_DEGREE

        sft = FeatureType.from_spec(
            "trk", "heading:Double,*geom:Point:srid=4326"
        )
        store = DataStore(tile=64)
        store.create_schema(sft)
        rng = np.random.default_rng(11)
        n = 3000
        x = rng.uniform(-1, 3, n)
        y = rng.uniform(-1, 3, n)
        heading = rng.uniform(0, 360, n)
        fc = FeatureCollection.from_columns(
            sft, [str(i) for i in range(n)],
            {"heading": heading, "geom": (x, y)},
        )
        store.write("trk", fc)
        return store, (x, y, heading)

    # an L-shaped route: east along y=0 then north along x=2
    ROUTE = np.array([[0.0, 0.0], [2.0, 0.0], [2.0, 2.0]])

    def _brute(self, x, y, heading, buffer_m, thr, bidirectional):
        from geomesa_tpu.process.knn import METERS_PER_DEGREE
        from geomesa_tpu.process.route import (
            _point_segment_distances, heading_diff,
        )

        d, b = _point_segment_distances(
            x, y, self.ROUTE[:-1], self.ROUTE[1:]
        )
        k = np.argmin(d, axis=1)
        rng = np.arange(len(k))
        dist = d[rng, k]
        diff = heading_diff(b[rng, k], heading)
        m = diff <= thr
        if bidirectional:
            m |= np.abs(diff - 180.0) <= thr
        return (dist <= buffer_m) & m

    def test_matches_brute_force(self, route_ds):
        from geomesa_tpu.process import route_search

        store, (x, y, heading) = route_ds
        out = route_search(
            store, "trk", self.ROUTE, buffer_m=30_000,
            heading_threshold_deg=25.0, heading_field="heading",
        )
        want = np.flatnonzero(self._brute(x, y, heading, 30_000, 25.0, False))
        got = np.sort(np.asarray(out.ids, dtype=np.int64).astype(np.int64))
        np.testing.assert_array_equal(got, want)
        assert len(want) > 0

    def test_bidirectional_superset(self, route_ds):
        from geomesa_tpu.process import route_search

        store, (x, y, heading) = route_ds
        uni = route_search(
            store, "trk", self.ROUTE, 30_000, 25.0,
            heading_field="heading",
        )
        bi = route_search(
            store, "trk", self.ROUTE, 30_000, 25.0,
            heading_field="heading", bidirectional=True,
        )
        want = np.flatnonzero(self._brute(x, y, heading, 30_000, 25.0, True))
        np.testing.assert_array_equal(
            np.sort(np.asarray(bi.ids, dtype=np.int64)), want
        )
        assert len(bi) > len(uni)

    def test_heading_required_for_points(self, route_ds):
        from geomesa_tpu.process import route_search

        store, _ = route_ds
        with pytest.raises(ValueError, match="heading_field"):
            route_search(store, "trk", self.ROUTE, 1000, 10.0)

    def test_wkt_route_and_filter(self, route_ds):
        from geomesa_tpu.filter import ecql
        from geomesa_tpu.process import route_search

        store, (x, y, heading) = route_ds
        out = route_search(
            store, "trk", "LINESTRING(0 0, 2 0, 2 2)", 30_000, 25.0,
            heading_field="heading",
            filter=ecql.parse("bbox(geom, -1, -1, 1, 1)"),
        )
        brute = self._brute(x, y, heading, 30_000, 25.0, False)
        brute &= (x >= -1) & (x <= 1) & (y >= -1) & (y <= 1)
        np.testing.assert_array_equal(
            np.sort(np.asarray(out.ids, dtype=np.int64)),
            np.flatnonzero(brute),
        )


class TestTransformProcesses:
    """point2point / track_label / date_offset / bin+arrow conversion
    (reference geomesa-process transform tier)."""

    @pytest.fixture(scope="class")
    def tracks(self):
        sft = FeatureType.from_spec(
            "trk2", "track:String,dtg:Date,*geom:Point:srid=4326"
        )
        t0 = np.datetime64("2024-03-01T00:00:00", "ms").astype(np.int64)
        HOUR = 3600_000
        rows = [
            # track a: 3 points, crosses a day boundary between p1 and p2
            ("a", t0 + 22 * HOUR, 0.0, 0.0),
            ("a", t0 + 23 * HOUR, 1.0, 0.0),
            ("a", t0 + 25 * HOUR, 2.0, 0.0),
            # track b: 2 points, second is a duplicate position
            ("b", t0 + 1 * HOUR, 5.0, 5.0),
            ("b", t0 + 2 * HOUR, 5.0, 5.0),
            # track c: single point
            ("c", t0 + 3 * HOUR, 9.0, 9.0),
        ]
        fc = FeatureCollection.from_columns(
            sft,
            [str(i) for i in range(len(rows))],
            {
                "track": np.array([r[0] for r in rows]),
                "dtg": np.array([r[1] for r in rows], dtype=np.int64),
                "geom": (
                    np.array([r[2] for r in rows]),
                    np.array([r[3] for r in rows]),
                ),
            },
        )
        return fc, t0

    def test_point2point_segments(self, tracks):
        from geomesa_tpu.process import point2point

        fc, t0 = tracks
        out = point2point(fc, "track", "dtg", min_points=1)
        # a: 2 segments; b: its only segment is singular (dropped); c: too small
        assert len(out) == 2
        assert list(out.columns["track"]) == ["a", "a"]
        assert list(out.ids) == ["a-0", "a-1"]
        g0 = out.geom_column.geometry(0)
        assert [tuple(c) for c in g0.coords] == [(0.0, 0.0), (1.0, 0.0)]
        HOUR = 3600_000
        np.testing.assert_array_equal(
            out.columns["dtg_start"], [t0 + 22 * HOUR, t0 + 23 * HOUR]
        )
        np.testing.assert_array_equal(
            out.columns["dtg_end"], [t0 + 23 * HOUR, t0 + 25 * HOUR]
        )

    def test_point2point_break_on_day(self, tracks):
        from geomesa_tpu.process import point2point

        fc, _ = tracks
        out = point2point(fc, "track", "dtg", min_points=1, break_on_day=True)
        assert len(out) == 1  # a's day-crossing segment dropped
        assert list(out.ids) == ["a-0"]

    def test_point2point_keep_singular(self, tracks):
        from geomesa_tpu.process import point2point

        fc, _ = tracks
        out = point2point(
            fc, "track", "dtg", min_points=1, filter_singular=False
        )
        assert len(out) == 3  # b's zero-length segment kept

    def test_track_label(self, tracks):
        from geomesa_tpu.process import track_label

        fc, t0 = tracks
        out = track_label(fc, "track", "dtg")
        assert len(out) == 3
        got = dict(zip(out.columns["track"].tolist(), out.columns["dtg"].tolist()))
        HOUR = 3600_000
        assert got == {
            "a": t0 + 25 * HOUR, "b": t0 + 2 * HOUR, "c": t0 + 3 * HOUR
        }

    def test_date_offset(self, tracks):
        from geomesa_tpu.process import date_offset

        fc, _ = tracks
        out = date_offset(fc, "dtg", 60_000)
        np.testing.assert_array_equal(
            np.asarray(out.columns["dtg"]),
            np.asarray(fc.columns["dtg"]) + 60_000,
        )
        # input unchanged
        assert out.columns["dtg"] is not fc.columns["dtg"]

    def test_bin_conversion_roundtrip(self, tracks):
        from geomesa_tpu.process import bin_conversion
        from geomesa_tpu.utils import bin_format

        fc, _ = tracks
        data = bin_conversion(fc, "track", "dtg")
        dec = bin_format.decode(data)
        assert len(dec["lat"]) == len(fc)
        np.testing.assert_allclose(dec["lon"], fc.representative_xy()[0])

    def test_arrow_conversion(self, tracks):
        pytest.importorskip("pyarrow")
        from geomesa_tpu.io.arrow import read_arrow_table
        from geomesa_tpu.process import arrow_conversion

        fc, _ = tracks
        table = read_arrow_table(arrow_conversion(fc))
        assert table.num_rows == len(fc)


class TestKnnLocalRadius:
    """Sketch-refined start radius (z2 store): sparse query regions grow
    the window host-side instead of paying device-query doubling rounds."""

    @pytest.fixture(scope="class")
    def clustered(self):
        rng = np.random.default_rng(21)
        sft = FeatureType.from_spec("c", "*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        # dense cluster at (0, 0), nothing within ~10 degrees of (40, 40)
        n = 30000
        x = rng.normal(0, 0.5, n)
        y = rng.normal(0, 0.5, n)
        ds.write("c", FeatureCollection.from_columns(
            sft, np.arange(n), {"geom": (x, y)}
        ), check_ids=False)
        return ds

    def test_z2_sketch_feeds_estimate_count(self, clustered):
        ds = clustered
        est = ds.estimate_count("c", "bbox(geom, -1, -1, 1, 1)")
        # sketch-based (not exact): right order of magnitude is enough
        true = ds.count("c", "bbox(geom, -1, -1, 1, 1)")
        assert true > 0
        assert 0.2 * true < est < 5 * true

    def test_sparse_region_grows_radius_without_queries(self, clustered):
        from geomesa_tpu.process.knn import _estimate_radius_m, knn_search

        ds = clustered
        r_dense = _estimate_radius_m(ds, "c", 10, 0.0, 0.0, 5e6)
        r_sparse = _estimate_radius_m(ds, "c", 10, 40.0, 40.0, 5e6)
        assert r_sparse > 10 * r_dense  # local sketch sees the emptiness
        queries = 0
        orig = ds.query

        def counting(*a, **k):
            nonlocal queries
            queries += 1
            return orig(*a, **k)

        ds.query = counting
        try:
            out = knn_search(ds, "c", 40.0, 40.0, k=5, max_distance_m=2e7)
        finally:
            ds.query = orig
        assert len(out) == 5
        assert queries <= 3


class TestThinProcesses:
    def test_query_sampling_minmax(self, ds):
        from geomesa_tpu.process import (
            minmax_process, query_process, sampling_process,
        )

        store, fc, (x, y, t, t0) = ds
        out = query_process(store, "p", "bbox(geom, -5, -5, 5, 5)")
        want = np.flatnonzero((x >= -5) & (x <= 5) & (y >= -5) & (y <= 5))
        assert np.array_equal(np.sort(np.asarray(out.ids, np.int64)), want)
        s = sampling_process(fc, 0.25)
        assert 0 < len(s) < len(fc)
        mm = minmax_process(store, "p", "dtg")
        assert int(mm[0]) == int(t.min()) and int(mm[1]) == int(t.max())
        mm2 = minmax_process(store, "p", "dtg", "bbox(geom, -5, -5, 5, 5)")
        assert int(mm2[0]) == int(t[want].min())


class TestKnnMany:
    def test_matches_per_point_search(self, ds):
        from geomesa_tpu.process import knn_many, knn_search

        store, fc, (x, y, t, t0) = ds
        rng = np.random.default_rng(33)
        pts = [(float(rng.uniform(-9, 9)), float(rng.uniform(-9, 9)))
               for _ in range(8)]
        # one far-away point forces the expansion rounds
        pts.append((60.0, 60.0))
        batched = knn_many(store, "p", pts, k=6, max_distance_m=2e7)
        for (qx, qy), got in zip(pts, batched):
            want = knn_search(store, "p", qx, qy, k=6, max_distance_m=2e7)
            assert got.ids.tolist() == want.ids.tolist(), (qx, qy)
        assert all(len(b) == 6 for b in batched)

    def test_with_filter(self, ds):
        from geomesa_tpu.filter import ecql
        from geomesa_tpu.process import knn_many, knn_search

        store, fc, _ = ds
        f = ecql.parse("kind = 'b'")
        got = knn_many(store, "p", [(0.0, 0.0)], k=5, filter=f)[0]
        want = knn_search(store, "p", 0.0, 0.0, k=5, filter=f)
        assert got.ids.tolist() == want.ids.tolist()
        assert set(got.columns["kind"]) == {"b"}


class TestKnnAntimeridian:
    def test_wraps_across_seam(self):
        """Neighbours across +/-180 must win over farther same-side points
        (the window becomes two boxes at the seam)."""
        from geomesa_tpu.process import knn_many, knn_search
        from geomesa_tpu.process.knn import haversine_m

        sft = FeatureType.from_spec("s", "*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        x = np.array([-179.9, -179.5, 178.0, 170.0, 0.0])
        y = np.zeros(5)
        ds.write("s", FeatureCollection.from_columns(
            sft, np.arange(5), {"geom": (x, y)}
        ))
        got = knn_search(ds, "s", 179.8, 0.0, k=2, estimated_distance_m=30_000)
        d = haversine_m(x, y, 179.8, 0.0)
        want = np.argsort(d)[:2]
        assert set(np.asarray(got.ids, np.int64).tolist()) == set(want.tolist())
        many = knn_many(ds, "s", [(179.8, 0.0)], k=2, estimated_distance_m=30_000)
        assert many[0].ids.tolist() == got.ids.tolist()


class TestTubeBruteForce:
    def test_matches_continuous_interpolation(self):
        from geomesa_tpu.process import tube_select
        from geomesa_tpu.process.knn import haversine_m

        rng = np.random.default_rng(0)
        sft = FeatureType.from_spec("ev", "dtg:Date,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        n = 20000
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
        x = rng.uniform(-5, 15, n)
        y = rng.uniform(-5, 15, n)
        t = t0 + rng.integers(0, 3600_000, n)
        ds.write("ev", FeatureCollection.from_columns(
            sft, np.arange(n), {"dtg": t, "geom": (x, y)}
        ), check_ids=False)
        track = np.stack([np.linspace(0, 10, 20), np.linspace(0, 10, 20)], axis=1)
        times = t0 + np.linspace(0, 3600_000, 20).astype(np.int64)
        out = tube_select(ds, "ev", track, times, buffer_m=100_000, bin_ms=60_000)
        cx = np.interp(t, times, track[:, 0])
        cy = np.interp(t, times, track[:, 1])
        exact = np.flatnonzero(haversine_m(x, y, cx, cy) <= 100_000)
        np.testing.assert_array_equal(
            np.sort(np.asarray(out.ids, np.int64)), exact
        )
        assert len(exact) > 50
