"""Streaming cache, Lambda hot/cold store, security, bucket index, views."""

import numpy as np
import pytest

from geomesa_tpu import geometry as geo, security
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.streaming import LambdaStore, StreamingFeatureCache
from geomesa_tpu.utils.spatial_index import BucketIndex
from geomesa_tpu.views import MergedView, RoutedView

SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"


def _row(name, x, y, t="2024-01-01T00:00:00Z"):
    return {"name": name, "dtg": t, "geom": geo.Point(x, y)}


class TestBucketIndex:
    def test_insert_query_remove(self):
        idx = BucketIndex(36, 18)
        idx.insert("a", (10, 10, 10, 10))
        idx.insert("b", (-10, -10, -10, -10))
        idx.insert("wide", (-20, -20, 20, 20))
        assert sorted(idx.query((5, 5, 15, 15))) == ["a", "wide"]
        assert sorted(idx.query((-15, -15, -5, -5))) == ["b", "wide"]
        assert idx.remove("wide")
        assert sorted(idx.query((5, 5, 15, 15))) == ["a"]
        assert not idx.remove("wide")

    def test_replace(self):
        idx = BucketIndex()
        idx.insert("a", (0, 0, 0, 0))
        idx.insert("a", (50, 50, 50, 50))
        assert idx.query((-1, -1, 1, 1)) == []
        assert idx.query((49, 49, 51, 51)) == ["a"]
        assert len(idx) == 1


class TestStreamingCache:
    def test_upsert_latest_wins(self):
        sft = FeatureType.from_spec("s", SPEC)
        cache = StreamingFeatureCache(sft)
        cache.upsert([_row("v1", 0, 0)], ids=["f1"])
        cache.upsert([_row("v2", 1, 1)], ids=["f1"])
        assert len(cache) == 1
        out = cache.query("bbox(geom, 0.5, 0.5, 2, 2)")
        assert out.ids.tolist() == ["f1"]
        assert np.asarray(out.columns["name"])[0] == "v2"
        # old location no longer matches
        assert len(cache.query("bbox(geom, -0.5, -0.5, 0.5, 0.5)")) == 0

    def test_delete_and_listeners(self):
        sft = FeatureType.from_spec("s", SPEC)
        cache = StreamingFeatureCache(sft)
        events = []
        cache.listeners.append(lambda ev, fid, row: events.append((ev, fid)))
        cache.upsert([_row("a", 0, 0)], ids=["x"])
        cache.upsert([_row("b", 0, 0)], ids=["x"])
        cache.delete(["x"])
        assert events == [("added", "x"), ("updated", "x"), ("removed", "x")]

    def test_expiry(self):
        sft = FeatureType.from_spec("s", SPEC)
        cache = StreamingFeatureCache(sft, expiry_ms=1000)
        cache.upsert([_row("a", 0, 0)], ids=["x"])
        assert cache.expire(now_ms=0) == 0  # not yet old (ingest time ~now)
        import time

        future = int(time.time() * 1000) + 10_000
        assert cache.expire(now_ms=future) == 1
        assert len(cache) == 0

    def test_filter_with_attributes(self):
        sft = FeatureType.from_spec("s", SPEC)
        cache = StreamingFeatureCache(sft)
        cache.upsert([_row("a", 0, 0), _row("b", 1, 1)], ids=["1", "2"])
        out = cache.query("name = 'b'")
        assert out.ids.tolist() == ["2"]

    def test_expire_survives_raising_listener(self):
        """One raising listener must not abort the sweep and leave
        expired rows resident; the error is counted in metrics."""
        from geomesa_tpu.metrics import MetricsRegistry

        reg = MetricsRegistry()
        sft = FeatureType.from_spec("s", SPEC)
        cache = StreamingFeatureCache(sft, expiry_ms=1000, metrics=reg)
        seen = []

        def bad(ev, fid, row):
            raise RuntimeError("listener boom")

        import time

        cache.upsert([_row(n, 0, 0) for n in "abc"], ids=["1", "2", "3"])
        # wire the listeners after ingest: upsert's write path does not
        # guard (see test below) — the sweep is what must survive
        cache.listeners.append(bad)
        cache.listeners.append(lambda ev, fid, row: seen.append((ev, fid)))
        future = int(time.time() * 1000) + 10_000
        assert cache.expire(now_ms=future) == 3  # sweep completed
        assert len(cache) == 0                   # nothing left resident
        assert reg.counters["geomesa.stream.listener_errors"] == 3
        # the well-behaved listener still saw every expiry
        assert [e for e in seen if e[0] == "expired"] == [
            ("expired", "1"), ("expired", "2"), ("expired", "3")
        ]

    def test_upsert_listener_errors_still_propagate(self):
        """Only maintenance sweeps guard: a write-path listener failure is
        the caller's to see (unchanged contract)."""
        sft = FeatureType.from_spec("s", SPEC)
        cache = StreamingFeatureCache(sft)
        cache.listeners.append(
            lambda ev, fid, row: (_ for _ in ()).throw(RuntimeError("boom"))
        )
        with pytest.raises(RuntimeError):
            cache.upsert([_row("a", 0, 0)], ids=["1"])


class TestLambdaStore:
    def _cold(self):
        ds = DataStore(tile=64)
        ds.create_schema(FeatureType.from_spec("s", SPEC))
        return ds

    def test_hot_cold_merge(self):
        lam = LambdaStore(self._cold(), "s")
        lam.write([_row("h", 0, 0)], ids=["hot1"])
        assert lam.count("bbox(geom, -1, -1, 1, 1)") == 1
        assert lam.persist_hot() == 1
        assert len(lam.hot) == 0
        # now served from cold
        assert lam.count("bbox(geom, -1, -1, 1, 1)") == 1
        # hot update wins over persisted cold row; persisting again
        # replaces the stale cold copy (reference LambdaDataStore persists
        # updates — its primary loop; advisor r2 medium fix)
        lam.write([_row("h2", 0.5, 0.5)], ids=["hot1"])
        out = lam.query("bbox(geom, -1, -1, 1, 1)")
        assert len(out) == 1
        assert np.asarray(out.columns["name"])[0] == "h2"
        assert lam.persist_hot() == 1
        assert len(lam.hot) == 0
        out = lam.query("bbox(geom, -1, -1, 1, 1)")
        assert len(out) == 1
        assert np.asarray(out.columns["name"])[0] == "h2"
        # the update survives a further flush cycle with nothing hot
        assert lam.persist_hot() == 0


class TestSecurity:
    def test_expression_eval(self):
        assert security.visible("", ["a"])
        assert security.visible("admin", ["admin"])
        assert not security.visible("admin", ["user"])
        assert security.visible("admin&user", ["admin", "user"])
        assert not security.visible("admin&user", ["admin"])
        assert security.visible("admin|user", ["user"])
        assert security.visible("a&(b|c)", ["a", "c"])
        assert not security.visible("a&(b|c)", ["a"])
        with pytest.raises(ValueError):
            security.visible("a&&b", ["a"])

    def test_store_masks_rows(self):
        spec = SPEC + ",vis:String;geomesa.vis.field=vis"
        sft = FeatureType.from_spec("sec", spec)
        n = 40
        rng = np.random.default_rng(0)
        t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
        vis = np.array(["", "admin", "admin&ops", "user"] * 10)
        fc_cols = {
            "name": np.array(["n"] * n),
            "dtg": t0 + np.arange(n),
            "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n)),
            "vis": vis,
        }
        ids = [str(i) for i in range(n)]

        def build(auths):
            ds = DataStore(tile=64, auths=auths)
            ds.create_schema(FeatureType.from_spec("sec", spec))
            ds.write("sec", FeatureCollection.from_columns(ds.get_schema("sec"), ids, dict(fc_cols)))
            return ds

        admin = build(["admin"])
        out = admin.query("sec", "bbox(geom, -20, -20, 20, 20)")
        assert set(np.asarray(out.columns["vis"])) == {"", "admin"}
        everyone = build(None)  # security disabled
        assert len(everyone.query("sec")) == n
        public = build([])
        assert set(np.asarray(public.query("sec").columns["vis"])) == {""}

    def test_aggregates_respect_visibility(self):
        spec = SPEC + ",vis:String;geomesa.vis.field=vis"
        n = 8
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
        ds = DataStore(tile=64, auths=[])
        ds.create_schema(FeatureType.from_spec("sec", spec))
        ds.write(
            "sec",
            FeatureCollection.from_columns(
                ds.get_schema("sec"),
                [str(i) for i in range(n)],
                {
                    "name": np.array(["n"] * n),
                    "dtg": t0 + np.arange(n),
                    "geom": (np.linspace(-5, 5, n), np.zeros(n)),
                    "vis": np.array(["", "admin"] * 4),
                },
            ),
        )
        q = (
            "bbox(geom,-10,-10,10,10) AND dtg DURING "
            "2023-12-31T00:00:00Z/2024-01-02T00:00:00Z"
        )
        # every read surface sees only the 4 public rows
        assert len(ds.query("sec", q)) == 4
        assert ds.count("sec") == 4
        assert ds.estimate_count("sec", q) == 4
        assert ds.density("sec", q).sum() == 4
        (cnt,) = ds.stats_query("sec", "Count()", q, estimate=True)
        assert cnt.count == 4
        assert ds.bounds("sec", q) is not None


class TestViews:
    def _store(self, ids, xs):
        ds = DataStore(tile=64)
        sft = FeatureType.from_spec("s", SPEC)
        ds.create_schema(sft)
        t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
        n = len(ids)
        ds.write("s", FeatureCollection.from_columns(sft, ids, {
            "name": np.array(["n"] * n),
            "dtg": t0 + np.arange(n),
            "geom": (np.asarray(xs, dtype=np.float64), np.zeros(n)),
        }))
        return ds

    def test_merged_dedup(self):
        a = self._store(["1", "2"], [0.0, 1.0])
        b = self._store(["2", "3"], [5.0, 2.0])  # id 2 duplicated
        view = MergedView([a, b], "s")
        out = view.query("bbox(geom, -1, -1, 3, 1)")
        assert sorted(out.ids.tolist()) == ["1", "2", "3"]
        # id 2 came from store a (x=1), not store b (x=5)
        x = out.columns["geom"].x[out.ids.tolist().index("2")]
        assert x == 1.0
        assert view.count() == 3

    def test_routed(self):
        coarse = self._store(["c"], [0.0])
        fine = self._store(["f"], [0.0])
        from geomesa_tpu.filter.extract import extract_geometries, geometry_bounds

        def router(f):
            g = extract_geometries(f, "geom")
            if not g.values:
                return 0
            (x0, y0, x1, y1) = geometry_bounds(g)[0]
            return 1 if (x1 - x0) < 10 else 0  # small boxes -> fine store

        view = RoutedView([coarse, fine], "s", router)
        assert view.query("bbox(geom, -1, -1, 1, 1)").ids.tolist() == ["f"]
        assert view.query("bbox(geom, -50, -50, 50, 50)").ids.tolist() == ["c"]
