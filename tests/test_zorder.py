"""Curve math unit tests: split/combine round trips, golden vectors, zdiv.

Modeled on the reference's Z3Test / Z2Test / Z3RangeTest
(/root/reference/geomesa-z3/src/test/scala/.../curve, .../zorder/sfcurve).
"""

import numpy as np
import pytest

from geomesa_tpu.curve.zorder import Z2, Z3, zdiv


class TestZ3:
    def test_split_combine_roundtrip(self):
        rng = np.random.default_rng(0)
        vals = rng.integers(0, 1 << 21, size=10_000, dtype=np.uint64)
        assert np.array_equal(Z3.combine(Z3.split(vals)), vals)

    def test_index_decode_roundtrip(self):
        rng = np.random.default_rng(1)
        x = rng.integers(0, 1 << 21, size=10_000, dtype=np.uint64)
        y = rng.integers(0, 1 << 21, size=10_000, dtype=np.uint64)
        t = rng.integers(0, 1 << 21, size=10_000, dtype=np.uint64)
        z = Z3.index(x, y, t)
        dx, dy, dt = Z3.decode(z)
        assert np.array_equal(dx, x)
        assert np.array_equal(dy, y)
        assert np.array_equal(dt, t)

    def test_golden_interleave(self):
        # z(1,0,0) = 0b001, z(0,1,0) = 0b010, z(0,0,1) = 0b100
        assert int(Z3.index(1, 0, 0)) == 1
        assert int(Z3.index(0, 1, 0)) == 2
        assert int(Z3.index(0, 0, 1)) == 4
        assert int(Z3.index(1, 1, 1)) == 7
        # bit i of x lands at position 3i
        for i in range(21):
            assert int(Z3.index(1 << i, 0, 0)) == 1 << (3 * i)
            assert int(Z3.index(0, 1 << i, 0)) == 1 << (3 * i + 1)
            assert int(Z3.index(0, 0, 1 << i)) == 1 << (3 * i + 2)

    def test_ordering_locality(self):
        # consecutive cells along x within an aligned pair differ by 1
        assert int(Z3.index(3, 5, 7)) != int(Z3.index(3, 5, 6))

    def test_max_values(self):
        m = (1 << 21) - 1
        z = int(Z3.index(m, m, m))
        assert z == (1 << 63) - 1

    def test_scalar_and_array_agree(self):
        xs = np.array([5, 1000, 2**20], dtype=np.uint64)
        batched = Z3.index(xs, xs, xs)
        singles = [int(Z3.index(int(v), int(v), int(v))) for v in xs]
        assert [int(b) for b in batched] == singles


class TestZ2:
    def test_split_combine_roundtrip(self):
        rng = np.random.default_rng(2)
        vals = rng.integers(0, 1 << 31, size=10_000, dtype=np.uint64)
        assert np.array_equal(Z2.combine(Z2.split(vals)), vals)

    def test_index_decode_roundtrip(self):
        rng = np.random.default_rng(3)
        x = rng.integers(0, 1 << 31, size=10_000, dtype=np.uint64)
        y = rng.integers(0, 1 << 31, size=10_000, dtype=np.uint64)
        z = Z2.index(x, y)
        dx, dy = Z2.decode(z)
        assert np.array_equal(dx, x)
        assert np.array_equal(dy, y)

    def test_golden_interleave(self):
        assert int(Z2.index(1, 0)) == 1
        assert int(Z2.index(0, 1)) == 2
        assert int(Z2.index(3, 3)) == 15
        for i in range(31):
            assert int(Z2.index(1 << i, 0)) == 1 << (2 * i)
            assert int(Z2.index(0, 1 << i)) == 1 << (2 * i + 1)

    def test_max_values(self):
        m = (1 << 31) - 1
        assert int(Z2.index(m, m)) == (1 << 62) - 1


class TestZdiv:
    """Brute-force validation of LITMAX/BIGMIN on a small 2-D space."""

    @pytest.mark.parametrize("seed", range(5))
    def test_litmax_bigmin_bruteforce(self, seed):
        rng = np.random.default_rng(seed)
        bits = 5  # 5 bits/dim -> z in [0, 1024)
        for _ in range(50):
            x0, x1 = sorted(rng.integers(0, 1 << bits, 2).tolist())
            y0, y1 = sorted(rng.integers(0, 1 << bits, 2).tolist())
            zmin = int(Z2.index(x0, y0))
            zmax = int(Z2.index(x1, y1))
            # all z inside the box
            xs, ys = np.meshgrid(np.arange(x0, x1 + 1), np.arange(y0, y1 + 1))
            inside = np.sort(
                Z2.index(xs.ravel().astype(np.uint64), ys.ravel().astype(np.uint64)).astype(np.int64)
            )
            # pick zval strictly inside [zmin, zmax] but outside the box
            candidates = [
                z for z in range(zmin + 1, zmax) if z not in set(inside.tolist())
            ]
            if not candidates:
                continue
            zval = int(rng.choice(candidates))
            litmax, bigmin = zdiv(Z2, zmin, zmax, zval)
            expect_lit = inside[inside < zval]
            expect_big = inside[inside > zval]
            if len(expect_lit):
                assert litmax == int(expect_lit[-1]), (
                    f"litmax box=({x0},{y0})..({x1},{y1}) zval={zval}"
                )
            if len(expect_big):
                assert bigmin == int(expect_big[0]), (
                    f"bigmin box=({x0},{y0})..({x1},{y1}) zval={zval}"
                )
