"""Docs stay honest: every API, knob, metric and rule id they name is real.

The guides promise a reference user that each named call is real; this
pins the exact surface so a rename breaks the build, not the reader.

Knob and metric NAME checks run against the static-analysis registries
(geomesa_tpu.analysis.registries) — the same single source of truth
scripts/check.py enforces — instead of parallel hand-kept lists: the
analyzer guarantees every doc-cited name resolves (doc-unknown-name)
and every knob is documented (knob-undocumented); these tests add the
per-subsystem completeness direction (each doc cites every knob/metric
of its area) and that the AST registry agrees with the runtime
conf.REGISTRY."""

import functools
import inspect
import os
import re

_ROOT = os.path.join(os.path.dirname(__file__), "..")


@functools.lru_cache(maxsize=1)
def _registries():
    from geomesa_tpu.analysis.core import Project
    from geomesa_tpu.analysis.registries import Registries

    return Registries.of(Project.load(_ROOT))


def _area_names(prefix: str) -> tuple[list[str], list[str]]:
    """(knob names, metric names) of one geomesa.<area>. prefix, from
    the analyzer registries."""
    regs = _registries()
    knobs = sorted(k for k in regs.knobs.knobs if k.startswith(prefix))
    metrics = sorted(
        n for n in regs.metrics.names() if n.startswith(prefix)
    )
    return knobs, metrics


def _assert_documented(doc: str, names) -> None:
    text = open(os.path.join(_ROOT, "docs", doc)).read()
    missing = [n for n in names if n not in text]
    assert not missing, f"docs/{doc} does not cite: {missing}"


def _assert_runtime_declared(names) -> None:
    """The AST-extracted knob registry agrees with the runtime property
    tier (conf.REGISTRY): every name resolves to a live SystemProperty."""
    from geomesa_tpu import conf

    for name in names:
        assert name in conf.REGISTRY, name
        assert conf.REGISTRY[name].name == name


def test_migration_guide_apis_exist():
    from geomesa_tpu import process as P
    from geomesa_tpu import streaming as S
    from geomesa_tpu.audit import FileAuditWriter  # noqa: F401
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.parallel.mesh import make_multihost_mesh  # noqa: F401
    from geomesa_tpu.planning.hints import QueryHints
    from geomesa_tpu.sql import (  # noqa: F401
        FUNCTIONS,
        spatial_join,
        spatial_join_indexed,
        sql_query,
    )

    for m in [
        "write", "modify_features", "upsert", "delete_features", "age_off",
        "query", "query_many", "density", "stats_query", "bin_query",
        "bounds", "count", "explain", "stats_for", "analyze_stats",
    ]:
        assert hasattr(DataStore, m), m
    for fn in [
        "knn_search", "knn_many", "proximity_search", "route_search",
        "tube_select", "unique_values", "join_search", "point2point",
        "track_label", "date_offset", "bin_conversion", "arrow_conversion",
    ]:
        assert hasattr(P, fn), fn
    for c in ["StreamingFeatureCache", "FeatureStream", "LambdaStore"]:
        assert hasattr(S, c), c
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.sft import FeatureType

    assert hasattr(FeatureType, "from_spec")
    assert hasattr(FeatureCollection, "from_columns")
    assert len(FUNCTIONS) >= 83
    QueryHints(
        transforms=["a"], sort_by="x", offset=1, sample=0.5, sample_by="t",
        loose=True, timeout=1.0, reproject="EPSG:3857",
    )
    assert "limit" in inspect.signature(DataStore.query).parameters


def test_durability_doc_apis_exist():
    """docs/durability.md stays honest the same way: every durability/
    fault API it names is real."""
    from geomesa_tpu import fault
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.storage import persist
    from geomesa_tpu.streaming import LambdaStore

    for name in ("save", "load", "damage_report", "StoreCorruptionError",
                 "StoreHealth", "DamageRecord"):
        assert hasattr(persist, name), name
    for name in ("inject", "with_retries", "fault_point", "injector",
                 "InjectedCrash", "InjectedIOError", "chaos", "ChaosSpec"):
        assert hasattr(fault, name), name
    assert set(fault.KINDS) == {
        "io_error", "crash", "partial_write", "bit_flip", "latency",
    }
    assert isinstance(DataStore.store_health, property)
    for m in ("persist_hot", "checkpoint", "recover", "write", "delete",
              "expire"):
        assert hasattr(LambdaStore, m), m
    assert "on_damage" in inspect.signature(persist.load).parameters
    # the streaming WAL surface the doc's "Streaming WAL" section names
    from geomesa_tpu.streaming import WalConfig, WriteAheadLog

    for m in ("append", "sync", "replay", "checkpoint", "retire", "close"):
        assert hasattr(WriteAheadLog, m), m
    for f in ("sync", "sync_interval_ms", "segment_bytes"):
        assert f in WalConfig.__dataclass_fields__, f
    for p in ("wal", "wal_dir", "wal_config"):
        assert p in inspect.signature(LambdaStore.__init__).parameters, p
    for p in ("metrics", "rng"):
        assert p in inspect.signature(fault.with_retries).parameters, p
    for p in ("seed", "rate", "points", "kinds"):
        assert p in inspect.signature(fault.chaos).parameters, p


def test_migration_guide_dotted_names_resolve():
    """Every `process.X` / `streaming.X` / `sql.X` / `ds.X(...)` name the
    guide mentions in backticks resolves against the real modules."""
    import geomesa_tpu.process as P
    import geomesa_tpu.sql as Q
    import geomesa_tpu.streaming as S
    from geomesa_tpu.datastore import DataStore

    path = os.path.join(os.path.dirname(__file__), "..", "docs", "migration.md")
    text = open(path).read()
    mods = {"process": P, "streaming": S, "sql": Q}
    for mod, name in re.findall(r"`(process|streaming|sql)\.(\w+)", text):
        assert hasattr(mods[mod], name), f"{mod}.{name}"
    for name in re.findall(r"`ds\.(\w+)", text):
        assert hasattr(DataStore, name), f"ds.{name}"


def test_feature_expiry_user_data_key():
    """The guide's geomesa.feature.expiry claim: age_off with no ttl
    reads the schema key (reference age-off configuration)."""
    import numpy as np

    from geomesa_tpu import DataStore, FeatureCollection, FeatureType

    sft = FeatureType.from_spec("ev", "dtg:Date,*geom:Point:srid=4326")
    sft.user_data["geomesa.feature.expiry"] = "7 days"
    ds = DataStore()
    ds.create_schema(sft)
    now = np.datetime64("2024-02-01T00:00:00", "ms").astype(np.int64)
    t = np.array([now - 10 * 86_400_000, now - 86_400_000], dtype=np.int64)
    ds.write("ev", FeatureCollection.from_columns(
        sft, ["old", "new"], {"dtg": t, "geom": (np.zeros(2), np.zeros(2))}))
    removed = ds.age_off("ev", now_ms=int(now))
    assert removed == 1
    assert [str(i) for i in ds.query("ev", "INCLUDE").ids] == ["new"]

    from geomesa_tpu.datastore import parse_expiry_ms

    assert parse_expiry_ms("7 days") == 7 * 86_400_000
    assert parse_expiry_ms("24 hours") == 86_400_000
    assert parse_expiry_ms("30 minutes") == 1_800_000
    assert parse_expiry_ms("90 seconds") == 90_000
    assert parse_expiry_ms("1 week") == 7 * 86_400_000
    assert parse_expiry_ms("5000") == 5000
    assert parse_expiry_ms("dtg(2 days)") == 2 * 86_400_000
    assert parse_expiry_ms("dtg(2 days)", dtg_field="dtg") == 2 * 86_400_000
    import pytest

    with pytest.raises(ValueError, match="unparseable"):
        parse_expiry_ms("fortnight")
    with pytest.raises(ValueError, match="not the time attribute"):
        # attribute-based expiry on a non-default attribute must refuse,
        # never silently sweep by the wrong column
        parse_expiry_ms("updated(7 days)", dtg_field="dtg")
    with pytest.raises(ValueError, match="no ttl_ms"):
        ds2 = DataStore()
        s2 = FeatureType.from_spec("e2", "dtg:Date,*geom:Point:srid=4326")
        ds2.create_schema(s2)
        ds2.age_off("e2")


def test_serving_doc_apis_exist():
    """docs/serving.md stays honest the same way: every serving API,
    knob, metric, and dotted name it documents is real."""
    import inspect

    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.metrics import MetricsRegistry
    from geomesa_tpu.serving import (
        QueryScheduler, ServingConfig, ServingRejected,  # noqa: F401
    )

    assert hasattr(DataStore, "serve")
    for m in ("submit", "query", "start", "close", "closed", "window_s"):
        assert hasattr(QueryScheduler, m), m
    for f in ("window_ms", "queue_max", "batch_max"):
        assert f in ServingConfig.__dataclass_fields__, f
    assert "block" in inspect.signature(QueryScheduler.submit).parameters
    # every geomesa.serving.* knob and metric (analyzer registries, the
    # single source of truth) is declared at runtime and cited by the doc
    knobs, metrics = _area_names("geomesa.serving.")
    assert len(knobs) >= 3 and len(metrics) >= 6, (knobs, metrics)
    _assert_runtime_declared(knobs)
    _assert_documented("serving.md", knobs + metrics)
    # the documented instrument kinds render through the registry,
    # including the histogram exposition the doc points operators at
    reg = MetricsRegistry()
    by_name = _registries().metrics.by_name()
    for n in metrics:
        kind = by_name[n][0].instrument
        if kind == "counter":
            reg.counter(n)
        elif kind == "gauge":
            reg.gauge(n, 0.0)
        elif kind == "histogram":
            reg.observe(n, 0.01)
        else:
            reg.timer_update(n, 0.01)
    text = reg.render_prometheus()
    assert "geomesa_serving_shed 1" in text
    # queue wait is a live histogram (docs/observability.md): proper
    # _bucket{le=...}/_sum/_count families
    assert 'geomesa_serving_queue_wait_seconds_bucket{le="' in text
    assert "geomesa_serving_queue_wait_seconds_count 1" in text
    # every `ds.X` / `sched.X` the guide mentions in backticks resolves
    path = os.path.join(os.path.dirname(__file__), "..", "docs", "serving.md")
    text = open(path).read()
    for name in re.findall(r"`ds\.(\w+)", text):
        assert hasattr(DataStore, name), f"ds.{name}"
    for name in re.findall(r"`sched\.(\w+)", text):
        assert hasattr(QueryScheduler, name), f"sched.{name}"


def test_data_plane_doc_honest():
    """docs/serving.md "The data plane" stays honest: the server and
    client APIs, the request/response headers, the status-code knobs
    and every geomesa.serve.* / geomesa.tenant.* name it documents are
    real, declared at runtime, and cited by both serving.md and the
    config.md knob index."""
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.serving import (
        DataClient, DataServer, ServeError, TenantRegistry,
    )
    from geomesa_tpu.serving import http as serve_http
    from geomesa_tpu.streaming.replica import ReplicaStore
    from geomesa_tpu.streaming.store import LambdaStore

    # serve(port=...) mounts the data plane on every tier the doc names
    for cls in (DataStore, LambdaStore, ReplicaStore):
        assert "port" in inspect.signature(cls.serve).parameters, cls
    for m in ("query", "ingest", "tenants", "health", "metrics_text",
              "request"):
        assert hasattr(DataClient, m), m
    for m in ("handle_get", "handle_post", "start", "close", "url",
              "port", "tenants"):
        assert hasattr(DataServer, m), m
    for m in ("tenant_of", "configure", "report", "weights", "queue_cap"):
        assert hasattr(TenantRegistry, m), m
    err = ServeError(429, "shed", retry_after=0.05)
    assert err.status == 429 and err.retry_after == 0.05
    assert hasattr(ReplicaStore, "tail_disk")
    # the documented headers are the module's constants, verbatim
    text = open(
        os.path.join(_ROOT, "docs", "serving.md")
    ).read()
    for h in (serve_http.AUTHS_HEADER, serve_http.TENANT_HEADER,
              serve_http.STALENESS_HEADER, serve_http.LEADER_HEADER,
              serve_http.ROWS_HEADER):
        assert h in text, h
    # knob/metric completeness, both directions, from the analyzer
    # registries (the single source of truth)
    serve_knobs, serve_metrics = _area_names("geomesa.serve.")
    tenant_knobs, tenant_metrics = _area_names("geomesa.tenant.")
    assert len(serve_knobs) == 4, serve_knobs
    assert len(tenant_knobs) == 3, tenant_knobs
    assert len(serve_metrics) >= 3, serve_metrics
    assert len(tenant_metrics) >= 4, tenant_metrics
    _assert_runtime_declared(serve_knobs + tenant_knobs)
    _assert_documented(
        "serving.md",
        serve_knobs + tenant_knobs + serve_metrics + tenant_metrics,
    )
    _assert_documented("config.md", serve_knobs + tenant_knobs)


def test_caching_doc_apis_exist():
    """docs/caching.md stays honest the same way: every cache API,
    knob, and metric name it documents is real."""
    from geomesa_tpu.cache import (  # noqa: F401
        BUCKET_MS,
        CacheConfig,
        GenerationTracker,
        KeyRange,
        QueryCache,
        ResultCache,
        TileAggregateCache,
        fingerprint,
        key_range_of,
        mutation_range,
    )
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.filter.predicates import canonical_key  # noqa: F401
    from geomesa_tpu.planning.hints import QueryHints
    from geomesa_tpu.storage import persist

    import inspect

    assert "cache" in inspect.signature(DataStore.__init__).parameters
    assert hasattr(DataStore, "attach_cache")
    # persist.load forwards store kwargs (including cache=) to DataStore
    assert any(
        p.kind is inspect.Parameter.VAR_KEYWORD
        for p in inspect.signature(persist.load).parameters.values()
    )
    QueryHints(cache="bypass")
    QueryHints(cache="pin")
    for m in ("fingerprint_plan", "key_range", "on_mutation",
              "on_schema_dropped", "on_quarantine", "stats"):
        assert hasattr(QueryCache, m), m
    # every geomesa.cache.* knob and metric (analyzer registries) is
    # declared at runtime and cited by the doc
    knobs, metrics = _area_names("geomesa.cache.")
    assert len(knobs) >= 6 and len(metrics) >= 12, (knobs, metrics)
    _assert_runtime_declared(knobs)
    _assert_documented("caching.md", knobs + metrics)


def test_ingest_doc_apis_exist():
    """docs/ingest.md stays honest the same way: every pipeline API,
    knob, metric, and fault point it documents is real."""
    import inspect

    from geomesa_tpu.ingest import (  # noqa: F401
        BulkLoader,
        IngestError,
        IngestResult,
        PipelineConfig,
        SortRun,
        ingest_files,
        merge_runs,
        plan_splits,
        shard_runs,
    )
    from geomesa_tpu.metrics import MetricsRegistry

    for m in ("put", "close", "abort"):
        assert hasattr(BulkLoader, m), m
    for f in ("workers", "queue_depth", "chunk_rows", "merge_min_bins"):
        assert f in PipelineConfig.__dataclass_fields__, f
    assert hasattr(PipelineConfig, "from_properties")
    for f in ("written", "errors", "splits", "split_errors", "stage_seconds"):
        assert f in IngestResult.__dataclass_fields__, f
    for attr in ("split_index", "worker_traceback"):
        assert attr in inspect.signature(IngestError.__init__).parameters
    assert "workers" in inspect.signature(ingest_files).parameters
    # every geomesa.ingest.* knob and metric (analyzer registries) is
    # declared at runtime and cited by the doc; the span-rows compaction
    # knob the doc's memory model leans on rides along
    knobs, metrics = _area_names("geomesa.ingest.")
    assert len(knobs) >= 4 and len(metrics) >= 4, (knobs, metrics)
    _assert_runtime_declared(knobs + ["geomesa.tpu.compact.span.rows"])
    _assert_documented(
        "ingest.md", knobs + metrics + ["geomesa.tpu.compact.span.rows"]
    )
    # the documented metric names render, including the f-string stage
    # timer family the registry records as a geomesa.ingest.* prefix
    assert "geomesa.ingest." in _registries().metrics.prefixes()
    by_name = _registries().metrics.by_name()
    reg = MetricsRegistry()
    for n in metrics:
        kind = by_name[n][0].instrument
        if kind == "counter":
            reg.counter(n)
        elif kind == "gauge":
            reg.gauge(n, 0.0)
        else:
            reg.timer_update(n, 0.0)
    for t in ("parse", "keys", "sort", "commit", "finalize"):
        reg.timer_update(f"geomesa.ingest.{t}", 0.0)
    assert "geomesa_ingest_queue_full 1" in reg.render_prometheus()
    # the documented fault points exist in the pipeline source (the fault
    # registry is pattern-based, so presence is a source-level contract)
    import geomesa_tpu.ingest.pipeline as pl
    import geomesa_tpu.ingest.splits as sp

    src = inspect.getsource(pl) + inspect.getsource(sp)
    for point in ("ingest.split.read", "ingest.parse", "ingest.keys",
                  "ingest.sort", "ingest.commit", "ingest.finalize"):
        assert point in src, point
    # `ds.compact` / `ds.write` mentioned by the doc resolve, and compact
    # takes the presorted perms the pipeline feeds it
    from geomesa_tpu.datastore import DataStore

    assert "presorted" in inspect.signature(DataStore.compact).parameters
    # the doc's dotted `ds.X` mentions resolve
    import re as _re

    path = os.path.join(os.path.dirname(__file__), "..", "docs", "ingest.md")
    text = open(path).read()
    for name in _re.findall(r"`ds\.(\w+)", text):
        assert hasattr(DataStore, name), f"ds.{name}"


def test_fused_coverage_doc_honest():
    """docs/serving.md "Fused coverage" + PERF.md §12 stay honest: every
    constant, API and file the matrix names is real and matches the
    code, and BENCH_FUSED.json (when present) actually shows the fused
    path faster with bit-identical results, as both docs claim."""
    import json

    from geomesa_tpu.scan import block_kernels as bk
    from geomesa_tpu.storage.table import IndexTable
    from geomesa_tpu.parallel.dtable import DistributedIndexTable

    root = os.path.join(os.path.dirname(__file__), "..")
    text = open(os.path.join(root, "docs", "serving.md")).read()
    assert "Fused coverage" in text

    # the documented E ladder is the code's E ladder, and every
    # pack_edges polygon fits a fused bucket (the matrix's 256-edge row)
    assert f"FUSED_E_BUCKETS = {bk.FUSED_E_BUCKETS}" in text
    assert bk.FUSED_E_BUCKETS[-1] == bk.E_BUCKETS[-1]
    assert bk.fused_e_bucket(bk.E_BUCKETS[-1]) == bk.FUSED_E_BUCKETS[-1]

    # documented APIs: the fused seam, the wide-only chunk rule, warmup,
    # and the mesh override the matrix's shard_map row relies on
    for name in ("scan_submit_many", "_submit_fused_chunk", "fused_slots",
                 "warmup"):
        assert hasattr(IndexTable, name), name
    assert "skip_inner_plane" in text and hasattr(bk, "skip_inner_plane")
    assert (
        DistributedIndexTable._submit_fused_chunk
        is not IndexTable._submit_fused_chunk
    )
    # kernel-level contract the matrix documents: block_scan_multi takes
    # the edge stack + per-slot selector
    import inspect

    sig = inspect.signature(bk.block_scan_multi).parameters
    for p in ("edges", "spip", "n_edges"):
        assert p in sig, p

    # the bench the docs point at exists and is registered (source-level
    # contract, like the ingest fault points — bench.py is not a package)
    bench_src = open(os.path.join(root, "bench.py")).read()
    assert "def config_fused" in bench_src
    assert '"fused": config_fused' in bench_src
    assert "BENCH_FUSED.json" in bench_src
    assert "BENCH_FUSED.json" in text

    # honesty of the recorded numbers: fused faster than both baselines,
    # results identical, on every non-skipped row
    path = os.path.join(root, "BENCH_FUSED.json")
    if os.path.exists(path):
        payload = json.load(open(path))
        timed = [r for r in payload["rows"] if "speedup" in r]
        assert timed, "BENCH_FUSED.json has no timed rows"
        for r in timed:
            assert r["identical"] is True, r["scenario"]
            assert r["fused_ms"] < r["per_query_ms"], r["scenario"]
            assert r["speedup"] >= 2.0, r["scenario"]  # the round-6 bar


def test_joins_doc_honest():
    """docs/joins.md + PERF.md §13 stay honest: every API, knob, metric,
    constant and artifact the raster/adaptive-join doc names is real, and
    BENCH_PIP_JOIN.json (when present) actually shows the raster path
    faster with bit-identical results, as the doc claims."""
    import inspect
    import json

    from geomesa_tpu import conf
    from geomesa_tpu import geometry as geo
    from geomesa_tpu.filter import raster as fr
    from geomesa_tpu.index.api import ScanConfig
    from geomesa_tpu.metrics import MetricsRegistry
    from geomesa_tpu.scan import block_kernels as bk

    root = os.path.join(os.path.dirname(__file__), "..")
    text = open(os.path.join(root, "docs", "joins.md")).read()

    # the raster build surface + the conservative-margin contract
    assert hasattr(fr, "build_raster") and hasattr(fr, "raster_for")
    for m in ("zranges", "pack_block", "classify_points", "cell_counts",
              "boundary_fraction", "decided_fraction"):
        assert hasattr(fr.RasterApprox, m), m
    assert hasattr(geo, "classify_raster_cells")
    for c in ("RASTER_FULL", "RASTER_PARTIAL", "RASTER_OUT"):
        assert hasattr(geo, c), c
    assert "RASTER_MARGIN" in text and fr.RASTER_MARGIN > 0

    # kernel tier: the rast config field, the R ladder, the fused operand
    assert "rast" in ScanConfig.__dataclass_fields__
    assert hasattr(bk, "FUSED_R_BUCKETS") and hasattr(bk, "R_BUCKETS")
    sig = inspect.signature(bk.block_scan_multi).parameters
    for p in ("rasts", "n_rints"):
        assert p in sig, p
    sig1 = inspect.signature(bk.block_scan).parameters
    for p in ("rast", "n_rints"):
        assert p in sig1, p

    # every geomesa.raster.* / geomesa.join.* knob and metric (analyzer
    # registries) is declared at runtime and cited by this doc, at the
    # doc table's defaults
    raster_knobs, raster_metrics = _area_names("geomesa.raster.")
    join_knobs, join_metrics = _area_names("geomesa.join.")
    assert len(raster_knobs) >= 5 and len(join_knobs) >= 4
    assert len(join_metrics) >= 6, join_metrics
    _assert_runtime_declared(raster_knobs + join_knobs)
    _assert_documented(
        "joins.md",
        raster_knobs + join_knobs + raster_metrics + join_metrics,
    )
    for name, default in [
        ("geomesa.raster.enabled", True),
        ("geomesa.raster.max.cells", 16384),
        ("geomesa.raster.min.edges", 8),
        ("geomesa.raster.kernel.intervals", 16),
        ("geomesa.raster.residue", "host"),
        ("geomesa.join.adaptive", True),
        ("geomesa.join.sample", 512),
        ("geomesa.join.broad.fraction", 0.25),
        ("geomesa.join.in.selectivity", 0.5),
    ]:
        assert conf.REGISTRY[name].default == default, name

    # join surfaces: strategy args + the counter read path the doc names
    from geomesa_tpu.process.join import join_search
    from geomesa_tpu.sql.join import spatial_join, spatial_join_indexed

    assert "strategy" in inspect.signature(spatial_join).parameters
    assert "metrics" in inspect.signature(spatial_join_indexed).parameters
    for p in ("explain", "metrics"):
        assert p in inspect.signature(join_search).parameters, p
    reg = MetricsRegistry()
    for c in join_metrics:
        reg.counter(c)
    assert reg.counter_value("geomesa.join.in_cap_fallback") == 1

    # the bench + gate the doc points at exist and are registered
    bench_src = open(os.path.join(root, "bench.py")).read()
    assert "def config_pip_join" in bench_src
    assert '"pip_join": config_pip_join' in bench_src
    assert os.path.exists(os.path.join(root, "scripts", "bench_gate.py"))
    assert "BENCH_PIP_JOIN.json" in text

    # honesty of the recorded numbers: raster faster than exact,
    # bit-identity computed in-bench, the >= 5x acceptance on the PIP
    # batch and the polygon join
    path = os.path.join(root, "BENCH_PIP_JOIN.json")
    if os.path.exists(path):
        payload = json.load(open(path))
        rows = {r["scenario"]: r for r in payload["rows"]}
        pip = rows["z2_polygon_pip_batch"]
        assert pip["identical"] is True
        assert pip["speedup"] >= 5.0
        assert pip["raster_ms_per_q"] < pip["exact_ms_per_q"]
        join = rows["z2_polygon_join"]
        assert join["identical"] is True
        assert join["speedup"] >= 5.0


def test_analysis_rule_catalog_documented():
    """docs/analysis.md stays honest: every shipped rule id appears in
    its catalog, and the catalog names no phantom rules."""
    from geomesa_tpu import analysis

    text = open(os.path.join(_ROOT, "docs", "analysis.md")).read()
    ids = {r.id for r in analysis.ALL_RULES} | {"parse-error"}
    for rid in sorted(ids):
        assert f"`{rid}`" in text, f"rule {rid!r} missing from docs/analysis.md"
    for rid in re.findall(r"^\| `([a-z][a-z0-9-]+)` \|", text, re.MULTILINE):
        assert rid in ids, f"docs/analysis.md catalogs unknown rule {rid!r}"


def test_streaming_doc_honest():
    """docs/streaming.md: every API it names is real, and it cites every
    geomesa.stream.* knob and metric (the per-area completeness
    direction; name VALIDITY is analyzer-checked by doc-unknown-name)."""
    from geomesa_tpu import streaming as S
    from geomesa_tpu.datastore import DataStore

    for name in ("StreamingFeatureCache", "StreamFlusher", "StreamConfig",
                 "LambdaStore", "FeatureStream"):
        assert hasattr(S, name), name
    for m in ("write", "flush", "persist_hot", "checkpoint", "query",
              "count", "serve", "close"):
        assert hasattr(S.LambdaStore, m), m
    for m in ("upsert", "delete", "expire", "evict", "snapshot_rows",
              "query_shadow"):
        assert hasattr(S.StreamingFeatureCache, m), m
    assert hasattr(S.StreamFlusher, "flush")
    assert hasattr(DataStore, "fold_upsert")
    assert hasattr(DataStore, "id_exists_mask")
    knobs, metrics = _area_names("geomesa.stream.")
    assert len(knobs) >= 5, knobs
    _assert_documented("streaming.md", knobs + metrics)
    _assert_documented("config.md", knobs)
    _assert_runtime_declared(knobs)
    # the stage-timer family (an f-string prefix) is cited as a family
    text = open(os.path.join(_ROOT, "docs", "streaming.md")).read()
    assert "geomesa.stream.*" in text


def test_concurrency_doc_honest():
    """docs/concurrency.md stays honest BOTH directions, derived from
    the LOCKS registry (the knob/metric/fault convention): every
    registered lock appears in the doc's table with its exact rank and
    hot flag, the table names no phantom locks, and every witness API /
    knob the doc leans on is real."""
    import inspect

    from geomesa_tpu import conf, lockwitness
    from geomesa_tpu.analysis.lockmodel import (
        DECLARED_BLOCKING, DECLARED_EDGES, LOCKS,
    )

    text = open(os.path.join(_ROOT, "docs", "concurrency.md")).read()
    # parse the registry table: | `Class.attr` | rank | hot? | guards |
    doc_rows = {}
    for line in text.splitlines():
        m = re.match(r"^\| `([\w.]+)` \| (\d+) \| (hot)? ?\|", line)
        if m:
            doc_rows[m.group(1)] = (int(m.group(2)), bool(m.group(3)))
    assert doc_rows, "docs/concurrency.md lock table not found"
    for name, d in sorted(LOCKS.items()):
        assert name in doc_rows, f"LOCKS entry {name} missing from the doc"
        assert doc_rows[name] == (d.rank, d.hot), (
            f"{name}: doc says {doc_rows[name]}, registry says "
            f"{(d.rank, d.hot)}"
        )
    for name in doc_rows:
        assert name in LOCKS, f"doc table names phantom lock {name!r}"
    # guarded fields the table cites are the registry's
    for name, d in LOCKS.items():
        for f in d.fields:
            assert f"`{f}`" in text or f in text, (name, f)
    # the witness surface the doc describes is real
    for fn in ("witness", "enable", "disable", "dump", "note_blocking",
               "held_locks"):
        assert hasattr(lockwitness, fn), fn
    for m in ("cycle", "snapshot", "reset", "note_acquire"):
        assert hasattr(lockwitness.WitnessReport, m), m
    assert "path" in inspect.signature(lockwitness.dump).parameters
    # env gate mapping: the documented GEOMESA_TPU_LOCK_WITNESS really
    # is the knob's env key, and both knobs resolve at runtime
    assert conf.LOCK_WITNESS.env_key == "GEOMESA_TPU_LOCK_WITNESS"
    assert "GEOMESA_TPU_LOCK_WITNESS" in text
    assert conf.REGISTRY["geomesa.tpu.lock.witness"].default is False
    assert conf.REGISTRY["geomesa.tpu.lock.witness.artifact"].default == (
        "/tmp/lock_witness.json"
    )
    # declared exceptions carry justifications (they are doc-adjacent:
    # each is a visible, accepted design cost)
    for a, b, why in DECLARED_EDGES:
        assert why and a in LOCKS and b in LOCKS
    for lock, pat, why in DECLARED_BLOCKING:
        assert why and lock in LOCKS and pat


def test_observability_doc_honest():
    """docs/observability.md stays honest the registry way: every
    obs/tracing/SLO API it names is real, every geomesa.obs.* knob and
    metric is declared at runtime and cited by the doc (and the knobs
    by config.md), and the documented histogram exposition renders."""
    import inspect

    import pytest

    from geomesa_tpu import obs
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.metrics import HIST_EDGES, Histogram, MetricsRegistry
    from geomesa_tpu.obs.trace import NULL_SPAN  # noqa: F401

    for name in ("Span", "Trace", "TraceBuffer", "Tracer", "SloObjective",
                 "SloTracker", "default_objectives", "install",
                 "phase_breakdown", "span", "tracer"):
        assert hasattr(obs, name), name
    for m in ("dump_trace", "slow_queries", "attach_slo", "slo_report"):
        assert hasattr(DataStore, m), m
    assert hasattr(DataStore, "slo")
    for m in ("begin", "end", "trace", "span", "activate", "add_span",
              "dump", "slow_queries", "traces", "reset", "armed"):
        assert hasattr(obs.Tracer, m), m
    for m in ("observe", "histogram_quantile"):
        assert hasattr(MetricsRegistry, m), m
    for f in ("name", "metric", "quantile", "threshold_s", "budget"):
        assert f in obs.SloObjective.__dataclass_fields__, f
    assert "objectives" in inspect.signature(
        DataStore.attach_slo
    ).parameters
    # the documented bucket ladder: sqrt-2 growth from 1 µs, 64 buckets
    assert len(HIST_EDGES) == 64 and HIST_EDGES[0] == 1e-6
    assert HIST_EDGES[2] / HIST_EDGES[0] == pytest.approx(2.0)
    assert Histogram().quantile(0.99) == 0.0
    # every geomesa.obs.* knob/metric resolves at runtime and is cited
    knobs, metrics = _area_names("geomesa.obs.")
    assert len(knobs) >= 12 and len(metrics) >= 3, (knobs, metrics)
    _assert_runtime_declared(knobs)
    _assert_documented("observability.md", knobs + metrics)
    _assert_documented("config.md", knobs)
    # the ops plane (docs/observability.md "The ops plane"): the APIs
    # and endpoints the doc tables promise are real
    for name in ("OpsServer", "TelemetryRecorder", "HealthMonitor",
                 "EstimateAccuracy", "ops_report", "stats_payload",
                 "error_factor"):
        assert hasattr(obs, name), name
    for m in ("serve_ops", "close", "ops", "accuracy"):
        assert hasattr(DataStore, m), m
    for m in ("start", "close", "handle", "port", "url", "closed"):
        assert hasattr(obs.OpsServer, m), m
    for m in ("sample", "series", "start", "stop"):
        assert hasattr(obs.TelemetryRecorder, m), m
    assert hasattr(obs.HealthMonitor, "evaluate")
    for m in ("record", "report", "stale", "reset", "sample_count"):
        assert hasattr(obs.EstimateAccuracy, m), m
    assert hasattr(obs.Tracer, "chrome_payload")
    import geomesa_tpu.obs.ops as ops_mod

    doc_text = open(os.path.join(_ROOT, "docs", "observability.md")).read()
    for endpoint in ("/metrics", "/health", "/stats", "/debug/slow",
                     "/debug/trace", "/debug/vars", "/debug/audit"):
        assert endpoint in doc_text, endpoint
        assert endpoint in inspect.getsource(ops_mod.OpsRoutes.handle), endpoint
    # the route table is shared: both the ops server and the data plane
    # mount it (docs/serving.md "The data plane")
    assert hasattr(ops_mod.OpsServer, "routes") or "OpsRoutes" in (
        inspect.getsource(ops_mod.OpsServer.__init__)
    )
    # every documented health reason code is a literal the monitor adds
    monitor_src = inspect.getsource(ops_mod.HealthMonitor.evaluate)
    for code in ("store.quarantine", "wal.needs_recovery", "slo.breach",
                 "hot.occupancy", "scheduler.shedding", "scheduler.queue",
                 "scheduler.saturated", "standing.drops", "stats.stale",
                 "replica.staleness", "replica.ship.giveup"):
        assert code in doc_text, code
        assert code in monitor_src, code
    # estimate accountability: the geomesa.plan.* namespace is complete
    # both directions in both docs
    plan_knobs, plan_metrics = _area_names("geomesa.plan.")
    assert len(plan_knobs) == 4 and len(plan_metrics) >= 2, (
        plan_knobs, plan_metrics,
    )
    _assert_runtime_declared(plan_knobs)
    _assert_documented("observability.md", plan_knobs + plan_metrics)
    _assert_documented("config.md", plan_knobs)
    from geomesa_tpu.planning.planner import QueryPlan

    for f in ("estimated_rows", "actual_rows"):
        assert f in QueryPlan.__dataclass_fields__, f
    # the histogram metrics the doc tables promise render as histograms
    reg = MetricsRegistry()
    for n in ("geomesa.query.scan", "geomesa.serving.queue_wait",
              "geomesa.stream.fold.slice", "geomesa.stream.wal.fsync"):
        reg.observe(n, 0.01)
    text = reg.render_prometheus()
    for base in ("geomesa_query_scan", "geomesa_serving_queue_wait",
                 "geomesa_stream_fold_slice", "geomesa_stream_wal_fsync"):
        assert f"# TYPE {base}_seconds histogram" in text
        assert f'{base}_seconds_bucket{{le="+Inf"}} 1' in text
    # every `ds.X` the guide mentions in backticks resolves
    path = os.path.join(_ROOT, "docs", "observability.md")
    doc = open(path).read()
    for name in re.findall(r"`ds\.(\w+)", doc):
        assert hasattr(DataStore, name), f"ds.{name}"


def test_standing_doc_honest():
    """docs/standing.md stays honest the registry way: every standing
    API it names is real, every geomesa.standing.* knob and metric is
    declared at runtime and cited by the doc (knobs by config.md too),
    the fault points exist in the source, and the documented bench +
    gate wiring is real."""
    import inspect

    from geomesa_tpu import process as P
    from geomesa_tpu import streaming as S
    from geomesa_tpu.metrics import MetricsRegistry

    for name in ("Subscription", "SubscriptionIndex", "StandingConfig",
                 "StandingQueryEngine", "WindowSpec", "WindowedAggregator",
                 "AlertQueue"):
        assert hasattr(S, name), name
    for m in ("standing", "subscribe", "unsubscribe"):
        assert hasattr(S.LambdaStore, m), m
    for m in ("register", "unregister", "route", "kernel_block",
              "register_geofences", "subscription_ids"):
        assert hasattr(S.SubscriptionIndex, m), m
    for m in ("on_batch", "match_points", "register", "add_window",
              "attach_flusher"):
        assert hasattr(S.StandingQueryEngine, m), m
    for m in ("accept_rows", "value", "windows", "partials"):
        assert hasattr(S.WindowedAggregator, m), m
    for m in ("put_many", "drain"):
        assert hasattr(S.AlertQueue, m), m
    for fn in ("standing_proximity", "standing_tube"):
        assert hasattr(P, fn), fn
    # the kernel seam the doc names: segment-level packing + the fused
    # multi-scan's PIP leg
    from geomesa_tpu.scan import block_kernels as bk

    assert hasattr(bk, "pack_edge_segments")
    sig = inspect.signature(bk.block_scan_multi).parameters
    for p in ("edges", "spip", "n_edges"):
        assert p in sig, p
    # every geomesa.standing.* knob/metric resolves at runtime and is
    # cited by the doc; knobs ride config.md's complete index too
    knobs, metrics = _area_names("geomesa.standing.")
    assert len(knobs) >= 5 and len(metrics) >= 10, (knobs, metrics)
    _assert_runtime_declared(knobs)
    _assert_documented("standing.md", knobs + metrics)
    _assert_documented("config.md", knobs)
    # the SLO knob the delivery section leans on
    _assert_runtime_declared(["geomesa.obs.slo.standing.p99.ms"])
    _assert_documented("standing.md", ["geomesa.obs.slo.standing.p99.ms"])
    # documented fault points exist at source level (the registry is
    # pattern-based, like the ingest fault points)
    import geomesa_tpu.streaming.standing as st

    src = inspect.getsource(st)
    for point in ("standing.match", "standing.deliver"):
        assert point in src, point
    for span in ("standing.route", "standing.match", "standing.deliver"):
        assert span in src, span
    # the documented metric kinds render through the registry
    by_name = _registries().metrics.by_name()
    reg = MetricsRegistry()
    for n in metrics:
        kind = by_name[n][0].instrument
        if kind == "counter":
            reg.counter(n)
        elif kind == "gauge":
            reg.gauge(n, 1.0)
        elif kind == "histogram":
            reg.observe(n, 0.01)
        else:
            reg.timer_update(n, 0.01)
    text = reg.render_prometheus()
    assert "geomesa_standing_subscriptions 1" in text
    assert 'geomesa_standing_latency_seconds_bucket{le="' in text
    # bench + gate wiring (source-level contract, like config_fused)
    bench_src = open(os.path.join(_ROOT, "bench.py")).read()
    assert "def config_standing" in bench_src
    assert '"standing": config_standing' in bench_src
    assert "BENCH_GEOFENCE.json" in bench_src
    gate_src = open(
        os.path.join(_ROOT, "scripts", "bench_gate.py")
    ).read()
    assert "standing_geofence" in gate_src
    doc = open(os.path.join(_ROOT, "docs", "standing.md")).read()
    assert "BENCH_GEOFENCE.json" in doc
    # every `lam.X` / `engine.X` the doc mentions in backticks resolves
    for name in re.findall(r"`lam\.(\w+)", doc):
        assert hasattr(S.LambdaStore, name), f"lam.{name}"
    for name in re.findall(r"`engine\.(\w+)", doc):
        assert hasattr(S.StandingQueryEngine, name), f"engine.{name}"


def test_replication_doc_honest():
    """docs/replication.md stays honest the registry way: every
    replication API it names is real, every geomesa.replica.* knob and
    metric is declared at runtime and cited by the doc (knobs by
    config.md too), the fault points and fencing hooks exist in the
    source, and the documented bench + gate wiring is real."""
    import inspect

    from geomesa_tpu import streaming as S
    from geomesa_tpu.metrics import MetricsRegistry

    for name in ("SegmentShipper", "ReplicaStore", "PipeTransport",
                 "SocketTransport"):
        assert hasattr(S, name), name
    for m in ("attach", "detach", "pump", "start", "stop",
              "gave_up_report"):
        assert hasattr(S.SegmentShipper, m), m
    for m in ("poll", "drain", "start", "stop", "promote", "query",
              "staleness_ms", "close"):
        assert hasattr(S.ReplicaStore, m), m
    from geomesa_tpu.streaming.replica import ReplicaError, StaleRead

    assert issubclass(StaleRead, ReplicaError)
    # the WAL-side shipping hooks the doc leans on
    from geomesa_tpu.streaming.wal import WriteAheadLog

    for m in ("ship_state", "log_term"):
        assert hasattr(WriteAheadLog, m), m
    assert isinstance(WriteAheadLog.term, property)
    # every geomesa.replica.* knob/metric resolves at runtime and is
    # cited by the doc; knobs ride config.md's complete index too
    knobs, metrics = _area_names("geomesa.replica.")
    assert len(knobs) >= 4 and len(metrics) >= 8, (knobs, metrics)
    _assert_runtime_declared(knobs)
    _assert_documented("replication.md", knobs + metrics)
    _assert_documented("config.md", knobs)
    # the staleness SLO knob the bounded-staleness section leans on
    _assert_runtime_declared(["geomesa.obs.slo.replica.staleness.p99.ms"])
    _assert_documented(
        "replication.md", ["geomesa.obs.slo.replica.staleness.p99.ms"]
    )
    # documented fault points exist at source level
    import geomesa_tpu.streaming.replica as rp

    src = inspect.getsource(rp)
    for point in ("replica.ship.segment", "replica.apply",
                  "replica.promote", "replica.fence"):
        assert point in src, point
    # the replay-progress gauge rides the recover() callback
    from geomesa_tpu.streaming.store import LambdaStore

    assert "on_progress" in inspect.signature(
        LambdaStore.recover
    ).parameters
    # the documented metric kinds render through the registry
    by_name = _registries().metrics.by_name()
    reg = MetricsRegistry()
    for n in metrics:
        kind = by_name[n][0].instrument
        if kind == "counter":
            reg.counter(n)
        elif kind == "gauge":
            reg.gauge(n, 1.0)
        elif kind == "histogram":
            reg.observe(n, 0.01)
        else:
            reg.timer_update(n, 0.01)
    text = reg.render_prometheus()
    assert 'geomesa_replica_staleness_ms_seconds_bucket{le="' in text
    # bench + gate wiring (source-level contract, like config_standing)
    bench_src = open(os.path.join(_ROOT, "bench.py")).read()
    assert "def config_replica" in bench_src
    assert '"replica": config_replica' in bench_src
    assert "BENCH_REPLICA.json" in bench_src
    gate_src = open(
        os.path.join(_ROOT, "scripts", "bench_gate.py")
    ).read()
    assert "BENCH_REPLICA" in gate_src
    doc = open(os.path.join(_ROOT, "docs", "replication.md")).read()
    assert "BENCH_REPLICA.json" in doc
    # every `fol.X` / `ship.X` the doc mentions in backticks resolves
    for name in re.findall(r"`fol\.(\w+)", doc):
        assert hasattr(S.ReplicaStore, name), f"fol.{name}"
    for name in re.findall(r"`ship\.(\w+)", doc):
        assert hasattr(S.SegmentShipper, name), f"ship.{name}"


def test_config_doc_lists_every_knob():
    """docs/config.md is the complete operator-facing knob index (the
    knob-undocumented rule's backstop): every declared SystemProperty
    appears there by full name."""
    regs = _registries()
    assert len(regs.knobs.knobs) >= 25
    text = open(os.path.join(_ROOT, "docs", "config.md")).read()
    missing = [n for n in sorted(regs.knobs.knobs) if n not in text]
    assert not missing, f"docs/config.md does not list: {missing}"


def test_tiles_doc_honest():
    """docs/tiles.md stays honest the registry way: every tile API it
    names is real, every geomesa.tiles.* knob and metric is declared
    at runtime and cited by the doc (knobs by config.md too), the
    fault points exist in the source, and the documented endpoint,
    CLI, bench and gate wiring is real."""
    import inspect

    from geomesa_tpu import cli
    from geomesa_tpu.cache import QueryCache
    from geomesa_tpu.metrics import MetricsRegistry
    from geomesa_tpu.serving.http import DataClient, DataServer
    from geomesa_tpu.tiles import (
        KINDS, TileGrid, TileLattice, TilePyramid, TilesConfig,
        encode_png, render,
    )

    for m in ("fetch", "fresh", "peek", "note_delta", "invalidate_type",
              "sweep", "stats"):
        assert hasattr(TilePyramid, m), m
    for m in ("leaf_span", "tile_bbox", "bin_leaf", "children_of",
              "leaf_tiles_overlapping", "n_tiles", "valid"):
        assert hasattr(TileLattice, m), m
    for f in ("leaf_zoom", "px", "cache_max_bytes", "ttl_s",
              "ttl_jitter", "max_age_s"):
        assert f in TilesConfig.__dataclass_fields__, f
    for f in ("grid", "tick", "count"):
        assert f in TileGrid.__dataclass_fields__, f
    assert KINDS == ("density", "count", "heat")
    assert callable(encode_png) and callable(render)
    # the cache-tier seam: mutation hooks forward to an attached
    # pyramid, and its stats ride the cache tier's stats() payload
    assert hasattr(QueryCache, "attach_pyramid")
    assert hasattr(QueryCache, "stats")
    src = inspect.getsource(QueryCache)
    assert "pyramid" in src and "note_delta" in src
    # the documented HTTP surface: the server mounts /tiles/, answers
    # conditional GETs, and the stdlib client wraps it
    serve_src = inspect.getsource(DataServer)
    assert "/tiles/" in serve_src
    assert "If-None-Match" in serve_src
    assert "TilePyramid" in serve_src
    assert hasattr(DataClient, "tile")
    for p in ("fmt", "mode", "etag"):
        assert p in inspect.signature(DataClient.tile).parameters, p
    # the documented CLI command
    assert hasattr(cli, "cmd_tile")
    # every geomesa.tiles.* knob/metric resolves at runtime and is
    # cited by the doc; knobs ride config.md's complete index too
    knobs, metrics = _area_names("geomesa.tiles.")
    assert len(knobs) >= 5 and len(metrics) >= 7, (knobs, metrics)
    _assert_runtime_declared(knobs)
    _assert_documented("tiles.md", knobs + metrics)
    _assert_documented("config.md", knobs)
    # the cross-area knobs the doc leans on: the shared TTL-jitter
    # spread and the tile-serving SLO objective
    _assert_runtime_declared(
        ["geomesa.cache.ttl.jitter", "geomesa.obs.slo.tiles.p99.ms"]
    )
    _assert_documented(
        "tiles.md",
        ["geomesa.cache.ttl.jitter", "geomesa.obs.slo.tiles.p99.ms"],
    )
    # documented fault points exist at source level
    import geomesa_tpu.tiles.pyramid as pyr

    src = inspect.getsource(pyr)
    for point in ("tiles.compose", "tiles.leaf.scan"):
        assert point in src, point
    # the documented metric kinds render through the registry
    by_name = _registries().metrics.by_name()
    reg = MetricsRegistry()
    for n in metrics:
        kind = by_name[n][0].instrument
        if kind == "counter":
            reg.counter(n)
        elif kind == "gauge":
            reg.gauge(n, 1.0)
        elif kind == "histogram":
            reg.observe(n, 0.01)
        else:
            reg.timer_update(n, 0.01)
    text = reg.render_prometheus()
    assert 'geomesa_tiles_fetch_seconds_bucket{le="' in text
    assert "geomesa_tiles_served 1" in text
    # bench + gate wiring (source-level contract, like config_replica)
    bench_src = open(os.path.join(_ROOT, "bench.py")).read()
    assert "def config_tiles" in bench_src
    assert '"tiles": config_tiles' in bench_src
    assert "BENCH_TILES.json" in bench_src
    gate_src = open(
        os.path.join(_ROOT, "scripts", "bench_gate.py")
    ).read()
    assert "tiles_serving" in gate_src
    assert "tiles_invalidation" in gate_src
    assert "BENCH_TILES" in gate_src
    doc = open(os.path.join(_ROOT, "docs", "tiles.md")).read()
    assert "BENCH_TILES.json" in doc
    # every `pyramid.X` the doc mentions in backticks resolves
    for name in re.findall(r"`pyramid\.(\w+)", doc):
        assert hasattr(TilePyramid, name), f"pyramid.{name}"


def test_tuning_doc_honest():
    """docs/tuning.md stays honest the registry way: every tuning API
    it names is real, every geomesa.tuning.* knob and metric is
    declared at runtime and cited by the doc (and the knobs by
    config.md), the controller table matches the machine-checked
    CONTROLLERS registry, and the bench + gate wiring the doc promises
    exists."""
    from geomesa_tpu import tuning
    from geomesa_tpu.analysis.registries import CONTROLLERS
    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.tuning.controllers import CONTROLLER_SPECS

    for name in ("TuningManager", "IndexReweighter", "BurnShed",
                 "KnobController", "ControllerSpec", "CONTROLLER_SPECS",
                 "CostEwma", "ProbeGate", "ewma_step", "doubling_ladder"):
        assert hasattr(tuning, name), name
    for m in ("attach_tuning", "tuning_report", "record_query"):
        assert hasattr(DataStore, m), m
    for m in ("on_query", "pulse", "report", "state", "save", "load"):
        assert hasattr(tuning.TuningManager, m), m
    # every geomesa.tuning.* knob/metric resolves at runtime and is
    # cited by both the subsystem doc and the operator index
    knobs, metrics = _area_names("geomesa.tuning.")
    assert len(knobs) == 9 and len(metrics) >= 5, (knobs, metrics)
    _assert_runtime_declared(knobs + ["geomesa.scan.fused.slots"])
    _assert_documented("tuning.md", knobs + metrics)
    _assert_documented("config.md", knobs + ["geomesa.scan.fused.slots"])
    # the controller table is the registry, verbatim: every registered
    # controller (and its steered knob) appears in the doc
    doc = open(os.path.join(_ROOT, "docs", "tuning.md")).read()
    for name in CONTROLLERS:
        assert name in doc, name
    for spec in CONTROLLER_SPECS:
        assert spec.knob in doc, spec.knob
    # ops surface: the endpoint + CLI command the doc promises are real
    import inspect

    import geomesa_tpu.obs.ops as ops_mod
    from geomesa_tpu import cli

    assert "/debug/tuning" in doc
    assert "/debug/tuning" in inspect.getsource(ops_mod.OpsRoutes.handle)
    assert hasattr(cli, "cmd_tune")
    # bench + gate wiring (source-level contract, like config_tiles)
    bench_src = open(os.path.join(_ROOT, "bench.py")).read()
    assert "def config_drift" in bench_src
    assert '"drift": config_drift' in bench_src
    assert "BENCH_DRIFT.json" in bench_src
    gate_src = open(
        os.path.join(_ROOT, "scripts", "bench_gate.py")
    ).read()
    assert "config_drift" in gate_src
    assert "BENCH_DRIFT" in gate_src
    assert "BENCH_DRIFT.json" in doc
    # every `ds.X` the guide mentions in backticks resolves
    for name in re.findall(r"`ds\.(\w+)", doc):
        assert hasattr(DataStore, name), f"ds.{name}"


def test_distributed_doc_honest():
    """docs/distributed.md stays honest the registry way: every pod API
    it names is real, every geomesa.pod.* knob is declared at runtime
    and cited by the doc (and config.md's index), the fault points and
    locks exist in the source/registry, and the documented probe,
    scale-driver, bench and gate wiring is real."""
    import inspect

    import geomesa_tpu.pod.store as pod_store
    import geomesa_tpu.pod.table as pod_table
    from geomesa_tpu import pod
    from geomesa_tpu.parallel.mesh import host_major_slices  # noqa: F401

    for name in ("HostGroup", "PodIndexTable", "PodStore",
                 "PodUnsupported", "make_host_group", "probe_capability"):
        assert hasattr(pod, name), name
    for m in ("mesh", "flat_mesh", "set_link_profile", "probe_links",
              "slot_cap"):
        assert hasattr(pod.HostGroup, m), m
    for m in ("write", "delete", "bulk_load", "subscribe", "unsubscribe",
              "drain_alerts", "query", "count", "flush", "checkpoint",
              "kill", "rejoin", "owner", "close"):
        assert hasattr(pod.PodStore, m), m
    for m in ("_host_blocks", "_merge_host_rows", "_submit_fused_chunk"):
        assert hasattr(pod.PodIndexTable, m), m
    # rejoin rides the same replay-progress callback recover() exposes
    assert "on_progress" in inspect.signature(
        pod.PodStore.rejoin
    ).parameters
    # every geomesa.pod.* knob resolves at runtime and is cited by the
    # subsystem doc and the operator index (the pod tier declares no
    # metrics of its own — its shards report through the scan tier's)
    knobs, metrics = _area_names("geomesa.pod.")
    assert len(knobs) == 4 and not metrics, (knobs, metrics)
    _assert_runtime_declared(knobs + ["geomesa.scan.fused.slots"])
    _assert_documented("distributed.md", knobs)
    _assert_documented("config.md", knobs)
    # documented fault points exist at source level on both seams
    src = inspect.getsource(pod_table) + inspect.getsource(pod_store)
    for point in ("pod.dispatch", "pod.join", "pod.wal.route",
                  "pod.wal.replay"):
        assert point in src, point
    # the pod locks the doc points at are registered with the ranks the
    # concurrency table shows (below every host store lock)
    from geomesa_tpu.analysis.lockmodel import LOCKS

    for name in ("HostGroup._probe_lock", "PodStore._route_lock"):
        assert name in LOCKS, name
        assert LOCKS[name].rank < LOCKS["DataStore._write_lock"].rank
    # probe + scale-driver wiring (single-provenance 1B run)
    doc = open(os.path.join(_ROOT, "docs", "distributed.md")).read()
    assert os.path.exists(
        os.path.join(_ROOT, "scripts", "probe_multiprocess.py")
    )
    assert os.path.exists(
        os.path.join(_ROOT, "scripts", "run_pod_scale.py")
    )
    assert "scripts/run_pod_scale.py" in doc
    assert "SCALE_1B.json" in doc
    # bench + gate wiring (source-level contract, like config_replica)
    bench_src = open(os.path.join(_ROOT, "bench.py")).read()
    assert "def config_pod" in bench_src
    assert '"pod": config_pod' in bench_src
    assert "BENCH_POD.json" in bench_src
    gate_src = open(
        os.path.join(_ROOT, "scripts", "bench_gate.py")
    ).read()
    assert "BENCH_POD" in gate_src
    assert "BENCH_POD.json" in doc
    # every `group.X` / `pod.X` the guide mentions in backticks resolves
    for name in re.findall(r"`group\.(\w+)", doc):
        assert hasattr(pod.HostGroup, name), f"group.{name}"
    from geomesa_tpu.analysis.registries import FAULT_POINTS

    fault_points = {p.split(".", 1)[1] for p in FAULT_POINTS if p.startswith("pod.")}
    for name in re.findall(r"`pod\.([\w.]+)`", doc):
        assert (
            hasattr(pod.PodStore, name.split(".", 1)[0]) or name in fault_points
        ), f"pod.{name}"
