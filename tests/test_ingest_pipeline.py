"""Staged multi-core ingest pipeline (geomesa_tpu.ingest): differential
equivalence vs the sequential write path under adversarial chunk
boundaries, the sharded sort's bit-exact stable merge, backpressure, and
bulk loads into non-empty stores."""

import json
import os

import numpy as np
import pytest

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.ingest import BulkLoader, PipelineConfig
from geomesa_tpu.ingest import sort as shsort
from geomesa_tpu.metrics import MetricsRegistry
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.storage import persist

SPEC = "name:String,val:Double,dtg:Date,*geom:Point:srid=4326"
T0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
DAY = 86_400_000


def _sft():
    return FeatureType.from_spec("p", SPEC)


def _fc(sft, ids, n, seed, day_lo=0, day_hi=40):
    rng = np.random.default_rng(seed)
    return FeatureCollection.from_columns(
        sft, ids,
        {
            "name": np.array([f"n{i % 7}" for i in range(n)]),
            "val": rng.uniform(0, 1, n),
            "dtg": T0 + rng.integers(day_lo * DAY, day_hi * DAY, n),
            "geom": (rng.uniform(-60, 60, n), rng.uniform(-45, 45, n)),
        },
    )


def _chunks(sizes, seed=0, **kw):
    """One FeatureCollection per size (0 = an empty chunk), globally
    unique ids in chunk order."""
    sft = _sft()
    out, base = [], 0
    for j, n in enumerate(sizes):
        ids = [f"f{base + i}" for i in range(n)]
        out.append(_fc(sft, ids, n, seed + j, **kw))
        base += n
    return out


def _seq_store(chunks):
    ds = DataStore()
    ds.create_schema(_sft())
    for fc in chunks:
        ds.write("p", FeatureCollection(ds.get_schema("p"), fc.ids, fc.columns))
    ds.compact("p")
    return ds


def _pipe_store(chunks, workers=3, **cfg_kw):
    ds = DataStore()
    ds.create_schema(_sft())
    loader = BulkLoader(
        ds, "p", config=PipelineConfig(workers=workers, **cfg_kw)
    )
    for fc in chunks:
        loader.put(FeatureCollection(ds.get_schema("p"), fc.ids, fc.columns))
    loader.close()
    return ds


def _assert_tables_identical(a, b, type_name="p"):
    names = {n for (t, n) in a._tables if t == type_name}
    assert names == {n for (t, n) in b._tables if t == type_name}
    for n in names:
        ta, tb = a._tables[(type_name, n)], b._tables[(type_name, n)]
        assert ta.n == tb.n and ta.block == tb.block
        assert ta.n_blocks == tb.n_blocks
        assert np.array_equal(ta.bins, tb.bins), n
        assert np.array_equal(ta.zs, tb.zs), n
        assert np.array_equal(np.asarray(ta.perm), np.asarray(tb.perm)), n
        for k in ta.col_names:
            assert np.array_equal(
                np.asarray(ta.cols3[k]), np.asarray(tb.cols3[k])
            ), (n, k)
    sa, sb = a.stats_for(type_name), b.stats_for(type_name)
    assert json.dumps(sa.to_json(), default=str, sort_keys=True) == json.dumps(
        sb.to_json(), default=str, sort_keys=True
    )


def _tree_bytes(root):
    out = {}
    for dirpath, _, files in os.walk(root):
        for f in files:
            p = os.path.join(dirpath, f)
            with open(p, "rb") as fh:
                out[os.path.relpath(p, root)] = fh.read()
    return out


class TestDifferentialEquivalence:
    def test_adversarial_chunk_boundaries(self, tmp_path):
        """Chunks smaller than a device block, chunks straddling many
        bins, and empty chunks: the pipelined load's persisted store is
        BYTE-identical to the sequential one, and the in-memory tables
        (keys, perm, device blocks) and stats match bit for bit."""
        # block is >= 4096 rows: 100-row chunks are far below one block;
        # the 40-day dtg span straddles ~6 weekly z3 bins per chunk
        sizes = [100, 0, 3000, 1, 0, 777, 2048, 5000, 17]
        chunks = _chunks(sizes, seed=11)
        seq = _seq_store(chunks)
        pipe = _pipe_store(chunks, workers=3, chunk_rows=512, queue_depth=2)
        _assert_tables_identical(seq, pipe)
        assert seq.count("p") == pipe.count("p") == sum(sizes)
        d1, d2 = tmp_path / "seq", tmp_path / "pipe"
        persist.save(seq, str(d1))
        persist.save(pipe, str(d2))
        t1, t2 = _tree_bytes(d1), _tree_bytes(d2)
        assert sorted(t1) == sorted(t2)
        for name in t1:
            assert t1[name] == t2[name], name

    def test_lsd_fallback_bins_few_still_identical(self):
        """All rows in ONE z3 bin (and z2 is always one bin): the §4f
        fallback path (whole-table LSD at finalize, no span merge) must
        produce the same tables too."""
        chunks = _chunks([500, 1200, 300], seed=3, day_lo=2, day_hi=3)
        seq = _seq_store(chunks)
        pipe = _pipe_store(chunks, workers=2, chunk_rows=256)
        _assert_tables_identical(seq, pipe)

    def test_span_merge_forced_still_identical(self):
        """merge_min_bins=1 forces the spanwise k-way merge even for
        single-bin tables — exercises the merge on z2 as well."""
        chunks = _chunks([900, 1100, 250, 800], seed=5)
        seq = _seq_store(chunks)
        pipe = _pipe_store(chunks, workers=2, chunk_rows=300, merge_min_bins=1)
        _assert_tables_identical(seq, pipe)

    def test_queries_match_sequential(self):
        chunks = _chunks([2000, 1500, 2500], seed=7)
        seq = _seq_store(chunks)
        pipe = _pipe_store(chunks)
        for q in (
            "bbox(geom, -10, -10, 10, 10)",
            "bbox(geom, -30, -20, 40, 30) AND dtg DURING "
            "2024-01-03T00:00:00Z/2024-01-20T00:00:00Z",
            "name = 'n3'",
        ):
            a, b = seq.query("p", q), pipe.query("p", q)
            assert sorted(map(str, a.ids)) == sorted(map(str, b.ids))


class TestSortMerge:
    def test_merge_matches_stable_lexsort_with_ties(self):
        """Deliberate duplicate (bin, z) keys across shards: the spanwise
        merge must reproduce np.lexsort's STABLE order exactly."""
        rng = np.random.default_rng(0)
        n = 20_000
        bins = rng.integers(0, 12, n).astype(np.int32)
        zs = rng.integers(0, 50, n).astype(np.uint64)  # many ties
        runs = []
        for s in range(0, n, 1024):
            runs.extend(
                shsort.shard_runs(bins[s : s + 1024], zs[s : s + 1024], s, 400)
            )
        perm = shsort.merge_runs(runs)
        expect = np.lexsort((zs, bins))
        assert np.array_equal(perm, expect)

    def test_merge_parallel_pool(self):
        from concurrent.futures import ThreadPoolExecutor

        rng = np.random.default_rng(1)
        n = 5000
        bins = rng.integers(0, 30, n).astype(np.int32)
        zs = rng.integers(0, 2**40, n).astype(np.uint64)
        runs = shsort.shard_runs(bins, zs, 0, 700)
        with ThreadPoolExecutor(4) as pool:
            perm = shsort.merge_runs(runs, pool=pool)
        assert np.array_equal(perm, np.lexsort((zs, bins)))

    def test_single_run_passthrough(self):
        bins = np.zeros(100, np.int32)
        zs = np.arange(100, dtype=np.uint64)[::-1].copy()
        runs = shsort.shard_runs(bins, zs, 10, 1000)
        perm = shsort.merge_runs(runs)
        assert np.array_equal(perm, 10 + np.lexsort((zs, bins)))


class TestBulkLoader:
    def test_bulk_into_non_empty_store(self):
        """A bulk load appended to an existing table goes through the
        normal delta compaction (presorted perms only cover the new rows)
        and still matches the sequential result."""
        first = _chunks([1500], seed=21)[0]
        sft = _sft()
        more = [
            _fc(sft, [f"x{i}" for i in range(800)], 800, 22),
            _fc(sft, [f"y{i}" for i in range(600)], 600, 23),
        ]
        seq = DataStore()
        seq.create_schema(_sft())
        seq.write("p", FeatureCollection(seq.get_schema("p"), first.ids, first.columns))
        for fc in more:
            seq.write("p", FeatureCollection(seq.get_schema("p"), fc.ids, fc.columns))
        seq.compact("p")

        pipe = DataStore()
        pipe.create_schema(_sft())
        pipe.write("p", FeatureCollection(pipe.get_schema("p"), first.ids, first.columns))
        loader = BulkLoader(pipe, "p", config=PipelineConfig(workers=2))
        for fc in more:
            loader.put(FeatureCollection(pipe.get_schema("p"), fc.ids, fc.columns))
        loader.close()
        pipe.compact("p")
        seq.compact("p")
        _assert_tables_identical(seq, pipe)

    def test_duplicate_ids_abort_atomically(self):
        ds = DataStore()
        ds.create_schema(_sft())
        sft = ds.get_schema("p")
        loader = BulkLoader(ds, "p")
        loader.put(_fc(sft, [f"a{i}" for i in range(50)], 50, 1))
        loader.put(_fc(sft, [f"a{i}" for i in range(30)], 30, 2))  # dup ids
        with pytest.raises(ValueError, match="duplicate feature ids"):
            loader.close()
        # atomic: NOTHING was published
        assert ds.count("p") == 0
        assert ds._chunks["p"] == []
        assert ("p", "z3") not in ds._tables

    def test_backpressure_counter_and_peak_gauge(self):
        reg = MetricsRegistry()
        ds = DataStore(metrics=reg)
        ds.create_schema(_sft())
        sft = ds.get_schema("p")
        loader = BulkLoader(
            ds, "p", config=PipelineConfig(workers=1, queue_depth=1)
        )
        for j in range(6):
            loader.put(_fc(sft, [f"c{j}_{i}" for i in range(2000)], 2000, j))
        res = loader.close()
        assert res.written == 12000
        snap = reg.snapshot()
        assert snap["counters"]["geomesa.ingest.rows"] == 12000
        assert snap["counters"]["geomesa.ingest.chunks"] == 6
        assert snap["counters"].get("geomesa.ingest.queue_full", 0) >= 1
        assert snap["gauges"]["geomesa.ingest.chunk_bytes_peak"] > 0
        for stage in ("keys", "sort", "finalize"):
            assert snap["timers"][f"geomesa.ingest.{stage}"]["count"] >= 1
        assert res.stage_seconds["keys"] > 0

    def test_put_after_close_rejected(self):
        ds = DataStore()
        ds.create_schema(_sft())
        loader = BulkLoader(ds, "p")
        loader.close()
        with pytest.raises(RuntimeError, match="closed"):
            loader.put(_fc(ds.get_schema("p"), ["q0"], 1, 0))

    def test_empty_close_is_noop(self):
        ds = DataStore()
        ds.create_schema(_sft())
        res = BulkLoader(ds, "p").close()
        assert res.written == 0
        assert ds.count("p") == 0


class TestLoadUsesPipeline:
    def test_save_load_roundtrip_exact(self, tmp_path):
        """persist.load routes through the BulkLoader: the reloaded store
        answers exactly like the original (and its stats survive)."""
        chunks = _chunks([1200, 900], seed=31)
        ds = _seq_store(chunks)
        persist.save(ds, str(tmp_path / "s"))
        back = persist.load(str(tmp_path / "s"))
        assert back.count("p") == ds.count("p")
        q = "bbox(geom, -15, -15, 15, 15)"
        assert sorted(map(str, back.query("p", q).ids)) == sorted(
            map(str, ds.query("p", q).ids)
        )
        assert back.stats_for("p").total_count() == ds.stats_for("p").total_count()


class TestReviewRegressions:
    def test_concurrent_producers_mint_disjoint_ordinals(self):
        """Two threads put() concurrently: chunk base offsets must never
        overlap (the sort permutation is built from them), and the final
        table matches a sequential load of the same rows."""
        import threading

        ds = DataStore()
        ds.create_schema(_sft())
        sft = ds.get_schema("p")
        loader = BulkLoader(ds, "p", config=PipelineConfig(workers=2))
        per, n_chunks = 400, 10

        def producer(tag):
            for j in range(n_chunks):
                loader.put(_fc(
                    sft, [f"{tag}{j}_{i}" for i in range(per)], per,
                    seed=hash(tag) % 1000 + j,
                ))

        threads = [
            threading.Thread(target=producer, args=(t,)) for t in ("a", "b")
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        res = loader.close()
        assert res.written == 2 * n_chunks * per == ds.count("p")
        # every row is queryable exactly once (overlapping ordinals would
        # duplicate some ids and drop others)
        out = ds.query("p", "INCLUDE")
        assert len(set(map(str, out.ids))) == 2 * n_chunks * per

    def test_id_check_does_not_truncate_wide_ids(self):
        """A store with short string ids must not reject a LONGER unique
        id because of a fixed-width astype truncation ('12345' -> '123')."""
        ds = DataStore()
        ds.create_schema(_sft())
        sft = ds.get_schema("p")
        ds.write("p", _fc(sft, ["123", "ab"], 2, 1))
        # int ids cast through the stored '<U3' dtype would truncate
        # 12345 to '123' and spuriously collide
        fc = _fc(sft, ["x1", "x2"], 2, 2)
        fc = FeatureCollection(sft, np.array([12345, 67890]), fc.columns)
        assert ds.write("p", fc) == 2
        assert ds.count("p") == 4
