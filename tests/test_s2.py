"""S2 curve: roundtrip, covering correctness (brute force), store paths."""

import numpy as np
import pytest

from geomesa_tpu.curve import s2 as s2mod
from geomesa_tpu.curve.s2 import S2SFC, cell_id_from_lonlat, cell_center_lonlat, cell_range
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType


def _rand_lonlat(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)


class TestCellIds:
    def test_roundtrip_center_close(self):
        lon, lat = _rand_lonlat(2000)
        cells = cell_id_from_lonlat(lon, lat)
        clon, clat = cell_center_lonlat(cells)
        # a leaf cell is ~1e-7 degrees across; centers must be within a cell
        assert np.abs(clat - lat).max() < 1e-5
        dlon = np.abs(((clon - lon) + 180) % 360 - 180) * np.cos(np.radians(lat))
        assert dlon.max() < 1e-5

    def test_leaf_ids_distinct_and_valid(self):
        lon, lat = _rand_lonlat(5000, seed=1)
        cells = cell_id_from_lonlat(lon, lat)
        assert len(np.unique(cells)) > 4990  # collisions ~ impossible
        assert (cells & np.uint64(1)).all()  # leaf ids end in 1
        faces = cells >> np.uint64(61)
        assert faces.max() <= 5

    def test_locality(self):
        # nearby points share long cell-id prefixes more than far ones
        a = cell_id_from_lonlat(np.array([10.0]), np.array([10.0]))[0]
        b = cell_id_from_lonlat(np.array([10.0001]), np.array([10.0001]))[0]
        c = cell_id_from_lonlat(np.array([-120.0]), np.array([-45.0]))[0]
        near = int(a ^ b).bit_length()
        far = int(a ^ c).bit_length()
        assert near < far

    def test_coarse_level_ranges_nest(self):
        lon, lat = np.array([42.5]), np.array([-13.25])
        leaf = cell_id_from_lonlat(lon, lat)[0]
        for level in (5, 10, 20):
            coarse = cell_id_from_lonlat(lon, lat, level=level)[0]
            lo, hi = cell_range(np.array([coarse]))
            assert lo[0] <= leaf <= hi[0]


BOXES = [
    (-10.0, -10.0, 10.0, 10.0),
    (100.0, 30.0, 140.0, 70.0),     # reaches the north polar face
    (-179.0, -89.0, 179.0, -50.0),  # south polar band
    (170.0, -20.0, 180.0, 20.0),    # hugs the antimeridian
    (-170.0, 10.0, 170.0, 12.0),    # wide band wrapping most faces
    (0.0, 80.0, 360.0 - 359.0, 90.0),
    (-45.1, 44.9, -44.9, 45.1),     # face corner
]


class TestCovering:
    @pytest.mark.parametrize("box", BOXES)
    def test_no_misses(self, box):
        xmin, ymin, xmax, ymax = box
        rng = np.random.default_rng(7)
        n = 4000
        lon = rng.uniform(xmin, xmax, n)
        lat = rng.uniform(ymin, ymax, n)
        cells = cell_id_from_lonlat(lon, lat)
        sfc = S2SFC()
        ranges = sfc.ranges([box])
        assert ranges
        lows = np.array([r.lower for r in ranges], dtype=np.uint64)
        highs = np.array([r.upper for r in ranges], dtype=np.uint64)
        idx = np.searchsorted(lows, cells, side="right") - 1
        ok = (idx >= 0) & (cells <= highs[np.clip(idx, 0, len(highs) - 1)])
        assert ok.all(), f"{(~ok).sum()} points outside covering for {box}"

    def test_range_budget(self):
        sfc = S2SFC(max_cells=64)
        ranges = sfc.ranges([(-170.0, -80.0, 170.0, 80.0)])
        assert 0 < len(ranges) <= 8 * 64  # merged, bounded

    def test_inverted_box_raises(self):
        with pytest.raises(ValueError):
            S2SFC().ranges([(10, 0, -10, 5)])


class TestStoreIntegration:
    def _store(self, enabled):
        spec = f"dtg:Date,*geom:Point:srid=4326;geomesa.indices.enabled={enabled}"
        sft = FeatureType.from_spec("s2t", spec)
        ds = DataStore(tile=64)
        ds.create_schema(sft)
        n = 3000
        rng = np.random.default_rng(3)
        t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
        x = rng.uniform(-180, 180, n)
        y = rng.uniform(-90, 90, n)
        t = t0 + rng.integers(0, 20 * 86400_000, n)
        ds.write("s2t", FeatureCollection.from_columns(
            sft, [str(i) for i in range(n)], {"dtg": t, "geom": (x, y)}
        ))
        return ds, (x, y, t)

    def test_s2_query_matches_brute_force(self):
        ds, (x, y, t) = self._store("s2")
        assert [i.name for i in ds.indexes("s2t")] == ["s2"]
        hits = ds.query("s2t", "bbox(geom, -30, 20, 40, 60)")
        truth = (x >= -30) & (x <= 40) & (y >= 20) & (y <= 60)
        assert sorted(hits.ids.tolist()) == sorted(
            np.arange(len(x)).astype(str)[truth].tolist()
        )

    def test_s3_query_matches_brute_force(self):
        ds, (x, y, t) = self._store("s3")
        assert [i.name for i in ds.indexes("s2t")] == ["s3"]
        lo = np.datetime64("2024-01-03T00:00:00", "ms").astype(np.int64)
        hi = np.datetime64("2024-01-12T00:00:00", "ms").astype(np.int64)
        q = (
            "bbox(geom, -60, -40, 60, 40) AND dtg DURING "
            "2024-01-03T00:00:00Z/2024-01-12T00:00:00Z"
        )
        hits = ds.query("s2t", q)
        truth = (
            (x >= -60) & (x <= 60) & (y >= -40) & (y <= 40) & (t >= lo) & (t < hi)
        )
        assert sorted(hits.ids.tolist()) == sorted(
            np.arange(len(x)).astype(str)[truth].tolist()
        )

    def test_default_indexes_unchanged(self):
        sft = FeatureType.from_spec("p", "dtg:Date,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        assert [i.name for i in ds.indexes("p")] == ["z3", "z2"]
