"""End-to-end DataStore tests: device scan results must exactly match a
host brute-force filter evaluation.

The analogue of the reference's TestGeoMesaDataStore-based index tests
(/root/reference/geomesa-index-api/src/test/scala/org/locationtech/geomesa/
index/TestGeoMesaDataStore.scala:40-150, Z3IndexTest.scala:35): the whole
planner/index/scan stack runs against the in-memory store with zero infra
(JAX CPU), randomized queries cross-checked against brute force.
"""

import numpy as np
import pytest

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu.filter import ecql
from geomesa_tpu.filter.predicates import BBox, During, And, Cmp, IdFilter, INCLUDE
from geomesa_tpu.planning.explain import Explainer
from geomesa_tpu.planning.planner import QueryGuardError

N = 20_000
T0 = 1514764800000  # 2018-01-01T00:00:00Z
WEEK_MS = 7 * 86400000


def make_point_store(n=N, seed=0, tile=256):
    rng = np.random.default_rng(seed)
    sft = FeatureType.from_spec(
        "gdelt", "name:String,count:Integer,dtg:Date,*geom:Point:srid=4326"
    )
    ds = DataStore(tile=tile)
    ds.create_schema(sft)
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    # cluster half the points so queries hit dense regions too
    x[: n // 2] = rng.normal(-77, 3, n // 2).clip(-180, 180)
    y[: n // 2] = rng.normal(38, 3, n // 2).clip(-90, 90)
    t = rng.integers(T0, T0 + 8 * WEEK_MS, n)
    fc = FeatureCollection.from_columns(
        sft,
        ids=[f"f{i}" for i in range(n)],
        columns={
            "name": rng.choice(["a", "b", "c"], n),
            "count": rng.integers(0, 100, n).astype(np.int32),
            "dtg": t,
            "geom": (x, y),
        },
    )
    ds.write("gdelt", fc)
    return ds, fc


@pytest.fixture(scope="module")
def point_store():
    return make_point_store()


def brute(fc, f):
    return set(fc.mask(f.evaluate(fc.batch)).ids.tolist())


def ids(result):
    return set(result.ids.tolist())


class TestZ3QueryPath:
    def test_bbox_time_queries_match_brute_force(self, point_store):
        ds, fc = point_store
        rng = np.random.default_rng(42)
        for _ in range(25):
            cx, cy = rng.uniform(-170, 170), rng.uniform(-80, 80)
            w, h = rng.uniform(0.5, 30, 2)
            t_lo = int(rng.integers(T0, T0 + 7 * WEEK_MS))
            t_hi = t_lo + int(rng.integers(3600_000, 2 * WEEK_MS))
            f = And(
                [
                    BBox("geom", cx - w, cy - h, cx + w, cy + h),
                    During("dtg", t_lo, t_hi),
                ]
            )
            exp = Explainer()
            got = ids(ds.query("gdelt", f, explain=exp))
            assert "z3" in exp.render()
            # boxes generated past +/-180 wrap across the antimeridian
            # (GeoTools BBOX semantics) — apply the same normalization
            # to the brute-force truth
            from geomesa_tpu.filter.predicates import normalize_antimeridian

            assert got == brute(fc, normalize_antimeridian(f))

    def test_tiny_and_empty_boxes(self, point_store):
        ds, fc = point_store
        f = And([BBox("geom", 0, 0, 1e-9, 1e-9), During("dtg", T0, T0 + WEEK_MS)])
        assert ids(ds.query("gdelt", f)) == brute(fc, f)

    def test_whole_world_with_time(self, point_store):
        ds, fc = point_store
        f = During("dtg", T0 + WEEK_MS, T0 + 2 * WEEK_MS)
        got = ids(ds.query("gdelt", f))
        assert got == brute(fc, f)
        assert len(got) > 0

    def test_interval_spanning_many_bins(self, point_store):
        ds, fc = point_store
        f = And(
            [
                BBox("geom", -90, 20, -60, 50),
                During("dtg", T0 + 1000, T0 + 6 * WEEK_MS + 12345),
            ]
        )
        assert ids(ds.query("gdelt", f)) == brute(fc, f)


class TestZ2QueryPath:
    def test_bbox_only_uses_z2(self, point_store):
        ds, fc = point_store
        f = BBox("geom", -80, 35, -74, 41)
        exp = Explainer()
        got = ids(ds.query("gdelt", f, explain=exp))
        assert "Strategy: z2" in exp.render()
        assert got == brute(fc, f)

    def test_random_bboxes(self, point_store):
        ds, fc = point_store
        rng = np.random.default_rng(7)
        for _ in range(15):
            cx, cy = rng.uniform(-170, 170), rng.uniform(-80, 80)
            w, h = rng.uniform(0.1, 40, 2)
            f = BBox("geom", cx - w, cy - h, cx + w, cy + h)
            from geomesa_tpu.filter.predicates import normalize_antimeridian

            assert ids(ds.query("gdelt", f)) == brute(
                fc, normalize_antimeridian(f)
            )

    def test_polygon_intersects(self, point_store):
        ds, fc = point_store
        f = ecql.parse(
            "INTERSECTS(geom, POLYGON ((-80 30, -70 30, -70 45, -85 45, -80 30)))"
        )
        assert ids(ds.query("gdelt", f)) == brute(fc, f)


class TestOtherPaths:
    def test_id_lookup(self, point_store):
        ds, fc = point_store
        f = IdFilter(("f10", "f999", "f19999", "missing"))
        exp = Explainer()
        got = ids(ds.query("gdelt", f, explain=exp))
        assert "id-lookup" in exp.render()
        assert got == {"f10", "f999", "f19999"}

    def test_include_returns_all(self, point_store):
        ds, fc = point_store
        assert len(ds.query("gdelt")) == len(fc)

    def test_attribute_only_full_scan(self, point_store):
        ds, fc = point_store
        f = Cmp("count", ">", 90)
        assert ids(ds.query("gdelt", f)) == brute(fc, f)

    def test_mixed_residual_attribute(self, point_store):
        ds, fc = point_store
        f = ecql.parse(
            "BBOX(geom, -85, 30, -70, 45) AND dtg DURING "
            "2018-01-05T00:00:00Z/2018-01-20T00:00:00Z AND count > 50"
        )
        exp = Explainer()
        got = ids(ds.query("gdelt", f, explain=exp))
        assert "z3" in exp.render()
        assert got == brute(fc, f)

    def test_or_of_boxes(self, point_store):
        ds, fc = point_store
        f = ecql.parse("BBOX(geom, -80, 35, -75, 40) OR BBOX(geom, 10, 10, 20, 20)")
        assert ids(ds.query("gdelt", f)) == brute(fc, f)

    def test_limit(self, point_store):
        ds, _ = point_store
        got = ds.query("gdelt", BBox("geom", -180, -90, 180, 90), limit=17)
        assert len(got) == 17

    def test_count(self, point_store):
        ds, fc = point_store
        f = BBox("geom", -80, 35, -74, 41)
        assert ds.count("gdelt", f) == len(brute(fc, f))

    def test_disjoint_filter_empty(self, point_store):
        ds, _ = point_store
        f = And([BBox("geom", 0, 0, 10, 10), BBox("geom", 50, 50, 60, 60)])
        assert len(ds.query("gdelt", f)) == 0

    def test_guard_blocks_full_scan(self):
        ds, _ = make_point_store(n=100, tile=64)
        ds.block_full_table_scans = True
        with pytest.raises(QueryGuardError):
            ds.query("gdelt", Cmp("count", ">", 90))

    def test_explain_renders(self, point_store):
        ds, _ = point_store
        text = ds.explain(
            "gdelt",
            "BBOX(geom, -85, 30, -70, 45) AND dtg DURING "
            "2018-01-05T00:00:00Z/2018-01-20T00:00:00Z",
        )
        assert "Strategy: z3" in text and "Ranges:" in text


class TestSchemaLifecycle:
    def test_create_get_delete(self):
        ds = DataStore()
        ds.create_schema("t1", "dtg:Date,*geom:Point:srid=4326")
        assert ds.type_names() == ["t1"]
        assert ds.get_schema("t1").is_points
        ds.delete_schema("t1")
        assert ds.type_names() == []

    def test_duplicate_schema_rejected(self):
        ds = DataStore()
        ds.create_schema("t1", "*geom:Point")
        with pytest.raises(ValueError):
            ds.create_schema("t1", "*geom:Point")

    def test_incremental_writes(self):
        ds = DataStore(tile=64)
        sft = ds.create_schema("t", "dtg:Date,*geom:Point")
        rows1 = [
            {"dtg": T0 + i * 1000, "geom": f"POINT ({i} {i})", "__id__": f"a{i}"}
            for i in range(50)
        ]
        rows2 = [
            {"dtg": T0 + i * 1000, "geom": f"POINT ({-i} {i})", "__id__": f"b{i}"}
            for i in range(1, 50)
        ]
        ds.write("t", rows1)
        ds.write("t", rows2)
        assert ds.count("t") == 99
        f = ecql.parse("BBOX(geom, 0.5, 0.5, 49.5, 49.5)")
        assert len(ds.query("t", f)) == 49

    def test_duplicate_ids_rejected(self):
        ds = DataStore()
        ds.create_schema("t", "dtg:Date,*geom:Point")
        rows = [{"dtg": T0, "geom": "POINT (0 0)", "__id__": "x"}] * 2
        with pytest.raises(ValueError):
            ds.write("t", rows)


class TestAntimeridianBBox:
    def test_seam_crossing_bbox_wraps(self):
        from geomesa_tpu.filter.predicates import Not, BBox

        sft = FeatureType.from_spec("s", "*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        x = np.array([-179.0, 175.0, 0.0, 179.5])
        y = np.array([0.0, 5.0, 0.0, -5.0])
        ds.write("s", FeatureCollection.from_columns(
            sft, np.arange(4), {"geom": (x, y)}
        ))
        out = ds.query("s", "bbox(geom, 170, -10, 190, 10)")
        assert set(np.asarray(out.ids, np.int64).tolist()) == {0, 1, 3}
        out2 = ds.query("s", "NOT (bbox(geom, 170, -10, 190, 10))")
        assert set(np.asarray(out2.ids, np.int64).tolist()) == {2}
        assert ds.count("s", "bbox(geom, 170, -10, 190, 10)") == 3
        # western crossing: wraps to [-180, -170] + [170, 180]
        out3 = ds.query("s", "bbox(geom, -190, -10, -170, 10)")
        assert set(np.asarray(out3.ids, np.int64).tolist()) == {0, 1, 3}

    def test_fully_out_of_range_boxes_shift(self):
        """Boxes lying ENTIRELY beyond +/-180 shift into range (an
        inverted two-box split returned wrong rows before)."""
        sft = FeatureType.from_spec("s2", "*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        x = np.array([-179.0, 175.0, 0.0, 179.5])
        y = np.array([0.0, 5.0, 0.0, -5.0])
        ds.write("s2", FeatureCollection.from_columns(
            sft, np.arange(4), {"geom": (x, y)}
        ))
        assert len(ds.query("s2", "bbox(geom, 185, -10, 190, 10)")) == 0
        out = ds.query("s2", "bbox(geom, -190, -10, -185, 10)")
        assert set(np.asarray(out.ids, np.int64).tolist()) == {1}
        out = ds.query("s2", "bbox(geom, 181, -10, 182, 10)")
        assert set(np.asarray(out.ids, np.int64).tolist()) == {0}

    def test_non_finite_bbox_errors_cleanly(self):
        """An overflowed bbox literal must not hang the planner's wrap
        loop — it surfaces as a clean error."""
        from geomesa_tpu.filter.predicates import BBox

        sft = FeatureType.from_spec("s3", "*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        ds.write("s3", FeatureCollection.from_columns(
            sft, np.arange(2),
            {"geom": (np.array([0.0, 1.0]), np.array([0.0, 1.0]))},
        ))
        with pytest.raises(ValueError):
            ds.query("s3", BBox("geom", float("inf"), -10, float("inf"), 10))


class TestMergedViewAggregations:
    def test_density_and_bounds_over_stores(self):
        from geomesa_tpu.views import MergedView

        rng = np.random.default_rng(0)
        stores, xs, ys = [], [], []
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
        for k in range(2):
            sft = FeatureType.from_spec("ev", "dtg:Date,*geom:Point:srid=4326")
            ds = DataStore()
            ds.create_schema(sft)
            n = 5000
            x = rng.uniform(-50, 50, n)
            y = rng.uniform(-50, 50, n)
            ds.write("ev", FeatureCollection.from_columns(
                sft, np.arange(k * n, (k + 1) * n),
                {"dtg": np.full(n, t0), "geom": (x, y)},
            ), check_ids=False)
            stores.append(ds)
            xs.append(x)
            ys.append(y)
        v = MergedView(stores, "ev")
        q = "bbox(geom, -10, -10, 10, 10)"
        g = v.density(q, envelope=(-10, -10, 10, 10), width=32, height=32)
        x = np.concatenate(xs)
        y = np.concatenate(ys)
        true = int(((x >= -10) & (x <= 10) & (y >= -10) & (y <= 10)).sum())
        assert abs(float(g.sum()) - true) <= max(2, 0.02 * true)
        b = v.bounds(q)
        assert b is not None
        assert b[0] >= -10.01 and b[1] >= -10.01 and b[2] <= 10.01 and b[3] <= 10.01


class TestUpdateSurface:
    """upsert + modify_features (reference GeoTools FeatureWriter update /
    FeatureStore.modifyFeatures)."""

    @staticmethod
    def _store():
        from geomesa_tpu.datastore import DataStore

        sft = FeatureType.from_spec(
            "upd", "name:String,age:Int,dtg:Date,*geom:Point:srid=4326"
        )
        ds = DataStore()
        ds.create_schema(sft)
        rng = np.random.default_rng(0)
        n = 500
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
        fc = FeatureCollection.from_columns(
            sft, [str(i) for i in range(n)],
            {"name": np.array([f"n{i % 7}" for i in range(n)], dtype=object),
             "age": rng.integers(0, 90, n),
             "dtg": t0 + rng.integers(0, 20 * 86400_000, n),
             "geom": (rng.uniform(-60, 60, n), rng.uniform(-40, 40, n))},
        )
        ds.write("upd", fc)
        return ds, sft, fc

    def test_upsert_replaces_by_id(self):
        ds, sft, fc = self._store()
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
        # replace rows 10..19 with new geometry far away + new ages
        repl = FeatureCollection.from_columns(
            sft, [str(i) for i in range(10, 20)],
            {"name": np.array(["moved"] * 10, dtype=object),
             "age": np.full(10, 999),
             "dtg": np.full(10, t0),
             "geom": (np.full(10, 150.0), np.full(10, 80.0))},
        )
        assert ds.upsert("upd", repl) == 10
        assert ds.count("upd") == 500  # replaced, not appended
        hits = ds.query("upd", "bbox(geom, 149, 79, 151, 81)")
        assert sorted(hits.ids.tolist()) == [str(i) for i in range(10, 20)]
        assert set(np.asarray(hits.columns["age"]).tolist()) == {999}
        # new ids append
        extra = FeatureCollection.from_columns(
            sft, ["x1"],
            {"name": np.array(["new"], dtype=object), "age": np.array([1]),
             "dtg": np.array([t0]), "geom": (np.array([0.5]), np.array([0.5]))},
        )
        ds.upsert("upd", extra)
        assert ds.count("upd") == 501

    def test_modify_features_moves_index_cells(self):
        ds, sft, fc = self._store()
        moved = ds.modify_features(
            "upd", {"geom": __import__("geomesa_tpu.geometry", fromlist=["Point"]).Point(170.0, 85.0), "age": 7},
            "name = 'n3'",
        )
        want = int((np.asarray(fc.columns["name"]) == "n3").sum())
        assert moved == want
        # all moved rows now found at the NEW location through the index
        hits = ds.query("upd", "bbox(geom, 169, 84, 171, 86)")
        assert len(hits) == want
        assert set(np.asarray(hits.columns["age"]).tolist()) == {7}
        # and no n3 rows remain anywhere else
        others = ds.query("upd", "name = 'n3' AND bbox(geom, -180, -90, 168, 83)")
        assert len(others) == 0
        assert ds.count("upd") == 500

    def test_modify_unknown_attr_raises(self):
        ds, _, _ = self._store()
        with pytest.raises(KeyError):
            ds.modify_features("upd", {"nope": 1}, "INCLUDE")


class TestUpdateReviewFixes:
    def test_upsert_bad_batch_leaves_store_untouched(self):
        ds, sft, fc = TestUpdateSurface._store()
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
        dup = FeatureCollection.from_columns(
            sft, ["3", "3"],
            {"name": np.array(["x", "x"], dtype=object),
             "age": np.array([1, 2]), "dtg": np.array([t0, t0]),
             "geom": (np.array([0.0, 0.0]), np.array([0.0, 0.0]))},
        )
        with pytest.raises(ValueError):
            ds.upsert("upd", dup)
        # the existing row 3 survived with its original attributes
        assert ds.count("upd") == 500
        row = ds.query("upd", "IN ('3')")
        assert np.asarray(row.columns["name"]).tolist() == ["n3"]

    def test_modify_extent_schema_geometry(self):
        from geomesa_tpu import geometry as geo
        from geomesa_tpu.datastore import DataStore

        sft = FeatureType.from_spec("ext", "v:Int,*geom:Polygon:srid=4326")
        ds = DataStore(); ds.create_schema(sft)
        ds.write("ext", FeatureCollection.from_columns(
            sft, ["a", "b"],
            {"v": np.array([1, 2]),
             "geom": [geo.box(0, 0, 1, 1), geo.box(5, 5, 6, 6)]}))
        # a Point value on an extent schema stays in the packed column
        # (the write path accepts heterogeneous geometries the same way)
        # and, crucially, loses no rows
        ds.modify_features("ext", {"geom": geo.Point(9, 9)}, "v = 1")
        assert ds.count("ext") == 2
        assert ds.query("ext", "bbox(geom, 8, 8, 10, 10)").ids.tolist() == ["a"]
        # a polygon value moves the row's index cell
        moved = ds.modify_features("ext", {"geom": geo.box(50, 50, 51, 51)}, "v = 1")
        assert moved == 1
        hits = ds.query(
            "ext", "INTERSECTS(geom, POLYGON((49 49, 52 49, 52 52, 49 52, 49 49)))")
        assert hits.ids.tolist() == ["a"]
        assert ds.count("ext") == 2

    def test_point_schema_rejects_polygon_value(self):
        from geomesa_tpu import geometry as geo

        ds, _, _ = TestUpdateSurface._store()
        with pytest.raises(TypeError):
            ds.modify_features("upd", {"geom": geo.box(0, 0, 1, 1)}, "INCLUDE")
        assert ds.count("upd") == 500


class TestModifyDtypeSafety:
    def test_fixed_width_string_not_truncated(self):
        from geomesa_tpu.datastore import DataStore

        sft = FeatureType.from_spec("fw", "name:String,*geom:Point:srid=4326")
        ds = DataStore(); ds.create_schema(sft)
        # fixed-width '<U2' column, as from_columns produces for plain lists
        ds.write("fw", FeatureCollection.from_columns(
            sft, ["a", "b"],
            {"name": np.array(["n1", "n2"]),
             "geom": (np.array([0.0, 1.0]), np.array([0.0, 1.0]))}))
        ds.modify_features("fw", {"name": "renamed"}, "IN ('a')")
        got = ds.query("fw", "IN ('a')")
        assert np.asarray(got.columns["name"]).tolist() == ["renamed"]

    def test_lossy_numeric_cast_refused(self):
        ds, _, _ = TestUpdateSurface._store()
        with pytest.raises(TypeError):
            ds.modify_features("upd", {"age": 3.5}, "IN ('1')")
        # whole-valued floats are fine
        ds.modify_features("upd", {"age": 7.0}, "IN ('1')")
        got = ds.query("upd", "IN ('1')")
        assert np.asarray(got.columns["age"]).tolist() == [7]

    def test_non_geometry_value_clean_error(self):
        ds, _, _ = TestUpdateSurface._store()
        with pytest.raises(TypeError, match="tuple"):
            ds.modify_features("upd", {"geom": (1.0, 2.0)}, "IN ('1')")


class TestPagingOffset:
    def test_offset_pages_are_stable_and_disjoint(self):
        from geomesa_tpu.planning.hints import QueryHints

        ds, fc = make_point_store(n=500, seed=3)
        f = "bbox(geom, -180, -90, 180, 90)"
        pages = []
        for off in range(0, 500, 100):
            h = QueryHints(sort_by="count", offset=off)
            page = ds.query("gdelt", f, limit=100, hints=h)
            pages.append(page.ids.tolist())
        flat = [i for p in pages for i in p]
        assert len(flat) == 500 and len(set(flat)) == 500
        # pages follow the sort order
        h_all = QueryHints(sort_by="count")
        want = ds.query("gdelt", f, hints=h_all).ids.tolist()
        assert flat == want
        # offset past the end yields empty, negative rejected
        h = QueryHints(offset=10_000)
        assert len(ds.query("gdelt", f, hints=h)) == 0
        with pytest.raises(ValueError):
            QueryHints(offset=-1).validate()
        with pytest.raises(ValueError):
            QueryHints(offset=2.5).validate()
