"""Production streaming tier (docs/streaming.md): the incremental
hot->cold fold, the pipelined flusher's atomicity + fault matrix, exact
reads under concurrent flushes, generation scoping under sustained
writes, and the raster aggregation push-down satellite."""

import threading
import time

import numpy as np
import pytest

from geomesa_tpu import conf, fault, geometry as geo
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter.predicates import And, During, Intersects
from geomesa_tpu.metrics import MetricsRegistry
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.streaming import LambdaStore, StreamConfig

SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"
T0 = int(np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64))
DAY = 86_400_000


def _build(n=4000, seed=0, spec=SPEC, cache=None, metrics=None, name="t"):
    rng = np.random.default_rng(seed)
    ds = DataStore(tile=64, cache=cache, metrics=metrics)
    sft = FeatureType.from_spec(name, spec)
    ds.create_schema(sft)
    if n:
        ds.write(name, _batch(sft, [f"f{i}" for i in range(n)], seed=seed))
        ds.compact(name)
    return ds


def _batch(sft, ids, seed=1, name="n", box=(-60.0, -60.0, 60.0, 60.0)):
    rng = np.random.default_rng(seed)
    m = len(ids)
    x0, y0, x1, y1 = box
    return FeatureCollection.from_columns(sft, list(ids), {
        "name": np.array([name] * m),
        "dtg": T0 + rng.integers(0, 30 * DAY, m),
        "geom": (rng.uniform(x0, x1, m), rng.uniform(y0, y1, m)),
    })


def _assert_tables_identical(a, b, type_name="t"):
    import jax

    for idx in a.indexes(type_name):
        ta, tb = a.table(type_name, idx.name), b.table(type_name, idx.name)
        assert type(ta) is type(tb), idx.name
        assert np.array_equal(
            np.asarray(ta.perm, np.int64), np.asarray(tb.perm, np.int64)
        ), f"{idx.name} perm"
        assert np.array_equal(ta.bins, tb.bins), f"{idx.name} bins"
        assert np.array_equal(ta.zs, tb.zs), f"{idx.name} zs"
        cols_a = getattr(ta, "cols3", None)
        if cols_a is not None:
            for k in cols_a:
                assert np.array_equal(
                    np.asarray(jax.device_get(cols_a[k])),
                    np.asarray(jax.device_get(tb.cols3[k])),
                ), (idx.name, k)
    fa, fb = a.features(type_name), b.features(type_name)
    assert fa.ids.tolist() == fb.ids.tolist()
    for col in fa.columns:
        ca, cb = fa.columns[col], fb.columns[col]
        if hasattr(ca, "x"):
            assert np.array_equal(ca.x, cb.x) and np.array_equal(ca.y, cb.y)
        else:
            assert np.array_equal(np.asarray(ca), np.asarray(cb)), col


def _star(cx, cy, r, n_arms=9):
    a = np.linspace(0, 2 * np.pi, 2 * n_arms + 1)[:-1]
    rad = np.where(np.arange(2 * n_arms) % 2 == 0, r, 0.35 * r)
    return geo.Polygon(
        [(cx + rr * np.cos(t), cy + rr * np.sin(t)) for t, rr in zip(a, rad)]
    )


# -- the incremental fold (DataStore.fold_upsert) --------------------------


class TestFoldUpsert:
    @pytest.mark.parametrize("n_upd,n_new", [
        (300, 200),   # mixed replace + append
        (500, 0),     # pure replace
        (0, 400),     # pure append
        (1, 1),       # minimal
        (4000, 100),  # replace EVERY existing row
    ])
    def test_bit_identical_to_upsert(self, n_upd, n_new):
        a, b = _build(), _build()
        rng = np.random.default_rng(7)
        upd = rng.choice(4000, n_upd, replace=False) if n_upd else []
        ids = [f"f{i}" for i in upd] + [f"g{j}" for j in range(n_new)]
        sft = a.get_schema("t")
        batch = _batch(sft, ids, seed=11, name="u")
        a.upsert("t", batch)
        a.compact("t")
        assert b.fold_upsert("t", batch) == len(ids)
        b.compact("t")  # pure appends ride the delta tier until compaction
        _assert_tables_identical(a, b)
        for q in [
            "bbox(geom,-20,-20,40,40)",
            "bbox(geom,0,0,10,10) AND dtg DURING "
            "2024-01-01T00:00:00Z/2024-01-20T00:00:00Z",
        ]:
            ra, rb = a.query("t", q), b.query("t", q)
            assert ra.ids.tolist() == rb.ids.tolist(), q

    def test_tie_keys_bit_identical_to_full_recompaction(self):
        """Duplicate positions/timestamps (identical (bin, z) keys) pin
        the stable tie order: folded rows must land exactly where the
        whole-table stable sort of ``concat(survivors, batch)`` puts
        them — the from-scratch recompaction order. (The delete-and-
        rewrite ``upsert`` path routes ties through ``merged_table``'s
        insert-before rule instead; result SETS are identical, the
        sorted tie order is not, so the oracle here is a fresh build.)"""
        a, b = _build(n=0), _build(n=0)
        sft = a.get_schema("t")
        n = 512
        base = FeatureCollection.from_columns(
            sft, [f"f{i}" for i in range(n)], {
                "name": np.array(["n"] * n),
                "dtg": np.full(n, T0, np.int64),
                "geom": (np.repeat(np.arange(8.0), n // 8),
                         np.zeros(n)),
            })
        for ds in (a, b):
            ds.write("t", base)
            ds.compact("t")
        ids = [f"f{i}" for i in range(0, 200, 2)] + ["x1", "x2", "x3"]
        m = len(ids)
        batch = FeatureCollection.from_columns(sft, ids, {
            "name": np.array(["u"] * m),
            "dtg": np.full(m, T0, np.int64),
            "geom": (np.repeat(np.arange(8.0), -(-m // 8))[:m], np.zeros(m)),
        })
        b.fold_upsert("t", batch)
        # full-recompaction oracle: survivors (ordinal order) + batch,
        # written once into a fresh store and sorted from scratch
        keep = np.ones(n, bool)
        keep[[int(i[1:]) for i in ids if i.startswith("f")]] = False
        a.delete_schema("t")
        a.create_schema(FeatureType.from_spec("t", SPEC))
        a.write("t", FeatureCollection.concat([base.mask(keep), batch]))
        a.compact("t")
        _assert_tables_identical(a, b)

    def test_attribute_index_falls_back_but_matches(self):
        spec = SPEC.replace("name:String", "name:String:index=true")
        a, b = _build(spec=spec), _build(spec=spec)
        sft = a.get_schema("t")
        ids = [f"f{i}" for i in range(50, 150)] + ["new0", "new1"]
        batch = _batch(sft, ids, seed=3, name="upd")
        a.upsert("t", batch)
        a.compact("t")
        b.fold_upsert("t", batch)
        _assert_tables_identical(a, b)
        assert (
            a.query("t", "name = 'upd'").ids.tolist()
            == b.query("t", "name = 'upd'").ids.tolist()
        )

    def test_empty_store_and_empty_batch(self):
        ds = _build(n=0)
        sft = ds.get_schema("t")
        assert ds.fold_upsert("t", FeatureCollection.from_rows(sft, [])) == 0
        assert ds.fold_upsert("t", _batch(sft, ["a", "b"], seed=5)) == 2
        assert len(ds.features("t")) == 2
        # duplicate ids within a batch are refused before any mutation
        with pytest.raises(ValueError):
            ds.fold_upsert("t", _batch(sft, ["c", "c"], seed=6))
        assert len(ds.features("t")) == 2

    def test_uncompacted_delta_folds_first(self):
        a, b = _build(), _build()
        sft = a.get_schema("t")
        extra = _batch(sft, [f"d{i}" for i in range(100)], seed=9)
        for ds in (a, b):
            ds.write("t", extra)  # below the compaction threshold: host delta
        batch = _batch(sft, [f"f{i}" for i in range(40)] + ["d1", "q0"], seed=13)
        a.upsert("t", batch)
        a.compact("t")
        b.fold_upsert("t", batch)
        _assert_tables_identical(a, b)

    def test_scoped_invalidation_preserves_unrelated_entries(self):
        """The fold bumps generations over the touched key ranges only:
        a warm cached result over an untouched region must survive the
        flush (the round-8 whole-type compaction bump killed it)."""
        reg = MetricsRegistry()
        ds = _build(cache=True, metrics=reg, seed=21)
        sft = ds.get_schema("t")
        far = "bbox(geom, 40, 40, 55, 55)"
        near = "bbox(geom, -55, -55, -40, -40)"
        n_far, n_near = len(ds.query("t", far)), len(ds.query("t", near))
        # fold a batch strictly inside the NEAR region
        batch = _batch(sft, [f"z{i}" for i in range(50)], seed=22,
                       box=(-54.0, -54.0, -41.0, -41.0))
        ds.fold_upsert("t", batch)
        h0 = reg.counters.get("geomesa.cache.hit", 0)
        assert len(ds.query("t", far)) == n_far       # served from cache
        assert reg.counters.get("geomesa.cache.hit", 0) == h0 + 1
        # the touched region's entry was invalidated AND the fresh scan
        # sees the folded rows
        assert len(ds.query("t", near)) == n_near + 50


# -- the sliced fold (round 11: kill the fold pause) ------------------------


def _adversarial_batch(sft, seed=31):
    """A fold batch crafted for adversarial slice boundaries: a
    pure-APPEND prefix (a slice with nothing to replace), a pure-UPDATE
    run, then a mixed tail — so small ``slice_rows`` values cut slices
    of every composition, straddling chunk/bin boundaries."""
    rng = np.random.default_rng(seed)
    upd = rng.choice(4000, 600, replace=False)
    ids = (
        [f"n{j}" for j in range(150)]
        + [f"f{i}" for i in upd[:400]]
        + [f"n{150 + j}" for j in range(50)]
        + [f"f{i}" for i in upd[400:]]
    )
    return ids, _batch(sft, ids, seed=seed + 1, name="u")


class TestSlicedFold:
    # 64 < the tile-64 block (4096 rows); 100 straddles the batch's
    # composition boundaries; 1000 gives one fat slice + a remainder
    @pytest.mark.parametrize("slice_rows", [64, 100, 1000])
    def test_bit_identical_to_monolithic_and_recompaction(self, slice_rows):
        a, b, c = _build(), _build(), _build()
        sft = a.get_schema("t")
        ids, batch = _adversarial_batch(sft)
        a.fold_upsert("t", batch)  # monolithic (slice_rows default off at this size)
        published: list = []
        b.fold_upsert(
            "t", batch, slice_rows=slice_rows,
            on_slice=lambda i: published.append(list(i)),
        )
        # every id published exactly once, in batch order, per slice
        assert [f for sl in published for f in sl] == ids
        assert len(published) == -(-len(ids) // slice_rows)
        _assert_tables_identical(a, b)
        # and against the delete-and-rewrite recompaction oracle
        c.upsert("t", batch)
        for ds in (b, c):
            ds.compact("t")
        for q in [
            "bbox(geom,-20,-20,40,40)",
            "bbox(geom,0,0,10,10) AND dtg DURING "
            "2024-01-01T00:00:00Z/2024-01-20T00:00:00Z",
        ]:
            assert sorted(b.query("t", q).ids.tolist()) == sorted(
                c.query("t", q).ids.tolist()
            ), q

    def test_mid_fold_state_is_exact_prefix_fold(self):
        """A crash between slices leaves EXACTLY the fold of the applied
        batch prefix — one live version of every id, queries consistent
        — and re-folding the whole batch converges (idempotent)."""
        a, b = _build(), _build()
        sft = a.get_schema("t")
        ids, batch = _adversarial_batch(sft)
        sr = 128
        with fault.inject("stream.fold.slice", kind="crash", after=2, times=1):
            with pytest.raises(fault.InjectedCrash):
                b.fold_upsert("t", batch, slice_rows=sr)
        # prefix oracle: fold of the first two slices only
        prefix = batch.take(np.arange(2 * sr))
        a.fold_upsert("t", prefix)
        _assert_tables_identical(a, b)
        # retry converges to the full fold, bit-identical to monolithic
        b.fold_upsert("t", batch, slice_rows=sr)
        c = _build()
        c.fold_upsert("t", batch)
        for q in ["bbox(geom,-60,-60,60,60)", "bbox(geom,-5,-5,25,25)"]:
            assert sorted(b.query("t", q).ids.tolist()) == sorted(
                c.query("t", q).ids.tolist()
            ), q

    def test_fold_fault_matrix_publish_and_stage(self):
        """crash/io_error at the new stream.fold.* points: an io_error
        retries inside the flusher's bounded retry (the whole-batch
        re-fold is idempotent over published slices); a crash surfaces
        with the published prefix committed, hot rows resident, and
        LambdaStore reads exact throughout (hot-wins shadowing)."""
        ds = _build(n=2000, seed=8)
        lam = LambdaStore(ds, "t", config=StreamConfig(
            chunk_rows=256, fold_rows=1, slice_rows=200,
        ))
        rows = [
            {"name": "v2", "dtg": T0 + i, "geom": geo.Point(i * 0.01, 2.0)}
            for i in range(800)
        ]
        ids = [f"f{i}" for i in range(600)] + [f"x{j}" for j in range(200)]
        lam.write([dict(r) for r in rows], ids=ids)
        expect = sorted(
            [f"f{i}" for i in range(600, 2000)] + ids
        )
        # crash mid-fold: published prefix + resident hot = exact reads
        with fault.inject("stream.fold.publish", kind="crash", after=1, times=1):
            with pytest.raises(fault.InjectedCrash):
                lam.flush()
        assert len(lam.hot) == 800  # eviction never ran
        got = sorted(str(i) for i in lam.query("bbox(geom,-60,-60,60,60)").ids.tolist())
        assert got == expect
        # transient io_error at the slice point: retried internally
        with fault.inject("stream.fold.slice", kind="io_error", times=1):
            assert lam.flush() == 800
        assert len(lam.hot) == 0
        got = sorted(str(i) for i in lam.query("bbox(geom,-60,-60,60,60)").ids.tolist())
        assert got == expect
        lam.close()

    def test_stage_fault_leaves_flush_atomic(self):
        """A fault while PRE-STAGING (micro-flush time) aborts that flush
        before any publish; the retry re-stages and converges."""
        ds = _build(n=500, seed=9)
        lam = LambdaStore(ds, "t", config=StreamConfig(chunk_rows=64))
        before = len(ds.features("t"))
        lam.write([
            {"name": "u", "dtg": T0 + i, "geom": geo.Point(i * 0.01, -1.0)}
            for i in range(100)
        ], ids=[f"f{i}" for i in range(50)] + [f"new{j}" for j in range(50)])
        with fault.inject("stream.fold.stage", kind="io_error", times=None):
            with pytest.raises(OSError):
                lam.flush()
        assert len(ds.features("t")) == before  # nothing published
        assert len(lam.hot) == 100
        assert lam.flush() == 50   # appends publish; updates stay deferred
        assert lam.persist_hot() == 50
        assert len(lam.hot) == 0
        lam.close()

    def test_prestaged_rows_skip_fold_window_parse(self):
        """Deferred updates parse/key at micro-flush time; the fold
        window re-parses NOTHING when no rows changed after staging —
        and a row re-updated after staging folds its NEWEST version."""
        reg = MetricsRegistry()
        ds = _build(n=1000, seed=10, metrics=reg)
        lam = LambdaStore(ds, "t", config=StreamConfig(chunk_rows=128))
        upd = [
            {"name": "s1", "dtg": T0 + i, "geom": geo.Point(i * 0.01, 3.0)}
            for i in range(200)
        ]
        lam.write([dict(r) for r in upd], ids=[f"f{i}" for i in range(200)])
        assert lam.flush() == 0     # pure updates: deferred + pre-staged
        assert reg.counter_value("geomesa.stream.fold.prestaged") == 200
        # re-update a subset AFTER staging: the newer rows must win
        lam.write([
            {"name": "s2", "dtg": T0 + i, "geom": geo.Point(i * 0.01, 3.5)}
            for i in range(40)
        ], ids=[f"f{i}" for i in range(40)])
        assert lam.flush() == 0
        # second stage covers only the re-updated rows
        assert reg.counter_value("geomesa.stream.fold.prestaged") == 240
        for _ch, fut in list(lam.flusher._staged):
            fut.result()  # staging is async: settle before counting
        parses = reg.histograms["geomesa.stream.parse"].count
        assert parses > 0  # the pre-staging itself parsed (not vacuous)
        assert lam.persist_hot() == 200
        # the fold window parsed nothing fresh: every row came pre-staged
        assert reg.histograms["geomesa.stream.parse"].count == parses
        assert sorted(
            str(i) for i in lam.query("name = 's2'").ids.tolist()
        ) == [f"f{i}" for i in sorted(range(40), key=str)]
        assert len(lam.query("name = 's1'")) == 160
        lam.close()

    def test_deleted_rows_release_staged_chunks(self):
        """Update-then-delete must not pin pre-staged fold state forever
        (the staged chunk's rows never re-enter a flush snapshot): the
        hot-tier removal hooks drop the staged chunk + bookkeeping."""
        ds = _build(n=300, seed=14)
        lam = LambdaStore(ds, "t", config=StreamConfig(chunk_rows=64))
        for cycle in range(3):
            lam.write([
                {"name": f"c{cycle}", "dtg": T0 + i,
                 "geom": geo.Point(i * 0.01, -2.0)}
                for i in range(40)
            ], ids=[f"f{i}" for i in range(40)])
            assert lam.flush() == 0  # pure updates: deferred + staged
            assert len(lam.flusher._staged) >= 1
            lam.delete([f"f{i}" for i in range(40)])
            assert lam.flusher._staged == []         # chunk released
            assert lam.flusher._staged_rows == {}    # bookkeeping too
        # and the store still answers exactly (the rows are gone hot,
        # stale cold copies shadowed... deletes are hot-tier only, so
        # the ORIGINAL cold rows resurface — the documented semantics)
        assert len(lam.query("name = 'c2'")) == 0
        lam.close()

    def test_unstage_during_fold_wait_stays_dropped(self):
        """A hot-tier delete landing WHILE a fold waits on staged
        futures must stay dropped: the fold's write-back may not
        resurrect a chunk unstage() released mid-wait (and must pop
        bookkeeping identity-conditionally, so concurrent re-staging
        keeps its entry)."""
        ds = _build(n=200, seed=15)
        lam = LambdaStore(ds, "t", config=StreamConfig(chunk_rows=32))
        fl = lam.flusher
        mk = lambda lo: [
            {"name": "s", "dtg": T0 + i, "geom": geo.Point(i * 0.01, 1.0)}
            for i in range(lo, lo + 32)
        ]
        rows_a, rows_b = mk(0), mk(32)
        ids_a = [f"f{i}" for i in range(32)]
        ids_b = [f"f{i}" for i in range(32, 64)]
        with fault.inject(
            "stream.flush.keys", kind="latency", times=None, delay_s=0.3
        ):
            fl.stage(list(zip(ids_a, rows_a)))  # chunk A: in the batch
            fl.stage(list(zip(ids_b, rows_b)))  # chunk B: retained side
            t = threading.Thread(
                target=lambda: (time.sleep(0.05), fl.unstage(ids_b))
            )
            t.start()
            # B is classified retained instantly; A's future wait spans
            # the concurrent unstage of B
            consumed, rest = fl._take_staged(list(zip(ids_a, rows_a)))
            t.join()
        assert [fid for ch in consumed for fid in ch.ids] == ids_a
        assert rest == []
        assert fl._staged == []        # B not resurrected
        assert fl._staged_rows == {}   # A spent + B unstaged
        lam.close()

    def test_concurrent_cached_reads_exact_mid_slice(self):
        """Readers racing a sliced fold (latency-widened mid-slice
        windows, cache tier on) must observe the exact hot-wins answer
        at EVERY instant — never a half-applied fold."""
        reg = MetricsRegistry()
        ds = _build(n=3000, seed=41, cache=True, metrics=reg)
        lam = LambdaStore(ds, "t", config=StreamConfig(
            chunk_rows=256, fold_rows=1, slice_rows=150,
        ))
        rows = [
            {"name": "mid", "dtg": T0 + i, "geom": geo.Point(i * 0.001, 0.5)}
            for i in range(600)
        ]
        ids = [f"f{i}" for i in range(500)] + [f"m{j}" for j in range(100)]
        lam.write([dict(r) for r in rows], ids=ids)
        expect = sorted(
            [f"f{i}" for i in range(500, 3000)] + ids
        )
        q = "bbox(geom,-60,-60,60,60)"
        errors: list = []
        stop = threading.Event()

        def reader():
            while not stop.is_set():
                got = sorted(str(i) for i in lam.query(q).ids.tolist())
                if got != expect:
                    errors.append(len(got))

        t = threading.Thread(target=reader)
        t.start()
        try:
            with fault.inject(
                "stream.fold.publish", kind="latency", times=None,
                delay_s=0.01,
            ):
                lam.flush()  # the sliced fold, slices paused open
        finally:
            stop.set()
            t.join()
        assert not errors
        assert reg.counter_value("geomesa.stream.fold.slices") >= 2
        lam.close()

    def test_device_fold_plan_bit_identical_to_host_path(self):
        from geomesa_tpu import conf

        a, b = _build(), _build()
        sft = a.get_schema("t")
        _, batch = _adversarial_batch(sft, seed=51)
        conf.STREAM_FOLD_DEVICE.set("on")  # auto is TPU-only; force here
        try:
            a.fold_upsert("t", batch, slice_rows=128)
        finally:
            conf.STREAM_FOLD_DEVICE.clear()
        b.fold_upsert("t", batch, slice_rows=128)  # CPU auto: host path
        _assert_tables_identical(a, b)

    def test_fold_progress_surfaces_in_explain_and_gauge(self):
        reg = MetricsRegistry()
        ds = _build(n=2000, seed=12, metrics=reg)
        sft = ds.get_schema("t")
        _, batch = _adversarial_batch(sft, seed=13)
        seen: list = []

        def pacer():
            # mid-fold: the progress surface is live for explain + gauge
            seen.append(ds._fold_progress.get("t"))
            from geomesa_tpu.planning.explain import Explainer

            exp = Explainer()
            plan = ds.planner.plan("t", "bbox(geom,-10,-10,10,10)")
            ds.planner.execute(plan, explain=exp)
            assert any("fold in progress" in ln.lower() for ln in exp.lines)

        ds.fold_upsert("t", batch, slice_rows=200, pacer=pacer)
        assert seen and all(s is not None for s in seen)
        assert reg.counter_value("geomesa.stream.fold.slices") == -(-800 // 200)
        assert ds._fold_progress.get("t") is None  # cleared after
        assert ds.last_fold_report["slices"] == -(-800 // 200)
        assert len(ds.last_fold_report["slice_s"]) == -(-800 // 200)


# -- the pipelined flusher -------------------------------------------------


class TestStreamFlusher:
    def _lambda(self, n=2000, seed=0, metrics=None, config=None, cache=None):
        ds = _build(n=n, seed=seed, metrics=metrics, cache=cache)
        return ds, LambdaStore(ds, "t", config=config)

    def test_incremental_flush_matches_legacy(self):
        ds_i, lam_i = self._lambda()
        ds_l, lam_l = self._lambda()
        sft = ds_i.get_schema("t")
        rows = [
            {"name": "h", "dtg": T0 + i, "geom": geo.Point(i * 0.01, -i * 0.01)}
            for i in range(500)
        ]
        ids = [f"f{i}" for i in range(250)] + [f"h{i}" for i in range(250)]
        lam_i.write(rows, ids=ids)
        lam_l.write(rows, ids=ids)
        # micro-batch flush: the 250 NEW ids append; the 250 updates stay
        # in the hot overlay (below the fold threshold) — reads exact
        assert lam_i.flush(incremental=True) == 250
        assert len(lam_i.hot) == 250
        assert lam_l.flush(incremental=False) == 500
        for q in ["bbox(geom,-60,-60,60,60)", "name = 'h'"]:
            ri = sorted(lam_i.query(q).ids.tolist())
            rl = sorted(lam_l.query(q).ids.tolist())
            assert ri == rl, q
        # full persist folds the pending updates; still identical
        assert lam_i.persist_hot() == 250
        assert len(lam_i.hot) == 0
        for q in ["bbox(geom,-60,-60,60,60)", "name = 'h'"]:
            ri = sorted(lam_i.query(q).ids.tolist())
            rl = sorted(lam_l.query(q).ids.tolist())
            assert ri == rl, q
        lam_i.close(), lam_l.close()

    def test_stage_metrics_and_admission_window(self):
        reg = MetricsRegistry()
        cfg = StreamConfig(workers=2, chunk_rows=64, queue_depth=1)
        ds, lam = self._lambda(metrics=reg, config=cfg)
        lam.write([
            {"name": "h", "dtg": T0 + i, "geom": geo.Point(i * 0.001, 0.0)}
            for i in range(1000)
        ], ids=[f"h{i}" for i in range(1000)])
        assert lam.flush() == 1000
        for stage in ("parse", "keys", "sort", "commit"):
            h = reg.histograms.get(f"geomesa.stream.{stage}")
            assert h is not None and h.count >= 1, stage
        assert reg.counters.get("geomesa.stream.flushes") == 1
        assert reg.counters.get("geomesa.stream.rows") == 1000
        # 1000 rows / 64-row chunks through a 1-deep window: staging blocked
        assert reg.counters.get("geomesa.stream.queue_full", 0) > 0
        assert reg.gauges.get("geomesa.stream.hot_rows") == 0.0
        lam.close()

    def test_expiring_hot_tier_always_drains(self):
        """With expiry_ms configured, flush() must drain the overlay
        fully: an expire() sweep between flushes would otherwise drop a
        pending (unpersisted) update and resurface the stale cold row."""
        ds = _build(n=50, seed=17)
        lam = LambdaStore(ds, "t", config=StreamConfig(fold_rows=10**9))
        lam.hot.expiry_ms = 1
        lam.write([{"name": "upd", "dtg": T0, "geom": geo.Point(1.0, 1.0)}],
                  ids=["f0"])  # an UPDATE of a persisted id
        assert lam.flush() == 1   # drained despite the huge fold threshold
        assert len(lam.hot) == 0
        lam.hot.expire(now_ms=int(time.time() * 1000) + 10_000)
        out = ds.query("t", "IN ('f0')")
        assert np.asarray(out.columns["name"])[0] == "upd"
        lam.close()

    def test_worker_pool_warm_across_flushes(self):
        ds, lam = self._lambda()
        lam.write([{"name": "a", "dtg": T0, "geom": geo.Point(1, 1)}], ids=["a"])
        lam.flush()
        pool1 = lam.flusher._pool
        lam.write([{"name": "b", "dtg": T0, "geom": geo.Point(2, 2)}], ids=["b"])
        lam.flush()
        assert lam.flusher._pool is pool1  # kept warm, not rebuilt
        assert lam.flusher.flushes == 2
        lam.close()
        assert lam.flusher._pool is None
        lam.close()  # idempotent
        # a closed flusher recovers on the next flush
        lam.write([{"name": "c", "dtg": T0, "geom": geo.Point(3, 3)}], ids=["c"])
        assert lam.flush() == 1


# -- flush atomicity: the crash/fault matrix -------------------------------


class TestFlushFaultMatrix:
    POINTS = (
        "stream.flush.parse", "stream.flush.keys", "stream.flush.sort",
        "streaming.persist",
    )

    def _lambda(self, tmp_path):
        from geomesa_tpu.storage import persist

        ds = _build(n=300, seed=3)
        root = tmp_path / "cold"
        persist.save(ds, root)
        lam = LambdaStore(ds, "t", config=StreamConfig(chunk_rows=32))
        lam.write([
            {"name": "h", "dtg": T0 + i, "geom": geo.Point(i * 0.01, 1.0)}
            for i in range(100)
        ], ids=[f"f{i}" for i in range(50)] + [f"h{i}" for i in range(50)])
        return ds, lam, root

    @staticmethod
    def _state(ds):
        fc = ds.features("t")
        return (
            fc.ids.tolist(),
            np.asarray(fc.columns["name"]).tolist(),
            {i.name: np.asarray(ds.table("t", i.name).zs).tobytes()
             for i in ds.indexes("t")},
        )

    @pytest.mark.parametrize("point", POINTS)
    @pytest.mark.parametrize("kind", ["crash", "io_error"])
    def test_fault_leaves_cold_untouched_hot_resident(
        self, tmp_path, point, kind
    ):
        from geomesa_tpu.storage import persist

        ds, lam, root = self._lambda(tmp_path)
        before = self._state(ds)
        exc = fault.InjectedCrash if kind == "crash" else OSError
        with fault.inject(point, kind=kind, times=None):
            with pytest.raises(exc):
                lam.persist_hot()
        assert self._state(ds) == before   # cold tier untouched
        assert len(lam.hot) == 100         # every hot row still resident
        # the on-disk store never tore: reload clean, no quarantine
        back = persist.load(root)
        assert back.store_health.status == "ok"
        assert not (root / "_quarantine").exists()
        # the fault cleared: the SAME flusher (warm pool) converges
        assert lam.persist_hot() == 100
        assert len(lam.hot) == 0
        assert "h0" in ds.features("t").ids.tolist()
        lam.close()

    def test_transient_commit_fault_retries_internally(self, tmp_path):
        ds, lam, _ = self._lambda(tmp_path)
        with fault.inject("streaming.persist", kind="io_error", times=1):
            assert lam.persist_hot() == 100  # one blip, retried inside
        assert len(lam.hot) == 0
        lam.close()


# -- exact reads under writes ----------------------------------------------


class TestExactReadsUnderFlush:
    def test_mid_persist_window_no_double_count(self):
        """Regression (round-8 bug): between the cold commit and the hot
        eviction a flushed row lives in BOTH tiers; queries racing that
        window returned/counted it twice. The ``streaming.evict`` fault
        point pauses the window open; queries inside it must dedup."""
        ds = _build(n=200, seed=5)
        lam = LambdaStore(ds, "t")
        lam.write([
            {"name": "h", "dtg": T0 + i, "geom": geo.Point(0.5 + i * 1e-4, 0.5)}
            for i in range(20)
        ], ids=[f"f{i}" for i in range(10)] + [f"h{i}" for i in range(10)])
        q = "bbox(geom, 0, 0, 1, 1)"
        expect = sorted(lam.query(q).ids.tolist())
        n_total_before = lam.count()
        in_window = threading.Event()
        done: list = []

        def flush():
            with fault.inject("streaming.evict", kind="latency", delay_s=1.0):
                done.append(lam.persist_hot())

        t = threading.Thread(target=flush)
        t.start()
        # wait until the cold commit landed (the window is open: rows in
        # BOTH tiers, eviction paused behind the latency fault)
        deadline = time.monotonic() + 10
        while "h0" not in ds.features("t").ids.tolist():
            assert time.monotonic() < deadline, "flush never committed"
            time.sleep(0.01)
        in_window.set()
        out = lam.query(q)
        got = out.ids.tolist()
        assert len(got) == len(set(got)), "duplicate ids mid-persist"
        assert sorted(got) == expect
        assert lam.count() == n_total_before
        # a write racing the evict window must survive it: the flush may
        # only evict the exact row versions it persisted
        lam.write([{"name": "late", "dtg": T0, "geom": geo.Point(0.6, 0.6)}],
                  ids=["h0"])
        t.join()
        assert done == [20]
        assert "h0" in lam.hot._rows  # the racing write stayed resident
        late = lam.query(q)
        names = dict(zip(late.ids.tolist(), np.asarray(late.columns["name"])))
        assert names["h0"] == "late"
        # after the window closes the answer is unchanged
        assert sorted(lam.query(q).ids.tolist()) == expect
        lam.close()

    def test_hot_update_shadows_stale_cold_copy(self):
        ds = _build(n=50, seed=6)
        lam = LambdaStore(ds, "t")
        lam.write([{"name": "v1", "dtg": T0, "geom": geo.Point(0.1, 0.1)}],
                  ids=["m"])
        lam.flush()
        # the update moves the feature OUT of the window: the stale cold
        # copy must be hidden even before any flush
        lam.write([{"name": "v2", "dtg": T0, "geom": geo.Point(30.0, 30.0)}],
                  ids=["m"])
        assert "m" not in lam.query("bbox(geom, 0, 0, 1, 1)").ids.tolist()
        out = lam.query("bbox(geom, 29, 29, 31, 31)")
        assert out.ids.tolist() == ["m"]
        assert np.asarray(out.columns["name"])[0] == "v2"
        lam.flush()
        out = lam.query("bbox(geom, 29, 29, 31, 31)")
        assert np.asarray(out.columns["name"])[0] == "v2"
        lam.close()

    def test_scheduler_admitted_cold_queries(self):
        reg = MetricsRegistry()
        ds = _build(n=2000, seed=7, metrics=reg)
        lam = LambdaStore(ds, "t")
        seq = {}
        qs = [f"bbox(geom, {i}, {i}, {i + 9}, {i + 9})" for i in range(-40, 40, 10)]
        lam.write([
            {"name": "h", "dtg": T0, "geom": geo.Point(i + 0.5, i + 0.5)}
            for i in range(-40, 40, 10)
        ], ids=[f"s{i}" for i in range(8)])
        for q in qs:
            seq[q] = sorted(lam.query(q).ids.tolist())
        sched = lam.serve()
        assert ds.scheduler is sched and not sched.closed
        s0 = reg.counters.get("geomesa.serving.submitted", 0)
        results: dict = {}
        lock = threading.Lock()

        def worker(q):
            out = sorted(lam.query(q).ids.tolist())
            with lock:
                results[q] = out

        threads = [threading.Thread(target=worker, args=(q,)) for q in qs * 4]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert results == seq
        assert reg.counters.get("geomesa.serving.submitted", 0) >= s0 + len(qs)
        sched.close()
        # scheduler closed: queries fall back to the direct path
        assert sorted(lam.query(qs[0]).ids.tolist()) == seq[qs[0]]
        lam.close()


# -- generation scoping under sustained streaming writes -------------------


class TestStreamingMutationFuzz:
    def test_cached_merge_never_stale_and_unrelated_entries_survive(self):
        """Cached-vs-oracle fuzz under sustained flushes: every merged
        answer over the cache-enabled cold store must equal a fresh
        uncached oracle built from the expected live state; meanwhile a
        repeated query over an untouched far region must keep HITTING
        its cached entry across flushes (scoped invalidation)."""
        reg = MetricsRegistry()
        ds = _build(n=1500, seed=8, cache=True, metrics=reg)
        lam = LambdaStore(ds, "t", config=StreamConfig(chunk_rows=256))
        sft = ds.get_schema("t")
        rng = np.random.default_rng(42)
        state = {}  # id -> (name, x, y, dtg): the expected merged view
        base = ds.features("t")
        bx, by = base.geom_column.x, base.geom_column.y
        bn = np.asarray(base.columns["name"])
        bt = np.asarray(base.columns["dtg"], np.int64)
        for i, fid in enumerate(base.ids.tolist()):
            state[fid] = (bn[i], float(bx[i]), float(by[i]), int(bt[i]))
        far = "bbox(geom, 70, 70, 85, 85)"   # no write ever lands here
        n_far = len(ds.query("t", far))
        queries = [
            "bbox(geom, -30, -30, 0, 0)",
            "bbox(geom, 5, 5, 25, 25)",
            "bbox(geom, -10, -10, 10, 10) AND dtg DURING "
            "2024-01-01T00:00:00Z/2024-01-15T00:00:00Z",
        ]
        far_hits0 = reg.counters.get("geomesa.cache.hit", 0)
        for rnd in range(6):
            # mutate: updates to existing ids + some appends, confined
            # to the [-30, 30] region
            ids = [f"f{int(i)}" for i in rng.choice(1500, 60, replace=False)]
            ids += [f"n{rnd}_{j}" for j in range(20)]
            m = len(ids)
            x = rng.uniform(-30, 30, m)
            y = rng.uniform(-30, 30, m)
            t = T0 + rng.integers(0, 14 * DAY, m).astype(np.int64)
            lam.write([
                {"name": f"r{rnd}", "dtg": int(t[j]),
                 "geom": geo.Point(float(x[j]), float(y[j]))}
                for j in range(m)
            ], ids=ids)
            for j, fid in enumerate(ids):
                state[fid] = (f"r{rnd}", float(x[j]), float(y[j]), int(t[j]))
            if rnd % 2 == 1:
                lam.flush()
            # oracle: an uncached store holding the expected live state
            oracle = DataStore(tile=64)
            oracle.create_schema(FeatureType.from_spec("t", SPEC))
            oids = sorted(state)
            oracle.write("t", FeatureCollection.from_columns(
                oracle.get_schema("t"), oids, {
                    "name": np.array([state[i][0] for i in oids]),
                    "dtg": np.array([state[i][3] for i in oids], np.int64),
                    "geom": (np.array([state[i][1] for i in oids]),
                             np.array([state[i][2] for i in oids])),
                }), check_ids=False)
            for q in queries:
                got = sorted(lam.query(q).ids.tolist())
                want = sorted(oracle.query("t", q).ids.tolist())
                assert got == want, (rnd, q)
            # the far region is untouched by every mutation above: its
            # cached entry must still serve (scoped generation bumps)
            assert len(ds.query("t", far)) == n_far
        assert reg.counters.get("geomesa.cache.hit", 0) > far_hits0
        # a final full persist (drains the pending-update overlay) stays
        # exact and still leaves the far entry warm
        lam.persist_hot()
        assert len(lam.hot) == 0
        oids = sorted(state)
        oracle = DataStore(tile=64)
        oracle.create_schema(FeatureType.from_spec("t", SPEC))
        oracle.write("t", FeatureCollection.from_columns(
            oracle.get_schema("t"), oids, {
                "name": np.array([state[i][0] for i in oids]),
                "dtg": np.array([state[i][3] for i in oids], np.int64),
                "geom": (np.array([state[i][1] for i in oids]),
                         np.array([state[i][2] for i in oids])),
            }), check_ids=False)
        for q in queries:
            assert sorted(lam.query(q).ids.tolist()) == sorted(
                oracle.query("t", q).ids.tolist()
            ), q
        assert len(ds.query("t", far)) == n_far
        lam.close()


# -- satellite: raster aggregation push-down -------------------------------


class TestRasterAggregationPushdown:
    def _store(self, n=120_000, seed=0, metrics=None, auths=None, spec=SPEC):
        rng = np.random.default_rng(seed)
        ds = DataStore(tile=64, metrics=metrics, auths=auths)
        sft = FeatureType.from_spec("t", spec)
        ds.create_schema(sft)
        cols = {
            "name": np.array(["n"] * n),
            "dtg": T0 + rng.integers(0, 30 * DAY, n),
            "geom": (rng.uniform(-20, 20, n), rng.uniform(-20, 20, n)),
        }
        if "vis" in spec:
            cols["vis"] = np.array(["", "admin"] * (n // 2))
        ds.write("t", FeatureCollection.from_columns(
            ds.get_schema("t"), np.arange(n).astype(str), cols),
            check_ids=False)
        return ds

    def _differential(self, ds, f):
        """(host-path results, raster-path results) for count/bounds/
        stats over one filter."""
        conf.RASTER_ENABLED.set(False)
        ds.planner.invalidate_config_memo()
        try:
            host = (
                ds.count("t", f),
                ds.bounds("t", f, estimate=False),
                ds.stats_query("t", "Count()", f)[0].count,
            )
        finally:
            conf.RASTER_ENABLED.set(None)
            ds.planner.invalidate_config_memo()
        rast = (
            ds.count("t", f),
            ds.bounds("t", f),
            ds.stats_query("t", "Count()", f, estimate=True)[0].count,
        )
        return host, rast

    @pytest.mark.parametrize("poly", [
        _star(0, 0, 8),
        _star(5, -5, 3, n_arms=17),
        geo.Polygon(  # concave with a hole
            [(-12, -12), (12, -12), (12, 12), (-12, 12)],
            holes=[[(-6, -6), (6, -6), (6, 6), (-6, 6)]],
        ),
    ])
    def test_count_bounds_stats_match_host_path(self, poly):
        reg = MetricsRegistry()
        ds = self._store(metrics=reg)
        f = Intersects("geom", poly)
        c0 = reg.counters.get("geomesa.query.raster_agg", 0)
        host, rast = self._differential(ds, f)
        assert rast[0] == host[0]
        assert rast[2] == host[2]
        assert host[1] is not None and np.allclose(rast[1], host[1])
        # all three raster-path calls took the push-down, host took none
        assert reg.counters.get("geomesa.query.raster_agg", 0) == c0 + 3

    def test_polygon_with_time_predicate(self):
        ds = self._store(seed=2)
        f = And([
            Intersects("geom", _star(0, 0, 8)),
            During("dtg", T0, T0 + 10 * DAY),
        ])
        host, rast = self._differential(ds, f)
        assert rast[0] == host[0] and rast[2] == host[2]
        assert np.allclose(rast[1], host[1])

    def test_visibility_disables_push_down_exactly(self):
        spec = SPEC + ",vis:String;geomesa.vis.field=vis"
        reg = MetricsRegistry()
        ds = self._store(n=10_000, metrics=reg, auths=[], spec=spec)
        f = Intersects("geom", _star(0, 0, 8))
        c0 = reg.counters.get("geomesa.query.raster_agg", 0)
        n = ds.count("t", f)
        # push-down refused (it cannot evaluate visibility); results
        # still exact through the host path
        assert reg.counters.get("geomesa.query.raster_agg", 0) == c0
        assert n == len(ds.query("t", f))

    def test_disjoint_polygon(self):
        ds = self._store(n=5_000, seed=4)
        f = Intersects("geom", _star(170, 80, 2))
        assert ds.count("t", f) == 0
        assert ds.bounds("t", f) is None
