"""Delta tier: append-after-build ingest, tiered scans, compaction.

VERDICT r2 item 3: write() cost proportional to batch size; queries see
main + delta consistently; compaction folds the delta into the device
table."""

import numpy as np

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu.storage.delta import TieredTable
from geomesa_tpu.storage.table import IndexTable


def _mk(n, seed, id_base=0):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-30, 30, n)
    y = rng.uniform(-30, 30, n)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    t = t0 + rng.integers(0, 21 * 86400_000, n)
    return x, y, t, t0


def _store():
    sft = FeatureType.from_spec("ev", "dtg:Date,*geom:Point:srid=4326")
    ds = DataStore()
    ds.create_schema(sft)
    return ds, sft


class TestDeltaTier:
    def test_appends_stay_in_delta_until_threshold(self):
        ds, sft = _store()
        x, y, t, _ = _mk(10_000, 0)
        fc = FeatureCollection.from_columns(sft, np.arange(10_000), {"dtg": t, "geom": (x, y)})
        ds.write("ev", fc, check_ids=False)
        assert isinstance(ds.table("ev", "z3"), IndexTable)  # first write compacts
        main_table = ds._tables[("ev", "z3")]

        x2, y2, t2, _ = _mk(500, 1)
        fc2 = FeatureCollection.from_columns(
            sft, 10_000 + np.arange(500), {"dtg": t2, "geom": (x2, y2)}
        )
        ds.write("ev", fc2, check_ids=False)
        t2_table = ds.table("ev", "z3")
        assert isinstance(t2_table, TieredTable)
        # the device table was NOT rebuilt: write cost ∝ batch
        assert ds._tables[("ev", "z3")] is main_table
        assert len(t2_table.delta.zs) == 500

    def test_query_sees_main_and_delta(self):
        ds, sft = _store()
        xs, ys, ts = [], [], []
        for k, n in enumerate([20_000, 700, 900]):
            x, y, t, _ = _mk(n, k)
            base = sum(len(a) for a in xs) and sum(len(a) for a in xs)
            fc = FeatureCollection.from_columns(
                sft,
                sum(len(a) for a in xs) + np.arange(n),
                {"dtg": t, "geom": (x, y)},
            )
            xs.append(x); ys.append(y); ts.append(t)
            ds.write("ev", fc, check_ids=False)
        x = np.concatenate(xs); y = np.concatenate(ys); t = np.concatenate(ts)
        t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
        lo, hi = int(t0 + 2 * 86400_000), int(t0 + 12 * 86400_000)
        q = (
            f"bbox(geom, -10, -10, 10, 10) AND dtg DURING "
            f"{np.datetime64(lo, 'ms')}Z/{np.datetime64(hi, 'ms')}Z"
        )
        out = ds.query("ev", q)
        expect = np.flatnonzero(
            (x >= -10) & (x <= 10) & (y >= -10) & (y <= 10) & (t >= lo) & (t < hi)
        )
        assert np.array_equal(np.sort(np.asarray(out.ids, np.int64)), expect)
        # count/estimate paths agree through the tiered table
        assert ds.count("ev", q) == len(expect)

    def test_compaction_folds_delta(self):
        ds, sft = _store()
        x, y, t, _ = _mk(5_000, 0)
        ds.write("ev", FeatureCollection.from_columns(sft, np.arange(5_000), {"dtg": t, "geom": (x, y)}), check_ids=False)
        x2, y2, t2, _ = _mk(300, 1)
        ds.write("ev", FeatureCollection.from_columns(sft, 5_000 + np.arange(300), {"dtg": t2, "geom": (x2, y2)}), check_ids=False)
        assert isinstance(ds.table("ev", "z3"), TieredTable)
        ds.compact("ev")
        tbl = ds.table("ev", "z3")
        assert isinstance(tbl, IndexTable)
        assert tbl.n == 5_300
        out = ds.query("ev", "bbox(geom, -10, -10, 10, 10)")
        m = np.concatenate([x, x2]), np.concatenate([y, y2])
        expect = np.flatnonzero((m[0] >= -10) & (m[0] <= 10) & (m[1] >= -10) & (m[1] <= 10))
        assert np.array_equal(np.sort(np.asarray(out.ids, np.int64)), expect)

    def test_duplicate_id_rejected_across_tiers(self):
        ds, sft = _store()
        x, y, t, _ = _mk(100, 0)
        ds.write("ev", FeatureCollection.from_columns(sft, np.arange(100), {"dtg": t, "geom": (x, y)}))
        x2, y2, t2, _ = _mk(10, 1)
        fc2 = FeatureCollection.from_columns(sft, 95 + np.arange(10), {"dtg": t2, "geom": (x2, y2)})
        try:
            ds.write("ev", fc2)
            assert False, "expected duplicate id error"
        except ValueError:
            pass

    def test_id_lookup_spans_tiers(self):
        ds, sft = _store()
        x, y, t, _ = _mk(1_000, 0)
        ds.write("ev", FeatureCollection.from_columns(sft, np.arange(1_000), {"dtg": t, "geom": (x, y)}), check_ids=False)
        x2, y2, t2, _ = _mk(50, 1)
        ds.write("ev", FeatureCollection.from_columns(sft, 1_000 + np.arange(50), {"dtg": t2, "geom": (x2, y2)}), check_ids=False)
        out = ds.query("ev", "IN ('3', '1020', '99999')")
        got = sorted(int(v) for v in out.ids)
        assert got == [3, 1020]


class TestDeleteFeatures:
    def test_delete_by_filter(self):
        ds, sft = _store()
        x, y, t, _ = _mk(2_000, 0)
        ds.write("ev", FeatureCollection.from_columns(sft, np.arange(2_000), {"dtg": t, "geom": (x, y)}), check_ids=False)
        inside = np.flatnonzero((x >= -5) & (x <= 5) & (y >= -5) & (y <= 5))
        removed = ds.delete_features("ev", "bbox(geom, -5, -5, 5, 5)")
        assert removed == len(inside)
        assert len(ds.features("ev")) == 2_000 - removed
        assert ds.count("ev", "bbox(geom, -5, -5, 5, 5)") == 0
        # survivors still queryable and exact
        out = ds.query("ev", "bbox(geom, -30, -30, 30, 30)")
        assert len(out) == 2_000 - removed
