"""Standing queries: the inverted subscription index (docs/standing.md).

Layers:

- **routing + registration**: FULL-cell zero-geometry matches, bulk vs
  per-subscription registration equivalence, replace/unregister;
- **the matcher differential suite**: fused-vs-host bit identity over
  mixed E-ladder candidate blocks, and the shapely oracle fuzz over
  concave/holed/sliver polygons including shared-boundary points
  (``contains`` ⊆ matched ⊆ ``covers`` — the even-odd ray cast may
  break ties either way exactly ON an edge, never off it);
- **windows**: incremental pane maintenance composes BIT-IDENTICALLY to
  a from-scratch recompute over the same pane fold order, tumbling and
  sliding; a WindowedAggregator works as a FeatureStream sink;
- **delivery**: bounded alert queue drops oldest, matcher faults never
  un-acknowledge a write (``standing.match`` / ``standing.deliver``
  fault points), the alert-latency histogram and default SLO objective
  are live;
- **durability**: an acknowledged subscription survives kill -9 —
  through checkpoints that retire its original segment — and a
  kill-anywhere seeded chaos case (no subscription invented, none lost
  past the acked watermark); WAL replay batching is bit-identical to
  record-at-a-time replay;
- **isolation**: dashboard queries through the serving scheduler keep
  their latency while the matcher runs on every batch.
"""

import os
import threading
import time

import numpy as np
import pytest

from geomesa_tpu import conf, fault
from geomesa_tpu import geometry as geo
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.metrics import MetricsRegistry
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.storage import persist
from geomesa_tpu.streaming import (
    AlertQueue,
    LambdaStore,
    StandingConfig,
    StandingQueryEngine,
    StreamConfig,
    Subscription,
    SubscriptionIndex,
    WalConfig,
    WindowSpec,
    WindowedAggregator,
)
from geomesa_tpu.streaming.standing import _ragged_pip, compose_partials

shapely = pytest.importorskip("shapely")
from shapely.geometry import Point as SPoint  # noqa: E402
from shapely.geometry import Polygon as SPolygon  # noqa: E402

SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"
T0 = int(np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64))
SFT = FeatureType.from_spec("t", SPEC)


@pytest.fixture(autouse=True)
def _clean_conf():
    yield
    for prop in (conf.STANDING_FUSED_MIN_POINTS, conf.STANDING_GRID_LEVEL,
                 conf.STREAM_WAL_REPLAY_BATCH, conf.STANDING_QUEUE_MAX):
        prop.clear()
    fault.injector().reset()


def jagged_star(cx, cy, r, n_arms, seed=0):
    rng = np.random.default_rng(seed)
    a = np.linspace(0, 2 * np.pi, 2 * n_arms + 1)[:-1]
    rad = np.where(
        np.arange(2 * n_arms) % 2 == 0, r,
        r * rng.uniform(0.3, 0.7, 2 * n_arms),
    )
    return geo.Polygon(
        [(cx + rr * np.cos(t), cy + rr * np.sin(t)) for t, rr in zip(a, rad)]
    )


def donut(cx, cy, r_out, r_in, n=24):
    a = np.linspace(0, 2 * np.pi, n + 1)
    shell = [(cx + r_out * np.cos(t), cy + r_out * np.sin(t)) for t in a]
    hole = [(cx + r_in * np.cos(t), cy + r_in * np.sin(t)) for t in a]
    return geo.Polygon(shell, [hole])


def to_shapely(p: geo.Polygon) -> SPolygon:
    return SPolygon(p.shell, [h for h in p.holes])


def engine(**cfg) -> StandingQueryEngine:
    return StandingQueryEngine(
        SFT, StandingConfig(**cfg), metrics=MetricsRegistry()
    )


def match_set(eng, x, y, t=None):
    pt, ords = eng.match_points(x, y, t_ms=t)
    ids = eng.index._ids
    return sorted((int(p), ids[int(o)]) for p, o in zip(pt, ords))


# -- routing + registration -------------------------------------------------


class TestSubscriptionIndex:
    def test_full_cells_match_with_zero_geometry_work(self):
        """A big convex polygon at a coarse routing level classifies
        interior cells FULL; points in them route as certain matches
        (full flag), only boundary-cell points carry full=False."""
        idx = SubscriptionIndex(StandingConfig(grid_level=8))
        square = geo.Polygon(
            [(-10, -10), (10, -10), (10, 10), (-10, 10), (-10, -10)]
        )
        idx.register(Subscription("big", "geofence", geom=square))
        pt, ords, full = idx.route(
            np.array([0.0, 9.99, 50.0]), np.array([0.0, 9.99, 50.0])
        )
        got = dict(zip(pt.tolist(), full.tolist()))
        assert got[0] is True      # deep interior: FULL cell, no PIP
        assert got[1] is False     # boundary cell: exact evaluation
        assert 2 not in got        # outside every registered cell

    def test_bulk_registration_equals_per_sub(self):
        rng = np.random.default_rng(3)
        geoms = [
            jagged_star(float(rng.uniform(-40, 40)),
                        float(rng.uniform(-30, 30)),
                        float(rng.uniform(0.2, 2.0)),
                        int(rng.integers(4, 20)), seed=i)
            for i in range(40)
        ]
        ids = [f"g{i}" for i in range(40)]
        a = SubscriptionIndex(StandingConfig())
        a.register_geofences(ids, geoms)
        b = SubscriptionIndex(StandingConfig())
        for i, g in zip(ids, geoms):
            b.register(Subscription(i, "geofence", geom=g))
        x = rng.uniform(-45, 45, 4000)
        y = rng.uniform(-35, 35, 4000)

        def routed(idx):
            pt, ords, full = idx.route(x, y)
            return sorted(zip(
                pt.tolist(), [idx._ids[o] for o in ords.tolist()],
                full.tolist(),
            ))

        assert routed(a) == routed(b)

    def test_replace_and_unregister(self):
        idx = SubscriptionIndex(StandingConfig())
        p1 = geo.Polygon([(0, 0), (1, 0), (1, 1), (0, 1), (0, 0)])
        p2 = geo.Polygon([(50, 50), (51, 50), (51, 51), (50, 51), (50, 50)])
        idx.register(Subscription("a", "geofence", geom=p1))
        idx.register(Subscription("a", "geofence", geom=p2))  # replace
        assert len(idx) == 1
        pt, ords, _ = idx.route(np.array([0.5, 50.5]), np.array([0.5, 50.5]))
        assert pt.tolist() == [1]  # only the replacement's region routes
        assert idx.unregister("a") is True
        assert idx.unregister("a") is False
        pt, _, _ = idx.route(np.array([50.5]), np.array([50.5]))
        assert len(pt) == 0
        # register-then-unregister with the overlay never yet compacted:
        # the all-dead compaction must produce the no-candidates shape,
        # not an empty CSR whose keys[-1] lookup IndexErrors route()
        idx2 = SubscriptionIndex(StandingConfig())
        idx2.register(Subscription("b", "geofence", geom=p1))
        assert idx2.unregister("b") is True
        pt, _, _ = idx2.route(np.array([0.5]), np.array([0.5]))
        assert len(pt) == 0

    def test_bulk_then_mutate_keeps_match_arrays_homogeneous(self):
        """Bulk and per-subscription registration (and the dead-slot
        bbox placeholder) must store the SAME (1, 4) bbox block shape:
        one raw tuple in the mix makes _ensure_arrays' np.asarray
        inhomogeneous — every later match raises, on_batch swallows
        it, and alerts silently stop."""
        idx = SubscriptionIndex(StandingConfig())
        geoms = [
            geo.Polygon([(2.0 * i, 0), (2.0 * i + 1, 0), (2.0 * i + 1, 1),
                         (2.0 * i, 1), (2.0 * i, 0)])
            for i in range(5)
        ]
        idx.register_geofences([f"b{i}" for i in range(5)], geoms)
        assert idx.unregister("b0") is True  # installs the dead bbox
        idx.register(Subscription("x", "geofence", geom=geoms[0]))
        _, _, _, bbox, rect = idx._ensure_arrays()
        assert bbox.shape == (len(idx._ids), 4)
        live = [idx._by_id[s] for s in idx.subscription_ids()]
        assert rect[live].all()  # squares keep their rect fast path
        eng = StandingQueryEngine(SFT, StandingConfig(),
                                  metrics=MetricsRegistry())
        eng.index = idx
        eng.matcher.index = idx
        pt, ords = eng.match_points(np.array([0.5, 2.5]),
                                    np.array([0.5, 0.5]))
        got = sorted((int(p), idx._ids[int(o)]) for p, o in zip(pt, ords))
        assert got == [(0, "x"), (1, "b1")]

    def test_wide_proximity_cover_routes_exactly(self):
        """A wide-radius proximity cover (>4096 routing cells) rides
        the bulk compaction arrays instead of the per-cell overlay
        loop under _lock; routing and matching stay exact."""
        eng = engine()
        eng.register(Subscription("wide", "proximity",
                                  points=[(10.0, 10.0)],
                                  distance_m=600_000.0))
        with eng.index._lock:
            assert eng.index._bulk, "wide cover did not take the bulk path"
        got = match_set(eng, np.array([10.2, 10.0, 40.0]),
                        np.array([10.2, 14.0, 40.0]))
        # (10.2, 10.2) is ~31km away (match); (10, 14) is ~445km
        # (match); (40, 40) is far outside
        assert got == [(0, "wide"), (1, "wide")]

    def test_empty_bulk_registration_keeps_the_gauge(self):
        reg = MetricsRegistry()
        idx = SubscriptionIndex(StandingConfig(), metrics=reg)
        p = geo.Polygon([(0, 0), (1, 0), (1, 1), (0, 1), (0, 0)])
        idx.register(Subscription("a", "geofence", geom=p))
        assert idx.register_geofences([], []) == 1  # a no-op feed tick
        assert reg.gauges["geomesa.standing.subscriptions"] == 1

    def test_replace_frees_dead_ordinal_payloads(self):
        """Ordinal slots are append-only (in-flight routed pairs and
        queued alert blocks stay label-consistent), but a churning
        subscription — a moving geofence re-registered every tick —
        must not retain each dead slot's edge array nor keep feeding
        dead edges into the match-side segment concat."""
        idx = SubscriptionIndex(StandingConfig())
        for step in range(50):
            x0 = float(step) * 0.1
            idx.register(Subscription("mover", "geofence", geom=geo.Polygon(
                [(x0, 0), (x0 + 1, 0), (x0 + 1, 1), (x0, 1), (x0, 0)]
            )))
        assert len(idx) == 1
        live_payloads = sum(e is not None for e in idx._edges_l)
        assert live_payloads == 1, "dead ordinals retained edge arrays"
        _, eoff, segs, _, _ = idx._ensure_arrays()
        assert eoff[-1] == 4, "dead edges leaked into the segment concat"
        # only the LAST position matches
        pt, ords, _ = idx.route(np.array([0.2, 5.4]), np.array([0.5, 0.5]))
        assert pt.tolist() == [1]

    def test_unsubscribe_racing_match_skips_only_that_subscription(self):
        """The matcher resolves proximity/tube side-table params AFTER
        the route snapshot; a concurrent unsubscribe popping the entry
        in that window must skip just that pair — not KeyError the
        whole batch's alerts away (on_batch would swallow it and drop
        every alert, live subscriptions included)."""
        eng = engine()
        eng.register(Subscription("p1", "proximity",
                                  points=[(0.0, 0.0)], distance_m=50_000))
        eng.register(Subscription("p2", "proximity",
                                  points=[(0.5, 0.0)], distance_m=50_000))
        eng.register(Subscription(
            "tb", "tube", track_xy=[(0.0, 0.0), (1.0, 0.0)],
            track_times_ms=[0, 1000], buffer_m=50_000,
        ))
        x = np.array([0.1, 0.45])
        y = np.zeros(2)
        t = np.array([500, 500], np.int64)
        pt, ords, full = eng.index.route(x, y)
        assert len(pt) >= 4  # both points x (both proximities + tube)
        # the race window: params popped between route and match
        with eng.index._lock:
            p1 = eng.index._by_id["p1"]
            tb = eng.index._by_id["tb"]
            eng.index._prox.pop(p1)
            eng.index._tube.pop(tb)
        out_pt, out_ord = eng._match_pairs(x, y, t, pt, ords, full)
        ids = eng.index._ids
        got = sorted((int(p), ids[int(o)]) for p, o in zip(out_pt, out_ord))
        assert got == [(0, "p2"), (1, "p2")]

    def test_kind_validation(self):
        with pytest.raises(ValueError, match="unknown subscription kind"):
            Subscription("x", "nope")
        with pytest.raises(ValueError, match="needs a"):
            SubscriptionIndex(StandingConfig())._cover(
                Subscription("x", "geofence")
            )
        with pytest.raises(ValueError, match="needs points"):
            SubscriptionIndex(StandingConfig())._cover(
                Subscription("x", "proximity", points=[], distance_m=10)
            )
        with pytest.raises(ValueError, match=">= 2 track points"):
            SubscriptionIndex(StandingConfig())._cover(
                Subscription("x", "tube", track_xy=[(0, 0)],
                             track_times_ms=[0], buffer_m=10)
            )


# -- the matcher differential suite ----------------------------------------


FUZZ_POLYGONS = [
    ("concave_star", jagged_star(10.0, 20.0, 3.0, 12, seed=1)),
    ("mid_star_128e", jagged_star(12.0, 21.0, 2.5, 60, seed=2)),
    ("big_star_256e", jagged_star(9.0, 18.5, 4.0, 127, seed=3)),
    ("past_ladder_300e", jagged_star(11.0, 19.0, 3.5, 150, seed=4)),
    ("donut_hole", donut(10.5, 20.5, 3.0, 1.5)),
    ("thin_sliver", geo.Polygon(
        [(8.0, 20.0), (12.0, 20.0001), (12.0, 20.0002), (8.0, 20.0001),
         (8.0, 20.0)]
    )),
]


class TestMatcherDifferential:
    def _events(self, rng, n=6000):
        x = rng.uniform(5.0, 16.0, n)
        y = rng.uniform(14.0, 26.0, n)
        return x, y

    def test_fused_vs_host_bit_identical(self):
        """The fused kernel path (mixed E-ladder candidate blocks in one
        engine) returns the same match set as the all-host ray cast —
        kernel-certain rows are exact, the near band refines through the
        identical f64 construction."""
        rng = np.random.default_rng(5)
        x, y = self._events(rng)
        results = {}
        for label, min_pts in (("fused", 1), ("host", 0)):
            conf.STANDING_FUSED_MIN_POINTS.set(min_pts)
            eng = engine(fused_min_points=min_pts)
            for name, poly in FUZZ_POLYGONS:
                eng.register(Subscription(name, "geofence", geom=poly))
            results[label] = match_set(eng, x, y)
        assert results["fused"] == results["host"]
        # the fused run actually took the kernel path for the dense
        # candidates (past-ladder polygons legitimately stay host-side)
        conf.STANDING_FUSED_MIN_POINTS.clear()

    @pytest.mark.parametrize("name,poly", FUZZ_POLYGONS)
    def test_shapely_oracle(self, name, poly):
        """contains ⊆ matched ⊆ covers, per polygon, on a point cloud
        concentrated around the boundary plus points exactly ON vertices
        and edge midpoints (the shared-boundary cases)."""
        rng = np.random.default_rng(11)
        x0, y0, x1, y1 = poly.bounds()
        pad = max(x1 - x0, y1 - y0) * 0.2 + 1e-3
        x = rng.uniform(x0 - pad, x1 + pad, 3000)
        y = rng.uniform(y0 - pad, y1 + pad, 3000)
        # shared-boundary points: vertices and edge midpoints
        shell = np.asarray(poly.shell, np.float64)
        mids = (shell[:-1] + shell[1:]) / 2.0
        x = np.concatenate([x, shell[:, 0], mids[:, 0]])
        y = np.concatenate([y, shell[:, 1], mids[:, 1]])
        eng = engine(fused_min_points=1)
        eng.register(Subscription("p", "geofence", geom=poly))
        matched = {p for p, _ in match_set(eng, x, y)}
        sp = to_shapely(poly)
        boundary = sp.boundary
        for i in range(len(x)):
            pt = SPoint(float(x[i]), float(y[i]))
            if boundary.distance(pt) <= 1e-9:
                # the tie zone: a point within ulps of an edge (every
                # vertex and float edge-midpoint lands here) may break
                # either way under the even-odd crossing construction —
                # deterministic, but not shapely-decidable; no claim
                continue
            if sp.contains(pt):
                assert i in matched, (name, i, x[i], y[i])
            else:
                assert i not in matched, (name, i, x[i], y[i])

    def test_proximity_and_tube_semantics(self):
        eng = engine()
        eng.register(Subscription(
            "near", "proximity", points=[(0.0, 0.0), (1.0, 1.0)],
            distance_m=30_000,
        ))
        track = np.array([(20.0, 20.0), (21.0, 20.0)])
        eng.register(Subscription(
            "tube", "tube", track_xy=track,
            track_times_ms=[T0, T0 + 3_600_000], buffer_m=25_000,
        ))
        from geomesa_tpu.process.knn import haversine_m

        x = np.array([0.1, 0.9, 3.0, 20.5, 20.5, 20.5])
        y = np.array([0.1, 1.1, 3.0, 20.0, 20.0, 23.0])
        #           in      in    out  mid-track at right/wrong time, far
        t = np.array([T0, T0, T0, T0 + 1_800_000, T0 - 10, T0 + 1_800_000])
        got = match_set(eng, x, y, t)
        assert (0, "near") in got and (1, "near") in got
        assert all(p != 2 for p, _ in got)
        assert (3, "tube") in got
        assert all(not (p == 4 and s == "tube") for p, s in got)
        assert all(not (p == 5 and s == "tube") for p, s in got)
        # the proximity refinement really is haversine min-distance
        d = haversine_m(np.array([0.1]), np.array([0.1]),
                        np.array([0.0]), np.array([0.0]))
        assert d[0] <= 30_000


class TestRectFastPathAndGate:
    def test_rect_fast_path_bit_identical_to_ray_cast(self):
        """An axis-aligned rectangle detected by the registration-time
        rect flag matches identically to the same shape forced through
        the ragged ray cast (5-vertex ring split into 8 segments is NOT
        detected) — including the half-open boundary semantics: left
        and bottom edges inside, right and top edges outside."""
        from geomesa_tpu.streaming.standing import _is_axis_rect

        rect = geo.Polygon([(2.0, 3.0), (6.0, 3.0), (6.0, 9.0),
                            (2.0, 9.0), (2.0, 3.0)])
        # same shape, midpoint-split edges: 8 segments, not flagged
        octo = geo.Polygon([(2.0, 3.0), (4.0, 3.0), (6.0, 3.0),
                            (6.0, 6.0), (6.0, 9.0), (4.0, 9.0),
                            (2.0, 9.0), (2.0, 6.0), (2.0, 3.0)])
        ea = engine()
        ea.register(Subscription("r", "geofence", geom=rect))
        eb = engine()
        eb.register(Subscription("r", "geofence", geom=octo))
        _, _, _, _, rect_a = ea.index._ensure_arrays()
        _, _, _, _, rect_b = eb.index._ensure_arrays()
        assert rect_a[0] and not rect_b[0]
        rng = np.random.default_rng(7)
        x = np.concatenate([rng.uniform(1.0, 7.0, 2000),
                            # exact edges and corners: the tie cases
                            [2.0, 6.0, 4.0, 4.0, 2.0, 6.0, 2.0, 6.0]])
        y = np.concatenate([rng.uniform(2.0, 10.0, 2000),
                            [5.0, 5.0, 3.0, 9.0, 3.0, 3.0, 9.0, 9.0]])
        got_a = match_set(ea, x, y)
        got_b = match_set(eb, x, y)
        assert got_a == got_b
        # half-open: left/bottom edge points in, right/top out
        n = 2000
        assert (n + 0, "r") in got_a      # x == 2.0 (left edge)
        assert (n + 1, "r") not in got_a  # x == 6.0 (right edge)
        assert (n + 2, "r") in got_a      # y == 3.0 (bottom edge)
        assert (n + 3, "r") not in got_a  # y == 9.0 (top edge)

    def test_is_axis_rect_rejects_non_rectangles(self):
        from geomesa_tpu.streaming.standing import (
            _is_axis_rect, _sub_segments,
        )

        def segs(poly):
            return _sub_segments(poly)

        tri = geo.Polygon([(0, 0), (4, 0), (2, 3), (0, 0)])
        assert not _is_axis_rect(segs(tri), tri.bounds())
        box = geo.Polygon([(0, 0), (4, 0), (4, 2), (0, 2), (0, 0)])
        assert _is_axis_rect(segs(box), box.bounds())
        # 4 segments, none axis-aligned: a rotated square
        rot = geo.Polygon([(0, 0), (2, 2), (4, 0), (2, -2), (0, 0)])
        assert not _is_axis_rect(segs(rot), rot.bounds())
        assert not _is_axis_rect(None, (0, 0, 1, 1))

    def test_gate_keeps_slow_fused_on_host_and_counts(self):
        """With the fused side measured slower per unit than the host
        ray cast, every fused-eligible candidate stays on the host path
        (geomesa.standing.gate.host counts them); with the fused side
        measured faster, candidates fuse. Deterministic: the EWMAs are
        seeded directly."""
        star = jagged_star(10.0, 10.0, 2.0, 24, seed=3)
        rng = np.random.default_rng(9)
        x = rng.uniform(8.0, 12.0, 4000)
        y = rng.uniform(8.0, 12.0, 4000)
        for fused_s, expect_fused in ((1e-3, 0), (1e-12, 1)):
            eng = engine(fused_min_points=1)
            eng.register(Subscription("s", "geofence", geom=star))
            eng.gate.update("host_s", 4e-9, 1)   # ~the CPU pip prior
            eng.gate.update("fused_s", fused_s, 1)
            eng.match_points(x, y)
            fused = eng.metrics.counter_value("geomesa.standing.fused")
            kept = eng.metrics.counter_value("geomesa.standing.gate.host")
            if expect_fused:
                assert fused >= 1 and kept == 0
            else:
                assert fused == 0 and kept >= 1

    def test_gate_probe_is_bounded_and_seeds_measurement(self):
        """Unmeasured fused side: the first batch probes exactly ONE
        member through the kernel (a full chunk of dense members costs
        seconds of slot work on a 1-core host) and the probe itself
        seeds the fused EWMA; the rest stay host that batch."""
        eng = engine(fused_min_points=1)
        n = 24
        for i in range(n):
            eng.register(Subscription(
                f"s{i}", "geofence",
                geom=jagged_star(10.0, 10.0, 2.0, 8, seed=i),
            ))
        assert eng.gate.fused_s is None
        x = np.full(16, 10.0)
        y = np.full(16, 10.0)
        eng.match_points(x, y)
        assert eng.gate.fused_s is not None
        assert eng.metrics.counter_value("geomesa.standing.fused") == 1
        assert eng.metrics.counter_value(
            "geomesa.standing.gate.host"
        ) == n - 1

    def test_match_raster_on_off_bit_identical(self):
        """The match-time raster refinement (cell lookup + residue ray
        cast) returns the same match set as the all-pairs ray cast,
        over concave/holed polygons with boundary-concentrated
        points."""
        polys = [("star", jagged_star(10.0, 10.0, 2.0, 24, seed=3)),
                 ("donut", donut(14.0, 18.0, 2.0, 1.0))]
        rng = np.random.default_rng(17)
        x = rng.uniform(7.0, 17.0, 8000)
        y = rng.uniform(7.0, 21.0, 8000)
        results = {}
        for label, cells in (("raster", 262_144), ("plain", 0)):
            eng = engine(fused_min_points=0, raster_cells=cells)
            for name, p in polys:
                eng.register(Subscription(name, "geofence", geom=p))
            assert eng.index.has_rasters() == (cells > 0)
            results[label] = match_set(eng, x, y)
        assert results["raster"] == results["plain"]

    def test_gate_off_always_fuses(self):
        eng = engine(fused_min_points=1, fused_gate=False)
        eng.gate.update("host_s", 1e-12, 1)  # host "measures" free
        eng.gate.update("fused_s", 1.0, 1)   # fused "measures" awful
        eng.register(Subscription(
            "s", "geofence", geom=jagged_star(10.0, 10.0, 2.0, 24, seed=3)
        ))
        eng.match_points(np.full(8, 10.0), np.full(8, 10.0))
        assert eng.metrics.counter_value("geomesa.standing.fused") >= 1
        assert eng.metrics.counter_value("geomesa.standing.gate.host") == 0


# -- windows ----------------------------------------------------------------


class TestWindows:
    def _rows(self, rng, n=500):
        ts = T0 + rng.integers(0, 60_000, n)
        vals = rng.uniform(-1e6, 1e6, n)
        xs = rng.uniform(-50, 50, n)
        ys = rng.uniform(-50, 50, n)
        rows = [
            {"name": "n", "dtg": int(ts[i]), "v": float(vals[i]),
             "geom": geo.Point(float(xs[i]), float(ys[i]))}
            for i in range(n)
        ]
        return rows, ts, vals, xs, ys

    @pytest.mark.parametrize("spec", [
        WindowSpec(size_ms=10_000, agg="count"),
        WindowSpec(size_ms=10_000, slide_ms=4_000, agg="count"),
        WindowSpec(size_ms=12_000, slide_ms=3_000, agg="stats",
                   fieldname="v"),
        WindowSpec(size_ms=8_000, agg="bounds"),
    ])
    def test_compose_equals_recompute_bit_identical(self, spec):
        """Maintaining panes incrementally (many small accept_rows
        batches, arbitrary arrival order) then composing == recomputing
        each window from raw rows grouped by pane, fold order fixed —
        to the BIT, not within epsilon."""
        rng = np.random.default_rng(17)
        rows, ts, vals, xs, ys = self._rows(rng)
        agg = WindowedAggregator(spec, time_field="dtg", max_panes=4096)
        order = rng.permutation(len(rows))
        for s in range(0, len(rows), 37):  # ragged, shuffled batches
            sel = order[s : s + 37]
            agg.accept_rows([rows[i] for i in sel],
                            times_ms=ts[sel], xs=xs[sel], ys=ys[sel])
        upto = int(ts.max()) + spec.size_ms + 1
        got = agg.windows(upto)
        assert got, "no windows composed"
        # oracle: group raw rows by pane IN PANE ORDER, fold each pane
        # in arrival order... pane folds are commutative-free sums, so
        # arrival order inside a pane must not matter for bit identity:
        # the pane partial is a left fold over += of f64 values in
        # ARRIVAL order; recompute with the same arrival order
        pane_ms = spec.pane_ms
        panes: dict = {}
        for s in range(0, len(rows), 37):
            for i in order[s : s + 37]:
                p = panes.setdefault(int(ts[i]) // pane_ms, [])
                p.append(i)
        parts = {}
        for pane, members in panes.items():
            part = {"n": 0}
            if spec.agg == "bounds":
                part = {"n": 0, "minx": np.inf, "miny": np.inf,
                        "maxx": -np.inf, "maxy": -np.inf}
            elif spec.agg == "stats":
                part = {"n": 0, "sum": 0.0, "min": np.inf, "max": -np.inf}
            for i in members:
                part["n"] += 1
                if spec.agg == "bounds":
                    part["minx"] = min(part["minx"], float(xs[i]))
                    part["miny"] = min(part["miny"], float(ys[i]))
                    part["maxx"] = max(part["maxx"], float(xs[i]))
                    part["maxy"] = max(part["maxy"], float(ys[i]))
                elif spec.agg == "stats":
                    part["sum"] = part["sum"] + float(vals[i])
                    part["min"] = min(part["min"], float(vals[i]))
                    part["max"] = max(part["max"], float(vals[i]))
            parts[pane] = part
        slide = spec.effective_slide_ms
        start = (min(panes) * pane_ms // slide) * slide
        want = []
        while start + spec.size_ms <= upto:
            lo = (start + spec.size_ms - spec.size_ms) // pane_ms
            hi = (start + spec.size_ms) // pane_ms
            v = compose_partials(
                spec, [parts[k] for k in range(lo, hi) if k in parts]
            )
            if v["n"]:
                want.append((start, v))
            start += slide
        assert got == want  # bit identity: dict == compares floats by ==

    def test_rows_without_event_time_are_skipped(self):
        """The engine encodes a missing/None dtg as a negative sentinel
        in its extracted time column; the aggregator must skip those
        rows — folding -1 as-is would seed pane -1, inflate counts, and
        stretch windows()' slide walk from ~epoch 0 to now."""
        agg = WindowedAggregator(
            WindowSpec(size_ms=1000, slide_ms=500), metrics=MetricsRegistry()
        )
        n = agg.accept_rows(
            [{"v": 1}, {"v": 2}, {"v": 3}],
            times_ms=np.array([T0, -1, T0 + 100], np.int64),
        )
        assert n == 2
        assert agg.value(T0 + 1000)["n"] == 2
        assert min(agg.partials()) >= 0
        wins = agg.windows(T0 + 2000)
        assert sum(v["n"] for _, v in wins) > 0
        assert all(s >= T0 - 1000 for s, _ in wins)

    def test_pane_retention_bounded(self):
        agg = WindowedAggregator(
            WindowSpec(size_ms=1000, agg="count"), time_field="dtg",
            metrics=MetricsRegistry(), max_panes=4,
        )
        rows = [{"dtg": i * 1000} for i in range(10)]
        agg.accept_rows(rows)
        assert len(agg.partials()) == 4
        assert agg.metrics.counter_value(
            "geomesa.standing.window.dropped") == 6

    def test_feature_stream_sink(self):
        """A WindowedAggregator is a FeatureStream sink: upserts fold
        (under the hot-tier lock — the declared lock edge), deletes are
        ignored."""
        from geomesa_tpu.streaming import FeatureStream, StreamingFeatureCache

        cache = StreamingFeatureCache(SFT)
        agg = WindowedAggregator(
            WindowSpec(size_ms=60_000, agg="count"), time_field="dtg",
        )
        FeatureStream.wrap(cache).to(agg)
        cache.upsert([
            {"__id__": "a", "name": "n", "dtg": T0,
             "geom": geo.Point(0.0, 0.0)},
            {"__id__": "b", "name": "n", "dtg": T0 + 1,
             "geom": geo.Point(1.0, 1.0)},
        ])
        cache.delete(["a"])
        assert agg.value(T0 + 60_000)["n"] == 2  # deletes don't unfold


# -- delivery ---------------------------------------------------------------


class TestDelivery:
    def test_alert_queue_bounded_drops_oldest(self):
        q = AlertQueue(maxlen=3, metrics=MetricsRegistry())
        q.put_many([{"i": i} for i in range(5)])
        assert q.dropped == 2
        assert [a["i"] for a in q.drain()] == [2, 3, 4]
        assert q.metrics.counter_value("geomesa.standing.dropped") == 2

    def test_alert_queue_columnar_blocks_bound_across_boundaries(self):
        """Columnar blocks and materialized lists share one bounded
        queue: overflow drops oldest alerts ACROSS block boundaries,
        and dicts materialize at drain with the block's snapshot."""
        from geomesa_tpu.streaming.standing import _AlertBlock

        q = AlertQueue(maxlen=4, metrics=MetricsRegistry())
        kinds = np.zeros(2, np.int8)
        sub_ids = ["a", "b"]
        q.put_block(_AlertBlock(
            np.arange(3), np.zeros(3, np.int64),
            ["e0", "e1", "e2"], sub_ids, kinds, {0: {"k": 1}},
        ))
        q.put_block(_AlertBlock(
            np.arange(3), np.full(3, 1, np.int64),
            ["f0", "f1", "f2"], sub_ids, kinds, {},
        ))
        assert len(q) == 4 and q.dropped == 2
        head = q.drain(max_n=1)
        assert head == [{"sub": "a", "kind": "geofence", "id": "e2",
                         "attrs": {"k": 1}}]
        q.put_many([{"sub": "x", "kind": "geofence", "id": "m0"}])
        assert [a["id"] for a in q.drain()] == ["f0", "f1", "f2", "m0"]
        assert len(q) == 0

    def _lam(self, tmp_path, **kw):
        ds = DataStore()
        ds.metrics = MetricsRegistry()  # not the shared global fallback
        ds.create_schema(FeatureType.from_spec("t", SPEC))
        return LambdaStore(ds, "t", **kw)

    def test_matcher_fault_never_unacks_the_write(self, tmp_path):
        """An injected standing.match fault is counted and swallowed —
        the write stays acknowledged and queryable (at-most-once
        alerts); same for standing.deliver."""
        lam = self._lam(tmp_path)
        lam.subscribe(Subscription("g", "geofence", geom=geo.Polygon(
            [(-1, -1), (1, -1), (1, 1), (-1, 1), (-1, -1)]
        )))
        for point in ("standing.match", "standing.deliver"):
            with fault.inject(point, kind="io_error", after=0, times=1):
                n = lam.write(
                    [{"name": "n", "dtg": np.datetime64(T0, "ms"),
                      "geom": geo.Point(0.0, 0.0)}], ids=[point],
                )
            assert n == 1
        eng = lam.standing()
        assert eng.metrics.counter_value("geomesa.standing.errors") == 2
        assert lam.count() == 2          # both writes acknowledged
        assert len(eng.alerts) == 0      # both batches' alerts dropped
        lam.write([{"name": "n", "dtg": np.datetime64(T0, "ms"),
                    "geom": geo.Point(0.0, 0.0)}], ids=["ok"])
        assert [a["id"] for a in eng.alerts.drain()] == ["ok"]
        lam.close()

    def test_latency_histogram_and_slo_objective(self, tmp_path):
        from geomesa_tpu.obs.slo import SloTracker, default_objectives

        names = {o.name: o for o in default_objectives()}
        assert "standing_alert_p99" in names
        assert names["standing_alert_p99"].metric == "geomesa.standing.latency"
        lam = self._lam(tmp_path)
        reg = lam.standing().metrics
        slo = SloTracker(
            [names["standing_alert_p99"]], window_s=60, slices=6
        ).attach(reg)
        lam.subscribe(Subscription("g", "geofence", geom=geo.Polygon(
            [(-1, -1), (1, -1), (1, 1), (-1, 1), (-1, -1)]
        )))
        lam.write([{"name": "n", "dtg": np.datetime64(T0, "ms"),
                    "geom": geo.Point(0.0, 0.0)}], ids=["a"])
        snap = reg.snapshot()["histograms"]
        assert snap["geomesa.standing.latency"]["count"] == 1
        assert snap["geomesa.standing.match"]["count"] == 1
        report = slo.report()
        row = report["objectives"][0]
        assert row["objective"] == "standing_alert_p99"
        assert row["count"] == 1
        # the standing metric family renders as a proper histogram
        assert "geomesa_standing_latency_seconds_bucket" in (
            reg.render_prometheus()
        )
        lam.close()

    def test_flusher_arrival_hook(self, tmp_path):
        """attach_flusher matches batches at flush arrival instead of at
        write (stores fed through the flusher directly)."""
        ds = DataStore()
        ds.create_schema(FeatureType.from_spec("t", SPEC))
        lam = LambdaStore(ds, "t")
        eng = StandingQueryEngine(
            ds.get_schema("t"), StandingConfig(), metrics=MetricsRegistry()
        )
        eng.register(Subscription("g", "geofence", geom=geo.Polygon(
            [(-1, -1), (1, -1), (1, 1), (-1, 1), (-1, -1)]
        )))
        eng.attach_flusher(lam.flusher)
        lam.write([{"name": "n", "dtg": np.datetime64(T0, "ms"),
                    "geom": geo.Point(0.0, 0.0)}], ids=["a"])
        assert len(eng.alerts) == 0      # not matched at write
        lam.flush()
        assert [a["id"] for a in eng.alerts.drain()] == ["a"]
        lam.close()


# -- durability -------------------------------------------------------------


def _saved_store(tmp_path, sync="always"):
    ds = DataStore()
    ds.create_schema(FeatureType.from_spec("t", SPEC))
    root = str(tmp_path / "s")
    persist.save(ds, root)
    lam = LambdaStore(
        ds, "t", config=StreamConfig(chunk_rows=256),
        wal_dir=os.path.join(root, "_wal"),
        wal_config=WalConfig(sync=sync, segment_bytes=8 << 10),
    )
    return lam, root


SQUARES = {
    f"s{i}": geo.Polygon([
        (i * 2.0, 0.0), (i * 2.0 + 1.0, 0.0), (i * 2.0 + 1.0, 1.0),
        (i * 2.0, 1.0), (i * 2.0, 0.0),
    ])
    for i in range(8)
}


class TestDurability:
    def test_subscriptions_survive_kill(self, tmp_path):
        lam, root = _saved_store(tmp_path)
        for sid, g in SQUARES.items():
            lam.subscribe(Subscription(sid, "geofence", geom=g,
                                       attrs={"k": sid}))
        lam.unsubscribe("s3")
        lam.wal.crash()
        lam.flusher.close()
        rec = LambdaStore.recover(root)
        assert rec.standing().index.subscription_ids() == sorted(
            set(SQUARES) - {"s3"}
        )
        # matching is live post-recovery, attrs intact
        rec.write([{"name": "n", "dtg": np.datetime64(T0, "ms"),
                    "geom": geo.Point(4.5, 0.5)}], ids=["e"])
        alerts = rec.standing().alerts.drain()
        assert [(a["sub"], a["id"], a["attrs"]["k"]) for a in alerts] == [
            ("s2", "e", "s2")
        ]
        rec.close()

    def test_subscriptions_survive_checkpoint_retirement(self, tmp_path):
        """A checkpoint retires the sealed segments holding the original
        's' records; the re-logged live set above the cover must keep
        every acknowledged registration recoverable."""
        lam, root = _saved_store(tmp_path)
        for sid, g in SQUARES.items():
            lam.subscribe(Subscription(sid, "geofence", geom=g))
        # roll enough rows through to seal + retire segments
        for b in range(4):
            lam.write([
                {"name": "x" * 50, "dtg": np.datetime64(T0, "ms"),
                 "geom": geo.Point(float(i % 90), 0.5)}
                for i in range(200)
            ], ids=[f"r{b}_{i}" for i in range(200)])
            lam.flush()
        lam.unsubscribe("s0")
        lam.checkpoint(root)
        assert lam.wal.metrics.counter_value(
            "geomesa.stream.wal.retired") >= 1, "checkpoint retired nothing"
        lam.wal.crash()
        lam.flusher.close()
        rec = LambdaStore.recover(root)
        assert rec.standing().index.subscription_ids() == sorted(
            set(SQUARES) - {"s0"}
        )
        assert rec.count() == 800
        # a second checkpoint cycle re-logs again (the re-log is itself
        # recovered state, not only constructor state)
        rec.checkpoint(root)
        rec.wal.crash()
        rec.flusher.close()
        rec2 = LambdaStore.recover(root)
        assert rec2.standing().index.subscription_ids() == sorted(
            set(SQUARES) - {"s0"}
        )
        rec2.close()

    def test_invalid_subscription_never_poisons_the_wal(self, tmp_path):
        """subscribe() validates BEFORE logging the 's' record: a body
        that cannot register must never reach the log (replay
        re-registers every record — a poison body would abort all
        future recoveries); and replay itself tolerates an
        unregistrable record from an old/hand-written WAL by skipping
        it (it can never have been acknowledged)."""
        lam, root = _saved_store(tmp_path)
        lam.subscribe(Subscription("good", "geofence", geom=SQUARES["s0"]))
        with pytest.raises(ValueError):
            lam.subscribe(Subscription("bad", "geofence", geom=None))
        with pytest.raises(ValueError):
            lam.subscribe(Subscription(
                "bad2", "proximity", points=np.zeros((0, 2)),
                distance_m=10.0,
            ))
        # a tube with mismatched/unsorted times REGISTERS cleanly (the
        # boxes only use xy) but every later routed batch would raise
        # inside np.interp / match silently wrong — validate must gate it
        with pytest.raises(ValueError, match="one time per"):
            lam.subscribe(Subscription(
                "bad3", "tube", track_xy=[(0, 0), (1, 1), (2, 2)],
                track_times_ms=[0, 1000], buffer_m=500.0,
            ))
        with pytest.raises(ValueError, match="ascending"):
            lam.subscribe(Subscription(
                "bad4", "tube", track_xy=[(0, 0), (1, 1)],
                track_times_ms=[1000, 0], buffer_m=500.0,
            ))
        # an unregistrable record planted directly (no validate gate)
        lam.wal.append("s", {"sub": {"id": "planted", "kind": "geofence"}})
        lam.wal.crash()
        lam.flusher.close()
        rec = LambdaStore.recover(root)
        assert rec.standing().index.subscription_ids() == ["good"]
        rec.close()

    def test_replay_batched_equals_record_at_a_time(self, tmp_path):
        """The satellite perf change is pure mechanism: batched replay
        (bulk hot-tier applies) recovers bit-identical query answers to
        the round-10 record-at-a-time path, across upserts, updates,
        deletes and watermarks."""
        lam, root = _saved_store(tmp_path, sync="off")
        rng = np.random.default_rng(23)
        for b in range(6):
            ids = [f"r{rng.integers(0, 300)}" for _ in range(120)]
            xs = rng.uniform(-50, 50, 120)
            ys = rng.uniform(-50, 50, 120)
            lam.write([
                {"name": f"v{b}_{i}", "dtg": np.datetime64(T0 + b, "ms"),
                 "geom": geo.Point(float(xs[i]), float(ys[i]))}
                for i in range(120)
            ], ids=ids)
            if b % 2 == 0:
                lam.flush()
            if b == 3:
                lam.delete([f"r{i}" for i in range(20)])
        lam.wal.sync()
        lam.wal.crash()
        lam.flusher.close()

        def answers():
            rec = LambdaStore.recover(root)
            fc = rec.query("INCLUDE")
            out = sorted(zip(
                (str(i) for i in fc.ids.tolist()),
                (str(v) for v in np.asarray(fc.columns["name"]).tolist()),
            ))
            rec.close()
            return out

        batched = answers()
        conf.STREAM_WAL_REPLAY_BATCH.set(0)
        record_at_a_time = answers()
        assert batched == record_at_a_time
        assert len(batched) > 0

    def test_bulk_insert_points_equals_insert(self):
        from geomesa_tpu.utils.spatial_index import BucketIndex

        rng = np.random.default_rng(31)
        n = 2000
        keys = [f"k{rng.integers(0, 1200)}" for _ in range(n)]
        xs = rng.uniform(-179, 179, n)
        ys = rng.uniform(-89, 89, n)
        a = BucketIndex()
        a.bulk_insert_points(keys, xs, ys)
        b = BucketIndex()
        for k, x, y in zip(keys, xs, ys):
            b.insert(k, (x, y, x, y))
        assert len(a) == len(b)
        for box in [(-50, -50, 50, 50), (-179, -89, 179, 89), (0, 0, 1, 1)]:
            assert sorted(a.query(box)) == sorted(b.query(box))


# -- kill-anywhere chaos ----------------------------------------------------


class TestChaosStanding:
    def test_kill_anywhere_no_registration_lost_or_invented(self, tmp_path):
        """The seeded chaos case: subscriptions registered concurrently
        with writes/flushes/checkpoints under an armed chaos schedule
        (standing.* fault points included), then a hard kill. Every
        ACKED registration survives recovery; nothing not at least
        attempted appears; post-recovery matching produces alerts
        exactly for live regions — no alert invented, none lost past
        the acked watermark."""
        lam, root = _saved_store(tmp_path)
        acked: dict = {}
        attempted: set = set()
        stop = threading.Event()
        errors: list = []
        test_lock = threading.Lock()

        def registrar():
            i = 0
            rng = np.random.default_rng(41)
            while not stop.is_set():
                i += 1
                sid = f"sub{i}"
                cx = float(rng.uniform(-60, 60))
                cy = float(rng.uniform(-40, 40))
                g = geo.Polygon([
                    (cx - 0.5, cy - 0.5), (cx + 0.5, cy - 0.5),
                    (cx + 0.5, cy + 0.5), (cx - 0.5, cy + 0.5),
                    (cx - 0.5, cy - 0.5),
                ])
                with test_lock:
                    try:
                        lam.subscribe(
                            Subscription(sid, "geofence", geom=g)
                        )
                    except (fault.InjectedCrash, OSError):
                        attempted.add(sid)
                        continue
                    acked[sid] = (cx, cy)
                time.sleep(0.002)

        def writer():
            rng = np.random.default_rng(43)
            b = 0
            while not stop.is_set():
                b += 1
                try:
                    lam.write([
                        {"name": "n", "dtg": np.datetime64(T0, "ms"),
                         "geom": geo.Point(float(rng.uniform(-60, 60)),
                                           float(rng.uniform(-40, 40)))}
                        for _ in range(8)
                    ], ids=[f"w{b}_{k}" for k in range(8)])
                except (fault.InjectedCrash, OSError):
                    pass
                time.sleep(0.001)

        def flusher():
            i = 0
            while not stop.is_set():
                time.sleep(0.04)
                i += 1
                try:
                    if i % 6 == 0:
                        lam.checkpoint(root)
                    else:
                        lam.flush()
                except (fault.InjectedCrash, OSError):
                    continue
                except Exception as e:
                    errors.append(repr(e))
                    stop.set()

        threads = [threading.Thread(target=t)
                   for t in (registrar, writer, flusher)]
        with fault.chaos(
            seed=777, rate=0.03,
            points="stream.*,streaming.*,persist.*,standing.*",
            kinds=("io_error", "latency"), delay_s=0.002,
        ) as spec:
            for t in threads:
                t.start()
            time.sleep(2.5)
            stop.set()
            for t in threads:
                t.join()
        assert not errors, errors[:3]
        assert spec.fired > 0, "the chaos schedule never fired"
        lam.wal.crash()
        lam.flusher.close()
        rec = LambdaStore.recover(root)
        live = set(rec.standing().index.subscription_ids())
        missing = set(acked) - live
        assert not missing, f"acked registrations lost: {sorted(missing)[:5]}"
        invented = live - set(acked) - attempted
        assert not invented, f"registrations invented: {sorted(invented)[:5]}"
        # matching honesty post-recovery: probe each acked region's
        # center — an alert for that subscription must fire; probe a
        # point outside every region — no alert at all
        probes = list(acked.items())[:20]
        if probes:
            rec.write(
                [{"name": "p", "dtg": np.datetime64(T0, "ms"),
                  "geom": geo.Point(cx, cy)} for _, (cx, cy) in probes],
                ids=[f"probe_{sid}" for sid, _ in probes],
            )
            alerts = rec.standing().alerts.drain()
            got = {(a["sub"], a["id"]) for a in alerts}
            for sid, _ in probes:
                assert (sid, f"probe_{sid}") in got, sid
            for sub, pid in got:
                assert sub in live, (sub, pid)  # no alert invented
        rec.close()


# -- scheduler isolation ----------------------------------------------------


class TestSchedulerInterleaving:
    def test_dashboard_p99_holds_while_matcher_runs(self, tmp_path):
        """Dashboard queries admitted through the serving scheduler keep
        their latency profile while the standing matcher evaluates every
        arriving batch (the PR 11 promise extended): the matcher runs on
        the WRITER thread and holds no store lock the query path needs,
        so the query p99 with the matcher armed stays within a generous
        CI-noise bound of the matcher-off p99."""
        rng = np.random.default_rng(53)
        ds = DataStore()
        sft = FeatureType.from_spec("t", SPEC)
        ds.create_schema(sft)
        n = 50_000
        ds.write("t", FeatureCollection.from_columns(
            sft, np.arange(n).astype(str), {
                "name": np.array(["c"] * n),
                "dtg": T0 + rng.integers(0, 86_400_000, n),
                "geom": (rng.uniform(-60, 60, n), rng.uniform(-40, 40, n)),
            }), check_ids=False)
        ds.compact("t")
        lam = LambdaStore(ds, "t", config=StreamConfig(chunk_rows=4096))
        sched = lam.serve()

        def run(with_matcher: bool) -> float:
            stop = threading.Event()

            def ingest():
                k = 0
                while not stop.is_set():
                    k += 1
                    xs = rng.uniform(-60, 60, 2000)
                    ys = rng.uniform(-40, 40, 2000)
                    lam.write([
                        {"name": "s", "dtg": np.datetime64(T0, "ms"),
                         "geom": geo.Point(float(xs[i]), float(ys[i]))}
                        for i in range(2000)
                    ], ids=[f"i{with_matcher}_{k}_{i}" for i in range(2000)])
                    lam.flush()

            t = threading.Thread(target=ingest)
            t.start()
            lat = []
            deadline = time.perf_counter() + 2.0
            while time.perf_counter() < deadline:
                q0 = time.perf_counter()
                lam.query("bbox(geom, -20, -20, 20, 20)")
                lat.append(time.perf_counter() - q0)
            stop.set()
            t.join()
            return float(np.percentile(np.asarray(lat), 99))

        base = run(False)
        eng = lam.standing()
        for i in range(50):
            eng.register(Subscription(
                f"g{i}", "geofence",
                geom=jagged_star(float(rng.uniform(-60, 60)),
                                 float(rng.uniform(-40, 40)),
                                 1.0, 12, seed=i),
            ))
        armed = run(True)
        matched = eng.metrics.counter_value("geomesa.standing.matched")
        assert matched > 0, "the matcher never matched — dead workload"
        sched.close()
        lam.close()
        # generous: CI hosts are 1-core and noisy; the regression this
        # pins is the matcher blocking the query path outright
        assert armed <= 5.0 * base + 0.25, (armed, base)
