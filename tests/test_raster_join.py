"""Raster-interval polygon approximations + adaptive join planning.

The contract under test (docs/joins.md): the raster tier MOVES work, it
never changes answers —

- raster-filtered query results are bit-identical to the exact
  (raster-disabled) path and to a shapely oracle, across concave
  polygons, holes, cells straddling boundaries, slivers thinner than a
  raster cell, and rasters with empty residue;
- interval classification never flips a definite-in/definite-out label
  (full => truly inside, out => truly outside) under fuzzing;
- every adaptive join strategy (exact / raster / fused probe /
  host-raster broad path) returns the same pairs.
"""

import numpy as np
import pytest

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu import geometry as geo
from geomesa_tpu.conf import (
    JOIN_BROAD_FRACTION, RASTER_ENABLED, RASTER_MIN_EDGES, RASTER_RESIDUE,
)
from geomesa_tpu.filter import raster as fr
from geomesa_tpu.filter.predicates import Intersects
from geomesa_tpu.scan import block_kernels as bk

shapely = pytest.importorskip("shapely")
from shapely.geometry import Point as SPoint  # noqa: E402
from shapely.geometry import Polygon as SPolygon  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_raster_conf():
    """Raster on (the default), caches clean, per test."""
    fr.clear_cache()
    yield
    for prop in (RASTER_ENABLED, RASTER_MIN_EDGES, RASTER_RESIDUE,
                 JOIN_BROAD_FRACTION):
        prop.clear()
    fr.clear_cache()


def jagged_star(cx, cy, r, n_arms, seed=0):
    rng = np.random.default_rng(seed)
    a = np.linspace(0, 2 * np.pi, 2 * n_arms + 1)[:-1]
    rad = np.where(
        np.arange(2 * n_arms) % 2 == 0, r, r * rng.uniform(0.3, 0.7, 2 * n_arms)
    )
    return geo.Polygon(
        [(cx + rr * np.cos(t), cy + rr * np.sin(t)) for t, rr in zip(a, rad)]
    )


def donut(cx, cy, r_out, r_in, n=24):
    a = np.linspace(0, 2 * np.pi, n + 1)
    shell = [(cx + r_out * np.cos(t), cy + r_out * np.sin(t)) for t in a]
    hole = [(cx + r_in * np.cos(t), cy + r_in * np.sin(t)) for t in a]
    return geo.Polygon(shell, [hole])


def to_shapely(p: geo.Polygon) -> SPolygon:
    return SPolygon(p.shell, [h for h in p.holes])


TEST_POLYGONS = [
    ("concave_star", jagged_star(10.0, 20.0, 3.0, 12, seed=1)),
    ("big_star_256e", jagged_star(-40.0, -10.0, 5.0, 127, seed=2)),
    ("donut_hole", donut(60.0, 30.0, 4.0, 2.0)),
    # a sliver thinner than any margin-safe raster cell: rasterization
    # must decline or stay all-partial — either way results stay exact
    ("thin_sliver", geo.Polygon(
        [(0.0, 0.0), (4.0, 1e-4), (4.0, 2e-4), (0.0, 1e-4), (0.0, 0.0)]
    )),
]


def make_point_store(n=120_000, seed=7, index="z2", lo=(-60, -40), hi=(80, 45)):
    rng = np.random.default_rng(seed)
    x = rng.uniform(lo[0], hi[0], n)
    y = rng.uniform(lo[1], hi[1], n)
    sft = FeatureType.from_spec("pts", "*geom:Point:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = index
    ds = DataStore()
    ds.create_schema(sft)
    ds.write("pts", FeatureCollection.from_columns(
        sft, np.arange(n), {"geom": (x, y)}), check_ids=False)
    return ds, x, y


def query_ids(ds, f):
    return np.sort(np.asarray(ds.query("pts", f).ids).astype(np.int64))


class TestRasterBuild:
    def test_classes_cover_and_margin(self):
        p = jagged_star(10.0, 20.0, 3.0, 12, seed=3)
        ap = fr.build_raster(p)
        assert ap is not None
        full, part, out = ap.cell_counts
        assert full > 0 and part > 0
        # full cells' centers AND corners (the margin guarantee's easy
        # checkable consequence) are inside the shapely polygon
        sp = to_shapely(p)
        jj, ii = np.nonzero(ap.classes == geo.RASTER_FULL)
        for j, i in list(zip(jj.tolist(), ii.tolist()))[::7][:64]:
            for dx in (0.0, 1.0):
                for dy in (0.0, 1.0):
                    px = ap.x0 + (i + dx) * ap.cell_w
                    py = ap.y0 + (j + dy) * ap.cell_h
                    assert sp.covers(SPoint(px, py)), (i, j)
        jj, ii = np.nonzero(ap.classes == geo.RASTER_OUT)
        for j, i in list(zip(jj.tolist(), ii.tolist()))[::17][:64]:
            px = ap.x0 + (i + 0.5) * ap.cell_w
            py = ap.y0 + (j + 0.5) * ap.cell_h
            assert not sp.intersects(SPoint(px, py)), (i, j)

    def test_sliver_declines_or_all_partial(self):
        p = dict(TEST_POLYGONS)["thin_sliver"]
        ap = fr.build_raster(p)
        # margin-safe cells are far wider than the sliver: no FULL cell
        # may exist (it would wrongly certify points near the boundary)
        if ap is not None:
            assert (ap.classes != geo.RASTER_FULL).all()

    def test_fuzz_labels_never_flip(self):
        """The acceptance fuzz case: for random points, a FULL label
        implies shapely-covered, an OUT label implies shapely-disjoint.
        PARTIAL carries no claim (the exact predicate decides)."""
        rng = np.random.default_rng(11)
        for seed in range(6):
            p = jagged_star(
                float(rng.uniform(-50, 50)), float(rng.uniform(-30, 30)),
                float(rng.uniform(0.5, 4.0)), int(rng.integers(5, 60)),
                seed=seed,
            )
            ap = fr.build_raster(p)
            if ap is None:
                continue
            sp = to_shapely(p)
            x0, y0, x1, y1 = p.bounds()
            px = rng.uniform(x0 - 0.5, x1 + 0.5, 500)
            py = rng.uniform(y0 - 0.5, y1 + 0.5, 500)
            cls = ap.classify_points(px, py)
            for k in np.flatnonzero(cls == geo.RASTER_FULL):
                assert sp.covers(SPoint(px[k], py[k]))
            for k in np.flatnonzero(cls == geo.RASTER_OUT):
                assert not sp.intersects(SPoint(px[k], py[k]))

    def test_zranges_partition_by_class(self):
        p = jagged_star(10.0, 20.0, 2.0, 8, seed=4)
        ap = fr.build_raster(p)
        lo, hi, cont = ap.zranges()
        assert len(lo) and (lo <= hi).all()
        assert (lo[1:] > hi[:-1]).all()  # disjoint ascending
        assert cont.any() and (~cont).any()
        # coalescing keeps coverage and never invents containment
        clo, chi, ccont = ap.zranges(max_ranges=max(4, len(lo) // 8))
        assert len(clo) <= max(4, len(lo) // 8)
        assert int(ccont.sum()) <= int(cont.sum())

    def test_pack_block_coalesces_to_bucket(self):
        p = jagged_star(10.0, 20.0, 3.0, 24, seed=5)
        ap = fr.build_raster(p)
        for bucket in (16, 64):
            blk = ap.pack_block(bucket)
            assert blk.shape == (1 + bucket, bk.LANES)
            # pad/used interval rows never claim full beyond the source
            assert (blk[1:, 0] <= blk[1:, 1]).sum() <= bucket


class TestRasterQueryDifferential:
    """Raster-filtered scan results bit-identical to the exact path and
    to the shapely oracle — the acceptance differential suite."""

    @pytest.mark.parametrize("name,poly", TEST_POLYGONS)
    def test_query_identical_and_oracle(self, name, poly):
        ds, x, y = make_point_store()
        f = Intersects("geom", poly)
        got_on = query_ids(ds, f)
        RASTER_ENABLED.set(False)
        fr.clear_cache()
        ds.planner.invalidate_config_memo()
        got_off = query_ids(ds, f)
        assert np.array_equal(got_on, got_off), name
        # shapely oracle over a sample (full oracle is O(n) shapely calls)
        sp = to_shapely(poly)
        mine = np.zeros(len(x), bool)
        mine[got_on] = True
        idx = np.random.default_rng(3).integers(0, len(x), 2000)
        want = np.array([
            sp.intersects(SPoint(float(x[k]), float(y[k]))) for k in idx
        ])
        assert np.array_equal(want, mine[idx]), name

    def test_empty_residue_polygon(self):
        """A cell-aligned rectangle-ish polygon large enough that some
        queries resolve with certain rows only — still exact. (Rectangles
        bypass the raster via the box path; a near-rectangular octagon
        exercises raster with a tiny residue.)"""
        p = geo.Polygon([
            (0, 0), (20, 0), (25, 5), (25, 25), (20, 30), (0, 30),
            (-5, 25), (-5, 5), (0, 0),
        ])
        ds, x, y = make_point_store(n=60_000, seed=9)
        f = Intersects("geom", p)
        got_on = query_ids(ds, f)
        RASTER_ENABLED.set(False)
        fr.clear_cache()
        ds.planner.invalidate_config_memo()
        assert np.array_equal(got_on, query_ids(ds, f))

    def test_device_residue_masks_bit_identical(self):
        """geomesa.raster.residue=device: the kernel's raster leg runs
        the exact _pip_unrolled/_pip_loop on the boundary residue, so
        final (ordinals, certain-refined) results equal the pre-raster
        path AND the raster-off masks agree post-refinement."""
        RASTER_RESIDUE.set("device")
        ds, x, y = make_point_store(n=60_000, seed=13)
        poly = jagged_star(10.0, 5.0, 4.0, 10, seed=6)
        idx = next(i for i in ds.indexes("pts") if i.name == "z2")
        cfg = idx.scan_config(Intersects("geom", poly))
        assert cfg.rast is not None and cfg.poly is not None
        table = ds.table("pts", "z2")
        rows_on, cert_on = table.scan(cfg)
        RASTER_ENABLED.set(False)
        fr.clear_cache()
        ds.planner.invalidate_config_memo()
        cfg_off = idx.scan_config(Intersects("geom", poly))
        assert cfg_off.rast is None
        rows_off, cert_off = table.scan(cfg_off)
        # device residue reuses the PIP tier verbatim: refined hit sets
        # agree exactly
        def refined(rows, cert):
            unc = np.flatnonzero(~cert)
            keep = cert.copy()
            if len(unc):
                keep[unc] = geo.points_in_polygon(x[rows[unc]], y[rows[unc]], poly)
            return np.sort(rows[keep])

        assert np.array_equal(refined(rows_on, cert_on), refined(rows_off, cert_off))
        # and every row the raster path certifies IS a true hit
        sp = to_shapely(poly)
        sample = rows_on[cert_on][::37][:100]
        for r in sample:
            assert sp.covers(SPoint(float(x[r]), float(y[r])))

    def test_fused_batch_equals_per_query(self):
        ds, _, _ = make_point_store(n=60_000, seed=17)
        idx = next(i for i in ds.indexes("pts") if i.name == "z2")
        rng = np.random.default_rng(23)
        cfgs = [
            idx.scan_config(Intersects("geom", jagged_star(
                float(rng.uniform(-40, 60)), float(rng.uniform(-30, 35)),
                float(rng.uniform(0.5, 3.0)), int(rng.integers(5, 40)),
                seed=k,
            )))
            for k in range(9)
        ]
        assert any(c.rast is not None for c in cfgs)
        table = ds.table("pts", "z2")
        fused = [f() for f in table.scan_submit_many(list(cfgs))]
        for cfg, (rows, cert) in zip(cfgs, fused):
            er, ec = table.scan(cfg)
            assert np.array_equal(rows, er)
            assert np.array_equal(cert, ec)

    def test_z3_raster_kernel_tier(self):
        """z3 keeps bbox-derived ranges but rides the kernel raster leg:
        results identical with raster on/off."""
        rng = np.random.default_rng(29)
        n = 50_000
        t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
        sft = FeatureType.from_spec("pts", "dtg:Date,*geom:Point:srid=4326")
        sft.user_data["geomesa.indices.enabled"] = "z3"
        ds = DataStore()
        ds.create_schema(sft)
        x = rng.uniform(-30, 30, n)
        y = rng.uniform(-25, 25, n)
        t = t0 + rng.integers(0, 20 * 86400_000, n)
        ds.write("pts", FeatureCollection.from_columns(
            sft, np.arange(n), {"dtg": t, "geom": (x, y)}), check_ids=False)
        from geomesa_tpu.filter.predicates import During

        poly = jagged_star(5.0, 3.0, 6.0, 14, seed=8)
        f = Intersects("geom", poly) & During(
            "dtg", t0, t0 + 12 * 86400_000
        )
        idx = next(i for i in ds.indexes("pts") if i.name == "z3")
        assert idx.scan_config(f).rast is not None
        on = query_ids(ds, f)
        RASTER_ENABLED.set(False)
        fr.clear_cache()
        ds.planner.invalidate_config_memo()
        assert np.array_equal(on, query_ids(ds, f))


class TestAdaptiveJoin:
    def _stores(self, n=40_000, n_poly=12, seed=31):
        from geomesa_tpu.sql.join import spatial_join  # noqa: F401

        rng = np.random.default_rng(seed)
        x = rng.uniform(-50, 50, n)
        y = rng.uniform(-40, 40, n)
        sft = FeatureType.from_spec("pts", "*geom:Point:srid=4326")
        right = FeatureCollection.from_columns(sft, np.arange(n), {"geom": (x, y)})
        polys = [
            jagged_star(
                float(rng.uniform(-40, 40)), float(rng.uniform(-30, 30)),
                float(rng.uniform(1.0, 8.0)), int(rng.integers(4, 50)), seed=k,
            )
            for k in range(n_poly)
        ]
        gsft = FeatureType.from_spec("polys", "*geom:Polygon:srid=4326")
        left = FeatureCollection.from_columns(
            gsft, np.arange(n_poly),
            {"geom": geo.PackedGeometryColumn.from_geometries(polys)},
        )
        return left, right, sft, x, y

    @pytest.mark.parametrize("predicate", ["intersects", "contains"])
    def test_strategies_identical(self, predicate):
        from geomesa_tpu.sql.join import spatial_join

        left, right, *_ = self._stores()
        exact = spatial_join(left, right, predicate, strategy="exact")
        rast = spatial_join(left, right, predicate, strategy="raster")
        auto = spatial_join(left, right, predicate, strategy="auto")
        for got in (rast, auto):
            assert np.array_equal(exact[0], got[0])
            assert np.array_equal(exact[1], got[1])

    def test_raster_strategy_counted(self):
        from geomesa_tpu.metrics import MetricsRegistry
        from geomesa_tpu.sql.join import spatial_join

        left, right, *_ = self._stores()
        m = MetricsRegistry()
        spatial_join(left, right, "intersects", strategy="raster", metrics=m)
        assert m.counter_value("geomesa.join.strategy.raster") > 0
        assert m.counter_value("geomesa.join.raster.decided") > 0

    def test_indexed_join_raster_on_off(self):
        from geomesa_tpu.sql.join import spatial_join_indexed

        left, right, sft, x, y = self._stores(n=60_000)
        ds = DataStore()
        ds.create_schema(sft)
        ds.write("pts", right, check_ids=False)
        on = spatial_join_indexed(ds, "pts", left, "contains")
        RASTER_ENABLED.set(False)
        fr.clear_cache()
        ds.planner.invalidate_config_memo()
        off = spatial_join_indexed(ds, "pts", left, "contains")
        assert np.array_equal(on[0], off[0])
        assert np.array_equal(on[1], off[1])

    def test_indexed_join_broad_host_path(self):
        """A polygon covering most of the store routes to the host-raster
        strategy (geomesa.join.strategy.host_raster) with identical
        pairs."""
        from geomesa_tpu.metrics import MetricsRegistry
        from geomesa_tpu.sql.join import spatial_join_indexed

        left, right, sft, x, y = self._stores(n=50_000, n_poly=3)
        # one near-world-sized polygon forces the broad path
        big = jagged_star(0.0, 0.0, 80.0, 20, seed=99)
        gsft = FeatureType.from_spec("polys", "*geom:Polygon:srid=4326")
        geoms = left.geom_column.geometries() + [big]
        left2 = FeatureCollection.from_columns(
            gsft, np.arange(len(geoms)),
            {"geom": geo.PackedGeometryColumn.from_geometries(geoms)},
        )
        ds = DataStore()
        ds.create_schema(sft)
        ds.write("pts", right, check_ids=False)
        JOIN_BROAD_FRACTION.set(0.2)
        m = MetricsRegistry()
        adaptive = spatial_join_indexed(ds, "pts", left2, "contains", metrics=m)
        assert m.counter_value("geomesa.join.strategy.host_raster") >= 1
        assert m.counter_value("geomesa.join.strategy.probe") >= 1
        JOIN_BROAD_FRACTION.set(2.0)  # probe-only: no broad routing
        plain = spatial_join_indexed(ds, "pts", left2, "contains")
        assert np.array_equal(adaptive[0], plain[0])
        assert np.array_equal(adaptive[1], plain[1])


class TestJoinProcessSelectivity:
    def test_in_cap_fallback_counted_and_traced(self):
        from geomesa_tpu.metrics import MetricsRegistry
        from geomesa_tpu.planning.explain import Explainer
        from geomesa_tpu.process import join_search

        rng = np.random.default_rng(41)
        n = 3000
        sft_a = FeatureType.from_spec(
            "tracks", "vessel:String,*geom:Point:srid=4326"
        )
        sft_b = FeatureType.from_spec(
            "vessels", "vessel:String,*geom:Point:srid=4326"
        )
        ds = DataStore()
        ds.create_schema(sft_a)
        ds.create_schema(sft_b)
        names = np.array([f"v{k}" for k in range(n)])
        for tname, sft in (("tracks", sft_a), ("vessels", sft_b)):
            ds.write(tname, FeatureCollection.from_columns(
                sft, np.arange(n),
                {"vessel": names,
                 "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n))},
            ), check_ids=False)
        m = MetricsRegistry()
        exp = Explainer()
        out = join_search(
            ds, "tracks", "vessels", "vessel", max_values=100,
            explain=exp, metrics=m,
        )
        assert m.counter_value("geomesa.join.in_cap_fallback") == 1
        assert "in_cap_fallback" in exp.render()
        assert len(out) == n
        # below the cap but high selectivity: the sampled gate also
        # routes to the host mask, visibly
        m2 = MetricsRegistry()
        out2 = join_search(
            ds, "tracks", "vessels", "vessel", max_values=n + 10, metrics=m2,
        )
        assert m2.counter_value("geomesa.join.in_skipped_selectivity") == 1
        assert len(out2) == n


class TestBenchGate:
    def _load_gate(self):
        import importlib.util
        import os

        path = os.path.join(
            os.path.dirname(__file__), "..", "scripts", "bench_gate.py"
        )
        spec = importlib.util.spec_from_file_location("bench_gate", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _payload(self, cost, identical=True):
        return {"rows": [
            {"scenario": "z2_polygon_pip_batch", "raster_ms_per_q": cost,
             "exact_ms_per_q": cost * 10, "identical": identical},
        ]}

    def test_pass_regress_and_identity(self, tmp_path):
        import json

        gate = self._load_gate()
        base = tmp_path / "base.json"
        base.write_text(json.dumps(self._payload(1.0)))
        ok = tmp_path / "ok.json"
        ok.write_text(json.dumps(self._payload(1.1)))
        bad = tmp_path / "bad.json"
        bad.write_text(json.dumps(self._payload(1.5)))
        broken = tmp_path / "broken.json"
        broken.write_text(json.dumps(self._payload(0.5, identical=False)))
        assert gate.gate(str(ok), str(base), 0.20) == 0
        assert gate.gate(str(bad), str(base), 0.20) == 1
        assert gate.gate(str(broken), str(base), 0.20) == 1
        assert gate.gate(str(tmp_path / "missing.json"), str(base), 0.2) == 2
        # a self-comparison can never detect a regression: refused
        assert gate.gate(str(base), str(base), 0.20) == 2

    def _stream_payload(self, rps, identical=True):
        return {"rows": [
            {"scenario": "stream_sustained", "streamed_rows_per_s": rps,
             "legacy_rows_per_s": rps / 3.0, "identical": identical},
        ]}

    def test_stream_scenario_direction_aware(self, tmp_path):
        """Throughput scenarios regress DOWNWARD: the gate must fail on
        falling rows/s and pass on rising rows/s (the inverse of the
        cost scenarios), and still enforce the identical flag."""
        import json

        gate = self._load_gate()
        base = tmp_path / "BENCH_STREAM.json"
        base.write_text(json.dumps(self._stream_payload(30_000.0)))
        ok = tmp_path / "BENCH_STREAM_ok.json"
        ok.write_text(json.dumps(self._stream_payload(33_000.0)))
        slower_ok = tmp_path / "BENCH_STREAM_slower.json"
        slower_ok.write_text(json.dumps(self._stream_payload(27_000.0)))
        bad = tmp_path / "BENCH_STREAM_bad.json"
        bad.write_text(json.dumps(self._stream_payload(20_000.0)))
        broken = tmp_path / "BENCH_STREAM_broken.json"
        broken.write_text(json.dumps(self._stream_payload(50_000.0, False)))
        assert gate.gate(str(ok), str(base), 0.20) == 0
        assert gate.gate(str(slower_ok), str(base), 0.20) == 0  # within 20%
        assert gate.gate(str(bad), str(base), 0.20) == 1
        assert gate.gate(str(broken), str(base), 0.20) == 1

    def test_default_baseline_inference(self, tmp_path):
        gate = self._load_gate()
        repo = str(tmp_path)
        assert gate.default_baseline("/x/BENCH_STREAM_fresh.json", repo) == (
            f"{repo}/BENCH_STREAM.json"
        )
        assert gate.default_baseline("/x/fresh.json", repo) == (
            f"{repo}/BENCH_PIP_JOIN.json"
        )
        assert gate.default_baseline("/x/BENCH_WAL_fresh.json", repo) == (
            f"{repo}/BENCH_WAL.json"
        )

    def _wal_payload(self, rps, ratio, identical=True):
        return {"rows": [
            {"scenario": "stream_wal", "wal_interval_rows_per_s": rps,
             "nowal_rows_per_s": rps / ratio,
             "interval_over_nowal": ratio, "identical": identical},
        ]}

    def test_wal_within_run_overhead_bound(self, tmp_path):
        """The ISSUE 10 acceptance bound is checked on the FRESH file
        alone: sync=interval throughput must stay within 15% of the
        same run's no-WAL path, regardless of how the baseline did."""
        import json

        gate = self._load_gate()
        base = tmp_path / "BENCH_WAL.json"
        base.write_text(json.dumps(self._wal_payload(50_000.0, 0.95)))
        ok = tmp_path / "BENCH_WAL_ok.json"
        ok.write_text(json.dumps(self._wal_payload(51_000.0, 0.90)))
        heavy = tmp_path / "BENCH_WAL_heavy.json"
        heavy.write_text(json.dumps(self._wal_payload(52_000.0, 0.70)))
        slow = tmp_path / "BENCH_WAL_slow.json"
        slow.write_text(json.dumps(self._wal_payload(30_000.0, 0.95)))
        assert gate.gate(str(ok), str(base), 0.20) == 0
        # overhead bound fails even though throughput beat the baseline
        assert gate.gate(str(heavy), str(base), 0.20) == 1
        # and the baseline comparison still guards absolute throughput
        assert gate.gate(str(slow), str(base), 0.20) == 1


class TestValidators:
    def _sft(self):
        return FeatureType.from_spec(
            "obs", "name:String,dtg:Date,*geom:Point:srid=4326"
        )

    def test_z_bounds_and_reasons(self):
        from geomesa_tpu.io.converters import Converter, FieldSpec

        sft = self._sft()
        conv = Converter(
            sft=sft,
            fields=[
                FieldSpec("name", "$1"),
                FieldSpec("dtg", "datetime($2)"),
                FieldSpec("geom", "point($3, $4)"),
            ],
            validators="index",
        )
        data = (
            "a,2024-01-01T00:00:00Z,10,20\n"      # ok
            "b,2024-01-01T00:00:00Z,200,20\n"     # lon out of bounds
            "c,2024-01-01T00:00:00Z,10,-95\n"     # lat out of bounds
            "d,not-a-date,10,20\n"                # parse error
            "e,2024-01-01T00:00:00Z,11,21\n"      # ok
        )
        fc = conv.convert(data)
        assert len(fc) == 2
        assert conv.errors == 3
        assert conv.error_reasons.get("parse") == 1
        zb = [k for k in conv.error_reasons if k.startswith("z-bounds")]
        assert sum(conv.error_reasons[k] for k in zb) == 2

    def test_raise_mode(self):
        from geomesa_tpu.io.converters import Converter, FieldSpec

        conv = Converter(
            sft=self._sft(),
            fields=[
                FieldSpec("name", "$1"),
                FieldSpec("dtg", "datetime($2)"),
                FieldSpec("geom", "point($3, $4)"),
            ],
            validators="z-bounds",
            drop_errors=False,
        )
        with pytest.raises(ValueError, match="z-bounds"):
            conv.convert("a,2024-01-01T00:00:00Z,500,20\n")

    def test_custom_validator_objects_in_process(self, tmp_path):
        """Custom Validator OBJECTS (unpicklable closures) work through
        the documented workers<=1 escape hatch, and a pool attempt fails
        with the clear error instead of a raw pickle traceback."""
        import pickle

        from geomesa_tpu.ingest.splits import ConverterConfig
        from geomesa_tpu.io.converters import Converter, FieldSpec
        from geomesa_tpu.io.ingest import ingest_files
        from geomesa_tpu.io.validators import Validator

        sft = self._sft()
        odd = Validator("odd-lon", lambda row: (
            None if int(row["geom"].x) % 2 == 1 else "even longitude"
        ))
        conv = Converter(
            sft=sft,
            fields=[
                FieldSpec("name", "$1"),
                FieldSpec("dtg", "datetime($2)"),
                FieldSpec("geom", "point($3, $4)"),
            ],
            validators=[odd],
        )
        path = tmp_path / "obs.csv"
        path.write_text(
            "a,2024-01-01T00:00:00Z,11,20\n"
            "b,2024-01-01T00:00:00Z,10,20\n"
        )
        ds = DataStore()
        ds.create_schema(sft)
        res = ingest_files(ds, conv, [str(path)], workers=1)
        assert res.written == 1 and res.errors == 1
        assert any(k.startswith("odd-lon") for k in res.error_reasons)
        with pytest.raises(ValueError, match="not picklable"):
            pickle.dumps(ConverterConfig.of(conv))

    def test_ingest_result_reasons(self, tmp_path):
        from geomesa_tpu.io.converters import Converter, FieldSpec
        from geomesa_tpu.io.ingest import ingest_files

        sft = self._sft()
        conv = Converter(
            sft=sft,
            fields=[
                FieldSpec("name", "$1"),
                FieldSpec("dtg", "datetime($2)"),
                FieldSpec("geom", "point($3, $4)"),
            ],
            validators="index",
        )
        path = tmp_path / "obs.csv"
        path.write_text(
            "a,2024-01-01T00:00:00Z,10,20\n"
            "b,2024-01-01T00:00:00Z,400,20\n"
            "c,2024-01-01T00:00:00Z,12,22\n"
        )
        ds = DataStore()
        ds.create_schema(sft)
        res = ingest_files(ds, conv, [str(path)], workers=1)
        assert res.written == 2
        assert res.errors == 1
        assert sum(res.error_reasons.values()) == 1
        assert any(k.startswith("z-bounds") for k in res.error_reasons)
