"""The observability layer (docs/observability.md): structured tracing
through the query/write paths, the slow-query log, live histograms in
context, and SLO tracking.

Layers:

- **disarmed is free**: with both arming knobs at 0 every tracing entry
  point returns the shared null singleton — no allocation, no trace;
- **trace vs explain**: the same regions are timed by both surfaces, so
  phase durations agree within tolerance and the breakdown rides the
  explain trail;
- **cross-thread correctness**: a scheduler-served query's span tree is
  ONE tree across the caller thread and the dispatcher (plan in one
  thread, scan in another, every phase parented on the root); fold
  slices land inside the flush trace;
- **surfaces**: sampling, the slow-query ring (fingerprint + span
  tree), Chrome trace-event export, SLO windows/burn rates and the
  ``/health``-servable report.
"""

import json
import threading
import time

import numpy as np
import pytest

from geomesa_tpu import conf, obs
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.metrics import MetricsRegistry
from geomesa_tpu.obs.trace import NULL_SPAN
from geomesa_tpu.planning.explain import Explainer
from geomesa_tpu.sft import FeatureType

SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"
T0 = int(np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64))
DAY = 86_400_000
Q = "BBOX(geom, -20, -20, 20, 20)"


@pytest.fixture(autouse=True)
def _fresh_tracer():
    """Every test gets a fresh tracer and restored knobs."""
    obs.install(obs.Tracer())
    yield
    for knob in (conf.OBS_TRACE_SAMPLE, conf.OBS_SLOW_MS,
                 conf.OBS_TRACE_BUFFER, conf.OBS_SLOW_MAX):
        knob.clear()
    obs.install(obs.Tracer())


def _arm(sample=1, slow_ms=0.0):
    conf.OBS_TRACE_SAMPLE.set(sample)
    conf.OBS_SLOW_MS.set(slow_ms)


def _disarm():
    conf.OBS_TRACE_SAMPLE.set(0)
    conf.OBS_SLOW_MS.set(0.0)


def _store(n=4000, metrics=None, cache=False):
    ds = DataStore(metrics=metrics, cache=cache)
    sft = FeatureType.from_spec("t", SPEC)
    ds.create_schema(sft)
    rng = np.random.default_rng(0)
    ds.write("t", FeatureCollection.from_columns(
        sft, [f"r{i}" for i in range(n)],
        {"name": np.array(["n"] * n),
         "dtg": T0 + rng.integers(0, 30 * DAY, n),
         "geom": (rng.uniform(-50, 50, n), rng.uniform(-50, 50, n))},
    ))
    return ds


# -- layer 1: disarmed is free --------------------------------------------


def test_disarmed_tracing_is_noop():
    """The no-op check: with both knobs at 0 the span entry returns the
    SHARED null singleton (identity — zero allocation on the hot path),
    roots never open, nothing is retained."""
    _disarm()
    t = obs.tracer()
    assert t.begin("query") is None
    # the singleton, not a fresh object per call
    assert obs.span("scan") is NULL_SPAN
    assert t.span("scan") is NULL_SPAN
    assert obs.span("scan") is obs.span("decode")
    with obs.span("scan") as s:
        assert s is NULL_SPAN
    ds = _store(n=500)
    for _ in range(5):
        ds.query("t", Q)
    assert t.traces() == []
    assert ds.slow_queries() == []


def test_disarmed_query_smoke_no_trace_state():
    """End to end through DataStore.query: the disarmed path leaves no
    tracer state behind (buffer, slow ring, root counter all zero)."""
    _disarm()
    ds = _store(n=500)
    ds.query("t", Q)
    t = obs.tracer()
    with t._lock:
        assert len(t.buffer) == 0 and t.slow == [] and t._n_roots == 0


# -- layer 2: trace vs explain --------------------------------------------


def test_trace_vs_explain_consistency():
    """The two timing surfaces cover the same regions: the trace's scan
    phase brackets the explainer's 'Device scan ... took X ms' line
    (within tolerance), and the per-phase breakdown is appended to the
    explain trail with the trace attached."""
    _arm(sample=1)
    ds = _store()
    ds.query("t", Q)  # warm the kernel variant so timings are honest
    exp = Explainer()
    out = ds.query("t", Q, explain=exp)
    assert len(out)
    tr = exp.trace
    assert tr is not None and tr is obs.tracer().traces()[-1]
    phases = {s.name: s for s in tr.phases()}
    assert {"plan", "scan", "decode"} <= set(phases)
    # explain's own span timing for the device scan
    lines = exp.lines
    i = next(j for j, l in enumerate(lines) if "Device scan" in l)
    took_ms = float(lines[i + 1].strip().removeprefix("took ").removesuffix("ms"))
    scan_ms = phases["scan"].dur_s * 1e3
    # the obs span wraps the explain span, so scan >= took, within slack
    assert scan_ms >= took_ms * 0.99
    assert scan_ms <= took_ms + max(2.0, took_ms)
    # the breakdown rides the trail
    assert any(l.startswith("trace: plan") for l in lines)
    assert any(l.startswith("trace: scan") for l in lines)
    assert any("phases cover" in l for l in lines)


def test_plan_probe_and_decompose_nested_under_plan():
    """The planner's memo probe and z-range decomposition are children
    of the plan phase: a cold plan decomposes, a warm repeat only
    probes."""
    _arm(sample=1)
    ds = _store()
    ds.query("t", Q)
    cold = obs.tracer().traces()[-1]
    names = {s.name for s in cold.spans}
    assert "plan.decompose" in names and "plan.probe" in names
    plan = next(s for s in cold.phases() if s.name == "plan")
    probe = next(s for s in cold.spans if s.name == "plan.probe")
    assert probe.parent_id == plan.span_id
    ds.query("t", Q)  # memoized: no decomposition
    warm = obs.tracer().traces()[-1]
    warm_names = [s.name for s in warm.spans]
    assert "plan.probe" in warm_names
    assert "plan.decompose" not in warm_names


# -- layer 3: cross-thread correctness ------------------------------------


def test_scheduler_thread_hop_keeps_one_tree():
    """A scheduler-served query's trace: plan in the caller thread,
    queue/dispatch/scan/decode attached from the dispatcher — ≥5
    distinct top-level phases, all parented on the root, and the
    top-level durations sum close to the root wall."""
    _arm(sample=1)
    ds = _store(metrics=MetricsRegistry())
    ds.query("t", Q)  # warm kernels outside the traced run
    obs.install(obs.Tracer())
    sched = ds.serve()
    try:
        out = sched.submit("t", Q).result(30)
        assert len(out)
    finally:
        sched.close()
    tr = next(
        t for t in reversed(obs.tracer().traces())
        if t.root.attrs and t.root.attrs.get("serving")
    )
    phases = tr.phases()
    names = [s.name for s in phases]
    assert len(set(names)) >= 5, names
    for want in ("plan", "queue", "dispatch", "scan", "decode"):
        assert want in names, names
    rid = tr.root.span_id
    assert all(s.parent_id == rid for s in phases)
    # the hop really happened: plan recorded on a different thread than
    # the device pull
    plan = next(s for s in phases if s.name == "plan")
    scan = next(s for s in phases if s.name == "scan")
    assert plan.tid != scan.tid
    covered = sum(s.dur_s for s in phases)
    assert covered <= tr.wall_s * 1.05
    assert covered >= tr.wall_s * 0.7, (covered, tr.wall_s)
    # queue wait went to the live histogram as well
    assert ds.metrics.snapshot()["histograms"][
        "geomesa.serving.queue_wait"
    ]["count"] >= 1


def test_flush_trace_carries_stage_and_fold_slice_spans():
    """The write path: one flush = one trace; worker-pool stage spans
    (parse/keys/sort) re-attach across the thread hop, and a sliced
    fold's per-slice publishes appear with the live pause histogram."""
    from geomesa_tpu.streaming import LambdaStore, StreamConfig

    _arm(sample=1)
    reg = MetricsRegistry()
    ds = DataStore(metrics=reg)
    sft = FeatureType.from_spec("t", SPEC)
    ds.create_schema(sft)
    lam = LambdaStore(ds, "t", config=StreamConfig(
        chunk_rows=128, workers=2, fold_rows=8, slice_rows=200,
    ))
    rng = np.random.default_rng(1)

    def rows(n, seed):
        r = np.random.default_rng(seed)
        return [
            {"__id__": f"w{i}", "name": "n",
             "dtg": np.datetime64(int(T0 + r.integers(0, DAY)), "ms"),
             "geom": f"POINT ({r.uniform(-50, 50):.5f} {r.uniform(-50, 50):.5f})"}
            for i in range(n)
        ]

    lam.write(rows(600, 2))
    lam.flush()
    lam.write(rows(600, 3))  # same ids: the update-fold path
    lam.flush(full=True)
    lam.close()
    flushes = [
        t for t in obs.tracer().traces() if t.name == "flush"
    ]
    assert flushes
    fold_flush = flushes[-1]
    names = [s.name for s in fold_flush.spans]
    for want in ("flush.parse", "flush.keys", "flush.sort", "flush.commit"):
        assert want in names, names
    slices = [s for s in fold_flush.spans if s.name == "fold.slice"]
    assert len(slices) >= 2  # 600 rows / 200 slice_rows
    commit = next(s for s in fold_flush.spans if s.name == "flush.commit")
    assert all(s.parent_id == commit.span_id for s in slices)
    # worker spans recorded from pool threads, same tree
    parse = next(s for s in fold_flush.spans if s.name == "flush.parse")
    assert parse.trace is fold_flush
    # the pause histogram is live
    h = reg.snapshot()["histograms"]["geomesa.stream.fold.slice"]
    assert h["count"] == len(slices)
    # write traces rooted too (WAL-less write: just the root)
    assert any(t.name == "write" for t in obs.tracer().traces())


def test_wal_spans_inside_write_trace(tmp_path):
    """A WAL-attached acknowledged write traces its append and fsync:
    wal.append/wal.sync spans inside the write root, and the fsync
    histogram records only real fsyncs."""
    from geomesa_tpu.storage import persist
    from geomesa_tpu.streaming import LambdaStore, StreamConfig, WalConfig

    _arm(sample=1)
    reg = MetricsRegistry()
    ds = DataStore(metrics=reg)
    sft = FeatureType.from_spec("t", SPEC)
    ds.create_schema(sft)
    root = tmp_path / "s"
    persist.save(ds, root)
    lam = LambdaStore(
        ds, "t", config=StreamConfig(chunk_rows=64),
        wal_dir=str(root / "_wal"),
        wal_config=WalConfig(sync="always"),
    )
    lam.write([{
        "__id__": "a", "name": "n",
        "dtg": np.datetime64(T0, "ms"), "geom": "POINT (1 1)",
    }])
    lam.close()
    wt = next(t for t in obs.tracer().traces() if t.name == "write")
    names = [s.name for s in wt.spans]
    assert "wal.append" in names and "wal.sync" in names
    assert reg.snapshot()["histograms"]["geomesa.stream.wal.fsync"]["count"] >= 1


# -- layer 4: surfaces ----------------------------------------------------


def test_sampling_retains_every_nth_root():
    _arm(sample=4)
    t = obs.tracer()
    for _ in range(8):
        with t.trace("query"):
            pass
    assert len(t.traces()) == 2


def test_retention_counters_record():
    """geomesa.obs.traces / geomesa.obs.slow_queries are LIVE counters:
    a tracer with an explicit registry records there; the default
    (metrics=None) falls back to the process-global registry like every
    other unconfigured component."""
    from geomesa_tpu.metrics import global_registry

    reg = MetricsRegistry()
    t = obs.install(obs.Tracer(metrics=reg))
    _arm(sample=1, slow_ms=0.0001)
    with t.trace("query"):
        time.sleep(0.001)
    assert reg.counter_value("geomesa.obs.traces") == 1
    assert reg.counter_value("geomesa.obs.slow_queries") == 1
    t2 = obs.install(obs.Tracer())  # default: global fallback
    before = global_registry().counter_value("geomesa.obs.traces")
    with t2.trace("query"):
        pass
    assert global_registry().counter_value("geomesa.obs.traces") == before + 1


def test_trace_buffer_is_bounded():
    conf.OBS_TRACE_BUFFER.set(8)
    obs.install(obs.Tracer())  # picks up the cap
    _arm(sample=1)
    t = obs.tracer()
    for i in range(20):
        with t.trace("query", i=i):
            pass
    kept = t.traces()
    assert len(kept) == 8
    assert kept[-1].root.attrs["i"] == 19  # newest retained


def test_slow_query_log_captures_fingerprint_and_tree():
    """Always-on slow log: sampling OFF, threshold tiny — every query
    lands in the ring with its plan fingerprint and full span tree."""
    _arm(sample=0, slow_ms=0.0001)
    ds = _store()
    ds.query("t", Q)
    assert obs.tracer().traces() == []  # not sampled ...
    slow = ds.slow_queries()
    assert len(slow) == 1  # ... but captured
    entry = slow[0]
    assert entry["wall_ms"] > 0
    assert entry["fingerprint"]["type"] == "t"
    assert entry["fingerprint"]["strategy"] in ("z3", "z2")
    span_names = {s["name"] for s in entry["trace"]["spans"]}
    assert {"plan", "scan", "decode"} <= span_names
    # ring is bounded
    conf.OBS_SLOW_MAX.set(3)
    for _ in range(6):
        ds.query("t", Q)
    assert len(ds.slow_queries()) == 3


def test_chrome_trace_export(tmp_path):
    _arm(sample=1, slow_ms=0.0001)
    ds = _store()
    ds.query("t", Q)
    path = ds.dump_trace(str(tmp_path / "trace.json"))
    payload = json.load(open(path))
    events = payload["traceEvents"]
    assert events
    for ev in events:
        assert ev["ph"] == "X"
        assert ev["dur"] >= 0 and ev["ts"] >= 0
    names = {ev["name"] for ev in events}
    assert {"query", "plan", "scan"} <= names
    # slow-ring traces export once even when also sampled
    ids = [ev["pid"] for ev in events if ev["name"] == "query"]
    assert len(ids) == len(set(ids))


def test_concurrent_tracing_keeps_trees_separate():
    """Parallel traced queries never cross-contaminate span trees
    (thread-local propagation): every span's trace is its own root's."""
    _arm(sample=1)
    ds = _store(n=2000)
    errs = []

    def worker(seed):
        try:
            for _ in range(5):
                ds.query("t", Q)
        except BaseException as e:  # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errs == []
    traces = obs.tracer().traces()
    assert len(traces) == 20
    for tr in traces:
        rid = tr.root.span_id
        for s in tr.spans:
            assert s.trace is tr
            assert s.parent_id is None or s.parent_id == rid or any(
                p.span_id == s.parent_id for p in tr.spans
            )


# -- layer 5: SLO tracking ------------------------------------------------


def test_slo_objective_windows_and_burn_rate():
    """Observations feed sliding windows; the report carries windowed
    quantiles, violation fractions and burn rates; old slices age out."""
    o = obs.SloObjective("q99", "geomesa.query.scan", 0.99, 0.1, budget=0.02)
    slo = obs.SloTracker([o], window_s=10.0, slices=5)
    now = 1_000_000.0
    for _ in range(96):
        slo.observe("geomesa.query.scan", 0.01, now=now)
    for _ in range(4):
        slo.observe("geomesa.query.scan", 0.5, now=now)
    rep = slo.report(now=now)
    row = rep["objectives"][0]
    assert row["count"] == 100 and row["violations"] == 4
    assert row["burn_rate"] == pytest.approx(0.04 / 0.02, rel=1e-6)
    assert not row["ok"] and rep["status"] == "breach"
    # the breach ages out of the window
    later = now + 30.0
    for _ in range(50):
        slo.observe("geomesa.query.scan", 0.01, now=later)
    rep2 = slo.report(now=later)
    row2 = rep2["objectives"][0]
    assert row2["count"] == 50 and row2["violations"] == 0
    assert row2["ok"] and rep2["status"] == "ok"
    assert row2["value_ms"] <= 100.0


def test_two_trackers_on_one_registry_fan_out():
    """Two stores sharing one registry (the bench pattern): a second
    attach must fan observations out to BOTH trackers, never silently
    detach the first; re-attaching the same tracker stays idempotent
    (no double counting)."""
    reg = MetricsRegistry()
    a = obs.SloTracker([
        obs.SloObjective("q99", "geomesa.query.scan", 0.99, 0.1),
    ]).attach(reg)
    a.attach(reg)  # idempotent re-attach
    b = obs.SloTracker([
        obs.SloObjective("q99", "geomesa.query.scan", 0.99, 0.1),
    ]).attach(reg)
    reg.observe("geomesa.query.scan", 0.01)
    assert a.report()["objectives"][0]["count"] == 1
    assert b.report()["objectives"][0]["count"] == 1


def test_slo_ignores_unmatched_metrics():
    slo = obs.SloTracker([
        obs.SloObjective("q99", "geomesa.query.scan", 0.99, 0.1),
    ])
    slo.observe("geomesa.stream.wal.fsync", 9.0)
    assert slo.report()["objectives"][0]["count"] == 0


def test_default_objectives_follow_knobs():
    objs = {o.name for o in obs.default_objectives()}
    assert objs == {
        "query_p99", "fold_slice_p99", "wal_fsync_p99",
        "standing_alert_p99", "replica_staleness_p99", "tiles_p99",
    }
    conf.OBS_SLO_WAL_P99_MS.set(0)
    conf.OBS_SLO_STANDING_P99_MS.set(0)
    conf.OBS_SLO_REPLICA_STALENESS_P99_MS.set(0)
    conf.OBS_SLO_TILES_P99_MS.set(0)
    try:
        objs = {o.name for o in obs.default_objectives()}
        assert "wal_fsync_p99" not in objs
        assert "standing_alert_p99" not in objs
        assert "replica_staleness_p99" not in objs
        assert "tiles_p99" not in objs
    finally:
        conf.OBS_SLO_WAL_P99_MS.clear()
        conf.OBS_SLO_STANDING_P99_MS.clear()
        conf.OBS_SLO_REPLICA_STALENESS_P99_MS.clear()
        conf.OBS_SLO_TILES_P99_MS.clear()


def test_datastore_slo_report_end_to_end():
    """attach_slo wires the registry observer: real queries move the
    query_p99 objective; the report is /health-servable (plain JSON
    types only); an unattached store reports ok/empty."""
    ds0 = _store(n=200)
    assert ds0.slo_report() == {
        "status": "ok", "window_s": 0.0, "objectives": []
    }
    ds = _store(metrics=MetricsRegistry())
    tracker = ds.attach_slo()
    assert ds.slo is tracker
    for _ in range(5):
        ds.query("t", Q)
    rep = ds.slo_report()
    row = next(r for r in rep["objectives"] if r["objective"] == "query_p99")
    assert row["count"] == 5
    assert row["value_ms"] > 0
    json.dumps(rep)  # strictly serializable
    # a store built WITHOUT a registry gets one on attach
    ds2 = _store()
    assert ds2.metrics is None
    ds2.attach_slo()
    assert ds2.metrics is not None
    ds2.query("t", Q)
    assert ds2.slo_report()["objectives"][0]["count"] == 1
    # re-attaching REPLACES the store's tracker (no fan-out chain to
    # the detached one: observations reach only the live tracker)
    old = ds2.slo
    new = ds2.attach_slo()
    assert new is not old
    assert ds2.metrics.observer == new.observe
    ds2.query("t", Q)
    assert new.report()["objectives"][0]["count"] == 1
    assert old.report()["objectives"][0]["count"] == 1  # frozen, detached
