"""Round-3 scan path: block kernels, contained ranges, boundary exactness.

Covers VERDICT r2 items 1-2: the one-call bitmask scan, automatic
refinement skipping (certain rows), and contained-range propagation."""

import numpy as np
import pytest

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu.filter import ecql
from geomesa_tpu.scan import block_kernels as bk

N = 40_000


def make_store(n=N, seed=3):
    rng = np.random.default_rng(seed)
    x = rng.uniform(-20, 20, n)
    y = rng.uniform(-20, 20, n)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    t = t0 + rng.integers(0, 28 * 86400_000, n)
    sft = FeatureType.from_spec("pts", "dtg:Date,*geom:Point:srid=4326")
    ds = DataStore()
    ds.create_schema(sft)
    fc = FeatureCollection.from_columns(sft, np.arange(n), {"dtg": t, "geom": (x, y)})
    ds.write("pts", fc, check_ids=False)
    return ds, (x, y, t, t0)


def brute(data, x0, y0, x1, y1, tlo, thi):
    x, y, t, _ = data
    return np.flatnonzero(
        (x >= x0) & (x <= x1) & (y >= y0) & (y <= y1) & (t >= tlo) & (t < thi)
    )


class TestBlockScanExactness:
    def setup_method(self):
        self.ds, self.data = make_store()

    def q(self, x0, y0, x1, y1, d0, d1):
        return (
            f"bbox(geom, {x0}, {y0}, {x1}, {y1}) AND "
            f"dtg DURING 2024-01-{d0:02d}T00:00:00Z/2024-01-{d1:02d}T00:00:00Z"
        )

    def test_matches_brute_force(self):
        t0 = self.data[3]
        for (x0, y0, x1, y1, d0, d1) in [
            (-5, -5, 5, 5, 3, 10),
            (-19.7, -3.3, 8.1, 0.2, 1, 28),
            (0.001, 0.001, 0.002, 0.002, 5, 6),
        ]:
            out = self.ds.query("pts", self.q(x0, y0, x1, y1, d0, d1))
            tlo = t0 + (d0 - 1) * 86400_000
            thi = t0 + (d1 - 1) * 86400_000
            expect = brute(self.data, x0, y0, x1, y1, tlo, thi)
            got = np.sort(np.asarray(out.ids, dtype=np.int64))
            assert np.array_equal(got, expect)

    def test_unaligned_ms_endpoints_exact(self):
        # endpoints not aligned to the week-bin second granularity: the
        # boundary-second rows must be refined exactly at ms precision
        t0 = self.data[3]
        tlo = int(t0 + 5 * 86400_000 + 123)  # +123 ms
        thi = int(t0 + 9 * 86400_000 + 777)
        lo = np.datetime64(tlo, "ms")
        hi = np.datetime64(thi, "ms")
        q = f"bbox(geom, -8, -8, 8, 8) AND dtg DURING {lo}Z/{hi}Z"
        out = self.ds.query("pts", q)
        expect = brute(self.data, -8, -8, 8, 8, tlo, thi)
        assert np.array_equal(np.sort(np.asarray(out.ids, dtype=np.int64)), expect)

    def test_refinement_skipped_for_decided_filter(self, monkeypatch):
        """A bbox+time filter decided by the index must refine only the
        uncertain boundary rows, not all candidates (VERDICT r2 item 2)."""
        from geomesa_tpu.filter.predicates import And

        calls = {"rows": 0}
        orig = And.evaluate

        def spy(self, batch):
            calls["rows"] += batch.n
            return orig(self, batch)

        monkeypatch.setattr(And, "evaluate", spy)
        out = self.ds.query("pts", self.q(-5, -5, 5, 5, 3, 10))
        assert len(out) > 100
        # full refinement would evaluate every candidate (= every hit and
        # then some); the boundary tier must touch well under 5% of them
        assert calls["rows"] < max(50, 0.05 * len(out))

    def test_contained_spans_certain(self):
        """Contained ranges' rows bypass the kernel and refinement."""
        ds, data = self.ds, self.data
        table = ds.table("pts", "z3")
        idx = [i for i in ds.indexes("pts") if i.name == "z3"][0]
        f = ecql.parse(self.q(-15, -15, 15, 15, 1, 22))
        cfg = idx.scan_config(f)
        assert cfg.range_contained is not None and cfg.contained_exact
        overlap, contained = table.candidate_spans_split(cfg)
        assert contained, "a large query should produce contained ranges"
        rows, certain = table.scan(cfg)
        assert certain.any()
        # every contained-span row is marked certain
        from geomesa_tpu.storage.table import _rows_in_spans

        table_rows = np.argsort(table.perm, kind="stable")  # ordinal -> row
        # sanity: certainty is consistent with brute-force membership
        t0 = data[3]
        expect = set(
            brute(data, -15, -15, 15, 15, t0, t0 + 21 * 86400_000).tolist()
        )
        assert set(rows[certain].tolist()) <= expect

    def test_attribute_clip_rows(self):
        """Attribute-index kernel hits clip back to exact value spans."""
        rng = np.random.default_rng(7)
        n = 5000
        sft = FeatureType.from_spec(
            "t2", "name:String:index=true,dtg:Date,*geom:Point:srid=4326"
        )
        ds = DataStore()
        ds.create_schema(sft)
        names = np.array(["alpha", "beta", "gamma"])[rng.integers(0, 3, n)]
        t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
        fc = FeatureCollection.from_columns(
            sft,
            np.arange(n),
            {
                "name": names,
                "dtg": t0 + rng.integers(0, 86400_000, n),
                "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n)),
            },
        )
        ds.write("t2", fc, check_ids=False)
        out = ds.query("t2", "name = 'beta' AND bbox(geom, -5, -5, 5, 5)")
        x, y = fc.columns["geom"].x, fc.columns["geom"].y
        expect = np.flatnonzero(
            (names == "beta") & (x >= -5) & (x <= 5) & (y >= -5) & (y <= 5)
        )
        assert np.array_equal(np.sort(np.asarray(out.ids, dtype=np.int64)), expect)


class TestBitPacking:
    def test_pack_decode_roundtrip(self):
        rng = np.random.default_rng(0)
        for block in (4096, 16384):
            sub, pack = block // 128, block // 128 // 32
            m = rng.uniform(size=(3, sub, 128)) < 0.1
            import jax.numpy as jnp

            from geomesa_tpu.scan.block_kernels import _pack_bits

            planes = np.stack(
                [np.asarray(_pack_bits(jnp.asarray(m[i]), pack)) for i in range(3)]
            )
            bids = np.array([5, 9, 11], np.int32)
            rows = bk.decode_bits(planes, bids, 3)
            flat = m.reshape(3, -1)
            # _pack_bits bit order: local row = (j*32 + b)*128 + lane; the
            # VMEM mask layout is row-major (sublane*128 + lane) — identical
            expect = np.concatenate(
                [np.flatnonzero(flat[i]) + bids[i] * block for i in range(3)]
            )
            assert np.array_equal(np.sort(rows), np.sort(expect))

    def test_window_slot_merge(self):
        w = np.array(
            [[3, 100, 604799], [4, 0, 604799], [5, 0, 604799], [6, 0, 42]], np.int32
        )
        slots = bk.merge_window_slots(w)
        assert slots.tolist() == [
            [3, 3, 100, 604799],
            [4, 5, 0, 604799],
            [6, 6, 0, 42],
        ]

    def test_window_slot_overflow_widens(self):
        # 12 disjoint single-bin windows -> merged down to 8 conservative slots
        w = np.array([[b * 3, 10, 20] for b in range(12)], np.int32)
        slots = bk.merge_window_slots(w)
        assert len(slots) <= 8
        # superset: every original window is covered by some slot
        for b, lo, hi in w.tolist():
            assert any(
                s[0] <= b <= s[1] and s[2] <= lo and s[3] >= hi for s in slots.tolist()
            )

    def test_window_slot_overflow_inner_drops(self):
        # the inner (certainty) plane must never widen: overflow drops slots,
        # so every surviving slot is one of the originals (subset semantics)
        w = np.array([[b * 3, 10, 20] for b in range(12)], np.int32)
        slots = bk.merge_window_slots(w, overflow="drop")
        assert len(slots) <= 8
        originals = {(b, b, lo, hi) for b, lo, hi in w.tolist()}
        assert all(tuple(s) in originals for s in slots.tolist())

    def test_many_interval_or_query_exact(self):
        """OR of >8 disjoint intervals: wide widens, inner drops — results
        must still be exact (code-review r3 regression)."""
        ds, data = make_store(n=20_000)
        x, y, t, t0 = data
        day = 86_400_000
        parts, m = [], np.zeros(len(t), bool)
        for k in range(10):
            lo = int(t0 + (2 * k) * day + 500)  # unaligned endpoints
            hi = int(t0 + (2 * k + 1) * day + 500)
            parts.append(
                f"dtg DURING {np.datetime64(lo, 'ms')}Z/{np.datetime64(hi, 'ms')}Z"
            )
            m |= (t >= lo) & (t < hi)
        q = f"bbox(geom, -10, -10, 10, 10) AND ({' OR '.join(parts)})"
        out = ds.query("pts", q)
        expect = np.flatnonzero(m & (x >= -10) & (x <= 10) & (y >= -10) & (y <= 10))
        assert np.array_equal(np.sort(np.asarray(out.ids, dtype=np.int64)), expect)

    def test_many_box_or_query_exact(self):
        """OR of >8 bboxes: wide collapses to a union, inner keeps subsets —
        results must still be exact (code-review r3 regression)."""
        ds, data = make_store(n=20_000)
        x, y, t, t0 = data
        boxes = [(-19 + 4 * k, -15 + k, -17.5 + 4 * k, -12 + k) for k in range(10)]
        q = " OR ".join(f"bbox(geom, {a}, {b}, {c}, {d})" for a, b, c, d in boxes)
        out = ds.query("pts", q)
        m = np.zeros(len(x), bool)
        for a, b, c, d in boxes:
            m |= (x >= a) & (x <= c) & (y >= b) & (y <= d)
        expect = np.flatnonzero(m)
        assert np.array_equal(np.sort(np.asarray(out.ids, dtype=np.int64)), expect)


class TestNativeZRanges:
    def test_native_matches_python(self):
        from geomesa_tpu import native

        if not native.available():
            pytest.skip("native lib unavailable")
        import os

        from geomesa_tpu.curve.z2sfc import Z2SFC

        sfc = Z2SFC()
        rng = np.random.default_rng(1)
        for _ in range(10):
            x0, y0 = rng.uniform(-170, 150), rng.uniform(-80, 70)
            w, h = rng.uniform(0.1, 30), rng.uniform(0.1, 15)
            bounds = [(x0, y0, x0 + w, y0 + h)]
            got = sfc.ranges(bounds)
            os.environ["GEOMESA_TPU_NO_NATIVE"] = "1"
            try:
                import geomesa_tpu.native as nat

                saved, nat._lib = nat._lib, False
                want = sfc.ranges(bounds)
            finally:
                nat._lib = saved
                del os.environ["GEOMESA_TPU_NO_NATIVE"]
            assert [(r.lower, r.upper, r.contained) for r in got] == [
                (r.lower, r.upper, r.contained) for r in want
            ]


class TestExtentModeKernel:
    """Direct extent=True kernel cases (XZ tables): bbox-INTERSECTS wide
    plane, all-false inner plane (bbox intersection can never certify the
    actual geometry predicate), and never-matching pad sentinels."""

    NAMES = ("gxmax", "gxmin", "gymax", "gymin")
    SUB = 32
    NB = 4

    def _cols(self):
        rng = np.random.default_rng(11)
        n = self.NB * self.SUB * 128
        x0 = rng.uniform(-170, 160, n).astype(np.float32)
        y0 = rng.uniform(-80, 70, n).astype(np.float32)
        w = rng.uniform(0.1, 10, n).astype(np.float32)
        h = rng.uniform(0.1, 8, n).astype(np.float32)
        cols = {"gxmin": x0, "gymin": y0, "gxmax": x0 + w, "gymax": y0 + h}
        # sentinel-pad the tail exactly like the table does
        from geomesa_tpu.storage.table import _SENTINELS

        for k in cols:
            cols[k][-700:] = _SENTINELS[k]
        import jax.numpy as jnp

        shape = (self.NB, self.SUB, 128)
        return cols, tuple(jnp.asarray(cols[k].reshape(shape)) for k in self.NAMES)

    def test_wide_intersects_inner_empty(self):
        host, cols3 = self._cols()
        boxes = bk.pack_boxes(
            np.array([[-30.0, -20.0, 40.0, 25.0]]),
            np.array([[-29.0, -19.0, 39.0, 24.0]]),  # inner MUST be ignored
        )
        wins = bk.pack_windows(None, None)
        bids, n_real = bk.pad_bids(np.arange(self.NB), self.NB)
        wide, inner = bk._xla_block_scan(
            cols3, bids, boxes, wins,
            col_names=self.NAMES, has_boxes=True, has_windows=False, extent=True,
        )
        # extent box scans skip the inner plane entirely (it would be
        # identically false: bbox intersection can never certify the
        # true geometry predicate)
        assert inner is None
        rows, certain = bk.decode_bits_pair(np.asarray(wide), None, bids, n_real)
        assert not certain.any()
        expect = np.flatnonzero(
            (host["gxmin"] <= 40) & (host["gxmax"] >= -30)
            & (host["gymin"] <= 25) & (host["gymax"] >= -20)
        )
        assert np.array_equal(rows, expect)
        assert len(rows) > 0

    def test_pad_sentinels_never_match(self):
        host, cols3 = self._cols()
        # a box covering the whole world still must not match sentinel rows
        boxes = bk.pack_boxes(np.array([[-180.0, -90.0, 180.0, 90.0]]), None)
        wins = bk.pack_windows(None, None)
        bids, n_real = bk.pad_bids(np.arange(self.NB), self.NB)
        wide, inner = bk._xla_block_scan(
            cols3, bids, boxes, wins,
            col_names=self.NAMES, has_boxes=True, has_windows=False, extent=True,
        )
        rows, _ = bk.decode_bits_pair(np.asarray(wide), inner, bids, n_real)
        n = self.NB * self.SUB * 128
        assert len(rows) == n - 700
        assert rows.max() < n - 700

    def test_interpret_parity_extent(self):
        _, cols3 = self._cols()
        boxes = bk.pack_boxes(np.array([[-30.0, -20.0, 40.0, 25.0]]), None)
        wins = bk.pack_windows(None, None)
        bids, _ = bk.pad_bids(np.array([0, 2]), self.NB)
        kw = dict(col_names=self.NAMES, has_boxes=True, has_windows=False, extent=True)
        w_ref, i_ref = bk._xla_block_scan(cols3, bids, boxes, wins, **kw)
        w_got, i_got = bk._pallas_block_scan(cols3, bids, boxes, wins, interpret=True, **kw)
        assert np.array_equal(np.asarray(w_ref), np.asarray(w_got))
        assert i_ref is None and i_got is None


class TestColumnProjection:
    """ColumnGroups analogue (reference index/conf/ColumnGroups.scala):
    scan variants DMA only the device columns the predicate reads."""

    def setup_method(self):
        self.ds, self.data = make_store(n=20000)

    def _cfg(self, q):
        idx = [i for i in self.ds.indexes("pts") if i.name == "z3"][0]
        return idx.scan_config(ecql.parse(q))

    def test_time_only_query_ships_no_xy(self):
        table = self.ds.table("pts", "z3")
        cfg = self._cfg("dtg DURING 2024-01-03T00:00:00Z/2024-01-07T00:00:00Z")
        assert cfg is not None and cfg.boxes is None and cfg.windows is not None
        rows, _ = table.scan(cfg)
        assert table.last_scan_cols == ("tbin", "toff")
        t0 = self.data[3]
        expect = brute(
            self.data, -1e9, -1e9, 1e9, 1e9, t0 + 2 * 86400_000, t0 + 6 * 86400_000
        )
        assert np.array_equal(np.sort(np.asarray(rows)), expect)

    def test_spatial_only_query_ships_no_time(self):
        table = self.ds.table("pts", "z3")
        cfg = self._cfg("bbox(geom, -5, -5, 5, 5)")
        if cfg is None:
            return  # z3 may decline bbox-only; z2 serves it
        table.scan(cfg)
        assert table.last_scan_cols == ("x", "y")

    def test_full_query_ships_all(self):
        table = self.ds.table("pts", "z3")
        cfg = self._cfg(
            "bbox(geom, -5, -5, 5, 5) AND dtg DURING 2024-01-03T00:00:00Z/2024-01-07T00:00:00Z"
        )
        table.scan(cfg)
        assert table.last_scan_cols == ("tbin", "toff", "x", "y")
        bytes_full = table.last_scan_bytes
        # measured bytes-scanned drop for the projected variant
        cfg2 = self._cfg("dtg DURING 2024-01-03T00:00:00Z/2024-01-07T00:00:00Z")
        table.scan(cfg2)
        assert table.last_scan_cols == ("tbin", "toff")
        assert table.last_scan_bytes < bytes_full


class TestLinkDerivedConstants:
    """Round 11 (VERDICT weak #8): the fused-chunk slot cap and M-bucket
    floor re-derive from the measured link probe instead of the 66 ms-era
    hand tuning; bench.py installs them before warmup."""

    def teardown_method(self):
        bk.set_link_constants(None)  # never leak tuning into other tests

    def test_design_link_reproduces_hand_tuning(self):
        from geomesa_tpu.storage.table import FUSED_CHUNK_SLOTS

        d = bk.derive_link_constants(66.0, 30.0)
        assert d["fused_chunk_slots"] == FUSED_CHUNK_SLOTS
        assert d["m_floor"] == bk.M_BUCKETS[0]

    def test_fast_link_shrinks_chunks_and_raises_floor(self):
        d = bk.derive_link_constants(0.4, 2000.0)
        assert d["fused_chunk_slots"] == 256
        assert d["m_floor"] == 128
        # intermediate links scale between the endpoints
        mid = bk.derive_link_constants(20.0, 30.0)
        assert 256 <= mid["fused_chunk_slots"] <= 1024
        assert mid["m_floor"] == bk.M_BUCKETS[0]

    def test_install_changes_bucket_and_cap_then_resets(self):
        base_bucket = bk.m_bucket_of(10)
        bk.set_link_constants(bk.derive_link_constants(0.4, 2000.0))
        try:
            # the floor applies ONLY to the single-query candidate
            # ladder — fused slot sizing (bucket_of) must stay unfloored
            # or small tables' chunks would inflate with pad-slot work
            assert bk.bucket_of(10) == 32
            assert bk.m_bucket_of(10) == 128
            assert len(bk.pad_bids(np.arange(10), 100)[0]) == 128
            assert bk.m_bucket_of(300) == 512   # ladder above floor intact
            assert bk.fused_slot_cap() == 256
            assert bk.link_constants()["m_floor"] == 128
            # a table built now clamps its fused shape to the new cap
            ds = DataStore(tile=64)
            sft = FeatureType.from_spec("lk", "*geom:Point:srid=4326")
            ds.create_schema(sft)
            n = 40_000
            rng = np.random.default_rng(3)
            ds.write("lk", FeatureCollection.from_columns(
                sft, np.arange(n).astype(str),
                {"geom": (rng.uniform(-60, 60, n), rng.uniform(-45, 45, n))},
            ), check_ids=False)
            ds.compact("lk")
            t = ds.table("lk", ds.indexes("lk")[0].name)
            assert t.fused_slots <= 256
        finally:
            bk.set_link_constants(None)
        assert bk.m_bucket_of(10) == base_bucket
        assert bk.fused_slot_cap() == 2048
