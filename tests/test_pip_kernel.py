"""Device point-in-polygon tier (VERDICT r4 #2): INTERSECTS with real
polygons on point tables resolves on device — wide = parity | near,
inner = parity & ~near — with host refinement only over the f32
uncertainty band. Differential: index path == brute-force full filter.

Reference: the always-refine polygon semantics the reference applies
server-side per row (geomesa-index-api/.../index/z2/Z2IndexKeySpace +
filter push-down); here the parity test IS the pushed-down filter.
"""

import numpy as np
import pytest

from geomesa_tpu import geometry as geo
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.scan import block_kernels as bk
from geomesa_tpu.sft import FeatureType

DAY = 86400_000
N = 6000


def _poly_wkt(kind, cx, cy, r, rng):
    if kind == "triangle":
        pts = [(cx - r, cy - r), (cx + r, cy - r), (cx, cy + r)]
    elif kind == "hex":
        a = np.linspace(0, 2 * np.pi, 7)[:-1] + rng.uniform(0, 1)
        pts = [(cx + r * np.cos(t), cy + 0.7 * r * np.sin(t)) for t in a]
    elif kind == "lshape":
        pts = [
            (cx - r, cy - r), (cx + r, cy - r), (cx + r, cy),
            (cx, cy), (cx, cy + r), (cx - r, cy + r),
        ]
    else:  # star-ish concave
        a = np.linspace(0, 2 * np.pi, 11)[:-1]
        rad = np.where(np.arange(10) % 2 == 0, r, 0.4 * r)
        pts = [(cx + rr * np.cos(t), cy + rr * np.sin(t)) for t, rr in zip(a, rad)]
    ring = ", ".join(f"{x:.6f} {y:.6f}" for x, y in pts + [pts[0]])
    return f"POLYGON(({ring}))"


@pytest.fixture(scope="module")
def stores():
    rng = np.random.default_rng(31)
    t0 = np.datetime64("2024-04-01T00:00:00", "ms").astype(np.int64)
    x = rng.uniform(-60, 60, N)
    y = rng.uniform(-40, 40, N)
    t = t0 + rng.integers(0, 30 * DAY, N)
    z2 = FeatureType.from_spec("p2", "*geom:Point:srid=4326")
    z2.user_data["geomesa.indices.enabled"] = "z2"
    z3 = FeatureType.from_spec("p3", "dtg:Date,*geom:Point:srid=4326")
    z3.user_data["geomesa.indices.enabled"] = "z3"
    ds = DataStore(tile=64)
    ds.create_schema(z2)
    ds.create_schema(z3)
    ds.write("p2", FeatureCollection.from_columns(
        z2, [str(i) for i in range(N)], {"geom": (x, y)}))
    ds.write("p3", FeatureCollection.from_columns(
        z3, [str(i) for i in range(N)], {"dtg": t, "geom": (x, y)}))
    return ds, x, y, t, t0


class TestPackEdges:
    def test_rect_and_hole(self):
        p = geo.Polygon(
            [(0, 0), (10, 0), (10, 10), (0, 10)],
            holes=[[(3, 3), (5, 3), (5, 5), (3, 5)]],
        )
        e = bk.pack_edges(p)
        assert e is not None and e.shape == (16, 128)
        # 8 real edges (4 shell + 4 hole), pads zeroed
        assert (e[8:, :6] == 0).all()

    def test_too_many_edges_fall_back(self):
        a = np.linspace(0, 2 * np.pi, 400)
        ring = [(np.cos(t), np.sin(t)) for t in a]
        assert bk.pack_edges(geo.Polygon(ring)) is None

    def test_non_polygon(self):
        assert bk.pack_edges(geo.from_wkt("LINESTRING(0 0, 1 1)")) is None


class TestPipConfig:
    def test_z2_intersects_gets_poly_config(self, stores):
        ds, *_ = stores
        from geomesa_tpu.filter import ecql

        idx = next(i for i in ds.indexes("p2") if i.name == "z2")
        rng = np.random.default_rng(0)
        f = ecql.parse(f"INTERSECTS(geom, {_poly_wkt('hex', 0, 0, 5, rng)})")
        cfg = idx.scan_config(f)
        assert cfg.poly is not None
        assert cfg.geom_precise
        assert not cfg.contained_exact  # bbox containment != polygon hit

    def test_bbox_still_bounds_exact(self, stores):
        ds, *_ = stores
        from geomesa_tpu.filter import ecql

        idx = next(i for i in ds.indexes("p2") if i.name == "z2")
        cfg = idx.scan_config(ecql.parse("bbox(geom, 0, 0, 10, 10)"))
        assert cfg.poly is None and cfg.geom_precise and cfg.contained_exact


class TestPipDifferential:
    @pytest.mark.parametrize("seed", range(24))
    def test_z2_polygon_queries(self, stores, seed):
        ds, x, y, _, _ = stores
        rng = np.random.default_rng(5100 + seed)
        kind = ["triangle", "hex", "lshape", "star"][seed % 4]
        cx, cy = float(rng.uniform(-40, 40)), float(rng.uniform(-25, 25))
        r = float(rng.choice([0.5, 3.0, 12.0]))
        expr = f"INTERSECTS(geom, {_poly_wkt(kind, cx, cy, r, rng)})"
        got = np.sort(np.asarray(ds.query("p2", expr).ids, dtype=np.int64))
        # brute force: full filter over every row
        from geomesa_tpu.filter import ecql

        f = ecql.parse(expr)
        truth = f.evaluate(ds.features("p2").batch)
        np.testing.assert_array_equal(got, np.flatnonzero(truth), err_msg=expr)

    @pytest.mark.parametrize("seed", range(12))
    def test_z3_polygon_time_queries(self, stores, seed):
        ds, x, y, t, t0 = stores
        rng = np.random.default_rng(5400 + seed)
        kind = ["triangle", "hex", "lshape", "star"][seed % 4]
        cx, cy = float(rng.uniform(-40, 40)), float(rng.uniform(-25, 25))
        r = float(rng.choice([1.0, 8.0]))
        lo = int(t0 + rng.integers(0, 20) * DAY)
        hi = lo + int(rng.choice([1, 7, 15])) * DAY
        expr = (
            f"INTERSECTS(geom, {_poly_wkt(kind, cx, cy, r, rng)}) AND "
            f"dtg DURING {np.datetime64(lo, 'ms')}Z/{np.datetime64(hi, 'ms')}Z"
        )
        got = np.sort(np.asarray(ds.query("p3", expr).ids, dtype=np.int64))
        from geomesa_tpu.filter import ecql

        truth = ecql.parse(expr).evaluate(ds.features("p3").batch)
        np.testing.assert_array_equal(got, np.flatnonzero(truth), err_msg=expr)

    def test_polygon_with_hole(self, stores):
        ds, x, y, _, _ = stores
        expr = (
            "INTERSECTS(geom, POLYGON((-20 -20, 20 -20, 20 20, -20 20, -20 -20), "
            "(-10 -10, 10 -10, 10 10, -10 10, -10 -10)))"
        )
        got = np.sort(np.asarray(ds.query("p2", expr).ids, dtype=np.int64))
        from geomesa_tpu.filter import ecql

        truth = ecql.parse(expr).evaluate(ds.features("p2").batch)
        np.testing.assert_array_equal(got, np.flatnonzero(truth))
        # the ring cut-out is live: fewer hits than the outer box alone
        outer = ds.query("p2", "bbox(geom, -20, -20, 20, 20)")
        assert 0 < len(got) < len(outer)

    def test_certainty_vector_mostly_certain(self, stores):
        """The device resolves the bulk of candidates: the near band is a
        thin boundary strip, so most rows come back certain."""
        ds, *_ = stores
        from geomesa_tpu.filter import ecql

        idx = next(i for i in ds.indexes("p2") if i.name == "z2")
        rng = np.random.default_rng(3)
        cfg = idx.scan_config(
            ecql.parse(f"INTERSECTS(geom, {_poly_wkt('hex', 0, 0, 20, rng)})")
        )
        table = ds.table("p2", "z2")
        ordinals, certain = table.scan(cfg)
        assert len(ordinals) > 50
        # wide includes near-band misses; certain rows must dominate
        assert certain.mean() > 0.5

    def test_mesh_matches_single(self, stores):
        from geomesa_tpu.parallel import make_mesh

        ds, x, y, t, t0 = stores
        rng = np.random.default_rng(9)
        sft = FeatureType.from_spec("pm", "*geom:Point:srid=4326")
        sft.user_data["geomesa.indices.enabled"] = "z2"
        dsm = DataStore(tile=64, mesh=make_mesh(4))
        dsm.create_schema(sft)
        dsm.write("pm", FeatureCollection.from_columns(
            sft, [str(i) for i in range(N)], {"geom": (x, y)}))
        expr = f"INTERSECTS(geom, {_poly_wkt('star', 5, 5, 15, rng)})"
        a = sorted(np.asarray(ds.query("p2", expr).ids).tolist())
        b = sorted(np.asarray(dsm.query("pm", expr).ids).tolist())
        assert a == b and len(a) > 0

    def test_density_on_polygon_filter_still_exact(self, stores):
        """Aggregation fast paths must NOT ride the poly mask (wide plane
        includes the near band): density falls to the host path and
        matches a brute-force scatter."""
        ds, x, y, _, _ = stores
        rng = np.random.default_rng(4)
        expr = f"INTERSECTS(geom, {_poly_wkt('lshape', 0, 0, 18, rng)})"
        grid = ds.density("p2", expr, envelope=(-60, -40, 60, 40), width=32, height=16)
        from geomesa_tpu.filter import ecql

        truth = ecql.parse(expr).evaluate(ds.features("p2").batch)
        assert int(grid.sum()) == int(truth.sum())


class TestPallasParity:
    """The Pallas edge-kernel plumbing (edge BlockSpec, refs slicing,
    _pip_unrolled) must produce bit-identical planes to the XLA variant —
    interpret mode runs the Pallas program on CPU (cf.
    test_block_scan.py::test_interpret_parity_extent)."""

    def _setup(self, n_edges_bucket):
        rng = np.random.default_rng(41)
        NB, SUB = 4, 32
        n = NB * SUB * 128
        x = rng.uniform(-30, 30, n).astype(np.float32).reshape(NB, SUB, 128)
        y = rng.uniform(-30, 30, n).astype(np.float32).reshape(NB, SUB, 128)
        a = np.linspace(0, 2 * np.pi, n_edges_bucket - 1)[:-1]
        ring = [(12 * np.cos(t), 9 * np.sin(t)) for t in a]
        edges = bk.pack_edges(geo.Polygon(ring))
        assert edges is not None and edges.shape[0] == n_edges_bucket
        boxes = bk.pack_boxes(np.array([[-12.5, -9.5, 12.5, 9.5]]), None)
        wins = bk.pack_windows(None, None)
        bids, n_real = bk.pad_bids(np.arange(NB), NB)
        return (x, y), bids, n_real, boxes, wins, edges

    @pytest.mark.parametrize("bucket", [16, 64])
    def test_interpret_parity_pip(self, bucket):
        cols3, bids, n_real, boxes, wins, edges = self._setup(bucket)
        kw = dict(
            col_names=("x", "y"), has_boxes=True, has_windows=False,
            extent=False, n_edges=edges.shape[0],
        )
        w_ref, i_ref = bk._xla_block_scan(cols3, bids, boxes, wins, edges, **kw)
        w_got, i_got = bk._pallas_block_scan(
            cols3, bids, boxes, wins, edges, interpret=True, **kw
        )
        assert np.array_equal(np.asarray(w_ref), np.asarray(w_got))
        assert np.array_equal(np.asarray(i_ref), np.asarray(i_got))
        # and the planes are live: some hits, some certainty
        rows, certain = bk.decode_bits_pair(
            np.asarray(w_ref), np.asarray(i_ref), bids, n_real
        )
        assert len(rows) > 0 and certain.any()
