"""Packed-time z3 device layout (the 1B-row single-chip budget): one i32
tw = bin << 16 | (offset >> shift) column instead of (tbin, toff) —
12 B/row. Differential: packed stores answer EXACTLY like unpacked ones
(tick-boundary rows refine on host via the wide/inner certainty tiers).
"""

import numpy as np
import pytest

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.index.z3 import PACKED_KEY, PACKED_SHIFT, pack_tw, windows_to_ticks
from geomesa_tpu.sft import FeatureType

DAY = 86400_000
N = 5000


def _store(packed: bool, n=N, seed=17, interval="week"):
    rng = np.random.default_rng(seed)
    sft = FeatureType.from_spec("pt", "dtg:Date,*geom:Point:srid=4326")
    sft.user_data["geomesa.indices.enabled"] = "z3"
    sft.user_data["geomesa.z3.interval"] = interval
    if packed:
        sft.user_data[PACKED_KEY] = "true"
    ds = DataStore(tile=64)
    ds.create_schema(sft)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    t = t0 + rng.integers(0, 45 * DAY, n)
    ds.write("pt", FeatureCollection.from_columns(
        sft, [str(i) for i in range(n)], {"dtg": t, "geom": (x, y)}))
    return ds, x, y, t, int(t0)


class TestPacking:
    def test_pack_roundtrip_bins(self):
        tb = np.array([0, 100, 2900, 32767], np.int32)
        to = np.array([0, 604799, 12345, 604800 - 1], np.int32)
        from geomesa_tpu.curve.binnedtime import TimePeriod

        tw = pack_tw(tb, to, PACKED_SHIFT[TimePeriod.WEEK])
        assert (tw >> 16 == tb).all()
        assert (tw >= 0).all()

    def test_bin_overflow_raises(self):
        with pytest.raises(ValueError, match="15 bits"):
            pack_tw(np.array([40000], np.int32), np.array([0], np.int32), 5)

    def test_tick_overflow_raises(self):
        # a month's max offset (2,678,399 s) >> 5 would bleed into the
        # bin bits (the review-caught MONTH shift bug); pack_tw refuses
        with pytest.raises(ValueError, match="tick overflow"):
            pack_tw(np.array([1], np.int32), np.array([2_678_399], np.int32), 5)
        # the correct month shift fits
        pack_tw(np.array([1], np.int32), np.array([2_678_399], np.int32), 6)

    def test_all_period_shifts_fit(self):
        from geomesa_tpu.curve.binnedtime import MAX_OFFSET, TimePeriod
        from geomesa_tpu.scan.block_kernels import TW_MASK

        for period, shift in PACKED_SHIFT.items():
            assert MAX_OFFSET[period] >> shift <= TW_MASK, period

    def test_window_tick_conversion_conservative(self):
        # wide floors; inner shrinks to fully-covered ticks
        w = np.array([[5, 63, 200]], np.int64)
        wide = windows_to_ticks(w, 5, inner=False)
        inner = windows_to_ticks(w, 5, inner=True)
        assert wide[0, 1] == 63 >> 5 and wide[0, 2] == 200 >> 5
        assert inner[0, 1] == (63 + 31) >> 5  # ceil
        assert inner[0, 2] == (200 - 31) >> 5

    def test_device_bytes_12_per_row(self):
        ds, *_ = _store(packed=True, n=3000)
        table = ds.table("pt", "z3")
        t = getattr(table, "main", table)
        assert set(t.col_names) == {"x", "y", "tw"}
        ds2, *_ = _store(packed=False, n=3000, seed=18)
        t2 = ds2.table("pt", "z3")
        t2 = getattr(t2, "main", t2)
        assert set(t2.col_names) == {"x", "y", "tbin", "toff"}


class TestPackedDifferential:
    @pytest.mark.parametrize("interval", ["week", "day", "month", "year"])
    @pytest.mark.parametrize("seed", range(10))
    def test_packed_equals_unpacked(self, seed, interval):
        ds_p, x, y, t, t0 = _store(packed=True, seed=29, interval=interval)
        ds_u, *_ = _store(packed=False, seed=29, interval=interval)
        rng = np.random.default_rng(6200 + seed)
        w = float(rng.choice([2.0, 20.0, 120.0]))
        qx = float(f"{rng.uniform(-175, 175 - w):.3f}")
        qy = float(f"{rng.uniform(-85, 85 - w / 2):.3f}")
        # window endpoints at arbitrary ms (NOT tick-aligned)
        lo = int(t0 + rng.integers(0, 40 * DAY))
        hi = lo + int(rng.integers(1, 10 * DAY))
        q = (f"bbox(geom, {qx}, {qy}, {qx + w}, {qy + w / 2}) AND dtg DURING "
             f"{np.datetime64(lo, 'ms')}Z/{np.datetime64(hi, 'ms')}Z")
        a = sorted(np.asarray(ds_p.query("pt", q).ids).tolist())
        b = sorted(np.asarray(ds_u.query("pt", q).ids).tolist())
        assert a == b, q
        mask = (x >= qx) & (x <= qx + w) & (y >= qy) & (y <= qy + w / 2) \
            & (t >= lo) & (t <= hi)
        assert a == sorted(str(i) for i in np.flatnonzero(mask))

    def test_tick_boundary_rows_exact(self):
        """Rows whose offset sits exactly at a tick edge, queried with
        windows cutting through the same tick."""
        sft = FeatureType.from_spec("tb", "dtg:Date,*geom:Point:srid=4326")
        sft.user_data["geomesa.indices.enabled"] = "z3"
        sft.user_data[PACKED_KEY] = "true"
        ds = DataStore(tile=64)
        ds.create_schema(sft)
        t0 = int(np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64))
        # a week-period tick is 32 s: place rows 1 ms apart around an edge
        base = t0 + 7 * 32000
        ts = np.array([base - 1, base, base + 1, base + 31999, base + 32000])
        ds.write("tb", FeatureCollection.from_columns(
            sft, [str(i) for i in range(5)],
            {"dtg": ts, "geom": (np.zeros(5), np.zeros(5))}))
        lo, hi = base, base + 31999  # exactly one tick, ms endpoints
        q = (f"bbox(geom, -1, -1, 1, 1) AND dtg DURING "
             f"{np.datetime64(lo, 'ms')}Z/{np.datetime64(hi, 'ms')}Z")
        got = sorted(np.asarray(ds.query("tb", q).ids).tolist())
        # DURING is half-open [lo, hi)
        want = sorted(str(i) for i in np.flatnonzero((ts >= lo) & (ts < hi)))
        assert got == want

    def test_delta_tier_and_compaction(self):
        ds, x, y, t, t0 = _store(packed=True, n=2000)
        sft = ds.get_schema("pt")
        rng = np.random.default_rng(8)
        t2 = t0 + rng.integers(0, 45 * DAY, 300)
        ds.write("pt", FeatureCollection.from_columns(
            sft, [f"d{i}" for i in range(300)],
            {"dtg": t2, "geom": (rng.uniform(-180, 180, 300), rng.uniform(-90, 90, 300))}))
        lo = t0 + 5 * DAY
        hi = t0 + 25 * DAY
        q = (f"bbox(geom, -90, -45, 90, 45) AND dtg DURING "
             f"{np.datetime64(lo, 'ms')}Z/{np.datetime64(hi, 'ms')}Z")
        got = set(np.asarray(ds.query("pt", q).ids).tolist())
        m1 = (x >= -90) & (x <= 90) & (y >= -45) & (y <= 45) & (t >= lo) & (t <= hi)
        xs2 = None
        fc2 = ds.features("pt")
        want = {str(i) for i in np.flatnonzero(m1)}
        gx = np.asarray(fc2.geom_column.x)[2000:]
        gy = np.asarray(fc2.geom_column.y)[2000:]
        m2 = (gx >= -90) & (gx <= 90) & (gy >= -45) & (gy <= 45) & (t2 >= lo) & (t2 <= hi)
        want |= {f"d{i}" for i in np.flatnonzero(m2)}
        assert got == want
        ds.compact("pt")
        got2 = set(np.asarray(ds.query("pt", q).ids).tolist())
        assert got2 == want

    def test_count_and_density_on_packed(self):
        ds, x, y, t, t0 = _store(packed=True)
        lo, hi = t0 + 3 * DAY, t0 + 30 * DAY
        q = (f"bbox(geom, -120, -60, 120, 60) AND dtg DURING "
             f"{np.datetime64(lo, 'ms')}Z/{np.datetime64(hi, 'ms')}Z")
        mask = (x >= -120) & (x <= 120) & (y >= -60) & (y <= 60) \
            & (t >= lo) & (t <= hi)
        assert ds.count("pt", q) == int(mask.sum())
        grid = ds.density("pt", q, envelope=(-180, -90, 180, 90), width=32, height=16)
        # device estimate path is tick-loose; exact host fallback isn't —
        # allow the documented wide margin only at tick edges
        assert abs(int(grid.sum()) - int(mask.sum())) <= int(0.02 * mask.sum()) + 64
