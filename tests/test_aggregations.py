"""Aggregation push-down: density grids, stats scans, BIN export, hints.

Each device aggregation is checked against a NumPy recomputation over the
same (exact-refined) query results, single-device and on the 8-device mesh.
"""

import numpy as np
import pytest

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.parallel import make_mesh
from geomesa_tpu.planning.hints import QueryHints
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.stats import stat_spec
from geomesa_tpu.utils import bin_format

SPEC = "name:String,age:Int,dtg:Date,*geom:Point:srid=4326"
Q_ST = "bbox(geom, -60, -40, 60, 40) AND dtg DURING 2024-01-03T00:00:00Z/2024-01-20T12:00:00Z"
ENV = (-60.0, -40.0, 60.0, 40.0)


def _store(mesh=None, n=5000, tile=64):
    sft = FeatureType.from_spec("pts", SPEC)
    ds = DataStore(tile=tile, mesh=mesh)
    ds.create_schema(sft)
    rng = np.random.default_rng(3)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    fc = FeatureCollection.from_columns(
        sft,
        [str(i) for i in range(n)],
        {
            "name": np.array([f"n{i % 7}" for i in range(n)]),
            "age": np.arange(n) % 90,
            "dtg": t0 + rng.integers(0, 45 * 86400_000, n),
            "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)),
        },
    )
    ds.write("pts", fc)
    return ds


@pytest.fixture(scope="module")
def ds():
    return _store()


def _expected_grid(fc, env, w, h, weight=None):
    x0, y0, x1, y1 = env
    col = fc.geom_column
    x, y = col.x, col.y
    wt = np.asarray(fc.columns[weight], np.float64) if weight else np.ones(len(fc))
    g = np.zeros(h * w)
    m = (x >= x0) & (x <= x1) & (y >= y0) & (y <= y1)
    px = np.clip(((x - x0) / (x1 - x0) * w).astype(np.int64), 0, w - 1)
    py = np.clip(((y - y0) / (y1 - y0) * h).astype(np.int64), 0, h - 1)
    np.add.at(g, (py * w + px)[m], wt[m])
    return g.reshape(h, w)


class TestDensity:
    def test_device_matches_brute_force(self, ds):
        grid = ds.density("pts", Q_ST, envelope=ENV, width=32, height=16)
        exact = _expected_grid(ds.query("pts", Q_ST), ENV, 32, 16)
        assert grid.shape == (16, 32)
        np.testing.assert_allclose(grid, exact)

    def test_device_path_taken(self, ds):
        # spatiotemporal-only filter -> device path (no host gather): verify
        # via the plan gate used by DataStore.density
        from geomesa_tpu.filter import ecql
        from geomesa_tpu.planning.planner import _filter_leaf_kinds

        f = ecql.parse(Q_ST)
        assert _filter_leaf_kinds(f, "geom", "dtg") == {"spatial", "temporal"}
        f2 = ecql.parse(Q_ST + " AND age < 30")
        assert _filter_leaf_kinds(f2, "geom", "dtg") is None

    def test_host_fallback_weighted(self, ds):
        q = Q_ST + " AND age < 30"
        grid = ds.density("pts", q, envelope=ENV, width=16, height=16, weight="age")
        exact = _expected_grid(ds.query("pts", q), ENV, 16, 16, weight="age")
        np.testing.assert_allclose(grid, exact)

    def test_distributed_matches_single(self, ds):
        dds = _store(make_mesh(8))
        g1 = ds.density("pts", Q_ST, envelope=ENV, width=32, height=16)
        g8 = dds.density("pts", Q_ST, envelope=ENV, width=32, height=16)
        np.testing.assert_allclose(g1, g8)

    def test_total_mass_is_hit_count_inside_env(self, ds):
        grid = ds.density("pts", Q_ST, envelope=ENV, width=64, height=64)
        assert grid.sum() == len(ds.query("pts", Q_ST))


class TestStats:
    def test_count_minmax(self, ds):
        out = ds.stats_query("pts", "Count();MinMax(age)", Q_ST)
        hits = ds.query("pts", Q_ST)
        assert out[0].count == len(hits)
        assert out[1].bounds == (
            np.asarray(hits.columns["age"]).min(),
            np.asarray(hits.columns["age"]).max(),
        )

    def test_enumeration_groupby(self, ds):
        out = ds.stats_query("pts", "Enumeration(name)", Q_ST)
        hits = ds.query("pts", Q_ST)
        vals, cnts = np.unique(np.asarray(hits.columns["name"]), return_counts=True)
        assert dict(out[0].top(100)) == dict(zip(vals.tolist(), cnts.tolist()))

        grouped = ds.stats_query("pts", "GroupBy(name,Count())", Q_ST)[0]
        assert {k: v[0].count for k, v in grouped.items()} == dict(
            zip(vals.tolist(), cnts.tolist())
        )

    def test_histogram_spec(self):
        fc = FeatureCollection.from_columns(
            FeatureType.from_spec("t", "v:Int,*geom:Point:srid=4326"),
            ["a", "b", "c", "d"],
            {"v": [1, 2, 8, 9], "geom": (np.zeros(4), np.zeros(4))},
        )
        (h,) = stat_spec.evaluate("Histogram(v,2,0,10)", fc)
        assert h.counts.tolist() == [2, 2]

    def test_bad_spec(self):
        with pytest.raises(ValueError):
            stat_spec.parse("Bogus(x)")


class TestBinFormat:
    def test_roundtrip_16(self):
        lon = np.array([10.5, -20.25])
        lat = np.array([1.5, 2.5])
        dtg = np.array([1_700_000_000_123, 1_700_000_111_999])
        data = bin_format.encode(lon, lat, dtg, np.array(["a", "b"]))
        assert len(data) == 32
        out = bin_format.decode(data)
        np.testing.assert_allclose(out["lon"], lon.astype(np.float32))
        np.testing.assert_allclose(out["lat"], lat.astype(np.float32))
        np.testing.assert_array_equal(out["dtg_s"], dtg // 1000)
        assert out["track"][0] != out["track"][1]

    def test_roundtrip_24_sorted(self):
        lon = np.array([1.0, 2.0, 3.0])
        lat = np.zeros(3)
        dtg = np.array([3_000, 1_000, 2_000], dtype=np.int64)
        data = bin_format.encode(
            lon, lat, dtg, np.arange(3), label=np.array([7, 8, 9]), sort=True
        )
        assert len(data) == 72
        out = bin_format.decode(data, label=True)
        assert out["dtg_s"].tolist() == [1, 2, 3]
        assert out["label"].tolist() == [8, 9, 7]

    def test_store_bin_query(self, ds):
        data = ds.bin_query("pts", Q_ST, track="name")
        hits = ds.query("pts", Q_ST)
        assert len(data) == 16 * len(hits)
        out = bin_format.decode(data)
        assert len(np.unique(out["track"])) == len(
            np.unique(np.asarray(hits.columns["name"]))
        )


class TestHints:
    def test_transforms_and_sort(self, ds):
        out = ds.query(
            "pts", Q_ST, hints=QueryHints(transforms=["age", "geom"], sort_by="-age")
        )
        assert set(out.columns) == {"age", "geom"}
        ages = np.asarray(out.columns["age"])
        assert (np.diff(ages) <= 0).all()

    def test_sampling(self, ds):
        full = ds.query("pts", Q_ST)
        half = ds.query("pts", Q_ST, hints=QueryHints(sample=0.5))
        assert 0 < len(half) <= len(full) // 2 + 1
        strat = ds.query("pts", Q_ST, hints=QueryHints(sample=0.25, sample_by="name"))
        # every surviving group came from the full result's groups
        assert set(np.asarray(strat.columns["name"])) <= set(
            np.asarray(full.columns["name"])
        )

    def test_loose_superset(self, ds):
        exact = ds.query("pts", Q_ST)
        loose = ds.query("pts", Q_ST, hints=QueryHints(loose=True))
        assert set(exact.ids.tolist()) <= set(loose.ids.tolist())
        # widening is one f32 ulp: loose adds at most a sliver
        assert len(loose) - len(exact) <= 5

    def test_bad_sample(self, ds):
        with pytest.raises(ValueError):
            ds.query("pts", Q_ST, hints=QueryHints(sample=1.5))

    def test_atemporal_index_cannot_claim_temporal_filter(self, ds):
        # a z2 config (windows=None) must not satisfy a temporal filter even
        # though its time_precise flag is vacuously True
        from geomesa_tpu.filter import ecql
        from geomesa_tpu.planning.planner import mask_decides_filter

        f = ecql.parse(Q_ST)
        sft = ds.get_schema("pts")
        z2 = next(i for i in ds.indexes("pts") if i.name == "z2")
        z3 = next(i for i in ds.indexes("pts") if i.name == "z3")
        assert not mask_decides_filter(f, z2.scan_config(f), sft)
        assert mask_decides_filter(f, z3.scan_config(f), sft)

    def test_stable_descending_sort(self):
        sft = FeatureType.from_spec("t", "v:Int,*geom:Point:srid=4326")
        fc = FeatureCollection.from_columns(
            sft,
            ["a", "b", "c", "d"],
            {"v": [2, 1, 2, 1], "geom": (np.zeros(4), np.zeros(4))},
        )
        out = fc.sort_values("-v")
        # ties keep original order: 2s are (a, c), 1s are (b, d)
        assert out.ids.tolist() == ["a", "c", "b", "d"]


class TestBounds:
    def test_estimate_matches_exact(self, ds):
        est = ds.bounds("pts", Q_ST, estimate=True)
        exact = ds.bounds("pts", Q_ST, estimate=False)
        assert est is not None and exact is not None
        # estimate is f32-loose; both must agree to f32 resolution
        np.testing.assert_allclose(est, exact, rtol=1e-6)

    def test_empty(self, ds):
        assert ds.bounds("pts", "bbox(geom, 179.99, 89.99, 180, 90)") is None

    def test_estimate_count_stat(self, ds):
        (est,) = ds.stats_query("pts", "Count()", Q_ST, estimate=True)
        (exact,) = ds.stats_query("pts", "Count()", Q_ST)
        assert abs(est.count - exact.count) <= 5  # loose f32 widening


class TestEmptyResults:
    def test_empty_bin_query(self, ds):
        assert ds.bin_query("pts", "bbox(geom, 179.99, 89.99, 180, 90)") == b""


class TestDensityMany:
    def test_matches_sequential(self):
        import numpy as np

        from geomesa_tpu.datastore import DataStore
        from geomesa_tpu.features import FeatureCollection
        from geomesa_tpu.sft import FeatureType

        rng = np.random.default_rng(0)
        n = 30_000
        sft = FeatureType.from_spec("d", "dtg:Date,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
        ds.write("d", FeatureCollection.from_columns(
            sft, np.arange(n).astype(str),
            {"dtg": t0 + rng.integers(0, 10**9, n),
             "geom": (rng.uniform(-60, 60, n), rng.uniform(-40, 40, n))}))
        # tile pyramid: 4 device tiles + 1 disjoint + 1 host-fallback (NOT)
        reqs = [
            ("bbox(geom, -60, -40, 0, 0)", (-60, -40, 0, 0)),
            ("bbox(geom, 0, 0, 60, 40)", (0, 0, 60, 40)),
            ("bbox(geom, -60, 0, 0, 40)", (-60, 0, 0, 40)),
            ("bbox(geom, 0, -40, 60, 0)", (0, -40, 60, 0)),
            ("bbox(geom, 100, 50, 120, 60) AND bbox(geom, -10, -10, -5, -5)",
             (100, 50, 120, 60)),
            ("NOT (bbox(geom, -60, -40, 0, 0))", (-60, -40, 60, 40)),
        ]
        many = ds.density_many("d", reqs, width=64, height=64)
        for (f, env), grid in zip(reqs, many):
            single = ds.density("d", f, envelope=env, width=64, height=64)
            np.testing.assert_array_equal(grid, single)
        # the four quadrant tiles cover every feature exactly once
        total = sum(g.sum() for g in many[:4])
        assert total == n

    def test_density_with_pending_delta(self):
        import numpy as np

        from geomesa_tpu.datastore import DataStore
        from geomesa_tpu.features import FeatureCollection
        from geomesa_tpu.sft import FeatureType

        rng = np.random.default_rng(1)
        sft = FeatureType.from_spec("dd", "dtg:Date,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)

        def batch(n, seed, prefix):
            r = np.random.default_rng(seed)
            return FeatureCollection.from_columns(
                sft, [f"{prefix}{i}" for i in range(n)],
                {"dtg": t0 + r.integers(0, 10**9, n),
                 "geom": (r.uniform(-50, 50, n), r.uniform(-30, 30, n))})

        ds.write("dd", batch(200_000, 0, "a"))  # compacts
        ds.write("dd", batch(500, 1, "b"))      # stays in the delta tier
        env = (-50, -30, 50, 30)
        grid = ds.density("dd", "bbox(geom, -50, -30, 50, 30)", envelope=env,
                          width=64, height=64)
        assert grid.sum() == 200_500  # main + delta rows both rendered
        many = ds.density_many(
            "dd", [("bbox(geom, -50, -30, 50, 30)", env)] * 3,
            width=64, height=64)
        for g in many:
            np.testing.assert_array_equal(g, grid)
