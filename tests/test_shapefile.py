"""Shapefile reader: binary .shp/.dbf decode (geomesa-convert-shp
analogue). The tests write spec-conformant files byte-by-byte, so they
validate the format understanding, not just a round-trip."""

import struct

import numpy as np
import pytest

from geomesa_tpu import DataStore
from geomesa_tpu.io.shapefile import read_shapefile


def _shp(records: list[bytes]) -> bytes:
    body = b""
    for i, content in enumerate(records):
        body += struct.pack(">ii", i + 1, len(content) // 2) + content
    total_words = (100 + len(body)) // 2
    header = struct.pack(">i5i", 9994, 0, 0, 0, 0, 0) + struct.pack(">i", total_words)
    header += struct.pack("<ii", 1000, 1)  # version, shape type (unused)
    header += struct.pack("<8d", 0, 0, 0, 0, 0, 0, 0, 0)
    assert len(header) == 100
    return header + body


def _point(x, y) -> bytes:
    return struct.pack("<i2d", 1, x, y)


def _polygon(rings: list[np.ndarray]) -> bytes:
    pts = np.concatenate(rings)
    parts = np.cumsum([0] + [len(r) for r in rings[:-1]]).astype("<i4")
    out = struct.pack("<i4d", 5, pts[:, 0].min(), pts[:, 1].min(),
                      pts[:, 0].max(), pts[:, 1].max())
    out += struct.pack("<2i", len(rings), len(pts))
    out += parts.tobytes() + pts.astype("<f8").tobytes()
    return out


def _polyline(lines: list[np.ndarray]) -> bytes:
    out = _polygon(lines)  # same layout, different type code
    return struct.pack("<i", 3) + out[4:]


def _dbf(fields: list[tuple], rows: list[list]) -> bytes:
    rec_size = 1 + sum(f[2] for f in fields)
    hdr_size = 32 + 32 * len(fields) + 1
    out = bytearray(struct.pack("<4BiHH20x", 3, 24, 1, 1, len(rows), hdr_size, rec_size))
    for name, ftype, length, dec in fields:
        out += struct.pack("<11sc4xBB14x", name.encode(), ftype.encode(), length, dec)
    out += b"\x0d"
    for row in rows:
        out += b" "
        for (name, ftype, length, dec), v in zip(fields, row):
            s = str(v)
            out += (s.rjust(length) if ftype in "NF" else s.ljust(length)).encode()[:length]
    return bytes(out)


CW = np.array([[0, 0], [0, 4], [4, 4], [4, 0], [0, 0]], float)  # clockwise
HOLE = np.array([[1, 1], [2, 1], [2, 2], [1, 2], [1, 1]], float)  # ccw


class TestShp:
    def test_points_with_dbf(self):
        shp = _shp([_point(10.5, -3.25), _point(-20.0, 40.0)])
        dbf = _dbf(
            [("name", "C", 8, 0), ("pop", "N", 6, 0), ("score", "N", 8, 3)],
            [["alpha", 120, 1.25], ["beta", 98765, -2.5]],
        )
        fc = read_shapefile(shp, dbf, type_name="cities")
        assert len(fc) == 2
        assert fc.columns["name"].tolist() == ["alpha", "beta"]
        assert fc.columns["pop"].tolist() == [120, 98765]
        assert np.allclose(fc.columns["score"], [1.25, -2.5])
        assert np.allclose(fc.columns["geom"].x, [10.5, -20.0])
        assert fc.sft.attributes[-1].type == "Point"

    def test_polygon_with_hole(self):
        fc = read_shapefile(_shp([_polygon([CW, HOLE])]))
        g = fc.columns["geom"].geometry(0)
        from geomesa_tpu import geometry as geo

        assert isinstance(g, geo.Polygon)
        assert len(g.holes) == 1
        assert g.bounds() == (0.0, 0.0, 4.0, 4.0)
        # hole is really a hole: its center is excluded
        assert not bool(geo.points_in_polygon(np.r_[1.5], np.r_[1.5], g)[0])
        assert bool(geo.points_in_polygon(np.r_[3.5], np.r_[3.5], g)[0])

    def test_two_shell_multipolygon(self):
        cw2 = CW + 10.0
        fc = read_shapefile(_shp([_polygon([CW, cw2])]))
        from geomesa_tpu import geometry as geo

        g = fc.columns["geom"].geometry(0)
        assert isinstance(g, geo.MultiPolygon) and len(g.parts) == 2

    def test_polyline(self):
        line = np.array([[0, 0], [5, 5], [10, 0]], float)
        fc = read_shapefile(_shp([_polyline([line])]))
        from geomesa_tpu import geometry as geo

        assert isinstance(fc.columns["geom"].geometry(0), geo.LineString)

    def test_null_shape_skipped(self):
        shp = _shp([struct.pack("<i", 0), _point(1, 2)])
        fc = read_shapefile(shp)
        assert len(fc) == 1 and fc.ids.tolist() == ["1"]

    def test_store_ingest(self, tmp_path):
        shp_path = tmp_path / "data.shp"
        dbf_path = tmp_path / "data.dbf"
        n = 50
        rng = np.random.default_rng(0)
        shp_path.write_bytes(
            _shp([_point(float(x), float(y))
                  for x, y in zip(rng.uniform(-60, 60, n), rng.uniform(-40, 40, n))])
        )
        dbf_path.write_bytes(
            _dbf([("name", "C", 6, 0)], [[f"s{i}"] for i in range(n)])
        )
        fc = read_shapefile(str(shp_path))  # sibling .dbf auto-discovered
        assert fc.columns["name"].tolist()[:2] == ["s0", "s1"]
        ds = DataStore()
        ds.create_schema(fc.sft)
        ds.write("shp", fc)
        assert ds.count("shp") == n

    def test_rejects_garbage(self):
        with pytest.raises(ValueError):
            read_shapefile(b"not a shapefile at all....." * 10)
