"""MetricsRegistry: thread-safety under concurrent callers and the
Prometheus exposition format (counters, gauges, timer
_seconds_count/_sum/_max)."""

import threading

import pytest

from geomesa_tpu.metrics import MetricsRegistry, global_registry, resolve


def test_concurrent_counters_lose_no_increments():
    reg = MetricsRegistry()
    n_threads, per = 8, 10_000

    def worker():
        for _ in range(per):
            reg.counter("hits")
            reg.counter("weighted", 3)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counters["hits"] == n_threads * per
    assert reg.counters["weighted"] == 3 * n_threads * per


def test_snapshot_and_render_under_concurrent_updates():
    """snapshot()/render_prometheus() iterate while writers insert NEW
    names (dict resize): must never raise and the final state is exact."""
    reg = MetricsRegistry()
    stop = threading.Event()
    errors = []

    def writer(k):
        i = 0
        while not stop.is_set():
            reg.counter(f"c.{k}.{i % 50}")
            reg.gauge(f"g.{k}.{i % 50}", i)
            reg.timer_update(f"t.{k}.{i % 50}", 0.001)
            i += 1

    def reader():
        while not stop.is_set():
            try:
                reg.snapshot()
                reg.render_prometheus()
            except BaseException as e:  # pragma: no cover - the failure mode
                errors.append(e)
                return

    threads = [threading.Thread(target=writer, args=(k,)) for k in range(4)]
    threads += [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    import time

    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join()
    assert errors == []
    snap = reg.snapshot()
    assert len(snap["counters"]) == 4 * 50
    assert all(t["count"] > 0 for t in snap["timers"].values())


def test_timer_context_manager_records():
    reg = MetricsRegistry()
    with reg.time("op"):
        pass
    with reg.time("op"):
        pass
    t = reg.timers["op"]
    assert t.count == 2
    assert t.max_s >= t.mean_s > 0


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    reg.counter("geomesa.query.count", 3)
    reg.gauge("geomesa.cache.bytes", 1024.0)
    reg.timer_update("geomesa.query.scan", 0.25)
    reg.timer_update("geomesa.query.scan", 0.75)
    text = reg.render_prometheus()
    lines = text.splitlines()
    assert text.endswith("\n")
    assert "# TYPE geomesa_query_count counter" in lines
    assert "geomesa_query_count 3" in lines
    assert "# TYPE geomesa_cache_bytes gauge" in lines
    assert "geomesa_cache_bytes 1024.0" in lines
    # timers: count + sum under the summary family; the max is its OWN
    # gauge family (strict OpenMetrics parsers allow only _sum/_count/
    # quantile samples inside a summary)
    i = lines.index("# TYPE geomesa_query_scan_seconds summary")
    assert lines[i + 1] == "geomesa_query_scan_seconds_count 2"
    assert lines[i + 2] == "geomesa_query_scan_seconds_sum 1.0"
    assert lines[i + 3] == "# TYPE geomesa_query_scan_seconds_max gauge"
    assert lines[i + 4] == "geomesa_query_scan_seconds_max 0.75"
    # p-worst latency is scrapeable for EVERY timer
    assert sum(l == "geomesa_query_scan_seconds_max 0.75" for l in lines) == 1


def test_snapshot_reports_max():
    reg = MetricsRegistry()
    reg.timer_update("t", 0.1)
    reg.timer_update("t", 0.9)
    snap = reg.snapshot()["timers"]["t"]
    assert snap == {"count": 2, "mean_s": pytest.approx(0.5), "max_s": 0.9}


def test_resolve_falls_back_to_global():
    assert resolve(None) is global_registry()
    reg = MetricsRegistry()
    assert resolve(reg) is reg
