"""MetricsRegistry: thread-safety under concurrent callers and the
Prometheus exposition format (counters, gauges, timer
_seconds_count/_sum/_max)."""

import threading

import pytest

from geomesa_tpu.metrics import MetricsRegistry, global_registry, resolve


def test_concurrent_counters_lose_no_increments():
    reg = MetricsRegistry()
    n_threads, per = 8, 10_000

    def worker():
        for _ in range(per):
            reg.counter("hits")
            reg.counter("weighted", 3)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.counters["hits"] == n_threads * per
    assert reg.counters["weighted"] == 3 * n_threads * per


def test_snapshot_and_render_under_concurrent_updates():
    """snapshot()/render_prometheus() iterate while writers insert NEW
    names (dict resize): must never raise and the final state is exact."""
    reg = MetricsRegistry()
    stop = threading.Event()
    errors = []

    progress = [0, 0, 0, 0]

    def writer(k):
        i = 0
        while not stop.is_set():
            reg.counter(f"c.{k}.{i % 50}")
            reg.gauge(f"g.{k}.{i % 50}", i)
            reg.timer_update(f"t.{k}.{i % 50}", 0.001)
            i += 1
            progress[k] = i

    def reader():
        while not stop.is_set():
            try:
                reg.snapshot()
                reg.render_prometheus()
            except BaseException as e:  # pragma: no cover - the failure mode
                errors.append(e)
                return

    threads = [threading.Thread(target=writer, args=(k,)) for k in range(4)]
    threads += [threading.Thread(target=reader) for _ in range(2)]
    for t in threads:
        t.start()
    import time

    # progress-based stop (not a fixed wall time): every writer must have
    # cycled all 50 names, or a loaded/2-core host starves one and the
    # exact-count assertion below flakes
    deadline = time.monotonic() + 10.0
    while min(progress) < 50 and time.monotonic() < deadline:
        time.sleep(0.02)
    stop.set()
    for t in threads:
        t.join()
    assert errors == []
    snap = reg.snapshot()
    assert len(snap["counters"]) == 4 * 50
    assert all(t["count"] > 0 for t in snap["timers"].values())


def test_timer_context_manager_records():
    reg = MetricsRegistry()
    with reg.time("op"):
        pass
    with reg.time("op"):
        pass
    t = reg.timers["op"]
    assert t.count == 2
    assert t.max_s >= t.mean_s > 0


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    reg.counter("geomesa.query.count", 3)
    reg.gauge("geomesa.cache.bytes", 1024.0)
    reg.timer_update("geomesa.query.plan", 0.25)
    reg.timer_update("geomesa.query.plan", 0.75)
    text = reg.render_prometheus()
    lines = text.splitlines()
    assert text.endswith("\n")
    assert "# TYPE geomesa_query_count counter" in lines
    assert "geomesa_query_count 3" in lines
    assert "# TYPE geomesa_cache_bytes gauge" in lines
    assert "geomesa_cache_bytes 1024.0" in lines
    # timers: count + sum under the summary family; the max is its OWN
    # gauge family (strict OpenMetrics parsers allow only _sum/_count/
    # quantile samples inside a summary)
    i = lines.index("# TYPE geomesa_query_plan_seconds summary")
    assert lines[i + 1] == "geomesa_query_plan_seconds_count 2"
    assert lines[i + 2] == "geomesa_query_plan_seconds_sum 1.0"
    assert lines[i + 3] == "# TYPE geomesa_query_plan_seconds_max gauge"
    assert lines[i + 4] == "geomesa_query_plan_seconds_max 0.75"
    # p-worst latency is scrapeable for EVERY timer
    assert sum(l == "geomesa_query_plan_seconds_max 0.75" for l in lines) == 1


def test_snapshot_reports_max():
    reg = MetricsRegistry()
    reg.timer_update("t", 0.1)
    reg.timer_update("t", 0.9)
    snap = reg.snapshot()["timers"]["t"]
    assert snap == {"count": 2, "mean_s": pytest.approx(0.5), "max_s": 0.9}


def test_resolve_falls_back_to_global():
    assert resolve(None) is global_registry()
    reg = MetricsRegistry()
    assert resolve(reg) is reg


# -- the histogram instrument (docs/observability.md) ---------------------


def test_histogram_quantile_vs_numpy_oracle():
    """Windowless quantiles from the fixed-log buckets agree with
    numpy's exact percentile within one bucket width (sqrt-2 growth:
    the upper edge is at most ~41.5% above the lower)."""
    import numpy as np

    rng = np.random.default_rng(7)
    reg = MetricsRegistry()
    for dist in (
        rng.lognormal(-6, 1.2, 5000),       # cache-probe-ish µs..ms
        rng.uniform(0.001, 0.5, 5000),      # scan-ish ms
        rng.exponential(0.05, 5000) + 1e-4,  # tail-heavy
    ):
        name = "geomesa.query.scan"
        reg = MetricsRegistry()
        for v in dist:
            reg.observe(name, float(v))
        for q in (0.5, 0.9, 0.99):
            got = reg.histogram_quantile(name, q)
            exact = float(np.percentile(dist, q * 100))
            # one log bucket: the estimate lies within a sqrt(2) factor
            assert exact / 2**0.5 <= got <= exact * 2**0.5, (q, got, exact)


def test_histogram_snapshot_and_unknown_name():
    reg = MetricsRegistry()
    assert reg.histogram_quantile("geomesa.query.scan", 0.99) == 0.0
    reg.observe("geomesa.query.scan", 0.010)
    reg.observe("geomesa.query.scan", 0.030)
    snap = reg.snapshot()["histograms"]["geomesa.query.scan"]
    assert snap["count"] == 2
    assert snap["mean_s"] == pytest.approx(0.02)
    assert 0.005 <= snap["p50_s"] <= 0.02
    assert 0.02 <= snap["p99_s"] <= 0.05


def _parse_openmetrics(text: str) -> dict:
    """A deliberately strict mini-parser for the exposition subset this
    registry emits: returns {family: (type, [(name, labels, value)])}
    and asserts the line grammar as it goes."""
    import re

    families: dict = {}
    current = None
    line_re = re.compile(
        r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
        r"(?:\{(?P<labels>[^}]*)\})? (?P<value>\S+)$"
    )
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, fam, kind = line.split(" ")
            assert fam not in families, f"duplicate TYPE for {fam}"
            families[fam] = (kind, [])
            current = fam
            continue
        m = line_re.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, labels, value = m.group("name"), m.group("labels"), m.group("value")
        float(value)  # must parse
        if labels:
            for pair in labels.split(","):
                assert re.fullmatch(r'[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"', pair), pair
        # a sample must belong to the most recent TYPE'd family
        assert current is not None and name.startswith(current), line
        families[current][1].append((name, labels, float(value)))
    return families


def test_histogram_prometheus_exposition_is_spec_correct():
    """The satellite-1 contract: histograms render cumulative
    ``_bucket{le=…}`` samples ending in ``+Inf`` == ``_count``, plus
    ``_sum``/``_count``; timers keep their summary + ``_seconds_max``
    gauge family untouched — all under a grammar-checked exposition."""
    reg = MetricsRegistry()
    for v in (0.0005, 0.003, 0.003, 0.25, 40.0, 1e9):  # incl. overflow
        reg.observe("geomesa.query.scan", v)
    reg.timer_update("geomesa.query.plan", 0.5)
    reg.counter("geomesa.query.count", 2)
    text = reg.render_prometheus()
    fams = _parse_openmetrics(text)

    kind, samples = fams["geomesa_query_scan_seconds"]
    assert kind == "histogram"
    buckets = [s for s in samples if s[0].endswith("_bucket")]
    # le labels: floats in strictly increasing order, then +Inf last
    les = [s[1] for s in buckets]
    assert all(l.startswith('le="') for l in les)
    edges = [l[4:-1] for l in les]
    assert edges[-1] == "+Inf"
    finite = [float(e) for e in edges[:-1]]
    assert finite == sorted(finite)
    # cumulative counts: non-decreasing, +Inf equals _count
    values = [s[2] for s in buckets]
    assert values == sorted(values)
    count = next(s[2] for s in samples if s[0].endswith("_count"))
    assert values[-1] == count == 6
    # the 1e9 observation lives only in the overflow bucket
    assert values[-1] > values[-2]
    sum_s = next(s[2] for s in samples if s[0].endswith("_sum"))
    assert sum_s == pytest.approx(0.0005 + 0.003 + 0.003 + 0.25 + 40.0 + 1e9)

    # timers unchanged: summary family + its own _max gauge family
    kind, _ = fams["geomesa_query_plan_seconds"]
    assert kind == "summary"
    kind, maxes = fams["geomesa_query_plan_seconds_max"]
    assert kind == "gauge" and maxes[0][2] == 0.5


def test_observer_hook_fires_outside_the_lock():
    """The SLO seam: observe() calls the attached observer AFTER the
    registry lock is released (re-entering observe from the hook must
    not deadlock), with the exact name/value."""
    reg = MetricsRegistry()
    seen = []

    def hook(name, seconds):
        seen.append((name, seconds))
        if len(seen) == 1:
            # re-entrancy: a hook that itself records must not deadlock
            reg.observe("geomesa.query.scan", 0.001)

    reg.observer = hook
    reg.observe("geomesa.serving.queue_wait", 0.25)
    assert seen == [
        ("geomesa.serving.queue_wait", 0.25),
        ("geomesa.query.scan", 0.001),
    ]


def test_ingest_metrics_family_renders():
    """The geomesa.ingest.* family (docs/ingest.md): counters, per-stage
    timers, and the peak-chunk-bytes gauge all render through the
    registry and the Prometheus exposition."""
    reg = MetricsRegistry()
    for c in ("geomesa.ingest.rows", "geomesa.ingest.chunks",
              "geomesa.ingest.errors", "geomesa.ingest.queue_full"):
        reg.counter(c, 2)
    for t in ("parse", "keys", "sort", "commit", "finalize"):
        reg.timer_update(f"geomesa.ingest.{t}", 0.01)
    reg.gauge("geomesa.ingest.chunk_bytes_peak", 12345.0)
    text = reg.render_prometheus()
    assert "geomesa_ingest_rows 2" in text
    assert "geomesa_ingest_queue_full 2" in text
    assert "geomesa_ingest_chunk_bytes_peak 12345.0" in text
    for t in ("parse", "keys", "sort", "commit", "finalize"):
        assert f"geomesa_ingest_{t}_seconds_count 1" in text
        assert f"geomesa_ingest_{t}_seconds_max" in text


def test_ingest_pipeline_records_real_metrics():
    """An actual pipelined bulk load populates the family: rows/chunks
    counters, stage timers, and the chunk-bytes gauge."""
    import numpy as np

    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.ingest import BulkLoader, PipelineConfig
    from geomesa_tpu.sft import FeatureType

    reg = MetricsRegistry()
    sft = FeatureType.from_spec("m", "dtg:Date,*geom:Point:srid=4326")
    ds = DataStore(metrics=reg)
    ds.create_schema(sft)
    rng = np.random.default_rng(0)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    loader = BulkLoader(ds, "m", config=PipelineConfig(workers=2))
    for j in range(3):
        n = 500
        loader.put(FeatureCollection.from_columns(
            sft, [f"c{j}_{i}" for i in range(n)],
            {"dtg": t0 + rng.integers(0, 10 * 86_400_000, n),
             "geom": (rng.uniform(-50, 50, n), rng.uniform(-50, 50, n))},
        ))
    res = loader.close()
    assert res.written == 1500
    snap = reg.snapshot()
    assert snap["counters"]["geomesa.ingest.rows"] == 1500
    assert snap["counters"]["geomesa.ingest.chunks"] == 3
    assert snap["gauges"]["geomesa.ingest.chunk_bytes_peak"] > 0
    for stage in ("keys", "sort", "finalize"):
        t = snap["timers"][f"geomesa.ingest.{stage}"]
        assert t["count"] >= 1 and t["mean_s"] >= 0
