"""Test harness: force JAX onto a virtual 8-device CPU mesh.

Mirrors the reference's TestGeoMesaDataStore strategy (SURVEY.md section 4):
the full stack runs against an in-memory backend with zero infra — here,
JAX CPU with a forced 8-device host platform so multi-device sharding
tests run without a TPU pod.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# a platform plugin (e.g. the axon TPU tunnel) may override JAX_PLATFORMS at
# import time; the config update wins as long as no backend is initialized yet
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    # tier-1 runs with `-m 'not slow'` (ROADMAP): long randomized suites
    # (crash matrices, fuzzers) carry the slow marker
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 run"
    )
