"""Pipelined multi-query execution: query_many must equal sequential
query() exactly, across plan kinds and on the mesh."""

import numpy as np
import pytest

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.parallel import make_mesh
from geomesa_tpu.sft import FeatureType

DAY = 86400_000


@pytest.fixture(scope="module", params=[None, 4], ids=["single", "mesh4"])
def store(request):
    mesh = None if request.param is None else make_mesh(request.param)
    sft = FeatureType.from_spec(
        "ev", "kind:String:index=true,dtg:Date,*geom:Point:srid=4326"
    )
    ds = DataStore(tile=64, mesh=mesh)
    ds.create_schema(sft)
    rng = np.random.default_rng(5)
    n = 6000
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    fc = FeatureCollection.from_columns(
        sft,
        [str(i) for i in range(n)],
        {
            "kind": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
            "dtg": t0 + rng.integers(0, 20 * DAY, n),
            "geom": (rng.uniform(-60, 60, n), rng.uniform(-45, 45, n)),
        },
    )
    ds.write("ev", fc)
    return ds


QUERIES = [
    "bbox(geom, -10, -10, 10, 10)",
    "bbox(geom, 5, 5, 40, 30) AND dtg DURING 2024-01-03T00:00:00Z/2024-01-09T00:00:00Z",
    "kind = 'b'",                            # attribute index
    "bbox(geom, -5, -5, 5, 5) OR kind = 'c'",  # union plan
    "IN ('17', '99', 'nope')",               # id lookup
    "bbox(geom, 170, 80, 175, 85)",          # empty result
    "INCLUDE",
]


def test_query_many_equals_sequential(store):
    ds = store
    seq = [ds.query("ev", q) for q in QUERIES]
    batched = ds.query_many("ev", QUERIES)
    assert len(batched) == len(seq)
    for a, b in zip(seq, batched):
        np.testing.assert_array_equal(
            np.sort(np.asarray(a.ids)), np.sort(np.asarray(b.ids))
        )
    assert sum(len(a) for a in seq) > 0


def test_query_many_with_limit(store):
    ds = store
    outs = ds.query_many("ev", ["INCLUDE", "bbox(geom, -10, -10, 10, 10)"], limit=7)
    assert all(len(o) <= 7 for o in outs)
    assert len(outs[0]) == 7


def test_query_many_respects_delta_tier(store):
    ds = store
    # append un-compacted rows: scan must see them through the delta tier
    before = len(ds.query("ev", "bbox(geom, -180, -90, 180, 90)"))
    sft = ds.get_schema("ev")
    t0 = np.datetime64("2024-01-21T00:00:00", "ms").astype(np.int64)
    add = FeatureCollection.from_columns(
        sft, [f"x{i}" for i in range(50)],
        {
            "kind": np.array(["a"] * 50),
            "dtg": np.full(50, t0),
            "geom": (np.full(50, 1.0), np.full(50, 1.0)),
        },
    )
    ds.write("ev", add)
    outs = ds.query_many("ev", ["bbox(geom, 0, 0, 2, 2)", "kind = 'a'"])
    assert sum(np.char.startswith(np.asarray(outs[0].ids, dtype=str), "x")) == 50
    after = len(ds.query_many("ev", ["bbox(geom, -180, -90, 180, 90)"])[0])
    assert after == before + 50  # no rows lost or double-counted


def test_scheduler_threaded_equals_sequential(store):
    """The serving tier's core contract (docs/serving.md): M threads
    submitting the QUERIES matrix through the micro-batch scheduler get
    results identical to sequential query() — per plan kind (simple
    scans, attribute index, union, id lookup, empty, full scan), on
    single-device and mesh4 stores."""
    from concurrent.futures import ThreadPoolExecutor

    ds = store
    seq = [ds.query("ev", q) for q in QUERIES]
    sched = ds.serve()
    try:
        def worker(_):
            futs = [sched.submit("ev", q) for q in QUERIES]
            return [f.result(120) for f in futs]

        with ThreadPoolExecutor(4) as ex:
            all_outs = list(ex.map(worker, range(4)))
    finally:
        sched.close()
    for outs in all_outs:
        assert len(outs) == len(seq)
        for a, b in zip(seq, outs):
            np.testing.assert_array_equal(
                np.sort(np.asarray(a.ids)), np.sort(np.asarray(b.ids))
            )
    assert sum(len(a) for a in seq) > 0


def test_warmup_compiles_all_variants():
    """After DataStore.warmup, a fresh mixed query batch triggers NO new
    XLA compiles. A UNIQUE block size (tile) gives this store distinct
    kernel shapes, so earlier tests' process-wide jit cache cannot mask a
    warmup no-op."""
    import logging

    import jax

    sft = FeatureType.from_spec(
        "ev", "kind:String:index=true,dtg:Date,*geom:Point:srid=4326"
    )
    ds = DataStore(tile=8192)  # SUB=64: shapes unique to this test
    ds.create_schema(sft)
    rng = np.random.default_rng(5)
    n = 60_000
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    ds.write("ev", FeatureCollection.from_columns(
        sft, [str(i) for i in range(n)],
        {
            "kind": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
            "dtg": t0 + rng.integers(0, 20 * DAY, n),
            "geom": (rng.uniform(-60, 60, n), rng.uniform(-45, 45, n)),
        },
    ))
    n_calls = ds.warmup("ev")
    assert n_calls > 0
    jax.config.update("jax_log_compiles", True)
    records: list = []
    handler = logging.Handler()
    handler.emit = lambda r: records.append(r.getMessage())
    loggers = [
        logging.getLogger(n)
        for n in ("jax._src.dispatch", "jax._src.interpreters.pxla", "jax._src.compiler")
    ]
    for lg in loggers:
        lg.addHandler(handler)
        lg.setLevel(logging.DEBUG)
    try:
        # spatial, spatio-temporal, attribute-only (False/False flags)
        for q in QUERIES[:3] + ["bbox(geom, 3, 3, 9, 9)"]:
            ds.query("ev", q)
        # the fused batch path: canonical chunk shape must already be
        # compiled (warmup's _submit_fused_chunk pass)
        ds.query_many("ev", QUERIES[:2] + [
            "bbox(geom, 3, 3, 9, 9)", "bbox(geom, -20, -20, -5, -5)",
            "bbox(geom, 10, -30, 30, -10) AND dtg DURING "
            "2024-01-02T00:00:00Z/2024-01-08T00:00:00Z",
        ])
    finally:
        jax.config.update("jax_log_compiles", False)
        for lg in loggers:
            lg.removeHandler(handler)
    compiles = [m for m in records if "Compiling" in m and "block_scan" in m]
    assert compiles == [], compiles
