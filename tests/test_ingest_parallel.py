"""Parallel converter ingest (MapReduce-ingest analogue): line-boundary
splits, process-pool conversion, single-writer commit."""

import numpy as np
import pytest

from geomesa_tpu import DataStore, FeatureType
from geomesa_tpu.io.converters import Converter, FieldSpec
from geomesa_tpu.io.ingest import ingest_files, plan_splits

SPEC = "name:String,val:Double,dtg:Date,*geom:Point:srid=4326"


def _write_csv(path, n, seed, header=True):
    rng = np.random.default_rng(seed)
    with open(path, "w") as fh:
        if header:
            fh.write("name,val,lon,lat,when\n")
        for i in range(n):
            fh.write(
                f"r{seed}_{i},{rng.uniform():.4f},{rng.uniform(-60, 60):.4f},"
                f"{rng.uniform(-45, 45):.4f},2024-02-0{1 + i % 9}T00:00:00Z\n"
            )
    return str(path)


def _converter():
    sft = FeatureType.from_spec("ing", SPEC)
    return Converter(
        sft=sft,
        fmt="delimited",
        skip_lines=1,
        id_field="$1",
        fields=[
            FieldSpec("name", "$1"),
            FieldSpec("val", "$2::double"),
            FieldSpec("geom", "point($3, $4)"),
            FieldSpec("dtg", "datetime($5)"),
        ],
    )


class TestSplits:
    def test_line_boundary_splits(self, tmp_path):
        p = _write_csv(tmp_path / "big.csv", 5000, 1)
        splits = plan_splits([p], "delimited", split_bytes=64 << 10)
        assert len(splits) > 3
        assert splits[0].skip_header and not splits[1].skip_header
        # splits tile the file exactly
        assert splits[0].start == 0
        for a, b in zip(splits, splits[1:]):
            assert a.end == b.start
        import os

        assert splits[-1].end == os.path.getsize(p)
        # every split starts at a line boundary
        with open(p, "rb") as fh:
            for s in splits[1:]:
                fh.seek(s.start - 1)
                assert fh.read(1) == b"\n"

    def test_non_delimited_never_splits(self, tmp_path):
        p = tmp_path / "doc.json"
        p.write_text("[]" * 100000)
        assert len(plan_splits([str(p)], "json", split_bytes=1024)) == 1


class TestParallelIngest:
    def _expected(self, paths):
        total = 0
        for p in paths:
            with open(p) as fh:
                total += sum(1 for _ in fh) - 1
        return total

    def test_multi_file_pool(self, tmp_path):
        paths = [_write_csv(tmp_path / f"f{i}.csv", 800, i) for i in range(4)]
        conv = _converter()
        ds = DataStore()
        ds.create_schema(conv.sft)
        res = ingest_files(ds, conv, paths, workers=2)
        assert res.written == self._expected(paths) == ds.count("ing")
        assert res.errors == 0

    def test_single_big_file_splits_match_serial(self, tmp_path):
        from geomesa_tpu.io import ingest as ing

        p = _write_csv(tmp_path / "big.csv", 4000, 9)
        old = ing.SPLIT_BYTES
        ing.SPLIT_BYTES = 32 << 10  # force many splits
        try:
            conv = _converter()
            ds = DataStore()
            ds.create_schema(conv.sft)
            res = ingest_files(ds, conv, [p], workers=2)
        finally:
            ing.SPLIT_BYTES = old
        assert res.splits > 1
        assert res.written == 4000 == ds.count("ing")
        # same rows as a serial single-split ingest
        serial = DataStore()
        serial.create_schema(_converter().sft)
        ingest_files(serial, _converter(), [p], workers=0)
        assert sorted(ds.features("ing").ids.tolist()) == sorted(
            serial.features("ing").ids.tolist()
        )

    def test_bad_rows_counted(self, tmp_path):
        p = tmp_path / "bad.csv"
        p.write_text(
            "name,val,lon,lat,when\n"
            "a,1.0,10,10,2024-02-01T00:00:00Z\n"
            "b,not-a-number,10,10,2024-02-01T00:00:00Z\n"
            "c,2.0,20,20,2024-02-02T00:00:00Z\n"
        )
        conv = _converter()
        ds = DataStore()
        ds.create_schema(conv.sft)
        res = ingest_files(ds, conv, [str(p)], workers=0)
        assert res.written == 2 and res.errors == 1

    def test_running_index_ids_namespaced(self, tmp_path):
        from geomesa_tpu.io import ingest as ing

        p = _write_csv(tmp_path / "noid.csv", 2000, 3)
        conv = _converter()
        conv.id_field = None
        conv.__post_init__()
        old = ing.SPLIT_BYTES
        ing.SPLIT_BYTES = 32 << 10
        try:
            ds = DataStore()
            ds.create_schema(conv.sft)
            res = ingest_files(ds, conv, [p], workers=2)
        finally:
            ing.SPLIT_BYTES = old
        assert res.written == 2000 == ds.count("ing")  # no id collisions


class TestSplitErrorAggregation:
    """The multiprocessing split path aggregates per-split errors
    deterministically (ordered by SPLIT, not worker completion) and
    surfaces worker tracebacks instead of swallowing them."""

    def _files_with_known_errors(self, tmp_path):
        """Three files with 0 / 1 / 2 bad rows respectively (ids unique
        across files)."""
        paths = []
        for i, n_bad in enumerate((0, 1, 2)):
            rows = [
                f"g{i}_{j},1.0,10,10,2024-02-01T00:00:00Z\n" for j in range(20)
            ] + [
                f"b{i}_{j},NOT_A_NUMBER,10,10,2024-02-01T00:00:00Z\n"
                for j in range(n_bad)
            ]
            p = tmp_path / f"f{i}.csv"
            p.write_text("name,val,lon,lat,when\n" + "".join(rows))
            paths.append(str(p))
        return paths

    def test_split_errors_ordered_by_split(self, tmp_path):
        paths = self._files_with_known_errors(tmp_path)
        conv = _converter()
        ds = DataStore()
        ds.create_schema(conv.sft)
        res = ingest_files(ds, conv, paths, workers=3)
        assert res.split_errors == [0, 1, 2]  # split order, always
        assert res.errors == 3
        assert res.written == 60 == ds.count("ing")

    def test_split_errors_ordered_pipelined(self, tmp_path):
        from geomesa_tpu.ingest import ingest_files as pipelined_ingest

        paths = self._files_with_known_errors(tmp_path)
        conv = _converter()
        ds = DataStore()
        ds.create_schema(conv.sft)
        res = pipelined_ingest(ds, conv, paths, workers=3)
        assert res.split_errors == [0, 1, 2]
        assert res.errors == 3
        assert res.written == 60 == ds.count("ing")
        assert res.stage_seconds["keys"] > 0  # stage attribution exists

    def test_worker_traceback_surfaced(self, tmp_path):
        """A worker whose converter RAISES (drop_errors=False on a bad
        record) surfaces IngestError carrying the worker's formatted
        traceback and the failing split's index — not a bare exception
        with the forked stack lost."""
        from geomesa_tpu.ingest import IngestError

        p1 = _write_csv(tmp_path / "ok.csv", 50, 1)
        p2 = tmp_path / "bad.csv"
        p2.write_text(
            "name,val,lon,lat,when\n"
            "z1,NOT_A_NUMBER,10,10,2024-02-01T00:00:00Z\n"
        )
        conv = _converter()
        conv.drop_errors = False
        ds = DataStore()
        ds.create_schema(conv.sft)
        with pytest.raises(IngestError) as ei:
            ingest_files(ds, conv, [p1, str(p2)], workers=2)
        assert ei.value.split_index == 1
        assert ei.value.worker_traceback  # the worker-side stack
        assert "Traceback" in ei.value.worker_traceback

    def test_pipelined_matches_classic_rows(self, tmp_path):
        """Both drivers over the same multi-split file ingest the same
        row set."""
        from geomesa_tpu.ingest import ingest_files as pipelined_ingest

        p = _write_csv(tmp_path / "big.csv", 3000, 8)
        ds1 = DataStore()
        ds1.create_schema(_converter().sft)
        r1 = ingest_files(ds1, _converter(), [p], workers=2)
        ds2 = DataStore()
        ds2.create_schema(_converter().sft)
        r2 = pipelined_ingest(
            ds2, _converter(), [p], workers=2, split_bytes=16 << 10
        )
        assert r1.written == r2.written == 3000
        assert r2.splits > 1
        assert sorted(ds1.features("ing").ids.tolist()) == sorted(
            ds2.features("ing").ids.tolist()
        )
