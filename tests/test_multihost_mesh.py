"""Multi-host mesh layout (VERDICT r4 weak #7: make_multihost_mesh was
host-major by construction but never executed with multiple process
indices). Synthetic-device unit tests pin the layout math; the query
path over a (hosts x devices_per_host) virtual mesh pins execution."""

import os

import numpy as np
import pytest

from geomesa_tpu.parallel.mesh import _host_major, make_multihost_mesh


class _Dev:
    """Stand-in device carrying a process_index (multi-process slices)."""

    def __init__(self, pid, local):
        self.process_index = pid
        self.id = pid * 100 + local

    def __repr__(self):
        return f"d{self.process_index}.{self.id % 100}"


class TestHostMajorLayout:
    def test_orders_by_process_then_local(self):
        # device list arrives interleaved (as a pod runtime may surface it)
        devs = [
            _Dev(1, 0), _Dev(0, 0), _Dev(1, 1), _Dev(0, 1),
            _Dev(1, 2), _Dev(0, 2), _Dev(1, 3), _Dev(0, 3),
        ]
        out = _host_major(devs, hosts=2, devices_per_host=4)
        assert [d.process_index for d in out] == [0, 0, 0, 0, 1, 1, 1, 1]
        # each host's run keeps ITS devices contiguous: the collective
        # schedule's intra-run phase stays on ICI, crossing DCN per host
        assert [d.id for d in out[:4]] == [0, 1, 2, 3]
        assert [d.id for d in out[4:]] == [100, 101, 102, 103]

    def test_partial_hosts_and_devices(self):
        devs = [_Dev(h, i) for h in range(4) for i in range(4)]
        out = _host_major(devs, hosts=2, devices_per_host=2)
        assert [d.process_index for d in out] == [0, 0, 1, 1]

    def test_undersized_host_rejected(self):
        devs = [_Dev(0, 0), _Dev(0, 1), _Dev(1, 0)]
        with pytest.raises(ValueError, match="host 1 has 1"):
            _host_major(devs, hosts=2, devices_per_host=2)


class TestMultihostQueryPath:
    def test_query_over_multihost_mesh(self):
        """The full store path over a 2x4 multihost-shaped mesh equals
        the single-device result (single process: synthetic host groups
        preserve the layout; the shard_map collectives run for real)."""
        from geomesa_tpu.datastore import DataStore
        from geomesa_tpu.features import FeatureCollection
        from geomesa_tpu.sft import FeatureType

        mesh = make_multihost_mesh(hosts=2, devices_per_host=4)
        assert mesh.devices.shape == (8,)
        rng = np.random.default_rng(3)
        n = 4000
        sft = FeatureType.from_spec("mh", "dtg:Date,*geom:Point:srid=4326")
        t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
        cols = {
            "dtg": t0 + rng.integers(0, 20 * 86400_000, n),
            "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)),
        }
        q = ("bbox(geom, -60, -30, 60, 30) AND dtg DURING "
             "2024-01-03T00:00:00Z/2024-01-15T00:00:00Z")
        results = []
        for m in (None, mesh):
            ds = DataStore(mesh=m)
            ds.create_schema(FeatureType.from_spec(sft.name, sft.to_spec()))
            ds.write("mh", FeatureCollection.from_columns(
                ds.get_schema("mh"), [str(i) for i in range(n)], dict(cols)))
            results.append({
                "rows": sorted(ds.query("mh", q).ids.tolist()),
                "count": ds.count("mh", q),
                "density": ds.density("mh", q, envelope=(-60, -30, 60, 30),
                                      width=16, height=8),
            })
        a, b = results
        assert a["rows"] == b["rows"] and len(a["rows"]) > 0
        assert a["count"] == b["count"]
        np.testing.assert_array_equal(a["density"], b["density"])


def test_two_process_probe():
    """The DCN-analogue path EXECUTED: two real processes, each with 4
    virtual CPU devices via jax.distributed, one host-major multihost
    mesh, one shard_map psum crossing the process boundary (VERDICT r4
    weak #7 — previously constructed but never run). Delegates to
    scripts/probe_multiprocess.py, which isolates the workers from the
    TPU tunnel plugin's sitecustomize hook (see its docstring)."""
    import subprocess
    import sys

    script = os.path.join(os.path.dirname(__file__), "..", "scripts",
                          "probe_multiprocess.py")
    out = subprocess.run(
        [sys.executable, os.path.abspath(script)],
        capture_output=True, text=True, timeout=240, start_new_session=True,
    )
    if out.returncode == 3:
        # the probe's distinct "unsupported here" code: this jax build's
        # CPU client has no cross-process collective transport
        pytest.skip("jax CPU backend lacks multiprocess computations")
    assert out.returncode == 0, (out.stdout[-800:], out.stderr[-800:])
    assert "cross-process psum" in out.stdout
