"""PodIndexTable (docs/distributed.md): per-host shard ownership must be
INVISIBLE to every read surface.

The pinned contract (ISSUE 20): a DataStore over a host group returns
results **bit-identical** to the same DataStore over the flat
single-process mesh on the same devices — same row sets, same ids, same
counts, same density grids, same bounds, same explain-visible plan — for
the full z2/z3/xz matrix of box and polygon configs, for the per-query
path AND the cross-host fused dispatch (query_many), on every available
driver. The sim driver runs everywhere (CPU CI); the distributed driver
skips via :class:`PodUnsupported` where the backend has no multi-process
collectives.
"""

import numpy as np
import pytest

from geomesa_tpu import fault
from geomesa_tpu import geometry as geo
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.pod import PodUnsupported, make_host_group
from geomesa_tpu.pod.table import PodIndexTable
from geomesa_tpu.sft import FeatureType

DAY = 86400_000
T0 = int(np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64))
DUR = "dtg DURING 2024-01-03T00:00:00Z/2024-01-12T00:00:00Z"
TRI = "POLYGON((-30 -20, 30 -20, 40 25, -25 20, -30 -20))"

# the z2/z3 matrix: box and polygon, timeless and time-bounded, plus the
# attribute / union / id / empty / full plan kinds riding along
Q_PTS = [
    "bbox(geom, -10, -10, 10, 10)",                      # z2 box
    f"intersects(geom, {TRI})",                          # z2 polygon
    f"bbox(geom, 5, 5, 40, 30) AND {DUR}",               # z3 box
    f"intersects(geom, {TRI}) AND {DUR}",                # z3 polygon
    "kind = 'b'",                                        # attribute index
    "bbox(geom, -5, -5, 5, 5) OR kind = 'c'",            # union plan
    "IN ('17', '99', 'nope')",                           # id lookup
    "bbox(geom, 170, 80, 175, 85)",                      # empty
    "INCLUDE",                                           # full scan
]

# the xz matrix (extent geometries): box and polygon, both epochs
Q_POLY = [
    "bbox(geom, -10, -10, 10, 10)",                      # xz2 box
    f"intersects(geom, {TRI})",                          # xz2 polygon
    f"bbox(geom, -20, -20, 30, 25) AND {DUR}",           # xz3 box
    f"intersects(geom, {TRI}) AND {DUR}",                # xz3 polygon
]


@pytest.fixture(scope="module", params=["sim", "distributed"])
def group(request):
    try:
        return make_host_group(hosts=4, devices_per_host=2, driver=request.param)
    except PodUnsupported as e:
        pytest.skip(f"pod driver {request.param!r} unavailable: {e}")


def _point_store(mesh, n=20_000, seed=7):
    sft = FeatureType.from_spec(
        "pts", "kind:String:index=true,dtg:Date,*geom:Point:srid=4326"
    )
    # tile=64: enough blocks that every host owns a real span and the
    # batch path genuinely packs fused chunks instead of routing singly
    ds = DataStore(tile=64, mesh=mesh)
    ds.create_schema(sft)
    rng = np.random.default_rng(seed)
    ds.write("pts", FeatureCollection.from_columns(
        sft, [str(i) for i in range(n)],
        {
            "kind": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
            "dtg": T0 + rng.integers(0, 20 * DAY, n),
            "geom": (rng.uniform(-60, 60, n), rng.uniform(-45, 45, n)),
        },
    ))
    return ds


def _poly_store(mesh, n=8000, seed=9):
    sft = FeatureType.from_spec("bld", "dtg:Date,*geom:Polygon:srid=4326")
    ds = DataStore(tile=64, mesh=mesh)
    ds.create_schema(sft)
    rng = np.random.default_rng(seed)
    x0 = rng.uniform(-60, 59, n)
    y0 = rng.uniform(-45, 44, n)
    polys = geo.PackedGeometryColumn.from_boxes(
        x0, y0, x0 + rng.uniform(0.01, 0.8, n), y0 + rng.uniform(0.01, 0.6, n)
    )
    ds.write("bld", FeatureCollection.from_columns(
        sft, [str(i) for i in range(n)],
        {"dtg": T0 + rng.integers(0, 20 * DAY, n), "geom": polys},
    ))
    return ds


@pytest.fixture(scope="module")
def stores(group):
    """(pod store, flat-mesh referee) pairs for the point and extent
    schemas — the equal-device-budget differential the acceptance pins."""
    return {
        "pts": (_point_store(group), _point_store(group.flat_mesh())),
        "bld": (_poly_store(group), _poly_store(group.flat_mesh())),
    }


def _ids(fc):
    return sorted(np.asarray(fc.ids, dtype=str).tolist())


class TestDifferentialMatrix:
    def test_pod_tables_built(self, stores):
        pod, _ = stores["pts"]
        tables = [t for (tn, _), t in pod._tables.items() if tn == "pts"]
        assert any(isinstance(t, PodIndexTable) for t in tables)

    @pytest.mark.parametrize("qi", range(len(Q_PTS)))
    def test_point_queries_bit_identical(self, stores, qi):
        pod, flat = stores["pts"]
        q = Q_PTS[qi]
        a, b = pod.query("pts", q), flat.query("pts", q)
        assert _ids(a) == _ids(b)
        assert pod.count("pts", q) == flat.count("pts", q) == len(b)

    @pytest.mark.parametrize("qi", range(len(Q_POLY)))
    def test_extent_queries_bit_identical(self, stores, qi):
        pod, flat = stores["bld"]
        q = Q_POLY[qi]
        assert _ids(pod.query("bld", q)) == _ids(flat.query("bld", q))
        assert pod.count("bld", q) == flat.count("bld", q)

    def test_results_nonvacuous(self, stores):
        pod, _ = stores["pts"]
        hits = [len(pod.query("pts", q)) for q in Q_PTS[:6]]
        assert all(h > 0 for h in hits), hits
        podp, _ = stores["bld"]
        assert all(len(podp.query("bld", q)) > 0 for q in Q_POLY)

    @pytest.mark.parametrize("tn,queries", [("pts", Q_PTS), ("bld", Q_POLY)])
    def test_explain_plan_shape_identical(self, stores, tn, queries):
        """The pod is a storage-layer move: the planner's explain trace
        (index choice, strategy, range counts) must be byte-identical
        to the flat mesh's."""
        pod, flat = stores[tn]
        for q in queries:
            assert pod.explain(tn, q) == flat.explain(tn, q)

    def test_density_and_bounds_identical(self, stores):
        pod, flat = stores["pts"]
        env = (-60, -45, 60, 45)
        for q in (Q_PTS[0], Q_PTS[2]):
            np.testing.assert_array_equal(
                pod.density("pts", q, envelope=env, width=32, height=16),
                flat.density("pts", q, envelope=env, width=32, height=16),
            )
            assert pod.bounds("pts", q) == flat.bounds("pts", q)


class TestFusedCrossHost:
    def test_query_many_fused_and_identical(self, stores, monkeypatch):
        """The cross-host fused dispatch: one batched leg per owning
        host per chunk (shard-level ``_fused_raw_finishes``), merged at
        the coordinator — and the batch must actually TAKE the fused
        path, not fall back to per-query routing."""
        from geomesa_tpu.parallel.dtable import DistributedIndexTable

        pod, flat = stores["pts"]
        calls = {"raw": 0}
        orig = DistributedIndexTable._fused_raw_finishes

        def spy(self, *a, **kw):
            calls["raw"] += 1
            return orig(self, *a, **kw)

        monkeypatch.setattr(DistributedIndexTable, "_fused_raw_finishes", spy)
        # >8 same-variant members per table: the packer must form real
        # fused chunks (route-single handles only tiny batches)
        rng = np.random.default_rng(21)
        boxes = []
        for _ in range(10):
            x0, y0 = rng.uniform(-55, 30), rng.uniform(-40, 20)
            boxes.append(
                f"bbox(geom, {x0:.3f}, {y0:.3f}, {x0 + 18:.3f}, {y0 + 14:.3f})"
            )
        batch = boxes + Q_PTS
        outs = pod.query_many("pts", batch)
        refs = flat.query_many("pts", batch)
        assert sum(len(o) for o in outs[:10]) > 0
        for a, b in zip(outs, refs):
            assert _ids(a) == _ids(b)
        assert calls["raw"] >= 1, "pod batch never took the fused dispatch"

    def test_extent_query_many_identical(self, stores):
        pod, flat = stores["bld"]
        for a, b in zip(pod.query_many("bld", Q_POLY),
                        flat.query_many("bld", Q_POLY)):
            assert _ids(a) == _ids(b)


class TestHeterogeneousSlotCaps:
    def test_mixed_link_profile_stays_bit_identical(self):
        """Satellite: per-host probed caps change each shard's canonical
        fused SHAPE (a slow host amortizes over a bigger bucket) but
        never the RESULTS — the differential holds with hosts on
        deliberately different ladder rungs."""
        group = make_host_group(hosts=4, devices_per_host=2, driver="sim")
        group.set_link_profile([0.4, 66.0, 8.25, None])
        pod = _point_store(group, n=8000, seed=11)
        flat = _point_store(group.flat_mesh(), n=8000, seed=11)
        caps = {s._slot_cap for s in pod.table("pts", "z2").shards}
        assert len(caps) > 1  # genuinely heterogeneous shapes
        for q in (Q_PTS[0], Q_PTS[2], Q_PTS[3]):
            assert _ids(pod.query("pts", q)) == _ids(flat.query("pts", q))
        for a, b in zip(pod.query_many("pts", Q_PTS[:4]),
                        flat.query_many("pts", Q_PTS[:4])):
            assert _ids(a) == _ids(b)


class TestPodFaultPoints:
    def test_dispatch_fault_surfaces_and_recovers(self, stores):
        """pod.dispatch / pod.join are real seams: an injected IO error
        on one host's scan leg propagates to the caller, and the next
        query — same table, same compiled kernels — is clean."""
        pod, flat = stores["pts"]
        with fault.inject("pod.dispatch", kind="io_error", times=1):
            with pytest.raises(OSError):
                pod.query("pts", Q_PTS[0])
        assert _ids(pod.query("pts", Q_PTS[0])) == _ids(flat.query("pts", Q_PTS[0]))

    def test_join_fault_surfaces_and_recovers(self, stores):
        pod, flat = stores["pts"]
        with fault.inject("pod.join", kind="io_error", times=1):
            with pytest.raises(OSError):
                pod.count("pts", Q_PTS[0])
        assert pod.count("pts", Q_PTS[0]) == flat.count("pts", Q_PTS[0])
