"""IO tier: persistence round-trips, exporters, converters, CLI."""

import json

import numpy as np
import pytest

from geomesa_tpu import cli, geometry as geo
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.io.converters import Converter, FieldSpec, compile_expression, infer_schema
from geomesa_tpu.io.exporters import export
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.storage import persist

SPEC = "name:String,age:Int,dtg:Date,*geom:Point:srid=4326"


def _store(n=200):
    sft = FeatureType.from_spec("t", SPEC)
    ds = DataStore(tile=64)
    ds.create_schema(sft)
    rng = np.random.default_rng(0)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    fc = FeatureCollection.from_columns(
        sft,
        [f"f{i}" for i in range(n)],
        {
            "name": np.array([f"n{i % 5}" for i in range(n)]),
            "age": np.arange(n) % 90,
            "dtg": t0 + rng.integers(0, 10 * 86400_000, n),
            "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)),
        },
    )
    ds.write("t", fc)
    return ds


class TestPersist:
    def test_roundtrip_points(self, tmp_path):
        ds = _store()
        persist.save(ds, str(tmp_path / "cat"))
        ds2 = persist.load(str(tmp_path / "cat"))
        assert ds2.type_names() == ["t"]
        q = "bbox(geom, -50, -50, 50, 50) AND age < 40"
        a = sorted(ds.query("t", q).ids.tolist())
        b = sorted(ds2.query("t", q).ids.tolist())
        assert a == b and len(a) > 0

    def test_roundtrip_extents(self, tmp_path):
        sft = FeatureType.from_spec("poly", "*geom:Polygon:srid=4326")
        ds = DataStore(tile=64)
        ds.create_schema(sft)
        polys = [geo.box(i, i, i + 2, i + 2) for i in range(20)]
        ds.write(
            "poly",
            FeatureCollection.from_columns(
                sft, [str(i) for i in range(20)], {"geom": polys}
            ),
        )
        persist.save(ds, str(tmp_path / "cat"))
        ds2 = persist.load(str(tmp_path / "cat"))
        got = ds2.query("poly", "bbox(geom, 5, 5, 8, 8)")
        want = ds.query("poly", "bbox(geom, 5, 5, 8, 8)")
        assert sorted(got.ids.tolist()) == sorted(want.ids.tolist())

    def test_empty_type(self, tmp_path):
        ds = DataStore()
        ds.create_schema(FeatureType.from_spec("e", SPEC))
        persist.save(ds, str(tmp_path / "cat"))
        ds2 = persist.load(str(tmp_path / "cat"))
        assert len(ds2.query("e")) == 0


class TestExporters:
    def test_csv(self):
        ds = _store(5)
        text = export(ds.query("t"), "csv")
        lines = text.strip().split("\n")
        assert lines[0] == "id,name,age,dtg,geom"
        assert len(lines) == 6
        assert "POINT (" in lines[1]

    def test_geojson(self):
        ds = _store(5)
        doc = json.loads(export(ds.query("t"), "geojson"))
        assert doc["type"] == "FeatureCollection"
        assert len(doc["features"]) == 5
        f0 = doc["features"][0]
        assert f0["geometry"]["type"] == "Point"
        assert set(f0["properties"]) == {"name", "age", "dtg"}

    def test_wkt_and_json(self):
        ds = _store(3)
        assert export(ds.query("t"), "wkt").count("POINT") == 3
        rows = json.loads(export(ds.query("t"), "json"))
        assert len(rows) == 3 and "__id__" in rows[0]

    def test_geojson_polygon(self):
        sft = FeatureType.from_spec("p", "*geom:Polygon:srid=4326")
        fc = FeatureCollection.from_columns(sft, ["a"], {"geom": [geo.box(0, 0, 1, 1)]})
        doc = json.loads(export(fc, "geojson"))
        assert doc["features"][0]["geometry"]["type"] == "Polygon"

    def test_unknown_format(self):
        ds = _store(1)
        with pytest.raises(ValueError):
            export(ds.query("t"), "shapefile")


class TestExpressions:
    def test_columns_and_casts(self):
        e = compile_expression("$2::int")
        assert e(["a", "41"]) == 41
        assert compile_expression("$1::double")(["2.5"]) == 2.5

    def test_functions(self):
        p = compile_expression("point($1, $2)")(["1.5", "-2"])
        assert (p.x, p.y) == (1.5, -2.0)
        assert compile_expression("concat($1, '-', $2)")(["a", "b"]) == "a-b"
        dt = compile_expression("datetime($1)")(["2024-01-02T03:04:05Z"])
        assert dt == int(np.datetime64("2024-01-02T03:04:05", "ms").astype(np.int64))
        assert compile_expression("md5($1)")(["x"]) == compile_expression("md5($1)")(["x"])

    def test_json_path(self):
        e = compile_expression("$.props.name")
        assert e({"props": {"name": "z"}}) == "z"

    def test_bad_expression(self):
        with pytest.raises(ValueError):
            compile_expression("nope!!(")


class TestConverter:
    CSV = "id,name,lon,lat,when\n1,alpha,10.5,-3.25,2024-01-02T00:00:00Z\n2,beta,20,40,2024-02-03T00:00:00Z\n"

    def test_delimited(self):
        sft = FeatureType.from_spec("c", "name:String,dtg:Date,*geom:Point:srid=4326")
        conv = Converter(
            sft=sft,
            fields=[
                FieldSpec("name", "$2"),
                FieldSpec("dtg", "datetime($5)"),
                FieldSpec("geom", "point($3, $4)"),
            ],
            id_field="$1",
            skip_lines=1,
        )
        fc = conv.convert(self.CSV)
        assert len(fc) == 2
        assert fc.ids.tolist() == ["1", "2"]
        assert fc.columns["geom"].x.tolist() == [10.5, 20.0]

    def test_error_rows_dropped(self):
        sft = FeatureType.from_spec("c", "age:Int,*geom:Point:srid=4326")
        conv = Converter(
            sft=sft,
            fields=[FieldSpec("age", "$1::int"), FieldSpec("geom", "point($2, $3)")],
        )
        fc = conv.convert("5,1,2\nbad,3,4\n7,5,6\n")
        assert len(fc) == 2 and conv.errors == 1

    def test_json_converter(self):
        sft = FeatureType.from_spec("j", "name:String,*geom:Point:srid=4326")
        conv = Converter(
            sft=sft,
            fields=[
                FieldSpec("name", "$.properties.name"),
                FieldSpec("geom", "point($.x, $.y)"),
            ],
            fmt="json",
        )
        fc = conv.convert(json.dumps([
            {"properties": {"name": "a"}, "x": 1, "y": 2},
            {"properties": {"name": "b"}, "x": 3, "y": 4},
        ]))
        assert fc.columns["geom"].y.tolist() == [2.0, 4.0]

    def test_infer(self):
        rows = [
            ["alpha", "3", "10.5", "-3.25", "2024-01-02T00:00:00Z"],
            ["beta", "4", "20.0", "40.0", "2024-02-03T00:00:00Z"],
        ]
        sft, conv = infer_schema("inf", rows, header=["name", "n", "lon", "lat", "when"])
        types = {a.name: a.type for a in sft.attributes}
        assert types["name"] == "String" and types["n"] == "Integer"
        assert types["when"] == "Date" and "geom" in types
        fc = conv.convert("alpha,3,10.5,-3.25,2024-01-02T00:00:00Z\n")
        assert fc.columns["geom"].x.tolist() == [10.5]


class TestCli:
    def test_workflow(self, tmp_path, capsys):
        cat = str(tmp_path / "cat")
        csv_file = tmp_path / "data.csv"
        csv_file.write_text(
            "name,lon,lat,when\nalpha,1.5,2.5,2024-01-02T00:00:00Z\n"
            "beta,-3.0,4.0,2024-02-03T00:00:00Z\n"
        )
        assert cli.main(["ingest", "-c", cat, "-f", "obs", "--infer", "--header", str(csv_file)]) == 0
        assert cli.main(["get-type-names", "-c", cat]) == 0
        assert cli.main(["describe-schema", "-c", cat, "-f", "obs"]) == 0
        assert cli.main(["count", "-c", cat, "-f", "obs"]) == 0
        assert cli.main(["explain", "-c", cat, "-f", "obs", "-q", "bbox(geom,0,0,5,5)"]) == 0
        out_file = str(tmp_path / "out.geojson")
        assert cli.main([
            "export", "-c", cat, "-f", "obs", "--format", "geojson", "-o", out_file,
        ]) == 0
        doc = json.loads(open(out_file).read())
        assert len(doc["features"]) == 2
        assert cli.main(["stats", "-c", cat, "-f", "obs", "--spec", "Count()"]) == 0
        captured = capsys.readouterr()
        assert "ingested 2 features" in captured.out
        assert '"count": 2' in captured.out

    def test_create_and_delete(self, tmp_path, capsys):
        cat = str(tmp_path / "cat")
        assert cli.main([
            "create-schema", "-c", cat, "-f", "s", "-s", "dtg:Date,*geom:Point:srid=4326",
        ]) == 0
        assert cli.main(["delete-schema", "-c", cat, "-f", "s"]) == 0
        assert cli.main(["get-type-names", "-c", cat]) == 0
        assert "created schema" in capsys.readouterr().out


class TestPartitionedPersistence:
    """v2 partitioned layout (DateTimeScheme analogue): one npz per coarse
    time partition, incremental re-saves skip unchanged partitions."""

    def _store(self, tmp, n=4000, extra=0):
        from geomesa_tpu import DataStore, FeatureCollection, FeatureType

        sft = FeatureType.from_spec("pp", "dtg:Date,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        rng = np.random.default_rng(1)
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
        fc = FeatureCollection.from_columns(
            sft, [str(i) for i in range(n)],
            {"dtg": t0 + rng.integers(0, 120 * 86400_000, n),
             "geom": (rng.uniform(-50, 50, n), rng.uniform(-40, 40, n))},
        )
        ds.write("pp", fc, check_ids=False)
        if extra:
            fc2 = FeatureCollection.from_columns(
                sft, [f"x{i}" for i in range(extra)],
                {"dtg": t0 + 119 * 86400_000 + rng.integers(0, 86400_000, extra),
                 "geom": (rng.uniform(-50, 50, extra), rng.uniform(-40, 40, extra))},
            )
            ds.write("pp", fc2, check_ids=False)
        return ds

    def test_roundtrip_partitioned(self, tmp_path):
        import os

        from geomesa_tpu.storage import persist

        ds = self._store(tmp_path)
        root = str(tmp_path / "cat")
        persist.save(ds, root)
        files = os.listdir(os.path.join(root, "pp"))
        assert len(files) >= 4  # 120 days / ~28-day partitions
        back = persist.load(root)
        assert back.count("pp") == ds.count("pp")
        q = "bbox(geom, -10, -10, 10, 10)"
        assert sorted(back.query("pp", q).ids.tolist()) == sorted(
            ds.query("pp", q).ids.tolist()
        )

    def test_incremental_save_skips_unchanged(self, tmp_path):
        import os

        from geomesa_tpu.storage import persist

        ds = self._store(tmp_path)
        root = str(tmp_path / "cat")
        persist.save(ds, root)
        tdir = os.path.join(root, "pp")
        mtimes = {f: os.path.getmtime(os.path.join(tdir, f)) for f in os.listdir(tdir)}
        # append rows only to the LAST partition, then re-save: the v3
        # content-addressed layout REPLACES the touched partition's file
        # (new name, old one garbage-collected) and leaves every other
        # file byte-identical in place
        ds2 = self._store(tmp_path, extra=300)
        import time as _time

        _time.sleep(0.02)
        persist.save(ds2, root)
        after = {f: os.path.getmtime(os.path.join(tdir, f)) for f in os.listdir(tdir)}
        kept = set(mtimes) & set(after)
        assert len(set(mtimes) - kept) == 1  # one old version dropped
        assert len(set(after) - kept) == 1   # one new version written
        assert all(after[f] == mtimes[f] for f in kept)  # rest untouched
        back = persist.load(root)
        assert back.count("pp") == ds2.count("pp")


class TestFixedWidthConverter:
    """fixed-width format (reference geomesa-convert-fixedwidth)."""

    def test_fixed_width(self):
        from geomesa_tpu.io.converters import Converter, FieldSpec

        sft = FeatureType.from_spec("fw", "name:String,*geom:Point:srid=4326")
        conv = Converter(
            sft,
            fields=[
                FieldSpec("name", "$1"),
                FieldSpec("geom", "point($2, $3)"),
            ],
            fmt="fixed-width",
            fixed_widths=[(0, 6), (6, 8), (14, 8)],
            skip_lines=1,
        )
        data = (
            "NAME  LON     LAT     \n"
            "alpha   10.50   20.25\n"
            "beta   -33.10   51.00\n"
            "\n"
        )
        fc = conv.convert(data)
        assert len(fc) == 2
        assert list(fc.columns["name"]) == ["alpha", "beta"]
        x, y = fc.representative_xy()
        np.testing.assert_allclose(x, [10.5, -33.1])
        np.testing.assert_allclose(y, [20.25, 51.0])

    def test_missing_widths_raises(self):
        from geomesa_tpu.io.converters import Converter, FieldSpec

        sft = FeatureType.from_spec("fw", "name:String,*geom:Point:srid=4326")
        conv = Converter(
            sft, fields=[FieldSpec("name", "$1")], fmt="fixed-width"
        )
        with pytest.raises(ValueError, match="fixed_widths"):
            list(conv.convert("abc\n"))


class TestDbapiConverter:
    """DB-API rows as converter records (geomesa-convert-jdbc analogue,
    driven through the standard library's sqlite3)."""

    def test_sqlite_roundtrip(self):
        import sqlite3

        from geomesa_tpu.io.converters import Converter, FieldSpec, dbapi_records

        conn = sqlite3.connect(":memory:")
        conn.execute("CREATE TABLE pts (name TEXT, lon REAL, lat REAL)")
        conn.executemany(
            "INSERT INTO pts VALUES (?, ?, ?)",
            [("a", 1.0, 2.0), ("b", -3.0, 4.5), ("c", 100.0, -45.0)],
        )
        sft = FeatureType.from_spec("db", "name:String,*geom:Point:srid=4326")
        conv = Converter(
            sft,
            fields=[
                FieldSpec("name", "$1"),
                FieldSpec("geom", "point($2, $3)"),
            ],
            id_field="$1",
        )
        fc = conv.convert_records(
            dbapi_records(conn, "SELECT name, lon, lat FROM pts ORDER BY name")
        )
        assert len(fc) == 3
        assert list(fc.ids) == ["a", "b", "c"]
        x, y = fc.representative_xy()
        np.testing.assert_allclose(x, [1.0, -3.0, 100.0])
        np.testing.assert_allclose(y, [2.0, 4.5, -45.0])
        conn.close()


class TestBytesColumns:
    def test_write_query_persist_roundtrip(self, tmp_path):
        """Bytes attributes: write must not crash the sketches, queries
        return them intact, and persistence is binary-safe (str()-ing
        would corrupt; np.unique on bytes crashes fnv hashing)."""
        from geomesa_tpu.datastore import DataStore

        sft = FeatureType.from_spec(
            "b", "payload:Bytes,flag:Boolean,*geom:Point:srid=4326"
        )
        ds = DataStore()
        ds.create_schema(sft)
        vals = [b"\x00\x01", b"hello", b"\xff\xfe", b""]
        payloads = np.empty(4, dtype=object)
        payloads[:] = vals
        ds.write("b", FeatureCollection.from_columns(
            sft, np.arange(4),
            {"payload": payloads, "flag": np.array([True, False, True, False]),
             "geom": (np.arange(4.0), np.zeros(4))},
        ))
        out = ds.query("b", "bbox(geom, -1, -1, 5, 1)")
        assert list(out.columns["payload"]) == vals
        persist.save(ds, tmp_path / "s")
        ds2 = persist.load(tmp_path / "s")
        assert list(ds2.features("b").columns["payload"]) == vals
        assert list(ds2.features("b").columns["flag"]) == [True, False, True, False]

    def test_none_bytes_and_partitioned_path(self, tmp_path):
        """None stays None through persistence (null mask, distinct from
        b""), including on the time-partitioned save path."""
        from geomesa_tpu.datastore import DataStore

        sft = FeatureType.from_spec(
            "bt", "payload:Bytes,dtg:Date,*geom:Point:srid=4326"
        )
        ds = DataStore()
        ds.create_schema(sft)
        vals = [b"x", None, b"", b"\xff"]
        payloads = np.empty(4, dtype=object)
        payloads[:] = vals
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
        # spread rows across two ~monthly partitions
        dtg = np.array([t0, t0, t0 + 40 * 86400_000, t0 + 40 * 86400_000])
        ds.write("bt", FeatureCollection.from_columns(
            sft, np.arange(4),
            {"payload": payloads, "dtg": dtg,
             "geom": (np.arange(4.0), np.zeros(4))},
        ))
        persist.save(ds, tmp_path / "s2")
        ds2 = persist.load(tmp_path / "s2")
        back = ds2.features("bt")
        got = {str(i): v for i, v in zip(back.ids, back.columns["payload"])}
        assert got == {"0": b"x", "1": None, "2": b"", "3": b"\xff"}


class TestOrc:
    """ORC feature IO + the file-pruning OrcStorage directory
    (reference OrcFileSystemStorage)."""

    @staticmethod
    def _fc(n=300, seed=0, name="orcs"):
        rng = np.random.default_rng(seed)
        sft = FeatureType.from_spec(
            name, "name:String,age:Int,dtg:Date,*geom:Point:srid=4326"
        )
        t0 = np.datetime64("2024-03-01T00:00:00", "ms").astype(np.int64)
        return FeatureCollection.from_columns(
            sft,
            np.arange(n).astype(str),
            {
                "name": np.array([f"n{i % 17}" for i in range(n)], dtype=object),
                "age": rng.integers(0, 90, n),
                "dtg": t0 + rng.integers(0, 20 * 86400_000, n),
                "geom": (rng.uniform(-60, 60, n), rng.uniform(-40, 40, n)),
            },
        )

    def test_roundtrip(self, tmp_path):
        from geomesa_tpu.io.orc import read_orc, write_orc

        fc = self._fc()
        path = str(tmp_path / "f.orc")
        write_orc(fc, path)
        fc2 = read_orc(path)  # schema from the sidecar
        assert fc2.sft.to_spec() == fc.sft.to_spec()
        assert fc2.ids.tolist() == fc.ids.tolist()
        np.testing.assert_array_equal(fc2.columns["age"], fc.columns["age"])
        np.testing.assert_array_equal(fc2.columns["dtg"], fc.columns["dtg"])
        assert list(fc2.columns["name"]) == list(fc.columns["name"])
        np.testing.assert_allclose(fc2.geom_column.x, fc.geom_column.x)

    def test_bbox_filter(self, tmp_path):
        from geomesa_tpu.io.orc import read_orc, write_orc

        fc = self._fc()
        path = str(tmp_path / "f.orc")
        write_orc(fc, path)
        bbox = (-10.0, -10.0, 20.0, 15.0)
        got = read_orc(path, bbox=bbox)
        x, y = np.asarray(fc.geom_column.x), np.asarray(fc.geom_column.y)
        m = (x >= bbox[0]) & (x <= bbox[2]) & (y >= bbox[1]) & (y <= bbox[3])
        assert sorted(got.ids.tolist()) == sorted(np.asarray(fc.ids)[m].tolist())

    def test_extent_geometries(self, tmp_path):
        from geomesa_tpu import geometry as geo
        from geomesa_tpu.io.orc import read_orc, write_orc

        sft = FeatureType.from_spec("polys", "v:Int,*geom:Polygon:srid=4326")
        polys = [geo.box(i, i, i + 2, i + 1) for i in range(5)]
        fc = FeatureCollection.from_columns(
            sft, np.arange(5).astype(str),
            {"v": np.arange(5), "geom": polys},
        )
        path = str(tmp_path / "p.orc")
        write_orc(fc, path)
        fc2 = read_orc(path)
        assert fc2.geom_column.geometry(3) == polys[3]

    def test_storage_prunes_files(self, tmp_path):
        from geomesa_tpu.io.orc import OrcStorage

        root = str(tmp_path / "store")
        st = OrcStorage(root)
        # three spatially separated chunks
        west = self._fc(seed=1)
        west.geom_column.x[:] = np.abs(west.geom_column.x) * -1 - 100  # [-160,-100]
        east = self._fc(seed=2)
        east.geom_column.x[:] = np.abs(east.geom_column.x) + 100  # [100, 160]
        mid = self._fc(seed=3)
        st.write(west)
        st.write(east)
        st.write(mid)
        assert len(st.meta["files"]) == 3
        # a query box straddling only the east chunk prunes the others
        files = st.files(bbox=(110, -10, 120, 10))
        assert len(files) == 1 and "chunk-000001" in files[0]
        got = st.query(bbox=(110, -10, 120, 10))
        x = np.asarray(east.geom_column.x)
        y = np.asarray(east.geom_column.y)
        m = (x >= 110) & (x <= 120) & (y >= -10) & (y <= 10)
        assert sorted(got.ids.tolist()) == sorted(np.asarray(east.ids)[m].tolist())
        # reopening sees the same metadata
        from geomesa_tpu.io.orc import OrcStorage as S2

        st2 = S2(root)
        assert len(st2.files()) == 3
        assert st2.query(bbox=(0, 0, 1, 1)) is not None

    def test_export_format(self):
        import io as _io

        import pyarrow.orc as orc

        from geomesa_tpu.io.exporters import export

        fc = self._fc(n=50)
        payload = export(fc, "orc")
        assert isinstance(payload, bytes)
        t = orc.ORCFile(_io.BytesIO(payload)).read()
        assert t.num_rows == 50 and "geom_x" in t.column_names


class TestLeaflet:
    def test_html_payload(self):
        from geomesa_tpu.io.exporters import export

        fc = TestOrc._fc(n=20, name="mapped")
        html = export(fc, "leaflet")
        assert html.startswith("<!DOCTYPE html>")
        assert "var points = " in html
        assert "L.geoJSON(points" in html
        assert '"type": "FeatureCollection"' in html
        # all 20 features inlined
        assert html.count('"type": "Feature"') == 20


class TestOrcLeafletReviewFixes:
    def test_extent_storage_bbox_query(self, tmp_path):
        from geomesa_tpu import geometry as geo
        from geomesa_tpu.io.orc import OrcStorage

        sft = FeatureType.from_spec("fp", "v:Int,*geom:Polygon:srid=4326")
        polys = [geo.box(4 * i, 0, 4 * i + 3, 2) for i in range(10)]
        fc = FeatureCollection.from_columns(
            sft, np.arange(10).astype(str),
            {"v": np.arange(10), "geom": polys},
        )
        st = OrcStorage(str(tmp_path / "s"))
        st.write(fc)
        got = st.query(bbox=(5, 0.5, 12, 1.5))  # intersects polys 1..3
        assert sorted(got.ids.tolist()) == ["1", "2", "3"]

    def test_leaflet_script_injection_escaped(self):
        from geomesa_tpu.io.exporters import export

        sft = FeatureType.from_spec("x<y", "name:String,*geom:Point:srid=4326")
        fc = FeatureCollection.from_columns(
            sft, ["0"],
            {"name": np.array(["</script><img src=x onerror=alert(1)>"],
                              dtype=object),
             "geom": (np.array([1.0]), np.array([2.0]))},
        )
        html = export(fc, "leaflet")
        assert "</script><img" not in html
        assert "<title>x&lt;y</title>" in html

    def test_uncompressed_orc(self, tmp_path):
        from geomesa_tpu.io.orc import read_orc, write_orc

        fc = TestOrc._fc(n=10)
        path = str(tmp_path / "u.orc")
        write_orc(fc, path, compression="uncompressed")
        assert len(read_orc(path)) == 10


class TestOrcEmptyChunk:
    def test_empty_chunk_always_pruned(self, tmp_path):
        from geomesa_tpu.io.orc import OrcStorage

        st = OrcStorage(str(tmp_path / "s"))
        st.write(TestOrc._fc(n=0))  # empty chunk
        st.write(TestOrc._fc(n=50, seed=9))
        # an origin-spanning box must still prune the empty chunk
        files = st.files(bbox=(-1.0, -1.0, 1.0, 1.0))
        assert all("chunk-000000" not in f for f in files)
        assert st.query(bbox=(-1.0, -1.0, 1.0, 1.0)) is not None


class TestDirectIngest:
    """CLI --file-format ingest of self-describing files (reference
    geomesa-convert-parquet / -shp)."""

    def _run(self, argv):
        from geomesa_tpu.cli import main

        return main(argv)

    def test_parquet_roundtrip(self, tmp_path, capsys):
        from geomesa_tpu.io.parquet import write_parquet

        fc = TestOrc._fc(n=120, name="direct")
        pq_file = str(tmp_path / "data.parquet")
        write_parquet(fc, pq_file)
        cat = str(tmp_path / "cat")
        rc = self._run([
            "ingest", "-c", cat, "-f", "direct",
            "--file-format", "parquet", pq_file,
        ])
        assert rc == 0
        assert "ingested 120" in capsys.readouterr().out
        rc = self._run(["count", "-c", cat, "-f", "direct"])
        assert rc == 0
        assert "120" in capsys.readouterr().out

    def test_orc_appends_and_schema_check(self, tmp_path, capsys):
        from geomesa_tpu.io.orc import write_orc

        fc = TestOrc._fc(n=40, name="direct")
        f1 = str(tmp_path / "a.orc"); f2 = str(tmp_path / "b.orc")
        write_orc(fc, f1)
        fc2 = TestOrc._fc(n=30, seed=5, name="direct")
        fc2 = type(fc2)(fc2.sft, np.array([f"b{i}" for i in range(30)]), fc2.columns)
        write_orc(fc2, f2)
        cat = str(tmp_path / "cat")
        rc = self._run([
            "ingest", "-c", cat, "-f", "direct", "--file-format", "orc", f1, f2,
        ])
        assert rc == 0
        assert "ingested 70" in capsys.readouterr().out
        # mismatched schema rejected
        other = FeatureCollection.from_columns(
            FeatureType.from_spec("direct", "v:Int,*geom:Point:srid=4326"),
            ["x"], {"v": np.array([1]), "geom": (np.array([0.0]), np.array([0.0]))},
        )
        f3 = str(tmp_path / "c.orc")
        write_orc(other, f3)
        with pytest.raises(SystemExit):
            self._run([
                "ingest", "-c", cat, "-f", "direct", "--file-format", "orc", f3,
            ])

    def test_shapefile(self, tmp_path, capsys):
        from geomesa_tpu.io.shapefile import write_shapefile

        fc = TestOrc._fc(n=25, name="shp_src")
        base = str(tmp_path / "data")
        write_shapefile(fc, base)
        cat = str(tmp_path / "cat")
        rc = self._run([
            "ingest", "-c", cat, "-f", "ships",
            "--file-format", "shp", base + ".shp",
        ])
        assert rc == 0
        assert "ingested 25" in capsys.readouterr().out


class TestDirectIngestReviewFixes:
    def _run(self, argv):
        from geomesa_tpu.cli import main

        return main(argv)

    def test_multi_shapefile_ids_rebased(self, tmp_path, capsys):
        from geomesa_tpu.io.shapefile import write_shapefile

        for stem, n in (("a", 10), ("b", 15)):
            write_shapefile(TestOrc._fc(n=n, seed=n, name="s"), str(tmp_path / stem))
        cat = str(tmp_path / "cat")
        rc = self._run([
            "ingest", "-c", cat, "-f", "ships", "--file-format", "shp",
            str(tmp_path / "a.shp"), str(tmp_path / "b.shp"),
        ])
        assert rc == 0
        assert "ingested 25" in capsys.readouterr().out

    def test_external_parquet_with_known_schema(self, tmp_path, capsys):
        import pyarrow as pa
        import pyarrow.parquet as pq

        # externally-written file: correct columns, NO geomesa metadata
        t = pa.table({
            "id": ["x1", "x2"],
            "name": ["a", "b"],
            "dtg": pa.array(
                np.array([1718000000000, 1718000001000]).astype("datetime64[ms]")
            ),
            "age": pa.array(np.array([3, 4], dtype=np.int32)),
            "geom_x": pa.array([1.0, 2.0]),
            "geom_y": pa.array([3.0, 4.0]),
        })
        p = str(tmp_path / "ext.parquet")
        pq.write_table(t, p)
        cat = str(tmp_path / "cat")
        # no schema in the catalog either -> clean error, not a traceback
        rc = self._run([
            "ingest", "-c", cat, "-f", "orcs", "--file-format", "parquet", p,
        ])
        assert rc == 1
        assert "pass sft explicitly" in capsys.readouterr().err
        # with the schema pre-created, the external file ingests
        rc = self._run([
            "create-schema", "-c", cat, "-f", "orcs",
            "-s", "name:String,age:Int,dtg:Date,*geom:Point:srid=4326",
        ])
        assert rc == 0
        rc = self._run([
            "ingest", "-c", cat, "-f", "orcs", "--file-format", "parquet", p,
        ])
        assert rc == 0
        assert "ingested 2" in capsys.readouterr().out


class TestGeoJsonArrowReaders:
    """Ingest direction of the GeoJSON and Arrow exporters."""

    def test_geojson_roundtrip_via_exporter(self):
        from geomesa_tpu.io.exporters import export
        from geomesa_tpu.io.geojson import read_geojson

        fc = TestOrc._fc(n=40, name="gj")
        text = export(fc, "geojson")
        back = read_geojson(text, type_name="gj")
        assert len(back) == 40
        assert back.sft.attr("dtg").type == "Date"  # ISO strings inferred
        assert back.sft.attr("age").type == "Int"
        np.testing.assert_array_equal(
            np.asarray(back.columns["dtg"]), np.asarray(fc.columns["dtg"]))
        np.testing.assert_allclose(back.geom_column.x, fc.geom_column.x)
        assert back.ids.tolist() == fc.ids.tolist()

    def test_geojson_polygons_and_missing_props(self):
        from geomesa_tpu.io.geojson import read_geojson

        obj = {
            "type": "FeatureCollection",
            "features": [
                {"type": "Feature", "id": "p1",
                 "geometry": {"type": "Polygon",
                              "coordinates": [[[0, 0], [2, 0], [2, 2], [0, 0]]]},
                 "properties": {"height": 10.5}},
                {"type": "Feature",
                 "geometry": {"type": "Polygon",
                              "coordinates": [[[5, 5], [6, 5], [6, 6], [5, 5]]]},
                 "properties": {}},
            ],
        }
        fc = read_geojson(obj, type_name="bld", id_offset=100)
        assert fc.sft.attr("height").type == "Double"
        assert not fc.sft.is_points
        # id-less features number with their OWN counter from id_offset
        assert fc.ids.tolist() == ["p1", "100"]

    def test_arrow_ipc_roundtrip(self):
        from geomesa_tpu.io.arrow import arrow_stream, read_arrow

        fc = TestOrc._fc(n=60, name="ar")
        payload = arrow_stream(fc)  # dictionary-encoded strings
        back = read_arrow(payload)
        assert back.sft.to_spec() == fc.sft.to_spec()
        assert list(back.columns["name"]) == list(fc.columns["name"])
        np.testing.assert_array_equal(
            np.asarray(back.columns["dtg"]), np.asarray(fc.columns["dtg"]))
        np.testing.assert_allclose(back.geom_column.y, fc.geom_column.y)

    def test_arrow_ipc_extent_geometries(self, tmp_path):
        from geomesa_tpu import geometry as geo
        from geomesa_tpu.io.arrow import arrow_stream, read_arrow

        sft = FeatureType.from_spec("pg", "v:Int,*geom:Polygon:srid=4326")
        polys = [geo.box(i, 0, i + 1, 1) for i in range(4)]
        fc = FeatureCollection.from_columns(
            sft, np.arange(4).astype(str), {"v": np.arange(4), "geom": polys})
        p = tmp_path / "x.arrow"
        p.write_bytes(arrow_stream(fc))
        back = read_arrow(str(p))
        assert back.geom_column.geometry(2) == polys[2]

    def test_cli_geojson_and_arrow_ingest(self, tmp_path, capsys):
        from geomesa_tpu.cli import main
        from geomesa_tpu.io.arrow import arrow_stream
        from geomesa_tpu.io.exporters import export

        fc = TestOrc._fc(n=30, name="mix")
        gj = tmp_path / "d.geojson"
        gj.write_text(export(fc, "geojson"))
        cat = str(tmp_path / "cat")
        assert main(["ingest", "-c", cat, "-f", "mix",
                     "--file-format", "geojson", str(gj)]) == 0
        assert "ingested 30" in capsys.readouterr().out
        ar = tmp_path / "d.arrow"
        fc2 = type(fc)(fc.sft, np.array([f"a{i}" for i in range(30)]), fc.columns)
        ar.write_bytes(arrow_stream(fc2))
        assert main(["ingest", "-c", cat, "-f", "mix",
                     "--file-format", "arrow", str(ar)]) == 0
        assert "ingested 30" in capsys.readouterr().out
        assert main(["count", "-c", cat, "-f", "mix"]) == 0
        assert "60" in capsys.readouterr().out


class TestReaderReviewFixes:
    def test_geojson_custom_geometry_name(self):
        from geomesa_tpu.io.exporters import export
        from geomesa_tpu.io.geojson import read_geojson

        sft = FeatureType.from_spec("t", "v:Int,*loc:Point:srid=4326")
        fc = FeatureCollection.from_columns(
            sft, ["0", "1"],
            {"v": np.array([1, 2]),
             "loc": (np.array([1.0, 2.0]), np.array([3.0, 4.0]))})
        text = export(fc, "geojson")
        back = read_geojson(text, sft=sft)
        assert back.sft.geom_field == "loc"
        np.testing.assert_allclose(back.geom_column.x, [1.0, 2.0])

    def test_delta_stream_self_describes(self):
        from geomesa_tpu.io.arrow import ArrowDeltaWriter, read_arrow

        fc = TestOrc._fc(n=25, name="dlt")
        w = ArrowDeltaWriter(fc.sft)
        w.write(fc.take(np.arange(10)))
        w.write(fc.take(np.arange(10, 25)))
        back = read_arrow(w.finish())  # no sft passed: metadata carries it
        assert back.sft.to_spec() == fc.sft.to_spec()
        assert len(back) == 25
        assert list(back.columns["name"]) == list(fc.columns["name"])


class TestConvertCommand:
    """Store-less converter run (reference ConvertCommand)."""

    def test_csv_to_geojson(self, tmp_path, capsys):
        import json as _json

        from geomesa_tpu import cli

        csv_file = tmp_path / "in.csv"
        csv_file.write_text(
            "alpha,1.5,2.5,2024-01-02T00:00:00Z\n"
            "beta,-3.0,4.0,2024-02-03T00:00:00Z\n"
        )
        conf = tmp_path / "conv.json"
        conf.write_text(_json.dumps({
            "format": "delimited",
            "fields": [
                {"name": "name", "transform": "$1"},
                {"name": "dtg", "transform": "datetime($4)"},
                {"name": "geom", "transform": "point($2, $3)"},
            ],
        }))
        rc = cli.main([
            "convert", "-s", "name:String,dtg:Date,*geom:Point:srid=4326",
            "--converter", str(conf), "--format", "geojson", str(csv_file),
        ])
        assert rc == 0
        payload = capsys.readouterr().out
        gj = _json.loads(payload)
        assert len(gj["features"]) == 2
        assert gj["features"][1]["properties"]["name"] == "beta"
        assert gj["features"][0]["geometry"]["coordinates"] == [1.5, 2.5]


class TestReaderReviewFixes2:
    def test_multi_geojson_fresh_catalog(self, tmp_path, capsys):
        from geomesa_tpu import cli
        from geomesa_tpu.io.exporters import export

        for stem, n, seed in (("a", 12, 1), ("b", 14, 2)):
            fc = TestOrc._fc(n=n, seed=seed, name="mix")
            (tmp_path / f"{stem}.geojson").write_text(export(fc, "geojson"))
        # rewrite files WITHOUT ids to force synthesis
        import json as _json

        for stem in ("a", "b"):
            p = tmp_path / f"{stem}.geojson"
            obj = _json.loads(p.read_text())
            for f in obj["features"]:
                f.pop("id", None)
            p.write_text(_json.dumps(obj))
        cat = str(tmp_path / "cat")
        rc = cli.main([
            "ingest", "-c", cat, "-f", "mix", "--file-format", "geojson",
            str(tmp_path / "a.geojson"), str(tmp_path / "b.geojson"),
        ])
        assert rc == 0
        assert "ingested 26" in capsys.readouterr().out

    def test_geojson_bytes_content(self):
        from geomesa_tpu.io.geojson import read_geojson

        payload = (b'{"type": "FeatureCollection", "features": ['
                   b'{"type": "Feature", "geometry": {"type": "Point", '
                   b'"coordinates": [1, 2]}, "properties": {"v": 3}}]}')
        fc = read_geojson(payload)
        assert len(fc) == 1 and fc.geom_column.x[0] == 1.0


class TestConvertReviewFixes:
    def test_multi_file_convert_rebases_ids(self, tmp_path, capsys):
        import json as _json

        from geomesa_tpu import cli

        for stem in ("a", "b"):
            (tmp_path / f"{stem}.csv").write_text("x,1,2\ny,3,4\n")
        conf = tmp_path / "c.json"
        conf.write_text(_json.dumps({
            "format": "delimited",
            "fields": [
                {"name": "name", "transform": "$1"},
                {"name": "geom", "transform": "point($2, $3)"},
            ]}))
        rc = cli.main([
            "convert", "-s", "name:String,*geom:Point:srid=4326",
            "--converter", str(conf), "--format", "geojson",
            str(tmp_path / "a.csv"), str(tmp_path / "b.csv"),
        ])
        assert rc == 0
        gj = _json.loads(capsys.readouterr().out)
        ids = [f["id"] for f in gj["features"]]
        assert len(set(ids)) == 4  # no collisions across files

    def test_all_failed_clean_error(self, tmp_path, capsys):
        import json as _json

        from geomesa_tpu import cli

        (tmp_path / "bad.csv").write_text("only-one-column\n")
        conf = tmp_path / "c.json"
        conf.write_text(_json.dumps({
            "format": "delimited",
            "fields": [
                {"name": "name", "transform": "$1"},
                {"name": "geom", "transform": "point($2, $3)"},
            ]}))
        rc = cli.main([
            "convert", "-s", "name:String,*geom:Point:srid=4326",
            "--converter", str(conf), str(tmp_path / "bad.csv"),
            str(tmp_path / "bad.csv"),
        ])
        assert rc == 1
        assert "no features converted" in capsys.readouterr().err

    def test_geojson_second_file_coerces_to_stored_schema(self, tmp_path, capsys):
        import json as _json

        from geomesa_tpu import cli

        def gj(vals):
            return _json.dumps({
                "type": "FeatureCollection",
                "features": [
                    {"type": "Feature",
                     "geometry": {"type": "Point", "coordinates": [i, i]},
                     "properties": {"v": v}}
                    for i, v in enumerate(vals)
                ],
            })

        (tmp_path / "a.geojson").write_text(gj([1.5, 2.5]))  # Double
        (tmp_path / "b.geojson").write_text(gj([3, 4]))      # would infer Int
        cat = str(tmp_path / "cat")
        rc = cli.main([
            "ingest", "-c", cat, "-f", "t", "--file-format", "geojson",
            str(tmp_path / "a.geojson"), str(tmp_path / "b.geojson"),
        ])
        assert rc == 0
        assert "ingested 4" in capsys.readouterr().out
