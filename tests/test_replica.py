"""WAL shipping: read replicas, bounded staleness, kill-the-leader
failover (ISSUE 16; docs/replication.md).

The invariants under test:

- **deterministic catch-up**: an empty follower, a mid-log-checkpoint
  bootstrap, and a restarted follower all converge to the leader's
  exact row set through the shipped-segment replay path;
- **damage stays local**: a checksum-damaged shipped chunk quarantines
  the FOLLOWER's segment copy (the leader stays intact) and the resync
  protocol re-converges;
- **fencing**: a promoted follower's term is durable, and a deposed
  leader's late shipments (lower term) are refused without applying a
  byte;
- **zero acked-row loss**: under ``sync=always``, killing the leader at
  any moment and promoting a follower (finishing replay from the dead
  leader's durable WAL) loses nothing acknowledged and invents nothing
  — proven deterministically and under the seeded chaos schedule with
  a leader + 2-follower topology;
- **bounded staleness**: the watermark is measured (None = unmeasured,
  which is NOT fresh), surfaces as the ``replica.staleness`` /health
  reason, and gates reads via ``max_staleness_ms``.
"""

import os
import socket
import threading
import time

import numpy as np
import pytest

from geomesa_tpu import conf, fault, geometry as geo
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.metrics import MetricsRegistry
from geomesa_tpu.obs.ops import HealthMonitor
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.storage import persist
from geomesa_tpu.streaming import (
    LambdaStore,
    PipeTransport,
    ReplicaStore,
    SegmentShipper,
    SocketTransport,
    StreamConfig,
    WalConfig,
)
from geomesa_tpu.streaming.replica import ReplicaError, StaleRead, _encode_msg
from geomesa_tpu.streaming.wal import WalError

SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"
T0 = int(np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64))
DAY = 86_400_000


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    fault.injector().reset()


def _cold(n=100, seed=0):
    ds = DataStore()
    sft = FeatureType.from_spec("t", SPEC)
    ds.create_schema(sft)
    if n:
        rng = np.random.default_rng(seed)
        ds.write("t", FeatureCollection.from_columns(
            sft, [f"c{i}" for i in range(n)],
            {"name": np.array(["n"] * n),
             "dtg": T0 + rng.integers(0, 30 * DAY, n),
             "geom": (rng.uniform(-50, 50, n), rng.uniform(-50, 50, n))},
        ))
        ds.compact("t")
    return ds


def _leader(tmp_path, n=100, seed=0, sync="always", seg=1 << 14,
            fold_rows=4096, metrics=None):
    """(root, leader LambdaStore) over a durably saved cold store with
    tiny segments so shipping crosses rotations."""
    ds = _cold(n=n, seed=seed)
    ds.metrics = metrics if metrics is not None else MetricsRegistry()
    root = tmp_path / "s"
    persist.save(ds, root)
    lam = LambdaStore(
        ds, "t",
        config=StreamConfig(chunk_rows=64, fold_rows=fold_rows),
        wal_dir=str(root / "_wal"),
        wal_config=WalConfig(
            sync=sync, segment_bytes=seg, sync_interval_ms=1e9,
        ),
    )
    return root, lam


def _follower(root, tmp_path, name="f1", **kw):
    """(ReplicaStore, leader-side transport endpoint) over its own
    replica directory."""
    kw.setdefault("config", StreamConfig(chunk_rows=64, fold_rows=4096))
    a, b = PipeTransport.pair()
    fol = ReplicaStore(
        str(root), str(tmp_path / name / "_wal"), b, type_name="t", **kw
    )
    return fol, a


def _rows(k, n=20):
    rng = np.random.default_rng(k)
    return [
        {"name": f"w{k}-{i}", "dtg": T0 + i,
         "geom": geo.Point(float(rng.uniform(-50, 50)),
                           float(rng.uniform(-50, 50)))}
        for i in range(n)
    ]


def _ids(k, n=20):
    return [f"w{k}-{i}" for i in range(n)]


def _names(store) -> dict:
    fc = store.query("INCLUDE")
    return dict(zip(
        (str(i) for i in fc.ids.tolist()),
        (str(v) for v in np.asarray(fc.columns["name"]).tolist()),
    ))


def _reasons(report) -> set:
    return {r["reason"] for r in report["reasons"]}


# -- the transport SPI ------------------------------------------------------


class TestTransport:
    def test_pipe_roundtrip_and_close(self):
        a, b = PipeTransport.pair()
        a.send({"m": "x", "v": 1})
        assert b.recv() == {"m": "x", "v": 1}
        assert b.recv() is None
        b.send({"m": "y"})
        assert a.recv() == {"m": "y"}
        a.close()
        with pytest.raises(OSError):
            b.send({"m": "z"})

    def test_socket_roundtrip(self):
        s0, s1 = socket.socketpair()
        a, b = SocketTransport(s0), SocketTransport(s1)
        try:
            a.send({"m": "seg", "off": 0, "data": "QUJD"})
            assert b.recv(timeout=5.0) == {
                "m": "seg", "off": 0, "data": "QUJD",
            }
            assert b.recv(timeout=0.01) is None
        finally:
            a.close(), b.close()

    def test_socket_reassembles_partial_frames(self):
        s0, s1 = socket.socketpair()
        b = SocketTransport(s1)
        try:
            wire = _encode_msg({"m": "state", "horizon": 7})
            s0.sendall(wire[:3])
            assert b.recv(timeout=0.05) is None  # frame still arriving
            s0.sendall(wire[3:] + _encode_msg({"m": "state", "horizon": 8}))
            assert b.recv(timeout=5.0) == {"m": "state", "horizon": 7}
            assert b.recv(timeout=5.0) == {"m": "state", "horizon": 8}
        finally:
            s0.close(), b.close()

    def test_socket_damaged_frame_poisons_stream(self):
        s0, s1 = socket.socketpair()
        b = SocketTransport(s1)
        try:
            wire = bytearray(_encode_msg({"m": "state", "horizon": 7}))
            wire[-1] ^= 0xFF  # corrupt the checksum
            s0.sendall(bytes(wire))
            with pytest.raises(ReplicaError):
                b.recv(timeout=5.0)
            # the stream is closed: frame boundaries past damage are
            # unrecoverable
            assert b.recv(timeout=0.01) is None
        finally:
            s0.close(), b.close()

    def test_listener_accept_connect(self):
        srv = SocketTransport.listen()
        try:
            done: list = []

            def follower_side():
                end = srv.accept(timeout=5.0)
                done.append(end.recv(timeout=5.0))
                end.close()

            t = threading.Thread(target=follower_side)
            t.start()
            leader = SocketTransport.connect("127.0.0.1", srv.port)
            leader.send({"m": "hello", "offsets": {}})
            t.join(10)
            leader.close()
            assert done == [{"m": "hello", "offsets": {}}]
        finally:
            srv.close()


# -- deterministic catch-up matrix ------------------------------------------


class TestCatchUp:
    def test_empty_follower_catches_up(self, tmp_path):
        root, lam = _leader(tmp_path)
        lam.write(_rows(1), ids=_ids(1))
        fol, end = _follower(root, tmp_path)
        ship = SegmentShipper(lam, chunk_bytes=512)
        ship.attach(end)
        lam.write(_rows(2), ids=_ids(2))
        ship.pump()
        fol.drain()
        assert fol.replayed == lam.wal.last_seq
        assert _names(fol) == _names(lam)
        assert fol.staleness_ms() is not None
        assert fol.metrics.counter_value(
            "geomesa.replica.applied.records") > 0
        fol.close(), lam.close()

    def test_midlog_checkpoint_bootstrap(self, tmp_path):
        """A follower bootstrapping from a checkpoint taken mid-log
        replays only the live suffix and still converges."""
        root, lam = _leader(tmp_path, seg=2 << 10)
        lam.write(_rows(1), ids=_ids(1))
        lam.checkpoint(root)  # retires covered segments
        lam.write(_rows(2), ids=_ids(2))
        fol, end = _follower(root, tmp_path)
        ship = SegmentShipper(lam, chunk_bytes=512)
        ship.attach(end)
        ship.pump()
        fol.drain()
        assert _names(fol) == _names(lam)
        assert fol.replayed == lam.wal.last_seq
        fol.close(), lam.close()

    def test_restarted_follower_resumes_from_offsets(self, tmp_path):
        """A restarted follower's hello carries its local segment sizes:
        the shipper re-sends nothing it already holds."""
        root, lam = _leader(tmp_path)
        fol, end = _follower(root, tmp_path)
        ship = SegmentShipper(lam, chunk_bytes=512)
        fid = ship.attach(end)
        lam.write(_rows(1), ids=_ids(1))
        ship.pump()
        fol.drain()
        wal_dir = fol.wal_dir
        fol.stop()
        fol.store.close()  # keep the local segment copies on disk
        ship.detach(fid)
        fol2, end2 = _follower(root, tmp_path)  # same replica dir
        assert fol2.wal_dir == wal_dir
        ship.attach(end2)
        shipped = ship.pump()  # hello drained, offsets match: 0 payload
        assert shipped == 0
        fol2.drain()
        assert _names(fol2) == _names(lam)
        fol2.close(), lam.close()

    def test_gap_triggers_resync_and_heals(self, tmp_path):
        """A seg chunk past the local size (a lost message) must not be
        applied across the hole: the follower truncates, asks for a
        re-ship, and converges."""
        root, lam = _leader(tmp_path)
        fol, end = _follower(root, tmp_path)
        ship = SegmentShipper(lam, chunk_bytes=1 << 20)
        ship.attach(end)
        lam.write(_rows(1), ids=_ids(1))
        # swallow the first shipped chunk: the follower sees a gap next
        ship.pump()
        dropped = fol.transport._inbox.popleft()
        lam.write(_rows(2), ids=_ids(2))
        ship.pump()
        fol.drain()
        assert fol.metrics.counter_value("geomesa.replica.resync") >= 1
        ship.pump()  # the resync request re-ships from byte 0
        fol.drain()
        assert _names(fol) == _names(lam)
        assert dropped  # the swallowed bytes were really withheld
        fol.close(), lam.close()

    def test_duplicate_chunk_is_idempotent(self, tmp_path):
        root, lam = _leader(tmp_path)
        fol, end = _follower(root, tmp_path)
        ship = SegmentShipper(lam, chunk_bytes=1 << 20)
        ship.attach(end)
        lam.write(_rows(1), ids=_ids(1))
        ship.pump()
        msgs = list(fol.transport._inbox)
        fol.drain()
        before = _names(fol)
        fol.transport._inbox.extend(msgs)  # replay the whole pump
        fol.drain()
        assert _names(fol) == before == _names(lam)
        fol.close(), lam.close()

    def test_damaged_chunk_quarantines_follower_leader_intact(
            self, tmp_path):
        """Checksum damage in a shipped chunk quarantines the FOLLOWER's
        local copy (its own ``_quarantine/_wal/``, a DamageRecord on its
        health) and resyncs from the intact leader."""
        root, lam = _leader(tmp_path)
        fol, end = _follower(root, tmp_path)
        ship = SegmentShipper(lam, chunk_bytes=1 << 20)
        ship.attach(end)
        lam.write(_rows(1), ids=_ids(1))
        ship.pump()
        fol.drain()
        # forge the next chunk: right offset, corrupted frame bytes
        import base64 as b64

        state = lam.wal.ship_state()
        name = state["segments"][-1][0]
        cur = fol._sizes[name]
        garbage = bytearray(_encode_msg({"k": "u", "s": 10 ** 6}))
        garbage[-1] ^= 0xFF  # checksum damage, not torn
        fol._handle({
            "m": "seg", "term": int(state["term"]), "name": name,
            "off": int(cur),
            "data": b64.b64encode(bytes(garbage)).decode("ascii"),
            "sealed": False,
        })
        qdir = os.path.join(fol.replica_root, "_quarantine", "_wal")
        assert os.path.isdir(qdir) and os.listdir(qdir)
        assert any(
            d.type_name == "_wal" and "shipped chunk" in d.detail
            for d in fol.store.cold.health.damage
        )
        assert fol.metrics.counter_value(
            "geomesa.stream.wal.quarantined") >= 1
        # the leader never saw the damage; the resync re-converges
        assert lam.wal.ship_state()["segments"]  # leader WAL intact
        ship.pump()
        fol.drain()
        assert _names(fol) == _names(lam)
        fol.close(), lam.close()

    def test_checkpoint_manifest_drops_follower_segments(self, tmp_path):
        """The state mark's segment manifest retires follower-local
        copies the leader checkpointed away."""
        root, lam = _leader(tmp_path, seg=2 << 10)
        fol, end = _follower(root, tmp_path)
        ship = SegmentShipper(lam, chunk_bytes=4096)
        ship.attach(end)
        for k in range(1, 5):
            lam.write(_rows(k), ids=_ids(k))
        ship.pump()
        fol.drain()
        before = set(fol._sizes)
        assert len(before) > 1, "shrink segment_bytes: no rotation"
        lam.checkpoint(root)
        ship.pump()
        fol.drain()
        live = {n for n, _, _ in lam.wal.ship_state()["segments"]}
        retired = before - live
        assert retired, "the checkpoint retired nothing"
        after = set(fol._sizes)
        assert after <= live and not (after & retired)
        assert sorted(os.listdir(fol.wal_dir)) == sorted(after)
        fol.close(), lam.close()


# -- staleness --------------------------------------------------------------


class TestStaleness:
    def test_unmeasured_until_first_mark(self, tmp_path):
        root, lam = _leader(tmp_path)
        fol, end = _follower(root, tmp_path)
        assert fol.staleness_ms() is None
        ship = SegmentShipper(lam)
        ship.attach(end)
        ship.pump()
        fol.drain()
        st = fol.staleness_ms()
        assert st is not None and st < 60_000
        fol.close(), lam.close()

    def test_watermark_semantics_deterministic(self, tmp_path):
        """Caught-up: staleness measures from the NEWEST fully-replayed
        mark. Behind every mark: at least as stale as the oldest."""
        root, lam = _leader(tmp_path)
        fol, end = _follower(root, tmp_path)
        r = fol.replayed
        fol._handle({"m": "state", "term": 0, "horizon": r,
                     "wall_ms": 1_000.0, "segments": []})
        fol._handle({"m": "state", "term": 0, "horizon": r,
                     "wall_ms": 2_000.0, "segments": []})
        fol._handle({"m": "state", "term": 0, "horizon": r + 10,
                     "wall_ms": 3_000.0, "segments": []})
        # newest replayed mark is wall=2000; the horizon-ahead mark at
        # 3000 is pending
        assert fol.staleness_ms(now_ms=2_500.0) == 500.0
        with fol._apply_lock:
            fol._marks.clear()
            fol._marks.append((r + 10, 4_000.0))
        # behind even the oldest retained mark: at LEAST that stale
        assert fol.staleness_ms(now_ms=5_000.0) == 1_000.0
        fol.close(), lam.close()

    def test_staleness_histogram_observed(self, tmp_path):
        root, lam = _leader(tmp_path)
        fol, end = _follower(root, tmp_path)
        ship = SegmentShipper(lam)
        ship.attach(end)
        lam.write(_rows(1), ids=_ids(1))
        ship.pump()
        fol.drain()
        h = fol.metrics.histograms.get("geomesa.replica.staleness.ms")
        assert h is not None and h.count >= 1
        fol.close(), lam.close()

    def test_slo_default_objective_follows_knob(self):
        from geomesa_tpu.obs.slo import default_objectives

        names = {o.name for o in default_objectives()}
        assert "replica_staleness_p99" in names
        obj = next(
            o for o in default_objectives()
            if o.name == "replica_staleness_p99"
        )
        assert obj.metric == "geomesa.replica.staleness.ms"
        conf.OBS_SLO_REPLICA_STALENESS_P99_MS.set(0)
        try:
            names = {o.name for o in default_objectives()}
            assert "replica_staleness_p99" not in names
        finally:
            conf.OBS_SLO_REPLICA_STALENESS_P99_MS.clear()

    def test_bounded_staleness_read(self, tmp_path):
        root, lam = _leader(tmp_path)
        fol, end = _follower(root, tmp_path)
        # unmeasured is NOT fresh: the bounded read refuses
        with pytest.raises(StaleRead):
            fol.query("INCLUDE", max_staleness_ms=60_000)
        ship = SegmentShipper(lam)
        ship.attach(end)
        ship.pump()
        fol.drain()
        assert len(fol.query("INCLUDE", max_staleness_ms=60_000)) == 100
        # an old watermark refuses a tight bound
        with fol._apply_lock:
            fol._marks.clear()
            fol._marks.append((0, time.time() * 1e3 - 50_000.0))
        with pytest.raises(StaleRead):
            fol.query("INCLUDE", max_staleness_ms=10_000)
        fol.close(), lam.close()


# -- /health ----------------------------------------------------------------


class TestHealth:
    def test_staleness_health_reason(self, tmp_path):
        root, lam = _leader(tmp_path)
        fol, end = _follower(root, tmp_path)
        mon = HealthMonitor(fol.store.cold, lam=fol.store)
        report = mon.evaluate()
        assert "replica.staleness" in _reasons(report)
        assert report["status"] == "degraded"
        assert "unmeasured" in next(
            r for r in report["reasons"]
            if r["reason"] == "replica.staleness"
        )["detail"]
        # catch up: the reason clears and the explain line surfaces
        ship = SegmentShipper(lam)
        ship.attach(end)
        lam.write(_rows(1), ids=_ids(1))
        ship.pump()
        fol.drain()
        report = mon.evaluate()
        assert "replica.staleness" not in _reasons(report)
        assert report["replica"]["replayed"] == fol.replayed
        assert report["replica"]["term"] == fol.term
        assert report["replica"]["staleness_ms"] is not None
        # an old watermark degrades again, with the knob in the detail
        with fol._apply_lock:
            fol._marks.clear()
            fol._marks.append((0, time.time() * 1e3 - 60_000.0))
        report = mon.evaluate()
        assert "replica.staleness" in _reasons(report)
        assert any(
            "geomesa.replica.staleness.max.ms" in r["detail"]
            for r in report["reasons"]
        )
        # knob 0 disables the check entirely
        conf.REPLICA_STALENESS_MAX_MS.set(0)
        try:
            assert "replica.staleness" not in _reasons(mon.evaluate())
        finally:
            conf.REPLICA_STALENESS_MAX_MS.clear()
        fol.close(), lam.close()

    def test_ship_giveup_health_reason(self, tmp_path):
        root, lam = _leader(tmp_path)
        fol, end = _follower(root, tmp_path)
        ship = SegmentShipper(lam, giveup_s=0.0)
        fid = ship.attach(end)
        mon = HealthMonitor(lam.cold, lam=lam)
        assert "replica.ship.giveup" not in _reasons(mon.evaluate())
        fol.transport.close()  # kills both pipe ends
        lam.write(_rows(1), ids=_ids(1))
        ship.pump()
        assert fid in ship.gave_up_report()
        assert lam.cold.metrics.counter_value(
            "geomesa.replica.ship.giveup") >= 1
        report = mon.evaluate()
        assert "replica.ship.giveup" in _reasons(report)
        assert any(
            "geomesa.replica.giveup.s" in r["detail"]
            for r in report["reasons"]
        )
        ship.detach(fid)
        assert "replica.ship.giveup" not in _reasons(mon.evaluate())
        fol.store.close(), lam.close()


# -- the retry budget (fault.with_retries max_elapsed_s) --------------------


class TestRetryBudget:
    def test_elapsed_budget_gives_up_immediately(self):
        m = MetricsRegistry()
        calls = [0]

        def fn():
            calls[0] += 1
            raise OSError("transient storm")

        with pytest.raises(OSError):
            fault.with_retries(
                fn, attempts=50, backoff_s=0.001, metrics=m,
                sleep=lambda s: None, max_elapsed_s=0.0,
            )
        assert calls[0] == 1  # budget consumed before any retry
        assert m.counter_value("geomesa.fault.retries_exhausted") == 1
        h = m.histograms.get("geomesa.fault.retry.giveup.ms")
        assert h is not None and h.count == 1

    def test_attempt_budget_also_observes_giveup(self):
        m = MetricsRegistry()

        def fn():
            raise OSError("down")

        with pytest.raises(OSError):
            fault.with_retries(
                fn, attempts=3, backoff_s=0.0, metrics=m,
                sleep=lambda s: None,
            )
        assert m.counter_value("geomesa.fault.retry") == 2
        assert m.counter_value("geomesa.fault.retries_exhausted") == 1
        assert m.histograms["geomesa.fault.retry.giveup.ms"].count == 1

    def test_budget_not_charged_on_success(self):
        m = MetricsRegistry()
        calls = [0]

        def fn():
            calls[0] += 1
            if calls[0] == 1:
                raise OSError("blip")
            return "ok"

        assert fault.with_retries(
            fn, attempts=5, backoff_s=0.0, metrics=m,
            sleep=lambda s: None, max_elapsed_s=30.0,
        ) == "ok"
        assert m.counter_value("geomesa.fault.retries_exhausted") == 0
        assert "geomesa.fault.retry.giveup.ms" not in m.histograms

    def test_shipper_transient_fault_absorbed(self, tmp_path):
        root, lam = _leader(tmp_path)
        fol, end = _follower(root, tmp_path)
        ship = SegmentShipper(lam, chunk_bytes=512)
        ship.attach(end)
        lam.write(_rows(1), ids=_ids(1))
        with fault.inject("replica.ship.segment", kind="io_error", times=1):
            ship.pump()
        fol.drain()
        assert not ship.gave_up_report()
        assert _names(fol) == _names(lam)
        assert lam.cold.metrics.counter_value("geomesa.fault.retry") >= 1
        fol.close(), lam.close()

    def test_shipper_bounded_giveup_then_recovers(self, tmp_path):
        root, lam = _leader(tmp_path)
        fol, end = _follower(root, tmp_path)
        ship = SegmentShipper(lam, chunk_bytes=512, giveup_s=0.0)
        fid = ship.attach(end)
        lam.write(_rows(1), ids=_ids(1))
        with fault.inject(
            "replica.ship.segment", kind="io_error", times=None,
        ):
            ship.pump()
            assert fid in ship.gave_up_report()
        # the storm passes: the next tick retries fresh and clears
        ship.pump()
        fol.drain()
        assert not ship.gave_up_report()
        assert _names(fol) == _names(lam)
        fol.close(), lam.close()


# -- replay progress (recover on_progress) ----------------------------------


class TestReplayProgress:
    def test_recover_reports_progress_and_gauge(self, tmp_path):
        reg = MetricsRegistry()
        root, lam = _leader(tmp_path, seg=2 << 10)
        for k in range(1, 5):
            lam.write(_rows(k), ids=_ids(k))
        last = lam.wal.last_seq
        lam.wal.crash()
        events: list = []
        rec = LambdaStore.recover(
            root, on_progress=lambda s, seg, b: events.append((s, seg, b)),
            metrics=reg,
        )
        assert len(events) >= 2, "shrink segment_bytes: one segment only"
        seqs = [e[0] for e in events]
        assert seqs == sorted(seqs) and seqs[-1] == last
        assert all(e[1].startswith("wal-") for e in events)
        reads = [e[2] for e in events]
        assert reads == sorted(reads) and reads[-1] > 0  # cumulative
        assert reg.gauges["geomesa.replica.replay.progress"] == last
        assert rec.count() == 180  # 100 cold + 4x20 replayed
        rec.close()


# -- fencing + failover -----------------------------------------------------


class TestFailover:
    def test_follower_is_read_only(self, tmp_path):
        root, lam = _leader(tmp_path)
        fol, _end = _follower(root, tmp_path)
        with pytest.raises(ReplicaError):
            fol.write(_rows(9), ids=_ids(9))
        fol.close(), lam.close()

    def test_kill_leader_promote_zero_acked_loss(self, tmp_path):
        """THE tentpole invariant: every acknowledged write survives a
        hard leader kill with an UNSHIPPED tail — promotion finishes
        replay from the dead leader's durable WAL."""
        root, lam = _leader(tmp_path)
        fol, end = _follower(root, tmp_path)
        ship = SegmentShipper(lam, chunk_bytes=4096)
        ship.attach(end)
        lam.write(_rows(1), ids=_ids(1))
        ship.pump()
        fol.drain()
        # acked but never shipped: the failover must recover these
        lam.write(_rows(2), ids=_ids(2))
        acked = _names(lam)
        lam.wal.crash()  # kill -9
        with fault.inject("replica.promote", kind="io_error", times=1):
            with pytest.raises(OSError):
                fol.promote(leader_wal_dir=str(root / "_wal"))
        term = fol.promote(leader_wal_dir=str(root / "_wal"))
        assert term == 1 and fol.term == 1
        assert _names(fol) == acked  # zero loss, nothing invented
        assert fol.metrics.counter_value("geomesa.replica.promotions") == 1
        # the promoted store accepts and logs writes
        fol.write(_rows(3), ids=_ids(3))
        assert len(fol.query("INCLUDE")) == len(acked) + 20
        # the fence is durable: a plain recover sees the term
        fol.store.wal.close()
        rec = LambdaStore.recover(
            root, type_name="t", wal_dir=fol.wal_dir,
        )
        assert rec.wal.term == 1
        assert len(rec.query("INCLUDE")) == len(acked) + 20
        rec.close()

    def test_stale_term_shipment_refused(self, tmp_path):
        """The deposed-leader case: after promotion, messages carrying a
        lower term are refused — no bytes applied, no marks taken."""
        root, lam = _leader(tmp_path)
        fol, end = _follower(root, tmp_path)
        ship = SegmentShipper(lam, chunk_bytes=4096)
        ship.attach(end)
        lam.write(_rows(1), ids=_ids(1))
        ship.pump()
        fol.drain()
        fol.promote()  # no disk catch-up needed: fully shipped
        assert fol.term == 1
        before = _names(fol)
        sizes = dict(fol._sizes)
        # the deposed leader (term 0) ships a late segment + mark
        stale_seg = {
            "m": "seg", "term": 0, "name": "wal-" + "0" * 20,
            "off": 0, "data": "QUJD", "sealed": False,
        }
        fol._handle(stale_seg)
        fol._handle({"m": "state", "term": 0, "horizon": 10 ** 6,
                     "wall_ms": 0.0, "segments": []})
        assert fol.metrics.counter_value("geomesa.replica.fenced") == 2
        assert _names(fol) == before and dict(fol._sizes) == sizes
        # the fence fault point is reachable (chaos kill-anywhere)
        with fault.inject("replica.fence", kind="io_error", times=1):
            with pytest.raises(OSError):
                fol._handle(stale_seg)
        fol.close(), lam.close()

    def test_apply_fault_then_resync_converges(self, tmp_path):
        """An io_error at the follower's apply point loses that chunk;
        the gap protocol (resync) re-converges on the next pumps."""
        root, lam = _leader(tmp_path)
        fol, end = _follower(root, tmp_path)
        ship = SegmentShipper(lam, chunk_bytes=1 << 20)
        ship.attach(end)
        lam.write(_rows(1), ids=_ids(1))
        ship.pump()
        with fault.inject("replica.apply", kind="io_error", times=1):
            with pytest.raises(OSError):
                fol.drain()
        lam.write(_rows(2), ids=_ids(2))
        ship.pump()
        fol.drain()  # gap detected -> resync requested
        ship.pump()  # re-ship from byte 0
        fol.drain()
        assert _names(fol) == _names(lam)
        fol.close(), lam.close()


# -- the chaos harness: leader + 2 followers, kill anywhere -----------------


def _replica_chaos_round(tmp_path, seconds, seed, rate=0.02):
    """Closed-loop leader ingest + shipping + two replaying followers
    under a seeded chaos schedule over replica.* AND stream.* points,
    ending in a hard mid-ingest leader kill and a follower promotion.
    Returns (oracle, attempted, promoted follower, other follower,
    spec)."""
    root, lam = _leader(tmp_path, n=200, seed=3, seg=8 << 10)
    fols, ends = [], []
    for name in ("f1", "f2"):
        fol, end = _follower(root, tmp_path, name=name)
        fols.append(fol), ends.append(end)
    ship = SegmentShipper(lam, chunk_bytes=4096, giveup_s=0.2)
    for end in ends:
        ship.attach(end)

    test_lock = threading.Lock()
    oracle: dict = {}     # id -> name: the ACKED state
    attempted: dict = {}  # id -> values whose ack never returned
    base = lam.cold.features("t")
    bn = np.asarray(base.columns["name"])
    for i, fid in enumerate(base.ids.tolist()):
        oracle[str(fid)] = str(bn[i])
    stop = threading.Event()
    errors: list = []
    counter = [0]
    rng = np.random.default_rng(seed)

    def writer():
        known = list(oracle)
        while not stop.is_set():
            k = int(rng.integers(1, 10))
            ids, rows, vals = [], [], []
            for _ in range(k):
                if rng.random() < 0.4:
                    fid = known[int(rng.integers(0, len(known)))]
                else:
                    counter[0] += 1
                    fid = f"w{counter[0]}"
                    known.append(fid)
                counter[0] += 1
                v = f"v{counter[0]}"
                x = float(rng.uniform(-50, 50))
                y = float(rng.uniform(-50, 50))
                ids.append(fid), vals.append(v)
                rows.append(
                    {"name": v, "dtg": T0, "geom": geo.Point(x, y)}
                )
            with test_lock:
                try:
                    lam.write(rows, ids=ids)
                except (fault.InjectedCrash, OSError, WalError):
                    # unacked (incl. every post-kill attempt)
                    for fid, v in zip(ids, vals):
                        attempted.setdefault(fid, set()).add(v)
                    continue
                for fid, v in zip(ids, vals):
                    oracle[fid] = v
            time.sleep(0.001)

    def pumper():
        while not stop.is_set():
            try:
                ship.pump()
            except (fault.InjectedCrash, OSError, ReplicaError, WalError):
                pass
            time.sleep(0.004)

    def applier(fol):
        def run():
            while not stop.is_set():
                try:
                    if not fol.poll():
                        time.sleep(0.002)
                except (fault.InjectedCrash, OSError, ReplicaError):
                    continue
        return run

    def reader():
        # bounded-staleness reads on both followers: StaleRead is a
        # legal answer under chaos, invented rows are not
        while not stop.is_set():
            for fol in fols:
                try:
                    fol.query("INCLUDE", max_staleness_ms=30_000)
                except (StaleRead, fault.InjectedCrash, OSError):
                    continue
                except Exception as e:  # a real bug
                    errors.append(("reader", repr(e)))
                    stop.set()
                    return
            time.sleep(0.005)

    threads = [
        threading.Thread(target=writer),
        threading.Thread(target=pumper),
        threading.Thread(target=applier(fols[0])),
        threading.Thread(target=applier(fols[1])),
        threading.Thread(target=reader),
    ]
    with fault.chaos(
        seed=seed, rate=rate,
        points="replica.*,stream.wal.*",
        kinds=("io_error", "latency", "crash"),
        delay_s=0.002,
    ) as spec:
        for t in threads:
            t.start()
        time.sleep(seconds * 0.7)
        lam.wal.crash()  # the mid-ingest leader kill
        time.sleep(seconds * 0.3)
        stop.set()
        for t in threads:
            t.join()
    assert not errors, errors[:3]
    assert spec.fired > 0, "the chaos schedule never fired — dead harness"
    term = fols[0].promote(leader_wal_dir=str(root / "_wal"))
    assert term >= 1
    return oracle, attempted, fols[0], fols[1], spec


def _assert_replica_invariants(oracle, attempted, promoted, lagging):
    got = _names(promoted)
    # 1. ZERO acknowledged-row loss on the promoted line
    missing = [fid for fid in oracle if fid not in got]
    assert not missing, f"acknowledged rows lost: {missing[:5]}"
    for fid, v in oracle.items():
        assert got[fid] == v or got[fid] in attempted.get(fid, ()), fid
    # 2. nothing invented: extras only from attempted (unacked) writes
    for fid, v in got.items():
        if fid not in oracle:
            assert v in attempted.get(fid, ()), fid
    # 3. a lagging follower may be behind but never invents rows either
    for fid, v in _names(lagging).items():
        assert (
            oracle.get(fid) == v
            or v in attempted.get(fid, ())
            or fid in oracle
        ), fid


class TestReplicaChaos:
    def test_replica_chaos_smoke(self, tmp_path):
        """Tier-1 confidence: a short fixed-seed leader+2-follower run
        with a mid-ingest kill (the slow soak repeats the kill)."""
        oracle, attempted, promoted, lagging, _spec = _replica_chaos_round(
            tmp_path, seconds=2.5, seed=47211
        )
        _assert_replica_invariants(oracle, attempted, promoted, lagging)
        promoted.close(), lagging.close()

    @pytest.mark.slow
    def test_replica_chaos_soak(self, tmp_path):
        """The acceptance run: >= 60 s of leader+2-follower rounds with
        REPEATED leader kills (one hard kill + promotion per round),
        zero acked-row loss and nothing invented after every failover.
        ``GEOMESA_TPU_CHAOS_SECONDS`` overrides for soak farms."""
        budget = float(os.environ.get("GEOMESA_TPU_CHAOS_SECONDS", 60.0))
        t0 = time.monotonic()
        kills = 0
        seed = int(os.environ.get("GEOMESA_TPU_CHAOS_SEED", 60042))
        while time.monotonic() - t0 < budget or kills < 2:
            oracle, attempted, promoted, lagging, spec = (
                _replica_chaos_round(
                    tmp_path / f"r{kills}", seconds=6.0,
                    seed=seed + kills,
                )
            )
            _assert_replica_invariants(
                oracle, attempted, promoted, lagging
            )
            assert spec.hits > 0
            promoted.close(), lagging.close()
            kills += 1
        assert kills >= 2  # repeated leader kills, not a single failover
