"""Multi-index union plans for cross-kind ORs (reference FilterSplitter
DNF options, FilterSplitter.scala:61-147): `bbox(...) OR attr = 'x'` runs
one scan per disjunct on its own index and dedup-unions the results,
instead of falling to a full host scan."""

import numpy as np
import pytest

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu.filter import ecql
from geomesa_tpu.planning.planner import QueryGuardError

SPEC = "name:String:index=true,age:Int,dtg:Date,*geom:Point:srid=4326"
N = 6000


@pytest.fixture(scope="module")
def ds():
    sft = FeatureType.from_spec("u", SPEC)
    store = DataStore()
    store.create_schema(sft)
    rng = np.random.default_rng(8)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    fc = FeatureCollection.from_columns(
        sft,
        [str(i) for i in range(N)],
        {
            "name": np.array([f"n{i % 23}" for i in range(N)]),
            "age": np.arange(N) % 80,
            "dtg": t0 + rng.integers(0, 30 * 86400_000, N),
            "geom": (rng.uniform(-60, 60, N), rng.uniform(-45, 45, N)),
        },
    )
    store.write("u", fc)
    return store


def brute(ds, q):
    fc = ds.features("u")
    mask = np.asarray(ecql.parse(q).evaluate(fc.batch))
    return sorted(fc.ids[mask].tolist())


class TestUnionPlans:
    def test_bbox_or_attribute(self, ds):
        q = "bbox(geom, -20, -15, 10, 10) OR name = 'n3'"
        plan = ds.planner.plan("u", q)
        assert plan.union is not None and len(plan.union) == 2
        assert plan.strategy.startswith("union(")
        got = sorted(ds.query("u", q).ids.tolist())
        assert got == brute(ds, q)
        assert len(got) > 0

    def test_dedup_overlapping_branches(self, ds):
        # many n5 rows also fall inside the box: union must not double-count
        q = "bbox(geom, -60, -45, 60, 45) OR name = 'n5'"
        out = ds.query("u", q)
        assert len(out.ids) == len(set(out.ids.tolist()))
        assert sorted(out.ids.tolist()) == brute(ds, q)

    def test_three_way_union_with_conjunctions(self, ds):
        q = (
            "(bbox(geom, -20, -15, 10, 10) AND dtg DURING "
            "2024-01-02T00:00:00Z/2024-01-12T00:00:00Z) "
            "OR name = 'n7' OR name = 'n11'"
        )
        plan = ds.planner.plan("u", q)
        assert plan.union is not None and len(plan.union) == 3
        assert sorted(ds.query("u", q).ids.tolist()) == brute(ds, q)

    def test_disjoint_branch_dropped(self, ds):
        # name='a' AND name='b' is unsatisfiable: only the bbox branch scans
        q = "bbox(geom, -20, -15, 10, 10) OR (name = 'a' AND name = 'b')"
        plan = ds.planner.plan("u", q)
        assert plan.union is None  # one live branch -> its single-index plan
        assert sorted(ds.query("u", q).ids.tolist()) == brute(ds, q)

    def test_all_branches_disjoint(self, ds):
        q = "(name = 'a' AND name = 'b') OR (name = 'c' AND name = 'd')"
        assert len(ds.query("u", q)) == 0

    def test_unindexable_disjunct_falls_back_to_full_scan(self, ds):
        # `age > 70` has no attribute index: a union would still need a
        # full scan for that branch, so the planner keeps one full scan
        q = "bbox(geom, -20, -15, 10, 10) OR age > 70"
        plan = ds.planner.plan("u", q)
        assert plan.union is None and plan.strategy == "full-scan"
        assert sorted(ds.query("u", q).ids.tolist()) == brute(ds, q)

    def test_guard_allows_union_blocks_full_scan(self, ds):
        ds.block_full_table_scans = True
        try:
            out = ds.query("u", "bbox(geom, -20, -15, 10, 10) OR name = 'n3'")
            assert len(out) > 0
            with pytest.raises(QueryGuardError):
                ds.query("u", "bbox(geom, -20, -15, 10, 10) OR age > 70")
        finally:
            ds.block_full_table_scans = False

    def test_not_pushdown(self, ds):
        # NOT(a AND b) -> NOT a OR NOT b; neither side indexable -> full
        # scan, but results stay exact
        q = "NOT (name = 'n1' AND age = 5)"
        assert sorted(ds.query("u", q).ids.tolist()) == brute(ds, q)

    def test_explain_shows_union(self, ds):
        text = ds.explain("u", "bbox(geom, -20, -15, 10, 10) OR name = 'n3'")
        assert "union(" in text

    def test_limit_applies_after_union(self, ds):
        q = "bbox(geom, -60, -45, 60, 45) OR name = 'n5'"
        out = ds.query("u", q, limit=7)
        assert len(out) == 7


def test_union_branches_under_seam_crossing_and():
    """Mixed-kind OR (time/attribute) ANDed with a seam-crossing bbox:
    union plans + antimeridian normalization must compose (caught
    divergent in a soak harness that lacked wrap semantics — the engine
    was right; this pins it)."""
    import numpy as np

    from geomesa_tpu.datastore import DataStore
    from geomesa_tpu.features import FeatureCollection
    from geomesa_tpu.sft import FeatureType

    rng = np.random.default_rng(5)
    sft = FeatureType.from_spec(
        "w", "code:Integer:index=true,dtg:Date,*geom:Point:srid=4326"
    )
    ds = DataStore(tile=64)
    ds.create_schema(sft)
    n = 4000
    t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
    x = rng.uniform(-180, 180, n)
    y = rng.uniform(-90, 90, n)
    t = t0 + rng.integers(0, 30 * 86400_000, n)
    code = rng.integers(0, 50, n)
    ds.write("w", FeatureCollection.from_columns(
        sft, [str(i) for i in range(n)],
        {"code": code.astype(np.int64), "dtg": t, "geom": (x, y)},
    ))
    lo = np.datetime64("2024-01-16", "ms").astype(np.int64)
    hi = np.datetime64("2024-01-20", "ms").astype(np.int64)
    expr = (
        "((dtg DURING 2024-01-16T00:00:00Z/2024-01-20T00:00:00Z) OR "
        "(code = 47)) AND bbox(geom, 131.7, -90, 191.7, 90)"
    )
    inner = ((t >= lo) & (t < hi)) | (code == 47)
    wrapped = inner & ((x >= 131.7) | (x <= 191.7 - 360.0))
    got = np.sort(np.asarray(ds.query("w", expr).ids, np.int64))
    np.testing.assert_array_equal(got, np.flatnonzero(wrapped))
    assert len(got) > 0
