"""Z2SFC / Z3SFC / XZ2SFC / XZ3SFC tests: round trips + query covering.

Modeled on the reference's Z3SFCTest / XZ2SFCTest
(/root/reference/geomesa-z3/src/test/scala/.../curve/).
"""

import numpy as np
import pytest

from geomesa_tpu.curve import XZ2SFC, XZ3SFC, Z2SFC, Z3SFC
from geomesa_tpu.curve.binnedtime import MAX_OFFSET, TimePeriod


def covers(ranges, codes):
    """Vector: is each code inside some range?"""
    if not ranges:
        return np.zeros(len(codes), dtype=bool)
    lo = np.array([r.lower for r in ranges])
    hi = np.array([r.upper for r in ranges])
    codes = np.asarray(codes, dtype=np.int64)
    idx = np.searchsorted(lo, codes, side="right") - 1
    return (idx >= 0) & (codes <= hi[np.clip(idx, 0, len(hi) - 1)])


class TestZ2SFC:
    def test_invert_roundtrip(self):
        sfc = Z2SFC()
        rng = np.random.default_rng(0)
        lon = rng.uniform(-180, 180, 1000)
        lat = rng.uniform(-90, 90, 1000)
        z = sfc.index(lon, lat)
        lon2, lat2 = sfc.invert(z)
        # 31 bits over 360 degrees -> ~1.7e-7 degree resolution
        assert np.allclose(lon, lon2, atol=1e-6)
        assert np.allclose(lat, lat2, atol=1e-6)

    def test_query_covering(self):
        sfc = Z2SFC()
        rng = np.random.default_rng(1)
        lon = rng.uniform(-180, 180, 5000)
        lat = rng.uniform(-90, 90, 5000)
        z = sfc.index(lon, lat).astype(np.int64)
        bbox = (-10.0, -10.0, 10.0, 10.0)
        ranges = sfc.ranges([bbox])
        inside = (lon >= bbox[0]) & (lat >= bbox[1]) & (lon <= bbox[2]) & (lat <= bbox[3])
        cov = covers(ranges, z)
        assert np.all(cov[inside]), "every point inside the bbox must be covered"


class TestZ3SFC:
    def test_invert_roundtrip(self):
        sfc = Z3SFC.for_period(TimePeriod.WEEK)
        rng = np.random.default_rng(2)
        lon = rng.uniform(-180, 180, 1000)
        lat = rng.uniform(-90, 90, 1000)
        t = rng.uniform(0, MAX_OFFSET[TimePeriod.WEEK], 1000)
        z = sfc.index(lon, lat, t)
        lon2, lat2, t2 = sfc.invert(z)
        assert np.allclose(lon, lon2, atol=2e-4)
        assert np.allclose(lat, lat2, atol=1e-4)
        assert np.allclose(t, t2, atol=MAX_OFFSET[TimePeriod.WEEK] / (1 << 21) + 1)

    def test_query_covering(self):
        sfc = Z3SFC.for_period(TimePeriod.WEEK)
        rng = np.random.default_rng(3)
        n = 5000
        lon = rng.uniform(-180, 180, n)
        lat = rng.uniform(-90, 90, n)
        t = rng.uniform(0, MAX_OFFSET[TimePeriod.WEEK], n)
        z = sfc.index(lon, lat, t).astype(np.int64)
        bbox = (30.0, 40.0, 45.0, 50.0)
        twin = (100_000.0, 400_000.0)
        ranges = sfc.ranges([bbox], [twin])
        inside = (
            (lon >= bbox[0]) & (lat >= bbox[1]) & (lon <= bbox[2]) & (lat <= bbox[3])
            & (t >= twin[0]) & (t <= twin[1])
        )
        cov = covers(ranges, z)
        assert np.all(cov[inside])

    def test_period_singletons(self):
        assert Z3SFC.for_period("week") is Z3SFC.for_period(TimePeriod.WEEK)
        assert Z3SFC.for_period("day") is not Z3SFC.for_period("week")


class TestXZ2SFC:
    def test_query_covering_random_boxes(self):
        sfc = XZ2SFC.for_precision(12)
        rng = np.random.default_rng(4)
        n = 2000
        # random small boxes (elements)
        cx = rng.uniform(-170, 170, n)
        cy = rng.uniform(-80, 80, n)
        w = rng.uniform(0, 5, n)
        h = rng.uniform(0, 5, n)
        xmin, xmax = cx - w / 2, cx + w / 2
        ymin, ymax = cy - h / 2, cy + h / 2
        codes = sfc.index(xmin, ymin, xmax, ymax).astype(np.int64)
        q = (-20.0, -20.0, 25.0, 30.0)
        ranges = sfc.ranges([q])
        intersects = (xmin <= q[2]) & (xmax >= q[0]) & (ymin <= q[3]) & (ymax >= q[1])
        cov = covers(ranges, codes)
        missed = intersects & ~cov
        assert not missed.any(), f"missed {int(missed.sum())} intersecting elements"

    def test_points_as_degenerate_boxes(self):
        sfc = XZ2SFC.for_precision(12)
        rng = np.random.default_rng(5)
        x = rng.uniform(-180, 180, 1000)
        y = rng.uniform(-90, 90, 1000)
        codes = sfc.index(x, y, x, y).astype(np.int64)
        q = (0.0, 0.0, 50.0, 50.0)
        ranges = sfc.ranges([q])
        inside = (x >= q[0]) & (x <= q[2]) & (y >= q[1]) & (y <= q[3])
        assert np.all(covers(ranges, codes)[inside])

    def test_contained_ranges_do_not_need_filtering(self):
        sfc = XZ2SFC.for_precision(12)
        rng = np.random.default_rng(6)
        n = 3000
        cx = rng.uniform(-170, 170, n)
        cy = rng.uniform(-80, 80, n)
        w = rng.uniform(0, 3, n)
        xmin, xmax = cx - w / 2, cx + w / 2
        ymin, ymax = cy - w / 2, cy + w / 2
        codes = sfc.index(xmin, ymin, xmax, ymax).astype(np.int64)
        q = (-40.0, -40.0, 40.0, 40.0)
        contained_ranges = [r for r in sfc.ranges([q]) if r.contained]
        cov = covers(contained_ranges, codes)
        intersects = (xmin <= q[2]) & (xmax >= q[0]) & (ymin <= q[3]) & (ymax >= q[1])
        # everything in a contained range must genuinely intersect the query
        assert np.all(intersects[cov])


class TestXZ3SFC:
    def test_query_covering(self):
        sfc = XZ3SFC.for_period(TimePeriod.WEEK)
        rng = np.random.default_rng(7)
        n = 1500
        cx = rng.uniform(-170, 170, n)
        cy = rng.uniform(-80, 80, n)
        w = rng.uniform(0, 4, n)
        t0 = rng.uniform(0, 500_000, n)
        dt = rng.uniform(0, 50_000, n)
        xmin, xmax = cx - w / 2, cx + w / 2
        ymin, ymax = cy - w / 2, cy + w / 2
        tmax = np.minimum(t0 + dt, MAX_OFFSET[TimePeriod.WEEK])
        codes = sfc.index(xmin, ymin, t0, xmax, ymax, tmax).astype(np.int64)
        q = (-30.0, -30.0, 100_000.0, 30.0, 30.0, 300_000.0)
        ranges = sfc.ranges([q])
        intersects = (
            (xmin <= q[3]) & (xmax >= q[0]) & (ymin <= q[4]) & (ymax >= q[1])
            & (t0 <= q[5]) & (tmax >= q[2])
        )
        cov = covers(ranges, codes)
        missed = intersects & ~cov
        assert not missed.any(), f"missed {int(missed.sum())}"
