"""PodStore (docs/distributed.md): per-host WAL / hot tier / standing
shards behind one routed facade — and the chaos matrix.

The pinned contracts (ISSUE 20):

- **equivalence** — routed writes, queries, counts, bulk loads and the
  UNION of per-host standing alerts all equal a single-process
  ``LambdaStore`` fed the same batches;
- **zero acknowledged loss** — with ``sync="always"`` an acked write is
  durable on its owning host: kill ANY single host (``kill -9``
  surface: hot tier gone, unsynced WAL buffer dropped) — including MID
  FLUSH, crashed between its WAL and its cold publish — and
  ``rejoin``'s per-host WAL replay reproduces the never-crashed pod
  bit-for-bit while every other host keeps serving untouched;
- **per-host fault seams** — ``pod.wal.route`` faults surface to the
  writer without corrupting earlier hosts' acks (retry converges), and
  a ``pod.wal.replay`` crash leaves the host down and cleanly
  re-joinable.

Tier-1 runs the single-host smoke of the kill matrix; the full
host x fault-point soak is @slow.
"""

import numpy as np
import pytest

from geomesa_tpu import fault
from geomesa_tpu import geometry as geo
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.pod import PodStore, make_host_group
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.streaming.standing import Subscription
from geomesa_tpu.streaming.store import LambdaStore
from geomesa_tpu.streaming.wal import WalConfig

SPEC = "dtg:Date,*geom:Point:srid=4326"
T0 = int(np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64))
HOSTS = 4


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    fault.injector().reset()


@pytest.fixture(scope="module")
def group():
    return make_host_group(hosts=HOSTS, devices_per_host=2, driver="sim")


def _sft():
    return FeatureType.from_spec("pd", SPEC)


def _rows(n, seed):
    r = np.random.default_rng(seed)
    return [
        {"dtg": int(T0 + r.integers(0, 10 * 86400_000)),
         "geom": geo.Point(float(r.uniform(-60, 60)), float(r.uniform(-30, 30)))}
        for _ in range(n)
    ]


def _subs():
    return [
        Subscription("fence", "geofence", geom=geo.Polygon(
            [(-20.0, -20.0), (20.0, -20.0), (20.0, 20.0), (-20.0, 20.0)]
        )),
        Subscription("near", "proximity",
                     points=np.array([[5.0, 5.0], [-40.0, 10.0]]),
                     distance_m=400_000.0),
    ]


def _pod(group, root=None, sync="always"):
    return PodStore(
        _sft(), group,
        root=None if root is None else str(root),
        wal_config=WalConfig(sync=sync),
    )


def _referee():
    cold = DataStore()
    cold.create_schema(_sft())
    return LambdaStore(cold, "pd")


def _alert_set(alerts):
    return sorted((a["sub"], a["kind"], a["id"]) for a in alerts)


def _ids(fc):
    return sorted(np.asarray(fc.ids, dtype=str).tolist())


class TestEquivalence:
    def test_write_query_count_alerts_match_single_process(self, group):
        pod, ref = _pod(group), _referee()
        try:
            for s in _subs():
                pod.subscribe(s)
                ref.subscribe(Subscription.from_record(s.to_record()))
            b0, ids0 = _rows(300, 1), [f"f{i}" for i in range(300)]
            b1, ids1 = _rows(200, 2), [f"g{i}" for i in range(200)]
            assert pod.write(b0, ids0) == ref.write(b0, ids0) == 300
            assert pod.write(b1, ids1) == ref.write(b1, ids1) == 200
            pa = _alert_set(pod.drain_alerts())
            ra = _alert_set(ref.standing().alerts.drain())
            assert pa == ra and len(pa) > 0
            assert {k for _, k, _ in pa} == {"geofence", "proximity"}
            assert pod.count() == ref.count() == 500
            assert _ids(pod.query()) == _ids(ref.query())
            # deletes route to the same owners the upserts did
            dead = [f"f{i}" for i in range(0, 300, 3)]
            assert pod.delete(dead) == ref.delete(dead) == 100
            assert pod.count() == ref.count() == 400
            assert _ids(pod.query()) == _ids(ref.query())
            # unsubscribe reaches every shard: no further fence alerts
            assert pod.unsubscribe("fence") and ref.unsubscribe("fence")
            b2, ids2 = _rows(100, 3), [f"h{i}" for i in range(100)]
            pod.write(b2, ids2), ref.write(b2, ids2)
            pa2 = _alert_set(pod.drain_alerts())
            assert pa2 == _alert_set(ref.standing().alerts.drain())
            assert all(s != "fence" for s, _, _ in pa2)
        finally:
            pod.close(), ref.close()

    def test_auto_ids_are_pod_unique(self, group):
        pod = _pod(group)
        try:
            assert pod.write(_rows(50, 4)) == 50
            assert pod.write(_rows(50, 5)) == 50
            assert pod.count() == 100
            ids = _ids(pod.query())
            assert len(set(ids)) == 100
            assert all(i.startswith("pod-") for i in ids)
        finally:
            pod.close()

    def test_ownership_partitions_rows(self, group):
        pod = _pod(group)
        try:
            ids = [f"f{i}" for i in range(200)]
            pod.write(_rows(200, 6), ids)
            per_host = [pod.stores[h].count() for h in range(HOSTS)]
            assert sum(per_host) == 200
            assert all(c > 0 for c in per_host)  # crc32 spreads the ids
            for h in range(HOSTS):
                owned = _ids(pod.stores[h].query())
                assert all(pod.owner(i) == h for i in owned)
        finally:
            pod.close()

    def test_bulk_load_matches_routed_writes(self, group):
        pod, ref = _pod(group), _referee()
        try:
            rng = np.random.default_rng(8)
            n = 400
            fc = FeatureCollection.from_columns(
                _sft(), [f"bl{i}" for i in range(n)],
                {"dtg": T0 + rng.integers(0, 10 * 86400_000, n),
                 "geom": (rng.uniform(-60, 60, n), rng.uniform(-30, 30, n))},
            )
            results = pod.bulk_load(fc)
            assert sum(r.written for r in results if r is not None) == n
            ref.cold.write("pd", fc)
            assert pod.count() == ref.count() == n
            assert _ids(pod.query()) == _ids(ref.query())
        finally:
            pod.close(), ref.close()


def _kill_mid_flush_and_verify(group, tmp_path, victim, point):
    """The chaos matrix body: referee pod (never crashed) vs a pod whose
    ``victim`` host crashes at ``point`` mid-flush, is killed, and
    rejoins via per-host WAL replay. Everything acknowledged must match
    the referee bit-for-bit afterwards."""
    pod = _pod(group, root=tmp_path / "crash")
    ref = _pod(group, root=tmp_path / "ref")
    try:
        for s in _subs():
            pod.subscribe(s)
            ref.subscribe(Subscription.from_record(s.to_record()))
        b0, ids0 = _rows(300, 10), [f"f{i}" for i in range(300)]
        pod.write(b0, ids0), ref.write(b0, ids0)
        # both pods consume the first batch's alerts (delivered = gone);
        # the checkpoint then anchors replay after this point
        assert _alert_set(pod.drain_alerts()) == _alert_set(ref.drain_alerts())
        pod.flush(), ref.flush()
        pod.checkpoint(), ref.checkpoint()
        b1, ids1 = _rows(160, 11), [f"g{i}" for i in range(160)]
        assert pod.write(b1, ids1) == ref.write(b1, ids1) == 160  # ACKED
        ref.flush()
        # the victim crashes INSIDE its own flush — after the WAL ack,
        # between micro-chunk stages / before the hot->cold publish
        with fault.inject(point, kind="crash", times=1):
            with pytest.raises(fault.InjectedCrash):
                pod.stores[victim].flush()
        pod.kill(victim)
        with pytest.raises(RuntimeError, match="down"):
            pod.count()
        # the OTHER hosts never noticed: they still serve their shards
        for h in range(HOSTS):
            if h != victim:
                assert _ids(pod.stores[h].query()) == _ids(ref.stores[h].query())
        pod.rejoin(victim)
        # bit-for-bit with the never-crashed referee: counts, ids, the
        # crashed host's own shard, and the replayed standing alerts
        assert pod.count() == ref.count() == 460 - 0
        assert _ids(pod.query()) == _ids(ref.query())
        assert _ids(pod.stores[victim].query()) == _ids(ref.stores[victim].query())
        pa, ra = pod.drain_alerts(), ref.drain_alerts()
        # alerts are at-most-once (docs/standing.md): the victim's
        # undrained in-memory queue died with it — exactly a
        # single-process crash's semantics — while every OTHER host's
        # alerts still match the referee's for the ids it owns
        assert _alert_set([a for a in pa if pod.owner(a["id"]) != victim]) \
            == _alert_set([a for a in ra if ref.owner(a["id"]) != victim])
        assert all(pod.owner(a["id"]) != victim for a in pa)
        # and the recovered host keeps serving: registrations survived
        b2 = _rows(80, 12)
        ids2 = [f"k{i}" for i in range(80)]
        assert pod.write(b2, ids2) == ref.write(b2, ids2) == 80
        assert _alert_set(pod.drain_alerts()) == _alert_set(ref.drain_alerts())
        assert _ids(pod.query()) == _ids(ref.query())
    finally:
        pod.close(), ref.close()


class TestKillMatrix:
    def test_kill_one_host_mid_flush_smoke(self, group, tmp_path):
        """Tier-1 smoke of the chaos matrix: one victim, crash at the
        hot->cold publish."""
        _kill_mid_flush_and_verify(group, tmp_path, 2, "streaming.persist")

    @pytest.mark.slow
    @pytest.mark.parametrize("victim", range(HOSTS))
    @pytest.mark.parametrize(
        "point", ["stream.flush.keys", "streaming.persist", "streaming.evict"]
    )
    def test_kill_any_host_any_stage_soak(self, group, tmp_path, victim, point):
        """The full matrix: ANY single host, crashed at every flush
        stage, recovers bit-for-bit (slow soak)."""
        _kill_mid_flush_and_verify(group, tmp_path, victim, point)

    def test_acked_rows_survive_kill_without_any_flush(self, group, tmp_path):
        """Zero acknowledged loss, pure-WAL edition: nothing was ever
        flushed, the hot tier dies with the host, and replay alone
        restores every acked row."""
        pod = _pod(group, root=tmp_path / "p")
        try:
            ids = [f"f{i}" for i in range(240)]
            assert pod.write(_rows(240, 13), ids) == 240  # acked
            before = _ids(pod.query())
            pod.kill(1)
            pod.rejoin(1)
            assert _ids(pod.query()) == before
            assert pod.count() == 240
        finally:
            pod.close()


class TestPodWalFaultPoints:
    def test_route_fault_leaves_earlier_acks_intact(self, group, tmp_path):
        """An IO error on the pod.wal.route hop fails the write AT a
        host boundary: hosts acked before it keep their slices (per-host
        ack contract), and retrying the same batch converges (upsert
        idempotence) — no loss, no duplicates."""
        pod = _pod(group, root=tmp_path / "p")
        try:
            ids = [f"f{i}" for i in range(120)]
            rows = _rows(120, 14)
            with fault.inject("pod.wal.route", kind="io_error", after=1,
                              times=1):
                with pytest.raises(OSError):
                    pod.write(rows, ids)
            partial = pod.count()
            assert 0 < partial < 120  # first host acked, later ones not
            assert pod.write(rows, ids) == 120  # retry converges
            assert pod.count() == 120
            assert _ids(pod.query()) == sorted(ids)
        finally:
            pod.close()

    def test_replay_crash_is_retryable(self, group, tmp_path):
        """A crash at pod.wal.replay leaves the host DOWN (not half
        recovered): a second rejoin replays clean."""
        pod = _pod(group, root=tmp_path / "p")
        try:
            ids = [f"f{i}" for i in range(100)]
            pod.write(_rows(100, 15), ids)
            before = _ids(pod.query())
            pod.kill(3)
            with fault.inject("pod.wal.replay", kind="crash", times=1):
                with pytest.raises(fault.InjectedCrash):
                    pod.rejoin(3)
            with pytest.raises(RuntimeError, match="down"):
                pod.count()
            pod.rejoin(3)
            assert _ids(pod.query()) == before
        finally:
            pod.close()

    def test_rejoin_requires_down_host(self, group, tmp_path):
        pod = _pod(group, root=tmp_path / "p")
        try:
            with pytest.raises(RuntimeError, match="not down"):
                pod.rejoin(0)
        finally:
            pod.close()

    def test_checkpoint_requires_root(self, group):
        pod = _pod(group)
        try:
            with pytest.raises(ValueError, match="root"):
                pod.checkpoint()
        finally:
            pod.close()
