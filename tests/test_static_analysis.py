"""The geomesa-lint suite is a tier-1 invariant (docs/analysis.md).

Three layers:

- **the tree is clean**: every shipped rule over geomesa_tpu/ +
  scripts/ + docs/*.md yields zero findings WITHOUT baseline help, and
  the checked-in baseline is empty (violations get fixed, not
  suppressed) — this is what makes the analyzer a ratchet;
- **the rules have teeth**: per-rule known-bad/known-good fixtures
  (tests/fixtures/analysis/) replay the defects that motivated each
  family — the PR 5 fused E-bucket grouping-key bug, the pre-PR 3
  unlocked MetricsRegistry mutation, an annotated scheduler queue
  mutated outside its condition — and each must be caught;
- **the gate convention**: scripts/check.py exits 0/1/2 exactly like
  scripts/bench_gate.py (0 clean, 1 findings, 2 unusable input), so CI
  treats both gates alike.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time

import pytest

from geomesa_tpu import analysis
from geomesa_tpu.analysis.core import (
    Project,
    default_baseline_path,
    load_baseline,
    run_rules,
)

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
FIXDIR = "tests/fixtures/analysis"


def _render(findings):
    return "\n".join(f.render() for f in findings)


# scope-sensitive fixtures are staged under SYNTHETIC in-scope paths
# (Project.add_file with text=) so the shipped rule scopes stay
# production-only — the kernel rules scan geomesa_tpu/scan|curve/, the
# lock-inference rule scans serving/cache/ingest/metrics
_SYNTHETIC_PATHS = {
    "kernel_bad.py": "geomesa_tpu/scan/_fixture_kernel_bad.py",
    "locks_bad_registry.py": "geomesa_tpu/serving/_fixture_locks_bad_registry.py",
    # the unregistered-lock direction needs an ENFORCED scope (the
    # concurrent tiers require a LOCKS registry entry)
    "race_bad_unregistered.py": "geomesa_tpu/streaming/_fixture_race_unregistered.py",
}


def _fixture_path(name: str) -> str:
    return _SYNTHETIC_PATHS.get(name, f"{FIXDIR}/{name}")


@pytest.fixture(scope="module")
def fixture_result():
    """One analysis run over the repo PLUS every rule fixture."""
    project = Project.load(ROOT)
    for fn in sorted(os.listdir(os.path.join(ROOT, FIXDIR))):
        if fn.endswith(".py"):
            src = open(os.path.join(ROOT, FIXDIR, fn)).read()
            project.add_file(_fixture_path(fn), text=src)
    return run_rules(project, analysis.ALL_RULES, baseline=set())


def _at(result, path, rule=None):
    return [
        f for f in result.findings
        if f.path == _fixture_path(path)
        and (rule is None or f.rule_id == rule)
    ]


# -- layer 1: the tree is clean ------------------------------------------


def test_repo_is_lint_clean_and_fast():
    t0 = time.perf_counter()
    result = analysis.run(ROOT, baseline=set())  # no suppression help
    dt = time.perf_counter() - t0
    assert result.clean, f"new lint findings:\n{_render(result.findings)}"
    # acceptance bound: a full-repo run fits CI comfortably
    assert dt < 10.0, f"analysis took {dt:.1f}s (budget 10s)"


def test_checked_in_baseline_is_empty():
    keys = load_baseline(default_baseline_path(ROOT))
    assert keys == set(), (
        "the shipped suppression baseline must stay empty — fix "
        f"violations instead of suppressing: {sorted(keys)}"
    )


def test_rule_ids_unique_and_well_formed():
    ids = [r.id for r in analysis.ALL_RULES]
    assert len(ids) == len(set(ids)), ids
    for r in analysis.ALL_RULES:
        assert r.id and r.id == r.id.lower() and " " not in r.id, r.id
        assert r.description, r.id
        assert r.fix_hint, r.id


# -- layer 2: the rules have teeth (fixtures) ----------------------------


def test_pr5_e_bucket_grouping_key_bug_is_caught(fixture_result):
    bad = _at(fixture_result, "fused_bad_pr5.py", "fused-key-dimension")
    assert len(bad) == 1, _render(bad)
    assert "fused_e_bucket" in bad[0].message
    assert "scan_submit_many" in bad[0].message
    # the hardened key is silent
    assert _at(fixture_result, "fused_good.py") == []


def test_fold_side_bucket_ladder_is_caught(fixture_result):
    """Round 11: the rule pattern widened to fold_<dim>_bucket — a
    future fold-operand ladder omitted from the grouping key is the
    same defect class as the PR 5 E-bucket bug."""
    bad = _at(fixture_result, "fold_bad_ladder.py", "fused-key-dimension")
    assert len(bad) == 1, _render(bad)
    assert "fold_s_bucket" in bad[0].message


def test_unlocked_metrics_registry_mutation_is_caught(fixture_result):
    bad = _at(fixture_result, "locks_bad_registry.py", "lock-guarded-mutation")
    assert len(bad) == 1, _render(bad)
    assert "counters" in bad[0].message
    assert "counter()" in bad[0].message
    assert "inferred" in bad[0].message  # inference mode, no annotation


def test_inherited_lock_annotation_still_enforced(fixture_result):
    """A guarded-by annotation is enforced even when the lock lives in
    a base class (no lock assignment visible in the annotated class)."""
    bad = _at(
        fixture_result, "locks_bad_inherited.py", "lock-guarded-mutation"
    )
    assert len(bad) == 1, _render(bad)
    assert "_items" in bad[0].message and "add" in bad[0].message
    # and no bad-annotation noise for the undetectable inherited lock
    assert all("annotation" not in f.symbol for f in bad)


def test_scheduler_guarded_by_mutation_is_caught(fixture_result):
    bad = _at(
        fixture_result, "locks_bad_scheduler.py", "lock-guarded-mutation"
    )
    assert len(bad) == 1, _render(bad)
    assert "_queue" in bad[0].message and "submit" in bad[0].message
    assert "guarded-by" in bad[0].message  # explicit-annotation mode
    # the disciplined twin (with *_locked and holds-lock escapes) passes
    assert _at(fixture_result, "locks_good.py") == []


def test_lock_order_cycle_is_caught(fixture_result):
    """geomesa-race: the A->B / B->A inversion is a cycle finding plus
    a rank violation on the inverted edge."""
    bad = _at(fixture_result, "race_bad_order.py", "lock-order-cycle")
    cycles = [f for f in bad if f.symbol.startswith("cycle:")]
    ranks = [f for f in bad if f.symbol.startswith("rank:")]
    assert len(cycles) == 1, _render(bad)
    assert "RaceyLedger._hot_lock" in cycles[0].message
    assert "RaceyLedger._audit_lock" in cycles[0].message
    assert "deadlock" in cycles[0].message
    assert len(ranks) == 1, _render(bad)
    assert "rank 19" in ranks[0].message and "rank 11" in ranks[0].message


def test_unregistered_concurrent_tier_lock_is_caught(fixture_result):
    """A lock constructed in an enforced scope (the concurrent tiers)
    without a LOCKS registry entry has no declared rank — the finding
    class the production registry in analysis/lockmodel.py closed."""
    bad = _at(
        fixture_result, "race_bad_unregistered.py", "lock-order-cycle"
    )
    assert len(bad) == 1, _render(bad)
    assert "UnrankedBuffer._buf_lock" in bad[0].message
    assert "no LOCKS registry entry" in bad[0].message


def test_pr9_checkpoint_cover_race_is_caught(fixture_result):
    """The PR 9 checkpoint-cover-before-drain race replays as a
    must-fail fixture (the E-bucket convention): the stale pending-set
    write-back is a check-then-act finding."""
    bad = _at(
        fixture_result, "race_bad_pr9_checkpoint.py",
        "atomicity-check-then-act",
    )
    assert len(bad) == 1, _render(bad)
    assert "_pending" in bad[0].message
    assert "checkpoint" in bad[0].message
    assert "without re-reading" in bad[0].message


def test_pr11_take_staged_race_is_caught(fixture_result):
    """The PR 11 _take_staged write-back race replays the same way:
    filtered-snapshot write-back without re-reading the staged list."""
    bad = _at(
        fixture_result, "race_bad_pr11_takestaged.py",
        "atomicity-check-then-act",
    )
    assert len(bad) == 1, _render(bad)
    assert "_staged" in bad[0].message and "take" in bad[0].message


def test_blocking_under_hot_lock_is_caught(fixture_result):
    """fsync + Future.result under an inline-annotated hot lock are the
    PR 8 reader-stall class (and the WAL _rotate fix this PR shipped)."""
    bad = _at(fixture_result, "race_bad_blocking.py", "blocking-under-lock")
    assert len(bad) == 2, _render(bad)
    kinds = {f.message.split(" call ")[0] for f in bad}
    assert kinds == {"fsync", "Future.result"}, kinds
    for f in bad:
        assert "HotTier._lock" in f.message


def test_guarded_escape_is_caught(fixture_result):
    """A guarded container returned bare / stored into an unguarded
    attribute is the adopted-row-dict aliasing class; copies and
    swap-and-drain stay legal."""
    bad = _at(fixture_result, "race_bad_escape.py", "guarded-escape")
    assert len(bad) == 2, _render(bad)
    symbols = {f.symbol for f in bad}
    assert symbols == {
        "LeakyCache.rows._rows:return", "LeakyCache.publish._rows:store",
    }, symbols


def test_race_good_twin_is_silent(fixture_result):
    """The disciplined twin exercises every rule's good path: rank-
    increasing order, one-hold check-then-act, blocking outside the
    lock, copy/swap escapes — zero geomesa-race findings."""
    for rule in ("lock-order-cycle", "atomicity-check-then-act",
                 "blocking-under-lock", "guarded-escape"):
        assert _at(fixture_result, "race_good.py", rule) == [], rule


def test_lock_registry_hygiene():
    """LOCKS registry invariants: Class.attr names, unique strictly
    ordered ranks... (rank ties would make the order a partial one),
    every entry discovered in the tree with a matching witness name,
    and every declared edge rank-increasing."""
    from geomesa_tpu.analysis.core import Project
    from geomesa_tpu.analysis.lockmodel import (
        DECLARED_EDGES, LOCKS, LockModel,
    )

    assert len(LOCKS) >= 12
    ranks = [d.rank for d in LOCKS.values()]
    assert len(ranks) == len(set(ranks)), "ranks must be unique"
    for name, d in LOCKS.items():
        assert name == d.name and "." in name, name
        assert d.doc, name
    model = LockModel.of(Project.load(ROOT))
    for name in LOCKS:
        assert name in model.sites, f"{name} has no construction site"
        assert model.sites[name].witness_name == name, name
    for a, b, why in DECLARED_EDGES:
        assert a in LOCKS and b in LOCKS, (a, b)
        assert LOCKS[a].rank < LOCKS[b].rank, (a, b)
        assert why, (a, b)


def test_static_model_edges_are_rank_consistent():
    """The production acquisition graph (AST-derived + declared) is
    acyclic and every ranked edge strictly increases — the invariant
    the lock-order-cycle rule enforces at zero findings."""
    from geomesa_tpu.analysis.core import Project
    from geomesa_tpu.analysis.lockmodel import LockModel

    model = LockModel.of(Project.load(ROOT))
    assert model.cycles() == []
    # the model must actually SEE the load-bearing nesting, not be
    # vacuously clean
    edges = model.predicted_edges()
    assert ("WriteAheadLog._sync_lock", "WriteAheadLog._lock") in edges
    assert (
        "StreamingFeatureCache._lock", "GenerationTracker._lock"
    ) in edges
    assert ("ResultCache._lock", "GenerationTracker._lock") in edges
    for a, b in edges:
        ra, rb = model.rank_of(a), model.rank_of(b)
        if ra is not None and rb is not None:
            assert ra < rb, (a, b)


def test_undeclared_knob_literal_is_caught(fixture_result):
    bad = _at(fixture_result, "knob_bad.py", "knob-undeclared")
    assert len(bad) == 1, _render(bad)
    assert "geomesa.scan.rangs.target" in bad[0].message  # the typo
    # the correctly spelled neighbor resolved against conf.py


def test_metric_convention_and_type_conflict_are_caught(fixture_result):
    conv = _at(fixture_result, "metric_bad.py", "metric-convention")
    assert len(conv) == 1 and "geomesa.Fixture-Area.hits" in conv[0].message
    dup = _at(fixture_result, "metric_bad.py", "metric-type-conflict")
    assert len(dup) == 1 and "geomesa.fixture.depth" in dup[0].message
    assert "counter" in dup[0].message and "gauge" in dup[0].message


def test_unregistered_histogram_is_caught(fixture_result):
    """ISSUE 13 must-fail: the observe()/histogram_quantile() instrument
    methods are registry extraction sites, so a histogram outside the
    naming registry fails metric-convention (both the write AND read
    sites), and a histogram/counter name collision fails
    metric-type-conflict."""
    conv = _at(fixture_result, "hist_bad.py", "metric-convention")
    assert len(conv) == 2, _render(conv)  # observe + histogram_quantile
    assert all("geomesa.Fixture-Hist.latency" in f.message for f in conv)
    dup = _at(fixture_result, "hist_bad.py", "metric-type-conflict")
    assert len(dup) == 1 and "geomesa.fixture.wait" in dup[0].message
    assert "histogram" in dup[0].message and "counter" in dup[0].message


def test_kernel_purity_hazards_are_caught(fixture_result):
    coerce = _at(fixture_result, "kernel_bad.py", "kernel-traced-coercion")
    # float(x) only: neither int(n_pad) (tuple static form) nor the
    # scalar-string static_argnames twin may be flagged
    assert len(coerce) == 1, _render(coerce)
    assert "float()" in coerce[0].message and "'x'" in coerce[0].message
    assert "bad_kernel" in coerce[0].message
    shape = _at(fixture_result, "kernel_bad.py", "kernel-dynamic-shape")
    assert len(shape) == 1 and "nonzero" in shape[0].message
    # baseline keys stay line-free (the suppression-stability contract)
    for f in coerce + shape:
        assert str(f.line) not in f.key, f.key


def test_unregistered_fault_point_is_caught(fixture_result):
    """A typo'd fault-point literal (the vacuous-crash-test failure
    mode) is flagged; registered points and non-literal names pass."""
    bad = _at(fixture_result, "fault_bad.py", "fault-point-unknown")
    assert len(bad) == 1, _render(bad)
    assert "streem.wal.append" in bad[0].message
    assert _at(fixture_result, "fault_good.py") == []


def test_fault_point_registry_matches_kinds():
    """Registry hygiene: FAULT_POINTS names are dotted, lowercase, and
    every one resolves to a real code site in the clean-tree run (the
    unreached/unexercised directions of the rule)."""
    from geomesa_tpu.analysis.registries import FAULT_POINTS

    assert len(FAULT_POINTS) >= 25
    for name, doc in FAULT_POINTS.items():
        assert "." in name and name == name.lower() and " " not in name, name
        assert doc, name


def test_unregistered_controller_spec_is_caught(fixture_result):
    """ISSUE 19 must-fail: one ControllerSpec trips every direction the
    controller-registry rule checks — unregistered name, undeclared
    knob, inverted bounds, unemitted objective — while the disciplined
    twin (mirroring the shipped derive controller) stays silent."""
    bad = _at(fixture_result, "controller_bad.py", "controller-registry")
    symbols = {f.symbol for f in bad}
    assert symbols == {
        "bogus_controller", "knob:bogus_controller",
        "bounds:bogus_controller", "objective:bogus_controller",
    }, _render(bad)
    for f in bad:
        assert "bogus_controller" in f.message


def test_controller_registry_matches_specs():
    """Registry hygiene: CONTROLLERS names are snake_case with docs,
    and the shipped spec tuple backs every entry exactly (the unbacked
    direction of the rule at zero findings on the clean tree)."""
    from geomesa_tpu.analysis.registries import CONTROLLERS
    from geomesa_tpu.tuning.controllers import CONTROLLER_SPECS

    assert len(CONTROLLERS) >= 4
    for name, doc in CONTROLLERS.items():
        assert name == name.lower() and " " not in name, name
        assert doc, name
    assert {s.name for s in CONTROLLER_SPECS} == set(CONTROLLERS)


def test_fstring_family_reported_once(fixture_result):
    """An f-string fragment is scanned exactly once: the JoinedStr
    branch owns it, the plain-Constant walk must skip it (the
    duplicate-findings regression)."""
    bad = _at(fixture_result, "knob_fstring.py", "knob-undeclared")
    assert len(bad) == 1, _render(bad)
    assert "geomesa.bogus" in bad[0].message


def test_warmup_ladder_gap_is_caught(fixture_result):
    bad = _at(fixture_result, "warmup_bad.py", "warmup-coverage")
    assert len(bad) == 1, _render(bad)  # R missing, E covered
    assert "FUSED_R_BUCKETS" in bad[0].message


# -- suppression machinery ------------------------------------------------


def test_baseline_and_inline_suppression(tmp_path):
    project = Project.load(ROOT)
    project.add_file(f"{FIXDIR}/knob_bad.py")
    rules = [r for r in analysis.ALL_RULES if r.id == "knob-undeclared"]
    result = run_rules(project, rules, baseline=set())
    bad = [f for f in result.findings if f.path.endswith("knob_bad.py")]
    assert len(bad) == 1
    # baselining the key suppresses it (and survives line drift: the key
    # carries the offending symbol, not the line number)
    assert str(bad[0].line) not in bad[0].key
    baselined = run_rules(project, rules, baseline={bad[0].key})
    assert not [
        f for f in baselined.findings if f.path.endswith("knob_bad.py")
    ]
    assert [
        f for f in baselined.suppressed if f.path.endswith("knob_bad.py")
    ]
    # inline `# lint: ignore[rule-id]` on the flagged line also works
    src = open(os.path.join(ROOT, FIXDIR, "knob_bad.py")).read()
    lines = src.splitlines()
    lines[bad[0].line - 1] += "  # lint: ignore[knob-undeclared]"
    alt = tmp_path / "knob_bad_suppressed.py"
    alt.write_text("\n".join(lines) + "\n")
    p2 = Project(str(tmp_path))
    p2.add_file("knob_bad_suppressed.py")
    r2 = run_rules(p2, rules, baseline=set())
    assert not r2.findings and r2.suppressed


# -- layer 3: the shared gate exit-code convention ------------------------


class TestCheckGateExitCodes:
    """scripts/check.py exits exactly like scripts/bench_gate.py
    (whose 0/1/2 contract is pinned by test_raster_join.TestBenchGate):
    0 clean, 1 findings, 2 unusable input."""

    def _run(self, *args):
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "check.py"), *args],
            capture_output=True, text=True, timeout=120,
        )
        return proc

    def _mini_repo(self, tmp_path, body):
        root = tmp_path / "repo"
        (root / "geomesa_tpu").mkdir(parents=True)
        (root / "geomesa_tpu" / "mod.py").write_text(body)
        return str(root)

    def test_clean_tree_exits_zero(self, tmp_path):
        root = self._mini_repo(tmp_path, '"""A module."""\n\nX = 1\n')
        proc = self._run("--root", root, "--json")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        import json

        payload = json.loads(proc.stdout)
        assert payload["clean"] is True and payload["findings"] == []

    def test_findings_exit_one(self, tmp_path):
        root = self._mini_repo(
            tmp_path,
            '"""A module citing geomesa.not.a.knob anywhere."""\n',
        )
        proc = self._run("--root", root, "--json")
        assert proc.returncode == 1, proc.stdout + proc.stderr
        import json

        payload = json.loads(proc.stdout)
        assert payload["findings"], payload
        assert payload["findings"][0]["rule"] == "knob-undeclared"

    def test_unusable_input_exits_two(self, tmp_path):
        assert self._run("--rules", "no-such-rule").returncode == 2
        assert self._run(
            "--root", str(tmp_path / "missing")
        ).returncode == 2
        assert self._run(
            "--baseline", str(tmp_path / "missing.txt")
        ).returncode == 2

    def test_write_baseline_bootstraps_then_suppresses(self, tmp_path):
        """The adopt-time workflow: --write-baseline CREATES a fresh
        baseline file, and a rerun against it exits 0."""
        root = self._mini_repo(
            tmp_path, '"""Cites geomesa.not.a.knob here."""\n'
        )
        bl = tmp_path / "bl" / "lint-baseline.txt"
        proc = self._run(
            "--root", root, "--write-baseline", "--baseline", str(bl)
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert bl.exists() and "knob-undeclared" in bl.read_text()
        rerun = self._run("--root", root, "--baseline", str(bl))
        assert rerun.returncode == 0, rerun.stdout + rerun.stderr
        # idempotent: a second write appends nothing (no duplicate keys)
        n_lines = len(bl.read_text().splitlines())
        again = self._run(
            "--root", root, "--write-baseline", "--baseline", str(bl)
        )
        assert again.returncode == 0
        assert len(bl.read_text().splitlines()) == n_lines

    def test_profile_table_and_json_schema_version(self, tmp_path):
        """--profile prints a per-rule wall-time table; --json carries
        the stable schema_version (the CI pinning contract)."""
        import json

        root = self._mini_repo(tmp_path, '"""A module."""\n\nX = 1\n')
        proc = self._run("--root", root, "--profile")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "knob-undeclared" in proc.stdout and " ms " in proc.stdout
        jproc = self._run("--root", root, "--profile", "--json")
        payload = json.loads(jproc.stdout)
        assert payload["schema_version"] == 1
        assert isinstance(payload["profile"], list) and payload["profile"]
        row = payload["profile"][0]
        assert set(row) == {"rule", "seconds", "raised"}
        plain = json.loads(self._run("--root", root, "--json").stdout)
        assert plain["schema_version"] == 1
        assert plain["changed_only"] is False

    def test_changed_scope(self, tmp_path):
        """--changed reports only findings in files the git work tree
        touched (rules still see the whole repo); a git-less root is
        unusable input (exit 2)."""
        import subprocess

        root = self._mini_repo(
            tmp_path, '"""Cites geomesa.not.a.knob here."""\n'
        )
        assert self._run("--root", root, "--changed").returncode == 2
        subprocess.run(["git", "init", "-q"], cwd=root, check=True)
        # untracked bad file: in scope -> finding survives the filter
        proc = self._run("--root", root, "--changed")
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "knob-undeclared" in proc.stdout
        # committed clean tree: nothing changed -> findings filter away
        subprocess.run(["git", "add", "-A"], cwd=root, check=True)
        subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t",
             "commit", "-qm", "x"], cwd=root, check=True,
        )
        proc = self._run("--root", root, "--changed")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "(changed files only)" in proc.stdout

    def test_parse_error_is_baselinable(self, tmp_path):
        """Adopt-time convergence on trees carrying broken files: the
        parse-error finding goes through the baseline like any other."""
        root = self._mini_repo(tmp_path, "def broken(:\n")
        assert self._run("--root", root).returncode == 1
        bl = tmp_path / "bl.txt"
        assert self._run(
            "--root", root, "--write-baseline", "--baseline", str(bl)
        ).returncode == 0
        assert "parse-error" in bl.read_text()
        rerun = self._run("--root", root, "--baseline", str(bl))
        assert rerun.returncode == 0, rerun.stdout + rerun.stderr
