"""BinnedTime codec tests (reference: BinnedTimeTest.scala)."""

import numpy as np
import pytest

from geomesa_tpu.curve.binnedtime import (
    MAX_OFFSET,
    MILLIS_PER_DAY,
    BinnedTime,
    TimePeriod,
)

MS_2020 = np.datetime64("2020-06-15T12:34:56.789", "ms").astype(np.int64)


class TestBinnedTime:
    @pytest.mark.parametrize("period", list(TimePeriod))
    def test_roundtrip(self, period):
        bt = BinnedTime(period)
        rng = np.random.default_rng(0)
        ms = rng.integers(0, 2_000_000_000_000, size=1000)  # 1970..2033
        bv = bt.to_binned(ms)
        back = bt.from_binned(bv.bin, bv.offset)
        # offsets are truncated to the period resolution
        res = {
            TimePeriod.DAY: 1,
            TimePeriod.WEEK: 1000,
            TimePeriod.MONTH: 1000,
            TimePeriod.YEAR: 60_000,
        }[TimePeriod.parse(period)]
        assert np.all(back <= ms)
        assert np.all(ms - back < res)

    @pytest.mark.parametrize("period", list(TimePeriod))
    def test_offsets_within_bounds(self, period):
        bt = BinnedTime(period)
        rng = np.random.default_rng(1)
        ms = rng.integers(0, 2_000_000_000_000, size=1000)
        bv = bt.to_binned(ms)
        assert np.all(bv.offset >= 0)
        assert np.all(bv.offset <= MAX_OFFSET[TimePeriod.parse(period)])

    def test_day_bins(self):
        bt = BinnedTime(TimePeriod.DAY)
        bv = bt.to_binned(MS_2020)
        assert int(bv.bin) == int(MS_2020 // MILLIS_PER_DAY)
        assert int(bv.offset) == int(MS_2020 % MILLIS_PER_DAY)

    def test_week_epoch_alignment(self):
        bt = BinnedTime(TimePeriod.WEEK)
        # 1970-01-01 is week 0 offset 0; 1970-01-08 is week 1 offset 0
        assert int(bt.to_binned(0).bin) == 0
        assert int(bt.to_binned(7 * MILLIS_PER_DAY).bin) == 1
        assert int(bt.to_binned(7 * MILLIS_PER_DAY).offset) == 0

    def test_month_calendar_boundaries(self):
        bt = BinnedTime(TimePeriod.MONTH)
        feb = np.datetime64("2020-02-01T00:00:00", "ms").astype(np.int64)
        bv = bt.to_binned(feb)
        assert int(bv.offset) == 0
        assert int(bv.bin) == (2020 - 1970) * 12 + 1

    def test_year_calendar_boundaries(self):
        bt = BinnedTime(TimePeriod.YEAR)
        y = np.datetime64("2021-01-01T00:00:00", "ms").astype(np.int64)
        bv = bt.to_binned(y)
        assert int(bv.offset) == 0
        assert int(bv.bin) == 2021 - 1970

    def test_bins_for_interval(self):
        bt = BinnedTime(TimePeriod.WEEK)
        lo = 10 * 7 * MILLIS_PER_DAY + 5_000_000
        hi = 12 * 7 * MILLIS_PER_DAY + 9_000_000
        bins, los, his = bt.bins_for_interval(lo, hi)
        assert bins.tolist() == [10, 11, 12]
        assert los[0] == 5_000
        assert his[0] == MAX_OFFSET[TimePeriod.WEEK]
        assert los[1] == 0
        assert his[2] == 9_000


class TestBoundsChecks:
    """Out-of-range instants raise instead of silently aliasing onto boundary
    bins (reference BinnedTime.scala:202-204 require checks)."""

    def test_pre_epoch_raises(self):
        import pytest
        from geomesa_tpu.curve.binnedtime import BinnedTime
        with pytest.raises(ValueError):
            BinnedTime("week").to_binned(-1)

    def test_past_max_bin_raises(self):
        import pytest
        import numpy as np
        from geomesa_tpu.curve.binnedtime import BinnedTime, MAX_BIN, MILLIS_PER_DAY
        bt = BinnedTime("day")
        too_far = (MAX_BIN + 1) * MILLIS_PER_DAY
        with pytest.raises(ValueError):
            bt.to_binned(too_far)
        # the boundary bin itself is fine
        ok = bt.to_binned(MAX_BIN * MILLIS_PER_DAY)
        assert int(ok.bin) == MAX_BIN

    def test_inverted_interval_raises(self):
        import pytest
        from geomesa_tpu.curve.binnedtime import BinnedTime
        with pytest.raises(ValueError):
            BinnedTime("week").bins_for_interval(100, 50)


class TestQuerySideClamping:
    """bins_for_interval clamps out-of-range query endpoints (query-side)
    while to_binned raises (ingest-side)."""

    def test_pre_epoch_query_clamped(self):
        from geomesa_tpu.curve.binnedtime import BinnedTime
        bins, lo, hi = BinnedTime("week").bins_for_interval(-10_000_000, 1_000_000_000)
        assert bins[0] == 0 and lo[0] == 0

    def test_far_future_query_clamped(self):
        from geomesa_tpu.curve.binnedtime import BinnedTime, MAX_BIN
        bt = BinnedTime("day")
        start = int(bt.from_binned(MAX_BIN - 1, 0))
        bins, lo, hi = bt.bins_for_interval(start, start * 10)
        assert bins[-1] == MAX_BIN and hi[-1] == bt.max_offset

    def test_clamp_all_periods(self):
        from geomesa_tpu.curve.binnedtime import BinnedTime, MAX_BIN
        for period in ("day", "week", "month", "year"):
            bins, lo, hi = BinnedTime(period).bins_for_interval(0, 10**18)
            assert bins[-1] == MAX_BIN, period
