"""The self-tuning controller tier (geomesa_tpu.tuning, docs/tuning.md).

Four pinned surfaces, per ISSUE 19:

1. **Gate differentials** — the four pre-existing measured-cost gates
   (tile compose gate, adaptive join gate, standing match gate, link
   slot ladder) migrated onto tuning/primitives.py; each test replays
   the PRE-migration arithmetic inline as a reference implementation
   and asserts the migrated gate produces the identical DECISION
   sequence over seeded inputs (decisions, not internal floats: the
   tile gate's old nudge-form EWMA is algebraically equal to the
   canonical blend but may differ in the last ulp).
2. **Disarmed bit-identity** — a store with a disarmed manager behaves
   bit-identically to a store without the tier: same plans, same
   explains, no hooks installed, no knob writes, zero pulses.
3. **The three legs armed** — reweighting converges with hysteresis,
   knob controllers hold/step/collapse within bounds, burn shedding
   engages before the queue is full and releases.
4. **Persistence** — learned state survives close()/reopen; a corrupt
   state file means re-learning, never failing.
"""

import json
import time

import numpy as np
import pytest

from geomesa_tpu import conf
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.metrics import MetricsRegistry
from geomesa_tpu.obs.accuracy import EstimateAccuracy
from geomesa_tpu.planning.explain import Explainer
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.tuning.burnshed import BurnShed
from geomesa_tpu.tuning.controllers import CONTROLLER_SPECS, KnobController
from geomesa_tpu.tuning.primitives import (
    CostEwma,
    ProbeGate,
    doubling_ladder,
    ewma_step,
)
from geomesa_tpu.tuning.reweight import IndexReweighter

DAY = 86400_000
Q = "bbox(geom, -10, -10, 10, 10)"

_TUNED_KNOBS = (
    "CACHE_MIN_COST",
    "SCAN_FUSED_SLOTS",
    "STREAM_FOLD_SLICE_ROWS",
    "STREAM_CHUNK_ROWS",
)


@pytest.fixture(autouse=True)
def _clean_tuned_state():
    """Armed controllers write through GLOBAL conf; every test leaves
    the steered knobs (and the link-probe constants) as it found them."""
    yield
    for name in _TUNED_KNOBS:
        getattr(conf, name).clear()
    from geomesa_tpu.scan import block_kernels as bk

    bk.set_link_constants(None)


def _mkstore(metrics=None, cache=None, n=512, seed=7):
    sft = FeatureType.from_spec(
        "ev", "kind:String:index=true,dtg:Date,*geom:Point:srid=4326"
    )
    ds = DataStore(tile=64, metrics=metrics, cache=cache)
    ds.create_schema(sft)
    rng = np.random.default_rng(seed)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    ds.write("ev", FeatureCollection.from_columns(
        sft, [str(i) for i in range(n)],
        {
            "kind": np.array(["a", "b", "c"])[rng.integers(0, 3, n)],
            "dtg": t0 + rng.integers(0, 20 * DAY, n),
            "geom": (rng.uniform(-60, 60, n), rng.uniform(-45, 45, n)),
        },
    ))
    return ds


# -- 1. shared primitives + the four gate differentials -------------------


def test_ewma_blend_matches_legacy_nudge_form():
    # the tile gate's old `prev + a*(s-prev)` and the canonical
    # `(1-a)*prev + a*s` are the same function; pin the equivalence the
    # migration leaned on
    rng = np.random.default_rng(3)
    blend, nudge = None, None
    for s in rng.uniform(1e-4, 2.0, 500):
        blend = ewma_step(blend, s)
        nudge = s if nudge is None else nudge + 0.25 * (s - nudge)
        assert blend == pytest.approx(nudge, rel=1e-12)


def test_probe_gate_explore_then_reprobe():
    g = ProbeGate(explore_min=3, reprobe_every=4)
    assert g.exploring
    for _ in range(3):
        g.note_trial()
    assert not g.exploring
    # every 4th blocked attempt re-probes, resetting the streak
    assert [g.block() for _ in range(9)] == [
        False, False, False, True, False, False, False, True, False
    ]


def test_cost_ewma_drops_non_positive_samples():
    e = CostEwma()
    assert e.value is None and e.value_or(7.5) == 7.5
    assert e.update_cost(1.0, 0) is None      # zero units: no signal
    assert e.update_cost(0.0, 10) is None     # zero seconds: no signal
    assert e.update_cost(2.0, 4) == 0.5       # first sample seeds
    assert e.value_or(7.5) == 0.5


def test_doubling_ladder_edges():
    assert doubling_ladder(0.0, 256, 2048) == 256
    assert doubling_ladder(256.0, 256, 2048) == 256
    assert doubling_ladder(256.0001, 256, 2048) == 512
    assert doubling_ladder(1e9, 256, 2048) == 2048


class _LegacyTilesGate:
    """The pre-migration cache/tiles.py gate verbatim: nudge-form EWMAs,
    _compose_n explore counter, _gated re-probe counter."""

    _EXPLORE_MIN, _REPROBE_EVERY, _A = 6, 8, 0.25

    def __init__(self):
        self._scan = {}
        self._comp = {}
        self._n = {}
        self._gated = {}

    def note_scan(self, t, s):
        prev = self._scan.get(t)
        self._scan[t] = s if prev is None else prev + self._A * (s - prev)

    def note_compose(self, t, s):
        prev = self._comp.get(t)
        self._comp[t] = s if prev is None else prev + self._A * (s - prev)
        self._n[t] = self._n.get(t, 0) + 1

    def worth_composing(self, t):
        if self._n.get(t, 0) < self._EXPLORE_MIN:
            return True
        scan, comp = self._scan.get(t), self._comp.get(t)
        if scan is None or comp is None or comp <= scan:
            return True
        g = self._gated.get(t, 0) + 1
        if g >= self._REPROBE_EVERY:
            self._gated[t] = 0
            return True
        self._gated[t] = g
        return False


def test_tiles_gate_differential():
    from geomesa_tpu.cache.generations import GenerationTracker
    from geomesa_tpu.cache.tiles import TileAggregateCache, TileCacheConf

    cache = TileAggregateCache(
        TileCacheConf(), GenerationTracker(), metrics=MetricsRegistry()
    )
    legacy = _LegacyTilesGate()
    rng = np.random.default_rng(11)
    got, want = [], []
    for _ in range(400):
        t = ("a", "b")[rng.integers(0, 2)]
        op = rng.integers(0, 3)
        if op == 0:
            s = float(rng.uniform(0.2, 1.0))
            cache.note_scan(t, s)
            legacy.note_scan(t, s)
        elif op == 1:
            # composes sometimes costlier than scans so the gate trips
            s = float(rng.uniform(0.2, 2.0))
            cache._note_compose(t, s)
            legacy.note_compose(t, s)
        else:
            got.append((t, cache.worth_composing(t)))
            want.append((t, legacy.worth_composing(t)))
    assert got == want
    assert {d for _, d in got} == {True, False}  # both branches exercised


class _LegacyJoinGate:
    """The pre-migration sql/join.py _AdaptiveGate verbatim."""

    _A = 0.25

    def __init__(self):
        self._pip = None
        self._cls = None

    def update(self, kind, seconds, units):
        if units <= 0 or seconds <= 0:
            return
        per = seconds / units
        if kind == "pip_s":
            self._pip = (
                per if self._pip is None
                else (1.0 - self._A) * self._pip + self._A * per
            )
        else:
            self._cls = (
                per if self._cls is None
                else (1.0 - self._A) * self._cls + self._A * per
            )

    def pick(self, n_cand, n_edges, boundary_frac):
        pip = self._pip if self._pip is not None else 4e-9
        cls = self._cls if self._cls is not None else 2e-8
        plain = n_cand * n_edges * pip
        rast = n_cand * cls + boundary_frac * n_cand * n_edges * pip
        return "raster" if rast < plain else "exact"


def test_join_gate_differential():
    from geomesa_tpu.sql.join import _AdaptiveGate

    gate, legacy = _AdaptiveGate(), _LegacyJoinGate()
    rng = np.random.default_rng(13)
    got, want = [], []
    # cold-start picks first (priors), then measured
    for _ in range(5):
        args = (int(rng.integers(1, 10_000)), int(rng.integers(3, 400)),
                float(rng.uniform(0.0, 1.0)))
        got.append(gate.pick(*args))
        want.append(legacy.pick(*args))
    for _ in range(300):
        if rng.integers(0, 2):
            kind = ("pip_s", "cls_s")[rng.integers(0, 2)]
            # include the non-positive-sample guard in the replay
            seconds = float(rng.uniform(-0.1, 0.5))
            units = int(rng.integers(0, 1_000_000))
            gate.update(kind, seconds, units)
            legacy.update(kind, seconds, units)
        else:
            args = (int(rng.integers(1, 10_000)), int(rng.integers(3, 400)),
                    float(rng.uniform(0.0, 1.0)))
            got.append(gate.pick(*args))
            want.append(legacy.pick(*args))
    assert got == want
    assert set(got) == {"raster", "exact"}


class _LegacyMatchGate:
    """The pre-migration streaming/standing.py _MatchGate verbatim."""

    _A, _HOST_PRIOR = 0.25, 4e-9

    def __init__(self):
        self._host = None
        self._fused = None

    def update(self, kind, seconds, units):
        if units <= 0 or seconds <= 0:
            return
        per = seconds / units
        if kind == "host_s":
            self._host = (
                per if self._host is None
                else (1.0 - self._A) * self._host + self._A * per
            )
        else:
            self._fused = (
                per if self._fused is None
                else (1.0 - self._A) * self._fused + self._A * per
            )

    def pick(self, host_units, fused_units):
        if self._fused is None:
            return None
        host = self._host if self._host is not None else self._HOST_PRIOR
        return fused_units * self._fused < host_units * host


def test_standing_gate_differential():
    from geomesa_tpu.streaming.standing import _MatchGate

    gate, legacy = _MatchGate(), _LegacyMatchGate()
    rng = np.random.default_rng(17)
    hu = rng.integers(1, 1_000_000, 32).astype(np.float64)
    fu = rng.integers(1, 1_000_000, 32).astype(np.float64)
    # fused unmeasured: both sides say "run the probe"
    assert gate.pick(hu, fu) is None and legacy.pick(hu, fu) is None
    saw_mask = False
    for _ in range(200):
        kind = ("host_s", "fused_s")[rng.integers(0, 2)]
        seconds = float(rng.uniform(0.0, 0.2))
        units = int(rng.integers(0, 5_000_000))
        gate.update(kind, seconds, units)
        legacy.update(kind, seconds, units)
        a, b = gate.pick(hu, fu), legacy.pick(hu, fu)
        if a is None or b is None:
            assert a is None and b is None
        else:
            saw_mask = True
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert saw_mask


def test_link_ladder_differential():
    from geomesa_tpu.scan.block_kernels import (
        DESIGN_LINK_RTT_MS,
        derive_link_constants,
    )
    from geomesa_tpu.storage.table import FUSED_CHUNK_SLOTS

    def legacy_slots(rtt_ms):
        want = (
            FUSED_CHUNK_SLOTS * max(float(rtt_ms), 1e-3) / DESIGN_LINK_RTT_MS
        )
        slots = 256
        while slots < want and slots < FUSED_CHUNK_SLOTS:
            slots *= 2
        return slots

    sweep = [1e-6, 1e-3, 0.01, 0.5, 1.0, 2.0, 3.0, 5.0, 8.0, 10.0,
             20.0, 40.0, 100.0, 1000.0, 1e6]
    # exact power-of-two boundaries, and a hair either side of each
    target = 256
    while target <= FUSED_CHUNK_SLOTS:
        rtt = target * DESIGN_LINK_RTT_MS / FUSED_CHUNK_SLOTS
        sweep += [rtt, rtt * (1 - 1e-9), rtt * (1 + 1e-9)]
        target *= 2
    for rtt in sweep:
        assert (
            derive_link_constants(rtt)["fused_chunk_slots"]
            == legacy_slots(rtt)
        ), f"rtt={rtt}"


# -- 2. disarmed == today, bit-identical ---------------------------------


def test_disarmed_is_bit_identical():
    plain = _mkstore(metrics=MetricsRegistry())
    tuned = _mkstore(metrics=MetricsRegistry())
    mgr = tuned.attach_tuning()  # geomesa.tuning.enabled defaults false
    assert mgr.enabled is False
    # no hooks installed
    assert tuned.planner.reweighter is None
    knobs_before = {
        s.knob: conf.REGISTRY[s.knob].get() for s in CONTROLLER_SPECS
    }
    for f in (Q, "kind = 'a'", "bbox(geom, 0, 0, 50, 40) AND kind = 'b'"):
        e1, e2 = Explainer(), Explainer()
        r1 = plain.query("ev", f, explain=e1)
        r2 = tuned.query("ev", f, explain=e2)
        np.testing.assert_array_equal(np.asarray(r1.ids), np.asarray(r2.ids))
        # identical traces modulo wall-clock timing lines
        strip = lambda exp: [l for l in exp.lines if "ms" not in l]
        assert strip(e1) == strip(e2)
    # the disarmed manager never pulsed, never wrote a knob
    assert mgr.report()["pulses"] == 0
    assert tuned.metrics.counter_value("geomesa.tuning.pulse") == 0
    knobs_after = {
        s.knob: conf.REGISTRY[s.knob].get() for s in CONTROLLER_SPECS
    }
    assert knobs_after == knobs_before
    plain.close()
    tuned.close()


def test_rearm_and_disarm_restore_hooks():
    ds = _mkstore(metrics=MetricsRegistry())
    sched = ds.serve()
    try:
        armed = ds.attach_tuning(enabled=True)
        assert ds.planner.reweighter is armed.reweighter
        assert sched.burn_gate is armed.burnshed
        disarmed = ds.attach_tuning(enabled=False)
        assert ds.tuning is disarmed
        assert ds.planner.reweighter is None
        assert sched.burn_gate is None
    finally:
        sched.close()
        ds.close()


# -- 3a. plan-feedback reweighting: convergence + hysteresis --------------


def _feed(acc, n, estimated, actual, index="z2"):
    for _ in range(n):
        acc.record("ev", index, estimated, actual)


def test_reweighter_convergence_and_hysteresis():
    acc = EstimateAccuracy()
    rw = IndexReweighter(acc, max_adjust=4.0, deadband=2.0, step=0.5,
                         min_count=8)
    # below min_count: too few samples to indict
    _feed(acc, 7, 29, 9)  # error factor 3.0
    assert rw.pulse() == [] and rw.factor("ev", "z2") == 1.0
    # chronic over-selector: p90 ~3x >= deadband -> multiplicative
    # growth, clamped at max_adjust
    _feed(acc, 3, 29, 9)
    trail = []
    for _ in range(6):
        for d in rw.pulse():
            trail.append(d["to"])
    assert trail == [1.5, 2.25, 3.375, 4.0]  # capped; then no-op pulses
    assert rw.factor("ev", "z2") == 4.0
    d = rw.pulse()
    assert d == []  # parked at the clamp: the trail records no non-moves
    # hold band: p90 lands between release (1.5) and deadband (2.0) —
    # the factor parks (no flapping either direction)
    _feed(acc, 150, 5, 4)   # error factor 1.2
    _feed(acc, 50, 7, 4)    # error factor 1.6
    p90 = [
        r for r in acc.report()["indexes"] if r["index"] == "z2"
    ][0]["p90_error"]
    assert 1.5 < p90 < 2.0, p90
    assert rw.pulse() == [] and rw.factor("ev", "z2") == 4.0
    # recovery: honest samples drive p90 to ~1.0 -> decay back to 1.0
    _feed(acc, 2000, 9, 9)  # error factor 1.0
    steps = []
    for _ in range(8):
        for d in rw.pulse():
            steps.append(d["to"])
    # decision records round to 4 decimals; the internal factor is exact
    assert steps == [2.6667, 1.7778, 1.1852, 1.0]
    assert rw.factor("ev", "z2") == 1.0
    assert rw.factors() == {}  # fully recovered keys leave the table


def test_reweight_factor_shows_in_plan_explain():
    ds = _mkstore(metrics=MetricsRegistry())
    try:
        mgr = ds.attach_tuning(enabled=True)
        e1 = Explainer()
        ds.query("ev", Q, explain=e1)
        [strat] = [l for l in e1.lines if l.strip().startswith("Strategy:")]
        chosen = strat.split()[1]
        assert not any("estimate-accuracy reweight" in l for l in e1.lines)
        mgr.reweighter.restore([["ev", chosen, 2.0]])
        e2 = Explainer()
        ds.query("ev", Q, explain=e2)
        assert any(
            f"Index {chosen}: estimate-accuracy reweight x2.00" in l
            for l in e2.lines
        )
    finally:
        ds.close()


# -- 3b. knob controllers ------------------------------------------------


def _spec(name):
    return next(s for s in CONTROLLER_SPECS if s.name == name)


def test_knob_controller_steps_flips_holds_and_clamps():
    spec = _spec("fold_slice_rows")  # lower-is-better, integral
    ctl = KnobController(spec)
    width = spec.hi - spec.lo
    assert ctl.propose(65536.0, 1.0) is None        # first reading seeds
    # improving: keep direction (relax_dir=-1), step down, clamp at lo
    assert ctl.propose(65536.0, 0.5) == spec.lo
    # mildly worse (outside deadband, not collapsed): reverse direction
    nxt = ctl.propose(spec.lo, 0.56)
    assert nxt == spec.lo + 0.25 * width
    assert nxt == float(int(nxt))                   # integral knob rounds
    # within the deadband: hold
    assert ctl.propose(nxt, 0.57) is None
    # at a clamp, a proposal that lands back on current is suppressed:
    # improving at lo keeps dir=-1, which clamps to lo == current
    lo_ctl = KnobController(spec)
    assert lo_ctl.propose(spec.lo, 100.0) is None
    assert lo_ctl.propose(spec.lo, 10.0) is None


def test_knob_controller_collapse_relaxes():
    spec = _spec("cache_min_cost")  # higher-is-better, relax_dir=-1
    ctl = KnobController(spec)
    assert ctl.propose(0.04, 100.0) is None
    assert ctl.propose(0.04, 101.0) is None  # deadband: steady is healthy
    # collapse: reading far below best -> step in the declared relax
    # direction (threshold down), not the hill-climb guess
    nxt = ctl.propose(0.04, 10.0)
    assert nxt == pytest.approx(0.04 - 0.25 * (spec.hi - spec.lo))
    # snapshot/restore round-trips; junk direction is rejected
    snap = ctl.snapshot()
    other = KnobController(spec)
    other.restore(snap)
    assert other.snapshot() == snap
    other.restore({"dir": 5})
    assert other.snapshot()["dir"] == snap["dir"]


def test_manager_pulse_steers_cache_min_cost(tmp_path):
    reg = MetricsRegistry()
    ds = _mkstore(metrics=reg, cache=True)
    try:
        conf.CACHE_MIN_COST.set(0.04)
        mgr = ds.attach_tuning(enabled=True, interval=1)
        reg.counter("geomesa.cache.hit", 100)
        assert mgr.pulse() == []  # seeds the counter baseline
        reg.counter("geomesa.cache.hit", 100)
        assert mgr.pulse() == []  # first delta seeds the controller
        reg.counter("geomesa.cache.hit", 10)  # hits collapsed
        decisions = mgr.pulse()
        [d] = [d for d in decisions if d["controller"] == "cache_min_cost"]
        assert d["knob"] == "geomesa.cache.min.cost"
        assert d["from"] == pytest.approx(0.04)
        assert d["to"] == pytest.approx(0.0275)
        # actuation is real: the knob AND the live cache conf moved
        assert conf.CACHE_MIN_COST.get() == pytest.approx(0.0275)
        assert ds.cache.result.conf.min_cost_s == pytest.approx(0.0275)
        assert reg.counter_value("geomesa.tuning.adjust") >= 1
        assert reg.counter_value("geomesa.tuning.pulse") == 3
        report = mgr.report()
        assert report["pulses"] == 3
        assert d in report["decisions"]
    finally:
        ds.close()


def test_manager_derive_controller_follows_link_rtt():
    from geomesa_tpu.scan import block_kernels as bk

    reg = MetricsRegistry()
    ds = _mkstore(metrics=reg)
    try:
        mgr = ds.attach_tuning(enabled=True)
        # no link probe yet: no reading, no move
        assert mgr.pulse() == []
        bk.set_link_constants(bk.derive_link_constants(20.0))
        derived = bk.derive_link_constants(20.0)["fused_chunk_slots"]
        # knob unpinned (0) and the auto path already lands on the
        # derived value: hold — the controller must not pin what the
        # probe constants already deliver
        assert mgr.pulse() == []
        assert int(conf.SCAN_FUSED_SLOTS.get() or 0) == 0
        # a stale pinned value diverging from the live RTT gets re-derived
        pinned = 256 if derived != 256 else 512
        conf.SCAN_FUSED_SLOTS.set(pinned)
        [d] = mgr.pulse()
        assert d["controller"] == "fused_chunk_slots"
        assert d["to"] == derived
        assert int(conf.SCAN_FUSED_SLOTS.get()) == derived
        assert reg.gauges.get("geomesa.tuning.link.rtt") == pytest.approx(20.0)
    finally:
        ds.close()


# -- 3c. SLO-burn admission shedding --------------------------------------


class _StubSlo:
    def __init__(self):
        self.burn = 0.0

    def report(self, now=None):
        return {"objectives": [
            {"objective": "query_p99", "burn_rate": self.burn},
        ]}


class _StubStore:
    def __init__(self, weights):
        class _T:
            def __init__(self, w):
                self._w = w

            def weights(self):
                return dict(self._w)

        class _S:
            pass

        self.slo = _StubSlo()
        self.scheduler = _S()
        self.scheduler.tenants = _T(weights)


def test_burn_shed_hysteresis_and_weight_tiers():
    store = _StubStore({"gold": 8.0, "bronze": 1.0})
    gate = BurnShed(store, threshold=2.0, release=1.0)
    assert gate.should_shed("bronze", now=1.0) is None  # no burn
    store.slo.burn = 3.0
    why = gate.should_shed("bronze", now=2.0)
    assert why is not None and "slo burn 3.00x" in why
    assert gate.should_shed("gold", now=2.0) is None  # top weight admits
    # unseen tenants (and the anonymous pool) get the default weight,
    # which sits below gold's: they shed too
    assert gate.should_shed("nobody", now=2.0) is not None
    assert gate.should_shed(None, now=2.0) is not None
    # hysteresis: between release and threshold an ENGAGED gate stays
    # engaged...
    store.slo.burn = 1.5
    assert gate.should_shed("bronze", now=3.0) is not None
    # ...releases only at/below release...
    store.slo.burn = 0.9
    assert gate.should_shed("bronze", now=4.0) is None
    # ...and a RELEASED gate does not re-engage in the same band
    store.slo.burn = 1.5
    assert gate.should_shed("bronze", now=5.0) is None


def test_burn_shed_uniform_weights_shed_nothing():
    store = _StubStore({"a": 1.0, "b": 1.0})
    store.slo.burn = 50.0
    gate = BurnShed(store, threshold=2.0)
    assert gate.should_shed("a", now=1.0) is None
    assert gate.should_shed("b", now=1.0) is None
    assert gate.report()["engaged"] is True


def test_burn_shed_engages_before_queue_full_and_releases():
    from geomesa_tpu.obs.slo import SloTracker
    from geomesa_tpu.serving import (
        QueryScheduler,
        ServingConfig,
        ServingRejected,
    )
    from geomesa_tpu.serving.tenancy import TenantRegistry

    reg = MetricsRegistry()
    ds = _mkstore(metrics=reg)
    # a short real window so the burn decays within the test
    ds.slo = SloTracker(window_s=0.6)
    tenants = TenantRegistry(metrics=reg)
    tenants.configure("gold", weight=8.0)
    tenants.configure("bronze", weight=1.0)
    # unstarted scheduler: queue states stay deterministic
    sched = QueryScheduler(
        ds, ServingConfig(queue_max=64), metrics=reg, tenants=tenants
    )
    ds.scheduler = sched
    try:
        mgr = ds.attach_tuning(enabled=True)
        assert sched.burn_gate is mgr.burnshed
        # p99 objective burning hard: every observation blows the budget
        for _ in range(60):
            ds.slo.observe("geomesa.query.scan", 60.0)
        mgr.pulse()
        assert mgr.burnshed.report()["engaged"]
        # the queue is EMPTY (far from queue_max=64), yet low-priority
        # work sheds — the gate fires before physical pressure exists
        shed = sched.submit("ev", Q, block=False, tenant="bronze")
        with pytest.raises(ServingRejected, match="slo burn"):
            shed.result(timeout=5)
        assert reg.counter_value("geomesa.tuning.shed") == 1
        # top-weight work admits through the same burn
        kept = sched.submit("ev", Q, block=False, tenant="gold")
        assert not kept.done()
        # burn decays past release as the window slides empty -> released
        time.sleep(1.0)
        mgr.pulse()
        assert not mgr.burnshed.report()["engaged"]
        ok = sched.submit("ev", Q, block=False, tenant="bronze")
        assert not ok.done()  # admitted (queued; scheduler never started)
        assert reg.counter_value("geomesa.tuning.shed") == 1
    finally:
        sched.close()
        ds.close()


# -- 4. persistence: learned state survives close()/reopen ----------------


def test_state_survives_close_and_reopen(tmp_path):
    path = str(tmp_path / "_tuning.json")
    ds1 = _mkstore(metrics=MetricsRegistry())
    mgr1 = ds1.attach_tuning(enabled=True, state_path=path)
    mgr1.reweighter.restore([["ev", "z2", 2.25]])
    mgr1.controllers["cache_min_cost"].restore(
        {"last": 5.0, "best": 9.0, "dir": 1}
    )
    conf.CACHE_MIN_COST.set(0.03)  # as if the controller had steered it
    ds1.close()  # saves
    state = json.load(open(path))
    assert state["factors"] == [["ev", "z2", 2.25]]
    conf.CACHE_MIN_COST.clear()  # simulate a fresh process
    ds2 = _mkstore(metrics=MetricsRegistry())
    mgr2 = ds2.attach_tuning(enabled=True, state_path=path)
    assert mgr2.reweighter.factor("ev", "z2") == 2.25
    assert mgr2.controllers["cache_min_cost"].snapshot() == {
        "last": 5.0, "best": 9.0, "dir": 1,
    }
    # tuned knob values re-applied: the reopened store starts from what
    # it learned, not from the defaults
    assert conf.CACHE_MIN_COST.get() == pytest.approx(0.03)
    ds2.close()


def test_corrupt_state_file_means_relearning_not_failing(tmp_path):
    path = tmp_path / "_tuning.json"
    path.write_text("{this is not json", encoding="utf-8")
    ds = _mkstore(metrics=MetricsRegistry())
    mgr = ds.attach_tuning(enabled=True, state_path=str(path))
    assert mgr.reweighter.factors() == {}
    assert mgr.pulse() == []  # fully operational
    ds.close()


# -- the ops surface ------------------------------------------------------


def test_tuning_report_shapes():
    ds = _mkstore(metrics=MetricsRegistry())
    try:
        bare = ds.tuning_report()
        assert bare["enabled"] is False
        mgr = ds.attach_tuning(enabled=True)
        report = ds.tuning_report()
        assert report["enabled"] is True
        assert report["interval"] == mgr.interval
        names = {row["name"] for row in report["controllers"]}
        assert names == {s.name for s in CONTROLLER_SPECS}
        for row in report["controllers"]:
            assert row["lo"] < row["hi"]
            assert row["knob"] in conf.REGISTRY
        assert report["burn"]["objective"] == "query_p99"
        assert report["plan_factors"] == {}
        assert report["decisions"] == []
    finally:
        ds.close()
