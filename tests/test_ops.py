"""The ops plane (docs/observability.md "The ops plane"): /metrics +
/health endpoints, telemetry history rings, estimate accountability.

Layers:

- **scrape correctness**: a REAL HTTP scrape of ``/metrics`` parses
  under the strict exposition mini-parser while serving load runs;
- **the health state machine**: ``/health`` flips
  healthy→degraded→unhealthy under injected faults (quarantined
  partition, WAL recovery debt, shed storm / saturated queue, hot-tier
  overrun) with exact machine-readable reasons;
- **estimate accountability**: every executed plan records estimated
  vs actual rows; a mutated-without-analyze store trips the
  "stats stale — re-analyze" reason and the auto-analyze hook clears
  it;
- **lifecycle**: the server binds/shuts down cleanly under
  ``DataStore.close()`` — no leaked thread or socket, the port
  immediately rebindable (the reuse-addr regression).
"""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from geomesa_tpu import conf, fault, obs
from geomesa_tpu.audit import AuditWriter
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.metrics import MetricsRegistry
from geomesa_tpu.obs.ops import HealthMonitor, TelemetryRecorder, ops_report
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.storage import persist

SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"
T0 = int(np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64))
DAY = 86_400_000
Q = "BBOX(geom, -20, -20, 20, 20)"


@pytest.fixture(autouse=True)
def _fresh_obs():
    """Fresh tracer + restored knobs around every test."""
    obs.install(obs.Tracer())
    yield
    for knob in (conf.OBS_TRACE_SAMPLE, conf.OBS_SLOW_MS,
                 conf.OBS_SLOW_MAX, conf.PLAN_ESTIMATE,
                 conf.PLAN_ESTIMATE_STALE_P90, conf.PLAN_ESTIMATE_MIN_COUNT,
                 conf.PLAN_ESTIMATE_AUTO_ANALYZE, conf.OBS_OPS_SAMPLE_MS,
                 conf.OBS_OPS_HISTORY, conf.OBS_SLO_QUERY_P99_MS):
        knob.clear()
    obs.install(obs.Tracer())


def _fc(sft, n, seed=0, prefix="r", lo=-50.0, hi=50.0):
    rng = np.random.default_rng(seed)
    return FeatureCollection.from_columns(
        sft, [f"{prefix}{i}" for i in range(n)],
        {"name": np.array(["n"] * n),
         "dtg": T0 + rng.integers(0, 30 * DAY, n),
         "geom": (rng.uniform(lo, hi, n), rng.uniform(lo, hi, n))},
    )


def _store(n=3000, metrics=True, audit=False):
    ds = DataStore(
        metrics=MetricsRegistry() if metrics else None,
        audit=AuditWriter() if audit else None,
    )
    sft = FeatureType.from_spec("t", SPEC)
    ds.create_schema(sft)
    if n:
        ds.write("t", _fc(sft, n))
    return ds


def _get(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:  # non-2xx still carries a body
        with e:
            return e.code, e.read().decode()


def _reasons(report):
    return {r["reason"] for r in report["reasons"]}


# -- layer 1: the /metrics scrape under the strict parser ------------------


def test_metrics_scrape_parses_strict_under_serving_load():
    """A real HTTP scrape of /metrics, taken WHILE scheduler-admitted
    queries run, parses under the strict exposition mini-parser and
    carries the histogram families the doc promises."""
    from test_metrics import _parse_openmetrics

    ds = _store()
    ds.query("t", Q)  # warm the kernel variant
    sched = ds.serve()
    srv = ds.serve_ops()
    try:
        stop = threading.Event()
        errs = []

        def load():
            while not stop.is_set():
                try:
                    sched.submit("t", Q).result(30)
                except BaseException as e:  # pragma: no cover
                    errs.append(e)
                    return

        t = threading.Thread(target=load)
        t.start()
        try:
            bodies = []
            for _ in range(3):
                code, text = _get(srv.url + "/metrics")
                assert code == 200
                bodies.append(text)
        finally:
            stop.set()
            t.join()
        assert errs == []
        fams = _parse_openmetrics(bodies[-1])
        kind, _ = fams["geomesa_query_scan_seconds"]
        assert kind == "histogram"
        kind, _ = fams["geomesa_plan_estimate_error_seconds"]
        assert kind == "histogram"
        assert fams["geomesa_query_count"][0] == "counter"
        # the scrape counted itself
        assert ds.metrics.counter_value("geomesa.obs.ops.scrapes") >= 3
    finally:
        ds.close()
    assert ds.ops.closed and sched.closed


# -- layer 2: the health state machine -------------------------------------


def test_health_ready_then_quarantine_degraded_then_wal_unhealthy(tmp_path):
    """The composite verdict walks healthy→degraded→unhealthy: a clean
    store is ready; a bit-flipped partition quarantined at load is
    degraded with the exact store.quarantine reason; a WAL holding
    unreplayed mutation records flips unhealthy (HTTP 503) with
    wal.needs_recovery on top."""
    from geomesa_tpu.streaming import LambdaStore, StreamConfig, WalConfig
    from geomesa_tpu.streaming.wal import WriteAheadLog

    ds = _store(n=800)
    report = HealthMonitor(ds).evaluate()
    assert report["status"] == "ready" and report["reasons"] == []

    # degraded: save with an injected bit flip, reload -> quarantine
    root = tmp_path / "s"
    with fault.inject("persist.partition.commit", kind="bit_flip"):
        persist.save(ds, root)
    back = persist.load(root)
    assert back.store_health.status == "degraded"
    srv = back.serve_ops()
    try:
        code, body = _get(srv.url + "/health")
        assert code == 200  # degraded still serves
        report = json.loads(body)
        assert report["status"] == "degraded"
        assert _reasons(report) == {"store.quarantine"}
        [r] = report["reasons"]
        assert r["severity"] == "degraded" and "quarantined" in r["detail"]

        # unhealthy: a WAL with acknowledged-but-unreplayed records.
        # Build one by writing through a WAL'd LambdaStore and closing
        # WITHOUT a checkpoint, then reopening the log standalone (the
        # explicit wal= escape hatch the plain constructor refuses).
        wal_root = tmp_path / "w"
        clean = _store(n=0)
        persist.save(clean, wal_root)
        lam0 = LambdaStore(
            clean, "t", config=StreamConfig(chunk_rows=64),
            wal_dir=str(wal_root / "_wal"),
            wal_config=WalConfig(sync="always"),
        )
        lam0.write([{
            "__id__": "a", "name": "n",
            "dtg": np.datetime64(T0, "ms"), "geom": "POINT (1 1)",
        }])
        lam0.close()
        wal = WriteAheadLog(str(wal_root / "_wal"))
        assert wal.needs_recovery
        try:
            lam = LambdaStore(back, "t", wal=wal)
            srv.monitor.lam = lam
            code, body = _get(srv.url + "/health")
            assert code == 503  # unhealthy: stop routing
            report = json.loads(body)
            assert report["status"] == "unhealthy"
            assert _reasons(report) == {
                "store.quarantine", "wal.needs_recovery",
            }
            sev = {r["reason"]: r["severity"] for r in report["reasons"]}
            assert sev["wal.needs_recovery"] == "unhealthy"
        finally:
            wal.close()
    finally:
        back.close()


def test_health_shed_storm_and_saturated_queue():
    """The serving checks: shed-counter movement since the previous
    evaluation is degraded (scheduler.shedding); a FULL admission
    queue is unhealthy (scheduler.saturated); a half-full queue is
    degraded (scheduler.queue); draining restores ready."""
    from geomesa_tpu.serving import QueryScheduler, ServingConfig

    ds = _store(n=400)
    # an UNSTARTED scheduler stages a deterministic queue (no
    # dispatcher thread drains it)
    sched = QueryScheduler(ds, ServingConfig(queue_max=4))
    ds.scheduler = sched
    mon = HealthMonitor(ds)
    assert mon.evaluate()["status"] == "ready"

    futs = [sched.submit("t", Q) for _ in range(2)]  # half full
    report = mon.evaluate()
    assert _reasons(report) == {"scheduler.queue"}
    assert report["status"] == "degraded"
    assert report["scheduler"] == {"queue_depth": 2, "queue_max": 4}

    futs += [sched.submit("t", Q) for _ in range(2)]  # full
    # the shed storm: a full queue + block=False sheds immediately
    from geomesa_tpu.serving.scheduler import ServingRejected

    shed = sched.submit("t", Q, block=False)
    with pytest.raises(ServingRejected):
        shed.result(1)
    report = mon.evaluate()
    assert _reasons(report) == {
        "scheduler.saturated", "scheduler.shedding",
    }
    assert report["status"] == "unhealthy"

    # the shed delta was consumed; with the queue still full only the
    # saturation remains
    report = mon.evaluate()
    assert _reasons(report) == {"scheduler.saturated"}

    sched.close()  # fails the staged futures, drains the queue
    for f in futs:
        with pytest.raises(RuntimeError):
            f.result(1)
    ds.scheduler = None
    assert mon.evaluate()["status"] == "ready"


def test_health_hot_occupancy_and_standing_drops(tmp_path):
    """The streaming checks: a hot tier holding more than 2x the fold
    threshold is degraded (hot.occupancy) and clears after a flush;
    standing alert-queue drops since the previous evaluation are
    degraded (standing.drops)."""
    from geomesa_tpu.streaming import LambdaStore, StreamConfig

    ds = _store(n=0)
    lam = LambdaStore(ds, "t", config=StreamConfig(
        chunk_rows=64, fold_rows=8, workers=1,
    ))
    try:
        srv = lam.serve_ops()
        mon = srv.monitor
        assert mon.evaluate()["status"] == "ready"
        lam.write([{
            "__id__": f"h{i}", "name": "n",
            "dtg": np.datetime64(T0, "ms"),
            "geom": f"POINT ({i % 50} {i % 50})",
        } for i in range(100)])
        code, body = _get(srv.url + "/health")
        report = json.loads(body)
        assert code == 200 and report["status"] == "degraded"
        assert _reasons(report) == {"hot.occupancy"}
        assert report["hot"]["rows"] == 100 and report["hot"]["fold_rows"] == 8
        lam.flush(full=True)
        assert mon.evaluate()["status"] == "ready"
        # standing drops ride the counter-delta path
        ds.metrics.counter("geomesa.standing.dropped", 7)
        report = mon.evaluate()
        assert _reasons(report) == {"standing.drops"}
        assert "7" in report["reasons"][0]["detail"]
        assert mon.evaluate()["status"] == "ready"  # delta consumed
    finally:
        ds.close()
        lam.close()


def test_health_slo_breach_reason():
    """A breaching SLO objective surfaces as one slo.breach reason with
    the objective, quantile and burn rate in the detail."""
    conf.OBS_SLO_QUERY_P99_MS.set(0.0001)  # everything breaches
    ds = _store(n=500)
    ds.attach_slo()
    for _ in range(3):
        ds.query("t", Q)
    report = HealthMonitor(ds).evaluate()
    assert report["status"] == "degraded"
    assert _reasons(report) == {"slo.breach"}
    assert "query_p99" in report["reasons"][0]["detail"]


# -- layer 3: estimate accountability --------------------------------------


def test_estimates_recorded_on_every_scan():
    """Every executed index scan records the sketch estimate next to
    the rows actually scanned: plan fields set, explain lines present,
    the error histogram populated, the per-index accuracy reported."""
    from geomesa_tpu.planning.explain import Explainer

    ds = _store()
    exp = Explainer()
    plan = ds.planner.plan("t", Q, explain=exp)
    assert plan.estimated_rows is not None and plan.estimated_rows > 0
    out = ds.planner.execute(plan, explain=exp)
    assert plan.actual_rows is not None and plan.actual_rows >= len(out)
    lines = exp.lines
    assert any(l.startswith("Estimated rows:") for l in lines)
    assert any(l.startswith("Estimate vs actual:") for l in lines)
    snap = ds.metrics.snapshot()["histograms"]
    assert snap["geomesa.plan.estimate.error"]["count"] == 1
    rows = ds.accuracy.report()["indexes"]
    assert len(rows) == 1
    assert rows[0]["type"] == "t" and rows[0]["count"] == 1
    assert rows[0]["p90_error"] >= 1.0
    # a fresh store's estimate is honest: well under the stale bar
    assert rows[0]["worst_error"] < float(conf.PLAN_ESTIMATE_STALE_P90.get())
    # the knob disables the whole loop
    conf.PLAN_ESTIMATE.set(False)
    plan2 = ds.planner.plan("t", Q)
    assert plan2.estimated_rows is None
    ds.planner.execute(plan2)
    assert ds.accuracy.sample_count() == 1  # unchanged


def test_stale_stats_flag_health_and_manual_reanalyze():
    """The accountability loop end to end: mutate the store WITHOUT
    re-analyzing (the documented accumulate-only sketch drift), run
    queries whose estimates are now wild, and the health surface says
    'stats stale — re-analyze'; analyze_stats + reset clears it."""
    conf.PLAN_ESTIMATE_MIN_COUNT.set(8)
    ds = _store(n=2000)
    sft = ds.get_schema("t")
    # move EVERY point far away through the streaming fold path, whose
    # stats are accumulate-only (docs/streaming.md's documented drift):
    # the sketches still claim the old region is dense
    ds.fold_upsert("t", _fc(sft, 2000, seed=1, lo=100.0, hi=140.0))
    mon = HealthMonitor(ds)
    for _ in range(10):
        ds.query("t", Q)  # old region: estimate >> actual
    stale = ds.accuracy.stale()
    assert stale and stale[0][0] == "t"
    report = mon.evaluate()
    assert "stats.stale" in _reasons(report)
    detail = next(
        r["detail"] for r in report["reasons"]
        if r["reason"] == "stats.stale"
    )
    assert "stats stale" in detail and "analyze_stats" in detail
    # the operator follows the instruction: fresh sketches, reset window
    ds.analyze_stats("t")
    ds.accuracy.reset("t")
    for _ in range(10):
        ds.query("t", Q)
    assert ds.accuracy.stale() == []
    assert "stats.stale" not in _reasons(mon.evaluate())


def test_stale_stats_auto_analyze_hook():
    """With geomesa.plan.estimate.auto.analyze on, the stale trip runs
    analyze_stats itself — once (the window resets), counted by
    geomesa.plan.estimate.analyze — and estimates recover."""
    conf.PLAN_ESTIMATE_MIN_COUNT.set(8)
    conf.PLAN_ESTIMATE_AUTO_ANALYZE.set(True)
    ds = _store(n=2000)
    sft = ds.get_schema("t")
    ds.fold_upsert("t", _fc(sft, 2000, seed=1, lo=100.0, hi=140.0))
    for _ in range(12):
        ds.query("t", Q)
    assert ds.metrics.counter_value("geomesa.plan.estimate.analyze") == 1
    # post-analyze: the window restarted and the fresh sketches stay
    # accurate, so no second trip
    for _ in range(12):
        ds.query("t", Q)
    assert ds.metrics.counter_value("geomesa.plan.estimate.analyze") == 1
    assert ds.accuracy.stale() == []


def test_estimate_compares_post_refinement_not_candidates():
    """Review-pinned: the recorded 'actual' is the POST-refinement
    matched count, not the index's candidate count — a spatial-only
    index serving a spatio-temporal filter over-selects candidates by
    design, and that must not flag fresh sketches stale."""
    conf.PLAN_ESTIMATE_MIN_COUNT.set(4)
    ds = DataStore(metrics=MetricsRegistry())
    sft = FeatureType.from_spec("t", SPEC)
    sft.user_data["geomesa.indices.enabled"] = "z2"  # atemporal index
    ds.create_schema(sft)
    ds.write("t", _fc(sft, 4000))
    # one day of thirty: the z2 scan's candidates ignore time entirely
    q = (
        "BBOX(geom, -40, -40, 40, 40) AND dtg DURING "
        "2024-01-01T00:00:00Z/2024-01-02T00:00:00Z"
    )
    for _ in range(6):
        plan = ds.planner.plan("t", q)
        out = ds.planner.execute(plan)
        assert plan.index == "z2"
        assert plan.actual_rows == len(out)  # matched, not candidates
    rows = ds.accuracy.report()["indexes"]
    assert rows[0]["p90_error"] < float(conf.PLAN_ESTIMATE_STALE_P90.get())
    assert ds.accuracy.stale() == []


def test_estimate_union_with_limit_not_skewed():
    """Review-pinned: a union plan with a limit records the union's
    matched count, not the truncated result — record_query's hits
    fallback must never compare the sketch estimate against a
    post-limit row count."""
    conf.PLAN_ESTIMATE_MIN_COUNT.set(2)
    ds = DataStore(metrics=MetricsRegistry())
    sft = FeatureType.from_spec(
        "t", "name:String:index=true,dtg:Date,*geom:Point:srid=4326"
    )
    ds.create_schema(sft)
    ds.write("t", _fc(sft, 4000))
    # spatial OR attribute: no single index serves both disjuncts
    q = "BBOX(geom, -40, -40, 0, 40) OR name = 'n'"
    for _ in range(3):
        plan = ds.planner.plan("t", q, limit=5)
        out = ds.planner.execute(plan)
        assert plan.union is not None and len(out) == 5
        # the union matched ~everything; the limit did not leak into
        # the recorded actual
        assert plan.actual_rows is not None and plan.actual_rows > 100
    assert ds.accuracy.stale() == []


# -- layer 4: telemetry rings + debug surfaces -----------------------------


def test_auto_analyze_claim_is_single_winner():
    """Review-pinned: the auto-analyze trip is an atomic claim — one
    winner per trip even with concurrent claimants; reset releases it
    for the next trip."""
    from geomesa_tpu.obs.accuracy import EstimateAccuracy

    acc = EstimateAccuracy()
    results = []
    barrier = threading.Barrier(8)

    def claimant():
        barrier.wait()
        results.append(acc.claim_analyze("t"))

    threads = [threading.Thread(target=claimant) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert sum(results) == 1  # exactly one winner
    acc.reset("t")
    assert acc.claim_analyze("t")  # released for the next trip


def test_health_first_evaluation_ignores_preexisting_counters():
    """Review-pinned: a monitor constructed AFTER a shed storm must not
    report it — the baseline snapshot seeds at construction, so the
    first evaluation measures 'since this monitor existed', not
    process lifetime."""
    ds = _store(n=0)
    ds.metrics.counter("geomesa.serving.shed", 5)
    ds.metrics.counter("geomesa.standing.dropped", 3)
    mon = HealthMonitor(ds)
    report = mon.evaluate()
    assert report["status"] == "ready" and report["reasons"] == []
    # NEW movement after construction still fires
    ds.metrics.counter("geomesa.serving.shed", 1)
    assert _reasons(mon.evaluate()) == {"scheduler.shedding"}


def test_telemetry_recorder_restarts_after_stop():
    """Review-pinned: stop() then start() resumes sampling (the stop
    event clears), so a paused recorder's history does not silently
    freeze."""
    reg = MetricsRegistry()
    reg.gauge("geomesa.stream.hot_rows", 1.0)
    rec = TelemetryRecorder(reg, interval_ms=10.0, history=64)
    rec.start()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if rec.series()["series"]:
            break
        time.sleep(0.01)
    rec.stop()
    n0 = len(rec.series()["series"]["geomesa.stream.hot_rows"]["v"])
    assert n0 >= 1
    rec.start()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        n = len(rec.series()["series"]["geomesa.stream.hot_rows"]["v"])
        if n > n0:
            break
        time.sleep(0.01)
    rec.stop()
    assert len(rec.series()["series"]["geomesa.stream.hot_rows"]["v"]) > n0


def test_telemetry_recorder_rings_window_and_bound():
    reg = MetricsRegistry()
    reg.gauge("geomesa.stream.hot_rows", 10.0)
    reg.counter("geomesa.query.count", 3)
    reg.observe("geomesa.query.scan", 0.02)
    rec = TelemetryRecorder(reg, interval_ms=1000.0, history=4)
    for k in range(8):
        reg.gauge("geomesa.stream.hot_rows", 10.0 + k)
        rec.sample(now=1000.0 + k)
    out = rec.series()
    ring = out["series"]["geomesa.stream.hot_rows"]
    assert len(ring["v"]) == 4  # bounded: oldest evicted
    assert ring["v"][-1] == 17.0
    assert out["series"]["geomesa.query.count"]["v"][-1] == 3.0
    assert "geomesa.query.scan.p99" in out["series"]
    assert out["series"]["geomesa.query.scan.p99"]["v"][-1] > 0
    # window filter keeps only recent points
    win = rec.series(window_s=2.5, now=1007.0)
    assert len(win["series"]["geomesa.stream.hot_rows"]["v"]) == 3


def test_debug_surfaces_slow_filter_audit_trace_crossref(tmp_path):
    """/debug/slow filters by type; /debug/audit rows carry the trace
    id that cross-references the slow capture and the Chrome export
    (pid); /stats serves the sketches; unknown paths 404."""
    conf.OBS_SLOW_MS.set(0.0001)  # everything is "slow"
    ds = _store(n=500, audit=True)
    sft2 = FeatureType.from_spec("u", SPEC)
    ds.create_schema(sft2)
    ds.write("u", _fc(sft2, 200, prefix="u"))
    ds.query("t", Q)
    ds.query("u", Q)
    srv = ds.serve_ops()
    try:
        _, body = _get(srv.url + "/debug/slow?type=u")
        only_u = json.loads(body)
        assert only_u and all(
            e["fingerprint"]["type"] == "u" for e in only_u
        )
        _, body = _get(srv.url + "/debug/slow")
        both = json.loads(body)
        assert {e["fingerprint"]["type"] for e in both} == {"t", "u"}
        # audit <-> slow <-> chrome cross-reference on one key
        _, body = _get(srv.url + "/debug/audit")
        audits = json.loads(body)
        assert len(audits) == 2
        trace_ids = {e["traceId"] for e in audits}
        assert None not in trace_ids
        slow_ids = {e["trace"]["trace_id"] for e in both}
        assert trace_ids == slow_ids
        _, body = _get(srv.url + "/debug/trace")
        chrome = json.loads(body)
        pids = {ev["pid"] for ev in chrome["traceEvents"]}
        assert trace_ids <= pids
        # /stats serves the sketch bundle per type
        _, body = _get(srv.url + "/stats")
        stats = json.loads(body)
        assert set(stats) == {"t", "u"}
        assert stats["t"]["count"]["count"] == 500
        # unknown path
        code, body = _get(srv.url + "/nope")
        assert code == 404 and "unknown path" in body
    finally:
        ds.close()


def test_ops_report_and_cli(tmp_path, capsys):
    """`geomesa ops` parity: the one-shot report carries health +
    slow + estimates, in text and --json."""
    from geomesa_tpu import cli

    conf.OBS_SLOW_MS.set(0.0001)
    ds = _store(n=400)
    ds.query("t", Q)
    rep = ops_report(ds, slow_n=5)
    assert rep["health"]["status"] in ("ready", "degraded")
    assert rep["slow_queries"] and rep["slow_queries"][0]["wall_ms"] > 0
    assert rep["health"]["estimates"]["indexes"]

    root = tmp_path / "cat"
    persist.save(ds, root)
    rc = cli.main(["ops", "-c", str(root), "--json"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["health"]["status"] == "ready"
    rc = cli.main(["ops", "-c", str(root), "--slow", "3"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "status: ready" in out
    assert "estimate accuracy" in out


# -- layer 5: lifecycle (the bugfix regression) ----------------------------


def test_close_joins_threads_and_port_rebinds_immediately():
    """The DataStore.close() contract: after close, no ops/telemetry
    thread survives and the SAME port rebinds immediately (reuse-addr)
    — three open/close cycles back to back."""
    ds = _store(n=200)
    srv = ds.serve_ops()
    port = srv.port
    _get(srv.url + "/health")
    ds.close()
    assert srv.closed
    for _ in range(2):
        srv2 = ds.serve_ops(port=port)  # closed one is replaced
        assert srv2 is ds.ops and srv2.port == port
        _get(srv2.url + "/health")
        ds.close()
        assert srv2.closed
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        leaked = [
            t for t in threading.enumerate()
            if t.name in ("geomesa-ops", "geomesa-telemetry") and t.is_alive()
        ]
        if not leaked:
            break
        time.sleep(0.02)
    assert leaked == [], leaked


def test_serve_ops_idempotent_and_close_covers_scheduler():
    ds = _store(n=200)
    srv = ds.serve_ops()
    assert ds.serve_ops() is srv  # idempotent while open
    sched = ds.serve()
    ds.close()
    assert srv.closed and sched.closed
    ds.close()  # idempotent
