"""Stats sketches + cost-based strategy selection."""

import numpy as np
import pytest

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.stats import Frequency, Histogram, MinMax, StatsStore, TopK, Z3Histogram


def test_minmax_merge():
    a, b = MinMax(), MinMax()
    a.observe(np.array([3, 7, 5]))
    b.observe(np.array([1, 9]))
    a += b
    assert a.bounds == (1, 9)
    assert a.count == 5


def test_histogram_estimate():
    h = Histogram(10, 0.0, 100.0)
    h.observe(np.random.default_rng(0).uniform(0, 100, 10000))
    est = h.estimate_range(20.0, 40.0)
    assert 1700 < est < 2300


def test_frequency_estimate():
    f = Frequency()
    col = np.array(["a"] * 500 + ["b"] * 50 + [f"x{i}" for i in range(100)])
    f.observe(col)
    assert f.estimate("a") >= 500
    assert f.estimate("a") < 700  # count-min overestimates but not wildly
    assert f.estimate("b") >= 50


def test_topk():
    t = TopK(k=2)
    t.observe(np.array(["a"] * 9 + ["b"] * 5 + ["c"]))
    assert [v for v, _ in t.top()] == ["a", "b"]
    other = TopK(k=2)
    other.observe(np.array(["c"] * 20))
    t += other
    assert t.top()[0][0] == "c"


def test_z3_histogram_estimate():
    rng = np.random.default_rng(1)
    n = 20000
    bins = rng.integers(0, 4, n).astype(np.int32)
    zs = rng.integers(0, 1 << 30, n).astype(np.uint64)
    h = Z3Histogram(30, prefix_bits=10)
    h.observe(bins, zs)
    # whole-space ranges per bin should estimate ~n
    est = h.estimate(
        np.array([0, 1, 2, 3]),
        np.zeros(4, np.uint64),
        np.full(4, (1 << 30) - 1, np.uint64),
    )
    assert 0.9 * n < est < 1.1 * n
    # half the z space ~ half the rows
    est_half = h.estimate(
        np.array([0, 1, 2, 3]),
        np.zeros(4, np.uint64),
        np.full(4, (1 << 29) - 1, np.uint64),
    )
    assert 0.4 * n < est_half < 0.6 * n


def _store(n=3000):
    sft = FeatureType.from_spec("t", "name:String,age:Int,dtg:Date,*geom:Point:srid=4326")
    ds = DataStore(tile=64)
    ds.create_schema(sft)
    rng = np.random.default_rng(5)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    fc = FeatureCollection.from_columns(
        sft,
        [str(i) for i in range(n)],
        {
            "name": np.array(["alice", "bob"] * (n // 2)),
            "age": rng.integers(0, 90, n),
            "dtg": t0 + rng.integers(0, 30 * 86400_000, n),
            "geom": (rng.uniform(-180, 180, n), rng.uniform(-90, 90, n)),
        },
    )
    ds.write("t", fc)
    return ds


def test_store_stats_built():
    ds = _store()
    st = ds.stats_for("t")
    assert isinstance(st, StatsStore)
    assert st.total_count() == 3000
    assert st.attribute_bounds("age") is not None
    assert st.estimate_equality("name", "alice") >= 1400
    lo, hi = st.attribute_bounds("age")
    assert st.estimate_range("age", float(lo), float(hi)) > 2500
    assert st.z3 is not None


def test_cost_prefers_selective_index():
    """The decider picks z3 over z2 for bbox+time (smaller span cost), and
    the explain trace records the costs (reference StrategyDecider)."""
    ds = _store()
    trace = ds.explain(
        "t",
        "bbox(geom, -10, -10, 10, 10) AND dtg DURING 2024-01-02T00:00:00Z/2024-01-04T00:00:00Z",
    )
    assert "Strategy: z3" in trace
    trace2 = ds.explain("t", "bbox(geom, -10, -10, 10, 10)")
    assert "Strategy: z2" in trace2


def test_histogram_rebin_merge():
    a = Histogram(10, 0.0, 10.0)
    a.observe(np.full(100, 5.0))
    b = Histogram(10, 50.0, 100.0)
    b.observe(np.full(50, 75.0))
    a += b
    assert a.lo == 0.0 and a.hi == 100.0
    assert a.counts.sum() == 150
    assert 90 < a.estimate_range(0.0, 10.0) < 110
    assert 40 < a.estimate_range(70.0, 80.0) < 60


def test_incremental_write_stats():
    """Stats accumulate across write batches (no full rebuild, no
    double-counted z3 sketch)."""
    sft = FeatureType.from_spec("inc", "name:String,dtg:Date,*geom:Point:srid=4326")
    ds = DataStore(tile=64)
    ds.create_schema(sft)
    rng = np.random.default_rng(9)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)

    def batch(k, n):
        return FeatureCollection.from_columns(
            sft,
            [f"{k}-{i}" for i in range(n)],
            {
                "name": np.array([f"u{i % 5}" for i in range(n)]),
                "dtg": t0 + rng.integers(0, 86400_000, n),
                "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n)),
            },
        )

    ds.write("inc", batch(0, 500))
    ds.write("inc", batch(1, 700))
    st = ds.stats_for("inc")
    assert st.total_count() == 1200
    # sketch mass equals row count exactly once (delta feeding)
    assert sum(st.z3.cells.values()) == 1200


def test_estimate_count():
    ds = _store()
    q = "bbox(geom, -60, -40, 60, 40) AND dtg DURING 2024-01-05T00:00:00Z/2024-01-15T00:00:00Z"
    est = ds.estimate_count("t", q)
    exact = ds.count("t", q)
    assert exact > 0
    assert 0.5 * exact < est < 2.0 * exact


def test_cost_changes_with_distribution():
    """Cost reflects actual data distribution: a bbox covering the dense
    half of the data costs more than the empty half (VERDICT task 8)."""
    sft = FeatureType.from_spec("d", "dtg:Date,*geom:Point:srid=4326")
    ds = DataStore(tile=64)
    ds.create_schema(sft)
    n = 4000
    rng = np.random.default_rng(6)
    # all points in the eastern hemisphere
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    fc = FeatureCollection.from_columns(
        sft,
        [str(i) for i in range(n)],
        {
            "dtg": t0 + rng.integers(0, 86400_000, n),
            "geom": (rng.uniform(10, 170, n), rng.uniform(-80, 80, n)),
        },
    )
    ds.write("d", fc)
    from geomesa_tpu.filter import ecql

    dense = ecql.parse("bbox(geom, 10, -80, 170, 80)")
    empty = ecql.parse("bbox(geom, -170, -80, -10, 80)")
    idx = [i for i in ds.indexes("d") if i.name == "z2"][0]
    c_dense = ds.planner.cost("d", "z2", idx.scan_config(dense), None)
    c_empty = ds.planner.cost("d", "z2", idx.scan_config(empty), None)
    assert c_dense > 100 * c_empty


class TestMarginalEstimator:
    """Marginal-histogram selectivity (estimate_bbox / estimate_filter):
    the bbox-only and spatio-temporal estimate paths on a z3-keyed store
    (the z-prefix sketch alone underestimated clustered data ~17x)."""

    @pytest.fixture(scope="class")
    def st_store(self):
        from geomesa_tpu.datastore import DataStore
        from geomesa_tpu.features import FeatureCollection

        rng = np.random.default_rng(31)
        sft = FeatureType.from_spec("st", "dtg:Date,*geom:Point:srid=4326")
        sft.user_data["geomesa.indices.enabled"] = "z3,z2"
        ds = DataStore()
        ds.create_schema(sft)
        n = 30000
        x = rng.normal(0, 0.5, n)
        y = rng.normal(0, 0.5, n)
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
        t = t0 + rng.integers(0, 30 * 86400_000, n)
        ds.write(
            "st",
            FeatureCollection.from_columns(
                sft, np.arange(n), {"dtg": t, "geom": (x, y)}
            ),
            check_ids=False,
        )
        return ds, (x, y, t)

    def test_bbox_only_on_z3_store(self, st_store):
        ds, (x, y, t) = st_store
        est = ds.estimate_count("st", "bbox(geom, -1, -1, 1, 1)")
        true = int(((x >= -1) & (x <= 1) & (y >= -1) & (y <= 1)).sum())
        assert 0.3 * true < est < 3 * true

    def test_spatiotemporal_product(self, st_store):
        ds, (x, y, t) = st_store
        lo = np.datetime64("2024-01-05", "ms").astype(np.int64)
        hi = np.datetime64("2024-01-20", "ms").astype(np.int64)
        est = ds.estimate_count(
            "st",
            "bbox(geom, -1, -1, 1, 1) AND dtg DURING "
            "2024-01-05T00:00:00Z/2024-01-20T00:00:00Z",
        )
        m = (x >= -1) & (x <= 1) & (y >= -1) & (y <= 1) & (t >= lo) & (t < hi)
        true = int(m.sum())
        assert 0.3 * true < est < 3 * true

    def test_disjoint_estimates_zero(self, st_store):
        ds, _ = st_store
        assert ds.estimate_count(
            "st", "bbox(geom, 0, 0, 1, 1) AND bbox(geom, 5, 5, 6, 6)"
        ) == 0

    def test_sparse_region_radius_grows(self, st_store):
        from geomesa_tpu.process.knn import _estimate_radius_m

        ds, _ = st_store
        r_dense = _estimate_radius_m(ds, "st", 10, 0.0, 0.0, 5e6)
        r_sparse = _estimate_radius_m(ds, "st", 10, 40.0, 40.0, 5e6)
        assert r_sparse > 10 * r_dense


class TestTakeBoundsGuard:
    def test_out_of_range_raises_and_negative_works(self):
        from geomesa_tpu.features import FeatureCollection

        sft = FeatureType.from_spec("t", "v:Integer,*geom:Point:srid=4326")
        n = 100
        fc = FeatureCollection.from_columns(
            sft, np.arange(n),
            {"v": np.arange(n), "geom": (np.zeros(n), np.zeros(n))},
        )
        with pytest.raises(IndexError):
            fc.take(np.array([n]))
        assert int(np.asarray(fc.take(np.array([-1])).columns["v"])[0]) == n - 1


def test_string_column_with_nones_writes_and_queries():
    """None in a String column must not crash the write-path sketches
    (np.unique can't sort mixed None/str); IS NULL and equality still
    answer correctly."""
    sft = FeatureType.from_spec("s", "name:String,*geom:Point:srid=4326")
    ds = DataStore()
    ds.create_schema(sft)
    names = np.empty(4, dtype=object)
    names[:] = ["a", None, "b", None]
    ds.write("s", FeatureCollection.from_columns(
        sft, np.arange(4), {"name": names, "geom": (np.arange(4.0), np.zeros(4))}
    ))
    assert sorted(np.asarray(ds.query("s", "name IS NULL").ids, np.int64).tolist()) == [1, 3]
    assert np.asarray(ds.query("s", "name = 'a'").ids, np.int64).tolist() == [0]


class TestDescriptiveStats:
    """Mergeable moments sketch vs numpy ground truth (reference
    DescriptiveStats.scala)."""

    def test_univariate_vs_numpy(self):
        from geomesa_tpu.stats.sketches import DescriptiveStats

        rng = np.random.default_rng(3)
        x = rng.gamma(2.0, 3.0, 10_000)  # skewed so g1/g2 are non-trivial
        d = DescriptiveStats(1)
        d.observe(x)
        assert d.count == len(x)
        assert d.min[0] == x.min() and d.max[0] == x.max()
        assert d.mean[0] == pytest.approx(x.mean(), rel=1e-12)
        assert d.variance(sample=False)[0] == pytest.approx(x.var(), rel=1e-10)
        assert d.variance(sample=True)[0] == pytest.approx(x.var(ddof=1), rel=1e-10)
        m = x.mean()
        g1 = np.mean((x - m) ** 3) / np.var(x) ** 1.5
        g2 = np.mean((x - m) ** 4) / np.var(x) ** 2 - 3.0
        assert d.skewness()[0] == pytest.approx(g1, rel=1e-8)
        assert d.kurtosis()[0] == pytest.approx(g2, rel=1e-8)

    def test_merge_exact(self):
        from geomesa_tpu.stats.sketches import DescriptiveStats

        rng = np.random.default_rng(4)
        x = rng.normal(5, 2, 5000)
        y = 0.5 * x + rng.normal(0, 1, 5000)
        whole = DescriptiveStats(2)
        whole.observe(x, y)
        merged = DescriptiveStats(2)
        for lo, hi in ((0, 1234), (1234, 1235), (1235, 5000)):
            part = DescriptiveStats(2)
            part.observe(x[lo:hi], y[lo:hi])
            merged += part
        assert merged.count == whole.count
        np.testing.assert_allclose(merged.mean, whole.mean, rtol=1e-12)
        np.testing.assert_allclose(merged.m2, whole.m2, rtol=1e-9)
        np.testing.assert_allclose(merged.m3, whole.m3, rtol=1e-8)
        np.testing.assert_allclose(merged.m4, whole.m4, rtol=1e-8)
        np.testing.assert_allclose(merged.comoment, whole.comoment, rtol=1e-9)

    def test_covariance_correlation(self):
        from geomesa_tpu.stats.sketches import DescriptiveStats

        rng = np.random.default_rng(5)
        x = rng.normal(0, 1, 8000)
        y = 0.8 * x + rng.normal(0, 0.6, 8000)
        d = DescriptiveStats(2)
        d.observe(x, y)
        want = np.cov(np.stack([x, y]), ddof=1)
        np.testing.assert_allclose(d.covariance(True), want, rtol=1e-9)
        corr = np.corrcoef(x, y)
        np.testing.assert_allclose(d.correlation(), corr, rtol=1e-9)
        j = d.to_json()
        assert j["count"] == 8000 and len(j["correlation"]) == 2

    def test_empty_and_dsl(self):
        from geomesa_tpu.stats import stat_spec
        from geomesa_tpu.stats.sketches import DescriptiveStats

        assert DescriptiveStats(1).to_json() == {"count": 0}
        sft = FeatureType.from_spec("d", "a:Double,b:Double,*geom:Point:srid=4326")
        n = 100
        rng = np.random.default_rng(6)
        a, b = rng.normal(0, 1, n), rng.normal(0, 1, n)
        fc = FeatureCollection.from_columns(
            sft, np.arange(n).astype(str),
            {"a": a, "b": b, "geom": (np.zeros(n), np.zeros(n))},
        )
        (res,) = stat_spec.evaluate("DescriptiveStats(a,b)", fc)
        assert res.count == n
        assert res.mean[0] == pytest.approx(a.mean())
        # SeqStat: a ';' list yields one sketch per term
        seq = stat_spec.evaluate("Count();DescriptiveStats(a)", fc)
        assert len(seq) == 2 and seq[0].count == n


class TestZ3Frequency:
    def test_point_estimates(self):
        from geomesa_tpu.stats.sketches import Z3Frequency

        rng = np.random.default_rng(7)
        total_bits = 42
        zf = Z3Frequency(total_bits=total_bits, prefix_bits=12)
        # two hot cells + background noise
        hot_z = np.uint64(0x123) << np.uint64(30)
        bins = np.concatenate([
            np.full(5000, 10), np.full(3000, 11),
            rng.integers(0, 8, 2000),
        ]).astype(np.uint64)
        zs = np.concatenate([
            np.full(5000, hot_z),
            np.full(3000, hot_z),
            rng.integers(0, 1 << 42, 2000).astype(np.uint64),
        ])
        zf.observe(bins, zs)
        assert zf.count == 10000
        # count-min overestimates only
        assert zf.estimate(10, int(hot_z)) >= 5000
        assert zf.estimate(11, int(hot_z)) >= 3000
        assert zf.estimate(10, int(hot_z)) <= 5000 + 2000
        # a cold cell stays near zero
        assert zf.estimate(300, 0) < 500

    def test_merge(self):
        from geomesa_tpu.stats.sketches import Z3Frequency

        a = Z3Frequency(total_bits=42)
        b = Z3Frequency(total_bits=42)
        a.observe(np.full(100, 5), np.full(100, 1 << 20))
        b.observe(np.full(50, 5), np.full(50, 1 << 20))
        a += b
        assert a.estimate(5, 1 << 20) >= 150


class TestStatsReviewFixes:
    def test_nan_rows_skipped(self):
        from geomesa_tpu.stats.sketches import DescriptiveStats

        x = np.array([1.0, 2.0, np.nan, 4.0])
        y = np.array([10.0, 20.0, 30.0, 40.0])
        d = DescriptiveStats(2)
        d.observe(x, y)
        assert d.count == 3  # NaN row dropped entirely
        assert d.mean[0] == pytest.approx(np.mean([1, 2, 4]))
        assert d.mean[1] == pytest.approx(np.mean([10, 20, 40]))
        assert not np.isnan(d.variance()).any()

    def test_z3frequency_merge_mismatch_refused(self):
        from geomesa_tpu.stats.sketches import Z3Frequency

        a = Z3Frequency(total_bits=42, prefix_bits=12)
        b = Z3Frequency(total_bits=42, prefix_bits=16)
        with pytest.raises(ValueError):
            a += b
        with pytest.raises(ValueError):
            Z3Frequency(total_bits=42, prefix_bits=0)

    def test_z3frequency_no_bin_alias(self):
        from geomesa_tpu.stats.sketches import Z3Frequency

        # full-resolution prefix: z occupies 42 bits; bins must not bleed
        zf = Z3Frequency(total_bits=42, prefix_bits=42)
        z_big = (1 << 40) + 17
        zf.observe(np.full(1000, 0), np.full(1000, z_big))
        assert zf.estimate(0, z_big) >= 1000
        assert zf.estimate(1, z_big) < 500  # distinct bin, same z
        assert zf.estimate(1, z_big - (1 << 40)) < 500

    def test_empty_spec_rejected(self):
        from geomesa_tpu.stats import stat_spec

        with pytest.raises(ValueError, match="at least one attribute"):
            stat_spec.parse("DescriptiveStats()")
