"""Query & aggregation cache tier (geomesa_tpu.cache; docs/caching.md).

Covers the ISSUE 2 tentpole: canonical fingerprints (``a AND b`` ==
``b AND a``), LRU/TTL/cost-aware admission, single-flight stampede
protection, generation-based invalidation, tile-aggregate composition
exactness, per-query bypass/pin hints, explain/metrics wiring, and the
slow-marked bench scenario (BENCH_CACHE.json)."""

import json
import threading
import time

import numpy as np
import pytest

from geomesa_tpu.cache import (
    BUCKET_MS, CacheConfig, GenerationTracker, KeyRange, QueryCache,
    fingerprint, key_range_of, schema_signature,
)
from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.filter import ecql
from geomesa_tpu.filter.predicates import And, BBox, canonical_key
from geomesa_tpu.metrics import MetricsRegistry
from geomesa_tpu.planning.explain import Explainer
from geomesa_tpu.planning.hints import QueryHints
from geomesa_tpu.sft import FeatureType

T0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
DAY = 86_400_000
SPEC = "name:String,dtg:Date,*geom:Point:srid=4326"


def _store(n=3000, seed=0, cache=True, metrics=None, indices="z3"):
    sft = FeatureType.from_spec("t", SPEC)
    sft.user_data["geomesa.indices.enabled"] = indices
    ds = DataStore(metrics=metrics or MetricsRegistry(), cache=cache)
    ds.create_schema(sft)
    rng = np.random.default_rng(seed)
    ds.write("t", FeatureCollection.from_columns(
        sft, [f"f{i}" for i in range(n)],
        {"name": np.array([f"n{i % 5}" for i in range(n)], dtype=object),
         "dtg": T0 + rng.integers(0, 60 * DAY, n),
         "geom": (rng.uniform(-170, 170, n), rng.uniform(-80, 80, n))},
    ), check_ids=False)
    return ds


def _same_rows(a, b):
    """Byte-identical results up to row order."""
    ia = np.argsort(np.asarray(a.ids).astype(str))
    ib = np.argsort(np.asarray(b.ids).astype(str))
    assert np.array_equal(np.asarray(a.ids)[ia], np.asarray(b.ids)[ib])
    ax, ay = a.representative_xy()
    bx, by = b.representative_xy()
    assert np.array_equal(np.asarray(ax)[ia], np.asarray(bx)[ib])
    assert np.array_equal(np.asarray(ay)[ia], np.asarray(by)[ib])


Q = "bbox(geom, -10, -10, 40, 30)"


# -- fingerprints (satellite: deterministic conjunction ordering) ----------

class TestFingerprint:
    def test_and_order_collides(self):
        a = ecql.parse("bbox(geom, -10, -10, 40, 30) AND name = 'n1'")
        b = ecql.parse("name = 'n1' AND bbox(geom, -10, -10, 40, 30)")
        assert canonical_key(a) == canonical_key(b)

    def test_or_order_collides_nested(self):
        a = ecql.parse("(name = 'n1' OR name = 'n2') AND bbox(geom, 0, 0, 9, 9)")
        b = ecql.parse("bbox(geom, 0, 0, 9, 9) AND (name = 'n2' OR name = 'n1')")
        assert canonical_key(a) == canonical_key(b)

    def test_different_filters_do_not_collide(self):
        a = ecql.parse("bbox(geom, -10, -10, 40, 30)")
        b = ecql.parse("bbox(geom, -10, -10, 40, 31)")
        assert canonical_key(a) != canonical_key(b)

    def test_canonical_key_sorts_conjunction_children(self):
        f = ecql.parse("name = 'n1' AND bbox(geom, -10, -10, 40, 30)")
        g = ecql.parse("bbox(geom, -10, -10, 40, 30) AND name = 'n1'")
        ka, kb = canonical_key(f), canonical_key(g)
        assert ka == kb
        # the key renders children in sorted order regardless of input
        inner = ka[len("And("):-1]
        assert inner == ",".join(sorted(canonical_key(c) for c in f.filters))

    def test_store_level_collision(self):
        """Logically-equal conjunctions share ONE cache entry end-to-end."""
        reg = MetricsRegistry()
        ds = _store(metrics=reg)
        r1 = ds.query("t", "bbox(geom, -10, -10, 40, 30) AND name = 'n1'")
        r2 = ds.query("t", "name = 'n1' AND bbox(geom, -10, -10, 40, 30)")
        _same_rows(r1, r2)
        assert reg.counters["geomesa.cache.hit"] == 1
        assert reg.counters["geomesa.cache.miss"] == 1
        assert len(ds.cache.result) == 1

    def test_result_hints_change_key_timeout_does_not(self):
        sft = FeatureType.from_spec("t", SPEC)
        sig = schema_signature(sft)
        f = ecql.parse(Q)

        def fp(hints):
            return fingerprint("t", sig, 0, "z3", f, None, hints, None)

        base = fp(None)
        assert fp(QueryHints(timeout=5.0)) == base  # failure knob, not result
        assert fp(QueryHints(transforms=["name"])) != base
        assert fp(QueryHints(sort_by="name")) != base
        assert fp(QueryHints(loose=True)) != base

    def test_auths_change_key(self):
        sft = FeatureType.from_spec("t", SPEC)
        sig = schema_signature(sft)
        f = ecql.parse(Q)
        a = fingerprint("t", sig, 0, "z3", f, None, None, ("admin",))
        b = fingerprint("t", sig, 0, "z3", f, None, None, ("user",))
        c = fingerprint("t", sig, 0, "z3", f, None, None, None)
        assert len({a, b, c}) == 3


# -- generation tracker ----------------------------------------------------

class TestGenerations:
    def test_overlapping_bump_invalidates(self):
        g = GenerationTracker()
        tick = g.tick()
        kr = KeyRange(boxes=((0.0, 0.0, 10.0, 10.0),), interval=(T0, T0 + DAY))
        assert not g.stale("t", kr, tick)
        g.bump("t", bounds=(5.0, 5.0, 6.0, 6.0), time_range=(T0, T0 + DAY))
        assert g.stale("t", kr, tick)

    def test_disjoint_space_does_not_invalidate(self):
        g = GenerationTracker()
        tick = g.tick()
        kr = KeyRange(boxes=((0.0, 0.0, 10.0, 10.0),), interval=None)
        g.bump("t", bounds=(100.0, 50.0, 120.0, 60.0), time_range=None)
        assert not g.stale("t", kr, tick)

    def test_disjoint_time_does_not_invalidate(self):
        g = GenerationTracker()
        tick = g.tick()
        kr = KeyRange(boxes=None, interval=(T0, T0 + DAY))
        g.bump("t", bounds=None, time_range=(T0 + 200 * DAY, T0 + 201 * DAY))
        assert not g.stale("t", kr, tick)

    def test_unknown_range_covers_everything(self):
        g = GenerationTracker()
        tick = g.tick()
        kr = KeyRange(boxes=((0.0, 0.0, 1.0, 1.0),), interval=(T0, T0 + 1))
        g.bump("t")
        assert g.stale("t", kr, tick)

    def test_other_type_untouched(self):
        g = GenerationTracker()
        tick = g.tick()
        g.bump("other")
        assert not g.stale("t", KeyRange.everything(), tick)

    def test_bucket_width_matches_persistence_partitions(self):
        from geomesa_tpu.storage.persist import PARTITION_MS

        assert BUCKET_MS == PARTITION_MS


# -- result cache ----------------------------------------------------------

class TestResultCache:
    def test_hit_returns_identical_rows(self):
        reg = MetricsRegistry()
        ds = _store(metrics=reg)
        r1 = ds.query("t", Q)
        r2 = ds.query("t", Q)
        _same_rows(r1, r2)
        assert reg.counters["geomesa.cache.hit"] == 1
        assert reg.counters["geomesa.cache.miss"] == 1
        assert reg.gauges["geomesa.cache.bytes"] > 0

    def test_write_invalidates(self):
        reg = MetricsRegistry()
        ds = _store(metrics=reg)
        n0 = len(ds.query("t", Q))
        sft = ds.get_schema("t")
        ds.write("t", FeatureCollection.from_columns(
            sft, ["new0", "new1"],
            {"name": np.array(["z", "z"], dtype=object),
             "dtg": np.full(2, int(T0)),
             "geom": (np.array([5.0, 6.0]), np.array([5.0, 6.0]))},
        ), check_ids=False)
        assert len(ds.query("t", Q)) == n0 + 2
        assert reg.counters["geomesa.cache.invalidation"] >= 1

    def test_disjoint_write_keeps_entry_warm(self):
        reg = MetricsRegistry()
        ds = _store(metrics=reg)
        ds.query("t", Q)  # populate: box is -10..40 x -10..30
        sft = ds.get_schema("t")
        ds.write("t", FeatureCollection.from_columns(
            sft, ["far0"],
            {"name": np.array(["z"], dtype=object),
             "dtg": np.full(1, int(T0)),
             "geom": (np.array([150.0]), np.array([70.0]))},
        ), check_ids=False)
        ds.query("t", Q)
        assert reg.counters["geomesa.cache.hit"] == 1  # still served warm

    def test_delete_and_upsert_invalidate(self):
        ds = _store()
        before = ds.query("t", "name = 'n1'")
        ds.delete_features("t", "name = 'n1'")
        assert len(ds.query("t", "name = 'n1'")) == 0
        sft = ds.get_schema("t")
        fid = str(np.asarray(before.ids)[0])
        ds.upsert("t", FeatureCollection.from_columns(
            sft, [fid],
            {"name": np.array(["n1"], dtype=object),
             "dtg": np.full(1, int(T0)),
             "geom": (np.array([0.0]), np.array([0.0]))},
        ))
        assert len(ds.query("t", "name = 'n1'")) == 1

    def test_bypass_hint_skips_probe_and_populate(self):
        reg = MetricsRegistry()
        ds = _store(metrics=reg)
        ds.query("t", Q, hints=QueryHints(cache="bypass"))
        assert len(ds.cache.result) == 0
        assert reg.counters["geomesa.cache.hit"] == 0
        assert reg.counters["geomesa.cache.miss"] == 0

    def test_pin_hint_beats_admission_and_eviction(self):
        # admission threshold no real scan here will ever clear
        conf = CacheConfig(max_bytes=1 << 16, min_cost_s=1e9,
                           tile_max_entries=0)
        reg = MetricsRegistry()
        ds = _store(metrics=reg, cache=conf)
        ds.query("t", Q)  # unpinned: rejected by cost admission
        assert len(ds.cache.result) == 0
        assert reg.counters["geomesa.cache.reject"] >= 1
        ds.query("t", Q, hints=QueryHints(cache="pin"))
        assert len(ds.cache.result) == 1
        # eviction pressure: distinct PINNED queries exceed the byte
        # budget, yet the first pinned entry is never evicted
        for i in range(12):
            ds.query("t", f"bbox(geom, {-60 + i}, -40, {60 + i}, 40)",
                     hints=QueryHints(cache="pin"))
        ds.query("t", Q)
        assert reg.counters["geomesa.cache.hit"] >= 1

    def test_ttl_expires_entries(self):
        conf = CacheConfig(ttl_s=0.05, tile_max_entries=0)
        reg = MetricsRegistry()
        ds = _store(metrics=reg, cache=conf)
        ds.query("t", Q)
        ds.query("t", Q)
        assert reg.counters["geomesa.cache.hit"] == 1
        time.sleep(0.06)
        ds.query("t", Q)
        assert reg.counters["geomesa.cache.expired"] == 1
        assert reg.counters["geomesa.cache.miss"] == 2

    def test_lru_eviction_respects_byte_budget(self):
        # entries here run ~12-60 KB: a 96 KB budget admits each one but
        # holds only a few at a time, forcing LRU churn
        conf = CacheConfig(max_bytes=96_000, tile_max_entries=0)
        reg = MetricsRegistry()
        ds = _store(metrics=reg, cache=conf)
        for i in range(16):
            ds.query("t", f"bbox(geom, {-80 + i}, -60, {80 - i}, 60)")
        assert ds.cache.result.bytes_resident <= conf.max_bytes
        assert reg.counters["geomesa.cache.eviction"] >= 1

    def test_schema_drop_clears_entries(self):
        ds = _store()
        ds.query("t", Q)
        assert len(ds.cache.result) == 1
        ds.delete_schema("t")
        assert len(ds.cache.result) == 0

    def test_cache_disabled_by_zero_budget(self):
        reg = MetricsRegistry()
        ds = _store(metrics=reg, cache=CacheConfig(max_bytes=0))
        ds.query("t", Q)
        ds.query("t", Q)
        assert reg.counters["geomesa.cache.hit"] == 0

    def test_cache_on_vs_off_byte_identical(self):
        cached = _store(cache=True)
        plain = _store(cache=False)
        assert plain.cache is None
        for q in (Q, "name = 'n2'",
                  "bbox(geom, 0, 0, 90, 45) AND name = 'n3'"):
            for _ in range(2):  # second pass serves from cache
                _same_rows(cached.query("t", q), plain.query("t", q))


# -- single-flight (satellite: concurrency test) ---------------------------

class TestSingleFlight:
    def test_concurrent_identical_queries_share_one_scan(self):
        reg = MetricsRegistry()
        ds = _store(metrics=reg)
        n_threads = 8
        scans = []
        orig = ds.planner._execute

        def counting_execute(plan, explain=None, hints=None, **kw):
            scans.append(1)
            time.sleep(0.15)  # hold the flight open so waiters pile up
            return orig(plan, explain, hints, **kw)

        ds.planner._execute = counting_execute
        barrier = threading.Barrier(n_threads)
        results, errors = [None] * n_threads, []

        def worker(i):
            try:
                barrier.wait()
                results[i] = ds.query("t", Q)
            except Exception as e:  # pragma: no cover - diagnostic
                errors.append(e)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n_threads)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        assert not errors
        assert len(scans) == 1, f"expected 1 underlying scan, got {len(scans)}"
        assert reg.counters["geomesa.cache.miss"] == 1
        assert reg.counters["geomesa.cache.stampede.coalesced"] >= 1
        # every thread was served: one scanned, the rest coalesced onto
        # its flight (or hit the freshly admitted entry if they lost the
        # race to the flight window)
        assert (reg.counters["geomesa.cache.stampede.coalesced"]
                + reg.counters["geomesa.cache.hit"]) == n_threads - 1
        for r in results[1:]:
            _same_rows(results[0], r)

    def test_waiter_recomputes_when_write_lands_mid_flight(self):
        """A mutation during the leader's scan must not let waiters adopt
        the pre-write snapshot."""
        reg = MetricsRegistry()
        ds = _store(metrics=reg)
        sft = ds.get_schema("t")
        orig = ds.planner._execute
        started = threading.Event()  # leader is inside its scan

        def slow_execute(plan, explain=None, hints=None, **kw):
            first = not started.is_set()
            started.set()
            out = orig(plan, explain, hints, **kw)
            if first:
                # a mutation lands AFTER the leader's snapshot but before
                # its flight completes
                ds.write("t", FeatureCollection.from_columns(
                    sft, ["mid0"],
                    {"name": np.array(["z"], dtype=object),
                     "dtg": np.full(1, int(T0)),
                     "geom": (np.array([5.0]), np.array([5.0]))},
                ), check_ids=False)
                time.sleep(0.08)  # hold the flight so the waiter joins it
            return out

        ds.planner._execute = slow_execute
        out = {}

        def leader():
            out["leader"] = ds.query("t", Q)

        def waiter():
            started.wait(timeout=5)
            out["waiter"] = ds.query("t", Q)

        t1 = threading.Thread(target=leader)
        t2 = threading.Thread(target=waiter)
        t1.start(); t2.start(); t1.join(); t2.join()
        # the waiter must see the mid-flight write (the leader's snapshot
        # predates it) — generation validation forces its own scan
        assert len(out["waiter"]) == len(out["leader"]) + 1
        assert reg.counters["geomesa.cache.stampede.coalesced"] == 0


class TestScanConfigMemo:
    def test_memo_dropped_on_write(self):
        """The planner's scan-config memo may not outlive a write: z3
        time bins clamp to the data's bin_range, which GROWS with writes
        — a stale memo entry would silently exclude the new bins (even
        on bypass queries; the memo sits under the result cache)."""
        sft = FeatureType.from_spec("t", SPEC)
        sft.user_data["geomesa.indices.enabled"] = "z3"
        ds = DataStore(cache=True)
        ds.create_schema(sft)

        def batch(ids, t):
            n = len(ids)
            return FeatureCollection.from_columns(
                sft, ids,
                {"name": np.array(["a"] * n, dtype=object),
                 "dtg": np.full(n, int(t)),
                 "geom": (np.zeros(n), np.zeros(n))})

        ds.write("t", batch(["a0"], T0), check_ids=False)
        q = ("bbox(geom, -1, -1, 1, 1) AND dtg DURING "
             "2024-01-01T00:00:00Z/2024-03-01T00:00:00Z")
        bypass = QueryHints(cache="bypass")
        assert len(ds.query("t", q, hints=bypass)) == 1  # memoizes config
        # 40 days later: a NEW z3 time bin, beyond the clamped range the
        # memoized decomposition saw
        ds.write("t", batch(["a1"], T0 + 40 * DAY), check_ids=False)
        assert len(ds.query("t", q, hints=bypass)) == 2
        assert len(ds.query("t", q)) == 2


# -- tile-aggregate cache --------------------------------------------------

class TestTileCache:
    def test_count_composition_exact_fuzz(self):
        reg = MetricsRegistry()
        ds = _store(n=4000, metrics=reg)
        plain = _store(n=4000, cache=False)
        rng = np.random.default_rng(7)
        for _ in range(12):
            x0 = float(rng.uniform(-170, 100))
            y0 = float(rng.uniform(-80, 40))
            w = float(rng.uniform(15, 70))
            q = f"bbox(geom, {x0}, {y0}, {x0 + w}, {y0 + w / 2})"
            assert ds.count("t", q) == len(plain.query("t", q)), q
        assert reg.counters.get("geomesa.cache.tile.reused", 0) > 0

    def test_bounds_composition_exact(self):
        ds = _store(n=4000)
        plain = _store(n=4000, cache=False)
        q = "bbox(geom, -60, -40, 60, 40)"
        got = ds.bounds("t", q)
        rows = plain.query("t", q)
        x, y = rows.representative_xy()
        want = (float(np.min(x)), float(np.min(y)),
                float(np.max(x)), float(np.max(y)))
        assert got == pytest.approx(want, abs=0)

    def test_tile_edge_rows_never_double_count(self):
        """Points exactly ON tile edges and query edges: half-open tile
        membership + closed query semantics must still compose exactly."""
        sft = FeatureType.from_spec("t", SPEC)
        sft.user_data["geomesa.indices.enabled"] = "z2"
        conf = CacheConfig(tile_bits=4)  # 22.5 x 11.25 degree tiles
        ds = DataStore(cache=conf)
        ds.create_schema(sft)
        step_x, step_y = 360.0 / 16, 180.0 / 16
        # a lattice of points sitting exactly on tile corners
        gx = -180.0 + np.arange(1, 15) * step_x
        gy = -90.0 + np.arange(1, 15) * step_y
        xx, yy = np.meshgrid(gx, gy)
        x, y = xx.ravel(), yy.ravel()
        n = len(x)
        ds.write("t", FeatureCollection.from_columns(
            sft, [f"e{i}" for i in range(n)],
            {"name": np.array(["e"] * n, dtype=object),
             "dtg": np.full(n, int(T0)), "geom": (x, y)},
        ), check_ids=False)
        plain = DataStore()
        plain.create_schema(FeatureType.from_spec("t", SPEC))
        plain.write("t", FeatureCollection.from_columns(
            plain.get_schema("t"), [f"e{i}" for i in range(n)],
            {"name": np.array(["e"] * n, dtype=object),
             "dtg": np.full(n, int(T0)), "geom": (x, y)},
        ), check_ids=False)
        # query boxes whose edges land exactly on tile edges, twice (the
        # second pass composes from cached tiles)
        for x0, y0, x1, y1 in (
            (-180.0 + step_x, -90.0 + step_y, step_x * 3, step_y * 2),
            (-step_x * 2, -step_y * 2, step_x * 2, step_y * 2),
            (0.0, 0.0, step_x * 4, step_y * 3),
        ):
            q = f"bbox(geom, {x0}, {y0}, {x1}, {y1})"
            want = len(plain.query("t", q))
            assert ds.count("t", q) == want, q
            assert ds.count("t", q) == want, q

    def test_shifted_bbox_reuses_interior(self):
        reg = MetricsRegistry()
        ds = _store(n=4000, metrics=reg)
        ds.count("t", "bbox(geom, -60, -40, 60, 40)")
        filled = reg.counters["geomesa.cache.tile.filled"]
        reused0 = reg.counters.get("geomesa.cache.tile.reused", 0)
        assert filled > 0
        # a 10%-shifted dashboard pan: most interior tiles come from cache
        ds.count("t", "bbox(geom, -48, -36, 72, 44)")
        assert reg.counters["geomesa.cache.tile.reused"] > reused0

    def test_write_invalidates_overlapping_tiles(self):
        ds = _store(n=4000)
        plain = _store(n=4000, cache=False)
        q = "bbox(geom, -60, -40, 60, 40)"
        assert ds.count("t", q) == len(plain.query("t", q))
        sft = ds.get_schema("t")
        batch = FeatureCollection.from_columns(
            sft, ["w0", "w1", "w2"],
            {"name": np.array(["w"] * 3, dtype=object),
             "dtg": np.full(3, int(T0)),
             "geom": (np.array([0.0, 1.0, 2.0]), np.array([0.0, 1.0, 2.0]))},
        )
        ds.write("t", batch, check_ids=False)
        plain.write("t", batch, check_ids=False)
        assert ds.count("t", q) == len(plain.query("t", q))

    def test_adaptive_cost_gate(self):
        """Composition that measures slower than the plain scan it
        replaces gates itself off — and re-probes periodically, reopening
        when the balance shifts back."""
        reg = MetricsRegistry()
        ds = _store(n=4000, metrics=reg)
        tc = ds.cache.tiles
        for _ in range(6):  # losing compositions vs a 10ms plain scan
            tc._note_compose("t", 0.050)
        tc.note_scan("t", 0.010)
        opened = [tc.worth_composing("t") for _ in range(16)]
        assert opened.count(False) >= 10          # mostly gated
        assert opened[1:].count(True) >= 1        # but re-probes
        assert reg.counters["geomesa.cache.tile.gated"] >= 10
        for _ in range(12):  # cheap composes reopen the gate for good
            tc._note_compose("t", 0.001)
        assert all(tc.worth_composing("t") for _ in range(8))
        # a composition's own union scan is not a plain-scan sample
        tc._scanning.active = True
        tc.note_scan("t", 99.0)
        tc._scanning.active = False
        assert tc._scan_s["t"] < 1.0

    def test_compose_duration_not_a_scan_sample(self):
        """A composition-served stats_query/bounds must not feed the
        adaptive gate's plain-scan baseline with its own duration (the
        gate would then compare composing against itself and never
        trip); the composition's inner union scan is excluded too."""
        ds = _store(n=2000)
        tc = ds.cache.tiles
        out = ds.stats_query("t", "Count()", "bbox(geom, -60, -40, 60, 40)")
        assert out[0].count > 0
        assert "t" not in tc._scan_s
        # a real row query IS a baseline sample
        ds.query("t", "bbox(geom, -60, -40, 60, 40)")
        assert "t" in tc._scan_s

    def test_tile_cache_disabled_for_visibility(self):
        """Row-level visibility changes per-row membership: the tile tier
        must decline, falling back to the (auth-fingerprinted) row path."""
        sft = FeatureType.from_spec(
            "t", "name:String,vis:String,dtg:Date,*geom:Point:srid=4326")
        sft.user_data["geomesa.vis.field"] = "vis"
        ds = DataStore(cache=True, auths=("a",))
        ds.create_schema(sft)
        n = 50
        ds.write("t", FeatureCollection.from_columns(
            sft, [f"v{i}" for i in range(n)],
            {"name": np.array(["x"] * n, dtype=object),
             "vis": np.array(["a" if i % 2 else "b" for i in range(n)],
                             dtype=object),
             "dtg": np.full(n, int(T0)),
             "geom": (np.linspace(-50, 50, n), np.linspace(-40, 40, n))},
        ), check_ids=False)
        assert ds._tile_compose("t", ecql.parse("bbox(geom, -60, -60, 60, 60)")) is None


# -- explain + metrics (satellite: attributable probe time) ----------------

class TestExplainAndMetrics:
    def test_explain_reports_status_and_probe_time(self):
        ds = _store()
        exp = Explainer()
        ds.query("t", Q, explain=exp)
        [line] = [l for l in exp.lines if l.strip().startswith("cache:")]
        assert "miss" in line and "probe" in line and "ms" in line
        exp = Explainer()
        ds.query("t", Q, explain=exp)
        [line] = [l for l in exp.lines if l.strip().startswith("cache:")]
        assert "hit" in line

    def test_probe_time_separate_from_scan_time(self):
        reg = MetricsRegistry()
        ds = _store(metrics=reg)
        ds.query("t", Q)
        ds.query("t", Q)
        probe = reg.timers["geomesa.query.cache_probe"]
        scan = reg.histograms["geomesa.query.scan"]
        assert probe.count == 2 and scan.count == 2
        # the probe is cache machinery only — it can never exceed the
        # whole execute the scan histogram covers
        assert probe.total_s <= scan.sum_s

    def test_plan_carries_cache_outcome(self):
        ds = _store()
        plan = ds.planner.plan("t", Q)
        ds.planner.execute(plan)
        assert plan.cache_status == "miss"
        assert plan.cache_probe_s >= 0.0
        plan2 = ds.planner.plan("t", Q)
        ds.planner.execute(plan2)
        assert plan2.cache_status == "hit"

    def test_tile_explain_reports_partial_then_hit(self):
        ds = _store(n=4000)
        exp = Explainer()
        ds.stats_query("t", "Count()", f="bbox(geom, -60, -40, 60, 40)",
                       explain=exp)
        [line] = [l for l in exp.lines if l.strip().startswith("cache:")]
        assert "tiles reused" in line
        exp = Explainer()
        ds.stats_query("t", "Count()", f="bbox(geom, -60, -40, 60, 40)",
                       explain=exp)
        [line] = [l for l in exp.lines if l.strip().startswith("cache:")]
        assert line.strip().startswith("cache: hit")

    def test_bad_cache_hint_rejected(self):
        with pytest.raises(ValueError):
            QueryHints(cache="nope").validate()


# -- streaming interplay ---------------------------------------------------

class TestStreamingInterplay:
    def test_lambda_hot_mutations_bump_generations(self):
        from geomesa_tpu.streaming import LambdaStore

        ds = _store(n=200)
        lam = LambdaStore(ds, "t", expiry_ms=10_000)
        assert lam.hot.generations is ds.cache.generations
        t0 = ds.cache.generations.tick()
        lam.write([{"name": "h", "dtg": int(T0), "geom": "POINT(1 1)"}],
                  ids=["h0"])
        assert ds.cache.generations.tick() > t0
        t1 = ds.cache.generations.tick()
        lam.hot.delete(["h0"])
        assert ds.cache.generations.tick() > t1

    def test_lambda_expiry_bumps(self):
        from geomesa_tpu.streaming import LambdaStore

        ds = _store(n=200)
        lam = LambdaStore(ds, "t", expiry_ms=1)
        lam.write([{"name": "h", "dtg": int(T0), "geom": "POINT(1 1)"}],
                  ids=["h0"])
        t0 = ds.cache.generations.tick()
        assert lam.hot.expire(now_ms=int(time.time() * 1000) + 10_000) == 1
        assert ds.cache.generations.tick() > t0

    def test_flush_invalidates_cold_cached_results(self):
        from geomesa_tpu.streaming import LambdaStore

        ds = _store(n=200)
        n0 = len(ds.query("t", Q))  # populate the cold result cache
        lam = LambdaStore(ds, "t")
        lam.write([{"name": "h", "dtg": int(T0), "geom": "POINT(5 5)"}],
                  ids=["hot0"])
        lam.persist_hot()
        assert len(ds.query("t", Q)) == n0 + 1


# -- bench scenario (satellite: CI/tooling; slow-marked) -------------------

@pytest.mark.slow
def test_bench_cache_scenario(tmp_path, monkeypatch):
    import bench

    monkeypatch.setenv("GEOMESA_BENCH_CACHE_N", "400000")
    monkeypatch.setenv("GEOMESA_BENCH_CACHE_QUERIES", "8")
    out = tmp_path / "BENCH_CACHE.json"
    rec = bench.config_cache(out_path=str(out))
    assert out.exists()
    data = json.loads(out.read_text())
    repeat = data["repeat_query"]
    assert repeat["hit_rate"] >= 0.99
    # acceptance: >= 5x latency reduction on a warm cache
    assert repeat["speedup"] >= 5.0, repeat
    shifted = data["shifted_bbox"]
    # either interior tiles composed, or the adaptive cost gate decided
    # composing loses on this backend/scale and protected the workload —
    # both are the tile tier working; which one wins is data-dependent
    assert shifted["tiles_reused_frac"] > 0.0 or shifted["gated"] > 0
    assert rec["metric"] == "cache_repeat_query_speedup"
