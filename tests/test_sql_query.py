"""SELECT front-end with ST_ predicate push-down (VERDICT r4 missing #5).

Reference: GeoMesaRelation + SQLRules — ST_ predicates rewrite into
GeoTools filters pushed into the relation scan; everything else evaluates
above it. Differential: sql_query == hand-built query + numpy truth.
"""

import numpy as np
import pytest

from geomesa_tpu.datastore import DataStore
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType
from geomesa_tpu.sql import sql_query
from geomesa_tpu.sql.query import parse_select

N = 4000


@pytest.fixture(scope="module")
def ds():
    rng = np.random.default_rng(77)
    sft = FeatureType.from_spec(
        "pts", "name:String:index=true,score:Double,dtg:Date,*geom:Point:srid=4326"
    )
    store = DataStore(tile=64)
    store.create_schema(sft)
    t0 = np.datetime64("2024-01-01T00:00:00", "ms").astype(np.int64)
    x = rng.uniform(-90, 90, N)
    y = rng.uniform(-45, 45, N)
    store.write("pts", FeatureCollection.from_columns(
        sft, [str(i) for i in range(N)],
        {"name": np.array(["a", "b", "c", "d"])[rng.integers(0, 4, N)],
         "score": rng.uniform(0, 100, N),
         "dtg": t0 + rng.integers(0, 30 * 86400_000, N),
         "geom": (x, y)},
    ))
    return store, x, y


class TestPushdown:
    def test_intersects_pushdown(self, ds):
        store, x, y = ds
        out = sql_query(store, (
            "SELECT * FROM pts WHERE st_intersects(geom, "
            "st_geomfromwkt('POLYGON((0 0, 40 0, 40 20, 0 20, 0 0))'))"
        ))
        want = (x >= 0) & (x <= 40) & (y >= 0) & (y <= 20)
        assert len(out) == int(want.sum())
        # the spatial predicate became an index plan, not a full scan
        plan = store.planner.plan(
            "pts", parse_select(
                "SELECT * FROM pts WHERE st_intersects(geom, "
                "st_geomfromwkt('POLYGON((0 0, 40 0, 40 20, 0 20, 0 0))'))",
                store.get_schema("pts"),
            ).filter,
        )
        assert plan.index is not None

    def test_contains_and_attribute(self, ds):
        store, x, y = ds
        out = sql_query(store, (
            "SELECT name FROM pts WHERE st_contains("
            "st_makebbox(-50, -30, 10, 10), geom) AND name = 'a'"
        ))
        names = np.asarray(store.features("pts").columns["name"])
        want = (x > -50) & (x < 10) & (y > -30) & (y < 10) & (names == "a")
        assert len(out) == int(want.sum())
        assert list(out.columns) == ["name"]

    def test_comparison_between_in_like(self, ds):
        store, x, y = ds
        fc = store.features("pts")
        score = np.asarray(fc.columns["score"])
        names = np.asarray(fc.columns["name"])
        out = sql_query(store, "SELECT * FROM pts WHERE score BETWEEN 20 AND 30")
        assert len(out) == int(((score >= 20) & (score <= 30)).sum())
        out = sql_query(store, "SELECT * FROM pts WHERE name IN ('a', 'c')")
        assert len(out) == int(np.isin(names, ["a", "c"]).sum())
        out = sql_query(store, "SELECT * FROM pts WHERE 50 < score")
        assert len(out) == int((score > 50).sum())

    def test_order_limit_offset(self, ds):
        store, *_ = ds
        out = sql_query(
            store, "SELECT name, score FROM pts ORDER BY score DESC LIMIT 5"
        )
        s = np.asarray(out.columns["score"])
        assert len(out) == 5 and (np.diff(s) <= 0).all()
        out2 = sql_query(
            store, "SELECT score FROM pts ORDER BY score DESC LIMIT 5 OFFSET 2"
        )
        assert len(out2) == 5
        np.testing.assert_allclose(
            np.asarray(out2.columns["score"])[:3], s[2:5], rtol=0
        )


class TestResiduals:
    def test_non_pushable_st_call(self, ds):
        store, x, y = ds
        out = sql_query(store, (
            "SELECT * FROM pts WHERE st_bbox(geom, -20, -20, 20, 20) "
            "AND st_x(geom) > 5"
        ))
        want = (x >= -20) & (x <= 20) & (y >= -20) & (y <= 20) & (x > 5)
        assert len(out) == int(want.sum())

    def test_residual_with_limit_exact(self, ds):
        store, x, y = ds
        out = sql_query(store, (
            "SELECT * FROM pts WHERE st_bbox(geom, -90, -45, 90, 45) "
            "AND st_x(geom) > 0 ORDER BY score LIMIT 7"
        ))
        assert len(out) == 7
        assert (np.asarray(out.geom_column.x) > 0).all()
        s = np.asarray(out.columns["score"])
        assert (np.diff(s) >= 0).all()

    def test_select_expressions(self, ds):
        store, x, y = ds
        out = sql_query(
            store, "SELECT st_x(geom) AS lon, name FROM pts LIMIT 10"
        )
        assert list(out.columns) == ["lon", "name"]
        assert len(out) == 10

    def test_mixed_or_falls_residual(self, ds):
        store, x, y = ds
        fc = store.features("pts")
        score = np.asarray(fc.columns["score"])
        out = sql_query(store, (
            "SELECT * FROM pts WHERE score > 90 OR st_x(geom) > 85"
        ))
        want = (score > 90) | (x > 85)
        assert len(out) == int(want.sum())

    def test_bad_sql_raises(self, ds):
        store, *_ = ds
        with pytest.raises(ValueError):
            sql_query(store, "SELECT * WHERE x = 1")
        with pytest.raises(ValueError):
            sql_query(store, "SELECT * FROM pts WHERE")


class TestOrderByAlias:
    def test_order_by_select_alias(self, ds):
        store, x, y = ds
        out = sql_query(store, (
            "SELECT st_x(geom) AS lon FROM pts "
            "WHERE st_bbox(geom, -20, -20, 20, 20) ORDER BY lon DESC LIMIT 6"
        ))
        lons = np.asarray(out.columns["lon"])
        assert len(out) == 6 and (np.diff(lons) <= 0).all()
        want = np.sort(x[(x >= -20) & (x <= 20) & (y >= -20) & (y <= 20)])[::-1][:6]
        np.testing.assert_allclose(lons, want)

    def test_order_by_alias_with_residual(self, ds):
        store, x, y = ds
        out = sql_query(store, (
            "SELECT st_x(geom) AS lon FROM pts WHERE "
            "st_bbox(geom, -20, -20, 20, 20) AND st_y(geom) > 0 "
            "ORDER BY lon LIMIT 4"
        ))
        lons = np.asarray(out.columns["lon"])
        sel = (x >= -20) & (x <= 20) & (y >= -20) & (y <= 20) & (y > 0)
        np.testing.assert_allclose(lons, np.sort(x[sel])[:4])
