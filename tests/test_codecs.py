"""TWKB codec, geohash, Parquet IO, CLI playback (round-4 parity adds)."""

import numpy as np
import pytest

from geomesa_tpu import geometry as geo
from geomesa_tpu.features import FeatureCollection
from geomesa_tpu.sft import FeatureType


class TestTwkb:
    def _rt(self, g, prec=7):
        from geomesa_tpu.io.twkb import from_twkb, to_twkb

        return from_twkb(to_twkb(g, prec))

    def test_point_precision(self):
        p = self._rt(geo.Point(10.123456789, -45.987654321))
        assert abs(p.x - 10.1234568) < 1e-7
        assert abs(p.y + 45.9876543) < 1e-7

    def test_linestring_delta_compression(self):
        from geomesa_tpu.io.twkb import to_twkb

        rng = np.random.default_rng(0)
        track = np.cumsum(rng.normal(0, 0.001, (500, 2)), axis=0) + [10, 20]
        line = geo.LineString(track)
        got = self._rt(line, 6)
        np.testing.assert_allclose(got.coords, np.round(track * 1e6) / 1e6, atol=1e-9)
        # delta varints beat WKB's fixed doubles by ~4x on smooth tracks
        assert len(to_twkb(line, 6)) * 3 < len(geo.to_wkb(line))

    def test_polygon_with_hole(self):
        shell = np.array([[0, 0], [10, 0], [10, 10], [0, 10], [0, 0]], float)
        hole = np.array([[2, 2], [4, 2], [4, 4], [2, 4], [2, 2]], float)
        pg = self._rt(geo.Polygon(shell, [hole]))
        np.testing.assert_allclose(pg.shell, shell)
        np.testing.assert_allclose(pg.holes[0], hole)

    def test_multis_and_empty(self):
        shell = np.array([[0, 0], [1, 0], [1, 1], [0, 1], [0, 0]], float)
        mp = self._rt(geo.MultiPolygon([geo.Polygon(shell), geo.Polygon(shell + 5)]))
        assert len(mp.parts) == 2
        np.testing.assert_allclose(mp.parts[1].shell, shell + 5)
        assert len(self._rt(geo.MultiPoint([])).parts) == 0

    def test_negative_precision(self):
        p = self._rt(geo.Point(12345.0, -6789.0), prec=-2)
        assert p.x == 12300.0 and p.y == -6800.0

    def test_bad_inputs(self):
        from geomesa_tpu.io.twkb import from_twkb, to_twkb

        with pytest.raises(ValueError, match="precision"):
            to_twkb(geo.Point(0, 0), precision=9)
        with pytest.raises(ValueError, match="metadata"):
            from_twkb(bytes([0x01, 0x02, 0, 0]))  # size flag unsupported


class TestGeohash:
    def test_known_vectors(self):
        from geomesa_tpu.utils import geohash as gh

        assert str(gh.encode(-5.603, 42.605, 5)) == "ezs42"
        assert str(gh.encode(10.40744, 57.64911, 11)) == "u4pruydqqvj"

    def test_roundtrip_all_precisions(self):
        from geomesa_tpu.utils import geohash as gh

        rng = np.random.default_rng(0)
        lon = rng.uniform(-180, 180, 200)
        lat = rng.uniform(-90, 90, 200)
        for p in (1, 5, 6, 12):
            hs = gh.encode(lon, lat, p)
            for h, lo, la in zip(hs.tolist()[:30], lon, lat):
                x0, y0, x1, y1 = gh.bbox(h)
                assert x0 <= lo <= x1 and y0 <= la <= y1
                cx, cy = gh.decode(h)
                assert str(gh.encode(cx, cy, p)) == h

    def test_neighbors(self):
        from geomesa_tpu.utils import geohash as gh

        n = gh.neighbors("ezs42")
        assert len(n) == 8 and len(set(n)) == 8
        for h in n:  # all adjacent cells touch the center cell's bbox
            x0, y0, x1, y1 = gh.bbox(h)
            cx0, cy0, cx1, cy1 = gh.bbox("ezs42")
            assert x0 <= cx1 + 1e-9 and x1 >= cx0 - 1e-9
            assert y0 <= cy1 + 1e-9 and y1 >= cy0 - 1e-9


class TestParquet:
    def test_point_roundtrip_and_pushdown(self, tmp_path):
        pytest.importorskip("pyarrow")
        from geomesa_tpu.io.parquet import read_parquet, write_parquet

        rng = np.random.default_rng(3)
        n = 5000
        sft = FeatureType.from_spec(
            "ev", "name:String,v:Integer,dtg:Date,*geom:Point:srid=4326"
        )
        x = rng.uniform(-180, 180, n)
        y = rng.uniform(-90, 90, n)
        t = np.datetime64("2024-01-01", "ms").astype(np.int64) + rng.integers(
            0, 10**9, n
        )
        fc = FeatureCollection.from_columns(
            sft, np.arange(n),
            {
                "name": np.array(["a", "b", "c"])[rng.integers(0, 3, n)].astype(object),
                "v": rng.integers(0, 100, n).astype(np.int32),
                "dtg": t,
                "geom": (x, y),
            },
        )
        p = tmp_path / "f.parquet"
        write_parquet(fc, p)
        back = read_parquet(p)  # schema from file metadata
        assert len(back) == n
        np.testing.assert_array_equal(np.asarray(back.columns["dtg"]), t)
        np.testing.assert_allclose(back.geom_column.x, x)
        sub = read_parquet(p, bbox=(-10, -10, 10, 10))
        m = (x >= -10) & (x <= 10) & (y >= -10) & (y <= 10)
        assert len(sub) == int(m.sum())

    def test_extent_roundtrip(self, tmp_path):
        pytest.importorskip("pyarrow")
        from geomesa_tpu.io.parquet import read_parquet, write_parquet

        sft = FeatureType.from_spec("bld", "*geom:Polygon:srid=4326")
        col = geo.PackedGeometryColumn.from_boxes(
            np.array([0.0, 5.0]), np.array([0.0, 5.0]),
            np.array([1.0, 6.0]), np.array([1.0, 6.0]),
        )
        fc = FeatureCollection.from_columns(sft, np.arange(2), {"geom": col})
        p = tmp_path / "g.parquet"
        write_parquet(fc, p)
        back = read_parquet(p)
        assert len(back) == 2
        np.testing.assert_allclose(back.geom_column.bboxes, col.bboxes, atol=1e-5)


class TestPlayback:
    def test_playback_command(self, tmp_path, capsys):
        from geomesa_tpu.cli import main
        from geomesa_tpu.datastore import DataStore
        from geomesa_tpu.storage import persist

        sft = FeatureType.from_spec("ev", "dtg:Date,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        rng = np.random.default_rng(4)
        n = 250
        t = np.datetime64("2024-01-01", "ms").astype(np.int64) + rng.integers(
            0, 10**8, n
        )
        ds.write("ev", FeatureCollection.from_columns(
            sft, np.arange(n),
            {"dtg": t, "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n))},
        ))
        persist.save(ds, tmp_path / "store")
        rc = main([
            "playback", "-c", str(tmp_path / "store"), "-f", "ev",
            "--batch-size", "100",
        ])
        assert rc == 0
        outp = capsys.readouterr().out
        assert f"played {n}/{n} (cache size {n})" in outp
        assert "playback done" in outp


class TestStatsAnalyze:
    def test_reanalyze_restores_histogram_resolution(self):
        """Real drift analyze_stats fixes: per-batch histograms rebin on
        merge when later batches widen the bounds, degrading resolution;
        a full re-sketch rebuilds at the final bounds."""
        from geomesa_tpu.datastore import DataStore

        sft = FeatureType.from_spec("ev", "v:Double,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        rng = np.random.default_rng(6)
        # batch 1 spans [0, 1]; batch 2 spans [0, 1000]: the merged
        # histogram rebins batch 1's mass into wide union-span bins
        for b, hi in enumerate((1.0, 1000.0)):
            n = 3000
            ds.write("ev", FeatureCollection.from_columns(
                sft, np.arange(b * n, (b + 1) * n),
                {"v": rng.uniform(0, hi, n),
                 "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n))},
            ), check_ids=False)
        drifted = ds.stats_for("ev").estimate_range("v", 0.0, 1.0)
        stats = ds.analyze_stats("ev")
        fresh = stats.estimate_range("v", 0.0, 1.0)
        true = 3000 + 3  # batch 1 entirely + ~3/1000 of batch 2
        # the fresh sketch must be strictly closer to the truth
        assert abs(fresh - true) < abs(drifted - true)
        assert 0.5 * true < fresh < 2 * true

    def test_cli_command(self, tmp_path, capsys):
        from geomesa_tpu.cli import main
        from geomesa_tpu.datastore import DataStore
        from geomesa_tpu.storage import persist

        sft = FeatureType.from_spec("ev", "v:Integer,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        rng = np.random.default_rng(6)
        n = 1000
        ds.write("ev", FeatureCollection.from_columns(
            sft, np.arange(n),
            {"v": np.arange(n), "geom": (rng.uniform(-10, 10, n), rng.uniform(-10, 10, n))},
        ))
        persist.save(ds, tmp_path / "s")
        rc = main(["stats-analyze", "-c", str(tmp_path / "s"), "-f", "ev"])
        assert rc == 0
        assert f"{n} features sketched" in capsys.readouterr().out
        # the command re-persists the store (reload still sees exact stats)
        ds2 = persist.load(tmp_path / "s")
        assert ds2.stats_for("ev").total_count() == n


class TestShapefileWriter:
    def test_point_roundtrip_with_attributes(self, tmp_path):
        from geomesa_tpu.io.shapefile import read_shapefile, write_shapefile

        rng = np.random.default_rng(0)
        n = 150
        sft = FeatureType.from_spec(
            "p", "name:String,v:Integer,s:Double,*geom:Point:srid=4326"
        )
        fc = FeatureCollection.from_columns(sft, np.arange(n), {
            "name": np.array([f"nm{i % 9}" for i in range(n)], dtype=object),
            "v": rng.integers(-50, 50, n).astype(np.int64),
            "s": rng.uniform(0, 10, n),
            "geom": (rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)),
        })
        base = str(tmp_path / "pts")
        write_shapefile(fc, base)
        back = read_shapefile(base + ".shp")
        assert len(back) == n
        np.testing.assert_array_equal(
            np.asarray(back.columns["v"]), np.asarray(fc.columns["v"])
        )
        np.testing.assert_allclose(
            np.asarray(back.columns["s"]), np.asarray(fc.columns["s"]), atol=1e-7
        )
        np.testing.assert_allclose(back.geom_column.x, fc.geom_column.x)
        assert list(back.columns["name"][:3]) == ["nm0", "nm1", "nm2"]

    def test_polygon_with_hole_roundtrip(self, tmp_path):
        from geomesa_tpu.io.shapefile import read_shapefile, write_shapefile

        shell = np.array([[0, 0], [10, 0], [10, 10], [0, 10], [0, 0]], float)
        hole = np.array([[2, 2], [2, 4], [4, 4], [4, 2], [2, 2]], float)
        sft = FeatureType.from_spec("pg", "*geom:Polygon:srid=4326")
        fc = FeatureCollection.from_rows(sft, [
            {"geom": geo.Polygon(shell, [hole])},
            {"geom": geo.Polygon(shell + 20)},
        ])
        base = str(tmp_path / "pg")
        write_shapefile(fc, base)
        back = read_shapefile(base + ".shp")
        assert len(back) == 2
        g0 = back.geom_column.geometry(0)
        assert isinstance(g0, geo.Polygon) and len(g0.holes) == 1
        assert abs(g0.area - (100 - 4)) < 1e-9

    def test_mixed_types_rejected(self, tmp_path):
        from geomesa_tpu.io.shapefile import write_shapefile

        sft = FeatureType.from_spec("m", "*geom:Geometry:srid=4326")
        fc = FeatureCollection.from_rows(sft, [
            {"geom": geo.Point(0, 0)},
            {"geom": geo.Polygon(np.array(
                [[0, 0], [1, 0], [1, 1], [0, 1], [0, 0]], float))},
        ])
        with pytest.raises(ValueError, match="single geometry type"):
            write_shapefile(fc, str(tmp_path / "m"))


class TestGmlExport:
    def test_well_formed_with_escaping(self):
        import xml.etree.ElementTree as ET

        from geomesa_tpu.io import export

        sft = FeatureType.from_spec(
            "ev", "name:String,dtg:Date,*geom:Point:srid=4326"
        )
        t0 = np.datetime64("2024-01-01", "ms").astype(np.int64)
        fc = FeatureCollection.from_columns(sft, ["a", "b"], {
            "name": np.array(["x<y&z", "ok"], dtype=object),
            "dtg": np.array([t0, t0 + 1000]),
            "geom": (np.array([1.5, -2.0]), np.array([3.0, 4.0])),
        })
        g = export(fc, "gml")
        root = ET.fromstring(g)
        assert len(root) == 2
        assert "x&lt;y&amp;z" in g
        assert "<gml:pos>1.5 3</gml:pos>" in g

    def test_gml_polygon_and_multi(self):
        import xml.etree.ElementTree as ET

        from geomesa_tpu.io import export

        shell = np.array([[0, 0], [1, 0], [1, 1], [0, 1], [0, 0]], float)
        sft = FeatureType.from_spec("pg", "*geom:Geometry:srid=4326")
        fc = FeatureCollection.from_rows(sft, [
            {"geom": geo.Polygon(shell, [shell * 0.2 + 0.3])},
            {"geom": geo.MultiPolygon([geo.Polygon(shell), geo.Polygon(shell + 5)])},
        ])
        g = export(fc, "gml")
        ET.fromstring(g)
        assert "gml:interior" in g and "gml:MultiSurface" in g


class TestCliShapefileExport:
    def test_export_shp(self, tmp_path, capsys):
        from geomesa_tpu.cli import main
        from geomesa_tpu.datastore import DataStore
        from geomesa_tpu.io.shapefile import read_shapefile
        from geomesa_tpu.storage import persist

        sft = FeatureType.from_spec("p", "v:Integer,*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        rng = np.random.default_rng(1)
        n = 300
        ds.write("p", FeatureCollection.from_columns(
            sft, np.arange(n),
            {"v": np.arange(n),
             "geom": (rng.uniform(-20, 20, n), rng.uniform(-20, 20, n))},
        ))
        persist.save(ds, tmp_path / "s")
        out = str(tmp_path / "out.shp")
        rc = main([
            "export", "-c", str(tmp_path / "s"), "-f", "p",
            "-q", "bbox(geom, -10, -10, 10, 10)", "--format", "shp", "-o", out,
        ])
        assert rc == 0
        back = read_shapefile(out)
        assert len(back) > 0
        assert (np.abs(back.geom_column.x) <= 10).all()
        assert (np.abs(back.geom_column.y) <= 10).all()

    def test_export_shp_empty_result_fails_cleanly(self, tmp_path, capsys):
        from geomesa_tpu.cli import main
        from geomesa_tpu.datastore import DataStore
        from geomesa_tpu.storage import persist

        sft = FeatureType.from_spec("p", "*geom:Point:srid=4326")
        ds = DataStore()
        ds.create_schema(sft)
        ds.write("p", FeatureCollection.from_columns(
            sft, np.arange(2), {"geom": (np.zeros(2), np.zeros(2))}
        ))
        persist.save(ds, tmp_path / "s")
        rc = main([
            "export", "-c", str(tmp_path / "s"), "-f", "p",
            "-q", "bbox(geom, 50, 50, 51, 51)", "--format", "shp",
            "-o", str(tmp_path / "o.shp"),
        ])
        assert rc == 1
        assert "shapefile export failed" in capsys.readouterr().err
