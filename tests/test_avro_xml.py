"""Avro object-container round-trips (geomesa-feature-avro analogue) and
the XML ingest converter (geomesa-convert-xml analogue), with golden
files pinned under tests/data/."""

import io
from pathlib import Path

import numpy as np
import pytest

from geomesa_tpu import DataStore, FeatureCollection, FeatureType
from geomesa_tpu.io.avro import read_avro, schema_dict, write_avro
from geomesa_tpu.io.converters import Converter, FieldSpec

DATA = Path(__file__).parent / "data"

SPEC = "name:String,age:Int,score:Double,flag:Boolean,dtg:Date,*geom:Point:srid=4326"


def make_fc(n=200, seed=1):
    rng = np.random.default_rng(seed)
    sft = FeatureType.from_spec("av", SPEC)
    t0 = np.datetime64("2024-03-01", "ms").astype(np.int64)
    return sft, FeatureCollection.from_columns(
        sft,
        [f"f{i}" for i in range(n)],
        {
            "name": np.array([f"name{i % 9}" for i in range(n)]),
            "age": (np.arange(n) % 77).astype(np.int32),
            "score": rng.uniform(-5, 5, n),
            "flag": (np.arange(n) % 3 == 0),
            "dtg": t0 + rng.integers(0, 86400_000 * 5, n),
            "geom": (rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)),
        },
    )


class TestAvro:
    def test_roundtrip_with_sft(self):
        sft, fc = make_fc()
        data = write_avro(fc)
        back = read_avro(data, sft)
        assert back.ids.tolist() == fc.ids.tolist()
        assert back.columns["name"].tolist() == fc.columns["name"].tolist()
        assert np.array_equal(back.columns["age"], fc.columns["age"])
        assert np.allclose(back.columns["score"], fc.columns["score"])
        assert np.array_equal(back.columns["flag"], fc.columns["flag"])
        assert np.array_equal(back.columns["dtg"], fc.columns["dtg"])
        assert np.allclose(back.columns["geom"].x, fc.columns["geom"].x)
        assert np.allclose(back.columns["geom"].y, fc.columns["geom"].y)

    def test_roundtrip_schema_inferred(self):
        _, fc = make_fc(50)
        back = read_avro(write_avro(fc))  # type rebuilt from embedded schema
        assert back.ids.tolist() == fc.ids.tolist()
        assert np.allclose(back.columns["score"], fc.columns["score"])
        x, y = back.representative_xy()
        assert np.allclose(x, fc.columns["geom"].x, atol=1e-9)

    def test_multi_block_files(self):
        sft, fc = make_fc(1000)
        data = write_avro(fc, block_rows=128)
        back = read_avro(data, sft)
        assert len(back) == 1000
        assert back.ids.tolist() == fc.ids.tolist()

    def test_polygon_geometries(self):
        sft = FeatureType.from_spec("pg", "name:String,*geom:Polygon:srid=4326")
        rows = [
            {"__id__": str(i), "name": f"p{i}",
             "geom": f"POLYGON(({i} 0, {i+2} 0, {i+2} 2, {i} 2, {i} 0))"}
            for i in range(20)
        ]
        fc = FeatureCollection.from_rows(sft, rows)
        back = read_avro(write_avro(fc), sft)
        assert back.geom_column.bboxes.shape == (20, 4)
        g = back.geom_column.geometry(3)
        assert g.bounds() == (3.0, 0.0, 5.0, 2.0)

    def test_golden_file(self):
        """Byte-stable writer + decodable golden file: regressions in the
        wire format are caught even without an external avro library."""
        sft, fc = make_fc(25, seed=7)
        data = write_avro(fc)
        golden = DATA / "features.avro"
        if not golden.exists():  # first run writes the golden
            golden.parent.mkdir(exist_ok=True)
            golden.write_bytes(data)
        assert data == golden.read_bytes()
        back = read_avro(golden.read_bytes(), sft)
        assert back.ids.tolist() == fc.ids.tolist()
        assert np.array_equal(back.columns["dtg"], fc.columns["dtg"])

    def test_schema_shape(self):
        sft, _ = make_fc(1)
        s = schema_dict(sft)
        assert s["fields"][0]["name"] == "__fid__"
        by_name = {f["name"]: f["type"] for f in s["fields"][1:]}
        assert by_name["age"] == ["null", "int"]
        assert by_name["dtg"] == ["null", {"type": "long", "logicalType": "timestamp-millis"}]
        assert by_name["geom"] == ["null", "bytes"]

    def test_ingest_avro_roundtrip_through_store(self):
        sft, fc = make_fc(300)
        ds = DataStore()
        ds.create_schema(sft)
        ds.write("av", read_avro(write_avro(fc), sft))
        assert ds.count("av") == 300
        assert len(ds.query("av", "bbox(geom, -180, -90, 180, 90)")) == 300


class TestAvroConverter:
    def test_avro_ingest_via_converter(self):
        """fmt='avro' converter: records from a container file through the
        field-expression pipeline (reference geomesa-convert-avro)."""
        sft, fc = make_fc(120)
        data = write_avro(fc)
        target = FeatureType.from_spec(
            "mapped", "label:String,when:Date,*geom:Point:srid=4326"
        )
        conv = Converter(
            sft=target,
            fmt="avro",
            id_field="$.__fid__",
            fields=[
                FieldSpec("label", "concat($.name, '-', $.age)"),
                FieldSpec("when", "$.dtg::long"),
                FieldSpec("geom", "geomFromWkb($.geom)"),
            ],
        )
        out = conv.convert(data)
        assert len(out) == 120
        assert out.ids.tolist() == fc.ids.tolist()
        assert out.columns["label"][0] == f"{fc.columns['name'][0]}-{fc.columns['age'][0]}"
        assert np.array_equal(out.columns["when"], fc.columns["dtg"])
        assert np.allclose(out.columns["geom"].x, fc.columns["geom"].x)


XML_DOC = """<?xml version="1.0"?>
<gml:featureCollection xmlns:gml="http://example.com/fake-gml">
  <gml:member>
    <gml:Observation station="alpha">
      <gml:when>2024-03-05T12:30:00Z</gml:when>
      <gml:value>12.5</gml:value>
      <gml:pos lat="48.2" lon="16.4"/>
    </gml:Observation>
  </gml:member>
  <gml:member>
    <gml:Observation station="beta">
      <gml:when>2024-03-05T13:00:00Z</gml:when>
      <gml:value>-3.25</gml:value>
      <gml:pos lat="-33.9" lon="151.2"/>
    </gml:Observation>
  </gml:member>
</gml:featureCollection>
"""

XML_SPEC = "station:String,value:Double,dtg:Date,*geom:Point:srid=4326"


class TestXmlConverter:
    def _converter(self):
        sft = FeatureType.from_spec("obs", XML_SPEC)
        return Converter(
            sft=sft,
            fmt="xml",
            xml_feature_tag="Observation",
            id_field="$.@station",
            fields=[
                FieldSpec("station", "$.@station"),
                FieldSpec("value", "$.value::double"),
                FieldSpec("dtg", "datetime($.when)"),
                FieldSpec("geom", "point($.pos.@lon, $.pos.@lat)"),
            ],
        )

    def test_parse_document(self):
        fc = self._converter().convert(XML_DOC)
        assert len(fc) == 2
        assert fc.ids.tolist() == ["alpha", "beta"]
        assert fc.columns["station"].tolist() == ["alpha", "beta"]
        assert np.allclose(fc.columns["value"], [12.5, -3.25])
        assert np.allclose(fc.columns["geom"].x, [16.4, 151.2])
        assert np.allclose(fc.columns["geom"].y, [48.2, -33.9])
        want = np.datetime64("2024-03-05T12:30:00", "ms").astype(np.int64)
        assert fc.columns["dtg"][0] == want

    def test_golden_file(self):
        golden = DATA / "observations.xml"
        if not golden.exists():
            golden.parent.mkdir(exist_ok=True)
            golden.write_text(XML_DOC)
        fc = self._converter().convert(golden.read_text())
        assert len(fc) == 2 and fc.ids.tolist() == ["alpha", "beta"]

    def test_bad_records_dropped(self):
        doc = XML_DOC.replace("<gml:value>12.5</gml:value>", "<gml:value>oops</gml:value>")
        conv = self._converter()
        fc = conv.convert(doc)
        assert len(fc) == 1 and conv.errors == 1

    def test_store_ingest(self):
        conv = self._converter()
        ds = DataStore()
        ds.create_schema(conv.sft)
        ds.write("obs", conv.convert(XML_DOC))
        out = ds.query("obs", "bbox(geom, 100, -90, 180, 0)")
        assert out.ids.tolist() == ["beta"]


def test_avro_bytes_column_roundtrip():
    """Bytes attributes survive the Avro container round trip as real
    bytes (from_rows used to str() them on decode)."""
    from geomesa_tpu.io.avro import read_avro, write_avro

    sft = FeatureType.from_spec("b", "payload:Bytes,*geom:Point:srid=4326")
    p = np.empty(3, dtype=object)
    p[:] = [b"\x00\x01", None, b"\xff"]
    fc = FeatureCollection.from_columns(
        sft, np.arange(3), {"payload": p, "geom": (np.zeros(3), np.zeros(3))}
    )
    rt = read_avro(write_avro(fc))
    assert list(rt.columns["payload"]) == [b"\x00\x01", None, b"\xff"]
